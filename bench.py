#!/usr/bin/env python
"""End-to-end benchmark: the course's ML 02–ML 13 compute path on TPU,
run at the scale class the reference claims ("data that exceeds one
machine", `SML/ML 00b - Spark Review.py:84`; MovieLens 1M, `MLE 01:18`):
ONE MILLION rows of the SF-Airbnb-shaped schema, seed 42, plus an
8M-row scale-escalation leg (`ml_scale`) where the host baseline takes
minutes and HBM residency pays off.

Legs (every BASELINE.json config):

  ML 02/03  StringIndexer+OHE+VectorAssembler+LinearRegression fit+predict
  ML 06/07  DecisionTree + RandomForest, then the ML 07 CrossValidator grid
            (maxDepth x numTrees, 3 folds, parallelism=4 — `ML 07:102-149`)
  ML 08     Hyperopt-style TPE search over RF params (4 evals, the course
            budget — `ML 08:146`)
  ML 11     XGBoost-equivalent (tpu_hist boosted trees), log-price target
  ML 12     batch inference via DeviceScorer-backed mapInPandas
  ML 13     applyInPandas per-group training
  serving   online scoring through sml_tpu/serving: closed-loop clients
            issuing low-row requests through the continuous micro-batcher
            (registry-style endpoint path); p50/p99 per-request latency,
            batch occupancy, and shed rate go to the sidecar as serve_*
            metrics (excluded from golden pins — they are load numbers)
  MLE 01/02 block-parallel ALS (MovieLens-1M scale) + fused-Lloyd KMeans
  ml_scale  8M-row LinearRegression + LogisticRegression fits through the
            compact expand-on-device programs (prepared features on BOTH
            sides, like the mle02 leg): the course's "exceeds one machine"
            claim made concrete — the host side runs sklearn on the same
            prepared matrix and takes minutes

Output contract (VERDICT r4 #2): the LAST stdout line is a SHORT headline
JSON — {metric, value, unit, vs_baseline, compile_seconds, pass_walls,
interference_suspected, golden_ok, backend, legs_file} — sized to survive
any capture tail window. Per-leg detail, probes, metrics, and each leg's
ENGINE-COUNTER deltas (staging bytes, cache hits, shuffle volume,
compile count — see docs/OBSERVABILITY.md) go to the `bench_legs.json`
sidecar and stderr.

Timing policy: THREE timed passes after two full warmup passes; each
leg's reported seconds is its BEST across the timed passes (every pass's
full detail is in the sidecar). The TPU sits behind a SHARED tunnel and
the host can be co-tenant-loaded; per-leg best-of-passes measures the
framework rather than the noisiest neighbor, and the tunnel/host probes
taken around every pass are recorded so a globally-slow session is
flagged (`interference_suspected`) instead of silently reported.

`vs_baseline` anchors to a MEASURED single-node pandas/sklearn execution
of the same legs. Expensive legs (>30s host) come from the committed
cache (baseline_host.json); every cheap leg is RE-MEASURED in this run
on this machine (r4's losing legs were host-path times compared against
a baseline captured on a different, uncontended machine) with the SAME
best-of-N discipline as the device legs (best of HOST_TIMED_PASSES),
so vs_baseline compares best-against-best instead of best-against-one.

Run `python bench.py --pin-goldens` on the virtual CPU mesh to (re)pin
the 1M-row metric goldens that the TPU run is checked against.
"""

# graftlint: disable-file=no-wallclock-in-engine -- bench harness: leg wall-clocks ARE the product here, measured outside the engine so profiler overhead never lands inside a timed pass

import argparse
import json
import os
import sys
import time

# XLA:CPU AOT cache replays log a benign machine-feature banner (pseudo-
# features like +prefer-no-scatter) at ERROR level per entry — silence the
# C++ logs before jax loads so the bench output stays readable
os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")

import numpy as np

N_ROWS = 1_000_000
N_RATINGS = 1_000_000  # MovieLens-1M-scale ALS workload (`MLE 01:18`)
N_SCALE = 8_000_000    # the scale-escalation leg (`ML 00b:84`)
SCALE_SEED = 43
SCALE_LOGIT_ITERS = 20  # both sides run the same Newton/lbfgs budget
LEGS_VERSION = 7  # bump when leg definitions change (invalidates the cache)
HERE = os.path.dirname(os.path.abspath(__file__))
BASELINE_CACHE = os.path.join(HERE, "baseline_host.json")
LEGS_FILE = os.path.join(HERE, "bench_legs.json")
GOLDEN_FILE = os.path.join(HERE, "GOLDEN.json")

# host legs cheaper than this re-measure EVERY run on the CURRENT machine;
# slower legs (30s-minutes, won by 10-50x margins that dwarf machine
# variance) come from the committed cache
HOST_REMEASURE_CUTOFF_S = 30.0

# re-measured host legs run this many passes and report their BEST — the
# SAME best-of-N discipline the device legs get (ADVICE r5 medium: one
# host pass against best-of-3 device passes structurally inflated
# vs_baseline). Expensive cached legs stay single-pass (their 10-50x
# margins dwarf pass noise; the sidecar labels them "cached").
HOST_TIMED_PASSES = 3

# peak dense f32 throughput used for the MFU estimate when running on a
# real TPU chip (v5e-class); on CPU the estimate is skipped
TPU_PEAK_F32_FLOPS = 4.9e13

# metric golden tolerances (TPU bf16-histogram path vs the CPU-mesh f32
# pins): trees can shift whole splits under operand rounding, linear/ALS
# paths accumulate in f32 either way
GOLDEN_TOLERANCES = {
    "rmse_lr": 0.01, "rmse_dt": 0.05, "rmse_rf": 0.05, "rmse_xgb": 0.05,
    "cv_best_rmse": 0.05, "rmse_als": 0.05, "scale_rmse_lr": 0.01,
    "scale_accuracy": 0.02,
}


class EngineCounterTrack:
    """Per-leg engine-counter deltas (staging bytes, cache hits, shuffle
    volume, compile count) from the profiler's counter stream: `mark(leg)`
    attributes everything counted since the previous mark to `leg`.
    Recorded into the bench_legs.json sidecar so BENCH runs carry
    cache-hit/byte-volume trajectories alongside wall time — a perf PR can
    diff engine behavior, not just seconds."""

    def __init__(self):
        from sml_tpu.utils.profiler import PROFILER
        self._prof = PROFILER
        self._prev = PROFILER.counters()
        self.legs = {}

    def mark(self, leg):
        cur = self._prof.counters()
        delta = {k: round(v - self._prev.get(k, 0.0), 3)
                 for k, v in cur.items() if v != self._prev.get(k, 0.0)}
        self.legs[leg] = delta
        self._prev = cur


def build_dataset(n):
    from sml_tpu.courseware import make_airbnb_dataset
    from sml_tpu.frame.session import get_session
    pdf = make_airbnb_dataset(n=n, seed=42)
    return get_session().createDataFrame(pdf), pdf


def build_ratings(n):
    """MovieLens-1M-shaped ratings at the real set's entity dims
    (~6040 users x ~3700 movies, `SML/ML Electives/MLE 01:18`)."""
    from sml_tpu.courseware import make_movielens_dataset
    from sml_tpu.frame.session import get_session
    pdf = make_movielens_dataset(n_users=6040, n_items=3700,
                                 n_ratings=n, seed=42)
    return get_session().createDataFrame(pdf), pdf


CAT_COLS = ["neighbourhood_cleansed", "room_type", "property_type"]
NUM_COLS = ["accommodates", "bathrooms", "bedrooms", "beds",
            "minimum_nights", "number_of_reviews", "review_scores_rating"]

# serving leg: closed-loop load (SERVE_CLIENTS concurrent clients, each
# issuing SERVE_REQUEST_ROWS-row requests back-to-back until the shared
# budget of SERVE_REQUESTS is spent) — identical on both sides
SERVE_CLIENTS = 8
SERVE_REQUEST_ROWS = 8
SERVE_REQUESTS = 2000
SERVE_MAX_BATCH_ROWS = 256
SERVE_FLUSH_MICROS = 1000

_scale_cache: dict = {}


def build_scale_parts():
    """Prepared features for the ml_scale leg, built ONCE per process and
    shared by every pass (prep is outside the timed region on BOTH sides,
    like the mle02 leg): fit the course prep chain on the 8M frame, then
    extract the compact block (featurizer.CompactParts). The host side
    gets the same features expanded to the dense matrix sklearn wants."""
    if _scale_cache:
        return _scale_cache["parts"], _scale_cache["yp"], _scale_cache["yl"]
    from sml_tpu.courseware import make_airbnb_dataset
    from sml_tpu.frame.session import get_session
    from sml_tpu.ml import Pipeline
    from sml_tpu.ml.feature import (Imputer, OneHotEncoder, StringIndexer,
                                    VectorAssembler)
    from sml_tpu.ml.featurizer import CompiledFeaturizer
    print(f"preparing ml_scale data ({N_SCALE} rows)...", file=sys.stderr)
    pdf = make_airbnb_dataset(n=N_SCALE, seed=SCALE_SEED)
    yp = np.asarray(pdf["price"], np.float32)
    yl = (yp > float(np.median(yp))).astype(np.float32)
    df = get_session().createDataFrame(pdf)
    idx = [c + "_idx" for c in CAT_COLS]
    ohe = [c + "_ohe" for c in CAT_COLS]
    imp = [c + "_imp" for c in NUM_COLS]
    prep = Pipeline(stages=[
        Imputer(strategy="median", inputCols=NUM_COLS, outputCols=imp),
        StringIndexer(inputCols=CAT_COLS, outputCols=idx,
                      handleInvalid="skip"),
        OneHotEncoder(inputCols=idx, outputCols=ohe),
        VectorAssembler(inputCols=ohe + imp, outputCol="features"),
    ]).fit(df)
    feat = CompiledFeaturizer.from_stages(prep.stages[:-1], prep.stages[-1])
    parts = feat.compact_parts(pdf)
    assert parts is not None and parts.keep is None
    _scale_cache.update(parts=parts, yp=yp, yl=yl)
    return parts, yp, yl


def run_scale_leg(timings, flops, metrics, eng=None):
    """8M-row LinearRegression + LogisticRegression through the compact
    expand-on-device programs (`linear_impl.fit_*_compact`): one Gram
    dispatch + one fused-IRLS dispatch, one-hot slots expanded on-chip.
    The logistic budget (20 Newton steps, executed unconditionally by the
    fused scan) is matched by the host side's lbfgs max_iter."""
    from sml_tpu.ml import linear_impl
    parts, yp, yl = build_scale_parts()
    d = parts.width
    n8 = parts.num.shape[0]
    t0 = time.perf_counter()
    res_lr = linear_impl.fit_linear_compact(parts, yp)
    res_lg = linear_impl.fit_logistic_compact(parts, yl,
                                              maxIter=SCALE_LOGIT_ITERS,
                                              tol=1e-9)
    timings["ml_scale"] = time.perf_counter() - t0
    if eng is not None:
        eng.mark("ml_scale")
    flops["ml_scale"] = (2.0 * n8 * (d + 1) ** 2
                         + 3.0 * SCALE_LOGIT_ITERS * n8 * (d + 1) ** 2)
    st = res_lr.stats or {}
    n_f = st.get("n", n8) or n8
    metrics["scale_rmse_lr"] = float(np.sqrt(st.get("sse", 0.0) / n_f))
    # accuracy on the first 1M rows, computed OUTSIDE the timed region
    # (an 8M predict_affine pass costs more than the fits themselves)
    head = parts._replace(num=parts.num[:1_000_000],
                          codes=parts.codes[:1_000_000])
    margin = head.predict_affine(res_lg.coefficients, res_lg.intercept)
    metrics["scale_accuracy"] = float(np.mean((margin > 0) == (yl[:1_000_000] > 0.5)))
    metrics["scale_d"] = float(d)


def run_serving_leg(lr_model, test, timings, flops, metrics, eng=None):
    """Online-serving leg (docs/SERVING.md): SERVE_CLIENTS closed-loop
    clients push SERVE_REQUEST_ROWS-row requests through the continuous
    micro-batcher in front of a warm DeviceScorer — the amortize-one-
    compiled-program-over-many-small-requests story, measured. Feature
    prep happens OUTSIDE the timed region on both sides (an online
    endpoint scores feature blocks); the timed region is admission →
    coalesce → device dispatch → per-request split.

    Latency percentiles come from the engine's OWN streaming metrics
    core (`obs.METRICS` `serve.request_ms`, fed by the micro-batcher at
    result time — docs/OBSERVABILITY.md): log-bucketed quantiles exact
    to one ~9% bucket, no raw sample lists, no sort. The leg also
    records the SLO burn-rate (`sml.serve.sloMillis`) from the same
    histogram — the number `obs.engine_health()` serves live."""
    import threading

    from sml_tpu import obs
    from sml_tpu.conf import GLOBAL_CONF as _SCONF
    from sml_tpu.ml import DeviceScorer
    from sml_tpu.serving import MicroBatcher
    from sml_tpu.utils.profiler import PROFILER

    from sml_tpu.serving import RequestShed

    scorer = DeviceScorer(lr_model)
    X = scorer._prep(test.toPandas())[:SERVE_REQUESTS * SERVE_REQUEST_ROWS]
    d = X.shape[1]
    slices = [X[lo:lo + SERVE_REQUEST_ROWS]
              for lo in range(0, len(X), SERVE_REQUEST_ROWS)]
    # warm the padded-shape buckets the coalescer can actually produce
    # (every multiple of the request size up to a full batch maps onto
    # bucket_rows' coarse grid — a handful of distinct shapes), so the
    # timed region measures serving, not first-seen-shape compiles; real
    # compile economics are the suite's warmup passes' job
    from sml_tpu.parallel.dispatch import bucket_rows
    warm = sorted({bucket_rows(r, 1) for r in
                   range(SERVE_REQUEST_ROWS,
                         SERVE_MAX_BATCH_ROWS + 1, SERVE_REQUEST_ROWS)})
    for rows in warm:
        scorer.score_block(np.ascontiguousarray(X[:rows]))
    c0 = PROFILER.counters()
    next_req = [0]
    req_lock = threading.Lock()

    def client(batcher):
        while True:
            with req_lock:
                i = next_req[0]
                if i >= len(slices):
                    return
                next_req[0] = i + 1
            try:
                batcher.submit(slices[i]).result(timeout=60)
            except RequestShed:
                continue  # shed is an answer, not a client crash — the
                # shed rate is reported from the serve.shed counter

    # the serving leg runs with the recorder ON: the micro-batcher feeds
    # every request's admission->result latency into the streaming
    # metrics core, and the percentiles below read from THAT histogram
    prev_obs = _SCONF.get("sml.obs.enabled")
    _SCONF.set("sml.obs.enabled", True)
    obs.METRICS.reset()  # this pass's leg owns its distribution
    t0 = time.perf_counter()
    try:
        with MicroBatcher(scorer.score_block,
                          host_score=scorer.score_block_host,
                          max_batch_rows=SERVE_MAX_BATCH_ROWS,
                          flush_micros=SERVE_FLUSH_MICROS) as batcher:
            threads = [threading.Thread(target=client, args=(batcher,))
                       for _ in range(SERVE_CLIENTS)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        timings["serving"] = time.perf_counter() - t0
        hist = obs.METRICS.histogram("serve.request_ms")
        slo = obs.slo_report()
    finally:
        _SCONF.set("sml.obs.enabled", bool(prev_obs))
    if eng is not None:
        eng.mark("serving")
    flops["serving"] = 2.0 * len(X) * d
    c1 = PROFILER.counters()

    def delta(k):
        return c1.get(k, 0.0) - c0.get(k, 0.0)

    batches = max(delta("serve.batches"), 1.0)
    reqs = max(delta("serve.requests"), 1.0)
    metrics["serve_p50_ms"] = round(hist.quantile(0.50), 3) if hist else 0.0
    metrics["serve_p99_ms"] = round(hist.quantile(0.99), 3) if hist else 0.0
    # like-for-like annotation (docs/LOADGEN.md): these percentiles come
    # from CLOSED-LOOP clients with no arrival schedule — they
    # self-throttle when the batcher queues, so they are NOT comparable
    # to open-loop numbers. The regress sentry only compares
    # serve_p50/p99 between records whose serve_closed_loop annotations
    # agree; the open-loop story lives in the `load` block
    metrics["serve_closed_loop"] = 1.0
    metrics["serve_slo_burn_rate"] = slo["burn_rate"]
    # the LITERAL worst request of the leg, by trace-id exemplar
    # (obs/_context.py): the id to chase through an exported trace's
    # flow arrows. A sidecar annotation, not a perf number — excluded
    # from golden pins (serve_*) and ignored by the bench_diff sentry
    # (non-numeric)
    metrics["serve_worst_trace"] = slo.get("worst_trace") or ""
    # numerator = rows that actually entered a device batch (serve.rows
    # also counts shed/host-routed admissions, which would inflate this
    # exactly when the degradation ladder is active)
    metrics["serve_occupancy"] = round(
        delta("serve.batch_rows") / (batches * SERVE_MAX_BATCH_ROWS), 4)
    metrics["serve_shed_rate"] = round(delta("serve.shed") / reqs, 4)
    metrics["serve_host_routed"] = delta("serve.host_routed")


def run_electives(ratings_df, train, timings, flops, eng=None):
    """MLE 01 (block-parallel ALS on MovieLens-1M scale) and MLE 02
    (fused-Lloyd KMeans) — the electives' flagship distributed fits
    (`MLE 01:159-201` "CV takes a few minutes, refit ~1 minute";
    `MLE 02:38-57`)."""
    from sml_tpu.ml import Pipeline
    from sml_tpu.ml.clustering import KMeans
    from sml_tpu.ml.evaluation import RegressionEvaluator
    from sml_tpu.ml.feature import Imputer, VectorAssembler
    from sml_tpu.ml.recommendation import ALS

    rank, als_iters = 8, 10
    t0 = time.perf_counter()
    als_train, als_test = ratings_df.randomSplit([0.8, 0.2], seed=42)
    als = ALS(userCol="userId", itemCol="movieId", ratingCol="rating",
              rank=rank, maxIter=als_iters, regParam=0.1, seed=42,
              coldStartStrategy="drop")
    als_model = als.fit(als_train)
    rmse_als = RegressionEvaluator(labelCol="rating").evaluate(
        als_model.transform(als_test))
    timings["mle01_als"] = time.perf_counter() - t0
    if eng is not None:
        eng.mark("mle01_als")
    n_tr = als_train.count()  # the fit's actual nnz (80% split)
    flops["mle01_als"] = 2.0 * als_iters * (n_tr * rank * rank
                                            + (6040 + 3700) * rank ** 3)

    k, km_iters = 8, 20
    # feature prep happens OUTSIDE the timed region on both sides: the
    # host baseline times only sklearn's KMeans.fit on a prepared matrix
    imp = [c + "_imp" for c in NUM_COLS]
    km_feats = Pipeline(stages=[
        Imputer(strategy="median", inputCols=NUM_COLS, outputCols=imp),
        VectorAssembler(inputCols=imp, outputCol="features"),
    ]).fit(train).transform(train)
    km_feats.cache()
    km_feats.toPandas()  # concat memoized: prep ends with features READY,
    # matching the host side's prepared matrix (Xk built outside timing)
    t0 = time.perf_counter()
    km_model = KMeans(k=k, maxIter=km_iters, seed=221).fit(km_feats)
    centers = km_model.clusterCenters()
    timings["mle02_kmeans"] = time.perf_counter() - t0
    if eng is not None:
        eng.mark("mle02_kmeans")
    n_train = train.count()
    flops["mle02_kmeans"] = 3.0 * km_iters * n_train * len(NUM_COLS) * k
    return {"rmse_als": rmse_als, "kmeans_k": float(len(centers))}


def run_suite(df, n_rows, ratings_df=None, with_scale=True):
    from sml_tpu.ml import DeviceScorer, Pipeline
    from sml_tpu.ml.evaluation import RegressionEvaluator
    from sml_tpu.ml.feature import (Imputer, OneHotEncoder, StringIndexer,
                                    VectorAssembler)
    from sml_tpu.ml.regression import (DecisionTreeRegressor,
                                       LinearRegression,
                                       RandomForestRegressor)
    from sml_tpu.ml.tuning import CrossValidator, ParamGridBuilder
    from sml_tpu.tune import Trials, fmin, hp, tpe
    from sml_tpu.xgboost import XgboostRegressor

    timings = {}
    flops = {}
    eng = EngineCounterTrack()
    train, test = df.randomSplit([0.8, 0.2], seed=42)
    train.cache()
    test.cache()
    n_train = train.count()
    idx = [c + "_idx" for c in CAT_COLS]
    ohe = [c + "_ohe" for c in CAT_COLS]
    imp = [c + "_imp" for c in NUM_COLS]
    prep = [
        Imputer(strategy="median", inputCols=NUM_COLS, outputCols=imp),
        StringIndexer(inputCols=CAT_COLS, outputCols=idx, handleInvalid="skip"),
    ]
    ev = RegressionEvaluator(labelCol="price")

    # ---- ML 02/03: linear pipeline --------------------------------------
    t0 = time.perf_counter()
    lr_model = Pipeline(stages=prep + [
        OneHotEncoder(inputCols=idx, outputCols=ohe),
        VectorAssembler(inputCols=ohe + imp, outputCol="features"),
        LinearRegression(labelCol="price"),
    ]).fit(train)
    rmse_lr = ev.evaluate(lr_model.transform(test))
    timings["ml02_lr"] = time.perf_counter() - t0
    eng.mark("ml02_lr")
    d_lr = lr_model.stages[-1].coefficients.toArray().shape[0] + 1
    flops["ml02_lr"] = 2.0 * n_train * d_lr * d_lr  # Gram pass X^T X

    # ---- ML 06/07: single trees then the CV grid ------------------------
    tree_feats = VectorAssembler(inputCols=idx + imp, outputCol="features")
    t0 = time.perf_counter()
    dt_model = Pipeline(stages=prep + [tree_feats,
                        DecisionTreeRegressor(labelCol="price", maxDepth=5,
                                              maxBins=40)]).fit(train)
    rmse_dt = ev.evaluate(dt_model.transform(test))
    timings["ml06_dt"] = time.perf_counter() - t0
    eng.mark("ml06_dt")
    flops["ml06_dt"] = 2.0 * 1 * 5 * n_train * 10 * 40

    t0 = time.perf_counter()
    rf_model = Pipeline(stages=prep + [tree_feats,
                        RandomForestRegressor(labelCol="price", maxDepth=6,
                                              numTrees=20, maxBins=40,
                                              seed=42)]).fit(train)
    rmse_rf = ev.evaluate(rf_model.transform(test))
    timings["ml07_rf"] = time.perf_counter() - t0
    eng.mark("ml07_rf")
    # histogram builds: trees x levels x (rows x features x bins) one-hot
    # accumulations (ops, not dense MXU flops — reported for scale)
    flops["ml07_rf"] = 2.0 * 20 * 6 * n_train * 10 * 40

    # the ML 07 tuning shape: grid over maxDepth x numTrees, 3 seeded folds,
    # parallelism=4 (trials placed on disjoint submeshes)
    t0 = time.perf_counter()
    feat_train = Pipeline(stages=prep + [tree_feats]).fit(train) \
        .transform(train)
    feat_train.cache()
    rf = RandomForestRegressor(labelCol="price", maxBins=40, seed=42)
    grid = (ParamGridBuilder()
            .addGrid(rf.getParam("maxDepth"), [2, 5])
            .addGrid(rf.getParam("numTrees"), [10, 20]).build())
    cv = CrossValidator(estimator=rf, estimatorParamMaps=grid, evaluator=ev,
                        numFolds=3, parallelism=4, seed=42)
    cv_model = cv.fit(feat_train)
    timings["ml07_cv"] = time.perf_counter() - t0
    eng.mark("ml07_cv")
    cv_best = float(min(cv_model.avgMetrics))
    # 12 fold fits (3 folds x 2/3 of train each = 2n per param map) + one
    # full-train refit of the winner (approximated by the grid mean)
    _grid_td = [(int(pm[rf.getParam("numTrees")]),
                 int(pm[rf.getParam("maxDepth")])) for pm in grid]
    flops["ml07_cv"] = (
        sum(2.0 * t * d * 2.0 * n_train * 10 * 40 for t, d in _grid_td)
        + 2.0 * np.mean([t * d for t, d in _grid_td]) * n_train * 10 * 40)

    # ---- ML 08: TPE search, course budget of 4 evals --------------------
    t0 = time.perf_counter()
    space = {"max_depth": hp.quniform("max_depth", 2, 8, 1),
             "num_trees": hp.quniform("num_trees", 5, 25, 5)}

    def objective(params):
        m = RandomForestRegressor(labelCol="price", maxBins=40, seed=42,
                                  maxDepth=int(params["max_depth"]),
                                  numTrees=int(params["num_trees"])) \
            .fit(feat_train)
        return ev.evaluate(m.transform(feat_train))

    fmin(objective, space, algo=tpe, max_evals=4, trials=Trials(),
         rstate=np.random.RandomState(42))
    timings["ml08_hyperopt"] = time.perf_counter() - t0
    eng.mark("ml08_hyperopt")
    # 4 evals at the space's mean budget (maxDepth~5, numTrees~15)
    flops["ml08_hyperopt"] = 4 * 2.0 * 15 * 5 * n_train * 10 * 40

    # ---- ML 11: boosted trees, log-price --------------------------------
    from sml_tpu.frame import functions as F
    t0 = time.perf_counter()
    log_train = train.withColumn("label", F.log(F.col("price")))
    log_test = test.withColumn("label", F.log(F.col("price")))
    xgb_model = Pipeline(stages=prep + [tree_feats,
                         XgboostRegressor(n_estimators=40, learning_rate=0.15,
                                          max_depth=6, max_bins=64,
                                          random_state=42)]).fit(log_train)
    pred = xgb_model.transform(log_test).withColumn(
        "prediction", F.exp(F.col("prediction")))
    rmse_xgb = ev.evaluate(pred)
    timings["ml11_xgb"] = time.perf_counter() - t0
    eng.mark("ml11_xgb")
    flops["ml11_xgb"] = 2.0 * 40 * 6 * n_train * 10 * 64

    # ---- ML 12: batch inference through the device scorer ---------------
    # the lesson's own tuning knob (`ML 12:90,121`): larger Arrow batches
    # amortize per-batch dispatch — the factorized scorer streams 50k rows
    from sml_tpu.conf import GLOBAL_CONF as _CONF
    _old_bs = _CONF.get("spark.sql.execution.arrow.maxRecordsPerBatch")
    _CONF.set("spark.sql.execution.arrow.maxRecordsPerBatch", 50000)
    t0 = time.perf_counter()
    scorer = DeviceScorer(lr_model)

    def predict_batches(it):
        import pandas as pd
        for out in scorer.score_batches(it):
            yield pd.DataFrame({"prediction": out})

    n_scored = test.mapInPandas(predict_batches, "prediction double").count()
    timings["ml12_mapinpandas"] = time.perf_counter() - t0
    eng.mark("ml12_mapinpandas")
    _CONF.set("spark.sql.execution.arrow.maxRecordsPerBatch", _old_bs)
    flops["ml12_mapinpandas"] = 2.0 * n_scored * d_lr

    # ---- serving: closed-loop online micro-batched scoring --------------
    serve_metrics = {}
    run_serving_leg(lr_model, test, timings, flops, serve_metrics, eng)

    # ---- ML 13: per-group training fan-out ------------------------------
    t0 = time.perf_counter()

    def train_group(pdf):
        import pandas as pd
        from sklearn.linear_model import LinearRegression as SkLR
        cols = ["accommodates", "bedrooms"]
        g = pdf.dropna(subset=cols + ["price"])
        if len(g) < 5:
            return pd.DataFrame({"room_type": [pdf["room_type"].iloc[0]],
                                 "n": [len(g)], "mse": [float("nan")]})
        m = SkLR().fit(g[cols], g["price"])
        mse = float(np.mean((m.predict(g[cols]) - g["price"]) ** 2))
        return pd.DataFrame({"room_type": [g["room_type"].iloc[0]],
                             "n": [len(g)], "mse": [mse]})

    n_groups = train.groupby("room_type").applyInPandas(
        train_group, "room_type string, n bigint, mse double").count()
    timings["ml13_applyinpandas"] = time.perf_counter() - t0
    eng.mark("ml13_applyinpandas")
    # per-group sklearn LR payload (host math by course design, `ML 13`)
    flops["ml13_applyinpandas"] = 2.0 * n_train * 2 * 2

    metrics = {"rmse_lr": rmse_lr, "rmse_dt": rmse_dt, "rmse_rf": rmse_rf,
               "rmse_xgb": rmse_xgb, "cv_best_rmse": cv_best,
               "rows_scored": n_scored, "groups": n_groups}
    metrics.update(serve_metrics)
    if ratings_df is not None:
        metrics.update(run_electives(ratings_df, train, timings, flops, eng))
    if with_scale:
        run_scale_leg(timings, flops, metrics, eng)
    return timings, metrics, flops, eng.legs


def _host_als(ratings, rank, iters, reg, seed=42):
    """Efficient single-node numpy ALS (the honest host anchor — sklearn
    has no ALS): per-side normal equations accumulated with sorted
    reduceat segment sums, batched np.linalg.solve, ALS-WR reg."""
    users = ratings["userId"].to_numpy(np.int64)
    items = ratings["movieId"].to_numpy(np.int64)
    r = ratings["rating"].to_numpy(np.float32)
    n_u, n_i = users.max() + 1, items.max() + 1
    rng = np.random.default_rng(seed)
    U = rng.normal(0, 0.1, (n_u, rank)).astype(np.float32)
    V = rng.normal(0, 0.1, (n_i, rank)).astype(np.float32)

    def half(ids, n_out, other_rows, rr):
        order = np.argsort(ids, kind="stable")
        ids_s = ids[order]
        F = other_rows[order]
        rs = rr[order]
        starts = np.minimum(np.searchsorted(ids_s, np.arange(n_out)),
                            max(len(F) - 1, 0))
        outer = (F[:, :, None] * F[:, None, :]).reshape(len(F), -1)
        A = np.add.reduceat(outer, starts, axis=0).reshape(n_out, rank, rank)
        b = np.add.reduceat(F * rs[:, None], starts, axis=0)
        cnt = np.bincount(ids_s, minlength=n_out).astype(np.float32)
        # reduceat yields a bogus single element for empty segments: zero
        empty = cnt == 0
        A[empty] = 0.0
        b[empty] = 0.0
        lam = reg * np.maximum(cnt, 1.0)
        A = A + lam[:, None, None] * np.eye(rank, dtype=np.float32)[None]
        sol = np.linalg.solve(A, b[:, :, None])[:, :, 0]
        sol[empty] = 0.0
        return sol.astype(np.float32)

    for _ in range(iters):
        U = half(users, n_u, V[items], r)
        V = half(items, n_i, U[users], r)
    return U, V


# ---------------------------------------------------------------- host baseline
def run_host_baseline(pdf, ratings_pdf=None, only=None):
    """The SAME legs executed the single-node pandas/sklearn way — the
    measured anchor for vs_baseline (replaces r1's invented constant).
    `only` restricts to a subset of leg names (the per-run re-measure of
    cheap legs); None measures everything."""
    import pandas as pd
    from sklearn.ensemble import (HistGradientBoostingRegressor,
                                  RandomForestRegressor as SkRF)
    from sklearn.linear_model import LinearRegression as SkLR
    from sklearn.model_selection import GridSearchCV, train_test_split
    from sklearn.tree import DecisionTreeRegressor as SkDT

    def want(leg):
        return only is None or leg in only

    timings = {}
    work = pdf.copy()
    for c in NUM_COLS:
        work[c] = pd.to_numeric(work[c], errors="coerce")
        work[c] = work[c].fillna(work[c].median())
    train, test = train_test_split(work, test_size=0.2, random_state=42)

    def featurize(frame, ohe):
        X = pd.get_dummies(frame[CAT_COLS], dtype=float) if ohe else \
            frame[CAT_COLS].apply(lambda s: s.astype("category").cat.codes)
        return pd.concat([X, frame[NUM_COLS]], axis=1).to_numpy(np.float64)

    m = None
    if want("ml02_lr") or want("ml12_mapinpandas") or want("serving"):
        t0 = time.perf_counter()
        Xtr, Xte = featurize(train, True), featurize(test, True)
        m = SkLR().fit(Xtr, train["price"])
        float(np.sqrt(np.mean((m.predict(Xte) - test["price"]) ** 2)))
        if want("ml02_lr"):
            timings["ml02_lr"] = time.perf_counter() - t0

    # featurization happens inside the leg, as in the framework leg (every
    # Pipeline.fit re-featurizes); later legs reuse the matrices, which
    # only favors the host baseline
    need_tree = any(want(k) for k in
                    ("ml06_dt", "ml07_rf", "ml07_cv", "ml08_hyperopt",
                     "ml11_xgb"))
    if need_tree:
        t0 = time.perf_counter()
        Xtr_t, Xte_t = featurize(train, False), featurize(test, False)
        if want("ml06_dt"):
            SkDT(max_depth=5).fit(Xtr_t, train["price"]).predict(Xte_t)
            timings["ml06_dt"] = time.perf_counter() - t0

    if want("ml07_rf"):
        t0 = time.perf_counter()
        SkRF(max_depth=6, n_estimators=20, random_state=42, n_jobs=-1) \
            .fit(Xtr_t, train["price"]).predict(Xte_t)
        timings["ml07_rf"] = time.perf_counter() - t0

    if want("ml07_cv"):
        t0 = time.perf_counter()
        gs = GridSearchCV(SkRF(random_state=42, n_jobs=-1),
                          {"max_depth": [2, 5], "n_estimators": [10, 20]},
                          cv=3, scoring="neg_root_mean_squared_error",
                          n_jobs=1)
        gs.fit(Xtr_t, train["price"])
        timings["ml07_cv"] = time.perf_counter() - t0

    if want("ml08_hyperopt"):
        t0 = time.perf_counter()
        rng = np.random.RandomState(42)
        for _ in range(4):  # 4-eval random/TPE-budget search (ML 08:146)
            SkRF(max_depth=int(rng.randint(2, 9)),
                 n_estimators=int(rng.choice([5, 10, 15, 20, 25])),
                 random_state=42, n_jobs=-1).fit(Xtr_t, train["price"]) \
                .predict(Xtr_t)
        timings["ml08_hyperopt"] = time.perf_counter() - t0

    if want("ml11_xgb"):
        t0 = time.perf_counter()
        hp = HistGradientBoostingRegressor(max_iter=40, learning_rate=0.15,
                                           max_depth=6, max_bins=64,
                                           random_state=42) \
            .fit(Xtr_t, np.log(train["price"])).predict(Xte_t)
        # same work as the framework leg: exp back to price scale + rmse
        float(np.sqrt(np.mean((np.exp(hp) - test["price"]) ** 2)))
        timings["ml11_xgb"] = time.perf_counter() - t0

    if want("ml12_mapinpandas"):
        # like the course's pyfunc (`ML 12:101-143`) and the framework leg,
        # the scorer featurizes each raw batch before predicting (with a
        # stable dummy-column layout, as a persisted pyfunc would)
        dummy_cols = pd.get_dummies(test[CAT_COLS], dtype=float).columns

        def featurize_batch(b):
            X = pd.get_dummies(b[CAT_COLS], dtype=float).reindex(
                columns=dummy_cols, fill_value=0.0)
            return pd.concat([X, b[NUM_COLS]], axis=1).to_numpy(np.float64)

        t0 = time.perf_counter()
        bs = 10_000  # the arrow batch size the framework leg streams at
        preds = [m.predict(featurize_batch(test.iloc[lo:lo + bs]))
                 for lo in range(0, len(test), bs)]
        np.concatenate(preds)
        timings["ml12_mapinpandas"] = time.perf_counter() - t0

    if want("serving"):
        # the no-batching anchor: the SAME closed-loop request stream
        # scored one request at a time (sklearn predict per request) —
        # what an endpoint without coalescing pays
        Xs = featurize(test, True)[:SERVE_REQUESTS * SERVE_REQUEST_ROWS]
        t0 = time.perf_counter()
        for lo in range(0, len(Xs), SERVE_REQUEST_ROWS):
            m.predict(Xs[lo:lo + SERVE_REQUEST_ROWS])
        timings["serving"] = time.perf_counter() - t0

    if want("ml13_applyinpandas"):
        # the framework leg groups the RAW train frame (NaNs intact, so the
        # fn's dropna drops ~24k real rows — 3% bedrooms NaN); the host side
        # must too — grouping the pre-imputed `train` made its dropna a
        # no-op and the baseline ~1.7x faster than the same loop on equal
        # data (r4 fairness fix). Same rows as `train` by construction:
        # select the split's surviving indices from the raw frame.
        raw_train = pdf.loc[train.index]
        t0 = time.perf_counter()
        for _, g in raw_train.groupby("room_type"):
            g = g.dropna(subset=["accommodates", "bedrooms", "price"])
            if len(g) >= 5:
                gm = SkLR().fit(g[["accommodates", "bedrooms"]], g["price"])
                float(np.mean((gm.predict(g[["accommodates", "bedrooms"]])
                               - g["price"]) ** 2))
        timings["ml13_applyinpandas"] = time.perf_counter() - t0

    if ratings_pdf is not None and want("mle01_als"):
        rng = np.random.RandomState(42)
        tr_mask = rng.rand(len(ratings_pdf)) < 0.8
        t0 = time.perf_counter()
        U, V = _host_als(ratings_pdf[tr_mask], rank=8, iters=10, reg=0.1)
        te = ratings_pdf[~tr_mask]
        pred = np.sum(U[te["userId"].to_numpy(np.int64)]
                      * V[te["movieId"].to_numpy(np.int64)], axis=1)
        float(np.sqrt(np.mean((pred - te["rating"].to_numpy(np.float64))
                              ** 2)))
        timings["mle01_als"] = time.perf_counter() - t0

    if want("mle02_kmeans"):
        from sklearn.cluster import KMeans as SkKMeans
        t0 = time.perf_counter()
        Xk = train[NUM_COLS].to_numpy(np.float64)
        SkKMeans(n_clusters=8, init="k-means++", n_init=1, max_iter=20,
                 random_state=221).fit(Xk)
        timings["mle02_kmeans"] = time.perf_counter() - t0

    if want("ml_scale"):
        # same prepared features as the device side (build_scale_parts),
        # expanded to the dense matrix sklearn operates on; same model
        # budgets (lstsq LR; logistic at SCALE_LOGIT_ITERS)
        from sklearn.linear_model import LogisticRegression as SkLogit
        parts, yp, yl = build_scale_parts()
        Xs = parts.expand_host()
        t0 = time.perf_counter()
        SkLR().fit(Xs, yp)
        SkLogit(max_iter=SCALE_LOGIT_ITERS, solver="lbfgs").fit(Xs, yl)
        timings["ml_scale"] = time.perf_counter() - t0
        del Xs

    return timings


def get_host_baseline(pdf, ratings_pdf=None):
    if os.path.exists(BASELINE_CACHE):
        with open(BASELINE_CACHE) as f:
            cached = json.load(f)
        if cached.get("n_rows") == N_ROWS and \
                cached.get("legs_version") == LEGS_VERSION:
            return cached["timings"]
    print("measuring single-node host baseline (cached afterwards)...",
          file=sys.stderr)
    timings = run_host_baseline(pdf, ratings_pdf)
    with open(BASELINE_CACHE, "w") as f:
        json.dump({"n_rows": N_ROWS, "legs_version": LEGS_VERSION,
                   "timings": timings,
                   "note": "single-node pandas/sklearn execution of the same "
                           "legs on the same host; measured, not assumed"},
                  f, indent=1)
    return timings


# ----------------------------------------------------------------- probes
_probe_state: dict = {}


def probe():
    """Co-tenant/interference probe (VERDICT r4 #4): a fixed tiny device
    round-trip and a fixed host numpy workload, best-of-3 each. Taken
    around every timed pass; a session whose BEST probes sit far above
    the session minimum is flagged instead of silently reported."""
    import jax
    import jax.numpy as jnp
    if "fn" not in _probe_state:
        # graftlint: disable=dispatch-bypass -- interference probe: must measure the raw tunnel untouched by routing, caches, or the audit
        _probe_state["fn"] = jax.jit(lambda x: (x @ x).sum())
        _probe_state["x"] = jax.device_put(
            np.eye(64, dtype=np.float32), jax.devices()[0])
        _probe_state["host_a"] = np.random.default_rng(0).normal(
            size=(2_000_000,))
        jax.device_get(_probe_state["fn"](_probe_state["x"]))  # compile
    dev_ms = []
    for _ in range(3):
        t0 = time.perf_counter()
        jax.device_get(_probe_state["fn"](_probe_state["x"]))
        dev_ms.append((time.perf_counter() - t0) * 1e3)
    host_ms = []
    a = _probe_state["host_a"]
    for _ in range(3):
        t0 = time.perf_counter()
        float((a * a).sum())
        np.linalg.lstsq(np.outer(a[:200], a[:200]) + np.eye(200),
                        a[:200], rcond=None)
        host_ms.append((time.perf_counter() - t0) * 1e3)
    return {"device_ms": round(min(dev_ms), 2),
            "host_ms": round(min(host_ms), 2)}


def second_fit_probe(train):
    """Quantized-engine acceptance probe: two IDENTICAL-shape XGBoost fits
    in this (so-far tree-cold) process. Fit 1 pays python trace, XLA
    compile (or persistent-cache load), host binning, and H2D staging;
    fit 2 must ride the compiled-program cache, the quantized bin-index
    cache, and the staged device buffers — the engine's whole reuse story
    in one number. Run BEFORE the warmup passes so fit 1 is genuinely
    cold for the boosting path."""
    from sml_tpu.frame import functions as F
    from sml_tpu.ml import Pipeline
    from sml_tpu.ml.feature import Imputer, StringIndexer, VectorAssembler
    from sml_tpu.xgboost import XgboostRegressor

    idx = [c + "_idx" for c in CAT_COLS]
    imp = [c + "_imp" for c in NUM_COLS]
    labeled = train.withColumn("label", F.log(F.col("price")))
    feats = Pipeline(stages=[
        Imputer(strategy="median", inputCols=NUM_COLS, outputCols=imp),
        StringIndexer(inputCols=CAT_COLS, outputCols=idx,
                      handleInvalid="skip"),
        VectorAssembler(inputCols=idx + imp, outputCol="features"),
    ]).fit(labeled).transform(labeled)
    feats.cache()
    feats.toPandas()  # featurization outside both timed fits
    est = XgboostRegressor(n_estimators=40, learning_rate=0.15, max_depth=6,
                           max_bins=64, random_state=42)
    t0 = time.perf_counter()
    est.fit(feats)
    first = time.perf_counter() - t0
    t0 = time.perf_counter()
    est.fit(feats)
    second = time.perf_counter() - t0
    # release the probe's cached frame before the timed legs (the warm
    # bin-cache/program entries it leaves behind are the point; a pinned
    # 800k-row featurized frame is not)
    feats.unpersist()
    out = {"first_fit_s": round(first, 3), "second_fit_s": round(second, 3),
           "speedup": round(first / max(second, 1e-9), 2)}
    print(f"second-fit probe (identical-shape XGBoost): {out}",
          file=sys.stderr)
    return out


# ------------------------------------------------------------- multichip leg
MULTICHIP_ROWS = 200_000
MULTICHIP_TREES = 20
MULTICHIP_DEPTH = 6
MULTICHIP_BINS = 32


def run_multichip(rows: int = MULTICHIP_ROWS) -> dict:
    """`--multichip`: the fit-throughput SCALING leg (ISSUE 6) — the same
    bootstrap-forest fit executed on 1, 2, 4, ... device meshes over the
    live device set, with the quantized bin matrix row-sharded per mesh
    and every histogram merge a `psum` over the mesh's data axis.

    Per width the leg records: best-of-3 warm fit seconds (compile +
    staging paid in a warmup fit), fit throughput, speedup vs the
    1-device mesh, the per-trace collective launch/byte counters (the
    ICI allreduce volume one program carries — captured from the warmup
    trace, since collectives are counted at TRACE time), and a model
    PARITY check against the 1-device fit (sampling draws are
    mesh-layout-invariant, so every width must produce the same forest
    up to float reduction order).

    On a 1-device host this degenerates to a single row honestly; the
    committed MULTICHIP artifact runs it under the simulated 8-device
    CPU mesh (`XLA_FLAGS=--xla_force_host_platform_device_count=8`),
    where "scaling" measures the engine's dispatch structure, not real
    ICI — real-chip numbers come from running the same flag on a pod
    slice. Results merge into the bench sidecar as the `multichip`
    block, rendered by scripts/render_perf.py."""
    import jax
    import jax.numpy as jnp

    from sml_tpu import obs
    from sml_tpu.conf import GLOBAL_CONF
    from sml_tpu.ml import tree_impl
    from sml_tpu.ml._tree_models import _fit_ensemble
    from sml_tpu.parallel import mesh as meshlib

    n_avail = len(jax.devices())
    widths = [w for w in (1, 2, 4, 8, 16, 32, 64) if w <= n_avail]
    rng = np.random.default_rng(42)
    F = 10
    X = rng.normal(size=(rows, F)).astype(np.float32)
    y = (X[:, 0] * 3 - X[:, 1] ** 2 + 0.5 * X[:, 2]
         + rng.normal(0, 0.3, rows)).astype(np.float32)
    probe = X[:4096]

    prev_obs = GLOBAL_CONF.get("sml.obs.enabled")
    GLOBAL_CONF.set("sml.obs.enabled", True)
    entries = []
    ref_pred = None
    straggler = None
    try:
        for w in widths:
            mesh = meshlib.build_mesh(w)
            with meshlib.use_mesh(mesh):
                def fit():
                    return _fit_ensemble(
                        X, y, categorical={}, max_depth=MULTICHIP_DEPTH,
                        max_bins=MULTICHIP_BINS, min_instances=1,
                        min_info_gain=0.0, n_trees=MULTICHIP_TREES,
                        feature_k=None, bootstrap=True, subsample=1.0,
                        seed=42, loss="squared")

                obs.reset()
                spec = fit()  # warmup: compile + bin + stage + trace
                coll = obs.RECORDER.counters()
                best = float("inf")
                for _ in range(3):
                    t0 = time.perf_counter()
                    fit()
                    best = min(best, time.perf_counter() - t0)
                pred = spec.predict_margin(probe)
                # per-device straggler attribution (obs/_skew.py): time
                # the same per-shard reduction on EACH chip's resident
                # bin block (best-of-3) — the per-chip compute profile
                # the BSP decomposition splits into compute vs
                # collective-wait, rendered as per-device trace lanes
                staged = tree_impl.stage_tree_data(
                    X, y, max_bins=MULTICHIP_BINS)
                # group-aware iteration (host_row_blocks, not the flat
                # addressable list): on a hierarchical mesh each probe
                # carries its device's host-group id, so the timings
                # feed the per-HOST skew lanes next to the per-chip
                # ones; on a flat mesh every device is group 0 and the
                # host roll-up degenerates harmlessly
                blocks = [(g, dev, blk)
                          for g, devblks in meshlib.host_row_blocks(
                              staged.binned_dev, mesh)
                          for dev, blk in devblks]
                # graftlint: disable=dispatch-bypass -- skew probe: must time ONE chip's shard in isolation, untouched by routing or the mesh (a dispatched program would re-shard the block)
                probe_fn = jax.jit(
                    lambda b: (b.astype(jnp.float32) ** 2).sum(axis=0))
                jax.block_until_ready(probe_fn(blocks[0][2]))  # compile
                shard_walls = []
                for _g, _dev, blk in blocks:
                    bw = float("inf")
                    for _ in range(3):
                        t0 = time.perf_counter()
                        jax.block_until_ready(probe_fn(blk))
                        bw = min(bw, time.perf_counter() - t0)
                    shard_walls.append(bw)
                attr = obs.SKEW.note(
                    f"multichip_{w}dev", shard_walls,
                    devices=[d.id for _, d, _ in blocks],
                    hosts=[g for g, _, _ in blocks], wall_s=best,
                    psum_bytes=coll.get("collective.psum_bytes", 0.0),
                    psum_launches=coll.get("collective.psum", 0.0))
                straggler = obs.straggler_report()
            if ref_pred is None:
                ref_pred = pred
            parity = bool(np.allclose(pred, ref_pred, rtol=1e-4, atol=1e-4))
            entries.append({
                "devices": w,
                "seconds": round(best, 4),
                "rows_per_s": round(rows / best, 1),
                "speedup_vs_1": round(entries[0]["seconds"] / best, 3)
                if entries else 1.0,
                "collective_psum": int(coll.get("collective.psum", 0)),
                "collective_psum_bytes":
                    float(coll.get("collective.psum_bytes", 0.0)),
                "parity_vs_1": parity,
                "skew": None if attr is None else {
                    "slowest_device": int(attr["slowest_device"]),
                    "skew_ratio": round(attr["skew_ratio"], 4),
                    "wait_share": round(attr["wait_share"], 4),
                    "per_device_compute_ms": [round(c * 1e3, 4)
                                              for c in shard_walls],
                },
            })
            print(f"  multichip {w}d: {best:.3f}s "
                  f"({rows / best:,.0f} rows/s, "
                  f"psum {coll.get('collective.psum_bytes', 0) / 1e6:.2f} "
                  f"MB/trace, parity={parity}, skew "
                  f"{entries[-1]['skew']['skew_ratio'] if entries[-1]['skew'] else '-'}"
                  f")", file=sys.stderr)
    finally:
        GLOBAL_CONF.set("sml.obs.enabled", bool(prev_obs))
    return {
        "rows": rows, "n_features": F, "n_trees": MULTICHIP_TREES,
        "max_depth": MULTICHIP_DEPTH, "max_bins": MULTICHIP_BINS,
        "backend": jax.default_backend(), "n_devices": n_avail,
        "note": "best-of-3 warm fits per mesh width; collective counters "
                "are per-TRACE statics (multiply by executions for wire "
                "traffic); parity_vs_1 = same forest as the 1-device "
                "mesh (layout-invariant sampling); skew = per-device "
                "straggler attribution from per-shard compute probes "
                "(obs/_skew.py, docs/OBSERVABILITY.md)",
        "widths": entries,
        # aggregate straggler attribution for the WIDEST mesh (obs.reset
        # runs per width, so the tracker holds the last width's notes)
        "straggler": straggler,
    }


def multichip_main(rows: int) -> None:
    """Run the scaling leg standalone, merge the `multichip` block into
    the bench sidecar, and print the short headline JSON last."""
    block = run_multichip(rows)
    doc = {}
    if os.path.exists(LEGS_FILE):
        with open(LEGS_FILE) as f:
            doc = json.load(f)
    doc["multichip"] = block
    with open(LEGS_FILE, "w") as f:
        json.dump(doc, f, indent=1)
    best = max(e["speedup_vs_1"] for e in block["widths"])
    straggler = block.get("straggler") or {}
    print(json.dumps({
        "metric": "multichip fit-throughput scaling",
        "value": best,
        "unit": "x vs 1 device",
        "n_devices": block["n_devices"],
        "backend": block["backend"],
        "parity_ok": all(e["parity_vs_1"] for e in block["widths"]),
        "straggler_device": straggler.get("slowest_device"),
        "skew_ratio": straggler.get("skew_ratio"),
        "legs_file": "bench_legs.json",
    }))


# ------------------------------------------------------------ multihost leg
MULTIHOST_ROWS = 100_000


def run_multihost(rows: int = MULTIHOST_ROWS) -> dict:
    """`--multihost`: the DCN-aware hierarchical-collective leg (ISSUE
    20) — the same boosted fit executed on 1..H virtual-host meshes
    (`parallel.mesh.host_mesh`: the 8-device sim partitioned into host
    groups, `jax.process_index()` slices on a real pod), with every
    histogram merge a two-level `psum_hierarchical` (intra-group
    reduce-scatter over "ici", inter-group allreduce over "dcn",
    allgather back) instead of one flat allreduce.

    Per host-group shape the leg records: best-of-3 warm fit seconds
    and rows/s, the PER-HOP collective launch/byte statics
    (`collective.psum.ici/.dcn`, `collective.psum_bytes.ici/.dcn` —
    trace-time counts, like the multichip block), the DCN byte fraction
    vs the flat-mesh allreduce payload (the whole point: the cross-host
    hop must carry ~payload/ici_size, not the full payload), model
    parity vs the 1-host-group fit (layout-invariant sampling), and a
    per-HOST skew table from group-aware per-shard compute probes
    (obs/_skew.py host lanes). Merges into the bench sidecar as the
    `multihost` block; obs/regress.py judges DCN-byte growth, lost
    parity, and a vanished skew table as regressions."""
    import jax
    import jax.numpy as jnp

    from sml_tpu import obs
    from sml_tpu.conf import GLOBAL_CONF
    from sml_tpu.ml import tree_impl
    from sml_tpu.ml._tree_models import _fit_ensemble
    from sml_tpu.parallel import mesh as meshlib

    n_avail = len(jax.devices())
    shapes = [h for h in (1, 2, 4, 8, 16)
              if h <= n_avail and n_avail % h == 0]
    rng = np.random.default_rng(42)
    F = 10
    X = rng.normal(size=(rows, F)).astype(np.float32)
    y = (X[:, 0] * 3 - X[:, 1] ** 2 + 0.5 * X[:, 2]
         + rng.normal(0, 0.3, rows)).astype(np.float32)
    probe = X[:4096]

    def fit():
        return _fit_ensemble(
            X, y, categorical={}, max_depth=MULTICHIP_DEPTH,
            max_bins=MULTICHIP_BINS, min_instances=1, min_info_gain=0.0,
            n_trees=MULTICHIP_TREES, feature_k=None, bootstrap=True,
            subsample=1.0, seed=42, loss="squared")

    prev_obs = GLOBAL_CONF.get("sml.obs.enabled")
    GLOBAL_CONF.set("sml.obs.enabled", True)
    entries = []
    ref_pred = None
    straggler = None
    try:
        # flat-mesh reference: the single-hop allreduce payload every
        # DCN fraction below is judged against
        with meshlib.use_mesh(meshlib.build_mesh(n_avail)):
            obs.reset()
            fit()
            flat_bytes = float(obs.RECORDER.counters()
                               .get("collective.psum_bytes", 0.0))
        for h in shapes:
            mesh = meshlib.host_mesh(h)
            per = n_avail // h
            with meshlib.use_mesh(mesh):
                obs.reset()
                spec = fit()  # warmup: compile + bin + stage + trace
                coll = obs.RECORDER.counters()
                best = float("inf")
                for _ in range(3):
                    t0 = time.perf_counter()
                    fit()
                    best = min(best, time.perf_counter() - t0)
                pred = spec.predict_margin(probe)
                # per-host straggler attribution: same per-shard compute
                # probe as the multichip leg, iterated GROUP-AWARE so
                # each timing carries its host id and the tracker's
                # host lanes + slowest-host roll-up light up
                staged = tree_impl.stage_tree_data(
                    X, y, max_bins=MULTICHIP_BINS)
                blocks = [(g, dev, blk)
                          for g, devblks in meshlib.host_row_blocks(
                              staged.binned_dev, mesh)
                          for dev, blk in devblks]
                # graftlint: disable=dispatch-bypass -- skew probe: must time ONE chip's shard in isolation, untouched by routing or the mesh (a dispatched program would re-shard the block)
                probe_fn = jax.jit(
                    lambda b: (b.astype(jnp.float32) ** 2).sum(axis=0))
                jax.block_until_ready(probe_fn(blocks[0][2]))  # compile
                shard_walls = []
                for _g, _dev, blk in blocks:
                    bw = float("inf")
                    for _ in range(3):
                        t0 = time.perf_counter()
                        jax.block_until_ready(probe_fn(blk))
                        bw = min(bw, time.perf_counter() - t0)
                    shard_walls.append(bw)
                attr = obs.SKEW.note(
                    f"multihost_{h}x{per}", shard_walls,
                    devices=[d.id for _, d, _ in blocks],
                    hosts=[g for g, _, _ in blocks], wall_s=best,
                    psum_bytes=coll.get("collective.psum_bytes.dcn", 0.0),
                    psum_launches=coll.get("collective.psum.dcn", 0.0))
                straggler = obs.straggler_report()
            if ref_pred is None:
                ref_pred = pred
            parity = bool(np.allclose(pred, ref_pred, rtol=1e-4, atol=1e-4))
            dcn_b = float(coll.get("collective.psum_bytes.dcn", 0.0))
            ici_b = float(coll.get("collective.psum_bytes.ici", 0.0))
            # the acceptance bound: the cross-host hop may carry at most
            # the inter-group fraction (payload / ici_size) of the flat
            # allreduce's bytes — 1% slack covers padding-to-ici_size
            dcn_ok = (dcn_b <= flat_bytes / per * 1.01 + 1024
                      if dcn_b and flat_bytes else None)
            host_skew = None
            if attr is not None and attr.get("host_ids"):
                host_skew = [{"host": int(g),
                              "compute_ms": round(c * 1e3, 4)}
                             for g, c in zip(attr["host_ids"],
                                             attr["per_host_compute_s"])]
            entries.append({
                "hosts": h,
                "per_host": per,
                "seconds": round(best, 4),
                "rows_per_s": round(rows / best, 1),
                "speedup_vs_1": round(entries[0]["seconds"] / best, 3)
                if entries else 1.0,
                "psum_ici": int(coll.get("collective.psum.ici", 0)),
                "psum_dcn": int(coll.get("collective.psum.dcn", 0)),
                "psum_bytes_ici": ici_b,
                "psum_bytes_dcn": dcn_b,
                "all_gather_bytes_ici":
                    float(coll.get("collective.all_gather_bytes.ici", 0.0)),
                "dcn_fraction": round(dcn_b / flat_bytes, 4)
                if dcn_b and flat_bytes else None,
                "dcn_le_flat_fraction": dcn_ok,
                "parity_ok": parity,
                "slowest_host": None if attr is None
                else attr.get("slowest_host"),
                "host_skew": host_skew,
            })
            e = entries[-1]
            print(f"  multihost {h}x{per}: {best:.3f}s "
                  f"({rows / best:,.0f} rows/s, dcn "
                  f"{dcn_b / 1e3:.2f} KB/trace "
                  f"[{e['dcn_fraction'] if e['dcn_fraction'] is not None else '-'}"
                  f" of flat], parity={parity}, "
                  f"slowest_host={e['slowest_host']})", file=sys.stderr)
    finally:
        GLOBAL_CONF.set("sml.obs.enabled", bool(prev_obs))
    return {
        "rows": rows, "n_features": F, "n_trees": MULTICHIP_TREES,
        "max_depth": MULTICHIP_DEPTH, "max_bins": MULTICHIP_BINS,
        "backend": jax.default_backend(), "n_devices": n_avail,
        "flat_psum_bytes": flat_bytes,
        "note": "best-of-3 warm fits per host-group shape; per-hop "
                "collective counters are per-TRACE statics; "
                "dcn_fraction = the cross-host hop's psum bytes as a "
                "fraction of the flat allreduce payload (bounded by "
                "1/per_host — the hierarchical-allreduce win); "
                "parity_ok = same model as the 1-host-group mesh "
                "(layout-invariant sampling); host_skew = per-host "
                "compute attribution from group-aware shard probes "
                "(obs/_skew.py host lanes)",
        "shapes": entries,
        # aggregate straggler attribution for the LAST shape (obs.reset
        # runs per shape): includes the host-level roll-up
        "straggler": straggler,
    }


def multihost_main(rows: int) -> None:
    """Run the multi-host leg standalone, merge the `multihost` block
    into the bench sidecar, and print the short headline JSON last."""
    block = run_multihost(rows)
    doc = {}
    if os.path.exists(LEGS_FILE):
        with open(LEGS_FILE) as f:
            doc = json.load(f)
    doc["multihost"] = block
    with open(LEGS_FILE, "w") as f:
        json.dump(doc, f, indent=1)
    fracs = [e["dcn_fraction"] for e in block["shapes"]
             if e.get("dcn_fraction")]
    straggler = block.get("straggler") or {}
    print(json.dumps({
        "metric": "multihost DCN-byte fraction (hierarchical vs flat)",
        "value": min(fracs) if fracs else None,
        "unit": "x of flat allreduce payload (cross-host hop)",
        "n_devices": block["n_devices"],
        "backend": block["backend"],
        "parity_ok": all(e["parity_ok"] for e in block["shapes"]),
        "dcn_bound_ok": all(e["dcn_le_flat_fraction"] in (True, None)
                            for e in block["shapes"]),
        "slowest_host": straggler.get("slowest_host"),
        "host_skew_ratio": straggler.get("host_skew_ratio"),
        "legs_file": "bench_legs.json",
    }))


# ---------------------------------------------------------- kernelbench leg
KERNELBENCH_ROWS = 60_000
KERNELBENCH_TREES = 8


def run_kernelbench(rows: int = KERNELBENCH_ROWS) -> dict:
    """`--kernelbench`: the fused-kernel sweep (ISSUE 9) — the same
    bootstrap-forest fit across a maxBins × maxDepth grid, timed once
    under `sml.tree.kernel=xla` (the one-hot dot + cumsum HLO chain) and
    once under `=pallas` (the fused `native/hist_kernel.py` bin-accumulate
    + split-scan launches), best-of-3 warm fits per leg with the compile
    paid in a warmup fit.

    Per leg the sidecar records both walls, the ratio, the per-path
    `kernel.*` counter deltas captured from the warmup trace
    (pallas_launch/interpret are trace-time statics, like collective.*),
    and a bit-parity check of the two paths' predictions. On non-TPU
    backends the pallas path runs in INTERPRET mode — those numbers
    measure emulation overhead, not kernel speed (the `interpret` flag in
    the block says which kind of run this is); `obs/regress.py` judges
    `kernel.fallback` growth across committed sidecars as a regression
    either way. Results merge into the bench sidecar as the `kernel`
    block, rendered by scripts/render_perf.py."""
    import jax

    from sml_tpu import obs
    from sml_tpu.conf import GLOBAL_CONF
    from sml_tpu.ml._tree_models import _fit_ensemble

    rng = np.random.default_rng(9)
    F = 10
    X = rng.normal(size=(rows, F)).astype(np.float32)
    y = (X[:, 0] * 2 - X[:, 1] ** 2 + 0.3 * X[:, 3]
         + rng.normal(0, 0.3, rows)).astype(np.float32)
    probe = X[:4096]

    prev_obs = GLOBAL_CONF.get("sml.obs.enabled")
    prev_kernel = GLOBAL_CONF.get("sml.tree.kernel")
    GLOBAL_CONF.set("sml.obs.enabled", True)
    legs = []
    try:
        for max_bins in (32, 128):
            for max_depth in (4, 6):
                entry = {"max_bins": max_bins, "max_depth": max_depth}
                counters = {}
                preds = {}
                for path in ("xla", "pallas"):
                    GLOBAL_CONF.set("sml.tree.kernel", path)

                    def fit():
                        return _fit_ensemble(
                            X, y, categorical={}, max_depth=max_depth,
                            max_bins=max_bins, min_instances=1,
                            min_info_gain=0.0, n_trees=KERNELBENCH_TREES,
                            feature_k=None, bootstrap=True, subsample=1.0,
                            seed=7, loss="squared")

                    obs.reset()
                    spec = fit()  # warmup: compile + trace-time counters
                    snap = obs.RECORDER.counters()
                    for k, v in snap.items():
                        if k.startswith(("kernel.", "tree.fit_dispatch")):
                            counters[f"{path}:{k}"] = float(v)
                    best = float("inf")
                    for _ in range(3):
                        t0 = time.perf_counter()
                        fit()
                        best = min(best, time.perf_counter() - t0)
                    entry[f"{path}_s"] = round(best, 4)
                    preds[path] = spec.predict_margin(probe)
                entry["pallas_vs_xla"] = round(
                    entry["xla_s"] / entry["pallas_s"], 3)
                entry["parity_ok"] = bool(
                    np.array_equal(preds["xla"], preds["pallas"]))
                entry["kernel_counters"] = {
                    "kernel.pallas_launch":
                        counters.get("pallas:kernel.pallas_launch", 0.0),
                    "kernel.interpret":
                        counters.get("pallas:kernel.interpret", 0.0),
                    "kernel.fallback":
                        counters.get("pallas:kernel.fallback", 0.0)
                        + counters.get("xla:kernel.fallback", 0.0),
                }
                legs.append(entry)
                print(f"  kernel b{max_bins} d{max_depth}: "
                      f"xla {entry['xla_s']:.3f}s, pallas "
                      f"{entry['pallas_s']:.3f}s "
                      f"({entry['pallas_vs_xla']}x, parity="
                      f"{entry['parity_ok']}, launches "
                      f"{entry['kernel_counters']['kernel.pallas_launch']:.0f})",
                      file=sys.stderr)
    finally:
        GLOBAL_CONF.set("sml.obs.enabled", bool(prev_obs))
        GLOBAL_CONF.set("sml.tree.kernel", prev_kernel)
    return {
        "rows": rows, "n_features": F, "n_trees": KERNELBENCH_TREES,
        "backend": jax.default_backend(),
        "interpret": jax.default_backend() != "tpu",
        "note": "best-of-3 warm fits per (maxBins, maxDepth, path); "
                "kernel.* counters are per-TRACE statics from the warmup "
                "fit; on non-TPU backends the pallas path runs in "
                "interpret mode (parity, not speed — see docs/KERNELS.md)",
        "legs": legs,
    }


# ------------------------------------------- kernelbench inference autotuner
KERNELBENCH_INFER_SHAPES = ((32, 4), (128, 6))   # (maxBins, maxDepth)
KERNELBENCH_INFER_BATCHES = (8192, 49152)        # scoring batch widths
KERNELBENCH_INFER_BLOCKS = (512, 2048, 8192)     # pallas block_rows sweep


def run_kernelbench_infer(rows: int = KERNELBENCH_ROWS) -> dict:
    """`--kernelbench` tentpole 2 (ISSUE 12): the traversal-kernel
    AUTOTUNER. For each (model shape, maxBins, batch width) point, sweep
    the candidate traversal specs — the XLA where-sum path plus the
    fused `native/traverse_kernel.py` launch at several `block_rows`
    schemes (the conf default `sml.infer.kernelBlockRows` among them) —
    best-of-3 warm scoring dispatches apiece, then PERSIST the winner
    into the prewarm manifest (`parallel.prewarm.record_tuned`), so
    replica spin-up and later processes resolve the tuned spec without
    re-sweeping (`sml.infer.autotune`).

    Every candidate's predictions are checked bit-identical against the
    XLA path (the interpret-mode contract on non-TPU backends, where
    these walls measure emulation overhead, not kernel speed — the
    `interpret` flag says which kind of run this is). `replay_ok` proves
    the round trip: with the sweep conf restored, the live resolver
    returns each point's persisted winner from the manifest alone, and
    the `infer_kernel` prewarm rebuilder replays one entry clean.
    Results merge into the sidecar as the `kernel_infer` block —
    separate from the fit sweep's `kernel` block, so the two coexist —
    rendered by scripts/render_perf.py; `obs/regress.py` flags a
    vanished block, fallback growth, or a lost beats-default/replay
    proof."""
    import jax

    from sml_tpu import obs
    from sml_tpu.conf import GLOBAL_CONF
    from sml_tpu.ml import inference, tree_impl
    from sml_tpu.ml._tree_models import _fit_ensemble
    from sml_tpu.parallel import prewarm
    from sml_tpu.utils.profiler import PROFILER

    rng = np.random.default_rng(11)
    F = 10
    n_fit = min(rows, 60_000)
    X = rng.normal(size=(n_fit, F)).astype(np.float32)
    y = (X[:, 0] * 2 - X[:, 1] ** 2 + 0.3 * X[:, 3]
         + rng.normal(0, 0.3, n_fit)).astype(np.float32)
    Xs = rng.normal(size=(max(KERNELBENCH_INFER_BATCHES), F)) \
        .astype(np.float32)

    prev = {k: GLOBAL_CONF.get(k) for k in
            ("sml.obs.enabled", "sml.infer.kernel",
             "sml.infer.kernelBlockRows", "sml.infer.autotune")}
    GLOBAL_CONF.set("sml.obs.enabled", True)
    GLOBAL_CONF.set("sml.infer.autotune", False)  # sweep forces specs
    default_rows = int(prev["sml.infer.kernelBlockRows"])
    legs = []
    tuned = []
    t_sweep0 = time.perf_counter()
    obs.reset()
    try:
        for max_bins, max_depth in KERNELBENCH_INFER_SHAPES:
            spec = _fit_ensemble(
                X, y, categorical={}, max_depth=max_depth,
                max_bins=max_bins, min_instances=1, min_info_gain=0.0,
                n_trees=KERNELBENCH_TREES, feature_k=None, bootstrap=True,
                subsample=1.0, seed=7, loss="squared")
            sf, sb, lv, w = spec.stacked()
            for batch in KERNELBENCH_INFER_BATCHES:
                binned = tree_impl.bin_with(
                    np.asarray(Xs[:batch], np.float64), spec.binning)

                def score():
                    return inference.predict_forest_sharded(
                        binned, sf, sb, lv, w, spec.depth,
                        base=spec.base, n_bins=max_bins)

                # the conf default is ALWAYS a candidate (the spec the
                # winner must beat), whatever the knob is set to
                blocks = sorted(set(KERNELBENCH_INFER_BLOCKS)
                                | {default_rows})
                cands = [("xla", 0)] + [("pallas", br) for br in blocks]
                entry = {"max_bins": max_bins, "max_depth": max_depth,
                         "batch_rows": batch, "candidates": []}
                preds = {}
                for kern, br in cands:
                    GLOBAL_CONF.set("sml.infer.kernel", kern)
                    GLOBAL_CONF.set("sml.infer.kernelBlockRows",
                                    br or default_rows)
                    preds[(kern, br)] = score()  # warmup: compile
                    best = float("inf")
                    for _ in range(3):
                        t0 = time.perf_counter()
                        score()
                        best = min(best, time.perf_counter() - t0)
                    entry["candidates"].append(
                        {"kernel": kern, "block_rows": br,
                         "seconds": round(best, 4)})
                xla_pred = preds[("xla", 0)]
                entry["parity_ok"] = all(
                    np.array_equal(xla_pred, p) for p in preds.values())
                default_s = next(
                    c["seconds"] for c in entry["candidates"]
                    if c["kernel"] == "pallas"
                    and c["block_rows"] == default_rows)
                winner = min(entry["candidates"], key=lambda c: c["seconds"])
                entry["default_s"] = default_s
                entry["best_s"] = winner["seconds"]
                entry["best_spec"] = {"kernel": winner["kernel"],
                                      "block_rows": winner["block_rows"]}
                entry["beats_default"] = winner["seconds"] < default_s
                key = inference.infer_spec_key(
                    sf.shape[0], spec.depth, F, max_bins, batch)
                prewarm.record_tuned("infer_kernel", key,
                                     entry["best_spec"])
                tuned.append((key, entry["best_spec"]))
                legs.append(entry)
                print(f"  infer b{max_bins} d{max_depth} n{batch}: "
                      f"default {default_s:.4f}s, best "
                      f"{winner['seconds']:.4f}s "
                      f"({winner['kernel']}/{winner['block_rows']}, "
                      f"parity={entry['parity_ok']})", file=sys.stderr)
        sweep_s = time.perf_counter() - t_sweep0
        PROFILER.count("infer.kernel.autotune_s", float(sweep_s))
        # round-trip proof: the live resolver must return each persisted
        # winner from the manifest WITHOUT a sweep, and the prewarm
        # rebuilder must replay an entry clean (replica spin-up's path)
        for k in ("sml.infer.kernel", "sml.infer.kernelBlockRows"):
            GLOBAL_CONF.set(k, prev[k])
        GLOBAL_CONF.set("sml.infer.autotune", True)
        replay_ok = True
        for key, spec_rec in tuned:
            kern, br, was_tuned = inference.resolve_infer_kernel(
                n_trees=key["trees"], depth=key["depth"],
                n_nodes=2 ** (key["depth"] + 1) - 1,
                n_feat=key["features"], n_bins=key["bins"],
                n_rows=key["rows"])
            if (kern, br) != (spec_rec["kernel"], spec_rec["block_rows"]) \
                    or not was_tuned:
                replay_ok = False
        try:
            inference._replay_infer_kernel(
                {"key": tuned[0][0], "spec": tuned[0][1]})
        except Exception:
            replay_ok = False
        fallbacks = float(obs.RECORDER.counters()
                          .get("infer.kernel.fallback", 0.0))
    finally:
        for k, v in prev.items():
            GLOBAL_CONF.set(k, v)
    return {
        "rows": n_fit, "n_features": F, "n_trees": KERNELBENCH_TREES,
        "backend": jax.default_backend(),
        "interpret": jax.default_backend() != "tpu",
        "default_block_rows": default_rows,
        "note": "best-of-3 warm scoring dispatches per candidate spec; "
                "winners persisted to the prewarm manifest "
                "(record_tuned) and resolved back without a sweep "
                "(replay_ok); on non-TPU backends pallas runs in "
                "interpret mode (parity, not speed — docs/KERNELS.md)",
        "legs": legs,
        "fallbacks": fallbacks,
        "tuned_beats_default": any(e["beats_default"] for e in legs),
        "replay_ok": replay_ok,
        "autotune_sweep_s": round(sweep_s, 3),
    }


def kernelbench_main(rows: int) -> None:
    """Run the fit-kernel sweep AND the inference autotuner standalone,
    merge their blocks into the bench sidecar — `kernel` (fit) and
    `kernel_infer` (scoring) are SEPARATE keys so neither run clobbers
    the other — and print the short headline JSON last."""
    block = run_kernelbench(rows)
    infer_block = run_kernelbench_infer(rows)
    doc = {}
    if os.path.exists(LEGS_FILE):
        with open(LEGS_FILE) as f:
            doc = json.load(f)
    doc["kernel"] = block
    doc["kernel_infer"] = infer_block
    with open(LEGS_FILE, "w") as f:
        json.dump(doc, f, indent=1)
    best = max(e["pallas_vs_xla"] for e in block["legs"])
    print(json.dumps({
        "metric": "fused-kernel sweep (pallas vs xla)",
        "value": best,
        "unit": "x vs xla path (best leg)",
        "backend": block["backend"],
        "interpret": block["interpret"],
        "parity_ok": all(e["parity_ok"] for e in block["legs"])
        and all(e["parity_ok"] for e in infer_block["legs"]),
        "fallbacks": sum(e["kernel_counters"]["kernel.fallback"]
                         for e in block["legs"])
        + infer_block["fallbacks"],
        "infer_tuned_beats_default": infer_block["tuned_beats_default"],
        "infer_replay_ok": infer_block["replay_ok"],
        "legs_file": "bench_legs.json",
    }))


# ------------------------------------------------------------- scale leg
SCALE_INGEST_ROWS = 10_000_000
SCALE_INGEST_F = 10
SCALE_INGEST_TREES = 2
SCALE_INGEST_DEPTH = 4
SCALE_INGEST_BINS = 32
SCALE_PREDICT_CAP = 1_000_000


def make_scale_source(rows: int, chunk_rows=None):
    """The bench synthetic generator as a ChunkSource: every chunk is
    MANUFACTURED from its global row range (per-chunk seeded rng), so the
    raw float dataset never exists whole on host — the two ingest passes
    regenerate identical chunks. Same functional form as the multichip
    leg's dataset."""
    from sml_tpu.frame._chunks import GeneratorChunkSource

    def make(start, stop):
        r = np.random.default_rng((1_000_003 * start) ^ 0xC0FFEE)
        n = stop - start
        X = r.normal(size=(n, SCALE_INGEST_F)).astype(np.float32)
        y = (X[:, 0] * 3 - X[:, 1] ** 2 + 0.5 * X[:, 2]
             + r.normal(0, 0.3, n)).astype(np.float32)
        return X, y

    return GeneratorChunkSource(rows, SCALE_INGEST_F, make,
                                chunk_rows=chunk_rows,
                                fingerprint=("bench-scale", rows,
                                             chunk_rows or 0))


def run_scale(rows: int = SCALE_INGEST_ROWS) -> dict:
    """`--rows N`: the out-of-core data-plane leg (ISSUE 10) — chunked
    columnar ingestion + streamed bin quantization + double-buffered H2D
    prefetch at data-plane scale, then a small tree fit and a streamed
    predict pass over the ingested compact representation.

    The block records ingest throughput (rows/s through sketch +
    quantize + device assembly), peak HOST bytes actually held by the
    plane (chunk buffers + the compact mirror — vs the raw float bytes
    it SAW but never held), the HBM ledger peaks (`chunk_stage` +
    `bin_cache` bound device residency to the compact representation),
    and the prefetch-overlap attribution: serial host-quantization
    seconds vs the pipelined wall, plus the `ingest.dispatch`/
    `ingest.drain` event-order proof that chunk i+1's staging overlapped
    chunk i's device work. Results merge into the bench sidecar as the
    `scale` block, rendered by scripts/render_perf.py; vanishing-block
    and rows/s regressions are judged by obs/regress.py."""
    import jax

    from sml_tpu import obs
    from sml_tpu.conf import GLOBAL_CONF
    from sml_tpu.ml._chunked import (fit_ensemble_chunked, ingest_source,
                                     iter_predictions)

    prev_obs = GLOBAL_CONF.get("sml.obs.enabled")
    GLOBAL_CONF.set("sml.obs.enabled", True)
    try:
        obs.reset()
        # bound the chunk COUNT at scale: each device bin-accumulate on a
        # backend that ignores donation (XLA:CPU) copies the full buffer,
        # so per-chunk cost grows with n — ~32 chunks keeps the CPU
        # artifact honest while real-TPU donation makes the per-chunk
        # cost O(chunk) at any count
        chunk_rows = max(GLOBAL_CONF.getInt("sml.data.chunkRows"),
                         -(-rows // 32))
        source = make_scale_source(rows, chunk_rows=chunk_rows)
        t0 = time.perf_counter()
        ing = ingest_source(source, SCALE_INGEST_BINS, label="scale")
        ingest_s = time.perf_counter() - t0

        # event-order proof: some chunk i+1 dispatched before chunk i
        # drained (the double-buffer actually double-buffered)
        evs = [(e.name, e.args.get("chunk")) for e in obs.RECORDER.events()
               if e.name in ("ingest.dispatch", "ingest.drain")]
        overlap_ok = False
        if any(n == "ingest.drain" for n, _ in evs):
            first_drain = next(i for i, (n, c) in enumerate(evs)
                               if n == "ingest.drain")
            ahead = {c for n, c in evs[:first_drain]
                     if n == "ingest.dispatch"}
            overlap_ok = len(ahead) >= 2

        t0 = time.perf_counter()
        spec = fit_ensemble_chunked(
            source, max_depth=SCALE_INGEST_DEPTH,
            max_bins=SCALE_INGEST_BINS, n_trees=SCALE_INGEST_TREES,
            bootstrap=True, seed=42)  # ingest memo-hit: fit cost only
        fit_s = time.perf_counter() - t0

        # streamed predict over a capped prefix — SAME chunking as the
        # ingest so the per-chunk generator seeds reproduce the same
        # rows; rmse is a sanity metric, unpinned
        p_rows = min(rows, SCALE_PREDICT_CAP)
        psrc = make_scale_source(p_rows, chunk_rows=chunk_rows)
        t0 = time.perf_counter()
        sse = 0.0
        cnt = 0
        for pred, yc in iter_predictions(spec, psrc):
            d = pred - np.asarray(yc, dtype=np.float64)
            sse += float(d @ d)
            cnt += d.size
        predict_s = time.perf_counter() - t0

        led = obs.LEDGER.snapshot()
        st = ing.stats
        prep_s = st["prep_s"]
        dispatch_s = st.get("dispatch_s", 0.0)
        pipeline_s = st["pipeline_s"]
        block = {
            "rows": rows,
            "n_features": SCALE_INGEST_F,
            "chunk_rows": st["chunk_rows"],
            "n_chunks": st["n_chunks"],
            "backend": jax.default_backend(),
            "n_devices": len(jax.devices()),
            "ingest_seconds": round(ingest_s, 3),
            "ingest_rows_per_s": round(rows / max(ingest_s, 1e-9), 1),
            "sketch_exact": st["sketch_exact"],
            "sketch_seconds": st["sketch_s"],
            "fit_seconds": round(fit_s, 3),
            "fit_trees": SCALE_INGEST_TREES,
            "fit_depth": SCALE_INGEST_DEPTH,
            "max_bins": SCALE_INGEST_BINS,
            "predict_rows": p_rows,
            "predict_seconds": round(predict_s, 3),
            "predict_rows_per_s": round(p_rows / max(predict_s, 1e-9), 1),
            "rmse": round(float(np.sqrt(sse / max(cnt, 1))), 6),
            # residency ledger: what the plane SAW vs what it HELD
            "raw_bytes_seen": st["raw_bytes"],
            "compact_bytes": st["compact_bytes"],
            "host_peak_bytes": st["compact_bytes"]
            + st["chunk_rows"] * SCALE_INGEST_F * 4 * 4,  # ~4 raw chunks
            "hbm": {
                "chunk_stage_peak_bytes": int(
                    led.get("chunk_stage", {}).get("peak", 0)),
                "bin_cache_peak_bytes": int(
                    led.get("bin_cache", {}).get("peak", 0)),
            },
            "prefetch": {
                "depth": st["prefetch_depth"],
                # serial-equivalent = host quantization + device-side
                # submission walls run back to back; overlap > 1 is the
                # wall the double buffer actually bought
                "prep_serial_s": prep_s,
                "dispatch_serial_s": dispatch_s,
                "pipeline_s": pipeline_s,
                "overlap": round((prep_s + dispatch_s)
                                 / max(pipeline_s, 1e-9), 3),
                "events_ok": overlap_ok,
            },
            "note": "chunked columnar ingest (two-pass: mergeable "
                    "quantile sketch, then per-chunk quantize + "
                    "double-buffered H2D + device bin-accumulate); raw "
                    "float data never resident whole on host or device "
                    "— HBM holds the compact matrix + ~prefetchChunks "
                    "chunk blocks (docs/DATAPLANE.md)",
        }
        print(f"  scale {rows:,} rows: ingest {ingest_s:.1f}s "
              f"({rows / ingest_s:,.0f} rows/s, sketch_exact="
              f"{st['sketch_exact']}), fit {fit_s:.1f}s, predict "
              f"{p_rows:,} in {predict_s:.1f}s; raw seen "
              f"{st['raw_bytes'] / 1e9:.2f} GB vs compact "
              f"{st['compact_bytes'] / 1e6:.1f} MB, chunk_stage peak "
              f"{block['hbm']['chunk_stage_peak_bytes'] / 1e6:.1f} MB, "
              f"overlap {block['prefetch']['overlap']}x "
              f"(events_ok={overlap_ok})", file=sys.stderr)
        return block
    finally:
        GLOBAL_CONF.set("sml.obs.enabled", bool(prev_obs))


def scale_main(rows: int) -> None:
    """Run the out-of-core leg standalone, merge the `scale` block into
    the bench sidecar, and print the short headline JSON last."""
    block = run_scale(rows)
    doc = {}
    if os.path.exists(LEGS_FILE):
        with open(LEGS_FILE) as f:
            doc = json.load(f)
    doc["scale"] = block
    with open(LEGS_FILE, "w") as f:
        json.dump(doc, f, indent=1)
    print(json.dumps({
        "metric": "out-of-core ingest throughput",
        "value": block["ingest_rows_per_s"],
        "unit": "rows/s",
        "rows": block["rows"],
        "backend": block["backend"],
        "overlap": block["prefetch"]["overlap"],
        "overlap_events_ok": block["prefetch"]["events_ok"],
        "chunk_stage_peak_mb": round(
            block["hbm"]["chunk_stage_peak_bytes"] / 1e6, 2),
        "compact_vs_raw": round(block["raw_bytes_seen"]
                                / max(block["compact_bytes"], 1), 2),
        "legs_file": "bench_legs.json",
    }))


# --------------------------------------------------------------- drift leg
DRIFT_ROWS = 120_000
DRIFT_HOLDOUT = 20_000
DRIFT_F = 8          # 7 continuous + 1 categorical slot
DRIFT_CARD = 6
DRIFT_EXPECTED = ["f0", "f2", "f7"]  # the features the injection moves


def make_drift_frame(rows, seed, shift=False):
    """Synthetic (X, y) for the drift leg. `shift=True` injects the
    covariate shift the detector must name: feature 0 moves +1.25
    (location), feature 2 scales 1.9x, and the categorical slot 7's
    frequency table inverts — everything else stays iid with the
    training distribution, so flags on other features are false
    positives by construction."""
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(rows, DRIFT_F)).astype(np.float64)
    cat_p = np.asarray([0.30, 0.25, 0.20, 0.15, 0.07, 0.03])
    if shift:
        X[:, 0] += 1.25
        X[:, 2] *= 1.9
        cat_p = cat_p[::-1].copy()
    X[:, 7] = rng.choice(DRIFT_CARD, size=rows, p=cat_p)
    y = (3.0 * X[:, 0] - X[:, 1] ** 2 + 0.5 * X[:, 2]
         + rng.normal(0, 0.3, rows)).astype(np.float32)
    return X, y


def run_drift(rows: int = DRIFT_ROWS) -> dict:
    """`--drift`: the model/data-observability proof leg (ISSUE 11) —
    fit a small forest through the chunked ingest (so the fitted model
    carries its training `DriftBaseline` built from the full-data
    pass-1 sketch), then judge three streams against that baseline:

    - an IID holdout draw (same distribution, fresh seed) must come
      back CLEAN — the noise-aware thresholds' no-false-positive proof;
    - an injected covariate shift (location + scale + categorical
      frequency) must FLAG, naming exactly the moved features, with the
      prediction distribution flagging too;
    - the same shifted stream re-ingested chunk-by-chunk with
      `drift_baseline=` must flag chunks (the continuous-training
      refit-trigger signal), while the iid stream's chunks stay clean.

    The block also proves the baseline save→load round trip is
    bit-compatible (reloaded-vs-self distance exactly zero). Results
    merge into the bench sidecar as the `drift` block, rendered by
    scripts/render_perf.py; a vanished block or a lost proof is flagged
    by obs/regress.py."""
    import jax

    from sml_tpu import obs
    from sml_tpu.conf import GLOBAL_CONF
    from sml_tpu.frame._chunks import ArrayChunkSource
    from sml_tpu.ml._chunked import fit_ensemble_chunked, ingest_source
    from sml_tpu.obs import drift as driftmod

    prev_obs = GLOBAL_CONF.get("sml.obs.enabled")
    GLOBAL_CONF.set("sml.obs.enabled", True)
    try:
        obs.reset()
        cat = {7: DRIFT_CARD}
        X, y = make_drift_frame(rows, seed=11)
        t0 = time.perf_counter()
        spec = fit_ensemble_chunked(
            ArrayChunkSource(X, y, chunk_rows=max(rows // 8, 1)),
            categorical=cat, max_depth=4, max_bins=32, n_trees=4,
            bootstrap=True, seed=7)
        fit_s = time.perf_counter() - t0
        baseline = spec.baseline
        assert baseline is not None, "chunked fit did not stamp a baseline"

        # save->load bit-compat: a reloaded baseline's self-distance is 0
        reloaded = driftmod.DriftBaseline.from_dict(
            json.loads(json.dumps(baseline.to_dict())))
        self_d = max(
            max(driftmod.psi_distance(sk, reloaded.features.features[f]),
                driftmod.quantile_shift(sk, reloaded.features.features[f]))
            for f, sk in baseline.features.features.items())

        t0 = time.perf_counter()
        Xh, _ = make_drift_frame(DRIFT_HOLDOUT, seed=999)
        rep_iid = driftmod.evaluate_block(
            baseline, Xh, spec.predict_margin(Xh), name="bench-iid")
        Xs, ys = make_drift_frame(DRIFT_HOLDOUT, seed=555, shift=True)
        rep_shift = driftmod.evaluate_block(
            baseline, Xs, spec.predict_margin(Xs), name="bench-shift")
        judge_s = time.perf_counter() - t0
        named_ok = set(DRIFT_EXPECTED).issubset(set(rep_shift["flagged"]))

        # ingest-time monitor: per-chunk verdicts against the baseline
        def _ingest_chunks(Xi, yi, tag):
            ingest_source(
                ArrayChunkSource(Xi, yi, chunk_rows=DRIFT_HOLDOUT // 8),
                32, cat, label=tag, drift_baseline=baseline)
            rep = obs.engine_health()["drift"]["ingest"]
            ch = rep.get("chunks") or {}
            return int(ch.get("observed", 0)), int(ch.get("flagged", 0))

        iid_chunks, iid_flagged = _ingest_chunks(
            *make_drift_frame(DRIFT_HOLDOUT, seed=333), "drift-iid")
        shift_chunks, shift_flagged = _ingest_chunks(Xs, ys, "drift-shift")

        block = {
            "rows": rows,
            "holdout_rows": DRIFT_HOLDOUT,
            "n_features": DRIFT_F,
            "backend": jax.default_backend(),
            "fit_seconds": round(fit_s, 3),
            "judge_seconds": round(judge_s, 3),
            "baseline": {
                "rows": baseline.n_rows,
                "sampled_rows": baseline.sampled_rows,
                "sketch_exact": bool(baseline.features.exact),
                "reload_self_distance": self_d,
                "reload_bit_compat": bool(self_d == 0.0),
            },
            "iid": {
                "flagged": bool(rep_iid["n_flagged"] > 0),
                "n_flagged": int(rep_iid["n_flagged"]),
                "max_severity": float(rep_iid["max_severity"]),
            },
            "shift": {
                "flagged": bool(rep_shift["n_flagged"] > 0),
                "n_flagged": int(rep_shift["n_flagged"]),
                "max_severity": float(rep_shift["max_severity"]),
                "top_features": rep_shift["top"],
                "flagged_features": rep_shift["flagged"],
                "expected": DRIFT_EXPECTED,
                "named_ok": bool(named_ok),
                "prediction_flagged": bool(
                    (rep_shift.get("prediction") or {}).get("flagged")),
            },
            "ingest": {
                "iid_chunks": iid_chunks,
                "iid_flagged_chunks": iid_flagged,
                "shift_chunks": shift_chunks,
                "shift_flagged_chunks": shift_flagged,
            },
            "note": "distances = per-feature PSI over baseline deciles + "
                    "normalized quantile shift + categorical frequency "
                    "PSI, judged against noise-aware thresholds "
                    "(resampled-baseline self-distance floors x "
                    "sml.obs.driftMargin); the iid row is the "
                    "no-false-positive proof, the shift row the "
                    "detection proof (docs/OBSERVABILITY.md)",
        }
        print(f"  drift: iid clean={not block['iid']['flagged']} "
              f"(max severity {block['iid']['max_severity']:.2f}), "
              f"shift flagged={block['shift']['flagged']} "
              f"({block['shift']['flagged_features']} vs expected "
              f"{DRIFT_EXPECTED}, named_ok={named_ok}, prediction_flagged="
              f"{block['shift']['prediction_flagged']}); ingest chunks "
              f"iid {iid_flagged}/{iid_chunks} vs shift "
              f"{shift_flagged}/{shift_chunks} flagged; baseline reload "
              f"self-distance {self_d}", file=sys.stderr)
        return block
    finally:
        GLOBAL_CONF.set("sml.obs.enabled", bool(prev_obs))


def drift_main(rows: int) -> None:
    """Run the drift leg standalone, merge the `drift` block into the
    bench sidecar, and print the short headline JSON last."""
    block = run_drift(rows)
    doc = {}
    if os.path.exists(LEGS_FILE):
        with open(LEGS_FILE) as f:
            doc = json.load(f)
    doc["drift"] = block
    with open(LEGS_FILE, "w") as f:
        json.dump(doc, f, indent=1)
    ok = (block["shift"]["flagged"] and block["shift"]["named_ok"]
          and not block["iid"]["flagged"]
          and block["baseline"]["reload_bit_compat"])
    print(json.dumps({
        "metric": "drift detection (injected covariate shift vs iid "
                  "holdout)",
        "value": 1.0 if ok else 0.0,
        "unit": "1 = shift flagged + features named + iid clean + "
                "baseline round-trip bit-compatible",
        "shift_flagged": block["shift"]["flagged"],
        "named_ok": block["shift"]["named_ok"],
        "iid_clean": not block["iid"]["flagged"],
        "ingest_flagged_chunks": block["ingest"]["shift_flagged_chunks"],
        "backend": block["backend"],
        "legs_file": "bench_legs.json",
    }))
    if not ok:
        sys.exit(1)


# --------------------------------------------------- continuous-training leg
CT_ROWS = 24_000
CT_F = 6


def _ct_frame(rows, seed, shift=False):
    """Synthetic (X, y) for the continuous-training leg: `shift=True`
    injects the covariate drift the trainer must catch (f0 location,
    f2 scale) — the label function is unchanged, so a warm-start refit
    on the drifted window genuinely improves window RMSE."""
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(rows, CT_F))
    if shift:
        X[:, 0] += 1.5
        X[:, 2] *= 2.0
    y = (3.0 * X[:, 0] + 0.5 * X[:, 2] - X[:, 1] ** 2
         + rng.normal(0, 0.3, rows)).astype(np.float32)
    return X, y


def run_ct(rows: int = CT_ROWS) -> dict:
    """`--ct`: the closed-loop continuous-training proof (ISSUE 14) —
    seed a baseline-carrying GBT into the registry and serve it, then
    run `sml_tpu.ct.ContinuousTrainer` over two live Delta streams:

    - a DRIFTING stream (injected covariate shift appended as new Delta
      versions) must trigger >= 1 WARM-START refit whose new version
      passes the canary gate (Staging mirror via sml.serve
      .canaryFraction, zero canary/request errors, window-quality win)
      and hot-swaps Production on the live endpoint;
    - an IID control stream must trigger ZERO refits across the same
      number of cycles (the drift trigger's no-false-positive proof).

    Results merge into the bench sidecar as the `ct` block, rendered by
    scripts/render_perf.py; a vanished block, a lost promotion, or a
    refit on the iid control is flagged by obs/regress.py."""
    import shutil
    import tempfile

    import jax
    import pandas as pd

    import sml_tpu.tracking as mlflow
    from sml_tpu import TpuSession, obs
    from sml_tpu.conf import GLOBAL_CONF
    from sml_tpu.ct import CanaryGate, ContinuousTrainer, DeltaChunkSource
    from sml_tpu.frame._chunks import ArrayChunkSource
    from sml_tpu.ml._chunked import fit_ensemble_chunked
    from sml_tpu.ml.regression import GBTRegressionModel
    from sml_tpu.serving import ServingEndpoint
    from sml_tpu.tracking import _store
    from sml_tpu.utils.profiler import PROFILER

    prev_obs = GLOBAL_CONF.get("sml.obs.enabled")
    prev_prof = GLOBAL_CONF.get("sml.profiler.enabled")
    prev_uri = _store.get_tracking_uri()
    GLOBAL_CONF.set("sml.obs.enabled", True)
    GLOBAL_CONF.set("sml.profiler.enabled", True)  # hot-swap receipts
    tmp = tempfile.mkdtemp(prefix="sml-ct-bench-")
    mlflow.set_tracking_uri(os.path.join(tmp, "runs"))
    spark = TpuSession.builder.appName("ct-bench").getOrCreate()
    cols = [f"f{i}" for i in range(CT_F)]
    try:
        obs.reset()
        # ---- seed model: baseline-carrying boosted ensemble, v1 in
        # Production, served with the canary mirror armed
        Xt, yt = _ct_frame(rows, seed=11)
        t0 = time.perf_counter()
        spec = fit_ensemble_chunked(
            ArrayChunkSource(Xt, yt, chunk_rows=max(rows // 8, 1)),
            categorical={}, max_depth=4, max_bins=32, n_trees=8,
            seed=7, loss="squared", step_size=0.3, boosting=True)
        fit_s = time.perf_counter() - t0
        assert spec.baseline is not None, "seed fit did not stamp a baseline"
        # the same seed model anchors TWO independent lineages: the
        # drifting pipeline (whose promotion moves ITS Production) and
        # the iid control (whose baseline must stay the seed model —
        # sharing one name would make the control judge iid data
        # against the drift-refit model and "detect" the promotion)
        with mlflow.start_run():
            mlflow.spark.log_model(GBTRegressionModel(spec), "model",
                                   registered_model_name="ct-bench-model")
            mlflow.spark.log_model(GBTRegressionModel(spec), "model-iid",
                                   registered_model_name="ct-bench-iid")
        _store.set_version_stage("ct-bench-model", 1, "Production")
        _store.set_version_stage("ct-bench-iid", 1, "Production")

        def append(path, batch_rows, seed, shift):
            X, y = _ct_frame(batch_rows, seed, shift)
            pdf = pd.DataFrame({c: X[:, i] for i, c in enumerate(cols)})
            pdf["y"] = y.astype(float)
            mode = "append" if os.path.exists(path) else "errorifexists"
            spark.createDataFrame(pdf).write.format("delta") \
                .mode(mode).save(path)

        batch = max(rows // 8, 1024)
        gate = CanaryGate(min_mirrored=4, timeout_s=30.0,
                          quality_tol=1.2, batch_rows=256)
        swaps0 = PROFILER.counters().get("serve.hot_swap", 0.0)

        # ---- drifting stream: refit -> gate -> promote -> hot-swap
        dpath = os.path.join(tmp, "drift-stream")
        t0 = time.perf_counter()
        with ServingEndpoint("ct-bench-model", "Production",
                             canary_fraction=1.0, flush_micros=500) as ep:
            trainer = ContinuousTrainer(
                "ct-bench-model", DeltaChunkSource(dpath, cols, "y"),
                endpoint=ep, gate=gate,
                fit_params={"seed": 7, "rounds_per_dispatch": 2},
                warm_rounds=4, min_rows=512, full_severity=1e9)
            append(dpath, batch, seed=21, shift=False)
            clean = trainer.step()
            append(dpath, batch, seed=22, shift=True)
            drifted = trainer.step()
            dstats = trainer.stats()
            endpoint_version = ep.current_version()
        loop_s = time.perf_counter() - t0
        swaps = PROFILER.counters().get("serve.hot_swap", 0.0) - swaps0

        # ---- iid control stream: same cadence, zero refits
        ipath = os.path.join(tmp, "iid-stream")
        control = ContinuousTrainer(
            "ct-bench-iid", DeltaChunkSource(ipath, cols, "y"),
            gate=gate, fit_params={"seed": 7},
            warm_rounds=4, min_rows=512, full_severity=1e9)
        for i in range(2):
            append(ipath, batch, seed=31 + i, shift=False)
            control.step()
        istats = control.stats()

        gate_verdict = (drifted.get("gate") or {})
        block = {
            "rows": rows,
            "n_features": CT_F,
            "backend": jax.default_backend(),
            "seed_fit_seconds": round(fit_s, 3),
            "loop_seconds": round(loop_s, 3),
            "drift": {
                "cycles": dstats["cycles"],
                "clean_cycles": dstats["clean"],
                "refits": dstats["refits"],
                "warm_refits": dstats["warm_refits"],
                "full_refits": dstats["full_refits"],
                "severity": float(drifted.get("severity", 0.0)),
                "clean_severity": float(clean.get("severity", 0.0)),
                "promoted": bool(dstats["promotions"] >= 1),
                "rollbacks": dstats["rollbacks"],
                "endpoint_version": endpoint_version,
                "hot_swap": bool(swaps >= 1),
                "request_errors": int(
                    gate_verdict.get("request_errors", -1)),
                "gate": {k: gate_verdict.get(k) for k in
                         ("passed", "mirrored", "canary_errors",
                          "request_errors", "mean_abs_diff",
                          "rmse_candidate", "rmse_incumbent")},
            },
            "iid": {
                "cycles": istats["cycles"],
                "refits": istats["refits"],
                "severity": float((control.last_report or {})
                                  .get("severity", 0.0)),
            },
            "note": "closed loop: Delta appends -> snapshot/advance "
                    "watermark -> PR-11 ingest drift monitor -> "
                    "warm-start round append under the saved bin edges "
                    "-> registry version -> Staging canary mirror -> "
                    "gate -> Production hot-swap "
                    "(docs/CONTINUOUS_TRAINING.md)",
        }
        ok = (block["drift"]["promoted"] and block["drift"]["hot_swap"]
              and block["drift"]["warm_refits"] >= 1
              and block["drift"]["request_errors"] == 0
              and block["drift"]["endpoint_version"] == 2
              and block["iid"]["refits"] == 0)
        block["closed_loop_ok"] = bool(ok)
        print(f"  ct: drift severity {block['drift']['severity']:.1f} -> "
              f"{block['drift']['warm_refits']} warm refit(s), promoted="
              f"{block['drift']['promoted']} (endpoint v"
              f"{block['drift']['endpoint_version']}, hot_swap="
              f"{block['drift']['hot_swap']}, request_errors="
              f"{block['drift']['request_errors']}); iid control "
              f"{block['iid']['refits']} refits over "
              f"{block['iid']['cycles']} cycles (severity "
              f"{block['iid']['severity']:.2f})", file=sys.stderr)
        return block
    finally:
        GLOBAL_CONF.set("sml.obs.enabled", bool(prev_obs))
        GLOBAL_CONF.set("sml.profiler.enabled", bool(prev_prof))
        mlflow.set_tracking_uri(prev_uri)
        shutil.rmtree(tmp, ignore_errors=True)


def ct_main(rows: int) -> None:
    """Run the continuous-training leg standalone, merge the `ct` block
    into the bench sidecar, and print the short headline JSON last."""
    block = run_ct(rows)
    doc = {}
    if os.path.exists(LEGS_FILE):
        with open(LEGS_FILE) as f:
            doc = json.load(f)
    doc["ct"] = block
    with open(LEGS_FILE, "w") as f:
        json.dump(doc, f, indent=1)
    print(json.dumps({
        "metric": "continuous-training closed loop (drift stream "
                  "promotes, iid stream holds)",
        "value": 1.0 if block["closed_loop_ok"] else 0.0,
        "unit": "1 = warm refit fired + canary gate promoted + "
                "hot-swap + zero request errors + zero iid refits",
        "warm_refits": block["drift"]["warm_refits"],
        "promoted": block["drift"]["promoted"],
        "iid_refits": block["iid"]["refits"],
        "backend": block["backend"],
        "legs_file": "bench_legs.json",
    }))
    if not block["closed_loop_ok"]:
        sys.exit(1)


FLEET_REQUESTS = 10_000
#: per-client pacing interval for the fleet leg's clients (ms): each
#: client INTENDS to send request k at epoch + k*interval and charges
#: latency from that intended instant (wrk2-style re-basing) — a
#: completion that arrives late delays the send but not the clock, so
#: the queueing the old send-time stamp hid is now on the record. Small
#: enough that a saturated fleet never actually sleeps (the load shape
#: the proofs depend on is unchanged)
FLEET_PACE_MS = 5.0


def run_fleet(requests: int = FLEET_REQUESTS) -> dict:
    """`--fleet`: the multi-replica serving-fleet proof (ISSUE 15) —
    register a linear model (v1 Production, v2 clean twin, v3 injected
    divergence), spin a warm 2-replica `fleet.ReplicaPool`, and drive a
    closed-loop load of `requests` requests through the `Router` across
    the three priority classes:

    - per-replica queue attribution + per-class p50/p99/shed under the
      published SLO (`sml.serve.sloMillis`), shedding priority-ordered
      (low first, high never — it degrades through the host ladder);
    - at least one occupancy-driven scale-UP during the load and one
      scale-DOWN after it (autoscaler bands);
    - a staged rollout of the clean candidate that PROMOTES, then one
      of the divergent candidate that AUTO-ROLLS-BACK, archives, and
      evicts the diverging replica with its black-box bundle on disk;
    - zero hung futures, and per-request trace ids recoverable through
      the router fan-in (`fleet.route` events × admission spans).

    Results merge into the bench sidecar as the `fleet` block, rendered
    by scripts/render_perf.py; a vanished block, a lost rollback or
    scale proof, a hung future, or a shed-rate/p99 regression is
    flagged by obs/regress.py."""
    import shutil
    import tempfile
    import threading

    import jax
    import pandas as pd

    import sml_tpu.tracking as mlflow
    from sml_tpu import TpuSession, obs
    from sml_tpu.conf import GLOBAL_CONF
    from sml_tpu.ct import CanaryGate
    from sml_tpu.fleet import Autoscaler, ReplicaPool, Router
    from sml_tpu.ml import Pipeline
    from sml_tpu.ml.feature import VectorAssembler
    from sml_tpu.ml.regression import LinearRegression
    from sml_tpu.serving import RequestShed
    from sml_tpu.tracking import _store
    from sml_tpu.utils.profiler import PROFILER

    prev_obs = GLOBAL_CONF.get("sml.obs.enabled")
    prev_prof = GLOBAL_CONF.get("sml.profiler.enabled")
    prev_ring = GLOBAL_CONF.get("sml.obs.ringEvents")
    prev_uri = _store.get_tracking_uri()
    GLOBAL_CONF.set("sml.obs.enabled", True)
    GLOBAL_CONF.set("sml.profiler.enabled", True)
    # the fan-in proof scans the ring for every routed request's trace:
    # size it so a 10k-request load cannot evict its own evidence
    GLOBAL_CONF.set("sml.obs.ringEvents", 1 << 18)
    tmp = tempfile.mkdtemp(prefix="sml-fleet-bench-")
    mlflow.set_tracking_uri(os.path.join(tmp, "runs"))
    spark = TpuSession.builder.appName("fleet-bench").getOrCreate()

    def fit(seed, slope):
        rng = np.random.default_rng(seed)
        pdf = pd.DataFrame({"a": rng.normal(size=4000),
                            "b": rng.normal(size=4000)})
        pdf["y"] = slope * pdf["a"] - pdf["b"] + 1.0 \
            + rng.normal(0, 0.1, len(pdf))
        va = VectorAssembler(inputCols=["a", "b"], outputCol="features")
        return Pipeline(stages=[va, LinearRegression(labelCol="y")]) \
            .fit(spark.createDataFrame(pdf))

    pool = None
    try:
        obs.reset()
        for m in (fit(3, 2.0), fit(3, 2.0), fit(9, -4.0)):
            with mlflow.start_run():
                mlflow.spark.log_model(
                    m, "model", registered_model_name="fleet-bench-model")
        _store.set_version_stage("fleet-bench-model", 1, "Production")

        classes = ["high", "normal", "low"]
        rows_per_req = 32
        queue_rows = 128
        pool = ReplicaPool(
            "fleet-bench-model", replicas=2, canary_fraction=1.0,
            flush_micros=8000, queue_rows=queue_rows, timeout_millis=0,
            host_fallback=True,
            blackbox_dir=os.path.join(tmp, "blackbox"))
        router = Router(pool, priorities=classes)
        asc = Autoscaler(pool, router, min_replicas=2, max_replicas=3,
                         scale_up_occupancy=0.5, scale_down_occupancy=0.1)

        # ---- closed-loop load: 12 clients over 3 priority classes ----
        X = np.random.default_rng(5).normal(
            size=(rows_per_req, 2)).astype(np.float32)
        clients = {"high": 3, "normal": 4, "low": 5}
        share = {"high": 0.2, "normal": 0.4, "low": 0.4}
        lat = {c: [] for c in classes}
        shed = {c: 0 for c in classes}
        hung = [0]
        lat_lock = threading.Lock()

        # coordinated-omission fix (docs/LOADGEN.md): each client paces
        # a per-client SCHEDULE (request k intended at epoch +
        # k*FLEET_PACE_MS) and charges latency from the INTENDED
        # arrival, not the post-completion send time — when the fleet
        # queues and delays a completion, the next request's clock has
        # already started, so the queueing lands on the record instead
        # of silently slowing the client's arrival rate
        interval = FLEET_PACE_MS / 1e3

        def client(cls, n):
            my_lat, my_shed = [], 0
            epoch = time.perf_counter()
            for k in range(n):
                intended = epoch + k * interval
                spare = intended - time.perf_counter()
                if spare > 0:
                    time.sleep(spare)
                try:
                    router.submit(X, cls).result(30.0)
                    my_lat.append((time.perf_counter() - intended) * 1e3)
                except RequestShed:
                    my_shed += 1
                except TimeoutError:
                    with lat_lock:
                        hung[0] += 1
            with lat_lock:
                lat[cls].extend(my_lat)
                shed[cls] += my_shed

        threads = []
        sent = {c: 0 for c in classes}
        for cls in classes:
            per = int(requests * share[cls]) // clients[cls]
            for _ in range(clients[cls]):
                sent[cls] += per
                threads.append(threading.Thread(
                    target=client, args=(cls, per)))
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        actions = []
        peak = pool.size()
        while any(t.is_alive() for t in threads):
            time.sleep(0.25)
            actions.append(asc.step()["action"])
            peak = max(peak, pool.size())
        for t in threads:
            t.join()
        load_s = time.perf_counter() - t0
        # ---- cooldown: the idle fleet retires back to the floor (a
        # load lull may already have retired it mid-run — band events
        # count wherever they fired) -----------------------------------
        for _ in range(3):
            a = asc.step()["action"]
            actions.append(a)
            if a == "down":
                break
        up_events = sum(1 for a in actions if a in ("up", "backfill"))
        down_events = sum(1 for a in actions if a == "down")
        # the SLO snapshot (and its all-time worst-request exemplar) is
        # taken HERE, before the rollouts: gate traffic drives the
        # endpoints directly (no fleet.route event), so a slow gate
        # request after this point must not become the "worst" the
        # fan-in proof then fails to find among the router's traces
        slo = obs.slo_report()

        # ---- staged rollouts: clean promote, then forced rollback ----
        gate = CanaryGate(min_mirrored=4, timeout_s=30.0,
                          max_abs_diff=0.05, batch_rows=64)
        Xg = np.random.default_rng(6).normal(size=(256, 2)) \
            .astype(np.float32)
        _store.set_version_stage("fleet-bench-model", 2, "Staging")
        clean = pool.promote(2, gate=gate, X=Xg)
        _store.set_version_stage("fleet-bench-model", 3, "Staging")
        rollback = pool.promote(3, gate=gate, X=Xg)
        bb = rollback.get("blackbox")
        bb_ok = bool(bb) and os.path.isfile(
            os.path.join(bb, "MANIFEST.json"))
        backfilled = asc.step()["action"]  # refill the evicted slot

        # ---- trace fan-in proof: router decision ↔ admission span ----
        route_traces, request_traces = set(), set()
        for ev in obs.RECORDER.events():
            if ev.name == "fleet.route":
                tid = (ev.args or {}).get("trace")
                if tid is not None:
                    route_traces.add(tid)
            elif ev.name == "trace.request":
                request_traces.add((ev.args or {}).get("trace"))
        fanin = len(route_traces & request_traces)
        worst_hex = slo.get("worst_trace")
        worst_in_fanin = (worst_hex is not None
                          and int(worst_hex, 16) in route_traces)
        fanin_ok = fanin > 0 and worst_in_fanin

        health = obs.engine_health()
        counters = PROFILER.counters()
        per_class = {}
        rates = {}
        for cls in classes:
            ls = sorted(lat[cls])
            served = len(ls)
            rate = shed[cls] / max(sent[cls], 1)
            rates[cls] = rate
            per_class[cls] = {
                "requests": sent[cls],
                "served": served,
                "shed": shed[cls],
                "shed_rate": round(rate, 4),
                "fleet_shed_counter": counters.get(
                    f"fleet.shed.{cls}", 0.0),
                "p50_ms": round(ls[len(ls) // 2], 3) if ls else None,
                "p99_ms": round(ls[min(int(len(ls) * 0.99),
                                       len(ls) - 1)], 3) if ls else None,
            }
        priority_order_ok = (rates["low"] >= rates["normal"]
                             >= rates["high"] and rates["high"] == 0.0)
        block = {
            "requests": sum(sent.values()),
            "rows_per_request": rows_per_req,
            "queue_rows": queue_rows,
            "backend": jax.default_backend(),
            "load_seconds": round(load_s, 3),
            "replicas": {"initial": 2, "min": 2, "max": 3, "peak": peak,
                         "final": pool.size()},
            "slo": {"target_ms": slo["target_ms"],
                    "burn_rate": slo["burn_rate"],
                    "breaches": slo["breaches"]},
            "priority": per_class,
            "priority_order_ok": bool(priority_order_ok),
            # like-for-like annotation: these latencies come from
            # CLOSED-LOOP clients (re-based on intended arrivals, but
            # still self-throttling past one in-flight request each) —
            # the regress sentry only compares p99s between blocks
            # whose closed_loop flags agree (docs/LOADGEN.md)
            "closed_loop": True,
            "pace_ms": FLEET_PACE_MS,
            "hung_futures": int(hung[0]),
            "reroutes": counters.get("fleet.reroutes", 0.0),
            "scale": {"up_events": up_events, "down_events": down_events,
                      "up_ok": bool(up_events >= 1),
                      "down_ok": bool(down_events >= 1),
                      "events": [a for a in actions if a != "hold"],
                      "post_rollback": backfilled},
            "rollout": {
                "clean": {"passed": bool(clean["passed"]),
                          "stages": len(clean["stages"])},
                "rollback": {
                    "rolled_back": bool(not rollback["passed"]
                                        and rollback["action"]
                                        == "rolled_back"),
                    "evicted": rollback.get("evicted"),
                    "divergence_check": (rollback.get("checks") or {})
                    .get("divergence"),
                    "blackbox_on_disk": bool(bb_ok)},
            },
            "trace": {"worst_ms": slo["worst_ms"],
                      "worst_trace": worst_hex,
                      "route_events": len(route_traces),
                      "fanin_requests": fanin,
                      "fanin_ok": bool(fanin_ok)},
            "shed_by_reason": dict(health["shed"]["by_reason"]),
            "note": "closed loop: Router priority admission over "
                    "per-replica QueuePressure -> micro-batched "
                    "replicas -> occupancy-banded Autoscaler; staged "
                    "rollout via per-replica CanaryGate pins with "
                    "auto-rollback + forensic eviction "
                    "(docs/FLEET.md)",
        }
        ok = (hung[0] == 0
              and block["scale"]["up_ok"] and block["scale"]["down_ok"]
              and block["rollout"]["clean"]["passed"]
              and block["rollout"]["rollback"]["rolled_back"]
              and block["rollout"]["rollback"]["evicted"] is not None
              and bb_ok
              and priority_order_ok and shed["low"] > 0
              and fanin_ok)
        block["fleet_ok"] = bool(ok)
        print(f"  fleet: {block['requests']} requests over "
              f"{len(classes)} classes in {load_s:.1f}s — shed "
              f"low/normal/high = {shed['low']}/{shed['normal']}/"
              f"{shed['high']}, scale up×{up_events} down×"
              f"{down_events} (peak {peak}), clean rollout "
              f"{'PROMOTED' if clean['passed'] else 'FAILED'}, "
              f"divergent rollout "
              f"{'ROLLED BACK' if not rollback['passed'] else 'PASSED?!'}"
              f" (evicted r{rollback.get('evicted')}, blackbox "
              f"{'ok' if bb_ok else 'MISSING'}), hung {hung[0]}, "
              f"fan-in {'ok' if fanin_ok else 'LOST'}", file=sys.stderr)
        return block
    finally:
        # close BEFORE the tmp dir (blackbox/tracking roots) vanishes
        # under live replicas — a mid-proof exception must not leak
        # flush threads and a registered pool into the rest of the run
        if pool is not None:
            try:
                pool.close()
            except Exception:
                pass
        GLOBAL_CONF.set("sml.obs.enabled", bool(prev_obs))
        GLOBAL_CONF.set("sml.profiler.enabled", bool(prev_prof))
        GLOBAL_CONF.set("sml.obs.ringEvents", int(prev_ring))
        mlflow.set_tracking_uri(prev_uri)
        shutil.rmtree(tmp, ignore_errors=True)


def fleet_main(requests: int) -> None:
    """Run the fleet leg standalone, merge the `fleet` block into the
    bench sidecar, and print the short headline JSON last."""
    block = run_fleet(requests)
    doc = {}
    if os.path.exists(LEGS_FILE):
        with open(LEGS_FILE) as f:
            doc = json.load(f)
    doc["fleet"] = block
    with open(LEGS_FILE, "w") as f:
        json.dump(doc, f, indent=1)
    print(json.dumps({
        "metric": "serving-fleet closed loop (priority shed ladder, "
                  "autoscale cycle, staged rollout + rollback)",
        "value": 1.0 if block["fleet_ok"] else 0.0,
        "unit": "1 = priority-ordered shed + scale up/down + clean "
                "promote + divergent rollback w/ blackbox + zero hung "
                "futures + trace fan-in recoverable",
        "requests": block["requests"],
        "hung_futures": block["hung_futures"],
        "scale_up": block["scale"]["up_events"],
        "scale_down": block["scale"]["down_events"],
        "rolled_back": block["rollout"]["rollback"]["rolled_back"],
        "backend": block["backend"],
        "legs_file": "bench_legs.json",
    }))
    if not block["fleet_ok"]:
        sys.exit(1)


#: the committed multi-phase open-loop trace for `--load` (seconds of
#: each phase at the nominal req/s BEFORE --load-scale): steady Poisson,
#: a 3x burst (mean-preserving on/off modulation), then a diurnal-shaped
#: ramp. The seed makes the schedule byte-reproducible.
LOAD_TRACE_SEED = 19
LOAD_PHASES = (("steady", 6.0, 30.0, None, "poisson"),
               ("burst", 6.0, 30.0, None, "bursty"),
               ("ramp", 6.0, 15.0, 45.0, "poisson"))
LOAD_WIDTHS = ((8, 0.70), (32, 0.22), (128, 0.06), (256, 0.02))
LOAD_CLASSES = (("high", 0.2), ("normal", 0.6), ("low", 0.2))
#: open-loop honesty tolerance for the bench leg (µs): fire-lag past
#: this counts load.overrun. Much wider than the 5 ms library default —
#: the bench box can be a 1-core container where a just-woken driver
#: worker waits behind a whole herd of GIL slices (every future the OFF
#: run's mis-tuned flush resolves wakes a parked thread) before it can
#: stamp its fire. The value is RECORDED in the block, so the claim
#: "zero overruns" always names the tolerance it was measured at
LOAD_OVERRUN_MICROS = 100_000
#: mis-tuned static flush deadline for the engineering-OFF run (µs): a
#: plausible hand-tuned value that eats most of the 50 ms SLO budget in
#: queueing — exactly what the auto-tuner exists to fix
LOAD_OFF_FLUSH_MICROS = 40_000
LOAD_SLO_MILLIS = 50


def run_load(scale: float = 1.0) -> dict:
    """`--load`: the open-loop trace-driven load proof (docs/LOADGEN.md)
    — replay the committed steady→3x-burst→ramp `TraceSpec` through
    `loadgen.OpenLoopDriver` against a warm 2-replica fleet TWICE:

    - OFF: static mis-tuned flush deadline (LOAD_OFF_FLUSH_MICROS), no
      burst-anticipating admission, no speculative prewarm — honest
      open-loop tails of a hand-tuned fleet;
    - ON: `sml.serve.flushAutoTune` + `sml.fleet.burstSlopeHorizonSec`
      + `loadgen.prewarm_widths` — the tail-engineering ladder the
      harness motivates.

    The sidecar `load` block carries the ON run's per-phase/per-class
    p50/p99/p99.9 with worst-request trace exemplars (round-tripped
    through the flight-recorder ring), the overrun count (must be 0 —
    an overrun means the harness, not the fleet, shaped the tails), and
    the on-vs-off p99.9 delta on the burst phase. obs/regress.py flags
    a vanished block, tail regressions, overrun growth, or a lost
    engineering win."""
    import shutil
    import tempfile

    import jax
    import pandas as pd

    import sml_tpu.tracking as mlflow
    from sml_tpu import TpuSession, obs
    from sml_tpu.conf import GLOBAL_CONF
    from sml_tpu.fleet import ReplicaPool, Router
    from sml_tpu.loadgen import (OpenLoopDriver, PhaseSpec, TraceSpec,
                                 prewarm_widths)
    from sml_tpu.ml import Pipeline
    from sml_tpu.ml.feature import VectorAssembler
    from sml_tpu.ml.regression import LinearRegression
    from sml_tpu.tracking import _store
    from sml_tpu.utils.profiler import PROFILER

    spec = TraceSpec(
        phases=tuple(PhaseSpec(name, dur, rate * scale,
                               None if rate_end is None
                               else rate_end * scale, arrival)
                     for name, dur, rate, rate_end, arrival
                     in LOAD_PHASES),
        widths=LOAD_WIDTHS, classes=LOAD_CLASSES, seed=LOAD_TRACE_SEED)
    requests = spec.compile()

    prev = {k: GLOBAL_CONF.get(k) for k in (
        "sml.obs.enabled", "sml.profiler.enabled", "sml.obs.ringEvents",
        "sml.serve.sloMillis", "sml.serve.flushAutoTune",
        "sml.fleet.burstSlopeHorizonSec", "sml.load.overrunMicros")}
    prev_uri = _store.get_tracking_uri()
    GLOBAL_CONF.set("sml.obs.enabled", True)
    GLOBAL_CONF.set("sml.profiler.enabled", True)
    # per-request exemplar round-trip scans the ring for every phase's
    # worst request: size it so two full replays cannot evict evidence
    GLOBAL_CONF.set("sml.obs.ringEvents", 1 << 18)
    GLOBAL_CONF.set("sml.serve.sloMillis", LOAD_SLO_MILLIS)
    GLOBAL_CONF.set("sml.load.overrunMicros", LOAD_OVERRUN_MICROS)
    # on a 1-core box the default 5 ms GIL switch interval makes a
    # just-woken driver worker wait many whole slices behind parked
    # scorer threads before it can even STAMP its fire time — that lag
    # books as a harness overrun. Shorter slices trade a little
    # throughput for honest open-loop pickup; restored in the finally
    prev_switch = sys.getswitchinterval()
    sys.setswitchinterval(0.001)
    tmp = tempfile.mkdtemp(prefix="sml-load-bench-")
    mlflow.set_tracking_uri(os.path.join(tmp, "runs"))
    spark = TpuSession.builder.appName("load-bench").getOrCreate()
    timeout_s = float(GLOBAL_CONF.get("sml.load.resultTimeoutSec"))

    def fit():
        rng = np.random.default_rng(3)
        pdf = pd.DataFrame({"a": rng.normal(size=4000),
                            "b": rng.normal(size=4000)})
        pdf["y"] = 2.0 * pdf["a"] - pdf["b"] + 1.0 \
            + rng.normal(0, 0.1, len(pdf))
        va = VectorAssembler(inputCols=["a", "b"], outputCol="features")
        return Pipeline(stages=[va, LinearRegression(labelCol="y")]) \
            .fit(spark.createDataFrame(pdf))

    def one_run(engineering: bool) -> dict:
        """One full replay of the committed trace against a fresh
        2-replica fleet; returns the driver's report plus the fleet's
        final flush deadlines."""
        GLOBAL_CONF.set("sml.serve.flushAutoTune", bool(engineering))
        GLOBAL_CONF.set("sml.fleet.burstSlopeHorizonSec",
                        5.0 if engineering else 0.0)
        pool = ReplicaPool(
            "load-bench-model", replicas=2, canary_fraction=0.0,
            flush_micros=LOAD_OFF_FLUSH_MICROS, queue_rows=4096,
            timeout_millis=0, host_fallback=True,
            blackbox_dir=os.path.join(tmp, "blackbox"))
        try:
            router = Router(pool,
                            priorities=[c for c, _ in LOAD_CLASSES])

            def score(X, priority, model):
                return router.score(X, priority, timeout=timeout_s)

            # both runs see warm per-bucket programs (the suite's
            # compile story is measured elsewhere); the ON run
            # additionally exercises the declared-width-mix prewarm
            # path the trace's spec feeds. Beyond the declared widths,
            # also warm the AGGREGATE buckets a backed-up flush can
            # reach (the batcher concatenates its whole queue, so the
            # bucketed batch width exceeds any single request's) — a
            # mid-replay compile stalls the 1-core interpreter long
            # enough to book harness overruns
            for rows in [w for w, _ in LOAD_WIDTHS] + [512, 1024, 2048]:
                score(np.zeros((rows, 2), dtype=np.float32), "high",
                      None)
            prewarm_stats = None
            if engineering:
                prewarm_stats = prewarm_widths(
                    lambda X: score(X, "high", None), spec,
                    feature_dim=2)
            obs.METRICS.reset()  # each replay owns its distributions
            # worker budget: a worker is just a thread parked in
            # result(), so cover the trace's worst case — peak arrival
            # rate x the worst observed latency (the OFF run's
            # mis-tuned deadline backs requests up ~250ms under the 3x
            # burst) — WITHOUT oversubscribing the host: on a 1-core
            # bench box every extra runnable thread steals GIL slices
            # from the dispatch loop and manufactures harness overruns
            driver = OpenLoopDriver(score, requests, feature_dim=2,
                                    workers=96)
            report = driver.run()
            report["flush_micros"] = sorted(
                r.endpoint._batcher.flush_micros
                for r in pool.replicas())
            report["prewarm"] = prewarm_stats
            return report
        finally:
            pool.close()

    try:
        obs.reset()
        with mlflow.start_run():
            mlflow.spark.log_model(
                fit(), "model", registered_model_name="load-bench-model")
        _store.set_version_stage("load-bench-model", 1, "Production")

        off = one_run(engineering=False)
        on = one_run(engineering=True)

        # ---- exemplar round-trip: each phase's worst request must be
        # recoverable in the flight-recorder ring by its trace id ----
        ring_traces = set()
        for ev in obs.RECORDER.events():
            if ev.name == "trace.request":
                ring_traces.add((ev.args or {}).get("trace"))
        exemplars = {}
        for name, ph in on["phases"].items():
            hexid = ph.get("worst_trace")
            exemplars[name] = bool(
                hexid and int(hexid, 16) in ring_traces)
        exemplar_ok = bool(exemplars) and all(exemplars.values())

        off_p999 = off["phases"]["burst"]["p999_ms"]
        on_p999 = on["phases"]["burst"]["p999_ms"]
        counters = PROFILER.counters()
        block = dict(on)
        block.update({
            "backend": jax.default_backend(),
            "open_loop": True,
            "trace": {
                "seed": LOAD_TRACE_SEED,
                "scale": float(scale),
                "phases": [{"name": n, "duration_s": d, "rate": r,
                            "rate_end": re_, "arrival": a}
                           for n, d, r, re_, a in LOAD_PHASES],
                "widths": [list(w) for w in LOAD_WIDTHS],
                "classes": [list(c) for c in LOAD_CLASSES],
            },
            "slo_millis": LOAD_SLO_MILLIS,
            "off_flush_micros": LOAD_OFF_FLUSH_MICROS,
            "overrun_micros": LOAD_OVERRUN_MICROS,
            "engineering": {
                "off": {"p999_ms": off_p999,
                        "p99_ms": off["phases"]["burst"]["p99_ms"],
                        "overrun": off["overrun"],
                        "flush_micros": off["flush_micros"]},
                "on": {"p999_ms": on_p999,
                       "p99_ms": on["phases"]["burst"]["p99_ms"],
                       "overrun": on["overrun"],
                       "flush_micros": on["flush_micros"]},
                "delta_p999_ms": round(off_p999 - on_p999, 3),
                "win": bool(on_p999 < off_p999),
                "burst_tighten": counters.get("fleet.burst_tighten",
                                              0.0),
                "speculative_prewarm": on.get("prewarm"),
            },
            "exemplars_recovered": exemplars,
            "exemplar_roundtrip_ok": bool(exemplar_ok),
            "note": "open loop: TraceSpec(steady -> 3x burst -> ramp) "
                    "replayed at the SCHEDULE through OpenLoopDriver "
                    "over a 2-replica Router fleet; latency charged "
                    "from scheduled arrival (docs/LOADGEN.md). "
                    "engineering = flushAutoTune + burstSlope "
                    "admission + declared-width prewarm, on vs off",
        })
        overruns = int(off["overrun"]) + int(on["overrun"])
        ok = (overruns == 0
              and block["engineering"]["win"]
              and exemplar_ok
              and int(on["served"]) > 0)
        block["load_ok"] = bool(ok)
        print(f"  load: {on['requests']} open-loop requests/run "
              f"({len(on['phases'])} phases), overruns "
              f"off/on = {off['overrun']}/{on['overrun']}, burst "
              f"p99.9 off {off_p999:.1f}ms -> on {on_p999:.1f}ms "
              f"({'WIN' if block['engineering']['win'] else 'LOST'}), "
              f"exemplars {'ok' if exemplar_ok else 'LOST'}",
              file=sys.stderr)
        return block
    finally:
        sys.setswitchinterval(prev_switch)
        for k, v in prev.items():
            GLOBAL_CONF.set(k, v)
        mlflow.set_tracking_uri(prev_uri)
        shutil.rmtree(tmp, ignore_errors=True)


def load_main(scale: float) -> None:
    """Run the open-loop load leg standalone, merge the `load` block
    into the bench sidecar, and print the short headline JSON last."""
    block = run_load(scale)
    doc = {}
    if os.path.exists(LEGS_FILE):
        with open(LEGS_FILE) as f:
            doc = json.load(f)
    doc["load"] = block
    with open(LEGS_FILE, "w") as f:
        json.dump(doc, f, indent=1)
    print(json.dumps({
        "metric": "open-loop trace harness (coordinated-omission-free "
                  "tails + tail-engineering on-vs-off)",
        "value": 1.0 if block["load_ok"] else 0.0,
        "unit": "1 = zero overruns + burst-phase p99.9 win (auto-tune "
                "+ burst admission + width prewarm) + per-phase worst-"
                "request exemplars recoverable",
        "requests": block["requests"],
        "overrun": block["overrun"],
        "burst_p999_off_ms": block["engineering"]["off"]["p999_ms"],
        "burst_p999_on_ms": block["engineering"]["on"]["p999_ms"],
        "backend": block["backend"],
        "legs_file": "bench_legs.json",
    }))
    if not block["load_ok"]:
        sys.exit(1)


# ----------------------------------------------------------------- goldens
def check_goldens(metrics):
    """Compare this run's metric values against the CPU-mesh 1M-row pins
    (GOLDEN.json `bench_metrics_1m`, written by --pin-goldens). Relative
    tolerances per metric (GOLDEN_TOLERANCES); exact counts must match
    exactly. Returns (ok, drifts)."""
    try:
        with open(GOLDEN_FILE) as f:
            golden = json.load(f)
    except OSError:
        return True, {"note": "no GOLDEN.json"}
    pins = golden.get("bench_metrics_1m", {}).get("metrics")
    if not pins:
        return True, {"note": "no bench_metrics_1m pins"}
    drifts = {}
    ok = True
    for k, pinned in pins.items():
        if k not in metrics:
            continue
        got = float(metrics[k])
        if k in ("rows_scored", "groups", "kmeans_k", "scale_d"):
            if int(got) != int(pinned):
                ok = False
                drifts[k] = {"pinned": pinned, "got": got, "exact": True}
            continue
        tol = GOLDEN_TOLERANCES.get(k, 0.05)
        rel = abs(got - float(pinned)) / max(abs(float(pinned)), 1e-12)
        if rel > tol:
            ok = False
            drifts[k] = {"pinned": float(pinned), "got": got,
                         "rel_drift": round(rel, 5), "tol": tol}
    return ok, drifts


def pin_goldens():
    """Run the suite ONCE on the current backend (meant for the virtual
    8-device CPU mesh) and write the metric pins the TPU run is checked
    against. The 8M scale leg is skipped — its device programs take tens
    of minutes on a CPU mesh; scale metrics are recorded (unpinned) in
    the bench JSON."""
    import jax
    df, pdf = build_dataset(N_ROWS)
    df.cache()
    ratings_df, _ = build_ratings(N_RATINGS)
    ratings_df.cache()
    _, metrics, _, _ = run_suite(df, N_ROWS, ratings_df, with_scale=False)
    with open(GOLDEN_FILE) as f:
        golden = json.load(f)
    golden["bench_metrics_1m"] = {
        "backend": jax.default_backend(),
        "n_rows": N_ROWS,
        "note": "suite metrics pinned on the virtual 8-device CPU mesh "
                "(f32); the TPU bench asserts its metrics within "
                "GOLDEN_TOLERANCES of these",
        # serve_* metrics are LOAD numbers (latency/occupancy under this
        # machine's contention), not model outputs — never pinned
        "metrics": {k: (round(float(v), 6) if isinstance(v, float)
                        else v) for k, v in metrics.items()
                    if not k.startswith("serve_")},
    }
    with open(GOLDEN_FILE, "w") as f:
        json.dump(golden, f, indent=1)
    print(json.dumps({"pinned": golden["bench_metrics_1m"]["metrics"]},
                     default=float))


def main():
    import jax
    backend = jax.default_backend()
    print(f"devices: {jax.devices()}", file=sys.stderr)
    df, pdf = build_dataset(N_ROWS)
    df.cache()
    ratings_df, ratings_pdf = build_ratings(N_RATINGS)
    ratings_df.cache()
    base = get_host_baseline(pdf, ratings_pdf)

    from sml_tpu.conf import GLOBAL_CONF
    GLOBAL_CONF.set("sml.profiler.enabled", True)
    build_scale_parts()  # data gen + prep OUTSIDE the warmup accounting

    # opt-in (--prewarm / sml.prewarm.enabled): replay the program-prewarm
    # manifest BEFORE the warmup passes — every recorded program signature
    # rebuilds and first-dispatches from a concurrent pool, so the ~25
    # serial first-dispatch payments the r01 warmup measured overlap.
    # serial_s/wall_s in the sidecar is the overlap actually bought.
    prewarm_stats = None
    if GLOBAL_CONF.getBool("sml.prewarm.enabled"):
        from sml_tpu.parallel import prewarm as _prewarm
        prewarm_stats = _prewarm.prewarm()
        prewarm_stats = {k: (round(v, 3) if isinstance(v, float) else v)
                         for k, v in prewarm_stats.items()}
        print(f"prewarm: {prewarm_stats}", file=sys.stderr)

    # first/second identical-shape fit in a FRESH process: the quantized
    # bin cache + program caches + persistent compile cache at work (this
    # also pre-warms the ml11-shaped programs, shrinking warmup pass 1)
    sf_probe = second_fit_probe(df.randomSplit([0.8, 0.2], seed=42)[0])

    # TWO warmup passes at FULL shapes: pass 1 pays cold compiles, route
    # discovery, and background promotion of the datasets into HBM; pass 2
    # pays the post-promotion device-program compiles. The timed passes then
    # measure the converged steady state. Total warmup cost is reported as
    # compile_seconds — compile economics are part of the story, not
    # discarded (SURVEY §7 hard-part #6).
    t0 = time.perf_counter()
    run_suite(df, N_ROWS, ratings_df)
    pass1 = time.perf_counter() - t0
    t0 = time.perf_counter()
    run_suite(df, N_ROWS, ratings_df)
    pass2 = time.perf_counter() - t0
    warmup_secs = pass1 + pass2
    cal_probe = probe()

    # THREE timed passes. Each leg reports its BEST seconds across the
    # passes: the TPU sits behind a SHARED tunnel and the host can be
    # co-tenant-loaded (observed: the same ALS fit at 1.6s and 15.8s
    # within an hour, code identical; r4's driver capture had ml13 at
    # 4.3x its builder-measured time). Per-pass walls and probes are all
    # recorded; a globally-noisy session trips interference_suspected.
    from sml_tpu.utils.profiler import PROFILER
    passes = []
    for i in range(3):
        PROFILER.reset()
        p_before = probe()
        t0 = time.perf_counter()
        timings, metrics, flops, eng_legs = run_suite(df, N_ROWS, ratings_df)
        wall = time.perf_counter() - t0
        passes.append({"wall": wall, "timings": timings, "metrics": metrics,
                       "flops": flops, "engine_counters": eng_legs,
                       "probe_before": p_before,
                       "probe_after": probe(),
                       "profiler": PROFILER.report()})
    pass_walls = [round(p["wall"], 3) for p in passes]
    best_pass = min(passes, key=lambda p: p["wall"])
    metrics, flops = best_pass["metrics"], best_pass["flops"]

    # per-leg best across passes (the pass index is recorded per leg)
    leg_secs, leg_pass = {}, {}
    for k in best_pass["timings"]:
        vals = [p["timings"][k] for p in passes]
        leg_secs[k] = min(vals)
        leg_pass[k] = int(np.argmin(vals))
    value = sum(leg_secs.values())

    # per-run host re-measure of every cheap leg (same machine, same
    # session — r4's fairness gap), best of HOST_TIMED_PASSES to match the
    # device legs' best-of-3 discipline; expensive legs keep the cached
    # anchor
    # a leg MISSING from the committed cache (e.g. a newly added leg) is
    # treated as cheap and measured fresh this run, so adding a leg does
    # not force a LEGS_VERSION bump (= a full multi-minute re-measure of
    # the expensive cached legs)
    thin = [k for k in leg_secs
            if base.get(k, 0.0) < HOST_REMEASURE_CUTOFF_S]
    print(f"re-measuring host baseline for cheap legs "
          f"(best of {HOST_TIMED_PASSES}): {thin}", file=sys.stderr)
    host_passes = [run_host_baseline(pdf, ratings_pdf, only=set(thin))
                   for _ in range(HOST_TIMED_PASSES)]
    fresh = {k: min(p[k] for p in host_passes if k in p)
             for k in set().union(*host_passes)}
    host_eff = {k: fresh.get(k, base.get(k)) for k in leg_secs}
    base_wall = sum(v for v in host_eff.values() if v is not None)

    probes = [{"before": p["probe_before"], "after": p["probe_after"]}
              for p in passes]
    all_dev = [cal_probe["device_ms"]] + \
        [x[k]["device_ms"] for x in probes for k in ("before", "after")]
    all_host = [cal_probe["host_ms"]] + \
        [x[k]["host_ms"] for x in probes for k in ("before", "after")]
    # a wide probe spread means some pass ran while the tunnel/host was
    # co-tenant-loaded — the record says so instead of silently mixing
    # contended and clean measurements
    spread_dev = max(all_dev) / max(min(all_dev), 1e-9)
    spread_host = max(all_host) / max(min(all_host), 1e-9)
    interference = bool(spread_dev > 3.0 or spread_host > 3.0)

    per_leg = {}
    for k in sorted(leg_secs):
        v = leg_secs[k]
        hb = host_eff.get(k)
        leg = {"seconds": round(v, 3),
               "seconds_per_pass": [round(p["timings"][k], 3)
                                    for p in passes],
               "best_pass": leg_pass[k],
               "rows_per_sec": round((N_SCALE if k == "ml_scale"
                                      else N_ROWS) / v, 1),
               "host_baseline_seconds": round(hb, 3) if hb else None,
               "host_measured": (f"this-run-best-of-{HOST_TIMED_PASSES}"
                                 if k in fresh else "cached"),
               "host_seconds_per_pass": ([round(p[k], 3) for p in host_passes
                                          if k in p] if k in fresh else None),
               "speedup_vs_host": round(hb / v, 2) if hb else None,
               # engine-counter deltas for this leg from the BEST pass
               # (one coherent pass snapshot, not a per-leg mix): cache
               # hits/misses, h2d/d2h bytes, shuffle volume, compiles
               "engine_counters": best_pass["engine_counters"].get(k, {})}
        # dispatch-economics attribution (via the obs.note_compile
        # counters): programs first-built-and-dispatched during this leg
        # (a prewarmed run should show ~0 here), distinct program names
        # behind them, and tree-fit dispatch count (the fusion contract)
        eng_k = leg["engine_counters"]
        leg["programs_compiled"] = int(eng_k.get("compile.programs", 0))
        leg["programs_distinct"] = sum(
            1 for c in eng_k if c.startswith("compile.program."))
        leg["tree_fit_dispatches"] = int(eng_k.get("tree.fit_dispatch", 0))
        if k in flops:
            leg["device_flops_est"] = flops[k]
            # histogram legs count scatter-accumulation OPS (XLA rewrites
            # the one-hot dot; claiming dense-matmul flops would inflate
            # MFU ~40x), linear legs count real MXU flops
            if k == "ml13_applyinpandas":
                # per-group sklearn payload runs on HOST by course design
                # (`ML 13`): zero device flops, so device MFU is truly 0
                leg["flops_kind"] = "host-sklearn"
                if backend == "tpu":
                    leg["mfu_pct"] = 0.0
            else:
                leg["flops_kind"] = ("mxu-dense" if k in
                                     ("ml02_lr", "ml12_mapinpandas",
                                      "ml_scale")
                                     else "hist-ops")
                if backend == "tpu":
                    leg["mfu_pct"] = round(
                        100.0 * flops[k] / v / TPU_PEAK_F32_FLOPS, 4)
        per_leg[k] = leg
        print(f"  {k:22s} {v:7.2f}s  (host "
              f"{hb if hb is not None else float('nan'):7.2f}s  "
              f"{per_leg[k].get('speedup_vs_host')}x)", file=sys.stderr)
    for k, v in sorted(metrics.items()):
        val = f"{v:10.3f}" if isinstance(v, (int, float)) else f"{v:>10}"
        print(f"  {k:22s} {val}", file=sys.stderr)

    golden_ok, golden_drifts = (check_goldens(metrics)
                                if backend == "tpu" else (True, {}))

    # compile_seconds = warmup excess over two steady-state passes: the
    # compile + route-discovery + HBM-promotion overhead actually paid,
    # separated from the workload's own runtime. Steady state is the
    # MEDIAN timed pass, not the best — warmup has no contention
    # protection, so subtracting the best-of-3 would book a co-tenant's
    # slowdown as "compile overhead"
    median_wall = sorted(pass_walls)[len(pass_walls) // 2]
    compile_secs = max(0.0, warmup_secs - 2.0 * median_wall)
    print(f"  warmup passes: {pass1:.1f}s + {pass2:.1f}s "
          f"(compile overhead {compile_secs:.1f}s); "
          f"timed passes {pass_walls}; per-leg-best sum {value:.1f}s",
          file=sys.stderr)
    print("---- profiler (best timed pass) ----", file=sys.stderr)
    print(best_pass["profiler"], file=sys.stderr)

    sidecar = {
        "metric": "ml02-ml13 + mle01/mle02 + ml_scale suite (1M-row "
                  "SF-Airbnb-class, MovieLens-1M ALS, 8M-row scale leg)",
        "definition": "per-leg seconds are the BEST of 3 timed passes "
                      "after 2 warmup passes; value = sum of per-leg "
                      "best; re-measured host legs are the BEST of "
                      f"{HOST_TIMED_PASSES} passes (symmetric discipline); "
                      "all per-pass walls/probes recorded here",
        "value": round(value, 3),
        "vs_baseline": round(base_wall / value, 3),
        "baseline_seconds_measured_host": round(base_wall, 3),
        "host_remeasured_this_run": sorted(fresh.keys()),
        "compile_seconds": round(compile_secs, 1),
        "warmup_seconds": round(warmup_secs, 1),
        "warmup_note": "NOT XLA recompilation: with the persistent cache "
                       "warm, jax logs show every program loading as a "
                       "cache hit (0.1-0.8s each); the cost is the "
                       "per-program FIRST-DISPATCH overhead on the "
                       "tunneled backend (executable ship + device load "
                       "+ python trace + route calibration) times ~25 "
                       "distinct programs, paid once per process",
        "timed_pass_walls": pass_walls,
        "probe_calibration": cal_probe,
        "probes_per_pass": probes,
        "probe_spread": {"device": round(spread_dev, 2),
                         "host": round(spread_host, 2)},
        "interference_suspected": interference,
        "second_fit_probe": sf_probe,
        # warmup attribution for prewarmed runs: programs replayed before
        # the warmup passes, the pool wall-clock, and what those
        # first-dispatches would have cost serially (serial_s / wall_s =
        # overlap factor). None = prewarm off (cold manifest economics)
        "prewarm": prewarm_stats,
        "golden_ok": golden_ok,
        "golden_drifts": golden_drifts,
        "backend": backend,
        "n_rows": N_ROWS,
        "n_scale_rows": N_SCALE,
        # non-numeric values (the serve_worst_trace exemplar) pass
        # through as annotations — bench_diff only judges numbers
        "metrics": {k: (float(v) if isinstance(v, (int, float)) else v)
                    for k, v in metrics.items()},
        "legs": per_leg,
    }
    if LINT_STATS is not None:
        # the --lint gate's receipts: 0 unsuppressed violations by
        # construction (the gate refuses otherwise); suppression counts
        # and the active-rule census are what obs/regress.py judges
        sidecar["lint"] = LINT_STATS
    # the standalone-leg blocks (--multichip / --kernelbench) merge into
    # this sidecar from their own runs: carry them across a plain suite
    # run instead of silently dropping them — bench_diff treats a
    # vanished kernel block as coverage loss
    if os.path.exists(LEGS_FILE):
        try:
            with open(LEGS_FILE) as f:
                prev_doc = json.load(f)
            for block in ("multichip", "multihost", "kernel",
                          "kernel_infer", "scale", "drift", "lint", "ct",
                          "fleet", "load"):
                if block in prev_doc and block not in sidecar:
                    sidecar[block] = prev_doc[block]
        except (OSError, ValueError):
            pass
    with open(LEGS_FILE, "w") as f:
        json.dump(sidecar, f, indent=1)

    # the headline: SHORT, LAST, parseable inside any tail window
    print(json.dumps({
        "metric": "suite wall-clock (sum of per-leg best-of-3)",
        "value": round(value, 3),
        "unit": "seconds",
        "vs_baseline": round(base_wall / value, 3),
        "compile_seconds": round(compile_secs, 1),
        "prewarm": prewarm_stats,
        "pass_walls": pass_walls,
        "min_leg_speedup": min(v["speedup_vs_host"] for v in per_leg.values()
                               if v["speedup_vs_host"] is not None),
        "second_fit_speedup": sf_probe["speedup"],
        "interference_suspected": interference,
        "golden_ok": golden_ok,
        "backend": backend,
        "legs_file": "bench_legs.json",
    }))
    if not golden_ok:
        sys.exit(1)


#: stats of the --lint gate run, merged into the sidecar `lint` block
#: (and emitted as lint.* engine counters) so obs/regress.py can flag a
#: violation-count increase or a rule-count decrease between records
LINT_STATS = None


def run_graftlint() -> int:
    """`scripts/graftlint.py`'s engine via the standalone loader (no
    extra process, no jax import on the lint side). ONE lint pass
    produces both the gate verdict and LINT_STATS, so the receipts can
    never disagree with the verdict. Return contract mirrors the
    runner's: 0 clean, 1 violations, 2 internal error — the gate
    refuses to record on anything nonzero."""
    global LINT_STATS
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "_graftlint_runner",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "scripts", "graftlint.py"))
    runner = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(runner)
    lint = runner.load_linter()
    try:
        report = lint.run(root=os.path.dirname(os.path.abspath(__file__)))
    except Exception as e:
        print(f"bench: graftlint internal error: {type(e).__name__}: {e}",
              file=sys.stderr)
        LINT_STATS = None
        return 2
    print(report.format())
    by_rule = {}
    for v in report.violations:
        by_rule[v.rule] = by_rule.get(v.rule, 0) + 1
    LINT_STATS = {
        "rules": len(report.rule_names),
        "files": report.n_files,
        "violations": len(report.violations),
        "violations_by_rule": by_rule,
        "suppressed_pragma": report.n_suppressed_pragma,
        "suppressed_baseline": report.n_suppressed_baseline,
        # per-rule check() wall time (ms) — a rule whose cost quietly
        # balloons shows up in the sidecar and the lint.* counters
        "rule_times_ms": {n: round(t * 1000.0, 3)
                          for n, t in sorted(
                              getattr(report, "rule_times", {}).items())},
    }
    return 0 if report.clean else 1


def _emit_lint_counters() -> None:
    """lint.* engine counters for the flight recorder / per-leg counter
    snapshots — called once the engine is importable (the gate itself
    runs jax-free BEFORE any sml_tpu import)."""
    if LINT_STATS is None:
        return
    from sml_tpu.utils.profiler import PROFILER
    PROFILER.count("lint.runs")
    PROFILER.count("lint.rules", float(LINT_STATS["rules"]))
    PROFILER.count("lint.violations", float(LINT_STATS["violations"]))
    PROFILER.count("lint.suppressed_pragma",
                   float(LINT_STATS["suppressed_pragma"]))
    PROFILER.count("lint.suppressed_baseline",
                   float(LINT_STATS["suppressed_baseline"]))
    for rule_name, n in sorted(LINT_STATS["violations_by_rule"].items()):
        PROFILER.count(f"lint.rule.{rule_name}", float(n))
    for rule_name, ms in LINT_STATS.get("rule_times_ms", {}).items():
        PROFILER.count(f"lint.rule_ms.{rule_name}", float(ms))


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--pin-goldens", action="store_true",
                        help="run once on the current backend and write "
                             "GOLDEN.json bench_metrics_1m pins")
    parser.add_argument("--prewarm", action="store_true",
                        help="replay the program-prewarm manifest (from a "
                             "previous run's recordings next to the compile "
                             "cache) concurrently before warmup; equivalent "
                             "to setting sml.prewarm.enabled=true")
    parser.add_argument("--multichip", action="store_true",
                        help="run ONLY the multi-chip fit-throughput "
                             "scaling leg over 1..n-device meshes and "
                             "merge the `multichip` block into the "
                             "bench sidecar (simulate chips on CPU with "
                             "XLA_FLAGS=--xla_force_host_platform_"
                             "device_count=8)")
    parser.add_argument("--multichip-rows", type=int, default=MULTICHIP_ROWS,
                        help="row count for the --multichip leg")
    parser.add_argument("--multihost", action="store_true",
                        help="run ONLY the hierarchical DCN-aware "
                             "collective leg over 1..H virtual-host "
                             "meshes (host groups over the live device "
                             "set) and merge the `multihost` block into "
                             "the bench sidecar (simulate hosts on CPU "
                             "with XLA_FLAGS=--xla_force_host_platform_"
                             "device_count=8)")
    parser.add_argument("--multihost-rows", type=int, default=MULTIHOST_ROWS,
                        help="row count for the --multihost leg")
    parser.add_argument("--kernelbench", action="store_true",
                        help="run ONLY the fused-kernel sweep (maxBins × "
                             "maxDepth, sml.tree.kernel=pallas vs =xla, "
                             "best-of-3 warm fits) and merge the `kernel` "
                             "block into the bench sidecar; on non-TPU "
                             "backends the pallas path runs in interpret "
                             "mode (parity, not speed)")
    parser.add_argument("--kernelbench-rows", type=int,
                        default=KERNELBENCH_ROWS,
                        help="row count for the --kernelbench leg")
    parser.add_argument("--rows", type=int, default=None,
                        help="run ONLY the out-of-core data-plane leg at "
                             "this many rows (chunked ingest + streamed "
                             "quantization + double-buffered prefetch + "
                             "small fit + streamed predict; e.g. "
                             "--rows 10000000) and merge the `scale` "
                             "block into the bench sidecar")
    parser.add_argument("--drift", action="store_true",
                        help="run ONLY the model/data drift proof leg "
                             "(fit a baseline-carrying model through the "
                             "chunked ingest, then: iid holdout CLEAN, "
                             "injected covariate shift FLAGGED with the "
                             "moved features named, per-chunk ingest "
                             "monitor firing, baseline save/load "
                             "bit-compat) and merge the `drift` block "
                             "into the bench sidecar; exits 1 when any "
                             "proof fails")
    parser.add_argument("--drift-rows", type=int, default=DRIFT_ROWS,
                        help="training rows for the --drift leg")
    parser.add_argument("--ct", action="store_true",
                        help="run ONLY the continuous-training closed-"
                             "loop proof (seed GBT registered + served, "
                             "drifting Delta stream triggers a warm-"
                             "start refit that passes the canary gate "
                             "and hot-swaps Production; iid control "
                             "stream triggers zero refits) and merge "
                             "the `ct` block into the bench sidecar; "
                             "exits 1 when any proof fails")
    parser.add_argument("--ct-rows", type=int, default=CT_ROWS,
                        help="seed-model training rows for the --ct leg")
    parser.add_argument("--fleet", action="store_true",
                        help="run ONLY the multi-replica serving-fleet "
                             "proof (closed-loop priority-classed load "
                             "through the Router over a warm "
                             "ReplicaPool: per-class p50/p99/shed under "
                             "the SLO, one occupancy scale-up + one "
                             "scale-down, a clean staged rollout that "
                             "promotes and a divergent one that "
                             "auto-rolls-back with the evicted "
                             "replica's blackbox bundle, zero hung "
                             "futures, trace fan-in) and merge the "
                             "`fleet` block into the bench sidecar; "
                             "exits 1 when any proof fails")
    parser.add_argument("--fleet-requests", type=int,
                        default=FLEET_REQUESTS,
                        help="closed-loop request count for the "
                             "--fleet leg")
    parser.add_argument("--load", action="store_true",
                        help="run ONLY the open-loop trace-driven load "
                             "proof (committed steady -> 3x-burst -> "
                             "ramp TraceSpec replayed at the SCHEDULE "
                             "through loadgen.OpenLoopDriver over a "
                             "2-replica fleet, coordinated-omission-"
                             "free per-phase/per-class p50/p99/p99.9, "
                             "tail-engineering on-vs-off) and merge "
                             "the `load` block into the bench sidecar; "
                             "refuses a dirty tree like --lint; exits "
                             "1 when any proof fails")
    parser.add_argument("--load-scale", type=float, default=1.0,
                        help="rate multiplier applied to every phase "
                             "of the committed --load trace")
    parser.add_argument("--lint", action="store_true",
                        help="gate the run on a clean graftlint pass: a "
                             "bench record from a tree violating engine "
                             "invariants (stray host syncs, bypassed "
                             "dispatch) measures the wrong engine")
    parser.add_argument("--blackbox-on-fail", action="store_true",
                        help="arm black-box forensics (sml_tpu/obs/"
                             "blackbox.py): run with the flight recorder "
                             "on, and dump a postmortem bundle to "
                             "sml.obs.blackboxDir on an unhandled "
                             "exception, a hard stall, or a failed exit "
                             "— render it with scripts/blackbox_view.py")
    args = parser.parse_args()
    if args.prewarm:
        from sml_tpu.conf import GLOBAL_CONF as _CONF0
        _CONF0.set("sml.prewarm.enabled", True)
    if args.lint or args.load:
        # --load writes a committed, regress-judged record: like --lint,
        # a tree violating engine invariants measures the wrong engine,
        # so the gate refuses to record from one
        if run_graftlint() != 0:
            print("bench: refusing to record — graftlint found violations "
                  "(fix them or run without "
                  f"{'--lint' if args.lint else '--load'})",
                  file=sys.stderr)
            sys.exit(1)
        _emit_lint_counters()
    entry = (pin_goldens if args.pin_goldens else
             (lambda: multichip_main(args.multichip_rows))
             if args.multichip else
             (lambda: multihost_main(args.multihost_rows))
             if args.multihost else
             (lambda: kernelbench_main(args.kernelbench_rows))
             if args.kernelbench else
             (lambda: drift_main(args.drift_rows))
             if args.drift else
             (lambda: ct_main(args.ct_rows))
             if args.ct else
             (lambda: fleet_main(args.fleet_requests))
             if args.fleet else
             (lambda: load_main(args.load_scale))
             if args.load else
             (lambda: scale_main(args.rows))
             if args.rows else main)
    if args.blackbox_on_fail:
        from sml_tpu.conf import GLOBAL_CONF as _CONF1
        from sml_tpu.obs import blackbox as _blackbox
        _CONF1.set("sml.obs.enabled", True)
        _blackbox.install()
        try:
            entry()
        except SystemExit as e:
            # the excepthook never sees SystemExit (a golden-gate
            # failure exits 1 that way) — dump here; every OTHER
            # exception propagates to the armed excepthook, which dumps
            # exactly once
            if e.code not in (None, 0):
                path = _blackbox.dump_blackbox("bench-failure",
                                               exc=sys.exc_info())
                print(f"bench: blackbox bundle written: {path} "
                      f"(render with scripts/blackbox_view.py)",
                      file=sys.stderr)
            raise
    else:
        entry()
