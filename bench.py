#!/usr/bin/env python
"""End-to-end benchmark: the course's ML 02–ML 13 compute path on TPU.

Runs the BASELINE.json config suite against a deterministic SF-Airbnb-shaped
dataset (the real one is blob-hosted; same schema/size class, seed 42):

  ML 02/03  StringIndexer+OHE+VectorAssembler+LinearRegression fit+predict
  ML 06/07  DecisionTree + RandomForest fit+predict
  ML 11     XGBoost-equivalent (tpu_hist boosted trees) fit+predict
  ML 12     mapInPandas batch inference
  ML 13     applyInPandas per-group training

Prints ONE JSON line: wall-clock of the whole suite (after a compile warmup
pass on small data, so the number measures steady-state execution the way
the reference cluster — with its JIT-warm JVM — was measured).
`vs_baseline` is suite_rows/sec ÷ 2000 rows/s, a conservative anchor for the
same workload class on the reference's 8×A10G Databricks cluster
(BASELINE.json publishes no numbers; SURVEY §6)."""

import json
import sys
import time

import numpy as np

N_ROWS = 60_000
BASELINE_ROWS_PER_SEC = 2000.0


def build_dataset(n):
    from sml_tpu.courseware import make_airbnb_dataset
    from sml_tpu.frame.session import get_session
    pdf = make_airbnb_dataset(n=n, seed=42)
    return get_session().createDataFrame(pdf)


def run_suite(df, n_rows):
    from sml_tpu.ml import Pipeline
    from sml_tpu.ml.evaluation import RegressionEvaluator
    from sml_tpu.ml.feature import (Imputer, OneHotEncoder, StringIndexer,
                                    VectorAssembler)
    from sml_tpu.ml.regression import (DecisionTreeRegressor,
                                       RandomForestRegressor)
    from sml_tpu.xgboost import XgboostRegressor

    timings = {}
    train, test = df.randomSplit([0.8, 0.2], seed=42)
    train.cache()
    test.cache()
    cat_cols = ["neighbourhood_cleansed", "room_type", "property_type"]
    num_cols = ["accommodates", "bathrooms", "bedrooms", "beds",
                "minimum_nights", "number_of_reviews", "review_scores_rating"]
    idx = [c + "_idx" for c in cat_cols]
    ohe = [c + "_ohe" for c in cat_cols]
    imp = [c + "_imp" for c in num_cols]
    prep = [
        Imputer(strategy="median", inputCols=num_cols, outputCols=imp),
        StringIndexer(inputCols=cat_cols, outputCols=idx, handleInvalid="skip"),
    ]
    ev = RegressionEvaluator(labelCol="price")

    # ML 02/03: linear pipeline
    t0 = time.perf_counter()
    lr_pipe = Pipeline(stages=prep + [
        OneHotEncoder(inputCols=idx, outputCols=ohe),
        VectorAssembler(inputCols=ohe + imp, outputCol="features"),
    ])
    from sml_tpu.ml.regression import LinearRegression
    lr_model = Pipeline(stages=lr_pipe.getStages()
                        + [LinearRegression(labelCol="price")]).fit(train)
    rmse_lr = ev.evaluate(lr_model.transform(test))
    timings["ml02_lr"] = time.perf_counter() - t0

    # ML 06/07: trees (indexed categoricals, no OHE — ML 06:42)
    tree_feats = VectorAssembler(inputCols=idx + imp, outputCol="features")
    t0 = time.perf_counter()
    dt_model = Pipeline(stages=prep + [tree_feats,
                        DecisionTreeRegressor(labelCol="price", maxDepth=5,
                                              maxBins=40)]).fit(train)
    rmse_dt = ev.evaluate(dt_model.transform(test))
    timings["ml06_dt"] = time.perf_counter() - t0

    t0 = time.perf_counter()
    rf_model = Pipeline(stages=prep + [tree_feats,
                        RandomForestRegressor(labelCol="price", maxDepth=6,
                                              numTrees=20, maxBins=40,
                                              seed=42)]).fit(train)
    rmse_rf = ev.evaluate(rf_model.transform(test))
    timings["ml07_rf"] = time.perf_counter() - t0

    # ML 11: boosted trees, log-price target (exp back-transform)
    from sml_tpu.frame import functions as F
    t0 = time.perf_counter()
    log_train = train.withColumn("label", F.log(F.col("price")))
    log_test = test.withColumn("label", F.log(F.col("price")))
    xgb_model = Pipeline(stages=prep + [tree_feats,
                         XgboostRegressor(n_estimators=40, learning_rate=0.15,
                                          max_depth=6, max_bins=64,
                                          random_state=42)]).fit(log_train)
    pred = xgb_model.transform(log_test).withColumn(
        "prediction", F.exp(F.col("prediction")))
    rmse_xgb = ev.evaluate(pred)
    timings["ml11_xgb"] = time.perf_counter() - t0

    # ML 12: mapInPandas batch inference with the fitted LR model
    t0 = time.perf_counter()
    lr_last = lr_model.stages[-1]
    scored_input = test
    for s in lr_model.stages[:-1]:
        scored_input = s.transform(scored_input)
    w = lr_last.coefficients.toArray()
    b = lr_last.intercept

    def predict_batches(it):
        import pandas as pd
        for pdf in it:
            X = np.stack([v.toArray() for v in pdf["features"]])
            yield pd.DataFrame({"prediction": X @ w + b})

    n_scored = scored_input.mapInPandas(predict_batches,
                                        "prediction double").count()
    timings["ml12_mapinpandas"] = time.perf_counter() - t0

    # ML 13: per-group training fan-out
    t0 = time.perf_counter()

    def train_group(pdf):
        import pandas as pd
        from sklearn.linear_model import LinearRegression as SkLR
        cols = ["accommodates", "bedrooms"]
        g = pdf.dropna(subset=cols + ["price"])
        if len(g) < 5:
            return pd.DataFrame({"room_type": [pdf["room_type"].iloc[0]],
                                 "n": [len(g)], "mse": [float("nan")]})
        m = SkLR().fit(g[cols], g["price"])
        mse = float(np.mean((m.predict(g[cols]) - g["price"]) ** 2))
        return pd.DataFrame({"room_type": [g["room_type"].iloc[0]],
                             "n": [len(g)], "mse": [mse]})

    n_groups = train.groupby("room_type").applyInPandas(
        train_group, "room_type string, n bigint, mse double").count()
    timings["ml13_applyinpandas"] = time.perf_counter() - t0

    metrics = {"rmse_lr": rmse_lr, "rmse_dt": rmse_dt, "rmse_rf": rmse_rf,
               "rmse_xgb": rmse_xgb, "rows_scored": n_scored,
               "groups": n_groups}
    return timings, metrics


def main():
    import jax
    print(f"devices: {jax.devices()}", file=sys.stderr)
    df = build_dataset(N_ROWS)
    df.cache()
    # warmup pass at FULL shapes so the timed pass measures steady-state
    # execution, not XLA compiles (shapes are part of the compile key)
    t0 = time.perf_counter()
    run_suite(df, N_ROWS)
    print(f"warmup (incl. compiles): {time.perf_counter() - t0:.1f}s",
          file=sys.stderr)
    t0 = time.perf_counter()
    timings, metrics = run_suite(df, N_ROWS)
    wall = time.perf_counter() - t0
    for k, v in sorted(timings.items()):
        print(f"  {k:22s} {v:7.2f}s", file=sys.stderr)
    for k, v in sorted(metrics.items()):
        print(f"  {k:22s} {v:10.3f}", file=sys.stderr)
    rows_per_sec = N_ROWS / wall
    print(json.dumps({
        "metric": "ml02-ml13 suite wall-clock (60k-row SF-Airbnb-class, fit+predict)",
        "value": round(wall, 3),
        "unit": "seconds",
        "vs_baseline": round(rows_per_sec / BASELINE_ROWS_PER_SEC, 3),
    }))


if __name__ == "__main__":
    main()
