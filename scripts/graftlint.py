#!/usr/bin/env python
"""graftlint runner: engine-invariant static analysis over sml_tpu/,
bench.py, and scripts/.

Loads the framework in `sml_tpu/lint/` STANDALONE (importlib by path,
package name "graftlint") so a lint run never imports the sml_tpu
package — and therefore never imports jax: CI can gate on this from a
cold interpreter in well under a second (asserted by
tests/test_lint_clean.py).

Exit-code CONTRACT (relied on by `bench.py --lint` and CI — do not
reuse these codes for anything else):

    0  clean: no unsuppressed violations (also: --list-rules,
       --update-baseline success)
    1  violations found (including pragma/baseline hygiene findings)
    2  usage or internal error (unknown --rule, unreadable tree,
       a rule crashing); argparse errors exit 2 via argparse itself

See docs/LINT.md for the rule catalogue and suppression workflow.
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
PKG_NAME = "graftlint"


def load_linter():
    """The sml_tpu/lint package as a standalone top-level package."""
    if PKG_NAME in sys.modules:
        return sys.modules[PKG_NAME]
    pkg_dir = os.path.join(REPO, "sml_tpu", "lint")
    spec = importlib.util.spec_from_file_location(
        PKG_NAME, os.path.join(pkg_dir, "__init__.py"),
        submodule_search_locations=[pkg_dir])
    mod = importlib.util.module_from_spec(spec)
    sys.modules[PKG_NAME] = mod
    spec.loader.exec_module(mod)
    return mod


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="graftlint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    parser.add_argument("--json", action="store_true",
                        help="machine-readable output")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the active rule catalogue and exit")
    parser.add_argument("--rule", action="append", default=None,
                        metavar="NAME",
                        help="run only this rule (repeatable)")
    parser.add_argument("--changed-only", metavar="GIT_REF", default=None,
                        help="report only violations in files changed vs "
                             "GIT_REF (plus untracked files); the whole "
                             "tree is still analysed — cross-file rules "
                             "need it — only the REPORT is filtered")
    parser.add_argument("--no-baseline", action="store_true",
                        help="ignore .graftlint-baseline.json")
    parser.add_argument("--update-baseline", action="store_true",
                        help="rewrite the baseline from current violations "
                             "(new entries get a TODO reason graftlint then "
                             "flags until a human justifies them)")
    parser.add_argument("--root", default=REPO, help=argparse.SUPPRESS)
    args = parser.parse_args(argv)

    lint = load_linter()

    if args.list_rules:
        for name in sorted(lint.RULES):
            print(f"{name:<26} {lint.RULES[name].doc}")
        return 0

    try:
        # --update-baseline must see the UNSUPPRESSED violations: rebuilding
        # from a baseline-filtered report would erase every still-valid
        # reviewed entry (they never appear in the filtered report)
        report = lint.run(root=args.root, rule_names=args.rule,
                          use_baseline=(not args.no_baseline
                                        and not args.update_baseline))
    except KeyError as e:
        print(f"graftlint: {e}", file=sys.stderr)
        return 2
    except Exception as e:  # internal error (a rule crashed): contract = 2
        print(f"graftlint: internal error: {type(e).__name__}: {e}",
              file=sys.stderr)
        return 2

    if args.changed_only is not None:
        try:
            changed = _changed_files(args.root, args.changed_only)
        except Exception as e:  # bad ref / not a git tree: contract = 2
            print(f"graftlint: --changed-only: {e}", file=sys.stderr)
            return 2
        report = lint.Report(
            [v for v in report.violations if v.path in changed],
            report.rule_names, report.n_files,
            n_suppressed_pragma=report.n_suppressed_pragma,
            n_suppressed_baseline=report.n_suppressed_baseline,
            rule_times=report.rule_times,
            suppressed_detail=[(v, how) for v, how
                               in report.suppressed_detail
                               if v.path in changed])

    if args.update_baseline:
        baseline_mod = sys.modules[f"{PKG_NAME}.baseline"]
        path = os.path.join(args.root, baseline_mod.DEFAULT_BASENAME)
        suppressible = [v for v in report.violations
                        if v.rule not in lint.META_RULES]
        baseline_mod.update(path, suppressible)
        print(f"baseline rewritten: {path} ({len(suppressible)} entries — "
              f"edit the TODO reasons before committing)")
        return 0

    if args.json:
        print(json.dumps({
            "clean": report.clean,
            "rules": report.rule_names,
            "files": report.n_files,
            "changed_only": args.changed_only,
            "suppressed": {"pragma": report.n_suppressed_pragma,
                           "baseline": report.n_suppressed_baseline},
            "rule_times": {n: round(t, 6)
                           for n, t in sorted(report.rule_times.items())},
            "violations": [{"rule": v.rule, "path": v.path, "line": v.line,
                            "message": v.message, "snippet": v.snippet,
                            "status": "active"}
                           for v in report.violations],
            "suppressed_violations": [
                {"rule": v.rule, "path": v.path, "line": v.line,
                 "message": v.message, "status": how}
                for v, how in report.suppressed_detail],
        }, indent=1))
    else:
        print(report.format())
    return 0 if report.clean else 1


def _changed_files(root, ref):
    """Repo-relative paths changed vs `ref`, plus untracked files (a
    brand-new file must still be lintable pre-commit). Raises on any git
    failure — the caller maps that to exit code 2."""
    out = set()
    for cmd in (["git", "diff", "--name-only", ref, "--"],
                ["git", "ls-files", "--others", "--exclude-standard"]):
        proc = subprocess.run(cmd, cwd=root, capture_output=True,
                              text=True, timeout=30)
        if proc.returncode != 0:
            raise RuntimeError(proc.stderr.strip()
                               or f"`{' '.join(cmd)}` failed")
        out.update(line.strip() for line in proc.stdout.splitlines()
                   if line.strip())
    return out


if __name__ == "__main__":
    sys.exit(main())
