#!/usr/bin/env python
"""blackbox_view — render a black-box postmortem bundle offline.

A bundle (written by `obs.dump_blackbox()` / `install_blackbox()` /
`bench.py --blackbox-on-fail` — see sml_tpu/obs/blackbox.py) is a
directory of JSON artifacts from a crashed or stalled process. This
script turns it back into something a human debugs with:

- `trace.json` — the ring replayed through the SAME Chrome/Perfetto
  converter the live exporter uses (`sml_tpu/obs/_tracefmt.py`, loaded
  by FILE PATH: the graftlint pattern), including the causal flow
  arrows, ready for ui.perfetto.dev;
- a text summary — when (wall clock), why, what was in flight (with
  trace ids), which tickets stalled and where every thread was standing,
  the worst serving request by exemplar, the audit verdicts, and HBM
  occupancy.

STDLIB-ONLY and jax-free by construction (asserted in
tests/test_obs_forensics.py): the postmortem machine needs python,
nothing else.

Usage:
    python scripts/blackbox_view.py BUNDLE_DIR [--trace OUT.json]
"""

import argparse
import importlib.util
import json
import os
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)


def _load_tracefmt():
    path = os.path.join(REPO, "sml_tpu", "obs", "_tracefmt.py")
    spec = importlib.util.spec_from_file_location("_bb_tracefmt", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _load_json(bundle, name):
    try:
        with open(os.path.join(bundle, name)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def load_events(bundle):
    """(header args, event records) from events.jsonl; torn tail lines
    (the process may have died mid-write) are skipped, not fatal."""
    header, records = {}, []
    try:
        with open(os.path.join(bundle, "events.jsonl")) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if rec.get("kind") == "meta":
                    header = rec.get("args") or {}
                else:
                    records.append(rec)
    except OSError:
        pass
    return header, records


def _fmt_unix(ts):
    if not ts:
        return "unknown"
    import datetime
    return datetime.datetime.fromtimestamp(
        ts, tz=datetime.timezone.utc).isoformat()


def summarize(bundle, header, records, manifest, metrics, audit,
              ledger) -> str:
    man = manifest or {}
    lines = [f"blackbox bundle: {bundle}",
             f"  reason:      {man.get('reason', header.get('reason'))}",
             f"  dumped:      {_fmt_unix(man.get('dumped_unix'))}",
             f"  epoch_unix:  {_fmt_unix(man.get('epoch_unix'))} "
             f"(= trace ts 0)",
             f"  version:     sml_tpu {man.get('sml_tpu_version', '?')}, "
             f"pid {man.get('pid', '?')}",
             f"  events:      {len(records)} in ring "
             f"({man.get('dropped_events', 0)} dropped)"]
    by_kind = {}
    for r in records:
        by_kind[r.get("kind", "?")] = by_kind.get(r.get("kind", "?"), 0) + 1
    lines.append("  by kind:     " + ", ".join(
        f"{k}={v}" for k, v in sorted(by_kind.items())))
    exc = man.get("exception")
    if exc:
        lines.append(f"---- exception: {exc.get('type')}: "
                     f"{exc.get('value')}")
        for ln in (exc.get("traceback") or [])[-6:]:
            lines.append(f"  {ln}")
    stalls = [r for r in records if r.get("name") == "stall.detected"]
    if stalls:
        lines.append(f"---- stalls ({len(stalls)} flagged)")
        for s in stalls:
            a = s.get("args") or {}
            lines.append(
                f"  {a.get('name')} [{a.get('kind')}] elapsed "
                f"{a.get('elapsed_s')}s (threshold "
                f"{a.get('threshold_s')}s) trace={a.get('trace')}")
            stacks = a.get("stacks") or {}
            for tname, frames in list(stacks.items())[:4]:
                lines.append(f"    {tname}:")
                for fr in frames[-3:]:
                    lines.append(f"      {fr}")
    inflight = man.get("inflight") or []
    if inflight:
        lines.append(f"---- in flight at dump ({len(inflight)} tickets)")
        for t in inflight:
            lines.append(
                f"  {t.get('name')} [{t.get('kind')}] "
                f"{t.get('elapsed_s')}s elapsed, "
                f"{'STALLED' if t.get('flagged') else 'ok'}, "
                f"trace={t.get('trace')} thread={t.get('thread')}")
    if metrics:
        req = (metrics.get("metrics") or {}).get("serve.request_ms")
        slo = metrics.get("slo") or {}
        if req:
            lines.append(
                f"---- serving: {req.get('count')} requests, p50 "
                f"{req.get('p50'):.3g}ms p99 {req.get('p99'):.3g}ms, "
                f"worst {slo.get('worst_ms')}ms "
                f"(trace {slo.get('worst_trace')}), SLO burn "
                f"{slo.get('burn_rate')}")
    if audit and audit.get("report"):
        lines.append("---- dispatch audit (tail)")
        for ln in audit["report"].splitlines()[:6]:
            lines.append(f"  {ln}")
    if ledger:
        lines.append("---- HBM ledger")
        for pool, v in sorted(ledger.items()):
            if isinstance(v, dict):
                lines.append(f"  {pool:<14} live {v.get('live', 0) / 1e6:8.1f} MB  "
                             f"peak {v.get('peak', 0) / 1e6:8.1f} MB")
    return "\n".join(lines)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="render a black-box postmortem bundle (trace.json + "
                    "text summary), jax-free")
    parser.add_argument("bundle", help="bundle directory "
                                       "(blackbox-<utc>-<pid>)")
    parser.add_argument("--trace", default=None,
                        help="Chrome trace output path (default: "
                             "<bundle>/trace.json)")
    args = parser.parse_args(argv)
    if not os.path.isdir(args.bundle):
        print(f"not a bundle directory: {args.bundle}", file=sys.stderr)
        return 2

    header, records = load_events(args.bundle)
    manifest = _load_json(args.bundle, "MANIFEST.json")
    metrics = _load_json(args.bundle, "metrics.json")
    audit = _load_json(args.bundle, "audit.json")
    ledger = _load_json(args.bundle, "ledger.json")

    tracefmt = _load_tracefmt()
    out = args.trace or os.path.join(args.bundle, "trace.json")
    doc = tracefmt.trace_doc(
        records,
        dropped=(manifest or {}).get("dropped_events", 0) or 0,
        epoch_unix=(manifest or {}).get("epoch_unix")
        or header.get("epoch_unix"),
        producer="scripts/blackbox_view.py")
    with open(out, "w") as f:
        json.dump(doc, f)

    print(summarize(args.bundle, header, records, manifest, metrics,
                    audit, ledger))
    print(f"trace written: {out} (open at https://ui.perfetto.dev)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
