#!/usr/bin/env python
"""bench_diff — the perf-regression gate between two bench records.

Compares a candidate bench artifact (the `bench_legs.json` sidecar a
fresh `python bench.py` run writes, or a `BENCH_r0x.json` driver record)
against a committed base, with NOISE-AWARE tolerances derived from each
record's own best-of-N pass spread (see sml_tpu/obs/regress.py for the
rules). Exit status IS the verdict: 0 = no regressions, 1 = regressed —
so a PR's bench run gates mechanically instead of by PERF.md eyeball.

Usage:
    python scripts/bench_diff.py BASE [CAND] [--json] [--min-tol PCT]
                                 [--trace OUT.json]

With one argument the record is compared against ITSELF (the null check
CI runs on the committed artifacts: any finding on a self-compare is a
sentry bug). `--trace` writes the verdicts as Chrome-trace instant
markers; in-process, `obs.annotate_regressions()` lands the same
verdicts in the flight recorder.

Loaded STANDALONE (the graftlint pattern): this script imports
sml_tpu/obs/regress.py by file path, so the gate never imports jax and
runs in milliseconds.
"""

import argparse
import importlib.util
import json
import os
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)


def _load_regress():
    path = os.path.join(REPO, "sml_tpu", "obs", "regress.py")
    spec = importlib.util.spec_from_file_location("_bench_regress", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="noise-aware bench-record comparison (exit 1 on "
                    "regression)")
    parser.add_argument("base", help="committed bench record (sidecar or "
                                     "BENCH_r0x driver record)")
    parser.add_argument("cand", nargs="?", default=None,
                        help="candidate record (default: the base itself "
                             "— the null self-compare)")
    parser.add_argument("--json", action="store_true",
                        help="emit the full result as JSON instead of the "
                             "table")
    parser.add_argument("--min-tol", type=float, default=None,
                        help="wall-clock tolerance floor as a fraction "
                             "(default 0.05); recorded pass spread widens "
                             "it, capped so >=20%% always flags")
    parser.add_argument("--trace", default=None,
                        help="write verdicts as Chrome-trace instant "
                             "markers to this path")
    args = parser.parse_args(argv)

    regress = _load_regress()
    cand = args.cand or args.base
    min_tol = args.min_tol if args.min_tol is not None else regress.MIN_TOL
    result = regress.diff_paths(args.base, cand, min_tol)

    if args.trace:
        with open(args.trace, "w") as f:
            json.dump({"traceEvents": regress.trace_events(result),
                       "otherData": {"producer": "scripts/bench_diff.py",
                                     "base": args.base, "cand": cand}}, f)
    if args.json:
        print(json.dumps(result, indent=1))
    else:
        print(regress.render(result, args.base, cand))
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
