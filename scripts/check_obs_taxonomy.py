#!/usr/bin/env python
"""DEPRECATED shim — the obs name-taxonomy lint now lives in
`sml_tpu/lint/rules/taxonomy.py` as the graftlint rule `obs-taxonomy`.

Run `python scripts/graftlint.py` for the full engine-invariant rule
set; this entry point (and its `check_file` / `check_tree` /
`_load_taxonomy` / `main` API) is kept verbatim so existing tooling and
tests/test_obs_taxonomy.py keep working unchanged.
"""

from __future__ import annotations

import importlib.util
import os
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)


def _load_rule_module():
    """The taxonomy rule module via the standalone graftlint loader (no
    sml_tpu / jax import — same contract as the original script)."""
    spec = importlib.util.spec_from_file_location(
        "_graftlint_runner", os.path.join(HERE, "graftlint.py"))
    runner = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(runner)
    return runner.load_linter().rules.taxonomy


_taxonomy_rule = _load_rule_module()

PKG = os.path.join(REPO, "sml_tpu")
TARGETS = _taxonomy_rule.TARGETS
check_file = _taxonomy_rule.check_file
check_tree = _taxonomy_rule.check_tree
_load_taxonomy = _taxonomy_rule.load_taxonomy
main = _taxonomy_rule.cli_main


if __name__ == "__main__":
    sys.exit(main())
