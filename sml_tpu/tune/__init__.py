"""Hyperopt-compatible Bayesian tuning (SURVEY §1 L4; §2.2 P7).

Drop-in surface for the course's two hyperopt modes:

    from sml_tpu.tune import fmin, hp, tpe, Trials, SparkTrials, STATUS_OK

`SparkTrials` is an alias of `TpuTrials` — trials fan out over host threads
driving the chip pool rather than Spark executors.
"""

from ._fmin import (STATUS_FAIL, STATUS_OK, SparkTrials, TpuTrials, Trials,
                    anneal, fmin, rand, tpe)
from ._space import hp, space_eval

__all__ = ["fmin", "hp", "tpe", "rand", "anneal", "Trials", "TpuTrials",
           "SparkTrials", "STATUS_OK", "STATUS_FAIL", "space_eval"]
