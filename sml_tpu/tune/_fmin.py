"""`fmin` + TPE + trial stores: the hyperopt-mode tuning engine.

Two execution modes, exactly the taxonomy the reference teaches
(`SML/ML 08 - Hyperopt.py:17-23`):

- mode 1 — `Trials()`: the objective runs in-process and may itself launch
  distributed (mesh-wide) training, like `fmin` over MLlib pipelines
  (`ML 08:91-170`);
- mode 2 — `TpuTrials(parallelism=k)` (alias `SparkTrials`): single-node
  objectives (sklearn/JAX) are fanned out k-at-a-time, the
  `SparkTrials(parallelism=2)` pattern of `Labs/ML 08L:89-107` with chips
  instead of executors (SURVEY §2.2 P7 — the TPE proposer stays on host).

The TPE here is an independent implementation of the standard
good/bad-density algorithm (Bergstra et al.): split completed trials at the
γ-quantile of loss, model each group with a per-dimension KDE in unit space,
and take the candidate maximizing the good/bad density ratio.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ._space import Choice, Dimension, space_eval

STATUS_OK = "ok"
STATUS_FAIL = "fail"


class Trials:
    """In-process sequential trial store (hyperopt mode 1)."""

    parallelism = 1

    def __init__(self):
        self.trials: List[Dict[str, Any]] = []
        self._lock = threading.Lock()

    def record(self, params: Dict[str, Any], result: Dict[str, Any]) -> None:
        with self._lock:
            tid = len(self.trials)
            self.trials.append({
                "tid": tid,
                "misc": {"vals": {k: [v] for k, v in params.items()}},
                "result": result,
                "state": 2,  # JOB_STATE_DONE
            })

    # -- hyperopt-compatible accessors ------------------------------------
    @property
    def results(self) -> List[Dict[str, Any]]:
        return [t["result"] for t in self.trials]

    def losses(self) -> List[Optional[float]]:
        return [t["result"].get("loss") for t in self.trials]

    @property
    def best_trial(self) -> Dict[str, Any]:
        ok = [t for t in self.trials
              if t["result"].get("status") == STATUS_OK
              and t["result"].get("loss") is not None]
        if not ok:
            raise ValueError("no successful trials")
        return min(ok, key=lambda t: t["result"]["loss"])

    @property
    def argmin(self) -> Dict[str, Any]:
        return {k: v[0] for k, v in self.best_trial["misc"]["vals"].items()}

    def __len__(self):
        return len(self.trials)

    def _completed(self):
        return [({k: v[0] for k, v in t["misc"]["vals"].items()},
                 t["result"]["loss"])
                for t in self.trials
                if t["result"].get("status") == STATUS_OK
                and t["result"].get("loss") is not None]


class TpuTrials(Trials):
    """Parallel trial store: objectives fan out `parallelism`-wide
    (the `SparkTrials` replacement; each trial is a host thread driving the
    shared device pool instead of a Spark task on an executor)."""

    def __init__(self, parallelism: int = 2, timeout: Optional[float] = None):
        super().__init__()
        self.parallelism = max(1, int(parallelism))
        self.timeout = timeout


SparkTrials = TpuTrials  # drop-in name for course code


# ---------------------------------------------------------------------------
def _bw(obs: np.ndarray) -> float:
    """Unit-space KDE bandwidth, shared by the proposal sampler and the
    scoring density (one constant, one formula — they must stay in sync).
    The 0.1 floor keeps exploration alive once the good set clusters."""
    return max(float(np.std(obs)) * max(len(obs), 1) ** -0.2, 0.1)


def _kde_logpdf(x: np.ndarray, obs: np.ndarray) -> np.ndarray:
    """1-D Gaussian-KDE log-density in unit space, mixed with a uniform
    prior (weight 0.2) the way TPE keeps its prior component alive."""
    if len(obs) == 0:
        return np.zeros_like(x)
    bw = _bw(obs)
    d = (x[:, None] - obs[None, :]) / bw
    kde = np.mean(np.exp(-0.5 * d * d), axis=1) / (bw * np.sqrt(2 * np.pi))
    return np.log(0.9 * kde + 0.1 + 1e-300)


def _tpe_propose(space: Dict[str, Dimension], completed, rng: np.random.RandomState,
                 gamma: float = 0.25, n_candidates: int = 64) -> Dict[str, Any]:
    losses = np.array([l for _, l in completed])
    # good set = best γ-quantile, capped at 25 (hyperopt's linear schedule;
    # an r2-era √n schedule kept the set at ~3 clustered points, collapsing
    # the KDE bandwidth to its floor and freezing the search on plateaus)
    n_good = min(25, max(3, int(np.ceil(gamma * len(losses)))))
    cut = np.sort(losses)[n_good - 1]
    good = [p for p, l in completed if l <= cut][:n_good]
    bad = [p for p, l in completed if l > cut]
    out: Dict[str, Any] = {}
    for name, dim in space.items():
        if isinstance(dim, Choice):
            k = len(dim.options)
            cg = np.ones(k)
            cb = np.ones(k)
            for p in good:
                cg[int(p[name])] += 1
            for p in bad:
                cb[int(p[name])] += 1
            score = np.log(cg / cg.sum()) - np.log(cb / cb.sum())
            # sample ∝ good-probability · exp(score), mirroring the
            # continuous branch: a deterministic argmax freezes categorical
            # dims on plateaus exactly like it froze continuous ones
            w = (cg / cg.sum()) * np.exp(score - score.max())
            out[name] = int(rng.choice(k, p=w / w.sum()))
        else:
            g = np.array([dim.to_unit(p[name]) for p in good])
            b = np.array([dim.to_unit(p[name]) for p in bad])
            # candidates: 3/4 drawn around good observations (adaptive
            # bandwidth), 1/4 uniform exploration — the prior mixture that
            # keeps TPE from collapsing onto an early local mode
            n_exploit = (3 * n_candidates) // 4 if len(g) else 0
            bw = _bw(g) if len(g) else 1.0
            exploit = np.clip(g[rng.randint(0, max(len(g), 1), n_exploit)]
                              + rng.normal(0, bw, n_exploit), 0, 1) \
                if n_exploit else np.zeros(0)
            explore = rng.uniform(0, 1, n_candidates - n_exploit)
            cands = np.concatenate([exploit, explore])
            score = _kde_logpdf(cands, g) - _kde_logpdf(cands, b)
            # SAMPLE ∝ exp(score) instead of argmax: a deterministic argmax
            # re-proposes the good-set mode forever (nothing new ever enters
            # the good set — the r2 search could stall on plateaus and lose
            # to random); the softmax draw is the exploration TPE needs
            w = np.exp(score - score.max())
            out[name] = dim.from_unit(
                float(cands[rng.choice(len(cands), p=w / w.sum())]))
    return out


class _TPE:
    n_startup_trials = 10

    def suggest(self, space, trials: Trials, rng) -> Dict[str, Any]:
        completed = trials._completed()
        if len(completed) < self.n_startup_trials:
            return {k: d.sample(rng) for k, d in space.items()}
        return _tpe_propose(space, completed, rng)


class _Rand:
    def suggest(self, space, trials, rng) -> Dict[str, Any]:
        return {k: d.sample(rng) for k, d in space.items()}


tpe = _TPE()
rand = _Rand()
anneal = _Rand()


def _normalize_result(res) -> Dict[str, Any]:
    if isinstance(res, dict):
        if "status" not in res:
            res = {**res, "status": STATUS_OK}
        return res
    return {"loss": float(res), "status": STATUS_OK}


def fmin(fn: Callable, space: Dict[str, Dimension], algo=None,
         max_evals: int = 10, trials: Optional[Trials] = None,
         rstate: Optional[np.random.RandomState] = None,
         verbose: bool = False, show_progressbar: bool = False) -> Dict[str, Any]:
    """Minimize `fn` over `space`. Returns the best raw point
    (hp.choice dims as indices, like hyperopt; use `space_eval` to resolve)."""
    algo = algo or tpe
    suggest = algo.suggest if hasattr(algo, "suggest") else algo
    trials = trials if trials is not None else Trials()
    if rstate is None:
        rstate = np.random.RandomState()
    if isinstance(rstate, np.random.Generator):
        rstate = np.random.RandomState(rstate.integers(0, 2 ** 31))

    def run_one(params: Dict[str, Any]) -> None:
        values = space_eval(space, params)
        try:
            res = _normalize_result(fn(values))
        except Exception as e:  # failed trial, recorded not raised
            res = {"status": STATUS_FAIL, "error": repr(e)}
        trials.record(params, res)
        if verbose:
            print(f"trial {len(trials)}/{max_evals}: {values} -> "
                  f"{res.get('loss')}")

    width = getattr(trials, "parallelism", 1)
    # BATCH-CAPABLE objectives (fn.score_batch(values_list) -> losses):
    # candidates are proposed AND SCORED per generation, so an objective
    # backed by the grid-fused tree evaluator
    # (ml.tuning.fused_param_scores) pays ONE device dispatch per
    # generation instead of one per trial. score_batch returning None (or
    # raising) drops that generation to the ordinary per-trial path —
    # same proposals, same losses, just unfused dispatches.
    score_batch = getattr(fn, "score_batch", None)
    from ..conf import GLOBAL_CONF
    gen = GLOBAL_CONF.getInt("sml.tune.candidatesPerDispatch") \
        if callable(score_batch) else 1
    if max(width, gen) <= 1:
        while len(trials) < max_evals:
            run_one(suggest(space, trials, rstate))
    else:
        from ..parallel.mesh import run_placed_trials
        while len(trials) < max_evals:
            batch = min(max(width, gen), max_evals - len(trials))
            # batch proposals draw from the same posterior; rng state
            # advances per proposal so the batch is diverse
            proposals = [suggest(space, trials, rstate) for _ in range(batch)]
            results = None
            if callable(score_batch) and batch > 1:
                values = [space_eval(space, p) for p in proposals]
                try:
                    results = score_batch(values)
                except Exception:
                    results = None  # unfused path is always correct
            if results is not None:
                for p, res in zip(proposals, results):
                    trials.record(p, _normalize_result(res))
                    if verbose:
                        print(f"trial {len(trials)}/{max_evals}: "
                              f"{space_eval(space, p)} -> "
                              f"{trials.losses()[-1]}")
                continue
            # each worker thread is bound to its own submesh of the chip
            # pool — trials training JAX models land on disjoint chips
            # (SparkTrials' trial→executor placement, SURVEY P7).
            # Concurrency is capped at the USER'S parallelism, never the
            # generation size: a declined score_batch on a parallelism=1
            # store must fall back to sequential trials, not fan a
            # 4-candidate generation across submeshes
            run_placed_trials(proposals, run_one,
                              min(width, len(proposals)))
    return trials.argmin
