"""Search-space primitives (the `hp.*` surface).

The reference drives hyperopt with `hp.quniform` (`SML/ML 08 -
Hyperopt.py:117-122`) and `hp.choice`/`hp.uniform` (`SML/Labs/ML 08L -
Hyperopt Lab.py:97-101`). hyperopt is not vendored; this is an independent
implementation of the same search-space algebra: each dimension knows how to
sample itself, quantize, and map to/from the unit interval for the TPE
density model.
"""

from __future__ import annotations

from typing import Any, Dict, List, Sequence

import numpy as np


class Dimension:
    def __init__(self, label: str):
        self.label = label

    def sample(self, rng: np.random.RandomState):
        raise NotImplementedError

    def to_unit(self, v) -> float:
        """Map a value into [0,1] for density modeling."""
        raise NotImplementedError

    def from_unit(self, u: float):
        raise NotImplementedError


class Uniform(Dimension):
    def __init__(self, label, low, high, q=None, log=False):
        super().__init__(label)
        self.low, self.high, self.q, self.log = float(low), float(high), q, log

    def _quant(self, v: float) -> float:
        if self.q:
            v = np.round(v / self.q) * self.q
        return float(np.clip(v, self.low if not self.log else np.exp(self.low),
                             self.high if not self.log else np.exp(self.high)))

    def sample(self, rng):
        v = rng.uniform(self.low, self.high)
        if self.log:
            v = np.exp(v)
        return self._quant(v)

    def to_unit(self, v):
        x = np.log(v) if self.log else v
        return float((x - self.low) / (self.high - self.low + 1e-12))

    def from_unit(self, u):
        x = self.low + float(np.clip(u, 0, 1)) * (self.high - self.low)
        if self.log:
            x = np.exp(x)
        return self._quant(x)


class QNormal(Dimension):
    def __init__(self, label, mu, sigma, q=None, log=False):
        super().__init__(label)
        self.mu, self.sigma, self.q, self.log = float(mu), float(sigma), q, log

    def _quant(self, v):
        if self.q:
            v = np.round(v / self.q) * self.q
        return float(v)

    def sample(self, rng):
        v = rng.normal(self.mu, self.sigma)
        if self.log:
            v = np.exp(v)
        return self._quant(v)

    def to_unit(self, v):
        x = np.log(max(v, 1e-300)) if self.log else v
        return float(0.5 + 0.5 * np.tanh((x - self.mu) / (2 * self.sigma)))

    def from_unit(self, u):
        u = float(np.clip(u, 1e-6, 1 - 1e-6))
        x = self.mu + 2 * self.sigma * np.arctanh(2 * u - 1)
        if self.log:
            x = np.exp(x)
        return self._quant(x)


class Choice(Dimension):
    def __init__(self, label, options: Sequence[Any]):
        super().__init__(label)
        self.options = list(options)

    def sample(self, rng):
        return int(rng.randint(0, len(self.options)))

    def to_unit(self, v):
        return (float(v) + 0.5) / len(self.options)

    def from_unit(self, u):
        return int(np.clip(int(u * len(self.options)), 0, len(self.options) - 1))


class _HP:
    """The `hp` namespace: constructors mirror hyperopt's signatures."""

    @staticmethod
    def uniform(label, low, high):
        return Uniform(label, low, high)

    @staticmethod
    def quniform(label, low, high, q):
        return Uniform(label, low, high, q=q)

    @staticmethod
    def loguniform(label, low, high):
        return Uniform(label, low, high, log=True)

    @staticmethod
    def qloguniform(label, low, high, q):
        return Uniform(label, low, high, q=q, log=True)

    @staticmethod
    def normal(label, mu, sigma):
        return QNormal(label, mu, sigma)

    @staticmethod
    def qnormal(label, mu, sigma, q):
        return QNormal(label, mu, sigma, q=q)

    @staticmethod
    def lognormal(label, mu, sigma):
        return QNormal(label, mu, sigma, log=True)

    @staticmethod
    def choice(label, options):
        return Choice(label, options)

    @staticmethod
    def randint(label, upper):
        return Choice(label, list(range(int(upper))))


hp = _HP()


def space_eval(space: Dict[str, Dimension], point: Dict[str, Any]) -> Dict[str, Any]:
    """Resolve a raw fmin result (choice → index) into actual values."""
    out = {}
    for k, dim in space.items():
        v = point[k]
        if isinstance(dim, Choice):
            out[k] = dim.options[int(v)]
        else:
            out[k] = v
    return out


def resolve(space: Dict[str, Dimension], point: Dict[str, Any]) -> Dict[str, Any]:
    return space_eval(space, point)
