"""pandas API on the TPU frame engine — the Koalas layer (SURVEY §1 L8).

`SML/ML 14 - Koalas.py` exercises: `read_parquet/read_delta` (`:107-110`),
`ks.DataFrame(spark_df)` / `df.to_koalas()` / `kdf.to_spark()` (`:134-152`),
`value_counts` (`:172`), plotting (`:180-186`), `ks.sql("… {kdf}")`
(`:194`), the InternalFrame design (`:41-65`), default index types
(`:114-122`) and `compute.shortcut_limit` (`:201`).

Design mirrors Koalas' InternalFrame: a `_InternalFrame` pairs the immutable
distributed frame with index metadata; pandas-style mutations create a new
InternalFrame over derived columns (metadata-only updates), nothing executes
until a value is actually needed. Small results (≤ shortcut_limit rows) take
the pandas shortcut.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Union

import numpy as np
import pandas as pd

from ..frame.dataframe import DataFrame as SDataFrame
from ..frame.session import get_session
from ..frame import functions as F

_options: Dict[str, Any] = {
    "compute.shortcut_limit": 1000,
    "compute.default_index_type": "distributed-sequence",
    "plotting.backend": "matplotlib",
    "display.max_rows": 1000,
}


def set_option(key: str, value) -> None:
    _options[key] = value


def get_option(key: str):
    return _options[key]


def reset_option(key: str) -> None:
    defaults = {"compute.shortcut_limit": 1000,
                "compute.default_index_type": "distributed-sequence",
                "plotting.backend": "matplotlib", "display.max_rows": 1000}
    _options[key] = defaults[key]


class _OptionsNamespace:
    """`ks.options.plotting.backend = 'matplotlib'` attribute surface over
    the dotted option keys (`ML 14:180`)."""

    def __init__(self, prefix: str = ""):
        object.__setattr__(self, "_prefix", prefix)

    def _key(self, item: str) -> str:
        return f"{self._prefix}{item}" if not self._prefix else \
            f"{self._prefix}.{item}"

    def __getattr__(self, item):
        key = self._key(item)
        if key in _options:
            return _options[key]
        if any(k.startswith(key + ".") for k in _options):
            return _OptionsNamespace(key)
        raise AttributeError(key)

    def __setattr__(self, item, value):
        set_option(self._key(item), value)


options = _OptionsNamespace()


class _PlotAccessor:
    """`kdf.plot.hist(...)` / called directly `kdf.plot(...)` — delegates to
    pandas plotting on the collected data (`ML 14:181-186`)."""

    def __init__(self, obj):
        self._obj = obj

    def _pandas(self):
        return self._obj.to_pandas()

    def __call__(self, *a, **kw):
        return self._pandas().plot(*a, **kw)

    def hist(self, x=None, y=None, bins: int = 10, **kw):
        pdf = self._pandas()
        if isinstance(pdf, pd.Series):
            return pdf.plot.hist(bins=bins, **kw)
        cols = [c for c in (x, y) if c is not None and c in pdf.columns]
        if cols:
            pdf = pdf[cols]
        return pdf.plot.hist(bins=bins, **kw)

    def __getattr__(self, kind):
        if kind.startswith("_"):
            raise AttributeError(kind)

        def run(*a, **kw):
            return getattr(self._pandas().plot, kind)(*a, **kw)

        return run


class _InternalFrame:
    """(distributed frame, index column) — updates swap metadata, not data."""

    INDEX_COL = "__index_level_0__"

    def __init__(self, sdf: SDataFrame, index_col: Optional[str] = None):
        self.sdf = sdf
        self.index_col = index_col

    def with_index(self) -> "_InternalFrame":
        if self.index_col is not None:
            return self
        # distributed-sequence default index: per-partition offsets make a
        # global 0..n-1 sequence without a single-point shuffle (ML 14:114-122)
        sdf = self.sdf.withColumn(self.INDEX_COL,
                                  F.monotonically_increasing_id())
        return _InternalFrame(sdf, self.INDEX_COL)

    @property
    def data_columns(self) -> List[str]:
        return [c for c in self.sdf.columns if c != self.index_col]


class Series:
    def __init__(self, internal: _InternalFrame, column: str):
        self._internal = internal
        self._col = column

    # -- execution --------------------------------------------------------
    def to_pandas(self) -> pd.Series:
        pdf = self._internal.sdf.toPandas()
        s = pdf[self._col]
        if self._internal.index_col and self._internal.index_col in pdf.columns:
            s = s.set_axis(pdf[self._internal.index_col])
        return s

    toPandas = to_pandas

    def head(self, n: int = 5) -> "Series":
        return Series(_InternalFrame(self._internal.sdf.limit(n),
                                     self._internal.index_col), self._col)

    def _binop(self, other, fn) -> "Series":
        c = F.col(self._col)
        o = other._to_column() if isinstance(other, Series) else other
        out_col = fn(c, o)
        name = f"__tmp_{self._col}"
        sdf = self._internal.sdf.withColumn(name, out_col)
        return Series(_InternalFrame(sdf, self._internal.index_col), name)

    def _to_column(self):
        return F.col(self._col)

    def __add__(self, other):
        return self._binop(other, lambda a, b: a + b)

    def __sub__(self, other):
        return self._binop(other, lambda a, b: a - b)

    def __mul__(self, other):
        return self._binop(other, lambda a, b: a * b)

    def __truediv__(self, other):
        return self._binop(other, lambda a, b: a / b)

    def __gt__(self, other):
        return self._binop(other, lambda a, b: a > b)

    def __ge__(self, other):
        return self._binop(other, lambda a, b: a >= b)

    def __lt__(self, other):
        return self._binop(other, lambda a, b: a < b)

    def __le__(self, other):
        return self._binop(other, lambda a, b: a <= b)

    def __eq__(self, other):  # noqa: A003
        return self._binop(other, lambda a, b: a == b)

    # -- reductions -------------------------------------------------------
    def _agg(self, fn) -> float:
        out = self._internal.sdf.agg(fn(F.col(self._col)).alias("v")).toPandas()
        return out["v"].iloc[0]

    def mean(self):
        return float(self._agg(F.avg))

    def sum(self):  # noqa: A003
        return float(self._agg(F.sum))

    def max(self):  # noqa: A003
        return self._agg(F.max)

    def min(self):  # noqa: A003
        return self._agg(F.min)

    def count(self):
        return int(self._agg(F.count))

    def value_counts(self, normalize: bool = False, ascending: bool = False) -> pd.Series:
        out = (self._internal.sdf.groupBy(self._col).count()
               .orderBy("count", ascending=ascending).toPandas())
        s = out.set_index(self._col)["count"]
        if normalize:
            s = s / s.sum()
        return s

    def isna(self) -> "Series":
        return self._binop(None, lambda a, b: F.isnull(a))

    isnull = isna

    def fillna(self, value) -> "Series":
        return self._binop(None, lambda a, b: F.coalesce(a, F.lit(value)))

    def astype(self, dtype) -> "Series":
        name = {float: "double", int: "bigint", str: "string"}.get(dtype, str(dtype))
        return self._binop(None, lambda a, b: a.cast(name))

    @property
    def plot(self) -> _PlotAccessor:
        return _PlotAccessor(self)

    @property
    def hist(self):
        return self.to_pandas().hist

    def __repr__(self):
        return repr(self.to_pandas().head(int(_options["display.max_rows"])))


class DataFrame:
    """Koalas-style DataFrame over the distributed engine."""

    def __init__(self, data=None, index_col: Optional[str] = None):
        if isinstance(data, _InternalFrame):
            self._internal = data
        elif isinstance(data, SDataFrame):
            self._internal = _InternalFrame(data, index_col)
        elif isinstance(data, pd.DataFrame):
            self._internal = _InternalFrame(get_session().createDataFrame(data))
        elif isinstance(data, dict):
            self._internal = _InternalFrame(
                get_session().createDataFrame(pd.DataFrame(data)))
        else:
            raise TypeError(f"cannot build ks.DataFrame from {type(data)}")

    # -- interop (ML 14:134-152) -----------------------------------------
    def to_spark(self) -> SDataFrame:
        return self._internal.sdf

    def to_pandas(self) -> pd.DataFrame:
        pdf = self._internal.sdf.toPandas()
        if self._internal.index_col and self._internal.index_col in pdf.columns:
            pdf = pdf.set_index(self._internal.index_col)
            pdf.index.name = None
        return pdf

    toPandas = to_pandas

    # -- metadata ---------------------------------------------------------
    @property
    def columns(self) -> pd.Index:
        return pd.Index(self._internal.data_columns)

    @property
    def dtypes(self) -> pd.Series:
        mapping = {"double": np.dtype("float64"), "float": np.dtype("float32"),
                   "bigint": np.dtype("int64"), "int": np.dtype("int32"),
                   "string": np.dtype("O"), "boolean": np.dtype("bool")}
        return pd.Series({n: mapping.get(t, np.dtype("O"))
                          for n, t in self._internal.sdf.dtypes
                          if n != self._internal.index_col})

    @property
    def shape(self):
        return (self._internal.sdf.count(), len(self.columns))

    def __len__(self):
        return self._internal.sdf.count()

    # -- selection --------------------------------------------------------
    def __getitem__(self, key):
        if isinstance(key, str):
            return Series(self._internal, key)
        if isinstance(key, list):
            cols = key + ([self._internal.index_col]
                          if self._internal.index_col else [])
            return DataFrame(_InternalFrame(self._internal.sdf.select(*cols),
                                            self._internal.index_col))
        if isinstance(key, Series):  # boolean mask filter
            name = key._col
            sdf = key._internal.sdf.filter(F.col(name))
            keep = [c for c in sdf.columns if not c.startswith("__tmp_")]
            return DataFrame(_InternalFrame(sdf.select(*keep),
                                            self._internal.index_col))
        raise KeyError(key)

    def __getattr__(self, item):
        if item.startswith("_"):
            raise AttributeError(item)
        if item in self._internal.data_columns:
            return Series(self._internal, item)
        raise AttributeError(item)

    def __setitem__(self, key: str, value):
        if isinstance(value, Series):
            sdf = value._internal.sdf.withColumnRenamed(value._col, key) \
                if value._col.startswith("__tmp_") else \
                value._internal.sdf.withColumn(key, F.col(value._col))
            keep = [c for c in sdf.columns if not c.startswith("__tmp_")]
            self._internal = _InternalFrame(sdf.select(*keep),
                                            self._internal.index_col)
        else:
            self._internal = _InternalFrame(
                self._internal.sdf.withColumn(key, F.lit(value)),
                self._internal.index_col)

    # -- pandas verbs -----------------------------------------------------
    def head(self, n: int = 5) -> "DataFrame":
        return DataFrame(_InternalFrame(self._internal.sdf.limit(n),
                                        self._internal.index_col))

    def sort_values(self, by, ascending: bool = True) -> "DataFrame":
        by = [by] if isinstance(by, str) else list(by)
        return DataFrame(_InternalFrame(
            self._internal.sdf.orderBy(*by, ascending=ascending),
            self._internal.index_col))

    def drop(self, columns=None, labels=None) -> "DataFrame":
        cols = columns or labels or []
        cols = [cols] if isinstance(cols, str) else list(cols)
        return DataFrame(_InternalFrame(self._internal.sdf.drop(*cols),
                                        self._internal.index_col))

    def rename(self, columns: Dict[str, str]) -> "DataFrame":
        sdf = self._internal.sdf
        for old, new in columns.items():
            sdf = sdf.withColumnRenamed(old, new)
        return DataFrame(_InternalFrame(sdf, self._internal.index_col))

    def dropna(self, subset=None) -> "DataFrame":
        return DataFrame(_InternalFrame(self._internal.sdf.dropna(subset=subset),
                                        self._internal.index_col))

    def fillna(self, value) -> "DataFrame":
        return DataFrame(_InternalFrame(self._internal.sdf.fillna(value),
                                        self._internal.index_col))

    def describe(self) -> pd.DataFrame:
        return self._internal.sdf.describe().toPandas().set_index("summary")

    def groupby(self, by) -> "GroupBy":
        return GroupBy(self, [by] if isinstance(by, str) else list(by))

    def filter(self, items=None, like=None, regex=None) -> "DataFrame":  # noqa: A003
        """Column subsetting à la pandas (`ML 14:185` uses filter(items=…))."""
        cols = self._internal.data_columns
        if items is not None:
            keep = [c for c in cols if c in set(items)]
        elif like is not None:
            keep = [c for c in cols if like in c]
        elif regex is not None:
            import re as _re
            keep = [c for c in cols if _re.search(regex, c)]
        else:
            raise TypeError("filter requires items, like, or regex")
        sel = list(keep)
        if self._internal.index_col and \
                self._internal.index_col in self._internal.sdf.columns:
            sel.append(self._internal.index_col)  # carry the index through
        return DataFrame(_InternalFrame(self._internal.sdf.select(sel),
                                        self._internal.index_col))

    @property
    def plot(self) -> _PlotAccessor:
        return _PlotAccessor(self)

    def to_delta(self, path: str, mode: str = "overwrite") -> None:
        self._internal.sdf.write.format("delta").mode(mode).save(path)

    def to_parquet(self, path: str, mode: str = "overwrite") -> None:
        self._internal.sdf.write.mode(mode).parquet(path)

    def __repr__(self):
        limit = int(_options["compute.shortcut_limit"])
        return repr(self.head(limit).to_pandas())


class GroupBy:
    def __init__(self, kdf: DataFrame, keys: List[str]):
        self._kdf = kdf
        self._keys = keys

    def _run(self, out) -> pd.DataFrame:
        return out.toPandas().set_index(self._keys)

    def count(self) -> pd.DataFrame:
        return self._run(self._kdf._internal.sdf.groupBy(*self._keys).count())

    def mean(self) -> pd.DataFrame:
        return self._run(self._kdf._internal.sdf.groupBy(*self._keys).avg())

    def sum(self) -> pd.DataFrame:  # noqa: A003
        return self._run(self._kdf._internal.sdf.groupBy(*self._keys).sum())

    def max(self) -> pd.DataFrame:  # noqa: A003
        return self._run(self._kdf._internal.sdf.groupBy(*self._keys).max())

    def min(self) -> pd.DataFrame:  # noqa: A003
        return self._run(self._kdf._internal.sdf.groupBy(*self._keys).min())

    def agg(self, spec: Dict[str, str]) -> pd.DataFrame:
        return self._run(self._kdf._internal.sdf.groupBy(*self._keys).agg(spec))


# ------------------------------------------------------------------- module fns
def from_pandas(pdf: pd.DataFrame) -> DataFrame:
    return DataFrame(pdf)


def read_csv(path: str, header: bool = True, **kw) -> DataFrame:
    return DataFrame(get_session().read.csv(path, header=header,
                                            inferSchema=True))


def read_parquet(path: str) -> DataFrame:
    return DataFrame(get_session().read.parquet(path))


def read_delta(path: str, version: Optional[int] = None) -> DataFrame:
    reader = get_session().read.format("delta")
    if version is not None:
        reader = reader.option("versionAsOf", version)
    return DataFrame(reader.load(path))


def sql(query: str, **frames) -> DataFrame:
    """`ks.sql("SELECT * FROM {kdf} WHERE …")` — formatted frame references
    register as temp views (ML 14:194)."""
    import re
    import inspect
    caller = inspect.currentframe().f_back.f_locals
    session = get_session()
    for name in re.findall(r"\{(\w+)\}", query):
        obj = frames.get(name, caller.get(name))
        if obj is None:
            raise ValueError(f"ks.sql: no frame named {name!r} in scope")
        sdf = obj.to_spark() if isinstance(obj, DataFrame) else obj
        sdf.createOrReplaceTempView(name)
        query = query.replace("{" + name + "}", name)
    return DataFrame(session.sql(query))


def range(n: int) -> DataFrame:  # noqa: A001,A003
    return DataFrame(get_session().range(n))
