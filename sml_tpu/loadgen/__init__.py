"""sml_tpu.loadgen — open-loop, trace-driven load harness.

Every load number the repo had before this package came from
closed-loop synthetic clients (`bench.py --fleet` / `--serving`):
clients that wait for each response before sending the next, and
therefore SLOW THEIR OWN ARRIVAL RATE the moment the system queues —
the classic coordinated-omission trap. The percentiles such a client
reports describe the workload the system degraded its clients into,
not the workload the users offered. This package measures the offered
workload honestly:

- `TraceSpec` / `PhaseSpec` (`_spec`): a declarative workload model —
  phases of fixed rate, diurnal ramps, Poisson/bursty inter-arrivals
  with a configurable burst factor, a fat-tailed request-width mix, a
  priority-class mix, an optional multi-model key mix — compiled by a
  deterministic seeded generator into a concrete arrival schedule.
- `OpenLoopDriver` (`_driver`): fires each request at its SCHEDULED
  arrival instant regardless of completions, from a bounded worker
  pool with explicit `load.overrun` accounting (never silent), and
  charges latency from scheduled-arrival→result so queueing delay
  lands on the system's bill, not the client's. Per-phase/per-class
  p50/p99/p99.9 + shed/timeout rates, with worst-request trace
  exemplars per phase (`load.request_ms.<phase>` metrics).
- `closed_loop_probe` (`_driver`): the deliberately-wrong control for
  the omission proof — same schedule, closed-loop, send-time latency.
- `prewarm_widths`: speculative shape-bucket prewarm keyed off the
  trace's DECLARED width mix (`parallel.prewarm.speculative_prewarm`),
  so measured phases hit warm per-bucket programs.

The last completed driver's report is the `load` block of
`obs.engine_health()` (`load_report()`), and `bench.py --load` commits
the same shape as the sidecar `load` block that `obs/regress.py`
judges. See docs/LOADGEN.md for the trace grammar, the open-loop
semantics, and the tail-engineering ladder this harness motivates
(`sml.serve.flushAutoTune`, `sml.fleet.burstSlope*`).
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from ..conf import _register

_register("sml.load.workers", 32, int,
          "Open-loop driver worker-pool width: how many in-flight "
          "requests the replay can hold before a fire is delayed past "
          "its scheduled instant (delays past sml.load.overrunMicros "
          "count load.overrun — the driver is never silently the "
          "bottleneck)")
_register("sml.load.overrunMicros", 5000, int,
          "Open-loop honesty tolerance: a request picked up this many "
          "microseconds after its SCHEDULED arrival instant counts "
          "load.overrun (the schedule outran the driver's pool). "
          "Overruns flag in the bench sidecar and regress — a load "
          "report with overruns indicts the harness, not the system")
_register("sml.load.resultTimeoutSec", 30.0, float,
          "Bounded wait the load harness places on each request's "
          "result (FleetFuture/ScoreFuture.result(timeout=)); expiry "
          "raises the typed RequestTimeout, counted serve.timeout + "
          "load.timeout — an open-loop driver must never hang on one "
          "lost future")

from ._driver import OpenLoopDriver, closed_loop_probe  # noqa: E402
from ._spec import PhaseSpec, Request, TraceSpec  # noqa: E402

__all__ = ["PhaseSpec", "Request", "TraceSpec", "OpenLoopDriver",
           "closed_loop_probe", "load_report", "prewarm_widths"]

# ------------------------------------------------------------ registry
# the last COMPLETED driver, for the `load` block of engine_health()
# (read lazily off sys.modules — a health poll never imports this
# package, same contract as the fleet block)
_last_lock = threading.Lock()
_LAST: Dict[str, Optional[OpenLoopDriver]] = {"driver": None}


def _register_driver(driver: OpenLoopDriver) -> None:
    with _last_lock:
        _LAST["driver"] = driver


def load_report() -> Optional[Dict[str, object]]:
    """The load block of `obs.engine_health()`: the most recent
    completed open-loop replay's honest-tail report. None until a
    replay ran — like the fleet block, absence means the subsystem
    never ran."""
    with _last_lock:
        driver = _LAST["driver"]
    return None if driver is None else driver.report()


def prewarm_widths(fn, spec: TraceSpec, *, feature_dim: int = 8,
                   workers: Optional[int] = None) -> dict:
    """Speculative shape-bucket prewarm keyed off the trace's DECLARED
    width mix: pad each declared width onto the dispatch shape grid
    (`dispatch.bucket_rows`) and first-dispatch `fn` on a zero block
    per distinct bucket, so the measured phases reuse warm programs
    instead of paying trace+dispatch inside the tails."""
    from ..parallel import dispatch
    from ..parallel.prewarm import speculative_prewarm
    shapes = sorted({(dispatch.bucket_rows(int(rows), 1),
                      int(feature_dim))
                     for rows, _ in spec.widths})
    return speculative_prewarm(fn, shapes, workers=workers)
