"""Declarative workload model → concrete arrival schedule.

A `TraceSpec` is a declarative description of production-shaped load —
phases of fixed rate, diurnal ramps, Poisson or bursty inter-arrivals,
a fat-tailed request-width distribution, a priority-class mix, an
optional multi-model key mix — and `compile()` turns it into a concrete
list of `Request`s (scheduled instant, rows, class, model key) with a
DETERMINISTIC seeded generator: the same spec + seed always produces
the same schedule, so a committed bench trace is reproducible and a
test can assert arrivals byte-for-byte.

Arrival processes (per phase, mean rate preserved in every mode):

- ``uniform``: deterministic spacing at the instantaneous rate — the
  zero-variance floor, useful for isolating service-time variance.
- ``poisson``: nonhomogeneous Poisson via thinning (Lewis & Shedler):
  candidates at the phase's max rate, each kept with probability
  rate(t)/rate_max. Exact for ramps; no per-step discretization bias.
- ``bursty``: Poisson modulated by an on/off square wave —
  `burst_cycles` cycles per phase, the first `burst_fraction` of each
  cycle at `burst_factor` x the nominal rate and the remainder at the
  complementary off-rate, so the PHASE MEAN stays the nominal rate
  while the instantaneous rate swings the way real traffic does
  (requires burst_factor <= 1/burst_fraction to keep the off-rate
  non-negative; validated at compile).

Ramps: `rate_end` interpolates the nominal rate linearly across the
phase (a diurnal shoulder); None holds `rate` flat.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import numpy as np


@dataclass(frozen=True)
class Request:
    """One scheduled arrival: fire at `t` seconds after trace start."""
    index: int
    t: float
    rows: int
    priority: str
    phase: str
    model: Optional[str] = None


@dataclass(frozen=True)
class PhaseSpec:
    """One phase of the trace: `duration_s` of `arrival`-process load at
    a nominal `rate` (ramping to `rate_end` when set) req/s."""
    name: str
    duration_s: float
    rate: float
    rate_end: Optional[float] = None   # None = flat; else linear ramp
    arrival: str = "poisson"           # uniform | poisson | bursty
    burst_factor: float = 3.0          # burst-window rate multiplier
    burst_fraction: float = 0.2        # fraction of each cycle bursting
    burst_cycles: int = 4              # on/off cycles per phase

    def _validate(self) -> None:
        if self.duration_s <= 0 or self.rate <= 0:
            raise ValueError(
                f"phase {self.name!r}: duration_s and rate must be > 0")
        if self.arrival not in ("uniform", "poisson", "bursty"):
            raise ValueError(
                f"phase {self.name!r}: unknown arrival process "
                f"{self.arrival!r} (uniform | poisson | bursty)")
        if self.arrival == "bursty":
            if not (0.0 < self.burst_fraction < 1.0):
                raise ValueError(
                    f"phase {self.name!r}: burst_fraction must be in "
                    f"(0, 1)")
            if self.burst_factor * self.burst_fraction >= 1.0:
                raise ValueError(
                    f"phase {self.name!r}: burst_factor x burst_fraction "
                    f"must stay under 1 so the off-rate is positive "
                    f"(mean-preserving modulation)")

    # ------------------------------------------------------ rate model
    def _nominal(self, t: float) -> float:
        """The ramped nominal rate at phase-relative time t."""
        end = self.rate if self.rate_end is None else float(self.rate_end)
        return self.rate + (end - self.rate) * (t / self.duration_s)

    def _modulation(self, t: float) -> float:
        """The burst square-wave multiplier at phase-relative time t
        (1.0 outside bursty mode). Mean over a full cycle is exactly 1."""
        if self.arrival != "bursty":
            return 1.0
        cycle = self.duration_s / max(self.burst_cycles, 1)
        pos = (t % cycle) / cycle
        if pos < self.burst_fraction:
            return self.burst_factor
        off = ((1.0 - self.burst_fraction * self.burst_factor)
               / (1.0 - self.burst_fraction))
        return off

    def rate_at(self, t: float) -> float:
        """Instantaneous arrival rate (req/s) at phase-relative t."""
        return self._nominal(t) * self._modulation(t)

    def _rate_max(self) -> float:
        peak = max(self.rate,
                   self.rate if self.rate_end is None else self.rate_end)
        if self.arrival == "bursty":
            peak *= self.burst_factor
        return float(peak)

    # ------------------------------------------------------- arrivals
    def arrivals(self, rng: np.random.Generator) -> List[float]:
        """Phase-relative arrival instants, deterministic under `rng`."""
        self._validate()
        if self.arrival == "uniform":
            out, t = [], 0.0
            while True:
                t += 1.0 / self.rate_at(t)
                if t >= self.duration_s:
                    return out
                out.append(t)
        # Lewis-Shedler thinning at the phase's max rate: exact for
        # ramps AND the burst square wave, no discretization grid
        lam_max = self._rate_max()
        out, t = [], 0.0
        while True:
            t += float(rng.exponential(1.0 / lam_max))
            if t >= self.duration_s:
                return out
            if float(rng.random()) * lam_max <= self.rate_at(t):
                out.append(t)


def _weighted(rng: np.random.Generator, choices: Sequence[Tuple],
              n: int) -> List:
    values = [c[0] for c in choices]
    w = np.asarray([float(c[1]) for c in choices], dtype=np.float64)
    if (w <= 0).all():
        raise ValueError("mix weights must include a positive weight")
    idx = rng.choice(len(values), size=n, p=w / w.sum())
    return [values[i] for i in idx]


@dataclass(frozen=True)
class TraceSpec:
    """The whole trace: phases in order, plus the request mixes sampled
    independently per arrival — `widths` [(rows, weight)] (fat tails go
    here), `classes` [(priority, weight)], optional `models`
    [(model key, weight)]. `seed` makes compile() deterministic."""
    phases: Tuple[PhaseSpec, ...]
    widths: Tuple[Tuple[int, float], ...] = ((1, 1.0),)
    classes: Tuple[Tuple[str, float], ...] = (("normal", 1.0),)
    models: Tuple[Tuple[str, float], ...] = ()
    seed: int = 0

    def duration_s(self) -> float:
        return sum(p.duration_s for p in self.phases)

    def phase_names(self) -> List[str]:
        return [p.name for p in self.phases]

    def compile(self) -> List[Request]:
        """The concrete schedule: every phase's arrivals (offset by the
        phases before it) with rows/class/model sampled per request.
        Same spec + seed → identical list, always."""
        if not self.phases:
            raise ValueError("TraceSpec needs at least one phase")
        names = [p.name for p in self.phases]
        if len(set(names)) != len(names):
            raise ValueError(f"phase names must be unique: {names}")
        rng = np.random.default_rng(int(self.seed))
        out: List[Request] = []
        offset = 0.0
        for phase in self.phases:
            times = phase.arrivals(rng)
            rows = _weighted(rng, self.widths, len(times))
            classes = _weighted(rng, self.classes, len(times))
            models = (_weighted(rng, self.models, len(times))
                      if self.models else [None] * len(times))
            for t, r, c, m in zip(times, rows, classes, models):
                out.append(Request(index=len(out), t=offset + t,
                                   rows=int(r), priority=str(c),
                                   phase=phase.name, model=m))
            offset += phase.duration_s
        return out
