"""Open-loop replay — fire at the SCHEDULE, charge latency to the system.

The coordinated-omission trap (docs/LOADGEN.md): a closed-loop client
waits for each response before sending the next request, so the moment
the system queues, the client *slows its own arrival rate down* and the
percentiles it reports describe a workload nobody asked for. The
`OpenLoopDriver` is the fix: every request fires at its scheduled
arrival instant regardless of completions, and latency is measured from
the SCHEDULED arrival to the result — queueing delay (including any
delay inside the driver's own bounded worker pool) is charged to the
system under test, never hidden in the client.

Honesty guarantees:

- Bounded worker pool (`sml.load.workers`), but NEVER silent overrun:
  a request picked up more than `sml.load.overrunMicros` after its
  scheduled instant counts `load.overrun` — the driver telling you its
  own pool, not the system, became the bottleneck. Its latency is
  still charged from the schedule (pessimistic, not optimistic).
- Outcome accounting is internal and lock-guarded — the `load.*`
  PROFILER counters and `load.request_ms*` METRICS mirrors are
  best-effort (both no-op when their recorder is off), the driver's
  own report never is.
- Per-request trace contexts (`obs.mint_request`) ride the metrics
  exemplars, so `load.request_ms.<phase>` can name the literal worst
  request of each phase for the flight recorder to look up.

`closed_loop_probe` is the deliberately-wrong control: the same
schedule driven closed-loop, latency stamped from send time. Its only
job is the omission proof in tests and the sidecar's like-for-like
annotation — never report its numbers as load results.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from ..conf import GLOBAL_CONF
from ..obs import _context as _trace
from ..obs._metrics import METRICS as _METRICS
from ..obs._recorder import RECORDER as _OBS
from ..serving._batcher import RequestShed
from ..utils.profiler import PROFILER, now
from ._spec import Request

#: outcome slots the driver accounts per request (shed/timeout/error
#: requests still get a latency sample — a shed IS a fast answer, a
#: timeout IS a slow one; hiding either would be omission again)
OUTCOMES = ("served", "shed", "timeout", "errors")


def _percentiles(samples: Sequence[float]) -> Dict[str, float]:
    if not samples:
        return {"p50_ms": 0.0, "p99_ms": 0.0, "p999_ms": 0.0}
    arr = np.asarray(samples, dtype=np.float64)
    p50, p99, p999 = np.percentile(arr, (50.0, 99.0, 99.9))
    return {"p50_ms": round(float(p50), 3),
            "p99_ms": round(float(p99), 3),
            "p999_ms": round(float(p999), 3)}


class OpenLoopDriver:
    """Replay a compiled schedule open-loop against a scoring callable.

    `score(X, priority, model)` is the system under test — typically a
    fleet router's bounded-wait `score` (raises `RequestShed` /
    `RequestTimeout` for the non-served outcomes). The driver owns the
    schedule, the worker pool, and the accounting; it never retries."""

    def __init__(self, score: Callable[[np.ndarray, Optional[str],
                                        Optional[str]], object],
                 requests: Sequence[Request], *,
                 feature_dim: int = 8,
                 workers: Optional[int] = None,
                 overrun_micros: Optional[int] = None):
        self._score = score
        self._requests = list(requests)
        self._feature_dim = int(feature_dim)
        self._workers = int(GLOBAL_CONF.getInt("sml.load.workers")
                            if workers is None else workers)
        self._overrun_s = float(
            GLOBAL_CONF.getInt("sml.load.overrunMicros")
            if overrun_micros is None else overrun_micros) / 1e6
        # one zero block per distinct width, built up front: the fire
        # path must not pay an allocation that scales with row width
        self._blocks = {
            rows: np.zeros((rows, self._feature_dim), dtype=np.float32)
            for rows in {r.rows for r in self._requests}}
        # the driver's OWN accounting — PROFILER/METRICS are mirrors
        # that no-op when disabled, this never does
        self._lock = threading.Lock()
        self._counts: Dict[str, int] = {k: 0 for k in OUTCOMES}
        self._counts["requests"] = 0
        self._counts["overrun"] = 0
        # (phase, priority) -> latency samples; (phase, None) = all
        self._samples: Dict[tuple, List[float]] = {}
        # phase -> (worst latency, trace id or None)
        self._worst: Dict[str, tuple] = {}
        self._wall_s = 0.0
        self._ran = False

    # ------------------------------------------------------- fire path
    def _record(self, r: Request, ms: float, outcome: str,
                trace_id: Optional[int]) -> None:
        with self._lock:
            self._counts["requests"] += 1
            self._counts[outcome] += 1
            self._samples.setdefault((r.phase, None), []).append(ms)
            self._samples.setdefault((r.phase, r.priority), []).append(ms)
            worst = self._worst.get(r.phase)
            if worst is None or ms > worst[0]:
                self._worst[r.phase] = (ms, trace_id)
        PROFILER.count("load.requests")
        PROFILER.count(f"load.{outcome}")
        _METRICS.observe("load.request_ms", ms, exemplar=trace_id)
        _METRICS.observe(f"load.request_ms.{r.phase}", ms,
                         exemplar=trace_id)
        _METRICS.observe(f"load.request_ms.{r.phase}.{r.priority}", ms,
                         exemplar=trace_id)

    def _fire_one(self, r: Request, epoch: float) -> None:
        sched = epoch + r.t
        lag = now() - sched
        if lag > self._overrun_s:
            # the schedule outran the pool: the driver itself delayed
            # this fire. NEVER silent — it flags in report()/regress
            with self._lock:
                self._counts["overrun"] += 1
            PROFILER.count("load.overrun")
        ctx = _trace.mint_request(rows=r.rows)
        trace_id = None if ctx is None else ctx.trace_id
        outcome = "served"
        try:
            with _trace.activate(ctx):
                self._score(self._blocks[r.rows], r.priority, r.model)
        except RequestShed:
            outcome = "shed"
        except TimeoutError:  # RequestTimeout subclasses TimeoutError
            outcome = "timeout"
        except Exception:
            outcome = "errors"
        # latency from the SCHEDULED arrival: queueing delay anywhere
        # between the schedule and the result is the system's bill
        self._record(r, (now() - sched) * 1e3, outcome, trace_id)

    def run(self) -> Dict[str, object]:
        """Replay the whole schedule; returns `report()`. The dispatch
        loop sleeps to each scheduled instant and hands the fire to the
        pool — a full pool queues the fire (counted as overrun past the
        tolerance), it never re-times the schedule."""
        if self._ran:
            raise RuntimeError("OpenLoopDriver is single-shot; build a "
                               "new driver to replay again")
        self._ran = True
        if _OBS.enabled:
            _OBS.emit("load", "load.run", args={
                "requests": len(self._requests),
                "workers": self._workers,
                "phases": sorted({r.phase for r in self._requests})})
        from concurrent.futures import ThreadPoolExecutor
        with ThreadPoolExecutor(
                max_workers=max(1, self._workers),
                thread_name_prefix="sml-loadgen") as pool:
            # pre-spawn every worker thread: the executor creates them
            # lazily per submit, and a fire that also pays thread
            # start-up would book-keep as a spurious overrun. The
            # barrier holds each no-op on its own thread, forcing the
            # pool to its full width before the clock starts
            barrier = threading.Barrier(max(1, self._workers) + 1)
            for _ in range(max(1, self._workers)):
                pool.submit(barrier.wait)
            barrier.wait()
            t0 = now()
            epoch = t0
            futures = []
            for r in self._requests:
                delay = (epoch + r.t) - now()
                if delay > 0:
                    time.sleep(delay)
                futures.append(pool.submit(self._fire_one, r, epoch))
            for f in futures:
                f.result()
        self._wall_s = now() - t0
        from . import _register_driver
        _register_driver(self)
        return self.report()

    # -------------------------------------------------------- reporting
    def report(self) -> Dict[str, object]:
        """The honest-tail block: totals, overruns, and per-phase
        per-class p50/p99/p99.9 with the worst request's latency and
        trace exemplar. Shapes match the bench sidecar's `load` block
        so regress can diff them directly."""
        with self._lock:
            counts = dict(self._counts)
            samples = {k: list(v) for k, v in self._samples.items()}
            worst = dict(self._worst)
        phases: Dict[str, dict] = {}
        order: List[str] = []
        for r in self._requests:
            if r.phase not in order:
                order.append(r.phase)
        for name in order:
            overall = samples.get((name, None), [])
            block = dict(_percentiles(overall))
            block["requests"] = len(overall)
            w_ms, w_trace = worst.get(name, (0.0, None))
            block["worst_ms"] = round(float(w_ms), 3)
            block["worst_trace"] = _trace.hex_id(w_trace)
            classes = {}
            for (ph, cls), lat in samples.items():
                if ph == name and cls is not None:
                    classes[cls] = dict(_percentiles(lat),
                                        count=len(lat))
            block["classes"] = dict(sorted(classes.items()))
            phases[name] = block
        n = max(counts["requests"], 1)
        return {
            "requests": counts["requests"],
            "served": counts["served"],
            "shed": counts["shed"],
            "timeout": counts["timeout"],
            "errors": counts["errors"],
            "overrun": counts["overrun"],
            "shed_rate": round(counts["shed"] / n, 4),
            "timeout_rate": round(counts["timeout"] / n, 4),
            "wall_s": round(self._wall_s, 3),
            "workers": self._workers,
            "phases": phases,
        }


def closed_loop_probe(score: Callable[[np.ndarray, Optional[str],
                                       Optional[str]], object],
                      requests: Sequence[Request], *,
                      feature_dim: int = 8) -> List[float]:
    """The coordinated-omission CONTROL: drive the same requests
    closed-loop (wait for each result before sending the next; latency
    stamped from SEND time, not schedule) and return the per-request
    latencies in ms. When the system stalls, these numbers stay small —
    that divergence from the open-loop report is the omission proof,
    which is the only thing this probe is for."""
    out: List[float] = []
    blocks = {r.rows: np.zeros((r.rows, int(feature_dim)),
                               dtype=np.float32)
              for r in requests}
    for r in requests:
        t0 = now()
        try:
            score(blocks[r.rows], r.priority, r.model)
        except Exception:
            pass  # the control only measures what a naive client times
        out.append((now() - t0) * 1e3)
    return out
