"""MLflow-compatible tracking + model registry, file-backed (SURVEY §1 L6).

Usage is a drop-in for the course's calls:

    from sml_tpu import tracking as mlflow
    with mlflow.start_run(run_name="LR-model") as run:
        mlflow.log_param("label", "price")
        mlflow.log_metric("rmse", rmse)
        mlflow.spark.log_model(pipeline_model, "model")
    mlflow.search_runs(exp_id, order_by=["metrics.rmse ASC"])

Covers: runs/params/metrics/artifacts/figures (`SML/ML 04 - MLflow
Tracking.py:70-228`), nested runs (`SML/ML 13 - Training with Pandas
Function API.py:93-108`), spark/sklearn/pyfunc model flavors with
`runs:/`/`models:/` URIs (`SML/ML 05 - MLflow Model Registry.py:197-210`),
the registry with stage transitions (`ML 05:171-175,293-298`), filter-string
run search (`SML/Solutions/Labs/ML 05L` answer), and autolog stubs.
`install_mlflow_shim()` aliases this module as `mlflow` in sys.modules so
untouched course code imports keep working.
"""

from __future__ import annotations

import os
import pickle
import shutil
import sys
import threading
import types
from typing import Any, Dict, List, Optional

import numpy as np
import pandas as pd

from . import _store
from ._store import get_tracking_uri, set_tracking_uri

_active_runs = threading.local()
_active_experiment = {"id": None}


# ------------------------------------------------------------------ run facade
class RunInfo:
    def __init__(self, meta: Dict[str, Any]):
        self.run_id = meta["run_id"]
        self.run_uuid = meta["run_id"]
        self.experiment_id = meta["experiment_id"]
        self.run_name = meta.get("run_name")
        self.status = meta.get("status")
        self.artifact_uri = meta.get("artifact_uri")
        self.start_time = meta.get("start_time")
        self.end_time = meta.get("end_time")


class RunData:
    def __init__(self, params, metrics, tags):
        self.params = params
        self.metrics = metrics
        self.tags = tags


class Run:
    def __init__(self, meta, params=None, metrics=None, tags=None):
        self.info = RunInfo(meta)
        self.data = RunData(params or {}, metrics or {}, tags or {})


class ActiveRun(Run):
    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        end_run("FAILED" if exc_type else "FINISHED")
        return False


def _run_stack() -> List[ActiveRun]:
    if not hasattr(_active_runs, "stack"):
        _active_runs.stack = []
    return _active_runs.stack


def set_experiment(name: str):
    exp = _store.get_or_create_experiment(name)
    _active_experiment["id"] = exp["experiment_id"]
    return types.SimpleNamespace(**exp)


def _current_experiment_id() -> str:
    if _active_experiment["id"] is None:
        _active_experiment["id"] = _store.default_experiment()["experiment_id"]
    return _active_experiment["id"]


def start_run(run_id: Optional[str] = None, run_name: Optional[str] = None,
              nested: bool = False, tags: Optional[Dict[str, str]] = None,
              experiment_id: Optional[str] = None) -> ActiveRun:
    stack = _run_stack()
    if stack and not nested:
        raise RuntimeError("a run is already active; use nested=True")
    exp_id = experiment_id or _current_experiment_id()
    parent = stack[-1].info.run_id if stack else None
    meta = _store.create_run(exp_id, run_name=run_name, tags=tags,
                             parent_run_id=parent)
    run = ActiveRun(meta)
    stack.append(run)
    return run


def end_run(status: str = "FINISHED") -> None:
    stack = _run_stack()
    if stack:
        run = stack.pop()
        _store.end_run(run.info.experiment_id, run.info.run_id, status)


def active_run() -> Optional[ActiveRun]:
    stack = _run_stack()
    return stack[-1] if stack else None


def _require_run() -> ActiveRun:
    run = active_run()
    if run is None:
        return start_run()
    return run


def log_param(key: str, value: Any) -> None:
    r = _require_run()
    _store.log_kv(r.info.experiment_id, r.info.run_id, "params", key, value)


def log_params(params: Dict[str, Any]) -> None:
    for k, v in params.items():
        log_param(k, v)


def log_metric(key: str, value: float, step: Optional[int] = None) -> None:
    r = _require_run()
    _store.log_kv(r.info.experiment_id, r.info.run_id, "metrics", key, value,
                  step=step)


def log_metrics(metrics: Dict[str, float], step: Optional[int] = None) -> None:
    for k, v in metrics.items():
        log_metric(k, v, step=step)


def log_engine_metrics(metrics: Dict[str, float],
                       step: Optional[int] = None) -> None:
    """Log flight-recorder engine metrics to the ACTIVE run (no implicit
    run creation — system metrics must never spawn runs). Keys are
    namespaced under `engine.` if not already; the MLflow system-metrics
    mirror, fed by `sml_tpu.obs.autolog_fit` on every outermost fit."""
    if active_run() is None:
        return
    log_metrics({(k if k.startswith("engine.") else f"engine.{k}"):
                 float(v) for k, v in metrics.items()}, step=step)


def set_tag(key: str, value: Any) -> None:
    r = _require_run()
    _store.log_kv(r.info.experiment_id, r.info.run_id, "tags", key, value)


def set_tags(tags: Dict[str, Any]) -> None:
    for k, v in tags.items():
        set_tag(k, v)


def _artifact_dir(artifact_path: Optional[str] = None) -> str:
    r = _require_run()
    d = _store.run_dir(r.info.experiment_id, r.info.run_id)
    out = os.path.join(d, "artifacts", artifact_path or "")
    os.makedirs(out, exist_ok=True)
    return out


def log_artifact(local_path: str, artifact_path: Optional[str] = None) -> None:
    shutil.copy(local_path, _artifact_dir(artifact_path))


def log_artifacts(local_dir: str, artifact_path: Optional[str] = None) -> None:
    shutil.copytree(local_dir, _artifact_dir(artifact_path), dirs_exist_ok=True)


def log_figure(figure, artifact_file: str) -> None:
    out = os.path.join(_artifact_dir(os.path.dirname(artifact_file) or None),
                       os.path.basename(artifact_file))
    figure.savefig(out)


def log_text(text: str, artifact_file: str) -> None:
    out = os.path.join(_artifact_dir(os.path.dirname(artifact_file) or None),
                       os.path.basename(artifact_file))
    with open(out, "w") as f:
        f.write(text)


def log_dict(d: Dict, artifact_file: str) -> None:
    import json
    out = os.path.join(_artifact_dir(os.path.dirname(artifact_file) or None),
                       os.path.basename(artifact_file))
    with open(out, "w") as f:
        json.dump(d, f, indent=1, default=str)


def get_run(run_id: str) -> Run:
    d = _store.find_run(run_id)
    if d is None:
        raise ValueError(f"run {run_id!r} not found")
    rec = _store.read_run(d)
    return Run(rec["meta"], rec["params"], rec["metrics"], rec["tags"])


# -------------------------------------------------------------- model flavors
class ModelSignature:
    def __init__(self, inputs, outputs):
        self.inputs = inputs
        self.outputs = outputs

    def to_dict(self):
        return {"inputs": self.inputs, "outputs": self.outputs}

    def __repr__(self):
        return f"inputs:\n  {self.inputs}\noutputs:\n  {self.outputs}"


def infer_signature(model_input, model_output) -> ModelSignature:
    def describe(x):
        if isinstance(x, pd.DataFrame):
            return [{"name": c, "type": str(x[c].dtype)} for c in x.columns]
        if isinstance(x, pd.Series):
            return [{"type": str(x.dtype)}]
        arr = np.asarray(x)
        return [{"type": str(arr.dtype), "shape": list(arr.shape)}]
    return ModelSignature(describe(model_input), describe(model_output))


def _resolve_model_uri(model_uri: str) -> str:
    """runs:/<id>/<path>, models:/<name>/<version|Stage>, or a local path."""
    if model_uri.startswith("runs:/"):
        rest = model_uri[len("runs:/"):]
        run_id, _, sub = rest.partition("/")
        d = _store.find_run(run_id)
        if d is None:
            raise ValueError(f"run {run_id!r} not found")
        return os.path.join(d, "artifacts", sub)
    if model_uri.startswith("models:/"):
        rest = model_uri[len("models:/"):]
        name, _, selector = rest.partition("/")
        versions = _store.list_model_versions(name)
        if not versions:
            raise ValueError(f"registered model {name!r} has no versions")
        if selector and selector.isdigit():
            pick = next((v for v in versions if str(v["version"]) == selector), None)
        elif selector:  # stage name
            staged = [v for v in versions if v["current_stage"] == selector]
            pick = staged[-1] if staged else None
        else:
            pick = versions[-1]
        if pick is None:
            raise ValueError(f"no version of {name!r} matches {selector!r}")
        return os.path.join(_store.model_dir(name), "versions",
                            str(pick["version"]), "model")
    return model_uri


def _log_model_dir(artifact_path: str, save_fn, registered_model_name=None,
                   signature=None, input_example=None, flavor="sml") -> str:
    run = _require_run()
    out = os.path.join(_store.run_dir(run.info.experiment_id, run.info.run_id),
                       "artifacts", artifact_path)
    os.makedirs(out, exist_ok=True)
    save_fn(out)
    meta = {"flavor": flavor, "run_id": run.info.run_id}
    if signature is not None:
        meta["signature"] = signature.to_dict()
    _store._write_json(os.path.join(out, "MLmodel.json"), meta)
    if input_example is not None:
        try:
            pd.DataFrame(input_example).to_json(
                os.path.join(out, "input_example.json"), orient="split")
        except Exception:
            pass
    if registered_model_name:
        register_model(f"runs:/{run.info.run_id}/{artifact_path}",
                       registered_model_name)
    return out


class _SparkFlavor:
    """Flavor for sml_tpu PipelineModel / any ml.base.Saveable."""

    @staticmethod
    def log_model(model, artifact_path: str, signature=None,
                  input_example=None, registered_model_name=None, **kw):
        return _log_model_dir(
            artifact_path, lambda d: model._save_to(os.path.join(d, "native")),
            registered_model_name=registered_model_name, signature=signature,
            input_example=input_example, flavor="spark")

    @staticmethod
    def save_model(model, path: str):
        model._save_to(os.path.join(path, "native"))
        _store._write_json(os.path.join(path, "MLmodel.json"),
                           {"flavor": "spark"})

    @staticmethod
    def load_model(model_uri: str):
        from ..ml.base import Saveable
        path = _resolve_model_uri(model_uri)
        return Saveable.load(os.path.join(path, "native"))


class _SklearnFlavor:
    @staticmethod
    def log_model(model, artifact_path: str, signature=None,
                  input_example=None, registered_model_name=None, **kw):
        def save(d):
            with open(os.path.join(d, "model.pkl"), "wb") as f:
                pickle.dump(model, f)
        return _log_model_dir(artifact_path, save,
                              registered_model_name=registered_model_name,
                              signature=signature, input_example=input_example,
                              flavor="sklearn")

    @staticmethod
    def save_model(model, path: str):
        os.makedirs(path, exist_ok=True)
        with open(os.path.join(path, "model.pkl"), "wb") as f:
            pickle.dump(model, f)
        _store._write_json(os.path.join(path, "MLmodel.json"),
                           {"flavor": "sklearn"})

    @staticmethod
    def load_model(model_uri: str):
        path = _resolve_model_uri(model_uri)
        with open(os.path.join(path, "model.pkl"), "rb") as f:
            return pickle.load(f)


class PyFuncModel:
    """Uniform predict(pandas) wrapper over any logged flavor."""

    def __init__(self, path: str):
        self._path = path
        self.metadata = types.SimpleNamespace(
            **_store._read_json(os.path.join(path, "MLmodel.json")))
        flavor = getattr(self.metadata, "flavor", "sklearn")
        if flavor == "spark" or os.path.isdir(os.path.join(path, "native")):
            from ..ml.base import Saveable
            self._native = Saveable.load(os.path.join(path, "native"))
            self._kind = "spark"
        else:
            with open(os.path.join(path, "model.pkl"), "rb") as f:
                self._native = pickle.load(f)
            self._kind = "sklearn"

    def predict(self, data):
        if self._kind == "sklearn":
            pred = self._native.predict(data)
            return np.asarray(pred)
        # native model: prefer the mesh-sharded device scorer (feature
        # stages on host, model math sharded over chips — SURVEY P8);
        # models without a device path fall back to frame transform
        if not hasattr(self, "_scorer"):
            from ..ml.inference import DeviceScorer
            try:
                self._scorer = DeviceScorer(self._native)
            except TypeError:
                self._scorer = None
        if self._scorer is not None:
            return self._scorer(pd.DataFrame(data))
        from ..frame.session import get_session
        df = get_session().createDataFrame(pd.DataFrame(data))
        out = self._native.transform(df).toPandas()
        col = "prediction" if "prediction" in out.columns else out.columns[-1]
        return out[col].values

    def unwrap_python_model(self):
        return self._native


class _PyfuncFlavor:
    @staticmethod
    def load_model(model_uri: str) -> PyFuncModel:
        return PyFuncModel(_resolve_model_uri(model_uri))

    @staticmethod
    def spark_udf(session, model_uri: str, result_type: str = "double"):
        """Column-function for batch scoring (`ML 09:80-81`,
        `Solutions/Labs/ML 12L`): returns a callable usable in
        `df.withColumn("pred", predict(*df.columns))`."""
        model = PyFuncModel(_resolve_model_uri(model_uri))
        from ..frame.column import Column, ensure_column

        def udf(*cols):
            cols = [ensure_column(c) for c in cols]

            def ev(pdf, ctx):
                data = pd.DataFrame({c._name: c._eval(pdf, ctx).values
                                     for c in cols})
                return pd.Series(np.asarray(model.predict(data), dtype=np.float64))

            return Column(ev, "prediction")

        return udf


spark = _SparkFlavor()
sklearn = _SklearnFlavor()
pyfunc = _PyfuncFlavor()


# --------------------------------------------------------------------- search
def _match_filter(rec: Dict[str, Any], filter_string: Optional[str]) -> bool:
    if not filter_string:
        return True
    import re
    for clause in re.split(r"\s+and\s+", filter_string, flags=re.I):
        m = re.match(r"\s*(params|metrics|tags|attributes)\.(\"[^\"]+\"|[\w.]+)"
                     r"\s*(=|!=|>=|<=|>|<|LIKE)\s*(.+?)\s*$", clause, re.I)
        if not m:
            raise ValueError(f"cannot parse filter clause {clause!r}")
        kind, key, op, val = m.groups()
        key = key.strip('"')
        val = val.strip().strip("'").strip('"')
        bucket = rec["meta"] if kind == "attributes" else rec[kind]
        have = bucket.get(key)
        if have is None:
            return False
        if kind == "metrics":
            have, val = float(have), float(val)
        else:
            have = str(have)
        ok = {"=": have == val, "!=": have != val,
              ">": have > val, "<": have < val,
              ">=": have >= val, "<=": have <= val,
              "LIKE": isinstance(have, str) and val.replace("%", "") in have,
              "like": isinstance(have, str) and val.replace("%", "") in have,
              }[op if op in ("=", "!=", ">", "<", ">=", "<=") else op]
        if not ok:
            return False
    return True


def _sorted_recs(recs, order_by: Optional[List[str]]):
    if not order_by:
        return recs
    for clause in reversed(order_by):
        parts = clause.split()
        key = parts[0]
        desc = len(parts) > 1 and parts[1].upper() == "DESC"
        kind, _, name = key.partition(".")

        def sort_key(r, kind=kind, name=name):
            if kind == "attributes":
                return r["meta"].get(name) or 0
            v = r.get(kind, {}).get(name)
            return (v is None, v)

        recs = sorted(recs, key=sort_key, reverse=desc)
    return recs


def search_runs(experiment_ids=None, filter_string: Optional[str] = None,
                order_by: Optional[List[str]] = None,
                max_results: int = 1000, output_format: str = "pandas"):
    if experiment_ids is None:
        experiment_ids = [_current_experiment_id()]
    if isinstance(experiment_ids, str):
        experiment_ids = [experiment_ids]
    recs = []
    for e in experiment_ids:
        recs.extend(_store.list_runs(e))
    recs = [r for r in recs if _match_filter(r, filter_string)]
    recs = _sorted_recs(recs, order_by)[:max_results]
    if output_format == "list":
        return [Run(r["meta"], r["params"], r["metrics"], r["tags"]) for r in recs]
    rows = []
    for r in recs:
        row = {"run_id": r["meta"]["run_id"],
               "experiment_id": r["meta"]["experiment_id"],
               "status": r["meta"].get("status"),
               "start_time": r["meta"].get("start_time"),
               "end_time": r["meta"].get("end_time"),
               "artifact_uri": r["meta"].get("artifact_uri")}
        for k, v in r["params"].items():
            row[f"params.{k}"] = v
        for k, v in r["metrics"].items():
            row[f"metrics.{k}"] = v
        for k, v in r["tags"].items():
            row[f"tags.{k}"] = v
        rows.append(row)
    return pd.DataFrame(rows)


def register_model(model_uri: str, name: str):
    src = _resolve_model_uri(model_uri)
    run_id = None
    if model_uri.startswith("runs:/"):
        run_id = model_uri[len("runs:/"):].partition("/")[0]
    meta = _store.create_model_version(name, src, run_id=run_id)
    return types.SimpleNamespace(**meta)


# --------------------------------------------------------------------- client
class MlflowClient:
    """The `MlflowClient` query/registry surface (`ML 04:196-228`,
    `ML 05:134-331`)."""

    def __init__(self, tracking_uri: Optional[str] = None):
        if tracking_uri:
            set_tracking_uri(tracking_uri)

    # tracking ----------------------------------------------------------
    def create_experiment(self, name: str) -> str:
        return _store.get_or_create_experiment(name)["experiment_id"]

    def get_experiment(self, experiment_id: str):
        meta = _store.get_experiment(experiment_id)
        return types.SimpleNamespace(**meta) if meta else None

    def get_experiment_by_name(self, name: str):
        for e in _store.list_experiments():
            if e["name"] == name:
                return types.SimpleNamespace(**e)
        return None

    def search_experiments(self):
        return [types.SimpleNamespace(**e) for e in _store.list_experiments()]

    list_experiments = search_experiments

    def get_run(self, run_id: str) -> Run:
        return get_run(run_id)

    def search_runs(self, experiment_ids, filter_string=None, order_by=None,
                    max_results=1000):
        return search_runs(experiment_ids, filter_string=filter_string,
                           order_by=order_by, max_results=max_results,
                           output_format="list")

    def list_artifacts(self, run_id: str, path: Optional[str] = None):
        d = _store.find_run(run_id)
        base = os.path.join(d, "artifacts", path or "")
        out = []
        for root, _dirs, files in os.walk(base):
            for f in files:
                rel = os.path.relpath(os.path.join(root, f),
                                      os.path.join(d, "artifacts"))
                out.append(types.SimpleNamespace(path=rel, is_dir=False))
        return out

    def set_tag(self, run_id: str, key: str, value) -> None:
        d = _store.find_run(run_id)
        rec = _store.read_run(d)
        _store.log_kv(rec["meta"]["experiment_id"], run_id, "tags", key, value)

    # registry ----------------------------------------------------------
    def create_registered_model(self, name: str, description: str = ""):
        return types.SimpleNamespace(**_store.create_registered_model(name, description))

    def get_registered_model(self, name: str):
        meta = _store.get_registered_model(name)
        if meta is None:
            raise ValueError(f"registered model {name!r} not found")
        ns = types.SimpleNamespace(**meta)
        ns.latest_versions = [types.SimpleNamespace(**v)
                              for v in _store.list_model_versions(name)]
        return ns

    def update_registered_model(self, name: str, description: str = ""):
        return types.SimpleNamespace(**_store.update_registered_model(name, description))

    def create_model_version(self, name: str, source: str, run_id=None,
                             description: str = ""):
        return types.SimpleNamespace(
            **_store.create_model_version(name, source, run_id, description))

    def get_model_version(self, name: str, version):
        meta = _store.get_model_version(name, version)
        if meta is None:
            raise ValueError(f"model version {name}/{version} not found")
        return types.SimpleNamespace(**meta)

    def update_model_version(self, name: str, version, description: str = ""):
        return types.SimpleNamespace(
            **_store.update_model_version(name, version, description))

    def transition_model_version_stage(self, name: str, version, stage: str,
                                       archive_existing_versions: bool = False):
        return types.SimpleNamespace(**_store.set_version_stage(
            name, version, stage, archive_existing_versions))

    def get_latest_versions(self, name: str, stages: Optional[List[str]] = None):
        versions = _store.list_model_versions(name)
        if stages:
            by_stage = {}
            for v in versions:
                if v["current_stage"] in stages:
                    by_stage[v["current_stage"]] = v
            return [types.SimpleNamespace(**v) for v in by_stage.values()]
        return [types.SimpleNamespace(**v) for v in versions[-1:]]

    def search_model_versions(self, filter_string: str):
        import re
        m = re.match(r"\s*name\s*=\s*'([^']+)'", filter_string)
        if not m:
            raise ValueError(f"unsupported filter {filter_string!r}")
        return [types.SimpleNamespace(**v)
                for v in _store.list_model_versions(m.group(1))]

    def delete_model_version(self, name: str, version) -> None:
        _store.delete_model_version(name, version)

    def delete_registered_model(self, name: str) -> None:
        _store.delete_registered_model(name)


# -------------------------------------------------------------------- autolog
class _AutologState:
    enabled = False
    log_models = True


def autolog(log_models: bool = True, disable: bool = False, **kw) -> None:
    _AutologState.enabled = not disable
    _AutologState.log_models = log_models


class _PysparkMLNamespace:
    autolog = staticmethod(autolog)


class _PysparkNamespace:
    ml = _PysparkMLNamespace()


pyspark = _PysparkNamespace()


# `mlflow.tracking.MlflowClient` parity: the module aliases itself as its
# own `tracking` submodule (`ML 04:196`, `ML 05` use both spellings)
tracking = sys.modules[__name__]


def install_mlflow_shim() -> None:
    """Alias this module as `mlflow` so course code imports run unchanged."""
    mod = sys.modules[__name__]
    sys.modules.setdefault("mlflow", mod)
    sys.modules.setdefault("mlflow.tracking", mod)
    sys.modules.setdefault("mlflow.spark", spark)   # type: ignore[arg-type]
    sys.modules.setdefault("mlflow.sklearn", sklearn)  # type: ignore[arg-type]
    sys.modules.setdefault("mlflow.pyfunc", pyfunc)  # type: ignore[arg-type]


__all__ = ["start_run", "end_run", "active_run", "log_param", "log_params",
           "log_metric", "log_metrics", "log_engine_metrics",
           "log_artifact", "log_artifacts",
           "log_figure", "log_text", "log_dict", "set_tag", "set_tags",
           "set_experiment", "set_tracking_uri", "get_tracking_uri",
           "get_run", "search_runs", "register_model", "infer_signature",
           "MlflowClient", "spark", "sklearn", "pyfunc", "pyspark",
           "autolog", "install_mlflow_shim", "ModelSignature", "PyFuncModel"]
