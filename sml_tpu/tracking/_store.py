"""File-based tracking store (runs, params, metrics, artifacts, registry).

The reference logs everything through MLflow (`SML/ML 04 - MLflow
Tracking.py:70-228`, registry `SML/ML 05 - MLflow Model Registry.py`). That
package is not vendored here; this store implements the same data model on
the local filesystem:

    <root>/experiments/<exp_id>/meta.json
    <root>/experiments/<exp_id>/<run_id>/{meta,params,metrics,tags}.json
    <root>/experiments/<exp_id>/<run_id>/artifacts/...
    <root>/registry/<name>/meta.json
    <root>/registry/<name>/versions/<v>/{meta.json, model/...}

Writes are atomic (tmp+rename) so concurrent trial threads (CV/hyperopt
autologging) can't tear JSON files.
"""

from __future__ import annotations

import json
import os
import shutil
import threading
import uuid
from typing import Any, Dict, List, Optional
from ..utils.profiler import PROFILER, wallclock

_lock = threading.RLock()
_tracking_root: Optional[str] = None

DEFAULT_DIRNAME = "smlruns"


def set_tracking_uri(path: str) -> None:
    global _tracking_root
    with _lock:
        _tracking_root = path.replace("file://", "")


def get_tracking_uri() -> str:
    global _tracking_root
    with _lock:
        if _tracking_root is None:
            _tracking_root = os.environ.get(
                "SML_TRACKING_DIR", os.path.join(os.getcwd(), DEFAULT_DIRNAME))
        os.makedirs(_tracking_root, exist_ok=True)
        return _tracking_root


def _write_json(path: str, obj: Any) -> None:
    tmp = f"{path}.tmp{os.getpid()}{threading.get_ident()}"
    with open(tmp, "w") as f:
        json.dump(obj, f, indent=1, default=str)
    os.replace(tmp, path)


def _read_json(path: str, default=None):
    try:
        with open(path) as f:
            return json.load(f)
    except (FileNotFoundError, json.JSONDecodeError):
        return {} if default is None else default


def new_id() -> str:
    return uuid.uuid4().hex


# ----------------------------------------------------------------- experiments
def experiments_dir() -> str:
    d = os.path.join(get_tracking_uri(), "experiments")
    os.makedirs(d, exist_ok=True)
    return d


def get_or_create_experiment(name: str) -> Dict[str, Any]:
    with _lock:
        for exp in list_experiments():
            if exp["name"] == name:
                return exp
        exp_id = new_id()[:12]
        meta = {"experiment_id": exp_id, "name": name,
                "creation_time": wallclock(), "lifecycle_stage": "active"}
        d = os.path.join(experiments_dir(), exp_id)
        os.makedirs(d, exist_ok=True)
        _write_json(os.path.join(d, "meta.json"), meta)
        return meta


def get_experiment(exp_id: str) -> Optional[Dict[str, Any]]:
    meta = _read_json(os.path.join(experiments_dir(), exp_id, "meta.json"))
    return meta or None


def list_experiments() -> List[Dict[str, Any]]:
    out = []
    for e in sorted(os.listdir(experiments_dir())):
        meta = _read_json(os.path.join(experiments_dir(), e, "meta.json"))
        if meta:
            out.append(meta)
    return out


def default_experiment() -> Dict[str, Any]:
    return get_or_create_experiment("Default")


# ----------------------------------------------------------------------- runs
def run_dir(exp_id: str, run_id: str) -> str:
    return os.path.join(experiments_dir(), exp_id, run_id)


def find_run(run_id: str) -> Optional[str]:
    """Locate a run's directory by id across experiments."""
    for e in os.listdir(experiments_dir()):
        d = run_dir(e, run_id)
        if os.path.isdir(d):
            return d
    return None


def create_run(exp_id: str, run_name: Optional[str] = None,
               tags: Optional[Dict[str, str]] = None,
               parent_run_id: Optional[str] = None) -> Dict[str, Any]:
    run_id = new_id()
    d = run_dir(exp_id, run_id)
    os.makedirs(os.path.join(d, "artifacts"), exist_ok=True)
    meta = {"run_id": run_id, "experiment_id": exp_id,
            "run_name": run_name or f"run-{run_id[:8]}",
            "status": "RUNNING", "start_time": wallclock(), "end_time": None,
            "artifact_uri": os.path.join(d, "artifacts")}
    _write_json(os.path.join(d, "meta.json"), meta)
    t = dict(tags or {})
    if run_name:
        t["mlflow.runName"] = run_name
    if parent_run_id:
        t["mlflow.parentRunId"] = parent_run_id
    _write_json(os.path.join(d, "tags.json"), t)
    _write_json(os.path.join(d, "params.json"), {})
    _write_json(os.path.join(d, "metrics.json"), {})
    return meta


def end_run(exp_id: str, run_id: str, status: str = "FINISHED") -> None:
    d = run_dir(exp_id, run_id)
    meta = _read_json(os.path.join(d, "meta.json"))
    meta["status"] = status
    meta["end_time"] = wallclock()
    _write_json(os.path.join(d, "meta.json"), meta)


def log_kv(exp_id: str, run_id: str, kind: str, key: str, value: Any,
           step: Optional[int] = None) -> None:
    with _lock:
        d = run_dir(exp_id, run_id)
        path = os.path.join(d, f"{kind}.json")
        data = _read_json(path)
        if kind == "metrics":
            hist = data.get(key, [])
            hist.append({"value": float(value), "step": step or len(hist),
                         "timestamp": wallclock()})
            data[key] = hist
        else:
            data[key] = str(value) if kind == "params" else value
        _write_json(path, data)


def read_run(d: str) -> Dict[str, Any]:
    meta = _read_json(os.path.join(d, "meta.json"))
    metrics_hist = _read_json(os.path.join(d, "metrics.json"))
    return {
        "meta": meta,
        "params": _read_json(os.path.join(d, "params.json")),
        "metrics": {k: v[-1]["value"] for k, v in metrics_hist.items() if v},
        "metrics_history": metrics_hist,
        "tags": _read_json(os.path.join(d, "tags.json")),
    }


def list_runs(exp_id: str) -> List[Dict[str, Any]]:
    base = os.path.join(experiments_dir(), exp_id)
    out = []
    if not os.path.isdir(base):
        return out
    for r in os.listdir(base):
        d = os.path.join(base, r)
        if os.path.isdir(d) and os.path.exists(os.path.join(d, "meta.json")):
            out.append(read_run(d))
    out.sort(key=lambda r: r["meta"].get("start_time", 0), reverse=True)
    return out


# -------------------------------------------------------------------- registry
def registry_dir() -> str:
    d = os.path.join(get_tracking_uri(), "registry")
    os.makedirs(d, exist_ok=True)
    return d


def model_dir(name: str) -> str:
    return os.path.join(registry_dir(), name)


def get_registered_model(name: str) -> Optional[Dict[str, Any]]:
    meta = _read_json(os.path.join(model_dir(name), "meta.json"))
    return meta or None


def create_registered_model(name: str, description: str = "") -> Dict[str, Any]:
    with _lock:
        existing = get_registered_model(name)
        if existing:
            return existing
        meta = {"name": name, "description": description,
                "creation_timestamp": wallclock(), "latest_version": 0}
        os.makedirs(os.path.join(model_dir(name), "versions"), exist_ok=True)
        _write_json(os.path.join(model_dir(name), "meta.json"), meta)
        return meta


def update_registered_model(name: str, description: str) -> Dict[str, Any]:
    with _lock:
        meta = get_registered_model(name)
        if meta is None:
            raise ValueError(f"registered model {name!r} not found")
        meta["description"] = description
        meta["last_updated_timestamp"] = wallclock()
        _write_json(os.path.join(model_dir(name), "meta.json"), meta)
        return meta


def create_model_version(name: str, source: str, run_id: Optional[str] = None,
                         description: str = "") -> Dict[str, Any]:
    with _lock:
        meta = create_registered_model(name)
        v = int(meta.get("latest_version", 0)) + 1
        meta["latest_version"] = v
        meta["last_updated_timestamp"] = wallclock()
        _write_json(os.path.join(model_dir(name), "meta.json"), meta)
        vd = os.path.join(model_dir(name), "versions", str(v))
        os.makedirs(vd, exist_ok=True)
        if os.path.isdir(source):
            shutil.copytree(source, os.path.join(vd, "model"), dirs_exist_ok=True)
        vmeta = {"name": name, "version": v, "source": source,
                 "run_id": run_id, "current_stage": "None",
                 "status": "READY", "description": description,
                 "creation_timestamp": wallclock()}
        _write_json(os.path.join(vd, "meta.json"), vmeta)
        return vmeta


def get_model_version(name: str, version) -> Optional[Dict[str, Any]]:
    vd = os.path.join(model_dir(name), "versions", str(version))
    meta = _read_json(os.path.join(vd, "meta.json"))
    return meta or None


def list_model_versions(name: str) -> List[Dict[str, Any]]:
    base = os.path.join(model_dir(name), "versions")
    if not os.path.isdir(base):
        return []
    out = []
    for v in sorted(os.listdir(base), key=lambda s: int(s)):
        meta = _read_json(os.path.join(base, v, "meta.json"))
        if meta:
            out.append(meta)
    return out


# Stage-transition listeners: the serving layer subscribes so an endpoint
# bound to `models:/<name>/<stage>` hot-swaps the moment a promotion lands
# instead of polling the registry. Fired OUTSIDE the store lock (listeners
# re-read the store; an endpoint swap may block briefly on an in-flight
# batch) with (name, version, stage, archived_versions).
_stage_listeners: List[Any] = []


def on_stage_transition(fn) -> None:
    """Register `fn(name, version, stage, archived_versions)` to fire after
    every `set_version_stage` commit. Idempotent per function object."""
    with _lock:
        if fn not in _stage_listeners:
            _stage_listeners.append(fn)


def remove_stage_listener(fn) -> None:
    with _lock:
        try:
            _stage_listeners.remove(fn)
        except ValueError:
            pass


def set_version_stage(name: str, version, stage: str,
                      archive_existing_versions: bool = False) -> Dict[str, Any]:
    """Move a version to `stage`. With `archive_existing_versions=True`
    (MLflow's promote semantics) every OTHER version currently holding the
    target stage moves to "Archived" in the same locked commit, so readers
    never observe two Production holders. The target version is validated
    BEFORE anything is archived — a bad version id must not half-apply the
    transition (the pre-fix order archived the incumbents and then raised,
    leaving the stage empty)."""
    archived: List[Any] = []
    with _lock:
        vd = os.path.join(model_dir(name), "versions", str(version))
        meta = _read_json(os.path.join(vd, "meta.json"))
        if not meta:
            raise ValueError(f"model version {name}/{version} not found")
        if archive_existing_versions:
            for other in list_model_versions(name):
                if other["current_stage"] == stage and \
                        str(other["version"]) != str(version):
                    other["current_stage"] = "Archived"
                    other["last_transition_timestamp"] = wallclock()
                    od = os.path.join(model_dir(name), "versions",
                                      str(other["version"]))
                    _write_json(os.path.join(od, "meta.json"), other)
                    archived.append(other["version"])
        meta["current_stage"] = stage
        meta["last_transition_timestamp"] = wallclock()
        _write_json(os.path.join(vd, "meta.json"), meta)
        listeners = list(_stage_listeners)
    for fn in listeners:  # outside the lock: listeners re-read the store
        try:
            fn(name, meta["version"], stage, list(archived))
        except Exception:  # noqa: BLE001 — listener hygiene: the commit
            # already landed; one raising listener (a half-closed
            # endpoint, a torn subscriber) must neither prevent LATER
            # listeners from observing the transition nor bubble into
            # the promoter, leaving the stage move half-observed.
            # Counted (like serve.canary_error) so a dead subscriber is
            # visible in the engine counters instead of silent
            PROFILER.count("tracking.listener_error")
    return meta


def resolve_stage(name: str, stage: str) -> Optional[Dict[str, Any]]:
    """The version meta a stage alias ("Production"/"Staging") currently
    resolves to: the LATEST READY version holding that stage, or None.
    The lookup the serving endpoint performs at bind time and again on
    every transition event."""
    picked = None
    for v in list_model_versions(name):
        if v.get("current_stage") == stage and v.get("status") == "READY":
            picked = v
    return picked


def update_model_version(name: str, version, description: str) -> Dict[str, Any]:
    with _lock:
        vd = os.path.join(model_dir(name), "versions", str(version))
        meta = _read_json(os.path.join(vd, "meta.json"))
        if not meta:
            raise ValueError(f"model version {name}/{version} not found")
        meta["description"] = description
        _write_json(os.path.join(vd, "meta.json"), meta)
        return meta


def delete_model_version(name: str, version) -> None:
    vd = os.path.join(model_dir(name), "versions", str(version))
    shutil.rmtree(vd, ignore_errors=True)


def delete_registered_model(name: str) -> None:
    shutil.rmtree(model_dir(name), ignore_errors=True)
