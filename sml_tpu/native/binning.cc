// Host-side histogram-binning kernel (tree engine hot path).
//
// The reference's tree learners discretize on JVM executors
// (Spark ML findSplits, `SML/ML 06 - Decision Trees.py:98-118`); here the
// per-feature quantile-edge SEARCH over the full column — the expensive
// part of make_bins/bin_with at 1M rows — runs as a threaded C++ kernel.
// Semantics mirror the NumPy path exactly: searchsorted(edges, x, 'left')
// for finite x, bin 0 for any non-finite value (tree_impl.make_bins).
//
// Built on demand by native/build.py (g++ -O3); callers fall back to the
// NumPy implementation when no compiler is available.

#include <cstdint>
#include <cmath>
#include <thread>
#include <vector>

extern "C" {

// One column: edges must be ascending; out[i] = #edges < x strictly left.
static void bin_column(const double* col, int64_t n, const float* edges,
                       int32_t n_edges, int32_t* out) {
    for (int64_t i = 0; i < n; ++i) {
        const double x = col[i];
        if (!std::isfinite(x)) {  // NaN/±inf → lowest bin, as in make_bins
            out[i] = 0;
            continue;
        }
        // branch-light lower_bound over the (tiny) edge array
        int32_t lo = 0, hi = n_edges;
        while (lo < hi) {
            const int32_t mid = (lo + hi) >> 1;
            if (static_cast<double>(edges[mid]) < x) lo = mid + 1;
            else hi = mid;
        }
        out[i] = lo;
    }
}

// Row-major (n, F) matrix; per-feature edge rows of length n_edges[f]
// inside an (F, max_edges) block. Features fan out over threads — columns
// are strided in the input, so each worker first packs its column.
// Templated over the input dtype: the fused feature path stages float32
// blocks, and a whole-matrix f64 conversion would double peak memory.
template <typename T>
static void bin_matrix_impl(const T* X, int64_t n, int32_t F,
                            const float* edges, const int32_t* n_edges,
                            int32_t max_edges, const uint8_t* is_categorical,
                            int32_t* out) {
    int hw = static_cast<int>(std::thread::hardware_concurrency());
    if (hw < 1) hw = 1;
    const int workers = F < hw ? F : hw;
    std::vector<std::thread> pool;
    pool.reserve(workers);
    for (int w = 0; w < workers; ++w) {
        pool.emplace_back([&, w]() {
            std::vector<double> colbuf(n);
            std::vector<int32_t> outbuf(n);
            for (int32_t f = w; f < F; f += workers) {
                if (is_categorical[f]) continue;  // host remaps those
                for (int64_t i = 0; i < n; ++i)
                    colbuf[i] = static_cast<double>(X[i * F + f]);
                bin_column(colbuf.data(), n, edges + (int64_t)f * max_edges,
                           n_edges[f], outbuf.data());
                for (int64_t i = 0; i < n; ++i) out[i * F + f] = outbuf[i];
            }
        });
    }
    for (auto& t : pool) t.join();
}

void bin_matrix(const double* X, int64_t n, int32_t F, const float* edges,
                const int32_t* n_edges, int32_t max_edges,
                const uint8_t* is_categorical, int32_t* out) {
    bin_matrix_impl<double>(X, n, F, edges, n_edges, max_edges,
                            is_categorical, out);
}

void bin_matrix_f32(const float* X, int64_t n, int32_t F, const float* edges,
                    const int32_t* n_edges, int32_t max_edges,
                    const uint8_t* is_categorical, int32_t* out) {
    bin_matrix_impl<float>(X, n, F, edges, n_edges, max_edges,
                           is_categorical, out);
}

}  // extern "C"
