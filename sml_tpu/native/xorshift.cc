// Spark XORShiftRandom draw kernel (draw-for-draw randomSplit parity).
//
// Implements java.util.Random.nextDouble over Spark's XORShift next():
//   next(bits): s ^= s << 21; s ^= s >>> 35; s ^= s << 4;
//               return (int)(s & ((1L << bits) - 1));
//   nextDouble: ((next(26) << 27) + next(27)) * 2^-53
// (org/apache/spark/util/random/XORShiftRandom.scala). The caller passes
// the ALREADY-HASHED seed (XORShiftRandom.hashSeed of seed+partitionIndex
// — see frame/sampling.py, which owns the MurmurHash3 seed scramble over
// Spark's 64-BYTE buffer: ByteBuffer.allocate(java.lang.Long.SIZE) where
// Long.SIZE is 64 bits, i.e. 8 big-endian seed bytes + 56 zeros hashed
// with length-64 finalization).

#include <cstdint>

extern "C" {

void xorshift_fill_doubles(long long hashed_seed, long long n, double* out) {
  uint64_t s = (uint64_t)hashed_seed;
  const double unit = 1.0 / 9007199254740992.0;  // 2^-53
  for (long long i = 0; i < n; ++i) {
    uint64_t x = s ^ (s << 21);
    x ^= (x >> 35);
    x ^= (x << 4);
    s = x;
    uint64_t hi = x & ((1ULL << 26) - 1);
    x = s ^ (s << 21);
    x ^= (x >> 35);
    x ^= (x << 4);
    s = x;
    uint64_t lo = x & ((1ULL << 27) - 1);
    out[i] = (double)((hi << 27) + lo) * unit;
  }
}

}  // extern "C"
