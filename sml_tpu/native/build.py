"""On-demand build + ctypes loader for the native host-side kernels.

The reference's host-side native layer (Spark JVM shuffle machinery, Arrow
C++) is replaced by small C++ kernels compiled here with g++ on first use and
cached under ``native/build/``. Everything is gated: if no compiler is
available the callers fall back to NumPy implementations with identical
semantics.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_HERE = os.path.dirname(os.path.abspath(__file__))
_BUILD_DIR = os.path.join(_HERE, "build")
_LOCK = threading.RLock()
_LIBS: dict = {}
_FAILED: set = set()


def _so_path(name: str) -> str:
    return os.path.join(_BUILD_DIR, f"lib{name}.so")


def load_library(name: str) -> Optional[ctypes.CDLL]:
    """Compile (if needed) and load ``native/<name>.cc``; None on failure."""
    with _LOCK:
        if name in _LIBS:
            return _LIBS[name]
        if name in _FAILED:
            return None
        src = os.path.join(_HERE, f"{name}.cc")
        so = _so_path(name)
        try:
            if (not os.path.exists(so)) or os.path.getmtime(so) < os.path.getmtime(src):
                os.makedirs(_BUILD_DIR, exist_ok=True)
                subprocess.run(
                    ["g++", "-O3", "-march=native", "-shared", "-fPIC",
                     "-std=c++17", src, "-o", so],
                    check=True, capture_output=True, timeout=120)
            lib = ctypes.CDLL(so)
        except Exception:
            _FAILED.add(name)
            return None
        _LIBS[name] = lib
        return lib
