"""Spark-semantics Murmur3 hashing: ctypes fast path + NumPy fallback.

Drives the `hash()` column function (`SML/Includes/Class-Utility-Methods.py:
161-165`), hash-partition shuffles, and dropDuplicates partition assignment.
Multi-column hashing chains: running hash starts at seed 42 and each column's
hash uses the previous as its seed; nulls leave the running hash unchanged.
"""

from __future__ import annotations

import ctypes
from typing import Iterable, Optional

import numpy as np
import pandas as pd

from .build import load_library

SEED = 42
_M32 = np.uint32(0xFFFFFFFF)


def _modular(fn):
    """uint32 arithmetic here is intentionally modular — silence overflow
    warnings locally without touching global numpy error state."""
    def wrapped(*args, **kwargs):
        with np.errstate(over="ignore"):
            return fn(*args, **kwargs)
    wrapped.__name__ = fn.__name__
    return wrapped


# ---------- vectorized NumPy implementation (fallback + reference) ----------

def _rotl32(x: np.ndarray, r: int) -> np.ndarray:
    return ((x << np.uint32(r)) | (x >> np.uint32(32 - r))).astype(np.uint32)


def _mix_k1(k1: np.ndarray) -> np.ndarray:
    k1 = (k1 * np.uint32(0xCC9E2D51)).astype(np.uint32)
    k1 = _rotl32(k1, 15)
    return (k1 * np.uint32(0x1B873593)).astype(np.uint32)


def _mix_h1(h1: np.ndarray, k1: np.ndarray) -> np.ndarray:
    h1 = (h1 ^ k1).astype(np.uint32)
    h1 = _rotl32(h1, 13)
    return (h1 * np.uint32(5) + np.uint32(0xE6546B64)).astype(np.uint32)


def _fmix(h1: np.ndarray, length) -> np.ndarray:
    h1 = (h1 ^ np.uint32(length)).astype(np.uint32)
    h1 ^= h1 >> np.uint32(16)
    h1 = (h1 * np.uint32(0x85EBCA6B)).astype(np.uint32)
    h1 ^= h1 >> np.uint32(13)
    h1 = (h1 * np.uint32(0xC2B2AE35)).astype(np.uint32)
    h1 ^= h1 >> np.uint32(16)
    return h1


def _np_hash_int(vals: np.ndarray, seeds: np.ndarray) -> np.ndarray:
    k1 = _mix_k1(vals.astype(np.int32).view(np.uint32))
    h1 = _mix_h1(seeds.view(np.uint32), k1)
    return _fmix(h1, 4).view(np.int32)


def _np_hash_long(vals: np.ndarray, seeds: np.ndarray) -> np.ndarray:
    v = vals.astype(np.int64).view(np.uint64)
    low = (v & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    high = (v >> np.uint64(32)).astype(np.uint32)
    h1 = _mix_h1(seeds.view(np.uint32), _mix_k1(low))
    h1 = _mix_h1(h1, _mix_k1(high))
    return _fmix(h1, 8).view(np.int32)


def _np_hash_double(vals: np.ndarray, seeds: np.ndarray) -> np.ndarray:
    d = vals.astype(np.float64).copy()
    d[d == 0.0] = 0.0  # normalize -0.0
    return _np_hash_long(d.view(np.int64), seeds)


@_modular
def _py_hash_bytes(data: bytes, seed: int) -> int:
    h1 = np.uint32(seed & 0xFFFFFFFF)
    n = len(data)
    aligned = n - (n & 3)
    for i in range(0, aligned, 4):
        word = np.uint32(int.from_bytes(data[i:i + 4], "little"))
        h1 = _mix_h1(h1, _mix_k1(word))
    for i in range(aligned, n):
        b = data[i]
        if b >= 128:
            b -= 256  # sign-extend
        h1 = _mix_h1(h1, _mix_k1(np.uint32(b & 0xFFFFFFFF)))
    return int(_fmix(h1, n).view(np.int32))


# ------------------------------- public API --------------------------------

def _lib():
    return load_library("murmur3")


@_modular
def hash_column(values, seeds: np.ndarray) -> np.ndarray:
    """Chain one column into running int32 hashes (`seeds`), Spark-style."""
    n = len(seeds)
    out = seeds.astype(np.int32).copy()
    s = pd.Series(values) if not isinstance(values, pd.Series) else values
    nulls = s.isna().to_numpy()

    kind = s.dtype.kind
    if kind in "iu":
        vals = s.to_numpy()
        # int32-or-smaller hashes as int; larger as long
        if s.dtype.itemsize <= 4:
            res = _np_hash_int(vals.astype(np.int32), out)
        else:
            res = _np_hash_long(vals.astype(np.int64), out)
        out[~nulls] = res[~nulls]
        return out
    if kind == "b":
        vals = s.fillna(False).to_numpy().astype(np.int32)
        res = _np_hash_int(vals, out)
        out[~nulls] = res[~nulls]
        return out
    if kind == "f":
        vals = s.fillna(0.0).to_numpy().astype(np.float64)
        if s.dtype.itemsize <= 4:
            v32 = vals.astype(np.float32)
            v32[v32 == 0.0] = 0.0
            res = _np_hash_int(v32.view(np.int32), out)
        else:
            res = _np_hash_double(vals, out)
        out[~nulls] = res[~nulls]
        return out

    # strings / objects → utf8 bytes
    lib = _lib()
    if lib is not None:
        bufs = []
        offsets = np.zeros(n + 1, dtype=np.int64)
        for i, v in enumerate(s):
            b = b"" if (nulls[i] or v is None) else str(v).encode("utf-8")
            bufs.append(b)
            offsets[i + 1] = offsets[i] + len(b)
        blob = b"".join(bufs)
        null_arr = nulls.astype(np.uint8)
        blob_buf = (ctypes.c_uint8 * max(len(blob), 1)).from_buffer_copy(blob or b"\x00")
        lib.mm3_hash_bytes_arr(
            blob_buf,
            offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            null_arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.c_int64(n),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        )
        return out
    for i, v in enumerate(s):
        if nulls[i] or v is None:
            continue
        out[i] = _py_hash_bytes(str(v).encode("utf-8"), int(out[i]))
    return out


@_modular
def hash_columns(columns: Iterable, n: Optional[int] = None, seed: int = SEED) -> np.ndarray:
    """Hash rows across columns with seed chaining (the `hash(*cols)` op)."""
    cols = list(columns)
    if n is None:
        n = len(cols[0])
    out = np.full(n, seed, dtype=np.int32)
    for c in cols:
        out = hash_column(c, out)
    return out


def hash_partition_ids(hashes: np.ndarray, num_parts: int) -> np.ndarray:
    """pmod(hash, num_parts) — shuffle placement."""
    m = hashes.astype(np.int64) % num_parts
    return m.astype(np.int32)


@_modular
def hash_scalar(value, seed: int = SEED) -> int:
    """Hash one Python scalar (harness `toHash` equivalent)."""
    arr = hash_columns([pd.Series([value])], n=1, seed=seed)
    return int(arr[0])
