// Murmur3_x86_32 hash kernels, Spark-semantics-compatible.
//
// The reference's hash-validation harness and `hash()` column function ride
// on Spark's Murmur3_x86_32 (seed 42): see
// `SML/Includes/Class-Utility-Methods.py:161-165` (toHash via spark hash())
// and hash-partitioned shuffles throughout L1. This is a from-scratch C++
// implementation of the same *algorithmic contract* (int/long/double/bytes
// mixing, per-trailing-byte tail, multi-column seed chaining) so hashes and
// hash-partition placement match the reference's observable behavior.
//
// Exposed C ABI (ctypes): vectorized hashers over contiguous arrays plus a
// bytes hasher over an offsets/len layout (Arrow string columns).

#include <cstdint>
#include <cstring>

static inline uint32_t rotl32(uint32_t x, int8_t r) {
  return (x << r) | (x >> (32 - r));
}

static inline uint32_t mix_k1(uint32_t k1) {
  k1 *= 0xcc9e2d51u;
  k1 = rotl32(k1, 15);
  k1 *= 0x1b873593u;
  return k1;
}

static inline uint32_t mix_h1(uint32_t h1, uint32_t k1) {
  h1 ^= k1;
  h1 = rotl32(h1, 13);
  h1 = h1 * 5 + 0xe6546b64u;
  return h1;
}

static inline uint32_t fmix(uint32_t h1, uint32_t length) {
  h1 ^= length;
  h1 ^= h1 >> 16;
  h1 *= 0x85ebca6bu;
  h1 ^= h1 >> 13;
  h1 *= 0xc2b2ae35u;
  h1 ^= h1 >> 16;
  return h1;
}

static inline int32_t hash_int(int32_t input, int32_t seed) {
  uint32_t k1 = mix_k1((uint32_t)input);
  uint32_t h1 = mix_h1((uint32_t)seed, k1);
  return (int32_t)fmix(h1, 4);
}

static inline int32_t hash_long(int64_t input, int32_t seed) {
  uint32_t low = (uint32_t)input;
  uint32_t high = (uint32_t)(((uint64_t)input) >> 32);
  uint32_t k1 = mix_k1(low);
  uint32_t h1 = mix_h1((uint32_t)seed, k1);
  k1 = mix_k1(high);
  h1 = mix_h1(h1, k1);
  return (int32_t)fmix(h1, 8);
}

static inline int32_t hash_bytes(const uint8_t* data, int64_t len, int32_t seed) {
  uint32_t h1 = (uint32_t)seed;
  int64_t aligned = len - (len & 3);
  for (int64_t i = 0; i < aligned; i += 4) {
    uint32_t half_word;
    std::memcpy(&half_word, data + i, 4);  // little-endian load
    h1 = mix_h1(h1, mix_k1(half_word));
  }
  // Trailing bytes mixed one at a time (sign-extended), matching the
  // reference stack's observable tail behavior.
  for (int64_t i = aligned; i < len; i++) {
    int32_t b = (int8_t)data[i];
    h1 = mix_h1(h1, mix_k1((uint32_t)b));
  }
  return (int32_t)fmix(h1, (uint32_t)len);
}

extern "C" {

// Each hasher chains: out[i] = hash(value[i], seed=out[i]); callers initialize
// out[] to 42 (or previous column's hashes) to get multi-column chaining.
// null_mask may be nullptr; a null leaves the running hash unchanged.

void mm3_hash_i32(const int32_t* vals, const uint8_t* null_mask, int64_t n,
                  int32_t* inout) {
  for (int64_t i = 0; i < n; i++) {
    if (null_mask && null_mask[i]) continue;
    inout[i] = hash_int(vals[i], inout[i]);
  }
}

void mm3_hash_i64(const int64_t* vals, const uint8_t* null_mask, int64_t n,
                  int32_t* inout) {
  for (int64_t i = 0; i < n; i++) {
    if (null_mask && null_mask[i]) continue;
    inout[i] = hash_long(vals[i], inout[i]);
  }
}

void mm3_hash_f64(const double* vals, const uint8_t* null_mask, int64_t n,
                  int32_t* inout) {
  for (int64_t i = 0; i < n; i++) {
    if (null_mask && null_mask[i]) continue;
    double d = vals[i];
    if (d == 0.0) d = 0.0;  // normalize -0.0
    int64_t bits;
    std::memcpy(&bits, &d, 8);
    inout[i] = hash_long(bits, inout[i]);
  }
}

// Strings in Arrow layout: concatenated utf8 buffer + int64 offsets[n+1].
void mm3_hash_bytes_arr(const uint8_t* buf, const int64_t* offsets,
                        const uint8_t* null_mask, int64_t n, int32_t* inout) {
  for (int64_t i = 0; i < n; i++) {
    if (null_mask && null_mask[i]) continue;
    int64_t start = offsets[i];
    int64_t len = offsets[i + 1] - start;
    inout[i] = hash_bytes(buf + start, len, inout[i]);
  }
}

int32_t mm3_hash_one_bytes(const uint8_t* data, int64_t len, int32_t seed) {
  return hash_bytes(data, len, seed);
}

int32_t mm3_hash_one_i64(int64_t v, int32_t seed) { return hash_long(v, seed); }
int32_t mm3_hash_one_i32(int32_t v, int32_t seed) { return hash_int(v, seed); }

// Hash-partition assignment: pmod(hash, num_partitions) — the shuffle
// placement rule (non-negative modulo).
void mm3_partition(const int32_t* hashes, int64_t n, int32_t num_parts,
                   int32_t* out) {
  for (int64_t i = 0; i < n; i++) {
    int32_t m = hashes[i] % num_parts;
    out[i] = m < 0 ? m + num_parts : m;
  }
}

}  // extern "C"
