"""Pallas fused batched tree-traversal kernel for the serving hot path.

PR 9 fused the *fit* hot path; scoring still rode the XLA ensemble
traversal in `ml/inference.py` (`_forest_margin`): per level, the
per-node one-hot, the feature-select one-hot, and the `(T, rows)`
per-tree margin stack are all separate HLOs whose intermediates
round-trip HBM between levels. This kernel fuses the whole descent
ON-CHIP — the accelerator-side batched traversal of "Booster: An
Accelerator for Gradient Boosting Decision Trees" (arXiv:2011.02022),
with the batched node layout of "GPU-acceleration for Large-scale Tree
Boosting" (arXiv:1706.08359) — for a block of rows at a time:

- The ensemble rides as a level-order **SoA node table**: one lane per
  node attribute — feature id (`sf`, −1 at leaves), split bin (`sb`),
  leaf/node value (`lv`) — stacked `(T, n_nodes)` per tree, exactly the
  heap layout `_EnsembleSpec.stacked()` already produces (children of
  node *i* at 2i+1 / 2i+2, so descent needs no child-pointer gathers).
  The tables are KB-scale and stay resident in VMEM for every grid step.
- Rows stream HBM→VMEM in blocks; the **depth-unrolled predicated
  descent** (the per-level node one-hot, the feature-select against the
  compact bin matrix, the child step) and the per-tree **leaf sums
  accumulate in-register** — only the final `(block,)` weighted margin
  leaves the kernel. The per-level one-hots and the `(T, rows)` margin
  stack never touch HBM.

The kernel body is op-for-op `ml/inference._forest_margin`'s math (same
one-hot where-sums — gather-free and exact in f32, see that docstring
for why — same select, same reductions). The traversal has NO cross-row
operation, so row blocking cannot change any output bit: interpret mode
(non-TPU backends, single block) and compiled mode (row-block grid) are
both BIT-IDENTICAL to the XLA path, which tests/test_traverse_kernel.py
asserts across DT/RF/xgboost, uint8/uint16 bin matrices, NaN rows, and
the logistic finalize.

Every `pl.pallas_call` in the package must live in `sml_tpu/native/`,
and every *invocation* of `forest_traverse` must come from the
`score_block` dispatch glue (`ml/inference.py`) — graftlint's
`dispatch-bypass` rule flags both, so the `infer.kernel.*` counters and
the fallback ladder stay authoritative.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from ..utils.profiler import PROFILER
from .hist_kernel import _tpu_compiler_params, available  # noqa: F401

#: compiled-path VMEM budget per grid step (the per-level one-hot tiles
#: plus the resident node tables; ~16 MB VMEM/core shared with operands)
TRAVERSE_VMEM_BUDGET = 8 << 20


def traverse_vmem_bytes(block_rows: int, n_trees: int, n_nodes: int,
                        n_feat: int) -> int:
    """Per-grid-step VMEM estimate of the compiled traversal: the f32
    per-level node one-hot and leaf one-hot tiles (`block·n_nodes` each),
    the feature-select tile (`block·F`), the in-register per-tree margin
    stack (`T·block`), the widened bin tile (`block·F`), and the resident
    SoA node tables (three `(T, n_nodes)` lanes). The guard in
    `ml/inference.py` demotes oversized (block_rows × trees) specs with
    this estimate instead of failing to lower mid-trace (block_rows=0 =
    the block-independent node-table term alone)."""
    blk = max(int(block_rows), 0)
    return int(4 * blk * (2 * n_nodes + 2 * n_feat + n_trees)
               + 12 * n_trees * n_nodes)


def max_block_rows(n_trees: int, n_nodes: int, n_feat: int) -> int:
    """Largest row block whose per-grid-step estimate fits
    `TRAVERSE_VMEM_BUDGET`, or 0 when even a minimal 8-row block cannot
    (the resident node tables alone bust the budget — the spec must
    demote to XLA). THE single source of the guard's arithmetic: the
    resolver in `ml/inference.py` clamps/demotes through this, so the
    budget math cannot drift from the `traverse_vmem_bytes` estimate."""
    fixed = traverse_vmem_bytes(0, n_trees, n_nodes, n_feat)
    per_row = traverse_vmem_bytes(1, n_trees, n_nodes, n_feat) - fixed
    blk = (TRAVERSE_VMEM_BUDGET - fixed) // max(per_row, 1)
    return int(blk) if blk >= 8 else 0


def _block_plan(n: int, interpret: bool,
                block_rows: Optional[int]) -> Tuple[int, int]:
    """(grid steps, rows per block). Interpret mode uses ONE block (no
    VMEM to bound; fewer traced ops). Compiled mode picks the largest
    divisor of `n` at or under the target so every grid step sees a full
    block — rows are bucket-padded by staging, so divisors are dense.
    Unlike the fit kernel's plan this never changes results: the
    traversal has no cross-row reduction, so blocking is pure VMEM
    scheduling.

    `block_rows` is resolved HOST-side (`inference.resolve_infer_kernel`
    reads `sml.infer.kernelBlockRows` once per program build, and the
    value rides the inference program cache key); this function runs at
    TRACE time and must never consult live conf — a read here would be
    burned into the executable and silently diverge from the keyed
    value. None/0 means no blocking: one full block."""
    if interpret or not block_rows:
        return 1, n
    target = max(1, min(int(block_rows), n))
    k = -(-n // target)
    while n % k:
        k += 1
    return k, n // k


def forest_traverse(binned, sf, sb, lv, weights, *, depth: int,
                    interpret: bool = False,
                    block_rows: Optional[int] = None):
    """Weighted stacked-ensemble margin for a per-chip row block, fused
    in one kernel launch: `(rows,)` f32 from the compact bin matrix.

    `binned` is the bin-cache operand as staged (uint8/uint16 — or int32
    on wide-bin models); `sf`/`sb`/`lv` are the level-order SoA node
    tables (`(T, n_nodes)`, `_EnsembleSpec.stacked()` layout) and
    `weights` the `(T,)` per-tree weights. Equivalent XLA-path
    computation, which the kernel body reproduces op-for-op per block:
    `ml/inference._forest_margin(binned, sf, sb, lv, weights, depth)`.

    The mask multiply, the base offset, and every psum of the fused
    eval program stay OUTSIDE the kernel in the `ml/inference.py` glue,
    so the kernel swap cannot change semantics — only where the per-level
    intermediates live."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    n, F = binned.shape
    T, n_nodes = sf.shape
    nblk, blk = _block_plan(n, interpret, block_rows)

    def kernel(b_ref, sf_ref, sb_ref, lv_ref, w_ref, out_ref):
        # the XLA path's exact ops on one row block (_forest_margin):
        # one-hot masked where-SUMs, exact in f32 — no gathers, no MXU
        # bf16 operand truncation
        binned_f = b_ref[...].astype(jnp.float32)
        fio = jnp.arange(F, dtype=jnp.float32)

        def one_tree(f, s, v):
            fpos = jnp.maximum(f, 0).astype(jnp.float32)
            internal = f >= 0
            s_f = s.astype(jnp.float32)
            node = jnp.zeros((blk,), dtype=jnp.int32)
            for lvl in range(depth):
                width = min(2 ** (lvl + 1) - 1, n_nodes)
                iota = jnp.arange(width, dtype=jnp.int32)
                oh = node[:, None] == iota[None, :]
                fa = jnp.sum(jnp.where(oh, fpos[None, :width], 0.0), axis=1)
                ba = jnp.sum(jnp.where(oh, s_f[None, :width], 0.0), axis=1)
                isin = jnp.any(oh & internal[None, :width], axis=1)
                xbin = jnp.sum(jnp.where(fio[None, :] == fa[:, None],
                                         binned_f, 0.0), axis=1)
                child = 2 * node + 1 + (xbin > ba).astype(jnp.int32)
                node = jnp.where(isin, child, node)
            leaf_oh = (node[:, None]
                       == jnp.arange(n_nodes, dtype=jnp.int32)[None, :])
            return jnp.sum(jnp.where(leaf_oh,
                                     v.astype(jnp.float32)[None, :], 0.0),
                           axis=1)

        per_tree = jax.vmap(one_tree)(sf_ref[...], sb_ref[...], lv_ref[...])
        out_ref[...] = jnp.sum(
            w_ref[...].astype(jnp.float32)[:, None] * per_tree, axis=0)

    kwargs = {}
    if not interpret:
        params = _tpu_compiler_params()
        if params is not None:
            kwargs["compiler_params"] = params
    PROFILER.count("kernel.pallas_launch")
    if interpret:
        PROFILER.count("kernel.interpret")
    return pl.pallas_call(
        kernel,
        grid=(nblk,),
        in_specs=[
            pl.BlockSpec((blk, F), lambda i: (i, 0)),
            pl.BlockSpec((T, n_nodes), lambda i: (0, 0)),
            pl.BlockSpec((T, n_nodes), lambda i: (0, 0)),
            pl.BlockSpec((T, n_nodes), lambda i: (0, 0)),
            pl.BlockSpec((T,), lambda i: (0,)),
        ],
        out_specs=pl.BlockSpec((blk,), lambda i: (i,)),
        out_shape=jax.ShapeDtypeStruct((n,), jnp.float32),
        interpret=interpret,
        **kwargs,
    )(binned, sf, sb, lv, weights)
