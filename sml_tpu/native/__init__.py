from .build import load_library
from .hashing import hash_columns, hash_partition_ids, hash_scalar

__all__ = ["load_library", "hash_columns", "hash_partition_ids", "hash_scalar"]
