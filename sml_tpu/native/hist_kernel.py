"""Pallas fused bin-accumulate + split-scan kernels for the tree hot path.

The XLA tree-build path (`ml/tree_impl._make_tree_builder`) emits the
level-wise histogram build as separate HLOs: a one-hot expansion of the
whole bin matrix into an (n, F*B) operand (`B1t`, materialized in HBM and
kept pre-transposed for the entire fit), a second (n, width*3) one-hot ×
stats product (`ns`), a dot, then a reshape/transpose/cumsum/argmax chain
— every level round-trips those intermediates through HBM. The custom
kernels here fuse each stage ON-CHIP (the approach of "GPU-acceleration
for Large-scale Tree Boosting", arXiv:1706.08359, and "Booster",
arXiv:2011.02022, ported to the TPU memory hierarchy):

- `hist_accumulate`: per-chip partial histogram straight FROM THE COMPACT
  BIN CACHE operand (uint8/uint16). Row blocks stream HBM→VMEM; the
  one-hot bin tile and the node×stats tile exist only in VMEM for the
  lifetime of one block's MXU contraction, and grid steps accumulate into
  the one resident (F*B, width*3) output block — the O(n×F×B) one-hot and
  the O(n×width×3) `ns` never touch HBM, and the fit-long `B1t` resident
  disappears entirely.
- `split_scan`: the per-level gain scan (cumsum over bins, XGBoost gain,
  min-instances / last-bin / feature-subspace masks, per-node argmax) on
  the post-psum (F, B, width, 3) histogram, in registers, emitting only a
  (6, width) best-split pack.

The psum stays OUTSIDE the kernels: per-chip partials are unchanged, so
the kernels compose with `shard_map` + `collectives.psum` (and the
histogram-subtraction halving, which operates on the post-psum histogram
between the two kernels) exactly like the XLA path.

INTERPRET-MODE CONTRACT (tier-1): on non-TPU backends the kernels run
under `pallas_call(interpret=True)` with a SINGLE row block, so the traced
kernel body is op-for-op the XLA path's math (same one-hot, same
`dot_general` dimension numbers, same cumsum/argmax) evaluated by the same
backend — fit outputs are BIT-IDENTICAL to the XLA path, which
tests/test_hist_kernel.py asserts. On hardware the row-block grid bounds
VMEM instead; cross-block f32 accumulation order then differs from one
big dot by float associativity only (see docs/KERNELS.md).

Every `pl.pallas_call` in the package must live in `sml_tpu/native/` —
graftlint's `dispatch-bypass` rule flags raw kernel launches anywhere
else, the same way it fences bare `jax.jit`.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np

from ..utils.profiler import PROFILER

_avail: Dict[str, bool] = {}


def available() -> bool:
    """Whether the Pallas toolchain can run a kernel in this process —
    probed ONCE with a tiny interpret-mode launch (import and
    interpret-machinery failures land here, so callers get a clean
    yes/no instead of a mid-trace exception). This does NOT prove every
    SHAPE lowers on real hardware — per-spec VMEM limits are guarded
    statically by `tree_impl._kernel_for` instead. The fallback ladder
    (`tree_impl._kernel_choice`) turns a False into the XLA path plus a
    `kernel.fallback` count."""
    hit = _avail.get("ok")
    if hit is None:
        try:
            import jax
            import jax.numpy as jnp
            from jax.experimental import pallas as pl

            def _probe(x_ref, o_ref):
                o_ref[...] = x_ref[...] + 1.0

            out = pl.pallas_call(
                _probe,
                out_shape=jax.ShapeDtypeStruct((1, 2), jnp.float32),
                interpret=True,
            )(jnp.ones((1, 2), jnp.float32))
            hit = bool(np.asarray(out)[0, 0] == 2.0)
        except Exception:
            hit = False
        _avail["ok"] = hit
    return hit


def _block_plan(n: int, interpret: bool,
                block_rows: Optional[int]) -> Tuple[int, int]:
    """(grid steps, rows per block) for the accumulate kernel.

    Interpret mode always uses ONE block: the whole per-chip row set goes
    through a single dot with the XLA path's exact dimension numbers —
    the bit-parity contract tier-1 asserts. Compiled mode picks the
    largest divisor of `n` at or under `block_rows` so every grid step
    sees a full block (no partial-block masking; rows are already
    bucket-padded by staging, so divisors are dense).

    `block_rows` is resolved HOST-side (`tree_impl._kernel_block_rows`
    reads `sml.tree.kernelBlockRows` once per program build, and the
    value rides every tree program cache key and the prewarm manifest);
    this function runs at TRACE time and must never consult live conf —
    a read here would be burned into the executable and silently diverge
    from the keyed value. None/0 means no blocking: one full block."""
    if interpret or not block_rows:
        return 1, n
    target = max(1, min(int(block_rows), n))
    k = -(-n // target)
    while n % k:
        k += 1
    return k, n // k


def _tpu_compiler_params():
    """Sequential-grid compiler params for the accumulating kernel (grid
    steps revisit the same output block, so the grid must not be declared
    parallel). Version-tolerant: absent/renamed param classes degrade to
    None (the compiler default) rather than failing the launch."""
    try:
        from jax.experimental.pallas import tpu as pltpu
        cls = getattr(pltpu, "CompilerParams", None) \
            or getattr(pltpu, "TPUCompilerParams", None)
        if cls is None:
            return None
        return cls(dimension_semantics=("arbitrary",))
    except Exception:
        return None


def hist_accumulate(binned, lid, grad, hess, weight, *, n_bins: int,
                    n_slots: int, hist_dtype=None, interpret: bool = False,
                    block_rows: Optional[int] = None):
    """Per-chip partial histogram for one tree level, fused in one kernel:
    (F*n_bins, n_slots*3) f32 from the COMPACT bin matrix.

    `binned` is the bin-cache operand as staged (uint8/uint16 — or int32
    on the single-tree path); `lid` is each row's one-hot slot at this
    level (the left-child slot under histogram subtraction), `weight` the
    effective per-row weight (0 excludes the row). Equivalent XLA-path
    computation, which the kernel body reproduces op-for-op per block:

        B1t  = one_hot(binned, B).reshape(n, F*B).T      # HBM resident
        ns   = (one_hot(lid, S) * (w>0)) ⊗ [g*w, h*w, w]  # HBM transient
        hist = B1t @ ns

    Here both one-hots are VMEM tiles of one row block; grid steps
    accumulate into the single resident output block."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    if hist_dtype is None:
        hist_dtype = jnp.float32
    n, F = binned.shape
    B, S = int(n_bins), int(n_slots)
    nblk, blk = _block_plan(n, interpret, block_rows)

    def kernel(b_ref, lid_ref, g_ref, h_ref, w_ref, out_ref):
        @pl.when(pl.program_id(0) == 0)
        def _init():
            out_ref[...] = jnp.zeros_like(out_ref)

        b = b_ref[...]
        w = w_ref[...]
        # the XLA path's exact ops on one row block: exact 0/1 one-hots
        # (bf16-safe on TPU), f32 MXU accumulation
        b1t = jax.nn.one_hot(b.astype(jnp.int32), B, dtype=hist_dtype) \
            .reshape(b.shape[0], F * B).T
        node1hot = jax.nn.one_hot(lid_ref[...], S, dtype=hist_dtype) \
            * (w > 0)[:, None].astype(hist_dtype)
        stats = jnp.stack([g_ref[...] * w, h_ref[...] * w, w], axis=1)
        ns = (node1hot[:, :, None]
              * stats[:, None, :].astype(hist_dtype)).reshape(b.shape[0],
                                                              S * 3)
        out_ref[...] += jax.lax.dot_general(
            b1t, ns, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    kwargs = {}
    if not interpret:
        params = _tpu_compiler_params()
        if params is not None:
            kwargs["compiler_params"] = params
    PROFILER.count("kernel.pallas_launch")
    if interpret:
        PROFILER.count("kernel.interpret")
    return pl.pallas_call(
        kernel,
        grid=(nblk,),
        in_specs=[
            pl.BlockSpec((blk, F), lambda i: (i, 0)),
            pl.BlockSpec((blk,), lambda i: (i,)),
            pl.BlockSpec((blk,), lambda i: (i,)),
            pl.BlockSpec((blk,), lambda i: (i,)),
            pl.BlockSpec((blk,), lambda i: (i,)),
        ],
        out_specs=pl.BlockSpec((F * B, S * 3), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((F * B, S * 3), jnp.float32),
        interpret=interpret,
        **kwargs,
    )(binned, lid, grad, hess, weight)


def split_scan(hist, feat_mask, min_inst, *, reg_lambda: float,
               gamma: float, interpret: bool = False):
    """Fused per-level gain scan on the POST-PSUM histogram: cumulative
    bin sums, the second-order XGBoost gain, the min-instances / last-bin
    / feature-subspace candidate masks, and the per-node argmax — all in
    registers, emitting a (6, width) f32 pack:

        [best_feature, best_bin, best_gain - gamma, G, H, W]

    `hist` is (F, B, width, 3) f32; `feat_mask` is the (width, F) 0/1
    RF-subspace mask computed by the caller (the draw uses the engine's
    jax.random stream, which must stay outside the kernel so the pallas
    and XLA paths consume identical randomness); `min_inst` is a (1, 1)
    f32 scalar operand (traced per-trial under grid fusion). The body is
    op-for-op tree_impl's XLA scan, so interpret mode is bit-identical."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    F, B, width = hist.shape[0], hist.shape[1], hist.shape[2]
    lam = float(reg_lambda)
    gam = float(gamma)

    def kernel(h_ref, fm_ref, mi_ref, out_ref):
        h = h_ref[...]
        hG = jnp.transpose(h[..., 0], (2, 0, 1))              # (width,F,B)
        hH = jnp.transpose(h[..., 1], (2, 0, 1))
        hW = jnp.transpose(h[..., 2], (2, 0, 1))
        GL = jnp.cumsum(hG, axis=2)
        HL = jnp.cumsum(hH, axis=2)
        WL = jnp.cumsum(hW, axis=2)
        G = GL[:, :, -1:]
        H = HL[:, :, -1:]
        W = WL[:, :, -1:]
        score = (GL ** 2 / (HL + lam + 1e-12)
                 + (G - GL) ** 2 / (H - HL + lam + 1e-12)
                 - G ** 2 / (H + lam + 1e-12))
        mi = mi_ref[0, 0]
        ok = (WL >= mi) & ((W - WL) >= mi)
        # 2-D+ iota (TPU requires it); values identical to arange(B)<B-1
        ok = ok & (jax.lax.broadcasted_iota(jnp.int32, (1, 1, B), 2)
                   < B - 1)
        ok = ok & (fm_ref[...] > 0)[:, :, None]
        sc = jnp.where(ok, score, -jnp.inf)
        flat_best = jnp.argmax(sc.reshape(width, F * B), axis=1)
        best_f = (flat_best // B).astype(jnp.int32)
        best_b = (flat_best % B).astype(jnp.int32)
        best_gain = 0.5 * jnp.take_along_axis(
            sc.reshape(width, F * B), flat_best[:, None], axis=1)[:, 0] \
            - gam
        out_ref[...] = jnp.stack([
            best_f.astype(jnp.float32), best_b.astype(jnp.float32),
            best_gain, G[:, 0, 0], H[:, 0, 0], W[:, 0, 0]])

    PROFILER.count("kernel.pallas_launch")
    if interpret:
        PROFILER.count("kernel.interpret")
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((6, width), jnp.float32),
        interpret=interpret,
    )(hist, feat_mask, min_inst)
