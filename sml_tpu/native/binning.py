"""ctypes wrapper for the C++ binning kernel, with NumPy parity fallback.

`bin_continuous(X, edges_list, categorical)` returns the (n, F) int32 bin
matrix for the CONTINUOUS features (categorical slots are left 0 for the
caller's remap pass) — semantics identical to the NumPy expression

    np.searchsorted(edges_f, X[:, f], side="left")  # then non-finite → 0

used by `ml.tree_impl.make_bins` / `bin_with`; a parity test pins the two
implementations against each other.
"""

from __future__ import annotations

import ctypes
from typing import Dict, List, Optional

import numpy as np

from .build import load_library

_sig_ready = False


def _lib() -> Optional[ctypes.CDLL]:
    global _sig_ready
    lib = load_library("binning")
    if lib is not None and not _sig_ready:
        tail = [ctypes.c_int64, ctypes.c_int32,
                ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_int32),
                ctypes.c_int32, ctypes.POINTER(ctypes.c_uint8),
                ctypes.POINTER(ctypes.c_int32)]
        lib.bin_matrix.argtypes = [ctypes.POINTER(ctypes.c_double)] + tail
        lib.bin_matrix.restype = None
        lib.bin_matrix_f32.argtypes = [ctypes.POINTER(ctypes.c_float)] + tail
        lib.bin_matrix_f32.restype = None
        _sig_ready = True
    return lib


def bin_continuous(X: np.ndarray, edges_list: List[np.ndarray],
                   categorical: Dict[int, int]) -> Optional[np.ndarray]:
    """(n, F) int32 bins for continuous slots via the native kernel, or
    None when the kernel is unavailable (caller uses the NumPy path)."""
    n, F = X.shape
    lib = _lib()
    if lib is None or n == 0 or F == 0:
        return None
    # keep the input dtype: an f32 block (the fused feature path's layout)
    # binned through an f64 copy would double peak memory at 1M+ rows
    if X.dtype == np.float32:
        Xc = np.ascontiguousarray(X)
        fn, ptr_t = lib.bin_matrix_f32, ctypes.c_float
    else:
        Xc = np.ascontiguousarray(X, dtype=np.float64)
        fn, ptr_t = lib.bin_matrix, ctypes.c_double
    max_edges = max((len(e) for e in edges_list), default=0)
    if max_edges == 0:
        return np.zeros((n, F), dtype=np.int32)
    edges = np.zeros((F, max_edges), dtype=np.float32)
    n_edges = np.zeros(F, dtype=np.int32)
    for f, e in enumerate(edges_list):
        edges[f, :len(e)] = e
        n_edges[f] = len(e)
    is_cat = np.zeros(F, dtype=np.uint8)
    for f in categorical:
        if 0 <= int(f) < F:
            is_cat[int(f)] = 1
    out = np.zeros((n, F), dtype=np.int32)
    fn(
        Xc.ctypes.data_as(ctypes.POINTER(ptr_t)),
        ctypes.c_int64(n), ctypes.c_int32(F),
        edges.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        n_edges.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        ctypes.c_int32(max_edges),
        is_cat.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
    return out
