from . import collectives, mesh
from .mesh import (DATA_AXIS, MODEL_AXIS, build_mesh, data_sharding, get_mesh,
                   submeshes, use_mesh_local,
                   mesh_device_count, pad_rows, replicated, row_mask,
                   set_mesh, shard_rows, use_mesh)

__all__ = [
    "collectives", "mesh", "DATA_AXIS", "MODEL_AXIS", "build_mesh",
    "data_sharding", "get_mesh", "mesh_device_count", "pad_rows",
    "replicated", "row_mask", "set_mesh", "shard_rows", "use_mesh",
    "submeshes", "use_mesh_local",
]
