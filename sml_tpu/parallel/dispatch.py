"""Latency-calibrated device dispatch: host mesh vs. accelerator mesh.

The reference runs every job on the cluster because Spark's scheduler is
where its parallelism lives; the cost of a round trip to an executor is
milliseconds. A TPU reached through a tunnel is different: one dispatch +
device→host read costs ~100-300ms of FIXED latency regardless of the math
(measured here at import of the first program), so a 60k-row Gram pass that
takes 2ms of host BLAS loses by two orders of magnitude if it rides the
chip. Spark has the same concept — `spark.sql.adaptive` and broadcast-join
thresholds pick an execution strategy from measured sizes — and this module
is that scheduler for the mesh runtime (VERDICT r2 weak #3: "no
measured-latency calibration").

Policy: every distributed program in this package is a `shard_map` over an
abstract mesh; the SAME program runs on a 1-device host-CPU mesh with zero
semantic change (collectives degenerate to identity). At call time the fit
or predict entry passes a work estimate (`WorkHint`); `mesh_for` compares

    t_device = rt_fixed + uncached_bytes/h2d_bw + flops/dev_rate + out/d2h_bw
    t_host   = flops/host_rate[kind]

using constants MEASURED once per process against the real device (no
hard-coded tunnel model) and routes accordingly. Large-N work (where the
reference's "scalable" claim lives) goes to the chip; interactive small-N
work stays on host and beats a single-node library instead of losing to it.

Overrides: ``sml.dispatch.mode`` conf = auto|device|host; tests that pin a
mesh via `use_mesh`/`use_mesh_local` are unaffected when the process
backend is CPU (no tunnel → always the active mesh).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Optional, Tuple

import numpy as np

from ..conf import GLOBAL_CONF, _register, _to_bool
from ..obs import _audit as _obs_audit
from ..obs._recorder import RECORDER as _OBS
from ..utils.profiler import now as _now
from . import mesh as meshlib

_register("sml.dispatch.mode", "auto", str,
          "auto: route programs host/device by measured latency; "
          "device: always the accelerator mesh; host: always the host mesh")
_register("sml.dispatch.autoPromote", True, _to_bool,
          "In auto mode, asynchronously stage a dataset into HBM when a "
          "device-resident copy would beat the host, so repeated fits "
          "(CV folds, tuning trials) converge onto the chip")


# --------------------------------------------------- persistent compile cache
# Two layers keep repeated fits (and the bench warmup) from recompiling:
# 1. shape-bucketed padding — `mesh.bucket_rows` (re-exported below) rounds
#    row counts onto a coarse grid (≤12.5% padding) so near-size datasets
#    (CV folds, randomSplit variants, tuning re-fits) hit the SAME compiled
#    program signature;
# 2. XLA's persistent compilation cache — a fresh process replays earlier
#    compiles from disk instead of re-running XLA.
bucket_rows = meshlib.bucket_rows

_compile_cache_state = {"dir": None}


def ensure_compile_cache() -> Optional[str]:
    """Point XLA's persistent compilation cache at `sml.compile.cacheDir`
    (conf), falling back to SML_TPU_COMPILE_CACHE / JAX_COMPILATION_CACHE_DIR
    env and then the repo-local .jax_cache default. Idempotent; returns the
    active directory (None = caching disabled or unsupported jax).

    Called at package import, and again automatically whenever
    `sml.compile.cacheDir` is set (a conf on_set hook — jax reads the
    config per compile, so later programs land in the new directory)."""
    import os
    conf_dir = str(GLOBAL_CONF.get("sml.compile.cacheDir") or "").strip()
    cache = conf_dir or os.environ.get("SML_TPU_COMPILE_CACHE")
    if cache == "0":
        return None
    import jax
    if not cache:
        # never override an explicit user choice (env var or pre-import
        # jax.config call) — only fill in the default. A jax config value
        # WE latched earlier is ours to re-point (clearing the conf knob
        # restores the default).
        if os.environ.get("JAX_COMPILATION_CACHE_DIR"):
            return os.environ["JAX_COMPILATION_CACHE_DIR"]
        try:
            current = jax.config.jax_compilation_cache_dir
            if current and current != _compile_cache_state["dir"]:
                return current
        except AttributeError:
            pass
        here = os.path.dirname(os.path.abspath(__file__))
        cache = os.path.join(here, os.pardir, os.pardir, ".jax_cache")
    cache = os.path.abspath(cache)
    if _compile_cache_state["dir"] == cache:
        return cache
    try:
        jax.config.update("jax_compilation_cache_dir", cache)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.2)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        # NOT "all": XLA:CPU AOT entries replay with machine-feature
        # mismatch warnings (pseudo-features like +prefer-no-scatter) and a
        # documented SIGILL risk; the jax-level executable cache is enough
        jax.config.update("jax_persistent_cache_enable_xla_caches", "none")
    except Exception:
        return None  # older jax without these flags: best-effort
    _compile_cache_state["dir"] = cache
    if _OBS.enabled:
        _OBS.emit("compile", "compile.cache_dir", args={"dir": cache})
    return cache


# setting the knob re-points the cache immediately (without this hook the
# import-time call would latch the default and the conf key would be dead)
GLOBAL_CONF.on_set("sml.compile.cacheDir",
                   lambda: ensure_compile_cache())

# effective host rates (elementwise ops/s) per program family — the
# BOOTSTRAP values only: every hinted host execution feeds its measured
# flops/sec back into OBSERVED_HOST below, so routing converges onto this
# host's real throughput instead of a constant. Bootstraps stay
# conservative (over-crediting the host only steers SMALL jobs hostward,
# where the fixed device latency dominates any estimation error).
_HOST_RATES = {
    # measured on THIS host's 1-device mesh (XLA:CPU): Gram at 2M rows ran
    # 3.8e9 flops in ~0.7s; the ensemble one-hot program 4.6e9 in ~3.8s
    "blas": 6e9,       # dense matmul-shaped work (Gram, forward passes)
    "scatter": 1.2e9,  # histogram/one-hot accumulation
    "scan": 1.0e9,     # long sequential scans (boosting rounds, ARIMA)
    # per-tree numpy traversal loop (predict): measured ~2e8 ops/s at 800k
    # rows — 6x below the histogram kernels; pricing predicts with the
    # "scatter" rate routed every forest predict hostward and cost the r4
    # bench 13.6s of host traversal on data already resident in HBM
    "traverse": 2.5e8,
    # argsort + reduceat segment reductions (host ALS normal equations):
    # measured ~8e7 effective ops/s against the nnz·rank² estimate — the
    # "blas" rate over-credited the host ~75x and silently routed whole
    # MovieLens-scale ALS fits onto a 14s host path
    "segment": 8e7,
}
_DEVICE_RATE = 2e12  # sustained non-MXU-peak device throughput estimate


class _ObservedRates:
    """MEASURED host throughput per WorkHint kind.

    The router's host-side cost model can only be as good as its rates;
    hard-coded constants were wrong by 6x for tree traversal (r4). Every
    hinted host execution calls `observe(kind, flops, seconds)` with its
    wall time; `host_time` prefers the observed estimate.

    The estimate is THROUGHPUT-WEIGHTED over a window of recent large
    observations — sum(flops)/sum(seconds) — not an EWMA or a max:

    - an EWMA lets one compile-inflated first call flip marginal work onto
      the tunneled device, where no further host samples ever correct it;
    - a max-of-window lets one warm SMALL call (whose per-op overhead
      profile looks nothing like an 800k-row traversal) over-credit the
      host for big jobs — r4 saw exactly this flapping, with 266k-row CV
      evals bouncing to a host path that cost ~1.4s each;
    - throughput weighting makes big calls dominate the estimate in
      proportion to the work they did, which is what routing big calls
      needs, while the flops floor keeps tiny-call noise out entirely.

    Observations AGE OUT (`_MAX_AGE_S`): routing by observed rates is
    otherwise a one-way ratchet — once one contended/throttled window
    flips a kind's routing to the device, no further host samples are
    ever taken for that kind and the stale slow rate persists until
    process restart. Stale entries fall out of the window, and an empty
    window falls back to the bootstrap constant, so the host gets
    re-probed after recovery."""

    _WINDOW = 8
    _MIN_FLOPS = 1e8  # below this, per-call overhead ≈ the signal
    _MAX_AGE_S = 120.0  # contention windows are transient at this scale

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._recent: dict = {}  # kind -> deque of (flops, seconds, t)

    def observe(self, kind: str, flops: float, seconds: float) -> None:
        # sub-ms timings are dominated by timer noise / python overhead
        if seconds < 1e-3 or flops < self._MIN_FLOPS:
            return
        from collections import deque
        with self._lock:
            dq = self._recent.get(kind)
            if dq is None:
                dq = self._recent[kind] = deque(maxlen=self._WINDOW)
            dq.append((flops, seconds, time.monotonic()))

    def rate(self, kind: str):
        cutoff = time.monotonic() - self._MAX_AGE_S
        with self._lock:
            dq = self._recent.get(kind)
            if dq:
                while dq and dq[0][2] < cutoff:
                    dq.popleft()
            if not dq:
                return None
            return sum(f for f, _, _ in dq) / sum(s for _, s, _ in dq)


OBSERVED_HOST = _ObservedRates()


class QueuePressure:
    """Rows currently queued for (or in flight on) the device by online
    serving — the dispatcher's backpressure signal. The serving
    micro-batcher feeds it (`add` at admission, `sub` when a batch
    completes or sheds); admission control reads `rows()` to decide when
    the device lane is saturated and traffic should degrade to the host
    route instead of queueing behind it. Deliberately NOT a term in
    `device_time` — fits price a single dispatch, while serving pressure
    is a property of the standing queue, and mixing the two would let a
    transient burst reroute long training jobs.

    `parent` chains per-replica queues into the process-wide signal:
    a fleet replica's own `QueuePressure(parent=DEVICE_QUEUE)` gives the
    router per-replica attribution (this replica's standing rows, not
    the fleet total) while every add/sub still reaches the one
    dispatcher signal — the device tunnel is shared no matter how many
    batchers feed it."""

    def __init__(self, parent: "Optional[QueuePressure]" = None) -> None:
        self._lock = threading.Lock()
        self._rows = 0
        self._parent = parent

    def add(self, rows: int) -> None:
        with self._lock:
            self._rows += int(rows)
        parent = self._parent
        if parent is not None:
            parent.add(rows)

    def sub(self, rows: int) -> None:
        with self._lock:
            self._rows = max(0, self._rows - int(rows))
        parent = self._parent
        if parent is not None:
            parent.sub(rows)

    def rows(self) -> int:
        with self._lock:
            return self._rows


#: process-wide device-queue pressure (one device tunnel per process)
DEVICE_QUEUE = QueuePressure()


import contextlib as _contextlib


@_contextlib.contextmanager
def observe_host(kind: str, flops: float):
    """Time a host-route execution and feed the measured rate back into
    the router — the ONE definition of what gets observed, shared by every
    host predict path."""
    t0 = _now()
    try:
        yield
    finally:
        OBSERVED_HOST.observe(kind, flops, _now() - t0)


@dataclass(frozen=True)
class WorkHint:
    """Caller's estimate of one program invocation's cost."""
    flops: float                 # elementwise-op / flop count on the data path
    kind: str = "blas"           # which _HOST_RATES family
    out_bytes: float = 256.0     # device→host result size
    in_bytes: Optional[float] = None  # H2D bytes if NOT already staged


class _Calibration:
    """Measured tunnel constants, taken lazily once per process."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._done = False
        self.rt_fixed = 0.0       # s per dispatch+readback of a tiny program
        self.h2d_bw = float("inf")  # bytes/s host→device
        self.d2h_bw = float("inf")  # bytes/s device→host

    def ensure(self) -> "_Calibration":
        if self._done:
            return self
        with self._lock:
            if self._done:
                return self
            import jax
            import jax.numpy as jnp
            dev = jax.devices()[0]
            if dev.platform == "cpu":
                self._done = True
                return self
            f = jax.jit(lambda x: (x @ x).sum())
            x = jax.device_put(np.eye(8, dtype=np.float32), dev)
            jax.device_get(f(x))  # compile outside the timing
            trips = []
            for _ in range(3):
                t0 = _now()
                jax.device_get(f(x))
                trips.append(_now() - t0)
            self.rt_fixed = max(min(trips), 1e-4)
            blk = np.ones((4 * 1024 * 1024,), np.float32)  # 16 MB
            h2d = []
            for _ in range(2):  # best-of-2: tunnel bandwidth is noisy
                t0 = _now()
                d = jax.device_put(blk, dev)
                # graftlint: disable=host-sync-in-hot-path -- calibration probe: the synchronous H2D wait IS the bandwidth measurement
                d.block_until_ready()
                h2d.append(_now() - t0)
                del d
            d = jax.device_put(blk, dev)
            # graftlint: disable=host-sync-in-hot-path -- calibration probe: drain the transfer before timing the D2H leg
            d.block_until_ready()
            self.h2d_bw = max(blk.nbytes / min(h2d), 1e6)
            t0 = _now()
            # graftlint: disable=host-sync-in-hot-path -- calibration probe: the synchronous D2H pull IS the bandwidth measurement
            np.asarray(d)
            self.d2h_bw = max(blk.nbytes / (_now() - t0), 1e6)
            self._done = True
            return self


CALIBRATION = _Calibration()

_host_mesh_lock = threading.Lock()
_host_mesh: Optional[object] = None


def host_mesh():
    """A cached 1-device host-CPU mesh. The same shard_map programs run on
    it unchanged (psum over one device is identity), so routing here changes
    latency, never results."""
    global _host_mesh
    with _host_mesh_lock:
        if _host_mesh is None:
            import jax
            from jax.sharding import Mesh
            cpus = jax.devices("cpu")
            _host_mesh = Mesh(np.asarray(cpus[:1]), (meshlib.DATA_AXIS,))
        return _host_mesh


def is_host_mesh(mesh) -> bool:
    """True only for THE host-dispatch mesh. Deliberately identity-based:
    a platform check would also match the virtual CPU test meshes, which
    are *device* meshes from the dispatcher's point of view."""
    return _host_mesh is not None and mesh is _host_mesh


def _default_backend() -> str:
    import jax
    return jax.default_backend()


def device_time(hint: WorkHint, cal: _Calibration) -> float:
    t = cal.rt_fixed + hint.flops / _DEVICE_RATE + hint.out_bytes / cal.d2h_bw
    if hint.in_bytes:
        t += hint.in_bytes / cal.h2d_bw
    return t


def host_time(hint: WorkHint) -> float:
    rate = OBSERVED_HOST.rate(hint.kind) \
        or _HOST_RATES.get(hint.kind, _HOST_RATES["blas"])
    return hint.flops / rate


def preroute(hint: Optional[WorkHint]) -> Optional[str]:
    """The decision when it doesn't depend on work size or staging state:
    "device"/"host" for forced modes and no-tunnel backends, None when a
    real estimate (decide) is needed. Lets callers skip the staging-cache
    probe (which hashes array windows) whenever the answer is forced."""
    if _default_backend() == "cpu":
        return "device"  # no tunnel: the active mesh IS the host
    mode = str(GLOBAL_CONF.get("sml.dispatch.mode"))
    if mode == "host":  # forced host must also catch unhinted programs
        return "host"
    if mode == "device" or hint is None:
        return "device"
    if CALIBRATION.ensure().rt_fixed <= 1e-3:  # locally attached chip
        return "device"
    return None


def _preroute_reason(hint: Optional[WorkHint]) -> str:
    """Why preroute() short-circuited — recorded by the dispatch audit so
    a forced decision is never mistaken for a priced one."""
    if _default_backend() == "cpu":
        return "no-tunnel"
    mode = str(GLOBAL_CONF.get("sml.dispatch.mode"))
    if mode in ("host", "device"):
        return "forced-mode"
    if hint is None:
        return "no-hint"
    return "local-chip"


def audit_preroute(hint: Optional[WorkHint], route: str) -> None:
    """Record a preroute short-circuit in the dispatch audit (no-op with
    the flight recorder off, or for unhinted programs — there is nothing
    to price). Shared by decide() and the preroute fast paths in
    _staging._route_mesh / evaluation._stats_route.

    Deliberately does NOT run the tunnel calibration: a forced route was
    never priced, and measuring bandwidths (seconds of probe traffic)
    just to stamp an audit row would make enabling observability change
    engine behavior. If calibration hasn't happened yet, the device
    prediction is the rate-only model and the record is marked
    uncalibrated so the audit's misroute logic won't trust it."""
    if not _OBS.enabled or hint is None:
        return
    _obs_audit.record(hint, route, host_time(hint),
                      device_time(hint, CALIBRATION), forced=True,
                      reason=_preroute_reason(hint),
                      calibrated=CALIBRATION._done)


def audit_decision(hint: Optional[WorkHint], route: str) -> None:
    """Record a priced, unforced decision a caller made from its own
    decide(..., _record=False) probes (see _staging._route_mesh's
    resident-cost fast path) — exactly one audit row per dispatch."""
    if not _OBS.enabled or hint is None:
        return
    cal = CALIBRATION.ensure()
    _obs_audit.record(hint, route, host_time(hint),
                      device_time(hint, cal), forced=False)


def decide(hint: Optional[WorkHint],
           _record: bool = True) -> Tuple[str, bool]:
    """(route, promote): route is "host"|"device"; promote is True when the
    device loses ONLY because of the one-time H2D staging cost — i.e. a
    device-resident copy of this dataset would win, so the caller should
    stage it in the background and let later fits ride the chip.

    `_record=False` suppresses the dispatch-audit row — for callers
    using decide() as an internal pricing PROBE rather than the decision
    itself (the audit must count dispatches, not probes)."""
    pre = preroute(hint)
    if pre is not None:
        if _record:
            audit_preroute(hint, pre)
        return pre, False
    cal = CALIBRATION.ensure()
    t_host = host_time(hint)
    t_device = device_time(hint, cal)
    if t_device <= t_host:
        if _record and _OBS.enabled:
            _obs_audit.record(hint, "device", t_host, t_device,
                              forced=False)
        return "device", False
    # Promote only on a DECISIVE resident-device win: flipping a dataset's
    # route costs a fresh trace/compile of every program it touches, so a
    # marginal (<3x) projected gain is not worth the switch.
    resident = WorkHint(hint.flops, hint.kind, hint.out_bytes, None)
    if _record and _OBS.enabled:
        _obs_audit.record(hint, "host", t_host, t_device, forced=False)
    return "host", 3.0 * device_time(resident, cal) <= t_host


def mesh_for(hint: Optional[WorkHint]):
    """Pick the execution mesh for one program invocation.

    Returns the active mesh (accelerator / placed submesh) or the host
    mesh. On a CPU-backend process (no tunnel) this is just `get_mesh()`;
    with no hint it is `get_mesh()` UNLESS sml.dispatch.mode=host, which
    forces the host mesh even for unhinted programs.
    """
    route, _ = decide(hint)
    return meshlib.get_mesh() if route == "device" else host_mesh()


def routed(hint: Optional[WorkHint]):
    """Context manager binding the dispatch decision as the thread's active
    mesh, so every `get_mesh()` in the wrapped fit/predict body (staging,
    program caches) resolves to the chosen mesh."""
    return meshlib.use_mesh_local(mesh_for(hint))
