"""The single communication backend (SURVEY §2.4).

Spark's shuffle, `treeAggregate`, Arrow IPC and XGBoost's Rabit allreduce all
collapse into XLA collectives over ICI (intra-slice) / DCN (multi-host):

- ``treeAggregate(gradient | Gram)``  → ``psum``            (allreduce)
- shuffle for keyed aggregation       → ``all_to_all`` on device, or the
  host-side Arrow repartition in ``sml_tpu.frame`` for string-heavy ops
- broadcast of models/params          → replication via sharding
- Rabit histogram allreduce           → the same ``psum``

These wrappers exist so estimator code never spells a raw `lax` collective —
one place to retarget if the axis naming or multi-host story changes.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..obs import _context as _trace
from ..obs._recorder import RECORDER as _OBS
from ..obs._watchdog import WATCHDOG as _WATCHDOG
from .mesh import DATA_AXIS, DCN_AXIS, ICI_AXIS


def _resolve_row_axis(axis):
    """`DATA_AXIS` spoken under a hierarchical (host-grouped) active mesh
    means "all row axes": a program written for the flat 1-D mesh (an
    evaluator's masked stats, a linear model's normal equations, a
    clustering step) reduces over ("dcn", "ici") without every call site
    learning about host groups — the two-axis mesh is a drop-in for the
    flat one. Explicit names and tuples pass through untouched, so
    topology-aware code (tree_impl threads `row_axes(mesh)` itself)
    keeps full control. Runs at TRACE time, like the flight-recorder
    notes: the active mesh is the one the enclosing shard_map is being
    built over."""
    if axis == DATA_AXIS:
        from . import mesh as _mesh
        m = _mesh.get_mesh()
        if _mesh.is_hierarchical(m):
            return _mesh.row_axes(m)
    return axis


def _payload_bytes(x) -> float:
    """Per-launch payload of one collective operand: every participating
    chip moves (shape x itemsize) bytes through the allreduce/gather ring.
    Computed from the TRACE-time abstract value (shapes are static), so it
    works on tracers and concrete arrays alike."""
    import numpy as _np
    try:
        dt = _np.dtype(getattr(x, "dtype", _np.float32))
    except TypeError:
        dt = _np.dtype(_np.float32)
    return float(_np.prod(_np.shape(x), dtype=_np.float64) * dt.itemsize)


def _note(op: str, x=None) -> None:
    """Flight-recorder collective event. These wrappers execute at TRACE
    time (the collective itself runs inside the compiled program), so one
    event marks one collective launch PER COMPILED PROGRAM — the static
    count a graph runtime can know without a device profiler; multiply by
    program executions for wire traffic. No-op when the recorder is off.

    With an operand `x`, the per-launch payload is counted into
    `collective.<op>_bytes` (rendered as a counter track by the trace
    exporter): the ICI allreduce volume of one split round is the
    histogram payload, and the histogram-subtraction trick's halving of
    it is directly visible in this counter.

    Tracing happens on the DISPATCHING thread, so the causal trace
    context riding it (obs/_context.py — e.g. a coalesced serving
    flush, a fused CV trial batch) tags the event: the collective hop
    of a request's causal chain, without a device profiler."""
    if _OBS.enabled:
        nbytes = None if x is None else _payload_bytes(x)
        _OBS.emit("collective", f"collective.{op}",
                  args=_trace.trace_args(
                      None if nbytes is None else {"bytes": nbytes}))
        _OBS.counter(f"collective.{op}")
        if nbytes:
            _OBS.counter(f"collective.{op}_bytes", nbytes)


def _note_hop(op: str, hop: str, x=None) -> None:
    """Per-HOP flight-recorder event for a hierarchical collective: same
    trace-time semantics as `_note`, but the launch and byte counters are
    keyed `collective.<op>.<hop>` / `collective.<op>_bytes.<hop>` so the
    cheap wide intra-host hop ("ici") and the narrow cross-host hop
    ("dcn") are separately visible — the DCN byte drop to the inter-group
    fraction is the whole point of the two-level reduce, and this counter
    is what asserts it (tests + the `multihost` bench block)."""
    if _OBS.enabled:
        nbytes = None if x is None else _payload_bytes(x)
        _OBS.emit("collective", f"collective.{op}.{hop}",
                  args=_trace.trace_args(
                      None if nbytes is None else {"bytes": nbytes}))
        _OBS.counter(f"collective.{op}.{hop}")
        if nbytes:
            _OBS.counter(f"collective.{op}_bytes.{hop}", nbytes)


def psum(x, axis=DATA_AXIS):
    """Allreduce-sum over the mesh axis — the `treeAggregate` replacement.
    `axis` may be a tuple of names (a host mesh's ("dcn", "ici") row axes);
    XLA reduces over their product as one flat allreduce. The default
    axis resolves against the active mesh (`_resolve_row_axis`)."""
    axis = _resolve_row_axis(axis)
    _note("psum", x)
    return lax.psum(x, axis_name=axis)


def psum_hierarchical(x, *, ici_axis: str = ICI_AXIS,
                      dcn_axis: str = DCN_AXIS, ici_size: int):
    """Two-level topology-aware allreduce for host-grouped meshes:

      1. reduce-scatter over the INTRA-group hop (`ici_axis`) — each of
         the `ici_size` group members ends holding the group-partial sum
         of one 1/ici_size chunk of the payload;
      2. allreduce the chunk over the INTER-group hop (`dcn_axis`) —
         the only cross-host traffic, payload/ici_size bytes per device
         instead of the full payload a flat allreduce would push through
         the ~10x-narrower DCN;
      3. allgather the reduced chunks back over `ici_axis`.

    The result equals `psum(x, (dcn_axis, ici_axis))` (bit-exact when the
    per-chunk sums are exact, e.g. integer-valued histogram counts;
    otherwise within float reduction-order noise, the same caveat as any
    mesh-width change). `ici_size` must be the static size of `ici_axis`
    (program makers read it from the mesh at trace time — `lax` has no
    axis-size query in the pinned jax). Chunking pads the flattened
    payload with zeros to a multiple of `ici_size`, which is exact for
    sums. ici_size<=1 degenerates to the flat psum over the DCN hop.

    Per-hop launches and bytes are recorded by `_note_hop`: the full
    payload on the ici reduce-scatter, payload/ici_size on the dcn
    allreduce and the ici allgather."""
    ici_size = int(ici_size)
    if ici_size <= 1:
        _note_hop("psum", "dcn", x)
        return lax.psum(x, axis_name=dcn_axis)
    shape = x.shape
    flat = x.reshape(-1)
    n = flat.shape[0]
    pad = (-n) % ici_size
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    _note_hop("psum", "ici", flat)
    chunk = lax.psum_scatter(flat, axis_name=ici_axis,
                             scatter_dimension=0, tiled=True)
    _note_hop("psum", "dcn", chunk)
    chunk = lax.psum(chunk, axis_name=dcn_axis)
    _note_hop("all_gather", "ici", chunk)
    out = lax.all_gather(chunk, axis_name=ici_axis, tiled=True)
    if pad:
        out = out[:n]
    return out.reshape(shape)


def psum_scalars(*xs, axis=DATA_AXIS):
    """ONE allreduce for several scalar statistics: stacks the operands and
    psums the vector, so k base/count reductions cost one collective launch
    instead of k (each launch pays fixed ICI latency). Elementwise across
    chips, so each result is bit-identical to its own psum. Returns the
    scalars in input order."""
    stacked = psum(jnp.stack([jnp.asarray(x, jnp.float32) for x in xs]), axis)
    return tuple(stacked[i] for i in range(len(xs)))


def pmean(x, axis=DATA_AXIS):
    axis = _resolve_row_axis(axis)
    _note("pmean", x)
    return lax.pmean(x, axis_name=axis)


def pmax(x, axis=DATA_AXIS):
    axis = _resolve_row_axis(axis)
    _note("pmax", x)
    return lax.pmax(x, axis_name=axis)


def pmin(x, axis=DATA_AXIS):
    axis = _resolve_row_axis(axis)
    _note("pmin", x)
    return lax.pmin(x, axis_name=axis)


def all_gather(x, axis: str = DATA_AXIS, *, tiled: bool = False):
    _note("all_gather", x)
    return lax.all_gather(x, axis_name=axis, tiled=tiled)


def reduce_scatter(x, axis: str = DATA_AXIS, *, scatter_dimension: int = 0):
    _note("reduce_scatter", x)
    return lax.psum_scatter(x, axis_name=axis, scatter_dimension=scatter_dimension, tiled=True)


def all_to_all(x, axis: str = DATA_AXIS, *, split_axis: int = 0, concat_axis: int = 0):
    """Device-side shuffle: exchange row blocks between chips over ICI."""
    _note("all_to_all", x)
    return lax.all_to_all(x, axis_name=axis, split_axis=split_axis, concat_axis=concat_axis, tiled=True)


def ppermute(x, perm, axis: str = DATA_AXIS):
    _note("ppermute", x)
    return lax.ppermute(x, axis_name=axis, perm=perm)


def axis_index(axis=DATA_AXIS):
    """Linear shard index over one axis name or a TUPLE of names (the
    ("dcn", "ici") row axes of a host mesh, major-to-minor): the flat
    position matches the flat mesh's index, so layout-keyed draws stay
    layout-invariant. The pinned jax has no `lax.axis_size`, so minor
    axis sizes come from `psum(1, axis)` — a constant fold at trace time,
    not a runtime collective."""
    axis = _resolve_row_axis(axis)
    if isinstance(axis, str):
        return lax.axis_index(axis_name=axis)
    idx = lax.axis_index(axis_name=axis[0])
    for name in axis[1:]:
        idx = idx * lax.psum(1, axis_name=name) + lax.axis_index(
            axis_name=name)
    return idx


def masked_count(mask, axis=DATA_AXIS):
    """Global true-row count given a per-shard 0/1 row mask."""
    return psum(jnp.sum(mask), axis)


class MultihostInitError(RuntimeError):
    """Typed failure surface of `initialize_multihost`: carries the
    coordinator / process context so a wedged bring-up is diagnosable
    from the exception alone (which peer config, which process slot)."""

    def __init__(self, msg: str, *, coordinator=None, num_processes=None,
                 process_id=None):
        super().__init__(msg)
        self.coordinator = coordinator
        self.num_processes = num_processes
        self.process_id = process_id


def initialize_multihost(coordinator: Optional[str] = None, num_processes: Optional[int] = None,
                         process_id: Optional[int] = None, *,
                         timeout_s: float = 300.0) -> bool:
    """Cross-host (DCN) bring-up. On a single host this is a no-op (fast
    path, returns False without touching `jax.distributed`); on a pod
    slice it wires `jax.distributed` so the same named collectives span
    hosts (the NCCL/MPI-equivalent bootstrap, without either) and returns
    True. Bring-up blocks until every process joins — bounded by
    `timeout_s` where the pinned jax supports `initialization_timeout` —
    and any failure (timeout, refused coordinator, double-init) surfaces
    as a typed `MultihostInitError` carrying the peer config instead of a
    bare RuntimeError from deep inside the runtime."""
    if num_processes is None or num_processes <= 1:
        return False
    import inspect
    kwargs = dict(coordinator_address=coordinator,
                  num_processes=num_processes, process_id=process_id)
    try:
        params = inspect.signature(jax.distributed.initialize).parameters
    except (TypeError, ValueError):  # builtins/C-accelerated: assume modern
        params = {"initialization_timeout": None}
    if "initialization_timeout" in params:
        kwargs["initialization_timeout"] = max(1, int(timeout_s))
    # the one HOST-SIDE collective wait in this module: bring-up blocks
    # until every process joins, which is exactly the hang a dead peer
    # produces — a watchdog ticket makes it a flagged stall with stacks
    # instead of a silent wedge (obs/_watchdog.py)
    with _WATCHDOG.watch("collective", "collective.initialize",
                         trace=_trace.current()):
        try:
            jax.distributed.initialize(**kwargs)
        except Exception as e:
            raise MultihostInitError(
                f"multi-host bring-up failed (coordinator={coordinator!r}, "
                f"num_processes={num_processes}, process_id={process_id}, "
                f"timeout_s={timeout_s}): {e}",
                coordinator=coordinator, num_processes=num_processes,
                process_id=process_id) from e
    return True
