"""The single communication backend (SURVEY §2.4).

Spark's shuffle, `treeAggregate`, Arrow IPC and XGBoost's Rabit allreduce all
collapse into XLA collectives over ICI (intra-slice) / DCN (multi-host):

- ``treeAggregate(gradient | Gram)``  → ``psum``            (allreduce)
- shuffle for keyed aggregation       → ``all_to_all`` on device, or the
  host-side Arrow repartition in ``sml_tpu.frame`` for string-heavy ops
- broadcast of models/params          → replication via sharding
- Rabit histogram allreduce           → the same ``psum``

These wrappers exist so estimator code never spells a raw `lax` collective —
one place to retarget if the axis naming or multi-host story changes.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..obs import _context as _trace
from ..obs._recorder import RECORDER as _OBS
from ..obs._watchdog import WATCHDOG as _WATCHDOG
from .mesh import DATA_AXIS


def _payload_bytes(x) -> float:
    """Per-launch payload of one collective operand: every participating
    chip moves (shape x itemsize) bytes through the allreduce/gather ring.
    Computed from the TRACE-time abstract value (shapes are static), so it
    works on tracers and concrete arrays alike."""
    import numpy as _np
    try:
        dt = _np.dtype(getattr(x, "dtype", _np.float32))
    except TypeError:
        dt = _np.dtype(_np.float32)
    return float(_np.prod(_np.shape(x), dtype=_np.float64) * dt.itemsize)


def _note(op: str, x=None) -> None:
    """Flight-recorder collective event. These wrappers execute at TRACE
    time (the collective itself runs inside the compiled program), so one
    event marks one collective launch PER COMPILED PROGRAM — the static
    count a graph runtime can know without a device profiler; multiply by
    program executions for wire traffic. No-op when the recorder is off.

    With an operand `x`, the per-launch payload is counted into
    `collective.<op>_bytes` (rendered as a counter track by the trace
    exporter): the ICI allreduce volume of one split round is the
    histogram payload, and the histogram-subtraction trick's halving of
    it is directly visible in this counter.

    Tracing happens on the DISPATCHING thread, so the causal trace
    context riding it (obs/_context.py — e.g. a coalesced serving
    flush, a fused CV trial batch) tags the event: the collective hop
    of a request's causal chain, without a device profiler."""
    if _OBS.enabled:
        nbytes = None if x is None else _payload_bytes(x)
        _OBS.emit("collective", f"collective.{op}",
                  args=_trace.trace_args(
                      None if nbytes is None else {"bytes": nbytes}))
        _OBS.counter(f"collective.{op}")
        if nbytes:
            _OBS.counter(f"collective.{op}_bytes", nbytes)


def psum(x, axis: str = DATA_AXIS):
    """Allreduce-sum over the mesh axis — the `treeAggregate` replacement."""
    _note("psum", x)
    return lax.psum(x, axis_name=axis)


def psum_scalars(*xs, axis: str = DATA_AXIS):
    """ONE allreduce for several scalar statistics: stacks the operands and
    psums the vector, so k base/count reductions cost one collective launch
    instead of k (each launch pays fixed ICI latency). Elementwise across
    chips, so each result is bit-identical to its own psum. Returns the
    scalars in input order."""
    stacked = psum(jnp.stack([jnp.asarray(x, jnp.float32) for x in xs]), axis)
    return tuple(stacked[i] for i in range(len(xs)))


def pmean(x, axis: str = DATA_AXIS):
    _note("pmean", x)
    return lax.pmean(x, axis_name=axis)


def pmax(x, axis: str = DATA_AXIS):
    _note("pmax", x)
    return lax.pmax(x, axis_name=axis)


def pmin(x, axis: str = DATA_AXIS):
    _note("pmin", x)
    return lax.pmin(x, axis_name=axis)


def all_gather(x, axis: str = DATA_AXIS, *, tiled: bool = False):
    _note("all_gather", x)
    return lax.all_gather(x, axis_name=axis, tiled=tiled)


def reduce_scatter(x, axis: str = DATA_AXIS, *, scatter_dimension: int = 0):
    _note("reduce_scatter", x)
    return lax.psum_scatter(x, axis_name=axis, scatter_dimension=scatter_dimension, tiled=True)


def all_to_all(x, axis: str = DATA_AXIS, *, split_axis: int = 0, concat_axis: int = 0):
    """Device-side shuffle: exchange row blocks between chips over ICI."""
    _note("all_to_all", x)
    return lax.all_to_all(x, axis_name=axis, split_axis=split_axis, concat_axis=concat_axis, tiled=True)


def ppermute(x, perm, axis: str = DATA_AXIS):
    _note("ppermute", x)
    return lax.ppermute(x, axis_name=axis, perm=perm)


def axis_index(axis: str = DATA_AXIS):
    return lax.axis_index(axis_name=axis)


def masked_count(mask, axis: str = DATA_AXIS):
    """Global true-row count given a per-shard 0/1 row mask."""
    return psum(jnp.sum(mask), axis)


def initialize_multihost(coordinator: Optional[str] = None, num_processes: Optional[int] = None,
                         process_id: Optional[int] = None) -> None:
    """Cross-host (DCN) bring-up. On a single host this is a no-op; on a pod
    slice it wires `jax.distributed` so the same named collectives span hosts
    (the NCCL/MPI-equivalent bootstrap, without either)."""
    if num_processes is None or num_processes <= 1:
        return
    # the one HOST-SIDE collective wait in this module: bring-up blocks
    # until every process joins, which is exactly the hang a dead peer
    # produces — a watchdog ticket makes it a flagged stall with stacks
    # instead of a silent wedge (obs/_watchdog.py)
    with _WATCHDOG.watch("collective", "collective.initialize",
                         trace=_trace.current()):
        jax.distributed.initialize(coordinator_address=coordinator,
                                   num_processes=num_processes,
                                   process_id=process_id)
