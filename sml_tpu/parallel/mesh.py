"""Device-mesh runtime: the substrate every distributed op rides on.

The reference scales by Spark row-partitions over executors (SURVEY §2.2 P1);
here rows shard over a `jax.sharding.Mesh` of TPU chips and every aggregation
becomes an XLA collective over ICI (SURVEY §2.4). This module owns mesh
construction (real chips or a virtual host-CPU mesh for tests), default axis
naming, and row-sharded staging of host arrays into HBM.
"""

from __future__ import annotations

import contextlib
import math
import threading
from typing import Iterator, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"    # row / batch parallelism (Spark partitions → chips)
MODEL_AXIS = "model"  # feature/block parallelism (Gram blocks, ALS factors)
TRIAL_AXIS = "trial"  # fused (grid point × fold) trial parallelism
DCN_AXIS = "dcn"      # inter-host hop of a hierarchical (host-grouped) mesh
ICI_AXIS = "ici"      # intra-host hop of a hierarchical (host-grouped) mesh


def shard_map_compat(f, *, mesh, in_specs, out_specs):
    """`shard_map` across jax versions, replication checking OFF — the one
    spelling every program wrapper uses. Newer jax exposes top-level
    `jax.shard_map(..., check_vma=...)`; 0.4.x has only
    `jax.experimental.shard_map.shard_map(..., check_rep=...)`. Passing the
    wrong kwarg is a TypeError, so the flag name is chosen by probing the
    import, not by try/except around the call."""
    try:
        from jax import shard_map as _sm
        return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_vma=False)
    except ImportError:
        from jax.experimental.shard_map import shard_map as _sm
        return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_rep=False)

_lock = threading.RLock()
_active_mesh: Optional[Mesh] = None
_tls = threading.local()  # per-thread mesh override (trial placement)


def build_mesh(
    n_devices: Optional[int] = None,
    axis_names: Sequence[str] = (DATA_AXIS,),
    shape: Optional[Sequence[int]] = None,
) -> Mesh:
    """Build a mesh over available devices.

    1-D ``(data,)`` by default. For 2-D meshes pass ``axis_names=("data",
    "model")`` and optionally an explicit ``shape``; otherwise all devices go
    on the first axis.
    """
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    n = len(devices)
    if shape is None:
        shape = [n] + [1] * (len(axis_names) - 1)
    if math.prod(shape) != n:
        raise ValueError(f"mesh shape {shape} != device count {n}")
    dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, tuple(axis_names))


def get_mesh() -> Mesh:
    """Return the active mesh: the calling thread's override if one is set
    (per-trial submesh placement), else the process-wide mesh (built lazily
    as a 1-D mesh over all devices)."""
    local = getattr(_tls, "mesh", None)
    if local is not None:
        return local
    global _active_mesh
    with _lock:
        if _active_mesh is None:
            _active_mesh = build_mesh()
        return _active_mesh


def set_mesh(mesh: Optional[Mesh]) -> None:
    global _active_mesh
    with _lock:
        _active_mesh = mesh


@contextlib.contextmanager
def use_mesh(mesh: Mesh) -> Iterator[Mesh]:
    """Temporarily swap the active mesh (tests, dryruns)."""
    global _active_mesh
    with _lock:
        prev = _active_mesh
        _active_mesh = mesh
    try:
        yield mesh
    finally:
        with _lock:
            _active_mesh = prev


@contextlib.contextmanager
def use_mesh_local(mesh: Optional[Mesh]) -> Iterator[Optional[Mesh]]:
    """Bind a mesh to the CURRENT THREAD only — the placement mechanism for
    task-parallel trials (SURVEY §2.2 P6/P7): each trial worker binds its
    own submesh so concurrent fits land on disjoint chips instead of
    serializing on one shared mesh."""
    prev = getattr(_tls, "mesh", None)
    _tls.mesh = mesh
    try:
        yield mesh
    finally:
        _tls.mesh = prev


_submesh_cache: dict = {}


def submeshes(k: int, mesh: Optional[Mesh] = None) -> list:
    """Partition the mesh's devices into min(k, n_devices) disjoint 1-D
    data-axis submeshes (cycled to length k when k > n_devices). Memoized so
    repeated tuning fits reuse identical Mesh objects and hit the per-mesh
    program caches instead of recompiling."""
    mesh = mesh or get_mesh()
    devices = list(mesh.devices.flat)
    n = len(devices)
    k = max(1, int(k))
    groups = min(k, n)
    key = (tuple(id(d) for d in devices), groups)
    if key not in _submesh_cache:
        per = n // groups
        extra = n % groups
        out = []
        start = 0
        for g in range(groups):
            size = per + (1 if g < extra else 0)
            if size == n and mesh.shape.get(DATA_AXIS) == n:
                # a "submesh" spanning the whole 1-D parent IS the parent:
                # returning the same object lets trial fits hit the parent
                # mesh's program caches instead of re-loading + re-warming
                # every executable on an identical-but-distinct Mesh (the
                # dominant warmup cost on a tunneled single chip)
                out.append(mesh)
            else:
                out.append(Mesh(np.asarray(devices[start:start + size]),
                                (DATA_AXIS,)))
            start += size
        _submesh_cache[key] = out
    cached = _submesh_cache[key]
    return [cached[i % groups] for i in range(k)]


_trial_mesh_cache: dict = {}


def trial_mesh(trial_dim: int, mesh: Optional[Mesh] = None) -> Mesh:
    """A 2-D ``("trial", "data")`` mesh over the SAME devices as the given
    (or active) 1-D data mesh: fused (grid point × fold) trial ELEMENTS
    shard over the leading axis while each trial lane keeps sharding its
    rows over the remaining devices — cross-chip trial parallelism
    (SURVEY §2.2 P6 re-expressed as a mesh axis instead of a thread pool).
    ``trial_dim`` must divide the device count. Memoized per (devices,
    trial_dim) so repeated fused grids reuse identical Mesh objects and
    hit the per-mesh program caches instead of recompiling."""
    mesh = mesh or get_mesh()
    devices = list(mesh.devices.flat)
    n = len(devices)
    trial_dim = max(1, int(trial_dim))
    if n % trial_dim:
        raise ValueError(f"trial axis {trial_dim} does not divide the "
                         f"{n}-device mesh")
    key = (tuple(id(d) for d in devices), trial_dim)
    if key not in _trial_mesh_cache:
        _trial_mesh_cache[key] = Mesh(
            np.asarray(devices).reshape(trial_dim, n // trial_dim),
            (TRIAL_AXIS, DATA_AXIS))
    return _trial_mesh_cache[key]


_host_mesh_cache: dict = {}


def host_mesh(hosts: Optional[int] = None,
              devices_per_host: Optional[int] = None,
              mesh: Optional[Mesh] = None) -> Mesh:
    """A 2-D ``(DCN_AXIS, ICI_AXIS)`` host-major mesh: row 0 is host group
    0's devices, row 1 host group 1's, ... — the topology a hierarchical
    allreduce exploits (cheap wide ICI within a row, narrow DCN across
    rows).

    On a single machine the groups are VIRTUAL hosts: the flat device set
    partitioned into `hosts` contiguous groups, so the whole multi-host
    code path is testable on the simulated 8-device CPU mesh. On a real
    multi-process TPU slice (`jax.process_count() > 1`) the groups are the
    `jax.process_index()` slices — one row per process — and `hosts`
    defaults to the process count.

    Because device d of the flat mesh lands at (d // per, d % per), row
    sharding over ``(DCN_AXIS, ICI_AXIS)`` places every global row on
    exactly the device the flat mesh would — the PR-6 layout-invariant
    sampling contract carries over unchanged, whatever the group shape.

    Memoized per (devices, hosts) so repeated fits reuse identical Mesh
    objects and hit the per-mesh program caches instead of recompiling."""
    import jax as _jax
    base = mesh.devices.flat if mesh is not None else _jax.devices()
    devices = list(base)
    n = len(devices)
    if hosts is None or hosts <= 0:
        from ..conf import GLOBAL_CONF as _CONF
        hosts = int(_CONF.get("sml.mesh.hostGroups") or 0)
    if hosts <= 0:
        pc = _jax.process_count()
        hosts = pc if pc > 1 else 1
    hosts = max(1, min(int(hosts), n))
    if devices_per_host is None:
        if n % hosts:
            raise ValueError(f"{hosts} host groups do not divide the "
                             f"{n}-device set")
        devices_per_host = n // hosts
    if hosts * devices_per_host != n:
        raise ValueError(f"host mesh {hosts}x{devices_per_host} != device "
                         f"count {n}")
    if _jax.process_count() > 1 and hosts == _jax.process_count():
        # real multi-host: one row per process, devices in process order
        devices = sorted(devices, key=lambda d: (d.process_index, d.id))
    key = (tuple(id(d) for d in devices), hosts)
    if key not in _host_mesh_cache:
        _host_mesh_cache[key] = Mesh(
            np.asarray(devices).reshape(hosts, devices_per_host),
            (DCN_AXIS, ICI_AXIS))
    return _host_mesh_cache[key]


def is_hierarchical(mesh: Optional[Mesh] = None) -> bool:
    """True when the mesh declares the two-hop host topology — the signal
    `sml.tree.hierarchicalAllreduce=auto` keys on."""
    mesh = mesh or get_mesh()
    return DCN_AXIS in mesh.shape and ICI_AXIS in mesh.shape


def row_axes(mesh: Optional[Mesh] = None) -> Tuple[str, ...]:
    """The mesh axes rows shard over: ``(DCN_AXIS, ICI_AXIS)`` on a
    hierarchical host mesh, ``(DATA_AXIS,)`` everywhere else."""
    mesh = mesh or get_mesh()
    if is_hierarchical(mesh):
        return (DCN_AXIS, ICI_AXIS)
    return (DATA_AXIS,)


def row_spec_entry(mesh: Optional[Mesh] = None):
    """The PartitionSpec element that shards rows on this mesh: the plain
    DATA_AXIS name, or the ("dcn", "ici") tuple that splits rows over both
    hops of a host mesh (host-major, so placement matches the flat mesh)."""
    ax = row_axes(mesh)
    return ax if len(ax) > 1 else ax[0]


def data_width(mesh: Optional[Mesh] = None) -> int:
    """Number of row shards: the flat data-axis size, or DCN×ICI on a
    hierarchical host mesh. Every `mesh.shape[DATA_AXIS]` site reads this
    instead so host meshes ride the same staging/padding arithmetic."""
    mesh = mesh or get_mesh()
    if is_hierarchical(mesh):
        return int(mesh.shape[DCN_AXIS]) * int(mesh.shape[ICI_AXIS])
    return int(mesh.shape[DATA_AXIS])


def host_group_of(mesh: Optional[Mesh] = None) -> dict:
    """device id → host-group index (the mesh's DCN row); flat meshes map
    every device to group 0 — the lookup straggler probes use to feed
    per-host skew lanes (obs/_skew.py)."""
    mesh = mesh or get_mesh()
    if not is_hierarchical(mesh):
        return {d.id: 0 for d in mesh.devices.flat}
    rows = mesh.devices.reshape(int(mesh.shape[DCN_AXIS]), -1)
    return {d.id: g for g, row in enumerate(rows) for d in row}


def host_partition(n_rows: int, hosts: int) -> list:
    """Contiguous [start, stop) global row ranges, one per host group —
    the per-host data-plane split. Host-major row sharding places block g
    exactly on group g's devices, so a ChunkSource host-view reading only
    its range feeds its own group's HBM without cross-host traffic.
    Remainder rows go to the leading groups (matching np.array_split)."""
    hosts = max(1, int(hosts))
    n = max(0, int(n_rows))
    per, extra = divmod(n, hosts)
    out, start = [], 0
    for g in range(hosts):
        stop = start + per + (1 if g < extra else 0)
        out.append((start, stop))
        start = stop
    return out


def host_row_blocks(arr, mesh: Optional[Mesh] = None) -> list:
    """Per-host view of a row-sharded array: one (group_index, [(device,
    shard_block), ...]) pair per host group, blocks ordered by row
    position within the group — the group-aware iteration a multi-host
    skew probe walks (each block resident on its device, so timing an op
    over it measures that chip alone, attributable to its host)."""
    mesh = mesh or get_mesh()
    groups = host_group_of(mesh)
    out: dict = {}
    for dev, blk in addressable_row_blocks(arr):
        out.setdefault(groups.get(dev.id, 0), []).append((dev, blk))
    return sorted(out.items())


def data_sharding(mesh: Optional[Mesh] = None, ndim: int = 2) -> NamedSharding:
    """Rows sharded over the mesh's row axes, everything else replicated."""
    mesh = mesh or get_mesh()
    spec = P(row_spec_entry(mesh), *([None] * (ndim - 1)))
    return NamedSharding(mesh, spec)


def replicated(mesh: Optional[Mesh] = None) -> NamedSharding:
    mesh = mesh or get_mesh()
    return NamedSharding(mesh, P())


def pad_rows(x: np.ndarray, multiple: int, fill: float = 0.0) -> Tuple[np.ndarray, int]:
    """Pad axis 0 to a multiple so row-sharding divides evenly (static shapes —
    XLA requires equal per-chip blocks; the pad tail is masked by callers)."""
    n = x.shape[0]
    rem = (-n) % multiple
    if rem == 0:
        return x, n
    pad_width = [(0, rem)] + [(0, 0)] * (x.ndim - 1)
    return np.pad(x, pad_width, constant_values=fill), n


def bucket_rows(n: int, multiple: int) -> int:
    """Round a row count up to a coarse power-of-two-fraction grid (≤12.5%
    padding) that also divides evenly by `multiple` (the mesh's data-axis
    size). Near-size datasets — CV folds, tuning-trial re-fits, randomSplit
    variations — land on the SAME padded shape and therefore the same
    compiled program, instead of paying one XLA compile per exact row count
    (SURVEY §7 hard-part #6; the padding tail is masked by every program)."""
    n = max(int(n), 1)
    multiple = max(int(multiple), 1)
    target = max(n, multiple)
    step = 1 << max(0, target.bit_length() - 4)  # grid of 8..16 * 2^k
    b = ((target + step - 1) // step) * step
    return ((b + multiple - 1) // multiple) * multiple


def shard_rows(x: np.ndarray, mesh: Optional[Mesh] = None) -> Tuple[jax.Array, int]:
    """Stage a host array into HBM sharded by rows over DATA_AXIS.

    Returns (device_array, true_row_count); rows are zero-padded to a
    per-chip-equal block, callers mask with the true count.
    """
    mesh = mesh or get_mesh()
    n_dev = data_width(mesh)
    padded, n_true = pad_rows(np.asarray(x), n_dev)
    arr = jax.device_put(padded, data_sharding(mesh, padded.ndim))
    return arr, n_true


def row_mask(n_padded: int, n_true: int, dtype=np.float32) -> np.ndarray:
    """Host-side 0/1 mask for padded rows (shard alongside the data)."""
    m = np.zeros((n_padded,), dtype=dtype)
    m[:n_true] = 1
    return m


def mesh_device_count(mesh: Optional[Mesh] = None) -> int:
    mesh = mesh or get_mesh()
    return math.prod(mesh.devices.shape)


def addressable_row_blocks(arr) -> list:
    """One (device, shard_block) pair per addressable shard of a
    row-sharded array, ordered by row position — the per-chip view a
    straggler probe iterates (each block is a jax.Array RESIDENT on its
    device, so timing an op over it measures that chip alone). See
    obs/_skew.py for the attribution these timings feed."""
    shards = sorted(arr.addressable_shards,
                    key=lambda s: tuple(sl.start or 0 for sl in s.index))
    return [(s.device, s.data) for s in shards]


PLACEMENT_LOG: list = []  # (trial_index, device_id tuple) per placed trial
_PLACEMENT_LOG_MAX = 4096


def _log_placement(idx: int, mesh: Mesh) -> None:
    with _lock:
        if len(PLACEMENT_LOG) >= _PLACEMENT_LOG_MAX:
            del PLACEMENT_LOG[: _PLACEMENT_LOG_MAX // 2]
        PLACEMENT_LOG.append((idx, tuple(d.id for d in mesh.devices.flat)))


def run_placed_trials(jobs: Sequence, fn, parallelism: int) -> list:
    """Run `fn(job)` for every job with REAL chip placement: `parallelism`
    worker threads, each bound (thread-locally) to its own disjoint submesh
    of the active mesh, so concurrent trials execute on different chips —
    the TPU replacement for Spark's driver thread pool + executor tasks
    (`SML/ML 07:120-130`, `SML/Labs/ML 08L:89-107`).

    Every trial's placement is recorded in `PLACEMENT_LOG` (trial index →
    submesh device ids), so placement is ASSERTABLE without wall-clock
    timing (VERDICT r2 #7)."""
    jobs = list(jobs)
    parallelism = max(1, int(parallelism))
    if parallelism <= 1 or len(jobs) <= 1:
        mesh = get_mesh()
        out = []
        for i, j in enumerate(jobs):
            _log_placement(i, mesh)
            out.append(fn(j))
        return out
    from concurrent.futures import ThreadPoolExecutor
    import queue as _queue

    meshes = submeshes(parallelism)
    q: _queue.SimpleQueue = _queue.SimpleQueue()
    for m in meshes:
        q.put(m)

    def bind_submesh():
        _tls.mesh = q.get_nowait()

    def run_one(args):
        i, job = args
        _log_placement(i, _tls.mesh)
        return fn(job)

    with ThreadPoolExecutor(max_workers=parallelism,
                            initializer=bind_submesh) as pool:
        return list(pool.map(run_one, enumerate(jobs)))
