"""Device-mesh runtime: the substrate every distributed op rides on.

The reference scales by Spark row-partitions over executors (SURVEY §2.2 P1);
here rows shard over a `jax.sharding.Mesh` of TPU chips and every aggregation
becomes an XLA collective over ICI (SURVEY §2.4). This module owns mesh
construction (real chips or a virtual host-CPU mesh for tests), default axis
naming, and row-sharded staging of host arrays into HBM.
"""

from __future__ import annotations

import contextlib
import math
import threading
from typing import Iterator, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"    # row / batch parallelism (Spark partitions → chips)
MODEL_AXIS = "model"  # feature/block parallelism (Gram blocks, ALS factors)
TRIAL_AXIS = "trial"  # fused (grid point × fold) trial parallelism


def shard_map_compat(f, *, mesh, in_specs, out_specs):
    """`shard_map` across jax versions, replication checking OFF — the one
    spelling every program wrapper uses. Newer jax exposes top-level
    `jax.shard_map(..., check_vma=...)`; 0.4.x has only
    `jax.experimental.shard_map.shard_map(..., check_rep=...)`. Passing the
    wrong kwarg is a TypeError, so the flag name is chosen by probing the
    import, not by try/except around the call."""
    try:
        from jax import shard_map as _sm
        return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_vma=False)
    except ImportError:
        from jax.experimental.shard_map import shard_map as _sm
        return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_rep=False)

_lock = threading.RLock()
_active_mesh: Optional[Mesh] = None
_tls = threading.local()  # per-thread mesh override (trial placement)


def build_mesh(
    n_devices: Optional[int] = None,
    axis_names: Sequence[str] = (DATA_AXIS,),
    shape: Optional[Sequence[int]] = None,
) -> Mesh:
    """Build a mesh over available devices.

    1-D ``(data,)`` by default. For 2-D meshes pass ``axis_names=("data",
    "model")`` and optionally an explicit ``shape``; otherwise all devices go
    on the first axis.
    """
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    n = len(devices)
    if shape is None:
        shape = [n] + [1] * (len(axis_names) - 1)
    if math.prod(shape) != n:
        raise ValueError(f"mesh shape {shape} != device count {n}")
    dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, tuple(axis_names))


def get_mesh() -> Mesh:
    """Return the active mesh: the calling thread's override if one is set
    (per-trial submesh placement), else the process-wide mesh (built lazily
    as a 1-D mesh over all devices)."""
    local = getattr(_tls, "mesh", None)
    if local is not None:
        return local
    global _active_mesh
    with _lock:
        if _active_mesh is None:
            _active_mesh = build_mesh()
        return _active_mesh


def set_mesh(mesh: Optional[Mesh]) -> None:
    global _active_mesh
    with _lock:
        _active_mesh = mesh


@contextlib.contextmanager
def use_mesh(mesh: Mesh) -> Iterator[Mesh]:
    """Temporarily swap the active mesh (tests, dryruns)."""
    global _active_mesh
    with _lock:
        prev = _active_mesh
        _active_mesh = mesh
    try:
        yield mesh
    finally:
        with _lock:
            _active_mesh = prev


@contextlib.contextmanager
def use_mesh_local(mesh: Optional[Mesh]) -> Iterator[Optional[Mesh]]:
    """Bind a mesh to the CURRENT THREAD only — the placement mechanism for
    task-parallel trials (SURVEY §2.2 P6/P7): each trial worker binds its
    own submesh so concurrent fits land on disjoint chips instead of
    serializing on one shared mesh."""
    prev = getattr(_tls, "mesh", None)
    _tls.mesh = mesh
    try:
        yield mesh
    finally:
        _tls.mesh = prev


_submesh_cache: dict = {}


def submeshes(k: int, mesh: Optional[Mesh] = None) -> list:
    """Partition the mesh's devices into min(k, n_devices) disjoint 1-D
    data-axis submeshes (cycled to length k when k > n_devices). Memoized so
    repeated tuning fits reuse identical Mesh objects and hit the per-mesh
    program caches instead of recompiling."""
    mesh = mesh or get_mesh()
    devices = list(mesh.devices.flat)
    n = len(devices)
    k = max(1, int(k))
    groups = min(k, n)
    key = (tuple(id(d) for d in devices), groups)
    if key not in _submesh_cache:
        per = n // groups
        extra = n % groups
        out = []
        start = 0
        for g in range(groups):
            size = per + (1 if g < extra else 0)
            if size == n and mesh.shape.get(DATA_AXIS) == n:
                # a "submesh" spanning the whole 1-D parent IS the parent:
                # returning the same object lets trial fits hit the parent
                # mesh's program caches instead of re-loading + re-warming
                # every executable on an identical-but-distinct Mesh (the
                # dominant warmup cost on a tunneled single chip)
                out.append(mesh)
            else:
                out.append(Mesh(np.asarray(devices[start:start + size]),
                                (DATA_AXIS,)))
            start += size
        _submesh_cache[key] = out
    cached = _submesh_cache[key]
    return [cached[i % groups] for i in range(k)]


_trial_mesh_cache: dict = {}


def trial_mesh(trial_dim: int, mesh: Optional[Mesh] = None) -> Mesh:
    """A 2-D ``("trial", "data")`` mesh over the SAME devices as the given
    (or active) 1-D data mesh: fused (grid point × fold) trial ELEMENTS
    shard over the leading axis while each trial lane keeps sharding its
    rows over the remaining devices — cross-chip trial parallelism
    (SURVEY §2.2 P6 re-expressed as a mesh axis instead of a thread pool).
    ``trial_dim`` must divide the device count. Memoized per (devices,
    trial_dim) so repeated fused grids reuse identical Mesh objects and
    hit the per-mesh program caches instead of recompiling."""
    mesh = mesh or get_mesh()
    devices = list(mesh.devices.flat)
    n = len(devices)
    trial_dim = max(1, int(trial_dim))
    if n % trial_dim:
        raise ValueError(f"trial axis {trial_dim} does not divide the "
                         f"{n}-device mesh")
    key = (tuple(id(d) for d in devices), trial_dim)
    if key not in _trial_mesh_cache:
        _trial_mesh_cache[key] = Mesh(
            np.asarray(devices).reshape(trial_dim, n // trial_dim),
            (TRIAL_AXIS, DATA_AXIS))
    return _trial_mesh_cache[key]


def data_sharding(mesh: Optional[Mesh] = None, ndim: int = 2) -> NamedSharding:
    """Rows sharded over DATA_AXIS, everything else replicated."""
    mesh = mesh or get_mesh()
    spec = P(DATA_AXIS, *([None] * (ndim - 1)))
    return NamedSharding(mesh, spec)


def replicated(mesh: Optional[Mesh] = None) -> NamedSharding:
    mesh = mesh or get_mesh()
    return NamedSharding(mesh, P())


def pad_rows(x: np.ndarray, multiple: int, fill: float = 0.0) -> Tuple[np.ndarray, int]:
    """Pad axis 0 to a multiple so row-sharding divides evenly (static shapes —
    XLA requires equal per-chip blocks; the pad tail is masked by callers)."""
    n = x.shape[0]
    rem = (-n) % multiple
    if rem == 0:
        return x, n
    pad_width = [(0, rem)] + [(0, 0)] * (x.ndim - 1)
    return np.pad(x, pad_width, constant_values=fill), n


def bucket_rows(n: int, multiple: int) -> int:
    """Round a row count up to a coarse power-of-two-fraction grid (≤12.5%
    padding) that also divides evenly by `multiple` (the mesh's data-axis
    size). Near-size datasets — CV folds, tuning-trial re-fits, randomSplit
    variations — land on the SAME padded shape and therefore the same
    compiled program, instead of paying one XLA compile per exact row count
    (SURVEY §7 hard-part #6; the padding tail is masked by every program)."""
    n = max(int(n), 1)
    multiple = max(int(multiple), 1)
    target = max(n, multiple)
    step = 1 << max(0, target.bit_length() - 4)  # grid of 8..16 * 2^k
    b = ((target + step - 1) // step) * step
    return ((b + multiple - 1) // multiple) * multiple


def shard_rows(x: np.ndarray, mesh: Optional[Mesh] = None) -> Tuple[jax.Array, int]:
    """Stage a host array into HBM sharded by rows over DATA_AXIS.

    Returns (device_array, true_row_count); rows are zero-padded to a
    per-chip-equal block, callers mask with the true count.
    """
    mesh = mesh or get_mesh()
    n_dev = mesh.shape[DATA_AXIS]
    padded, n_true = pad_rows(np.asarray(x), n_dev)
    arr = jax.device_put(padded, data_sharding(mesh, padded.ndim))
    return arr, n_true


def row_mask(n_padded: int, n_true: int, dtype=np.float32) -> np.ndarray:
    """Host-side 0/1 mask for padded rows (shard alongside the data)."""
    m = np.zeros((n_padded,), dtype=dtype)
    m[:n_true] = 1
    return m


def mesh_device_count(mesh: Optional[Mesh] = None) -> int:
    mesh = mesh or get_mesh()
    return math.prod(mesh.devices.shape)


def addressable_row_blocks(arr) -> list:
    """One (device, shard_block) pair per addressable shard of a
    row-sharded array, ordered by row position — the per-chip view a
    straggler probe iterates (each block is a jax.Array RESIDENT on its
    device, so timing an op over it measures that chip alone). See
    obs/_skew.py for the attribution these timings feed."""
    shards = sorted(arr.addressable_shards,
                    key=lambda s: tuple(sl.start or 0 for sl in s.index))
    return [(s.device, s.data) for s in shards]


PLACEMENT_LOG: list = []  # (trial_index, device_id tuple) per placed trial
_PLACEMENT_LOG_MAX = 4096


def _log_placement(idx: int, mesh: Mesh) -> None:
    with _lock:
        if len(PLACEMENT_LOG) >= _PLACEMENT_LOG_MAX:
            del PLACEMENT_LOG[: _PLACEMENT_LOG_MAX // 2]
        PLACEMENT_LOG.append((idx, tuple(d.id for d in mesh.devices.flat)))


def run_placed_trials(jobs: Sequence, fn, parallelism: int) -> list:
    """Run `fn(job)` for every job with REAL chip placement: `parallelism`
    worker threads, each bound (thread-locally) to its own disjoint submesh
    of the active mesh, so concurrent trials execute on different chips —
    the TPU replacement for Spark's driver thread pool + executor tasks
    (`SML/ML 07:120-130`, `SML/Labs/ML 08L:89-107`).

    Every trial's placement is recorded in `PLACEMENT_LOG` (trial index →
    submesh device ids), so placement is ASSERTABLE without wall-clock
    timing (VERDICT r2 #7)."""
    jobs = list(jobs)
    parallelism = max(1, int(parallelism))
    if parallelism <= 1 or len(jobs) <= 1:
        mesh = get_mesh()
        out = []
        for i, j in enumerate(jobs):
            _log_placement(i, mesh)
            out.append(fn(j))
        return out
    from concurrent.futures import ThreadPoolExecutor
    import queue as _queue

    meshes = submeshes(parallelism)
    q: _queue.SimpleQueue = _queue.SimpleQueue()
    for m in meshes:
        q.put(m)

    def bind_submesh():
        _tls.mesh = q.get_nowait()

    def run_one(args):
        i, job = args
        _log_placement(i, _tls.mesh)
        return fn(job)

    with ThreadPoolExecutor(max_workers=parallelism,
                            initializer=bind_submesh) as pool:
        return list(pool.map(run_one, enumerate(jobs)))
