"""Shared double-buffered staging pipeline.

The `sml.infer.prefetchBatches` pattern from `ml/inference.py` —
prep-on-worker-threads with bounded lookahead, serial dispatch, bounded
in-flight window, ordered drain — generalized so the batch-inference
path and the out-of-core chunked-ingest path (`ml/_chunked.py`) run the
SAME pipeline instead of two hand-rolled deque loops:

    item i+1's PREP (pandas/numpy feature work, chunk quantization —
    C paths that release the GIL) runs on worker threads while item i's
    DISPATCH output (an async device handle: dispatched program, H2D
    put) is still in flight; DRAIN forces/finalizes results in order.

Observability is built in, not bolted on per caller: every dispatch and
drain lands a `<family>.dispatch` / `<family>.drain` recorder event
(`infer.*` for inference, `ingest.*` for the chunk plane) — the
i+1-dispatches-before-i-drains event order IS the pipelining proof the
tests assert — and every in-flight item holds a stall-watchdog ticket
(`obs._watchdog`), so a wedged H2D transfer or dead tunnel is flagged
with stacks instead of hanging silently.

With the recorder disabled the instrumentation costs one attribute load
per item (the PR-2 contract); the pipeline itself runs regardless.
"""

from __future__ import annotations

from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Iterable, Iterator, Optional


def prefetch_pipeline(items: Iterable, prep: Callable, dispatch: Callable,
                      drain: Callable, *, depth: int, workers: int = 4,
                      family: str = "infer",
                      index_key: str = "batch") -> Iterator:
    """Run `items` through prep → dispatch → drain with `depth` items
    dispatched ahead of the drain point.

    - `prep(item)` runs on one of `workers` threads, at most `workers`
      ahead of the dispatch point (bounded lookahead — an eager
      Executor.map would drain the whole source).
    - `dispatch(i, prepped)` runs serially in submission order and
      returns an in-flight handle (async device work keeps running).
    - `drain(i, handle)` finalizes in order; its results are yielded.
    - `depth` <= 1 is fully synchronous (each item drains before the
      next dispatches).

    Events/tickets use `family` (`<family>.dispatch` / `<family>.drain`
    with args {index_key: i} — both families are registered in
    obs/taxonomy.py).
    """
    from ..obs import note_pipeline
    from ..obs._recorder import RECORDER
    from ..obs._watchdog import WATCHDOG

    depth = max(int(depth), 1)
    pending: deque = deque()

    def drain_one():
        i, handle, ticket = pending.popleft()
        try:
            out = drain(i, handle)
        finally:
            WATCHDOG.close(ticket)
        if RECORDER.enabled:
            note_pipeline(family, "drain", index_key, i)
        return out

    with ThreadPoolExecutor(max_workers=max(int(workers), 1)) as ex:
        it = iter(items)
        preps: deque = deque()

        def submit_next() -> bool:
            try:
                item = next(it)
            except StopIteration:
                return False
            preps.append(ex.submit(prep, item))
            return True

        try:
            for _ in range(max(int(workers), 1)):
                submit_next()
            i = 0
            while preps:
                prepped = preps.popleft().result()
                submit_next()
                ticket = WATCHDOG.open(family, f"{family}[{i}]")
                try:
                    handle = dispatch(i, prepped)
                except BaseException:
                    WATCHDOG.close(ticket)
                    raise
                if RECORDER.enabled:
                    note_pipeline(family, "dispatch", index_key, i)
                pending.append((i, handle, ticket))
                i += 1
                if len(pending) >= depth:
                    yield drain_one()
            while pending:
                yield drain_one()
        finally:
            # abandoned generator (caller broke early) or a raised
            # dispatch/drain: every in-flight item still gets its drain —
            # external resources (ledger holds, async buffers) release,
            # and no watchdog ticket is left to rot into a false stall
            while pending:
                j, handle, ticket = pending.popleft()
                WATCHDOG.close(ticket)
                try:
                    drain(j, handle)
                except Exception:
                    pass  # best-effort cleanup; results are discarded


def prefetch_map(items: Iterable, fn: Callable, *, depth: int,
                 workers: Optional[int] = None) -> Iterator:
    """Bounded-lookahead thread-parallel map, results in order — the
    pure-host half of the pattern (the factorized-linear scoring path):
    at most `depth` results outstanding, so the source iterator is never
    drained eagerly. depth <= 1 is synchronous."""
    depth = max(int(depth), 1)
    with ThreadPoolExecutor(max_workers=workers or min(depth, 4)) as ex:
        it = iter(items)
        window: deque = deque()

        def pull() -> bool:
            try:
                item = next(it)
            except StopIteration:
                return False
            window.append(ex.submit(fn, item))
            return True

        for _ in range(depth):
            pull()
        while window:
            out = window.popleft().result()
            pull()
            yield out
