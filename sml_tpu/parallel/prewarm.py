"""Concurrent program-prewarm manifest — amortize first-dispatch latency.

The r01 bench measured 260.7s of warmup that is NOT XLA recompilation:
with the persistent compile cache warm, every program *loads* as a cache
hit, but each of the ~25 distinct executables still pays a first-dispatch
tax on the tunneled backend (executable ship + device load + python
trace), serially, one program at a time as the suite first reaches it.

This module turns that serial sum into an overlapped pool:

- RECORDING (always on once a compile-cache directory exists): every
  program family dispatched through `ml._staging.cached_data_parallel`,
  the tree program caches (`tree_impl`), or `DeviceScorer` records a
  replayable signature — a family kind, the static build parameters, the
  padded operand shapes/dtypes, and the mesh signature — into
  `prewarm_manifest.json` next to the `sml.compile.cacheDir` artifacts.
  Recording is a dict lookup + an occasional atomic file write; it never
  touches the device.

- REPLAY (opt-in, `sml.prewarm.enabled`): `prewarm()` rebuilds every
  manifest program through the SAME per-process caches the real call
  sites hit and first-dispatches it on zero-filled operands of the
  recorded shapes from a `sml.prewarm.workers`-wide thread pool, so the
  per-program payments overlap instead of summing. Entries whose mesh
  signature (data-axis width + platform) doesn't match the live mesh are
  skipped — a manifest written under 8 virtual devices cannot be
  replayed onto 1 chip.

Every replay emits `prewarm.*` counters/events through the flight
recorder, so the overlap is visible in the trace and assertable in
tests. See docs/PERF.md ("Dispatch economics").
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
from typing import Any, Callable, Dict, List, Optional

from ..conf import GLOBAL_CONF, _register, _to_bool
from ..obs import _context as _trace
from ..obs._recorder import RECORDER as _OBS
from ..obs._watchdog import WATCHDOG as _WATCHDOG
from ..utils.profiler import PROFILER, now as _now

_register("sml.prewarm.enabled", False, _to_bool,
          "Replay the program-prewarm manifest at process start: rebuild "
          "and first-dispatch every recorded program signature from a "
          "background thread pool (sml.prewarm.workers wide) so the "
          "per-program first-dispatch payments on a tunneled backend "
          "overlap instead of summing. Recording into the manifest is "
          "always on (passive, host-only); this knob gates only the "
          "replay")
_register("sml.prewarm.workers", 4, int,
          "Thread-pool width for manifest replay: how many recorded "
          "programs rebuild + first-dispatch concurrently")

_MANIFEST_VERSION = 1

_lock = threading.Lock()
_state: Dict[str, Any] = {"path": None, "entries": None}
_tls = threading.local()  # replay re-entrancy guard
#: replay guard, keyed per (manifest path, mesh signature) — NOT once
#: per process: N in-process fleet replicas share one warm set of
#: program caches only while they share BOTH the manifest and the live
#: mesh, so replica 2..N skip (counted prewarm.replica_skip) while a
#: re-pointed compile-cache dir or a reshaped mesh warms again
_ran: Dict[Any, bool] = {}

#: kind -> rebuilder(meta) — populated by tree_impl / inference /
#: _staging at import; prewarm() imports them before replaying.
_REBUILDERS: Dict[str, Callable[[dict], None]] = {}

#: family -> factory(meta) -> program fn. For program fns that are
#: FACTORY-made (closures over static params, not importable by name):
#: the factory must be memoized so replay resolves the SAME fn object
#: the live call sites use — program caches key on fn identity.
_FN_FACTORIES: Dict[str, Callable[[dict], Callable]] = {}


def register_rebuilder(kind: str, fn: Callable[[dict], None]) -> None:
    _REBUILDERS[kind] = fn


def register_fn_factory(family: str, fn: Callable[[dict], Callable]) -> None:
    _FN_FACTORIES[family] = fn


def resolve_fn(src: list):
    """The program fn behind a recorded `data_parallel` signature:
    ["import", module, qualname] resolves by import; ["factory", family,
    meta] through the registered memoized factory."""
    if src[0] == "import":
        import importlib
        return getattr(importlib.import_module(src[1]), src[2])
    return _FN_FACTORIES[src[1]](src[2])


def fn_src(fn) -> Optional[list]:
    """A recordable source for a program fn, or None (unrecordable —
    e.g. an untagged local closure). Tagged factory fns (`fn._prewarm =
    (family, meta)`) win; otherwise only a module-level name that
    round-trips back to the same object qualifies."""
    tag = getattr(fn, "_prewarm", None)
    if tag is not None:
        return ["factory", str(tag[0]), dict(tag[1])]
    mod = getattr(fn, "__module__", None)
    qual = getattr(fn, "__qualname__", "")
    if mod and qual and "." not in qual:
        import sys
        m = sys.modules.get(mod)
        if m is not None and getattr(m, qual, None) is fn:
            return ["import", mod, qual]
    return None


def arg_specs(*arrays) -> List[list]:
    """[[shape, dtype], ...] for device/host operands — the shape half of
    a program's replayable signature."""
    return [[list(a.shape), str(a.dtype)] for a in arrays]


def manifest_path() -> Optional[str]:
    """The manifest lives next to the persistent XLA compile-cache
    artifacts (they describe the same executables); None when compile
    caching is off (nothing persists across processes to prewarm)."""
    from . import dispatch
    d = dispatch.ensure_compile_cache()
    if not d:
        return None
    return os.path.join(d, "prewarm_manifest.json")


def _guard_key() -> tuple:
    """The replay-guard identity: what must match for a second replica's
    warm caches to genuinely be this replica's warm caches."""
    return (manifest_path(), tuple(_mesh_sig()))


def _mesh_sig() -> list:
    from . import mesh as meshlib
    m = meshlib.get_mesh()
    n = meshlib.data_width(m) if meshlib.is_hierarchical(m) \
        else int(m.shape.get(meshlib.DATA_AXIS, 1))
    plat = str(list(m.devices.flat)[0].platform)
    return [n, plat]


def _load(path: str) -> Dict[str, dict]:
    with _lock:
        if _state["path"] == path and _state["entries"] is not None:
            return _state["entries"]
    entries: Dict[str, dict] = {}
    try:
        with open(path) as f:
            doc = json.load(f)
        if doc.get("version") == _MANIFEST_VERSION:
            entries = dict(doc.get("entries", {}))
    except (OSError, ValueError):
        entries = {}
    with _lock:
        _state["path"] = path
        _state["entries"] = entries
    return entries


def _flush(path: str) -> None:
    """Atomic write (tmp + rename) so a concurrently-starting process
    never reads a torn manifest."""
    with _lock:
        doc = {"version": _MANIFEST_VERSION,
               "entries": dict(_state["entries"] or {})}
    tmp = path + ".tmp"
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
        os.replace(tmp, path)
    except OSError:
        pass  # recording is best-effort; never fail a fit over it


def record(kind: str, meta: dict) -> None:
    """Record one replayable program signature (idempotent per distinct
    (kind, meta, mesh) — repeated dispatches of the same program cost one
    canonical-JSON hash and a set lookup)."""
    if getattr(_tls, "replaying", False):
        return  # replays must not re-record (or flush) their own entries
    path = manifest_path()
    if path is None:
        return
    entry = {"kind": kind, "meta": meta, "mesh": _mesh_sig()}
    try:
        blob = json.dumps(entry, sort_keys=True, default=str)
    except (TypeError, ValueError):
        return
    key = hashlib.sha1(blob.encode()).hexdigest()[:20]
    entries = _load(path)
    with _lock:
        if key in entries:
            return
        entries[key] = entry
    PROFILER.count("prewarm.recorded")
    _flush(path)


# ------------------------------------------------------- autotuned specs
# The kernel autotuner (`bench.py --kernelbench`) persists its winning
# traversal specs HERE, next to the program signatures they tune: the
# manifest already rides the compile-cache directory to every replica
# and replay, so a tuned (model shape, maxBins, batch width) → (kernel,
# block_rows) decision survives process restarts and replica spin-up
# without re-sweeping. Unlike ordinary `record` entries (idempotent,
# append-only), tuned entries live at a STABLE key derived from
# (kind, key, mesh) so a re-tune REPLACES the old winner.


def _tuned_entry_key(kind: str, key: dict) -> Optional[str]:
    try:
        blob = json.dumps({"kind": kind, "key": key, "mesh": _mesh_sig()},
                          sort_keys=True, default=str)
    except (TypeError, ValueError):
        return None
    return "tuned-" + hashlib.sha1(blob.encode()).hexdigest()[:20]


def record_tuned(kind: str, key: dict, spec: dict) -> None:
    """Persist (or replace) one autotuned spec for `key` on the live
    mesh. Best-effort like `record`: never fails a bench or a fit."""
    if getattr(_tls, "replaying", False):
        return
    path = manifest_path()
    if path is None:
        return
    ekey = _tuned_entry_key(kind, key)
    if ekey is None:
        return
    entry = {"kind": kind, "meta": {"key": dict(key), "spec": dict(spec)},
             "mesh": _mesh_sig()}
    entries = _load(path)
    with _lock:
        if entries.get(ekey) == entry:
            return
        entries[ekey] = entry
    PROFILER.count("prewarm.tuned")
    _flush(path)


def tuned_spec(kind: str, key: dict) -> Optional[dict]:
    """The persisted autotuned spec for `key` on the live mesh, or None.
    One canonical-JSON hash + a dict lookup against the cached manifest —
    cheap enough for per-dispatch resolution on the scoring path."""
    path = manifest_path()
    if path is None:
        return None
    ekey = _tuned_entry_key(kind, key)
    if ekey is None:
        return None
    entry = _load(path).get(ekey)
    if entry is None or entry.get("mesh") != _mesh_sig():
        return None
    return dict(entry["meta"]["spec"])


def _replay_one(entry: dict, stats: dict, stats_lock) -> None:
    _tls.replaying = True
    t0 = _now()
    ok = True
    # each replay is its own causal trace (obs/_context.py): the rebuild
    # + first-dispatch spans it triggers carry the replay's trace id,
    # and a replay wedged behind a dead tunnel registers as an in-flight
    # watchdog ticket instead of silently pinning a pool worker
    ctx = _trace.new_trace()
    try:
        with _trace.activate(ctx), \
                _WATCHDOG.watch("prewarm", f"prewarm.{entry['kind']}",
                                trace=ctx):
            _REBUILDERS[entry["kind"]](entry["meta"])
    except Exception:
        ok = False
    finally:
        _tls.replaying = False
    dt = _now() - t0
    with stats_lock:
        stats["replayed" if ok else "failed"] += 1
        stats["serial_s"] += dt
    if ok:
        PROFILER.count("prewarm.replayed")
    else:
        PROFILER.count("prewarm.failed")
    if _OBS.enabled:
        args = {"kind": entry["kind"], "ok": ok, "seconds": round(dt, 4)}
        if ctx is not None:
            args["trace"] = ctx.trace_id
        _OBS.emit("prewarm", "prewarm.replay", args=args)


def prewarm(workers: Optional[int] = None) -> dict:
    """Rebuild + first-dispatch every matching manifest program from a
    thread pool. Returns {programs, replayed, failed, skipped, wall_s,
    serial_s}: serial_s is what the same payments would have cost one at
    a time — serial_s / wall_s is the overlap the pool bought."""
    # rebuilders live in the modules that own the program caches
    from ..ml import _staging, inference, tree_impl  # noqa: F401
    key = _guard_key()
    with _lock:
        _ran[key] = True
    path = manifest_path()
    entries = _load(path) if path else {}
    sig = _mesh_sig()
    todo = [e for e in entries.values()
            if e.get("mesh") == sig and e.get("kind") in _REBUILDERS]
    stats = {"programs": len(todo), "replayed": 0, "failed": 0,
             "skipped": len(entries) - len(todo),
             "wall_s": 0.0, "serial_s": 0.0}
    if not todo:
        return stats
    if workers is None:
        workers = GLOBAL_CONF.getInt("sml.prewarm.workers")
    workers = max(1, int(workers))
    PROFILER.count("prewarm.programs", float(len(todo)))
    if _OBS.enabled:
        _OBS.emit("prewarm", "prewarm.start",
                  args={"programs": len(todo), "workers": workers})
    t0 = _now()
    stats_lock = threading.Lock()
    from concurrent.futures import ThreadPoolExecutor
    with ThreadPoolExecutor(max_workers=workers,
                            thread_name_prefix="sml-prewarm") as pool:
        for f in [pool.submit(_replay_one, e, stats, stats_lock)
                  for e in todo]:
            f.result()
    stats["wall_s"] = _now() - t0
    if _OBS.enabled:
        _OBS.emit("prewarm", "prewarm.done", args=dict(stats))
    return stats


def speculative_prewarm(fn: Callable, shapes: List[tuple],
                        workers: Optional[int] = None) -> dict:
    """Shape-bucket prewarm keyed off a DECLARED width mix instead of a
    recorded manifest: first-dispatch `fn` on zero-filled operands of
    each distinct shape from a thread pool, so a load trace's fat-tail
    widths (docs/LOADGEN.md) hit warm per-bucket programs instead of
    paying trace+dispatch inside the measured phases. `fn` takes one
    array; shapes are (rows, features) tuples, deduplicated. Failures
    are counted, never raised — speculation must not wedge a start-up.

    Returns {programs, warmed, failed, wall_s, serial_s} like
    `prewarm()`."""
    import numpy as np
    todo = sorted({tuple(int(d) for d in s) for s in shapes})
    stats = {"programs": len(todo), "warmed": 0, "failed": 0,
             "wall_s": 0.0, "serial_s": 0.0}
    if not todo:
        return stats
    if workers is None:
        workers = GLOBAL_CONF.getInt("sml.prewarm.workers")
    workers = max(1, int(workers))
    PROFILER.count("prewarm.speculative", float(len(todo)))
    stats_lock = threading.Lock()

    def _warm_one(shape: tuple) -> None:
        t0 = _now()
        ok = True
        ctx = _trace.new_trace()
        try:
            with _trace.activate(ctx), \
                    _WATCHDOG.watch("prewarm", "prewarm.speculative",
                                    trace=ctx):
                fn(np.zeros(shape, dtype=np.float32))
        except Exception:
            ok = False
        dt = _now() - t0
        with stats_lock:
            stats["warmed" if ok else "failed"] += 1
            stats["serial_s"] += dt
        if not ok:
            PROFILER.count("prewarm.failed")
        if _OBS.enabled:
            args = {"shape": list(shape), "ok": ok,
                    "seconds": round(dt, 4)}
            if ctx is not None:
                args["trace"] = ctx.trace_id
            _OBS.emit("prewarm", "prewarm.speculative", args=args)

    t0 = _now()
    from concurrent.futures import ThreadPoolExecutor
    with ThreadPoolExecutor(max_workers=workers,
                            thread_name_prefix="sml-spec-prewarm") as pool:
        for f in [pool.submit(_warm_one, s) for s in todo]:
            f.result()
    stats["wall_s"] = _now() - t0
    if _OBS.enabled:
        _OBS.emit("prewarm", "prewarm.speculative_done", args=dict(stats))
    return stats


def maybe_prewarm(block: bool = False) -> Optional[object]:
    """The opt-in replica-start hook (bench warmup, serving endpoint /
    fleet replica load): replay the manifest once per (manifest, mesh)
    when `sml.prewarm.enabled` is set — in a background thread by
    default, so model loads overlap the warmup instead of waiting on it.
    A second in-process replica under the SAME manifest and mesh shares
    the first replica's warm program caches, so it skips (counted
    `prewarm.replica_skip`); a replica starting after the compile-cache
    dir was re-pointed or the mesh reshaped warms its genuinely cold
    world instead of inheriting a stale guard."""
    if not GLOBAL_CONF.getBool("sml.prewarm.enabled"):
        return None
    key = _guard_key()
    with _lock:
        # claim BEFORE spawning: two replicas constructed back-to-back
        # must not both launch a replay (the thread sets nothing until it
        # is scheduled — check-then-act on the thread's own flag races)
        if _ran.get(key):
            PROFILER.count("prewarm.replica_skip")
            return None
        _ran[key] = True
    if block:
        return prewarm()
    t = threading.Thread(target=prewarm, daemon=True, name="sml-prewarm")
    t.start()
    return t
