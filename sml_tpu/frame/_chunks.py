"""Out-of-core data plane: chunked columnar ingestion (host side).

The engine's whole frame/CompactParts layer historically assumed the raw
dataset is resident in host memory and staged to device in one shot —
fine at the 60k-row course scale, wrong at the 10M–100M-row scale the
ROADMAP calls for. This module is the host half of the fix:

- `ChunkSource`: an ITERATOR PROTOCOL over row-block columnar chunks
  (`sml.data.chunkRows` rows apiece). A source must be re-iterable
  (`chunks()` returns a fresh iterator each call — the streamed
  quantization below is a two-pass fit) and yields `(X, y)` pairs in
  GLOBAL ROW ORDER, so downstream row-wise draws are a pure function of
  the global row index, never of the chunk layout.
- `FeatureSketch` / `DatasetSketch`: a MERGEABLE quantile sketch
  (mergeable the way `obs._metrics` snapshots merge — per-chunk
  summaries sum into one) built per chunk then unified into the bin
  edges. Below `_EXACT_CAP` retained values the sketch is EXACT: it
  holds the raw finite values and finalizes through the same
  `np.quantile` call as the monolithic `tree_impl.make_bins`, so bin
  edges are BIT-IDENTICAL on small data. Past the cap each feature
  compresses to `sml.data.sketchBuckets` weight-uniform centroids —
  edge error bounded by one bucket's weight, i.e. within one bin width
  whenever sketchBuckets >> maxBins (the monolithic path is itself
  subsampled past the same cap, so neither side is "the truth" there).
- `chunk_random_split` / `split_assignments`: distributed
  randomSplit/shuffle as CHUNK-LOCAL draws — membership per row comes
  from a stateless hash of (seed, global row index)
  (`sampling.row_uniforms`, the host mirror of the PR-6 `_sliced_draw`
  layout-invariance scheme), so split membership is bit-identical
  regardless of chunk size. Nested splits stay invariant too: a
  filtered source numbers its rows by their position in the FILTERED
  stream, which is itself chunk-layout-invariant.

The device half (per-chunk H2D + device bin-accumulate under the
double-buffered prefetch pipeline) lives in `ml/_staging.py` /
`ml/_chunked.py`; the knob table and memory model are in
docs/DATAPLANE.md.
"""

from __future__ import annotations

from typing import Callable, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from ..conf import GLOBAL_CONF

#: retained finite values per feature below which the sketch is EXACT
#: (raw values kept, edges from the same np.quantile the monolithic
#: make_bins runs). The SAME constant make_bins uses as its
#: deterministic-subsample threshold: below it both paths are exact and
#: bit-identical; above it both are approximations of the same stream.
_EXACT_CAP = 262_144


def default_chunk_rows() -> int:
    return max(int(GLOBAL_CONF.getInt("sml.data.chunkRows")), 1)


# ------------------------------------------------------------- chunk sources
class ChunkSource:
    """Base protocol for row-block columnar sources.

    Subclasses implement `_iter_chunks()` yielding `(X, y)` pairs —
    `X` a (rows, n_features) float ndarray, `y` a (rows,) ndarray or
    None — in global row order, bounded by `chunk_rows` rows each.
    `n_rows` may be None until a full pass has counted it (the two-pass
    ingest counts during the sketch pass). `fingerprint()` (optional)
    identifies the source CONTENT cheaply so repeated fits on the same
    source hit the ingest memo instead of re-reading.
    """

    n_features: int
    n_rows: Optional[int] = None

    @property
    def chunk_rows(self) -> int:
        return getattr(self, "_chunk_rows", None) or default_chunk_rows()

    def chunks(self) -> Iterator[Tuple[np.ndarray, Optional[np.ndarray]]]:
        """A FRESH iterator over the chunks (re-iterable by contract)."""
        n = 0
        for X, y in self._iter_chunks():
            n += int(np.shape(X)[0])
            yield X, y
        self.n_rows = n

    def _iter_chunks(self):
        raise NotImplementedError

    def fingerprint(self) -> Optional[tuple]:
        return None

    # ------------------------------------------------------------- sampling
    def randomSplit(self, weights: Sequence[float],
                    seed: int) -> List["FilteredChunkSource"]:
        return chunk_random_split(self, weights, seed)

    def sample(self, fraction: float, seed: int) -> "FilteredChunkSource":
        """Row-wise Bernoulli sample by the same stateless per-row draw
        as randomSplit — chunk-layout-invariant membership."""
        return FilteredChunkSource(self, 0.0, float(fraction), int(seed))

    def host_view(self, host: int, n_hosts: int) -> "HostChunkView":
        """This host group's slice of the chunk stream: the contiguous
        global row range `mesh.host_partition(n_rows, n_hosts)[host]`.
        Host-major row sharding places exactly that range on group
        `host`'s devices, so each group ingests only its own rows — the
        per-host data plane of a multi-host fit. Requires a known
        `n_rows` (the two-pass ingest counts it in the sketch pass)."""
        return HostChunkView(self, host, n_hosts)


class ArrayChunkSource(ChunkSource):
    """A resident (X, y) pair viewed as chunks — the parity anchor: the
    same data through `chunk_rows=None` (one chunk) and any smaller
    chunking must produce bit-identical ingests/splits."""

    def __init__(self, X: np.ndarray, y: Optional[np.ndarray] = None,
                 chunk_rows: Optional[int] = None):
        self._X = np.asarray(X)
        self._y = None if y is None else np.asarray(y)
        self._chunk_rows = int(chunk_rows) if chunk_rows else None
        self.n_features = int(self._X.shape[1])
        self.n_rows = int(self._X.shape[0])

    @property
    def chunk_rows(self) -> int:
        return self._chunk_rows or self.n_rows or 1

    def _iter_chunks(self):
        c = self.chunk_rows
        for start in range(0, self._X.shape[0], c):
            X = self._X[start:start + c]
            y = None if self._y is None else self._y[start:start + c]
            yield X, y

    def fingerprint(self) -> Optional[tuple]:
        # id-based, validity pinned by the arrays themselves being held:
        # good enough for in-process re-fit memoization; file sources
        # fingerprint content (path, mtime, size)
        return ("array", id(self._X), self._X.shape, str(self._X.dtype),
                None if self._y is None else id(self._y))


class GeneratorChunkSource(ChunkSource):
    """Chunks produced on demand by `make(start, stop) -> (X, y)` — the
    bench synthetic generator's shape: data is MANUFACTURED per chunk
    (seeded by the global row range, so regeneration across the two
    ingest passes is deterministic) and never materialized whole."""

    def __init__(self, n_rows: int, n_features: int,
                 make: Callable[[int, int], Tuple[np.ndarray, Optional[np.ndarray]]],
                 chunk_rows: Optional[int] = None,
                 fingerprint: Optional[tuple] = None):
        self.n_rows = int(n_rows)
        self.n_features = int(n_features)
        self._make = make
        self._chunk_rows = int(chunk_rows) if chunk_rows else None
        self._fingerprint = fingerprint

    def _iter_chunks(self):
        c = self.chunk_rows
        for start in range(0, self.n_rows, c):
            yield self._make(start, min(start + c, self.n_rows))

    def fingerprint(self) -> Optional[tuple]:
        return self._fingerprint


class FilteredChunkSource(ChunkSource):
    """A row-wise deterministic filter view: keeps parent row i iff
    `lo <= u(seed, i) < hi` where `u` is the stateless per-row uniform
    (`sampling.row_uniforms`). Membership depends only on the PARENT's
    global row index — identical for any parent chunking — and this
    source's own rows are numbered by filtered position, so nested
    splits are chunk-layout-invariant too."""

    def __init__(self, parent: ChunkSource, lo: float, hi: float, seed: int):
        self._parent = parent
        self._lo = float(lo)
        self._hi = float(hi)
        self._seed = int(seed)
        self.n_features = parent.n_features

    @property
    def chunk_rows(self) -> int:
        return self._parent.chunk_rows

    def _iter_chunks(self):
        from .sampling import row_uniforms
        start = 0
        n_kept = 0
        for X, y in self._parent.chunks():
            rows = int(np.shape(X)[0])
            u = row_uniforms(self._seed, start, rows)
            mask = (u >= self._lo) & (u < self._hi)
            start += rows
            if mask.any():
                n_kept += int(mask.sum())
                yield (np.asarray(X)[mask],
                       None if y is None else np.asarray(y)[mask])
        self.n_rows = n_kept

    def fingerprint(self) -> Optional[tuple]:
        pf = self._parent.fingerprint()
        if pf is None:
            return None
        return ("filter", pf, self._lo, self._hi, self._seed)


class FoldChunkSource(ChunkSource):
    """k-fold view for out-of-core cross validation: row i belongs to
    fold `split_assignments(seed, i, [1]*k)[...]`; this source keeps
    the rows IN fold `fold` (`invert=False`, the validation view) or
    everything else (`invert=True`, the training view). Fold membership
    is the same stateless per-row function as randomSplit — identical
    folds for any chunking."""

    def __init__(self, parent: ChunkSource, seed: int, k: int, fold: int,
                 invert: bool = False):
        self._parent = parent
        self._seed = int(seed)
        self._k = int(k)
        self._fold = int(fold)
        self._invert = bool(invert)
        self.n_features = parent.n_features

    @property
    def chunk_rows(self) -> int:
        return self._parent.chunk_rows

    def _iter_chunks(self):
        start = 0
        n_kept = 0
        weights = [1.0] * self._k
        for X, y in self._parent.chunks():
            rows = int(np.shape(X)[0])
            cell = split_assignments(self._seed, start, rows, weights)
            mask = (cell != self._fold) if self._invert \
                else (cell == self._fold)
            start += rows
            if mask.any():
                n_kept += int(mask.sum())
                yield (np.asarray(X)[mask],
                       None if y is None else np.asarray(y)[mask])
        self.n_rows = n_kept

    def fingerprint(self) -> Optional[tuple]:
        pf = self._parent.fingerprint()
        if pf is None:
            return None
        return ("fold", pf, self._seed, self._k, self._fold, self._invert)


class HostChunkView(ChunkSource):
    """One host group's contiguous row range of a parent source: rows
    [start, stop) where the bounds come from `mesh.host_partition` — the
    SAME global row order, restricted, never reshuffled. Because every
    row keeps its parent GLOBAL index for sampling purposes downstream
    (the staged rows land at their global positions), an H-host ingest
    assembles exactly the matrix the 1-host ingest does, row for row —
    layout-invariant sampling (PR 6) then makes the fits match too.
    Parent chunks are sliced, not re-buffered: a chunk straddling the
    boundary yields only its in-range rows."""

    def __init__(self, parent: ChunkSource, host: int, n_hosts: int):
        if parent.n_rows is None:
            raise ValueError("host_view needs a counted source "
                             "(parent.n_rows is None — run the sketch "
                             "pass first)")
        from ..parallel import mesh as _meshlib
        self._parent = parent
        self._host = int(host)
        self._n_hosts = int(n_hosts)
        if not 0 <= self._host < self._n_hosts:
            raise ValueError(f"host {host} outside 0..{n_hosts - 1}")
        self.start, self.stop = _meshlib.host_partition(
            parent.n_rows, self._n_hosts)[self._host]
        self.n_features = parent.n_features
        self.n_rows = self.stop - self.start

    @property
    def chunk_rows(self) -> int:
        return self._parent.chunk_rows

    def _iter_chunks(self):
        pos = 0
        for X, y in self._parent.chunks():
            rows = int(np.shape(X)[0])
            lo = max(self.start - pos, 0)
            hi = min(self.stop - pos, rows)
            pos += rows
            if lo < hi:
                yield (np.asarray(X)[lo:hi],
                       None if y is None else np.asarray(y)[lo:hi])
            if pos >= self.stop:
                break

    def fingerprint(self) -> Optional[tuple]:
        pf = self._parent.fingerprint()
        if pf is None:
            return None
        return ("host", pf, self._host, self._n_hosts)


def chunk_random_split(source: ChunkSource, weights: Sequence[float],
                       seed: int) -> List[FilteredChunkSource]:
    """randomSplit over a ChunkSource as chunk-local draws: the weight
    cells partition [0, 1) and each row lands in the cell its stateless
    uniform falls into — splits are DISJOINT, EXHAUSTIVE, and
    bit-identical for any chunking of the same source (asserted in
    tests/test_chunked_ingest.py). The frame-level `randomSplit` keeps
    its Spark draw-for-draw sampler; this is the out-of-core plane's
    layout-invariant equivalent (one conceptual replicated key, each
    chunk slicing its row block — the `_sliced_draw` scheme on host)."""
    total = float(sum(weights))
    bounds = np.cumsum([w / total for w in weights])
    outs = []
    lo = 0.0
    for i, hi in enumerate(bounds):
        # the last cell's upper bound is exactly 1.0: u < 1.0 always
        hi = 1.0 if i == len(bounds) - 1 else float(hi)
        outs.append(FilteredChunkSource(source, lo, hi, int(seed)))
        lo = hi
    return outs


def split_assignments(seed: int, start: int, n: int,
                      weights: Sequence[float]) -> np.ndarray:
    """Cell index per global row [start, start+n) for the given weights
    — the membership function `chunk_random_split` applies, exposed for
    fold assignment (CV) and membership parity tests."""
    from .sampling import row_uniforms
    total = float(sum(weights))
    bounds = np.cumsum([w / total for w in weights])
    u = row_uniforms(int(seed), int(start), int(n))
    return np.minimum(np.searchsorted(bounds, u, side="right"),
                      len(bounds) - 1).astype(np.int32)


# ------------------------------------------------------------ quantile sketch
class FeatureSketch:
    """Mergeable quantile summary of ONE feature's finite values.

    EXACT mode (<= `exact_cap` retained values): raw values are kept and
    `quantiles()` delegates to `np.quantile` over their concatenation —
    bit-identical to the monolithic path. Past the cap the sketch
    COMPRESSES to `buckets` weight-uniform centroids (value = the
    order-statistic at each segment's weight midpoint, weight = segment
    weight) and quantile queries interpolate over the weighted points;
    rank error is bounded by one segment's weight (~n/buckets rows), so
    edges land within one bin width for buckets >> maxBins. Merging two
    sketches concatenates their (value, weight) streams and re-compresses
    — associative up to compression, like `LogHistogram.merge`.
    """

    __slots__ = ("buckets", "exact_cap", "_vals", "_wts", "_n", "_exact",
                 "n_seen", "compressions")

    def __init__(self, buckets: Optional[int] = None,
                 exact_cap: int = _EXACT_CAP):
        self.buckets = int(buckets or
                           GLOBAL_CONF.getInt("sml.data.sketchBuckets"))
        self.exact_cap = int(exact_cap)
        self._vals: List[np.ndarray] = []
        self._wts: List[np.ndarray] = []
        self._n = 0          # retained entries across the pending lists
        self._exact = True
        self.n_seen = 0      # total finite values observed
        self.compressions = 0

    def update(self, col: np.ndarray) -> None:
        # dtype-preserving: exact-mode quantiles must run np.quantile on
        # the SAME dtype stream the monolithic make_bins sees (a float32
        # column quantiled in float64 lands on different edge bits)
        finite = np.asarray(col)
        finite = finite[np.isfinite(finite)]
        if finite.size == 0:
            return
        self.n_seen += int(finite.size)
        self._vals.append(finite)
        # weights materialize lazily at compression: in exact mode (the
        # common small-data path) a ones array per value would double
        # the sketch's residency for nothing
        self._wts.append(None)
        self._n += int(finite.size)
        if self._n > self.exact_cap:
            self._compress()

    def merge(self, other: "FeatureSketch") -> None:
        """Fold another sketch's summary in (per-chunk sketches built in
        parallel unify into one — the obs._metrics snapshot-merge
        shape). Exactness survives only while the merged total fits the
        cap."""
        self.n_seen += other.n_seen
        self._vals.extend(other._vals)
        self._wts.extend(other._wts)
        self._n += other._n
        self._exact = self._exact and other._exact
        if self._n > self.exact_cap:
            self._compress()

    def _compress(self) -> None:
        """Collapse the pending stream to `buckets` weight-uniform
        centroids: sort, then keep the order-statistic at each of
        `buckets` equal-weight segments' midpoints."""
        vals = np.concatenate(self._vals)
        wts = np.concatenate([np.ones(v.size, dtype=np.float64)
                              if w is None else w
                              for v, w in zip(self._vals, self._wts)])
        order = np.argsort(vals, kind="stable")
        v, w = vals[order], wts[order]
        if v.size > self.buckets:
            cw = np.cumsum(w)
            total = cw[-1]
            # segment midpoints in weight space; min/max always retained
            mids = (np.arange(self.buckets, dtype=np.float64) + 0.5) \
                * (total / self.buckets)
            idx = np.searchsorted(cw, mids, side="left")
            idx = np.unique(np.clip(idx, 0, v.size - 1))
            idx[0] = 0
            idx[-1] = v.size - 1
            # retained point i carries the weight since the previous
            # retained point; the last point sits at the stream end, so
            # total weight is preserved exactly
            keep_w = np.diff(np.concatenate(([0.0], cw[idx])))
            v, w = v[idx], keep_w
            self._exact = False
            self.compressions += 1
        self._vals = [v]
        self._wts = [w]
        self._n = int(v.size)

    @property
    def exact(self) -> bool:
        return self._exact

    def values_weights(self) -> Tuple[np.ndarray, np.ndarray]:
        """Consolidated (values, weights) view of the retained stream —
        unsorted concatenation in insertion order; lazily-materialized
        exact-mode weights come back as ones. The accessor the drift
        engine (obs/drift.py) resamples and CDFs through without
        reaching into the pending lists."""
        if self._n == 0:
            return (np.zeros(0, dtype=np.float64),
                    np.zeros(0, dtype=np.float64))
        vals = self._vals[0] if len(self._vals) == 1 \
            else np.concatenate(self._vals)
        wts = np.concatenate([np.ones(v.size, dtype=np.float64)
                              if w is None else w
                              for v, w in zip(self._vals, self._wts)])
        return vals, wts

    def cdf(self, xs: np.ndarray) -> np.ndarray:
        """Weighted fraction of the stream at or below each x (empirical
        CDF; exact over retained values, weight-interpolation-free over
        compressed centroids). Zeros when the sketch is empty."""
        xs = np.asarray(xs, dtype=np.float64)
        if self._n == 0:
            return np.zeros(xs.shape, dtype=np.float64)
        v, w = self.values_weights()
        order = np.argsort(v, kind="stable")
        v, w = v[order], w[order]
        cw = np.cumsum(w)
        total = cw[-1]
        idx = np.searchsorted(v, xs, side="right")
        out = np.where(idx > 0, cw[np.maximum(idx - 1, 0)], 0.0)
        return out / total

    # ------------------------------------------------------- serialization
    def to_dict(self) -> dict:
        """JSON-safe round-trip form (`from_dict` restores a sketch that
        quantiles/cdfs BIT-IDENTICALLY and stays merge-compatible).
        Exact mode serializes the raw value stream (weights omitted, so
        the lazy-ones invariant survives the trip); compressed mode
        serializes the (value, weight) centroids. Values record their
        dtype so a float32 column stream quantiles on the same bits
        after reload."""
        if not self._exact and len(self._vals) > 1:
            # consolidate pending post-compression chunks into the single
            # sorted (value, weight) pair first: compressed-mode
            # `quantiles()` reads exactly that snapshot, so serializing
            # the raw pending lists would reload a sketch whose
            # quantiles differ from the live object's
            self._compress()
        if self._n == 0:
            vals = np.zeros(0, dtype=np.float64)
            wts = None
        else:
            vals = self._vals[0] if len(self._vals) == 1 \
                else np.concatenate(self._vals)
            wts = None if all(w is None for w in self._wts) else \
                np.concatenate([np.ones(v.size, dtype=np.float64)
                                if w is None else w
                                for v, w in zip(self._vals, self._wts)])
        out = {
            "buckets": self.buckets,
            "exact_cap": self.exact_cap,
            "n_seen": self.n_seen,
            "compressions": self.compressions,
            "exact": bool(self._exact),
            "dtype": str(vals.dtype),
            "values": np.asarray(vals, dtype=np.float64).tolist(),
        }
        if wts is not None:
            out["weights"] = np.asarray(wts, dtype=np.float64).tolist()
        return out

    @classmethod
    def from_dict(cls, d: dict) -> "FeatureSketch":
        sk = cls(buckets=int(d["buckets"]), exact_cap=int(d["exact_cap"]))
        vals = np.asarray(d["values"], dtype=np.float64).astype(
            np.dtype(d.get("dtype", "float64")))
        sk.n_seen = int(d["n_seen"])
        sk.compressions = int(d.get("compressions", 0))
        sk._exact = bool(d.get("exact", True))
        if vals.size:
            sk._vals = [vals]
            w = d.get("weights")
            sk._wts = [None if w is None
                       else np.asarray(w, dtype=np.float64)]
            sk._n = int(vals.size)
        return sk

    def quantiles(self, qs: np.ndarray) -> np.ndarray:
        """Quantile values at probabilities `qs`. Exact mode calls
        np.quantile on the raw values (bit parity with make_bins);
        compressed mode interpolates the weighted order statistics with
        the same (N-1)*q linear-rank convention."""
        if self._n == 0:
            return np.zeros(0, dtype=np.float64)
        if self._exact:
            return np.quantile(np.concatenate(self._vals), qs)
        v = np.asarray(self._vals[0], dtype=np.float64)
        w = self._wts[0]
        cw = np.cumsum(w)
        total = cw[-1]
        # expanded-rank positions: point i spans ranks [cw[i-1], cw[i])
        h = np.asarray(qs, dtype=np.float64) * (total - 1.0)
        lo = np.searchsorted(cw, np.floor(h), side="right")
        hi = np.searchsorted(cw, np.ceil(h), side="right")
        lo = np.clip(lo, 0, v.size - 1)
        hi = np.clip(hi, 0, v.size - 1)
        frac = h - np.floor(h)
        return v[lo] + (v[hi] - v[lo]) * frac


class DatasetSketch:
    """Per-feature sketches + streamed categorical label stats — one
    object per ingest pass 1, updated chunk by chunk, finalized into a
    `tree_impl.Binning` via `tree_impl.finalize_binning` (the SAME
    assembly the monolithic make_bins now runs, so the two paths cannot
    drift)."""

    def __init__(self, n_features: int,
                 categorical: Optional[Dict[int, int]] = None,
                 buckets: Optional[int] = None,
                 exact_cap: int = _EXACT_CAP):
        self.n_features = int(n_features)
        self.categorical = dict(categorical or {})
        self.features = {f: FeatureSketch(buckets, exact_cap)
                         for f in range(n_features)
                         if f not in self.categorical}
        # categorical slot -> (sum_y, count) per category id, streamed
        self._cat_sum = {f: np.zeros(int(card), dtype=np.float64)
                         for f, card in self.categorical.items()}
        self._cat_cnt = {f: np.zeros(int(card), dtype=np.int64)
                         for f, card in self.categorical.items()}
        self.n_rows = 0

    def update(self, X: np.ndarray, y: Optional[np.ndarray] = None) -> None:
        X = np.asarray(X)
        self.n_rows += int(X.shape[0])
        for f, sk in self.features.items():
            sk.update(X[:, f])
        if self.categorical and y is not None:
            # round labels through float32 FIRST: the monolithic path
            # computes category means from the float32 y32, and a raw
            # float64 accumulation here could order two near-tied
            # categories differently than make_bins
            y = np.asarray(y, dtype=np.float32).astype(np.float64)
        for f in self.categorical:
            card = int(self.categorical[f])
            ids = np.clip(X[:, f].astype(np.int64), 0, card - 1)
            if y is not None:
                self._cat_sum[f] += np.bincount(ids, weights=y,
                                                minlength=card)
            self._cat_cnt[f] += np.bincount(ids, minlength=card)

    def merge(self, other: "DatasetSketch") -> None:
        self.n_rows += other.n_rows
        for f, sk in self.features.items():
            sk.merge(other.features[f])
        for f in self.categorical:
            self._cat_sum[f] += other._cat_sum[f]
            self._cat_cnt[f] += other._cat_cnt[f]

    @property
    def exact(self) -> bool:
        return all(sk.exact for sk in self.features.values())

    # ------------------------------------------------------- serialization
    def to_dict(self) -> dict:
        """JSON-safe round-trip of the whole dataset sketch (per-feature
        quantile sketches + streamed categorical tables) — the baseline
        persistence format of obs/drift.py and a checkpointable summary
        for any interrupted ingest pass. `from_dict` restores a sketch
        that is merge-compatible and quantile-bit-identical."""
        return {
            "n_features": self.n_features,
            "n_rows": self.n_rows,
            "categorical": {str(f): int(c)
                            for f, c in sorted(self.categorical.items())},
            "features": {str(f): sk.to_dict()
                         for f, sk in sorted(self.features.items())},
            "cat_sum": {str(f): self._cat_sum[f].tolist()
                        for f in sorted(self.categorical)},
            "cat_cnt": {str(f): self._cat_cnt[f].tolist()
                        for f in sorted(self.categorical)},
        }

    @classmethod
    def from_dict(cls, d: dict) -> "DatasetSketch":
        categorical = {int(f): int(c)
                       for f, c in (d.get("categorical") or {}).items()}
        out = cls(int(d["n_features"]), categorical)
        out.n_rows = int(d.get("n_rows", 0))
        out.features = {int(f): FeatureSketch.from_dict(sd)
                        for f, sd in (d.get("features") or {}).items()}
        for f in out.categorical:
            out._cat_sum[f] = np.asarray(d["cat_sum"][str(f)],
                                         dtype=np.float64)
            out._cat_cnt[f] = np.asarray(d["cat_cnt"][str(f)],
                                         dtype=np.int64)
        return out

    def cat_means(self, with_labels: bool) -> Dict[int, np.ndarray]:
        """Per-category mean label (inf for absent categories) — the
        label-mean category ordering make_bins applies. Streamed sums
        accumulate in float64; pathological ties between categories with
        numerically-equal means may order differently than the
        monolithic pairwise-summed np.mean (documented deviation)."""
        out = {}
        for f in self.categorical:
            card = int(self.categorical[f])
            means = np.full(card, np.inf)
            seen = self._cat_cnt[f] > 0
            if with_labels:
                means[seen] = self._cat_sum[f][seen] / self._cat_cnt[f][seen]
            else:
                means[seen] = np.nonzero(seen)[0].astype(np.float64)
            out[f] = means
        return out

    def to_binning(self, max_bins: int, with_labels: bool = True,
                   max_categories_error: bool = True):
        """Finalize into (Binning, edge_list, out_dtype) through
        `tree_impl.finalize_binning` — one assembly for both paths."""
        from ..ml.tree_impl import finalize_binning
        probs = np.linspace(0, 1, max_bins + 1)[1:-1]
        cont_q = {f: sk.quantiles(probs) if sk.n_seen else None
                  for f, sk in self.features.items()}
        return finalize_binning(self.n_features, max_bins, self.categorical,
                                cont_q, self.cat_means(with_labels),
                                max_categories_error=max_categories_error)
