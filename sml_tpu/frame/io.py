"""DataFrameReader / DataFrameWriter: csv, parquet, json, delta, tables.

Covers the IO surface the course exercises: `spark.read.csv` with
header/inferSchema/multiLine/escape/sep (`ML 01:34`, `ML 14:85`),
`read.parquet/json`, `read.format("delta").load` + time-travel options
(`ML 00c:113,192-209`), and writes with mode/partitionBy/overwriteSchema/
mergeSchema (`ML 00c:59,78`), `saveAsTable` (`ML 00c:70`), multi-part
parquet (`Labs/ML 00L:89-90`).

Files are written one part-file per partition (part-00000…​), preserving the
partition layout contract that seeded randomSplit depends on.
"""

from __future__ import annotations

import glob
import json as _json
import os
from typing import Any, Dict, List, Optional, Union

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq

from ..conf import GLOBAL_CONF
from .dataframe import DataFrame, _split_rows
from .types import StructType, parse_schema


def _to_bool(v) -> bool:
    return str(v).strip().lower() in ("true", "1", "yes")


class DataFrameReader:
    def __init__(self, session):
        self._session = session
        self._format = "parquet"
        self._options: Dict[str, Any] = {}
        self._schema: Optional[StructType] = None

    def format(self, source: str) -> "DataFrameReader":  # noqa: A003
        self._format = source.lower()
        return self

    def option(self, key: str, value) -> "DataFrameReader":
        self._options[key] = value
        return self

    def options(self, **opts) -> "DataFrameReader":
        self._options.update(opts)
        return self

    def schema(self, s: Union[str, StructType]) -> "DataFrameReader":
        self._schema = parse_schema(s)
        return self

    def load(self, path: Optional[str] = None) -> DataFrame:
        fmt = self._format
        if fmt == "delta":
            from ..delta.table import read_delta
            return read_delta(path, self._session, self._options)
        if fmt == "parquet":
            return self.parquet(path)
        if fmt == "csv":
            return self.csv(path)
        if fmt == "json":
            return self.json(path)
        raise ValueError(f"unknown format {fmt}")

    # ------------------------------------------------------------- formats
    def csv(self, path: str, header: Optional[bool] = None, sep: Optional[str] = None,
            inferSchema: Optional[bool] = None, multiLine: Optional[bool] = None,
            escape: Optional[str] = None, schema: Optional[Union[str, StructType]] = None) -> DataFrame:
        o = self._options
        header = header if header is not None else _to_bool(o.get("header", False))
        sep = sep or o.get("sep", o.get("delimiter", ","))
        infer = inferSchema if inferSchema is not None else _to_bool(o.get("inferSchema", False))
        escape = escape or o.get("escape", None)
        if schema is not None:
            self._schema = parse_schema(schema)

        files = _expand(path, (".csv", ".txt", ".tsv"))
        kwargs: Dict[str, Any] = {"sep": sep, "header": 0 if header else None}
        if escape:
            kwargs["escapechar"] = escape if escape != '"' else None
            if escape == '"':
                kwargs["doublequote"] = True
        if self._schema is not None:
            kwargs["dtype"] = str  # read raw then coerce to the given schema
        elif not infer:
            kwargs["dtype"] = str

        parts: List[pd.DataFrame] = []
        for f in files:
            pdf = pd.read_csv(f, **kwargs)
            if not header:
                pdf.columns = [f"_c{i}" for i in range(len(pdf.columns))]
            if self._schema is not None:
                from .dataframe import coerce_to_schema
                pdf = coerce_to_schema(pdf, self._schema)
            parts.append(pdf.reset_index(drop=True))
        return self._spread(parts)

    def parquet(self, path: str) -> DataFrame:
        files = _expand(path, (".parquet",))
        parts = []
        for f in files:
            t = pq.read_table(f)
            parts.append(_arrow_to_pandas(t))
        return self._spread(parts, split_single=False)

    def json(self, path: str) -> DataFrame:
        files = _expand(path, (".json",))
        parts = []
        for f in files:
            rows = []
            with open(f) as fh:
                text = fh.read().strip()
            if text.startswith("["):
                rows = _json.loads(text)
            else:
                for line in text.splitlines():
                    line = line.strip()
                    if line:
                        rows.append(_json.loads(line))
            parts.append(pd.json_normalize(rows, max_level=0))
        return self._spread(parts)

    def table(self, name: str) -> DataFrame:
        return self._session.table(name)

    def delta(self, path: str) -> DataFrame:
        return self.format("delta").load(path)

    def _spread(self, parts: List[pd.DataFrame], split_single: bool = True) -> DataFrame:
        if not parts:
            return DataFrame.from_partitions([pd.DataFrame()], session=self._session)
        if len(parts) == 1 and split_single:
            return DataFrame.from_pandas(parts[0], session=self._session)
        return DataFrame.from_partitions(parts, session=self._session)


class DataFrameWriter:
    def __init__(self, df: DataFrame):
        self._df = df
        self._format = "parquet"
        self._mode = "errorifexists"
        self._options: Dict[str, Any] = {}
        self._partition_by: List[str] = []

    def format(self, source: str) -> "DataFrameWriter":  # noqa: A003
        self._format = source.lower()
        return self

    def mode(self, m: str) -> "DataFrameWriter":
        self._mode = m.lower()
        return self

    def option(self, key: str, value) -> "DataFrameWriter":
        self._options[key] = value
        return self

    def options(self, **opts) -> "DataFrameWriter":
        self._options.update(opts)
        return self

    def partitionBy(self, *cols: str) -> "DataFrameWriter":
        self._partition_by = list(cols)
        return self

    def repartition(self, n: int) -> "DataFrameWriter":
        self._df = self._df.repartition(n)
        return self

    # -------------------------------------------------------------- targets
    def save(self, path: str) -> None:
        if self._format == "delta":
            from ..delta.table import write_delta
            write_delta(self._df, path, mode=self._mode, options=self._options,
                        partition_by=self._partition_by)
            return
        if os.path.exists(path):
            if self._mode in ("error", "errorifexists"):
                raise FileExistsError(f"path already exists: {path}")
            if self._mode == "ignore":
                return
            if self._mode == "overwrite":
                import shutil
                shutil.rmtree(path, ignore_errors=True)
        os.makedirs(path, exist_ok=True)
        parts = self._df._materialize()
        if self._mode == "append":
            existing = len(glob.glob(os.path.join(path, "part-*")))
        else:
            existing = 0
        if self._partition_by:
            self._save_partitioned(path, parts)
            return
        for i, p in enumerate(parts):
            name = f"part-{existing + i:05d}"
            if self._format == "parquet":
                pq.write_table(_pandas_to_arrow(p), os.path.join(path, name + ".snappy.parquet"))
            elif self._format == "csv":
                p.to_csv(os.path.join(path, name + ".csv"), index=False,
                         header=_to_bool(self._options.get("header", False)))
            elif self._format == "json":
                p.to_json(os.path.join(path, name + ".json"), orient="records", lines=True)
            else:
                raise ValueError(f"unknown format {self._format}")
        open(os.path.join(path, "_SUCCESS"), "w").close()

    def _save_partitioned(self, path: str, parts) -> None:
        import uuid
        from .dataframe import _concat
        pdf = _concat(parts)
        for keys, g in pdf.groupby(self._partition_by, sort=False, dropna=False):
            if not isinstance(keys, tuple):
                keys = (keys,)
            sub = os.path.join(path, *[f"{k}={v}" for k, v in zip(self._partition_by, keys)])
            os.makedirs(sub, exist_ok=True)
            body = g.drop(columns=self._partition_by).reset_index(drop=True)
            # unique part name so append mode never clobbers existing files
            pq.write_table(_pandas_to_arrow(body),
                           os.path.join(sub, f"part-{uuid.uuid4().hex[:12]}.snappy.parquet"))
        open(os.path.join(path, "_SUCCESS"), "w").close()

    def parquet(self, path: str, mode: Optional[str] = None) -> None:
        if mode:
            self._mode = mode.lower()
        self.format("parquet").save(path)

    def csv(self, path: str, mode: Optional[str] = None, header: bool = False) -> None:
        if mode:
            self._mode = mode.lower()
        self._options.setdefault("header", header)
        self.format("csv").save(path)

    def json(self, path: str, mode: Optional[str] = None) -> None:
        if mode:
            self._mode = mode.lower()
        self.format("json").save(path)

    def delta(self, path: str) -> None:
        self.format("delta").save(path)

    def saveAsTable(self, name: str) -> None:
        session = self._df._session
        if session is None:
            raise RuntimeError("no session")
        path = session.catalog._table_path(name)
        self.save(path)
        session.catalog._register_table(name, path, self._format)


# ------------------------------------------------------ out-of-core chunks
from ._chunks import ChunkSource as _ChunkSource


class ParquetChunkSource(_ChunkSource):
    """`_chunks.ChunkSource` over parquet part-files WITHOUT whole-file
    materialization: `pyarrow.ParquetFile.iter_batches` streams row
    blocks of `chunk_rows`, each assembled into a (rows, F) float matrix
    + optional label column — the on-disk entry to the out-of-core data
    plane (docs/DATAPLANE.md). Files iterate in the same sorted order
    `DataFrameReader.parquet` reads them, so global row order (and with
    it chunk-local split membership) matches the materialized frame's
    row order."""

    def __init__(self, path: str, feature_cols: List[str],
                 label_col: Optional[str] = None,
                 chunk_rows: Optional[int] = None):
        self._files = _expand(path, (".parquet",))
        self.feature_cols = list(feature_cols)
        self.label_col = label_col
        self._chunk_rows = int(chunk_rows) if chunk_rows else None
        self.n_features = len(self.feature_cols)
        self.n_rows: Optional[int] = None

    def _iter_chunks(self):
        cols = self.feature_cols + ([self.label_col] if self.label_col
                                    else [])
        for f in self._files:
            pf = pq.ParquetFile(f)
            for batch in pf.iter_batches(batch_size=self.chunk_rows,
                                         columns=cols):
                pdf = batch.to_pandas()
                X = np.column_stack([
                    np.asarray(pdf[c], dtype=np.float64)
                    for c in self.feature_cols])
                y = (np.asarray(pdf[self.label_col], dtype=np.float64)
                     if self.label_col else None)
                yield X, y

    def fingerprint(self):
        sig = tuple((f, os.path.getmtime(f), os.path.getsize(f))
                    for f in self._files)
        return ("parquet", sig, tuple(self.feature_cols), self.label_col,
                self.chunk_rows)


def read_parquet_chunks(path: str, featureCols: List[str],
                        labelCol: Optional[str] = None,
                        chunkRows: Optional[int] = None) -> ParquetChunkSource:
    """Open a parquet file/directory/glob as a ChunkSource for the
    out-of-core data plane: `sml_tpu.ml._chunked.fit_ensemble_chunked`
    and friends consume it without the dataset ever being resident."""
    return ParquetChunkSource(path, featureCols, labelCol, chunkRows)


def _expand(path: str, exts) -> List[str]:
    """Path may be a file, a directory of part-files, or a glob."""
    if os.path.isfile(path):
        return [path]
    if os.path.isdir(path):
        out = []
        for root, _dirs, files in os.walk(path):
            for f in sorted(files):
                if f.startswith(("_", ".")):
                    continue
                if any(f.endswith(e) for e in exts) or "." not in f:
                    out.append(os.path.join(root, f))
        if out:
            return out
        raise FileNotFoundError(f"no data files under {path}")
    hits = sorted(glob.glob(path))
    if hits:
        return hits
    raise FileNotFoundError(path)


def _arrow_to_pandas(t: pa.Table) -> pd.DataFrame:
    pdf = t.to_pandas()
    # list<float> columns come back as numpy arrays per row → keep as object
    return pdf.reset_index(drop=True)


def _pandas_to_arrow(pdf: pd.DataFrame) -> pa.Table:
    cols = {}
    for c in pdf.columns:
        s = pdf[c]
        if s.dtype == object and len(s) and s.map(
                lambda v: isinstance(v, (list, np.ndarray)), na_action="ignore").fillna(False).any():
            cols[c] = pa.array([None if v is None else list(np.asarray(v, dtype=np.float32))
                                for v in s], type=pa.list_(pa.float32()))
        else:
            cols[c] = pa.array(s)
    return pa.table(cols)
