from . import functions
from .column import Column
from .dataframe import DataFrame
from .grouped import GroupedData
from .session import TpuSession, get_session
from .types import (BooleanType, DataType, DateType, DoubleType, FloatType,
                    IntegerType, LongType, Row, StringType, StructField,
                    StructType, TimestampType, VectorType, parse_schema)

__all__ = [
    "functions", "Column", "DataFrame", "GroupedData", "TpuSession",
    "get_session", "Row", "StructType", "StructField", "StringType",
    "DoubleType", "FloatType", "IntegerType", "LongType", "BooleanType",
    "TimestampType", "DateType", "VectorType", "DataType", "parse_schema",
]
