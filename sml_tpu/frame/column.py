"""Column expression AST evaluated per-partition against pandas blocks.

The subset of Spark's column algebra the courseware exercises (SURVEY §1 L1):
arithmetic/comparison/boolean operators, cast, alias, isNull, when/otherwise,
string ops (`translate`, `contains`), sort orders, and aggregate columns.
Each Column carries an eval function ``(pdf, ctx) -> pd.Series`` so the whole
expression tree runs vectorized on a partition block; partition-aware
expressions (rand, monotonically_increasing_id) read the EvalContext.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, List, Optional

import numpy as np
import pandas as pd

from .types import DataType, parse_type


@dataclass
class EvalContext:
    partition_index: int = 0
    n_partitions: int = 1
    row_offset: int = 0  # global row index of the partition's first row


def _as_series(v, pdf: pd.DataFrame) -> pd.Series:
    if isinstance(v, pd.Series):
        return v
    return pd.Series([v] * len(pdf), index=pdf.index)


class Column:
    def __init__(self, eval_fn: Callable[[pd.DataFrame, EvalContext], Any],
                 name: str, *,
                 agg: Optional[Callable[[pd.Series], Any]] = None,
                 sort_desc: Optional[bool] = None,
                 children: Optional[List["Column"]] = None):
        self._eval_fn = eval_fn
        self._name = name
        self._agg = agg            # set ⇒ aggregate column (groupBy.agg / select-agg)
        self._sort_desc = sort_desc
        self._children = children or []

    # -- evaluation --
    def _eval(self, pdf: pd.DataFrame, ctx: Optional[EvalContext] = None) -> pd.Series:
        ctx = ctx or EvalContext()
        out = self._eval_fn(pdf, ctx)
        return _as_series(out, pdf)

    # -- naming --
    def alias(self, name: str) -> "Column":
        c = Column(self._eval_fn, name, agg=self._agg, sort_desc=self._sort_desc,
                   children=self._children)
        return c

    name = alias

    # -- operator helpers --
    def _bin(self, other, fn, sym, reverse=False) -> "Column":
        other_c = other if isinstance(other, Column) else LitColumn(other)

        def ev(pdf, ctx):
            a = self._eval(pdf, ctx)
            b = other_c._eval(pdf, ctx)
            return fn(b, a) if reverse else fn(a, b)

        l, r = (other_c._name, self._name) if reverse else (self._name, other_c._name)
        return Column(ev, f"({l} {sym} {r})")

    def __add__(self, o):
        return self._bin(o, lambda a, b: a + b, "+")

    def __radd__(self, o):
        return self._bin(o, lambda a, b: a + b, "+", reverse=True)

    def __sub__(self, o):
        return self._bin(o, lambda a, b: a - b, "-")

    def __rsub__(self, o):
        return self._bin(o, lambda a, b: a - b, "-", reverse=True)

    def __mul__(self, o):
        return self._bin(o, lambda a, b: a * b, "*")

    def __rmul__(self, o):
        return self._bin(o, lambda a, b: a * b, "*", reverse=True)

    def __truediv__(self, o):
        return self._bin(o, lambda a, b: a / b, "/")

    def __rtruediv__(self, o):
        return self._bin(o, lambda a, b: a / b, "/", reverse=True)

    def __neg__(self):
        return Column(lambda pdf, ctx: -self._eval(pdf, ctx), f"(- {self._name})")

    def __pow__(self, o):
        return self._bin(o, lambda a, b: a ** b, "**")

    def __mod__(self, o):
        return self._bin(o, lambda a, b: a % b, "%")

    def __eq__(self, o):  # type: ignore[override]
        return self._bin(o, lambda a, b: a == b, "=")

    def __ne__(self, o):  # type: ignore[override]
        return self._bin(o, lambda a, b: a != b, "!=")

    def __lt__(self, o):
        return self._bin(o, lambda a, b: a < b, "<")

    def __le__(self, o):
        return self._bin(o, lambda a, b: a <= b, "<=")

    def __gt__(self, o):
        return self._bin(o, lambda a, b: a > b, ">")

    def __ge__(self, o):
        return self._bin(o, lambda a, b: a >= b, ">=")

    def __and__(self, o):
        return self._bin(o, lambda a, b: a.fillna(False).astype(bool) & b.fillna(False).astype(bool)
                         if isinstance(a, pd.Series) and isinstance(b, pd.Series)
                         else a & b, "AND")

    def __or__(self, o):
        return self._bin(o, lambda a, b: a.fillna(False).astype(bool) | b.fillna(False).astype(bool)
                         if isinstance(a, pd.Series) and isinstance(b, pd.Series)
                         else a | b, "OR")

    def __invert__(self):
        return Column(lambda pdf, ctx: ~self._eval(pdf, ctx).fillna(False).astype(bool),
                      f"(NOT {self._name})")

    def __hash__(self):
        return id(self)

    # -- null / membership --
    def isNull(self) -> "Column":
        return Column(lambda pdf, ctx: self._eval(pdf, ctx).isna(),
                      f"({self._name} IS NULL)")

    def isNotNull(self) -> "Column":
        return Column(lambda pdf, ctx: self._eval(pdf, ctx).notna(),
                      f"({self._name} IS NOT NULL)")

    def isin(self, *values) -> "Column":
        vals = list(values[0]) if len(values) == 1 and isinstance(values[0], (list, tuple, set)) else list(values)
        return Column(lambda pdf, ctx: self._eval(pdf, ctx).isin(vals),
                      f"({self._name} IN ...)")

    def between(self, low, high) -> "Column":
        return (self >= low) & (self <= high)

    # -- strings --
    def contains(self, sub: str) -> "Column":
        return Column(lambda pdf, ctx: self._eval(pdf, ctx).astype(str).str.contains(sub, regex=False),
                      f"contains({self._name}, {sub})")

    def startswith(self, p: str) -> "Column":
        return Column(lambda pdf, ctx: self._eval(pdf, ctx).astype(str).str.startswith(p),
                      f"startswith({self._name}, {p})")

    def endswith(self, p: str) -> "Column":
        return Column(lambda pdf, ctx: self._eval(pdf, ctx).astype(str).str.endswith(p),
                      f"endswith({self._name}, {p})")

    def like(self, pattern: str) -> "Column":
        regex = "^" + pattern.replace("%", ".*").replace("_", ".") + "$"
        return Column(lambda pdf, ctx: self._eval(pdf, ctx).astype(str).str.match(regex),
                      f"({self._name} LIKE {pattern})")

    def substr(self, start: int, length: int) -> "Column":
        return Column(lambda pdf, ctx: self._eval(pdf, ctx).astype(str).str.slice(start - 1, start - 1 + length),
                      f"substr({self._name}, {start}, {length})")

    # -- cast --
    def cast(self, to) -> "Column":
        t: DataType = parse_type(to) if isinstance(to, str) else to

        def ev(pdf, ctx):
            s = self._eval(pdf, ctx)
            tn = t.simpleString()
            if tn in ("double", "float"):
                out = pd.to_numeric(s, errors="coerce")
                return out.astype(np.float64 if tn == "double" else np.float32)
            if tn in ("int", "bigint"):
                out = pd.to_numeric(s, errors="coerce")
                # Spark cast truncates toward zero; nulls stay null
                if out.isna().any():
                    return np.trunc(out)
                return out.astype(np.int64 if tn == "bigint" else np.int32)
            if tn == "boolean":
                return cast_to_boolean(s)
            if tn == "string":
                return s.map(lambda v: None if v is None or (isinstance(v, float) and np.isnan(v)) else str(v))
            if tn == "timestamp":
                return pd.to_datetime(s, errors="coerce")
            return s

        return Column(ev, f"CAST({self._name} AS {t.simpleString()})")

    astype = cast

    # -- when/otherwise chaining: only valid on CaseWhenColumn (functions.when) --
    def otherwise(self, value) -> "Column":
        raise TypeError("otherwise() can only follow when(); use functions.when(...)")

    def when(self, condition: "Column", value) -> "Column":
        raise TypeError("when() chaining can only follow functions.when(...)")

    # -- sort order --
    def desc(self) -> "Column":
        return Column(self._eval_fn, self._name, agg=self._agg, sort_desc=True)

    def asc(self) -> "Column":
        return Column(self._eval_fn, self._name, agg=self._agg, sort_desc=False)

    def __repr__(self):
        return f"Column<'{self._name}'>"


class CaseWhenColumn(Column):
    """First-match CASE WHEN semantics: a matched branch keeps its value even
    when that value is null (null is not used as an 'unmatched' marker)."""

    def __init__(self, branches, otherwise_col: Optional["Column"] = None, name=None):
        self._branches = list(branches)  # [(cond Column, value Column)]
        self._otherwise = otherwise_col
        label = name or ("CASE " + " ".join(
            f"WHEN {c._name} THEN {v._name}" for c, v in self._branches) +
            (f" ELSE {self._otherwise._name}" if self._otherwise else "") + " END")
        super().__init__(self._eval_case, label)

    def _eval_case(self, pdf: pd.DataFrame, ctx: EvalContext):
        result = pd.Series([None] * len(pdf), index=pdf.index, dtype=object)
        matched = pd.Series(False, index=pdf.index)
        for cond, val in self._branches:
            sel = cond._eval(pdf, ctx).fillna(False).astype(bool) & ~matched
            if sel.any():
                result[sel] = _as_series(val._eval(pdf, ctx), pdf)[sel]
            matched |= sel
        if self._otherwise is not None:
            rest = ~matched
            if rest.any():
                result[rest] = _as_series(self._otherwise._eval(pdf, ctx), pdf)[rest]
        return result.infer_objects()

    def when(self, condition: "Column", value) -> "CaseWhenColumn":
        val_c = value if isinstance(value, Column) else LitColumn(value)
        return CaseWhenColumn(self._branches + [(condition, val_c)], self._otherwise)

    def otherwise(self, value) -> "CaseWhenColumn":
        other = value if isinstance(value, Column) else LitColumn(value)
        return CaseWhenColumn(self._branches, other)


class NamedColumn(Column):
    """Reference to an existing column by name. `col("*")` is the star
    reference (`Solutions/Labs/ML 00L`: `df.select(col("*"), ...)`) —
    select() expands it to all input columns; evaluating it anywhere else
    is an error."""

    def __init__(self, name: str):
        if name == "*":
            def star_eval(pdf, ctx):
                raise ValueError(
                    "col('*') can only be expanded inside select()")
            super().__init__(star_eval, name)
        else:
            super().__init__(lambda pdf, ctx: pdf[name], name)
        self.ref = name


class LitColumn(Column):
    def __init__(self, value: Any):
        super().__init__(lambda pdf, ctx: _as_series(value, pdf), str(value))
        self.value = value


_TRUE_STRINGS = {"true", "t", "yes", "y", "1"}
_FALSE_STRINGS = {"false", "f", "no", "n", "0"}


def cast_to_boolean(s: pd.Series) -> pd.Series:
    """SQL cast-to-boolean: recognized string literals map to bool, anything
    else becomes null; numerics are nonzero-is-true."""
    if s.dtype.kind in "ifu":
        return s != 0
    if s.dtype.kind == "b":
        return s

    def conv(v):
        if v is None or (isinstance(v, float) and np.isnan(v)):
            return None
        if isinstance(v, (bool, np.bool_)):
            return bool(v)
        if isinstance(v, (int, float, np.integer, np.floating)):
            return v != 0
        t = str(v).strip().lower()
        if t in _TRUE_STRINGS:
            return True
        if t in _FALSE_STRINGS:
            return False
        return None

    return s.map(conv)


def ensure_column(x) -> Column:
    if isinstance(x, Column):
        return x
    if isinstance(x, str):
        return NamedColumn(x)
    return LitColumn(x)
