"""Partitioned, lazily-evaluated DataFrame (the L1 engine subset — SURVEY §7.2).

Design (TPU-first, no JVM):
- A DataFrame is a recipe (``_compute``) producing a list of pandas blocks
  ("partitions"); transformations compose recipes and nothing runs until an
  action (count/collect/show/write) — the laziness contract demonstrated in
  `SML/ML 00b - Spark Review.py:45`. First materialization is memoized (cache
  semantics are therefore `.cache()`-compatible).
- Narrow ops run per-partition with an EvalContext (partition index / global
  row offset) so partition-sensitive semantics — seeded `randomSplit`
  (`ML 02:38-52`), `rand`, `monotonically_increasing_id` — are deterministic
  and *documented* functions of (seed, partition layout), like the engine the
  course demonstrates.
- Wide ops (groupBy/join/orderBy/dropDuplicates) shuffle via Murmur3 hash
  partitioning (native kernel `sml_tpu/native/murmur3.cc`) into
  `sml.shuffle.partitions` blocks.
- Numeric compute that matters (ML fit/transform) never happens here: the ML
  layer stages columns into HBM sharded over the mesh
  (`sml_tpu/parallel/mesh.py`) and runs jitted XLA programs.
"""

from __future__ import annotations

import bisect
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence, Tuple, Union

import numpy as np
import pandas as pd

from ..conf import GLOBAL_CONF
from ..native.hashing import hash_columns, hash_partition_ids
from ..utils.profiler import PROFILER
from .column import Column, EvalContext, NamedColumn, ensure_column
from .types import Row, StructType, infer_schema_from_pandas, parse_schema

Partitions = List[pd.DataFrame]


def _split_rows(pdf: pd.DataFrame, n: int) -> Partitions:
    n = max(1, int(n))
    idx = np.array_split(np.arange(len(pdf)), n)
    return [pdf.iloc[ix].reset_index(drop=True) for ix in idx]


def _rows_from_pdf(pdf: pd.DataFrame) -> List[Row]:
    cols = list(pdf.columns)
    out = []
    for t in pdf.itertuples(index=False):
        vals = {c: (None if isinstance(v, float) and np.isnan(v) else v)
                for c, v in zip(cols, t)}
        out.append(Row(**vals))
    return out


def _concat(parts: Partitions) -> pd.DataFrame:
    parts = [p for p in parts if len(p.columns)]
    if not parts:
        return pd.DataFrame()
    return pd.concat(parts, ignore_index=True)


def coerce_to_schema(pdf: pd.DataFrame, schema: StructType) -> pd.DataFrame:
    """Project + cast a pandas block to a StructType (schema enforcement at
    pandas-fn boundaries, mirroring `mapInPandas`/`applyInPandas` contracts)."""
    # fast path: already exactly conforming (the common case for UDFs that
    # build their output frames from numpy results)
    names = [f.name for f in schema.fields]
    if list(pdf.columns) == names:
        want = {"double": "float64", "float": "float32",
                "bigint": "int64", "int": "int32", "boolean": "bool"}
        if all(want.get(f.dataType.simpleString()) == str(pdf[f.name].dtype)
               for f in schema.fields):
            return pdf.reset_index(drop=True)
    out = {}
    for f in schema.fields:
        if f.name in pdf.columns:
            s = pdf[f.name]
        else:
            s = pd.Series([None] * len(pdf))
        t = f.dataType.simpleString()
        if t in ("double", "float"):
            s = pd.to_numeric(s, errors="coerce").astype(np.float64 if t == "double" else np.float32)
        elif t in ("int", "bigint"):
            s = pd.to_numeric(s, errors="coerce")
            if not s.isna().any():
                s = s.astype(np.int64 if t == "bigint" else np.int32)
        elif t == "boolean":
            from .column import cast_to_boolean
            s = cast_to_boolean(s)
        elif t == "string":
            s = s.map(lambda v: None if v is None or (isinstance(v, float) and np.isnan(v)) else str(v))
        s = s.reset_index(drop=True)
        out[f.name] = s
    return pd.DataFrame(out)


class DataFrame:
    # real class attribute so `getattr(df, "isStreaming", False)` probes see
    # False instead of __getattr__'s NamedColumn fallback (which is TRUTHY —
    # it silently disabled every isStreaming-guarded fast path, r4)
    isStreaming = False

    def __init__(self, compute: Callable[[], Partitions],
                 session: Optional["TpuSession"] = None,
                 schema: Optional[StructType] = None,
                 op: Optional[str] = None):
        if op is None:
            # default tag: the engine method that built this frame — names
            # the `materialize.<op>` profiler spans (MLE 05-style per-op
            # engine observability) without threading labels everywhere
            import sys as _sys
            op = _sys._getframe(1).f_code.co_name
            if op in ("_derive", "_derive_rowlocal", "from_pandas",
                      "from_partitions"):
                op = _sys._getframe(2).f_code.co_name
        self._op = op
        self._compute = compute
        self._session = session
        self._schema_hint = schema
        self._parts: Optional[Partitions] = None
        self._offsets: Optional[List[int]] = None
        self._pdf_cache: Optional[pd.DataFrame] = None
        # ML column attributes (e.g. categorical cardinality set by
        # StringIndexer, per-slot metadata set by VectorAssembler) — the
        # equivalent of Spark ML's column metadata that tree learners read
        # for maxBins semantics (`ML 06:91-126`).
        self._ml_attrs: Dict[str, Any] = {}
        # (weights, seed) -> child frames: repeated identical randomSplits
        # return the same (immutable, deterministic) children so downstream
        # caches stay hot — see randomSplit
        self._split_memo: Dict[tuple, list] = {}

    # ------------------------------------------------------------------ core
    @classmethod
    def from_pandas(cls, pdf: pd.DataFrame, session=None,
                    num_partitions: Optional[int] = None,
                    schema: Optional[StructType] = None) -> "DataFrame":
        if num_partitions is None:
            num_partitions = GLOBAL_CONF.getInt("sml.default.parallelism")
        pdf = pdf.reset_index(drop=True)
        n = min(num_partitions, max(1, len(pdf)))
        return cls(lambda: _split_rows(pdf, n), session=session, schema=schema)

    @classmethod
    def from_partitions(cls, parts: Partitions, session=None,
                        schema: Optional[StructType] = None) -> "DataFrame":
        return cls(lambda: parts, session=session, schema=schema)

    def _materialize(self) -> Partitions:
        if self._parts is None:
            with PROFILER.span(f"materialize.{self._op}"):
                self._parts = self._compute()
                if not self._parts:
                    self._parts = [pd.DataFrame()]
            offs, acc = [], 0
            for p in self._parts:
                offs.append(acc)
                acc += len(p)
            self._offsets = offs
            # Release the recipe: the closure retains the whole parent chain,
            # which would otherwise pin every intermediate's partitions in
            # memory for the lifetime of this frame.
            self._compute = None  # type: ignore[assignment]
            # An evaluator-pushdown hook is dead once the frame is
            # materialized (the evaluator only consults it pre-materialize);
            # drop it so it stops pinning the parent frame's partitions.
            if self.__dict__.get("_fused_eval") is not None:
                self.__dict__["_fused_eval"] = None
        return self._parts

    def _contexts(self) -> List[EvalContext]:
        parts = self._materialize()
        return [EvalContext(i, len(parts), self._offsets[i]) for i in range(len(parts))]

    def _derive(self, fn: Callable[[pd.DataFrame, EvalContext], pd.DataFrame],
                schema: Optional[StructType] = None) -> "DataFrame":
        parent = self

        def compute() -> Partitions:
            parts = parent._materialize()
            ctxs = parent._contexts()
            return [fn(p, c) for p, c in zip(parts, ctxs)]

        out = DataFrame(compute, session=self._session, schema=schema)
        out._ml_attrs = dict(self._ml_attrs)
        return out

    def _derive_rowlocal(self, fn: Callable[[pd.DataFrame, EvalContext], pd.DataFrame],
                         schema: Optional[StructType] = None) -> "DataFrame":
        """_derive for ROW-LOCAL, row-count-preserving fns (model predicts):
        applies fn ONCE over the concatenated partitions and splits the
        result back on the same boundaries. One device round trip instead of
        one per partition — on the TPU tunnel each round trip has a fixed
        D2H latency, so per-partition prediction was paying it 8x."""
        parent = self

        def compute() -> Partitions:
            parts = parent._materialize()
            if len(parts) <= 1:
                ctxs = parent._contexts()
                return [fn(p, c) for p, c in zip(parts, ctxs)]
            whole = pd.concat(parts, ignore_index=True)
            out = fn(whole, EvalContext(0, 1, 0))
            if len(out) != len(whole):
                raise ValueError("_derive_rowlocal fn must preserve row count")
            bounds = np.cumsum([len(p) for p in parts])[:-1]
            lo = 0
            split = []
            for hi in list(bounds) + [len(out)]:
                split.append(out.iloc[lo:hi].reset_index(drop=True))
                lo = hi
            return split

        out = DataFrame(compute, session=self._session, schema=schema)
        out._ml_attrs = dict(self._ml_attrs)
        return out

    # ------------------------------------------------------------ metadata
    @property
    def schema(self) -> StructType:
        if self._schema_hint is not None:
            return self._schema_hint
        parts = self._materialize()
        biggest = max(parts, key=len)
        sch = infer_schema_from_pandas(biggest)
        self._schema_hint = sch
        return sch

    @property
    def columns(self) -> List[str]:
        return self.schema.names

    @property
    def dtypes(self) -> List[Tuple[str, str]]:
        return [(f.name, f.dataType.simpleString()) for f in self.schema.fields]

    def printSchema(self) -> None:
        print(self.schema.treeString())

    def __getitem__(self, item) -> Column:
        return NamedColumn(item)

    def __getattr__(self, item) -> Column:
        if item.startswith("_"):
            raise AttributeError(item)
        sch = self.__dict__.get("_schema_hint")
        if sch is not None and item not in sch.names:
            raise AttributeError(item)
        return NamedColumn(item)

    # ------------------------------------------------------------- actions
    def count(self) -> int:
        return sum(len(p) for p in self._materialize())

    def isEmpty(self) -> bool:
        return self.count() == 0

    def toPandas(self) -> pd.DataFrame:
        """Concatenate all partitions; the result is memoized per frame.
        Frames are immutable once materialized, and under pandas>=3
        copy-on-write the returned shallow copy is mutation-safe for the
        caller, so repeated toPandas (every pipeline stage fit calls it)
        costs one concat total instead of one per call."""
        if self._pdf_cache is None:
            self._pdf_cache = _concat(self._materialize()).reset_index(drop=True)
        if int(pd.__version__.split(".")[0]) < 3 \
                and pd.options.mode.copy_on_write is not True:
            # "warn" keeps legacy write-through semantics: not CoW-safe
            # someone disabled the CoW mode the package enabled at import:
            # a shallow copy would share mutable buffers with the cache
            return self._pdf_cache.copy(deep=True)
        return self._pdf_cache.copy(deep=False)

    def collect(self) -> List[Row]:
        return _rows_from_pdf(self.toPandas())

    def first(self) -> Optional[Row]:
        rows = self.limit(1).collect()
        return rows[0] if rows else None

    def head(self, n: int = 1):
        rows = self.limit(n).collect()
        if n == 1:
            return rows[0] if rows else None
        return rows

    def take(self, n: int) -> List[Row]:
        return self.limit(n).collect()

    def tail(self, n: int) -> List[Row]:
        """Last n rows as Rows (Spark's driver-collected tail)."""
        if n < 0:
            raise ValueError(f"tail expects a non-negative n, got {n}")
        pdf = self.toPandas()
        return _rows_from_pdf(pdf.iloc[max(0, len(pdf) - n):])

    def show(self, n: int = 20, truncate: bool = True) -> None:
        pdf = self.limit(n).toPandas()
        if truncate:
            pdf = pdf.map(lambda v: (str(v)[:17] + "...") if len(str(v)) > 20 else v)
        try:
            print(pdf.to_string(index=False))
        except Exception:
            print(pdf)

    # ------------------------------------------------------ narrow transforms
    def select(self, *cols) -> "DataFrame":
        if len(cols) == 1 and isinstance(cols[0], (list, tuple)):
            cols = tuple(cols[0])
        agg_cols = [c for c in cols if isinstance(c, Column) and c._agg is not None]
        if agg_cols and len(agg_cols) == len(cols):
            from .grouped import GroupedData
            return GroupedData(self, []).agg(*agg_cols)

        def fn(pdf: pd.DataFrame, ctx: EvalContext) -> pd.DataFrame:
            out: Dict[str, pd.Series] = {}
            for c in cols:
                if (isinstance(c, str) and c == "*") or \
                        (isinstance(c, NamedColumn) and c.ref == "*"):
                    for name in pdf.columns:
                        out[name] = pdf[name]
                    continue
                cc = ensure_column(c)
                out[cc._name] = cc._eval(pdf, ctx).reset_index(drop=True)
            return pd.DataFrame(out)

        return self._derive(fn)

    def selectExpr(self, *exprs: str) -> "DataFrame":
        from .sql import parse_simple_expr
        return self.select(*[parse_simple_expr(e) for e in exprs])

    def withColumn(self, name: str, col: Column) -> "DataFrame":
        cc = ensure_column(col)

        def fn(pdf, ctx):
            out = pdf.copy(deep=False)  # CoW: column adds never touch the parent
            out[name] = cc._eval(pdf, ctx).reset_index(drop=True).values
            return out

        out = self._derive(fn)
        # evaluator-pushdown propagation: replacing the prediction column
        # with a known elementwise link of ITSELF (the ML 11 shape —
        # train on log(price), exponentiate predictions, evaluate on the
        # original scale) keeps the fused-eval hook alive with the link
        # composed into its device program
        hook = getattr(self, "_fused_eval", None)
        unary = getattr(cc, "_unary_of", None)
        if hook is not None and unary is not None and unary[1] == name:
            # with_link verifies `name` is the hook's OWN prediction column
            # (a link over any other column must kill the hook, not wrap it)
            linked = hook.with_link(unary[0], name)
            if linked is not None:
                out._fused_eval = linked
        return out

    def withColumnRenamed(self, old: str, new: str) -> "DataFrame":
        return self._derive(lambda pdf, ctx: pdf.rename(columns={old: new}))

    def drop(self, *cols) -> "DataFrame":
        names = [c._name if isinstance(c, Column) else c for c in cols]
        return self._derive(lambda pdf, ctx: pdf.drop(columns=[c for c in names if c in pdf.columns]))

    def filter(self, condition: Union[Column, str]) -> "DataFrame":
        if isinstance(condition, str):
            from .sql import parse_simple_expr
            condition = parse_simple_expr(condition)

        def fn(pdf, ctx):
            mask = condition._eval(pdf, ctx).fillna(False).astype(bool)
            return pdf[mask.values].reset_index(drop=True)

        return self._derive(fn)

    where = filter

    def limit(self, n: int) -> "DataFrame":
        parent = self

        def compute() -> Partitions:
            taken, out = 0, []
            for p in parent._materialize():
                if taken >= n:
                    break
                take = min(n - taken, len(p))
                out.append(p.iloc[:take].reset_index(drop=True))
                taken += take
            return out or [pd.DataFrame()]

        return DataFrame(compute, session=self._session)

    def toDF(self, *names: str) -> "DataFrame":
        def fn(pdf, ctx):
            out = pdf.copy(deep=False)  # CoW: column adds never touch the parent
            out.columns = list(names)
            return out
        return self._derive(fn)

    def alias(self, name: str) -> "DataFrame":
        return self

    def dropna(self, how: str = "any", thresh: Optional[int] = None,
               subset: Optional[Sequence[str]] = None) -> "DataFrame":
        kwargs: Dict[str, Any] = {"thresh": thresh} if thresh is not None else {"how": how}
        return self._derive(lambda pdf, ctx: pdf.dropna(subset=subset, **kwargs)
                            .reset_index(drop=True))

    def fillna(self, value, subset: Optional[Sequence[str]] = None) -> "DataFrame":
        def fn(pdf, ctx):
            out = pdf.copy(deep=False)  # CoW: column adds never touch the parent
            if isinstance(value, dict):
                return out.fillna(value)
            cols = subset or out.columns
            for c in cols:
                if c in out.columns:
                    s = out[c]
                    if isinstance(value, (int, float)) and s.dtype.kind not in "ifu":
                        continue  # Spark: numeric fill only touches numeric cols
                    if isinstance(value, str) and s.dtype.kind in "ifub":
                        continue
                    out[c] = s.fillna(value)
            return out
        return self._derive(fn)

    @property
    def na(self) -> "DataFrameNaFunctions":
        return DataFrameNaFunctions(self)

    @property
    def stat(self) -> "DataFrameStatFunctions":
        return DataFrameStatFunctions(self)

    # -------------------------------------------------------- wide transforms
    def distinct(self) -> "DataFrame":
        return self.dropDuplicates()

    def dropDuplicates(self, subset: Optional[Sequence[str]] = None) -> "DataFrame":
        parent = self

        def compute() -> Partitions:
            with PROFILER.span("shuffle.dropDuplicates"):
                pdf = _concat(parent._materialize())
                pdf = pdf.drop_duplicates(subset=subset, keep="first").reset_index(drop=True)
                return _hash_repartition(pdf, subset or list(pdf.columns),
                                         GLOBAL_CONF.getInt("sml.shuffle.partitions"))

        return DataFrame(compute, session=self._session)

    drop_duplicates = dropDuplicates

    def union(self, other: "DataFrame") -> "DataFrame":
        parent = self

        def compute() -> Partitions:
            a = parent._materialize()
            b = other._materialize()
            cols = list(a[0].columns) if len(a[0].columns) else list(b[0].columns)

            def align(p: pd.DataFrame) -> pd.DataFrame:
                # Spark union is positional: rename right-side columns to the
                # left's names by position
                q = p.copy()
                q.columns = cols[:len(q.columns)]
                return q

            return [p for p in a if len(p)] + [align(p) for p in b if len(p)] or [pd.DataFrame()]

        return DataFrame(compute, session=self._session)

    unionAll = union

    def unionByName(self, other: "DataFrame", allowMissingColumns: bool = False) -> "DataFrame":
        parent = self

        def compute() -> Partitions:
            a = _concat(parent._materialize())
            b = _concat(other._materialize())
            if allowMissingColumns:
                out = pd.concat([a, b], ignore_index=True)
            else:
                out = pd.concat([a, b[list(a.columns)]], ignore_index=True)
            return _split_rows(out, GLOBAL_CONF.getInt("sml.shuffle.partitions"))

        return DataFrame(compute, session=self._session)

    def join(self, other: "DataFrame", on=None, how: str = "inner") -> "DataFrame":
        parent = self

        def compute() -> Partitions:
            with PROFILER.span("shuffle.join"):
                left = _concat(parent._materialize())
                right = _concat(other._materialize())
                keys = [on] if isinstance(on, str) else list(on) if on is not None else None
                hw = {"inner": "inner", "left": "left", "left_outer": "left",
                      "right": "right", "right_outer": "right", "outer": "outer",
                      "full": "outer", "full_outer": "outer", "cross": "cross"}.get(how)
                if hw is None and how in ("left_semi", "leftsemi"):
                    mask = left[keys].apply(tuple, axis=1).isin(right[keys].apply(tuple, axis=1))
                    out = left[mask].reset_index(drop=True)
                elif hw is None and how in ("left_anti", "leftanti"):
                    mask = left[keys].apply(tuple, axis=1).isin(right[keys].apply(tuple, axis=1))
                    out = left[~mask].reset_index(drop=True)
                elif hw == "cross":
                    out = left.merge(right, how="cross")
                else:
                    out = left.merge(right, on=keys, how=hw, suffixes=("", "_r"))
                nparts = GLOBAL_CONF.getInt("sml.shuffle.partitions")
                if keys:
                    return _hash_repartition(out, keys, nparts)
                return _split_rows(out, nparts)

        return DataFrame(compute, session=self._session)

    def crossJoin(self, other: "DataFrame") -> "DataFrame":
        return self.join(other, on=None, how="cross")

    def orderBy(self, *cols, ascending=None) -> "DataFrame":
        parent = self
        if len(cols) == 1 and isinstance(cols[0], (list, tuple)):
            cols = tuple(cols[0])

        def compute() -> Partitions:
            with PROFILER.span("shuffle.sort"):
                pdf = _concat(parent._materialize())
                by, asc_flags = [], []
                tmp_cols = []
                for i, c in enumerate(cols):
                    if isinstance(c, str):
                        by.append(c)
                        asc_flags.append(True)
                    else:
                        tmp = f"__sort_{i}"
                        pdf[tmp] = c._eval(pdf, EvalContext()).values
                        by.append(tmp)
                        tmp_cols.append(tmp)
                        asc_flags.append(not bool(c._sort_desc))
                if ascending is not None:
                    if isinstance(ascending, (list, tuple)):
                        asc_flags = list(ascending)
                    else:
                        asc_flags = [bool(ascending)] * len(by)
                pdf = pdf.sort_values(by=by, ascending=asc_flags, kind="mergesort")
                pdf = pdf.drop(columns=tmp_cols).reset_index(drop=True)
                return _split_rows(pdf, max(1, len(parent._materialize())))

        return DataFrame(compute, session=self._session)

    sort = orderBy

    def groupBy(self, *cols) -> "GroupedData":
        from .grouped import GroupedData
        if len(cols) == 1 and isinstance(cols[0], (list, tuple)):
            cols = tuple(cols[0])
        return GroupedData(self, [c if isinstance(c, Column) else NamedColumn(c) for c in cols])

    groupby = groupBy

    def agg(self, *cols) -> "DataFrame":
        return self.groupBy().agg(*cols)

    # ----------------------------------------------------- partitioning ops
    def repartition(self, num: Union[int, str, Column], *cols) -> "DataFrame":
        parent = self
        if not isinstance(num, int):
            cols = (num,) + cols
            num = GLOBAL_CONF.getInt("sml.shuffle.partitions")
        key_names = [c if isinstance(c, str) else c._name for c in cols]

        def compute() -> Partitions:
            with PROFILER.span("shuffle.repartition"):
                pdf = _concat(parent._materialize())
                if key_names:
                    return _hash_repartition(pdf, key_names, num)
                # round-robin exchange
                if len(pdf) == 0:
                    return [pd.DataFrame(columns=pdf.columns) for _ in range(num)]
                ids = np.arange(len(pdf)) % num
                return [pdf[ids == i].reset_index(drop=True) for i in range(num)]

        return DataFrame(compute, session=self._session)

    def coalesce(self, num: int) -> "DataFrame":
        parent = self

        def compute() -> Partitions:
            parts = parent._materialize()
            if num >= len(parts):
                return parts
            groups = np.array_split(np.arange(len(parts)), num)
            return [_concat([parts[i] for i in g]) for g in groups]

        return DataFrame(compute, session=self._session)

    @property
    def rdd(self) -> "_RDDShim":
        return _RDDShim(self)

    # -------------------------------------------------------------- sampling
    def randomSplit(self, weights: Sequence[float], seed: Optional[int] = None) -> List["DataFrame"]:
        """Spark's split, draw for draw (`frame/sampling.py`): each
        partition is locally sorted (Dataset.randomSplit's determinism
        sort), then every weight cell keeps row i iff its
        `XORShiftRandom(seed + partitionIndex)` uniform lands in the
        cell's [lo, hi) — so the result depends on the partition layout
        exactly as the course demonstrates (`ML 02:38-52`), with Spark's
        published sampler semantics (BernoulliCellSampler over the
        hashSeed-scrambled XORShift stream). Set
        ``sml.split.sampler=legacy`` for the pre-r5 numpy draws.

        Identical (weights, seed) splits of this frame return the SAME
        child frames (plan-cache reuse: frames are immutable and the
        sampler is deterministic, so the children are observationally
        identical — but repeated ML 02-style split→fit flows keep their
        downstream staging/shuffle caches hot)."""
        explicit_seed = seed is not None
        seed = int(seed) if explicit_seed else int(np.random.SeedSequence().entropy % (2 ** 31))
        sampler_mode = str(GLOBAL_CONF.get("sml.split.sampler"))
        memo_key = (tuple(float(w) for w in weights), seed, sampler_mode)
        if explicit_seed:
            hit = self._split_memo.get(memo_key)
            if hit is not None:
                return list(hit)
        total = float(sum(weights))
        bounds = np.cumsum([w / total for w in weights])
        parent = self
        legacy = sampler_mode == "legacy"

        def make(i: int) -> DataFrame:
            lo = 0.0 if i == 0 else bounds[i - 1]
            hi = bounds[i]

            def fn(pdf: pd.DataFrame, ctx: EvalContext) -> pd.DataFrame:
                if legacy:
                    rng = np.random.default_rng(
                        (seed << 16) + ctx.partition_index)
                    u = rng.random(len(pdf))
                    mask = (u >= lo) & (u < hi)
                    return pdf[mask].reset_index(drop=True)
                from .sampling import partition_uniforms, presplit_sort
                pdf = presplit_sort(pdf)
                u = partition_uniforms(seed, ctx.partition_index, len(pdf))
                mask = (u >= lo) & (u < hi)
                return pdf[mask].reset_index(drop=True)

            out = parent._derive(fn)
            out._op = "randomSplit"
            return out

        outs = [make(i) for i in range(len(weights))]
        if explicit_seed:
            # 2-deep: each entry's children, once materialized, pin ~one
            # dataset copy each — a wider memo could hold several copies
            # of a large cached frame for no realistic reuse pattern
            if len(self._split_memo) >= 2:
                self._split_memo.pop(next(iter(self._split_memo)))
            self._split_memo[memo_key] = list(outs)
        return outs

    def sample(self, withReplacement: bool = False, fraction: float = 0.1,
               seed: Optional[int] = None) -> "DataFrame":
        seed = int(seed) if seed is not None else np.random.SeedSequence().entropy % (2 ** 31)

        def fn(pdf: pd.DataFrame, ctx: EvalContext) -> pd.DataFrame:
            rng = np.random.default_rng((seed << 16) + ctx.partition_index)
            if withReplacement:
                n = rng.poisson(fraction * len(pdf))
                idx = rng.integers(0, max(len(pdf), 1), size=n) if len(pdf) else []
                return pdf.iloc[idx].reset_index(drop=True)
            mask = rng.random(len(pdf)) < fraction
            return pdf[mask].reset_index(drop=True)

        return self._derive(fn)

    # ------------------------------------------------------------ caching
    def cache(self) -> "DataFrame":
        self._materialize()
        return self

    def persist(self, *_args) -> "DataFrame":
        return self.cache()

    def unpersist(self) -> "DataFrame":
        # materialization releases the recipe (see _materialize), so data can
        # only be dropped if it is still recomputable
        if self._parts is not None:
            from .sampling import drop_sort_memo_for
            drop_sort_memo_for(self._parts)
        if self._compute is not None:
            self._parts = None
            self._offsets = None
        if self._pdf_cache is not None:
            from .grouped import drop_split_cache_for
            drop_split_cache_for(self._pdf_cache)
        self._pdf_cache = None
        return self

    # ------------------------------------------------------------- stats
    def describe(self, *cols) -> "DataFrame":
        return self._describe(["count", "mean", "stddev", "min", "max"], cols)

    def summary(self, *stats) -> "DataFrame":
        stats = list(stats) or ["count", "mean", "stddev", "min", "25%", "50%", "75%", "max"]
        return self._describe(stats, ())

    def _describe(self, stats: List[str], cols) -> "DataFrame":
        pdf = self.toPandas()
        if cols:
            pdf = pdf[list(cols)]
        out: Dict[str, list] = {"summary": stats}
        for c in pdf.columns:
            s = pdf[c]
            numeric = s.dtype.kind in "ifu"
            sn = pd.to_numeric(s, errors="coerce") if not numeric else s
            vals = []
            for st in stats:
                try:
                    if st == "count":
                        v = int(s.notna().sum())
                    elif st == "mean":
                        v = sn.mean() if numeric else None
                    elif st == "stddev":
                        v = sn.std(ddof=1) if numeric else None
                    elif st == "min":
                        v = s.min()
                    elif st == "max":
                        v = s.max()
                    elif st.endswith("%"):
                        v = sn.quantile(float(st[:-1]) / 100) if numeric else None
                    else:
                        v = None
                except Exception:
                    v = None
                vals.append(None if v is None else str(v))
            out[c] = vals
        res = pd.DataFrame(out)
        return DataFrame.from_pandas(res, session=self._session, num_partitions=1)

    def approxQuantile(self, col: Union[str, List[str]], probabilities: Sequence[float],
                      relativeError: float = 0.0) -> List:
        pdf = self.toPandas()
        if isinstance(col, str):
            s = pd.to_numeric(pdf[col], errors="coerce").dropna()
            return [float(s.quantile(p)) for p in probabilities]
        return [[float(pd.to_numeric(pdf[c], errors="coerce").dropna().quantile(p))
                 for p in probabilities] for c in col]

    def corr(self, col1: str, col2: str) -> float:
        pdf = self.toPandas()
        return float(pd.to_numeric(pdf[col1], errors="coerce")
                     .corr(pd.to_numeric(pdf[col2], errors="coerce")))

    # ------------------------------------------------------------- pandas fn
    def mapInPandas(self, fn: Callable, schema: Union[str, StructType]) -> "DataFrame":
        """Iterator-of-batches map (`ML 12:125-143`); batch size follows
        `sml.arrow.maxRecordsPerBatch`.

        The UDF is invoked ONCE with an iterator streaming every partition's
        batches (Spark's contract is per-executor-task; any batch boundary
        is valid). One invocation lets expensive UDF state — a loaded model,
        a compiled device program — amortize across the whole dataset, and
        lets device-backed UDF bodies (`DeviceScorer.score_batches`)
        pipeline host staging under device compute across batches.

        The whole invocation is priced through `parallel.dispatch.decide`
        with a per-cell WorkHint: a SMALL pandas-fn leg binds the host
        mesh for the UDF's duration, so device-capable bodies inside it
        (scorers) stop paying a tunnel round-trip per batch (r01's
        ml12_mapinpandas ran 0.58x host exactly this way). Large legs
        leave the inner per-batch routing untouched.
        """
        sch = parse_schema(schema)
        parent = self

        def compute():
            import contextlib

            from ..parallel import dispatch as _dispatch
            from ..parallel import mesh as _meshlib
            parts = parent._materialize()
            bs = GLOBAL_CONF.getInt("sml.arrow.maxRecordsPerBatch")

            def batches():
                for pdf in parts:
                    if len(pdf) == 0:
                        continue
                    for i in range(0, len(pdf), bs):
                        yield pdf.iloc[i:i + bs].reset_index(drop=True)

            n_rows = sum(len(p) for p in parts)
            n_cols = max((len(p.columns) for p in parts), default=1)
            # a linear-model-pass-per-cell estimate: generous to the fn
            # body, but the decision only flips SMALL legs hostward,
            # where the fixed per-dispatch tunnel latency dominates any
            # body by orders of magnitude
            hint = _dispatch.WorkHint(flops=2.0 * n_rows * max(n_cols, 1),
                                      kind="blas", out_bytes=8.0 * n_rows)
            route, _ = _dispatch.decide(hint)
            ctx = (_meshlib.use_mesh_local(_dispatch.host_mesh())
                   if route == "host" else contextlib.nullcontext())
            with ctx:
                outs = [coerce_to_schema(b, sch) for b in fn(batches())]
            return outs if outs else [coerce_to_schema(pd.DataFrame(), sch)]

        out = DataFrame(compute, session=self._session, schema=sch)
        out._ml_attrs = dict(self._ml_attrs)
        return out

    # ------------------------------------------------------------- views / IO
    def createOrReplaceTempView(self, name: str) -> None:
        if self._session is None:
            raise RuntimeError("DataFrame has no session; use TpuSession.createDataFrame")
        self._session.catalog._register_view(name, self)

    @property
    def write(self):
        from .io import DataFrameWriter
        return DataFrameWriter(self)

    @property
    def writeStream(self):
        from ..streaming.stream import DataStreamWriter
        return DataStreamWriter(self)

    def checkpoint(self, eager: bool = True) -> "DataFrame":
        self._materialize()
        return self

    def to_koalas(self, index_col: Optional[str] = None):
        """Lift into the pandas-API layer (`ML 14:134-152`)."""
        from ..pandas_api import DataFrame as KDataFrame
        return KDataFrame(self, index_col=index_col)

    to_pandas_on_spark = to_koalas
    pandas_api = to_koalas

    def __repr__(self):
        try:
            cols = ", ".join(f"{n}: {t}" for n, t in self.dtypes[:8])
        except Exception:
            cols = "..."
        return f"DataFrame[{cols}]"


class DataFrameNaFunctions:
    def __init__(self, df: DataFrame):
        self._df = df

    def drop(self, how: str = "any", thresh: Optional[int] = None,
             subset: Optional[Sequence[str]] = None) -> DataFrame:
        return self._df.dropna(how=how, thresh=thresh, subset=subset)

    def fill(self, value, subset: Optional[Sequence[str]] = None) -> DataFrame:
        return self._df.fillna(value, subset=subset)


class DataFrameStatFunctions:
    def __init__(self, df: DataFrame):
        self._df = df

    def corr(self, col1: str, col2: str) -> float:
        return self._df.corr(col1, col2)

    def approxQuantile(self, col, probabilities, relativeError=0.0):
        return self._df.approxQuantile(col, probabilities, relativeError)


class _RDDShim:
    """`df.rdd.getNumPartitions()` — the partition-introspection surface used
    at `ML 00b:84` and the repartition demos."""

    def __init__(self, df: DataFrame):
        self._df = df

    def getNumPartitions(self) -> int:
        return len(self._df._materialize())

    def glom(self):
        return [p.to_dict("records") for p in self._df._materialize()]


def _hash_repartition(pdf: pd.DataFrame, keys: List[str], num: int) -> Partitions:
    """Murmur3 hash-partition rows by key columns (shuffle placement).
    Records the post-shuffle partition skew (max/mean rows) — the MLE 05
    debugging taxonomy's skew signal (`MLE 05:24-29`)."""
    if len(pdf) == 0:
        return [pdf.reset_index(drop=True)]
    hashes = hash_columns([pdf[k] for k in keys], n=len(pdf))
    ids = hash_partition_ids(hashes, num)
    parts = [pdf[ids == i].reset_index(drop=True) for i in range(num)]
    sizes = np.array([len(p) for p in parts], dtype=float)
    if sizes.sum() > 0:
        PROFILER.count("shuffle.rows", float(sizes.sum()))
        # shallow estimate (object columns count pointer width): the
        # relative shuffle-volume signal MLE 05 reads off the Spark UI,
        # cheap enough to take on every shuffle
        PROFILER.count("shuffle.bytes",
                       float(pdf.memory_usage(index=False).sum()))
        with PROFILER.span("shuffle.partition", rows=int(sizes.sum()),
                           skew=float(sizes.max() / max(sizes.mean(), 1.0))):
            pass
    return parts
