"""TpuSession — the SparkSession equivalent (single-process driver, no JVM).

The Py4J bridge disappears (SURVEY §2.3): one Python driver owns the Arrow
host tables, the catalog (temp views + warehouse tables), the conf, and the
device mesh. `spark.` call-sites in the course map 1:1 onto this class.
"""

from __future__ import annotations

import os
import shutil
import tempfile
from typing import Any, Dict, List, Optional, Tuple, Union

import numpy as np
import pandas as pd

from ..conf import GLOBAL_CONF, TpuConf
from .dataframe import DataFrame
from .types import Row, StructType, parse_schema


class Catalog:
    def __init__(self, session: "TpuSession", warehouse: str):
        self._session = session
        self._warehouse = warehouse
        self._views_reg: Dict[str, DataFrame] = {}
        self._tables_reg: Dict[str, Tuple[str, str]] = {}  # name -> (path, fmt)
        self._databases = {"default"}
        self._current_db = "default"

    # views
    def _register_view(self, name: str, df: DataFrame) -> None:
        self._views_reg[name] = df

    def _views(self) -> Dict[str, DataFrame]:
        return dict(self._views_reg)

    def dropTempView(self, name: str) -> bool:
        from .sql import invalidate_cached_relation
        invalidate_cached_relation(self._session, name)
        return self._views_reg.pop(name, None) is not None

    def tableExists(self, name: str) -> bool:
        return name in self._views_reg or self._qualify(name) in self._tables_reg

    def listTables(self):
        return [Row(database=d, tableName=t, isTemporary=tmp)
                for d, t, tmp in self._list_tables()]

    def _list_tables(self):
        out = [("", v, True) for v in self._views_reg]
        for fq in self._tables_reg:
            db, _, t = fq.rpartition(".")
            out.append((db or "default", t, False))
        return out

    # databases
    def _create_database(self, name: str) -> None:
        self._databases.add(name)
        os.makedirs(os.path.join(self._warehouse, name + ".db"), exist_ok=True)

    def _invalidate_table(self, fq: str, path: Optional[str]) -> None:
        """Purge every session-SQL relation derived from a dropped table:
        its name aliases AND the path-keyed `_tt_*`/`_delta_*` snapshots,
        which otherwise survive a drop+recreate at the same path."""
        from .sql import invalidate_cached_path, invalidate_cached_relation
        for n in {fq, fq.replace(".", "_"), fq.split(".")[-1]}:
            invalidate_cached_relation(self._session, n)
        if path:
            invalidate_cached_path(self._session, path)

    def _drop_database(self, name: str) -> None:
        self._databases.discard(name)
        for fq in [k for k in self._tables_reg if k.startswith(name + ".")]:
            path, _fmt = self._tables_reg.pop(fq)
            self._invalidate_table(fq, path)
        shutil.rmtree(os.path.join(self._warehouse, name + ".db"), ignore_errors=True)

    def _use_database(self, name: str) -> None:
        self._databases.add(name)
        self._current_db = name

    def currentDatabase(self) -> str:
        return self._current_db

    # tables
    def _qualify(self, name: str) -> str:
        return name if "." in name else f"{self._current_db}.{name}"

    def _table_path(self, name: str) -> str:
        fq = self._qualify(name)
        db, _, t = fq.rpartition(".")
        return os.path.join(self._warehouse, db + ".db", t)

    def _register_table(self, name: str, path: str, fmt: str) -> None:
        self._tables_reg[self._qualify(name)] = (path, fmt)

    def _drop_table(self, name: str) -> None:
        fq = self._qualify(name)
        from .sql import invalidate_cached_relation
        invalidate_cached_relation(self._session, name)  # as-typed alias
        info = self._tables_reg.pop(fq, None)
        self._invalidate_table(fq, info[0] if info else None)
        if info:
            shutil.rmtree(info[0], ignore_errors=True)

    def _tables(self) -> Dict[str, Tuple[str, str]]:
        return dict(self._tables_reg)


class _Builder:
    def __init__(self):
        self._app = "sml_tpu"
        self._conf: Dict[str, Any] = {}

    def appName(self, name: str) -> "_Builder":
        self._app = name
        return self

    def master(self, _m: str) -> "_Builder":
        return self

    def config(self, key: str, value) -> "_Builder":
        self._conf[key] = value
        return self

    def enableHiveSupport(self) -> "_Builder":
        return self

    def getOrCreate(self) -> "TpuSession":
        s = TpuSession._instance or TpuSession(app_name=self._app)
        for k, v in self._conf.items():
            s.conf.set(k, v)
        return s


class TpuSession:
    _instance: Optional["TpuSession"] = None

    def __init__(self, app_name: str = "sml_tpu", warehouse: Optional[str] = None):
        self.app_name = app_name
        self.conf: TpuConf = GLOBAL_CONF
        self._warehouse = warehouse or os.path.join(tempfile.gettempdir(), "sml_tpu_warehouse")
        os.makedirs(self._warehouse, exist_ok=True)
        self.catalog = Catalog(self, self._warehouse)
        TpuSession._instance = self

    builder = None  # set below

    @classmethod
    def getActiveSession(cls) -> Optional["TpuSession"]:
        return cls._instance

    # ------------------------------------------------------------- creation
    def range(self, start: int, end: Optional[int] = None, step: int = 1,
              numPartitions: Optional[int] = None) -> DataFrame:
        if end is None:
            start, end = 0, start
        ids = np.arange(start, end, step, dtype=np.int64)
        pdf = pd.DataFrame({"id": ids})
        return DataFrame.from_pandas(pdf, session=self, num_partitions=numPartitions)

    def createDataFrame(self, data, schema: Optional[Union[str, StructType, List[str]]] = None,
                        numPartitions: Optional[int] = None) -> DataFrame:
        if isinstance(data, pd.DataFrame):
            pdf = data.copy()
            if isinstance(schema, list):
                pdf.columns = schema
        else:
            rows = list(data)
            if rows and isinstance(rows[0], Row):
                pdf = pd.DataFrame([r.asDict() for r in rows])
            elif rows and isinstance(rows[0], dict):
                pdf = pd.DataFrame(rows)
            else:
                if isinstance(schema, list):
                    pdf = pd.DataFrame(rows, columns=schema)
                elif isinstance(schema, (str, StructType)):
                    st = parse_schema(schema)
                    pdf = pd.DataFrame(rows, columns=st.names)
                else:
                    pdf = pd.DataFrame(rows, columns=[f"_{i+1}" for i in range(len(rows[0]))])
        st = parse_schema(schema) if isinstance(schema, (str, StructType)) else None
        if st is not None:
            from .dataframe import coerce_to_schema
            pdf = coerce_to_schema(pdf, st)
        return DataFrame.from_pandas(pdf, session=self, num_partitions=numPartitions, schema=st)

    # --------------------------------------------------------------- access
    @property
    def read(self):
        from .io import DataFrameReader
        return DataFrameReader(self)

    @property
    def readStream(self):
        from ..streaming.stream import DataStreamReader
        return DataStreamReader(self)

    def table(self, name: str) -> DataFrame:
        views = self.catalog._views()
        if name in views:
            return views[name]
        fq = self.catalog._qualify(name)
        info = self.catalog._tables().get(fq)
        if info is None:
            # fall back to a directory in the warehouse (created by saveAsTable
            # in an earlier session)
            path = self.catalog._table_path(name)
            if os.path.isdir(os.path.join(path, "_delta_log")):
                info = (path, "delta")
            elif os.path.isdir(path):
                info = (path, "parquet")
            else:
                raise ValueError(f"Table or view not found: {name}")
        path, fmt = info
        if fmt == "delta":
            from ..delta.table import read_delta
            return read_delta(path, self, {})
        return self.read.format(fmt).load(path)

    def sql(self, query: str) -> DataFrame:
        from .sql import run_sql
        return run_sql(self, query)

    @property
    def sparkContext(self):
        return _ContextShim(self)

    @property
    def streams(self):
        from ..streaming.stream import StreamManager
        return StreamManager()

    def stop(self) -> None:
        TpuSession._instance = None

    # ---------------------------------------------------------------- misc
    @property
    def version(self) -> str:
        from ..version import __version__
        return __version__


class _ContextShim:
    """`spark.sparkContext` knobs the course touches."""

    def __init__(self, session: TpuSession):
        self._session = session

    @property
    def defaultParallelism(self) -> int:
        return GLOBAL_CONF.getInt("sml.default.parallelism")

    def setLogLevel(self, _level: str) -> None:
        pass

    def parallelize(self, data, numSlices: Optional[int] = None):
        return self._session.createDataFrame(pd.DataFrame({"value": list(data)}),
                                             numPartitions=numSlices)


TpuSession.builder = _Builder()


def get_session() -> TpuSession:
    return TpuSession._instance or TpuSession()
