"""Column functions — the `pyspark.sql.functions` surface the course drives.

Coverage from SURVEY §1 L1: `col, lit, rand, log, exp, when, translate, avg,
hash, abs, monotonically_increasing_id` plus the aggregate family and common
helpers. Partition-aware semantics (rand seeding, monotonic ids) follow the
documented per-partition contract in `sml_tpu/frame/dataframe.py`.
"""

from __future__ import annotations

import math
from typing import Any, Optional, Union

import numpy as np
import pandas as pd

from ..native.hashing import hash_columns
from .column import Column, EvalContext, LitColumn, NamedColumn, ensure_column

ColumnOrName = Union[Column, str]


def col(name: str) -> Column:
    return NamedColumn(name)


column = col


def lit(value: Any) -> Column:
    return LitColumn(value)


# ----------------------------- scalar math ---------------------------------

def _unary(name: str, fn):
    def wrapper(c: ColumnOrName) -> Column:
        cc = ensure_column(c)
        out = Column(lambda pdf, ctx: fn(pd.to_numeric(cc._eval(pdf, ctx), errors="coerce")),
                     f"{name}({cc._name})")
        from .column import NamedColumn
        if isinstance(cc, NamedColumn):
            # pattern tag for withColumn's evaluator-pushdown propagation:
            # "this expression is <name> applied to the raw column <col>"
            out._unary_of = (name, cc._name)
        return out
    wrapper.__name__ = name
    return wrapper


log = _unary("log", np.log)
log1p = _unary("log1p", np.log1p)
log2 = _unary("log2", np.log2)
log10 = _unary("log10", np.log10)
exp = _unary("exp", np.exp)
sqrt = _unary("sqrt", np.sqrt)
abs = _unary("abs", np.abs)  # noqa: A001 - matches pyspark.sql.functions.abs
floor = _unary("floor", np.floor)
ceil = _unary("ceil", np.ceil)


def pow(base: ColumnOrName, exponent) -> Column:  # noqa: A001
    return ensure_column(base) ** exponent


def round(c: ColumnOrName, scale: int = 0) -> Column:  # noqa: A001
    cc = ensure_column(c)
    return Column(lambda pdf, ctx: cc._eval(pdf, ctx).round(scale), f"round({cc._name}, {scale})")


def negate(c: ColumnOrName) -> Column:
    return -ensure_column(c)


# ----------------------------- conditionals --------------------------------

def when(condition: Column, value) -> Column:
    from .column import CaseWhenColumn
    val_c = value if isinstance(value, Column) else LitColumn(value)
    return CaseWhenColumn([(condition, val_c)])


def coalesce(*cols: ColumnOrName) -> Column:
    ccs = [ensure_column(c) for c in cols]

    def ev(pdf, ctx):
        out = ccs[0]._eval(pdf, ctx)
        for c in ccs[1:]:
            out = out.where(out.notna(), c._eval(pdf, ctx))
        return out

    return Column(ev, "coalesce(...)")


def isnan(c: ColumnOrName) -> Column:
    cc = ensure_column(c)
    return Column(lambda pdf, ctx: pd.to_numeric(cc._eval(pdf, ctx), errors="coerce").isna(),
                  f"isnan({cc._name})")


def isnull(c: ColumnOrName) -> Column:
    return ensure_column(c).isNull()


# ------------------------------- strings -----------------------------------

def translate(src: ColumnOrName, matching: str, replace: str) -> Column:
    """Character-by-character translation (`ML 01:91-93` price cleanup)."""
    cc = ensure_column(src)
    table = str.maketrans(matching, replace[:len(matching)].ljust(len(matching))) \
        if len(replace) >= len(matching) else \
        {ord(ch): (replace[i] if i < len(replace) else None) for i, ch in enumerate(matching)}

    def ev(pdf, ctx):
        s = cc._eval(pdf, ctx)
        return s.map(lambda v: v.translate(table) if isinstance(v, str) else v)

    return Column(ev, f"translate({cc._name}, {matching}, {replace})")


def lower(c: ColumnOrName) -> Column:
    cc = ensure_column(c)
    return Column(lambda pdf, ctx: cc._eval(pdf, ctx).str.lower(), f"lower({cc._name})")


def upper(c: ColumnOrName) -> Column:
    cc = ensure_column(c)
    return Column(lambda pdf, ctx: cc._eval(pdf, ctx).str.upper(), f"upper({cc._name})")


def trim(c: ColumnOrName) -> Column:
    cc = ensure_column(c)
    return Column(lambda pdf, ctx: cc._eval(pdf, ctx).str.strip(), f"trim({cc._name})")


def initcap(c: ColumnOrName) -> Column:
    cc = ensure_column(c)
    return Column(lambda pdf, ctx: cc._eval(pdf, ctx).str.title(), f"initcap({cc._name})")


def concat(*cols: ColumnOrName) -> Column:
    ccs = [ensure_column(c) for c in cols]

    def ev(pdf, ctx):
        out = ccs[0]._eval(pdf, ctx).astype(str)
        for c in ccs[1:]:
            out = out + c._eval(pdf, ctx).astype(str)
        return out

    return Column(ev, "concat(...)")


def concat_ws(sep: str, *cols: ColumnOrName) -> Column:
    ccs = [ensure_column(c) for c in cols]

    def ev(pdf, ctx):
        parts = [c._eval(pdf, ctx).astype(str) for c in ccs]
        out = parts[0]
        for p in parts[1:]:
            out = out + sep + p
        return out

    return Column(ev, f"concat_ws({sep}, ...)")


def regexp_replace(c: ColumnOrName, pattern: str, replacement: str) -> Column:
    cc = ensure_column(c)
    return Column(lambda pdf, ctx: cc._eval(pdf, ctx).str.replace(pattern, replacement, regex=True),
                  f"regexp_replace({cc._name})")


def split(c: ColumnOrName, pattern: str) -> Column:
    cc = ensure_column(c)
    return Column(lambda pdf, ctx: cc._eval(pdf, ctx).str.split(pattern),
                  f"split({cc._name}, {pattern})")


def length(c: ColumnOrName) -> Column:
    cc = ensure_column(c)
    return Column(lambda pdf, ctx: cc._eval(pdf, ctx).str.len(), f"length({cc._name})")


# --------------------------- partition-aware -------------------------------

def rand(seed: Optional[int] = None) -> Column:
    """Uniform [0,1). Deterministic per (seed, partition_index) — the same
    partition-dependence contract the course demonstrates for randomSplit
    (`ML 02:38-52`)."""

    def ev(pdf: pd.DataFrame, ctx: EvalContext):
        s = seed if seed is not None else np.random.SeedSequence().entropy % (2 ** 31)
        rng = np.random.default_rng((int(s) << 16) + ctx.partition_index)
        return pd.Series(rng.random(len(pdf)), index=pdf.index)

    return Column(ev, f"rand({seed})")


def randn(seed: Optional[int] = None) -> Column:
    def ev(pdf: pd.DataFrame, ctx: EvalContext):
        s = seed if seed is not None else np.random.SeedSequence().entropy % (2 ** 31)
        rng = np.random.default_rng((int(s) << 16) + ctx.partition_index)
        return pd.Series(rng.standard_normal(len(pdf)), index=pdf.index)

    return Column(ev, f"randn({seed})")


def monotonically_increasing_id() -> Column:
    """(partition_id << 33) + row-position-in-partition, as in the engine the
    course uses (`ML 10:46`)."""

    def ev(pdf: pd.DataFrame, ctx: EvalContext):
        base = ctx.partition_index << 33
        return pd.Series(base + np.arange(len(pdf), dtype=np.int64), index=pdf.index)

    return Column(ev, "monotonically_increasing_id()")


def spark_partition_id() -> Column:
    return Column(lambda pdf, ctx: pd.Series(np.full(len(pdf), ctx.partition_index, dtype=np.int32),
                                             index=pdf.index),
                  "SPARK_PARTITION_ID()")


def hash(*cols: ColumnOrName) -> Column:  # noqa: A001
    """Murmur3 row hash with seed chaining — matches the native kernel
    (`sml_tpu/native/murmur3.cc`); used by the validation harness."""
    ccs = [ensure_column(c) for c in cols]

    def ev(pdf, ctx):
        series = [c._eval(pdf, ctx) for c in ccs]
        return pd.Series(hash_columns(series, n=len(pdf)), index=pdf.index)

    return Column(ev, "hash(...)")


# ------------------------------ aggregates ---------------------------------

def _aggregate(name: str, agg_fn):
    def wrapper(c: ColumnOrName) -> Column:
        cc = ensure_column(c)
        out = Column(cc._eval_fn, f"{name}({cc._name})", agg=agg_fn)
        out._children = [cc]
        return out
    wrapper.__name__ = name
    return wrapper


avg = _aggregate("avg", lambda s: pd.to_numeric(s, errors="coerce").mean())
mean = _aggregate("avg", lambda s: pd.to_numeric(s, errors="coerce").mean())
sum = _aggregate("sum", lambda s: pd.to_numeric(s, errors="coerce").sum())  # noqa: A001
min = _aggregate("min", lambda s: s.min())  # noqa: A001
max = _aggregate("max", lambda s: s.max())  # noqa: A001
stddev = _aggregate("stddev", lambda s: pd.to_numeric(s, errors="coerce").std(ddof=1))
stddev_samp = stddev
stddev_pop = _aggregate("stddev_pop", lambda s: pd.to_numeric(s, errors="coerce").std(ddof=0))
variance = _aggregate("variance", lambda s: pd.to_numeric(s, errors="coerce").var(ddof=1))
first = _aggregate("first", lambda s: s.iloc[0] if len(s) else None)
last = _aggregate("last", lambda s: s.iloc[-1] if len(s) else None)
collect_list = _aggregate("collect_list", lambda s: list(s.dropna()))
collect_set = _aggregate("collect_set", lambda s: sorted(set(s.dropna()), key=str))
countDistinct = _aggregate("count_distinct", lambda s: s.nunique())
median = _aggregate("median", lambda s: pd.to_numeric(s, errors="coerce").median())


def count(c: ColumnOrName) -> Column:
    if isinstance(c, str) and c == "*":
        out = Column(lambda pdf, ctx: pd.Series(np.ones(len(pdf), dtype=np.int64), index=pdf.index),
                     "count(1)", agg=lambda s: int(s.sum()))
        return out
    cc = ensure_column(c)
    out = Column(cc._eval_fn, f"count({cc._name})", agg=lambda s: int(s.notna().sum()))
    out._children = [cc]
    return out


def percentile_approx(c: ColumnOrName, percentage: float, accuracy: int = 10000) -> Column:
    cc = ensure_column(c)
    return Column(cc._eval_fn, f"percentile_approx({cc._name}, {percentage})",
                  agg=lambda s: pd.to_numeric(s, errors="coerce").quantile(percentage))


def corr(c1: ColumnOrName, c2: ColumnOrName) -> Column:
    a, b = ensure_column(c1), ensure_column(c2)

    def ev(pdf, ctx):
        return pd.concat({"a": a._eval(pdf, ctx), "b": b._eval(pdf, ctx)}, axis=1)

    out = Column(ev, f"corr({a._name}, {b._name})",
                 agg=lambda s: s["a"].corr(s["b"]) if isinstance(s, pd.DataFrame) else np.nan)
    return out


# ---------------------------- datetime helpers ------------------------------

def to_date(c: ColumnOrName, fmt: Optional[str] = None) -> Column:
    cc = ensure_column(c)
    return Column(lambda pdf, ctx: pd.to_datetime(cc._eval(pdf, ctx), format=fmt, errors="coerce").dt.floor("D"),
                  f"to_date({cc._name})")


def to_timestamp(c: ColumnOrName, fmt: Optional[str] = None) -> Column:
    cc = ensure_column(c)
    return Column(lambda pdf, ctx: pd.to_datetime(cc._eval(pdf, ctx), format=fmt, errors="coerce"),
                  f"to_timestamp({cc._name})")


def year(c: ColumnOrName) -> Column:
    cc = ensure_column(c)
    return Column(lambda pdf, ctx: pd.to_datetime(cc._eval(pdf, ctx), errors="coerce").dt.year,
                  f"year({cc._name})")


def month(c: ColumnOrName) -> Column:
    cc = ensure_column(c)
    return Column(lambda pdf, ctx: pd.to_datetime(cc._eval(pdf, ctx), errors="coerce").dt.month,
                  f"month({cc._name})")


def dayofmonth(c: ColumnOrName) -> Column:
    cc = ensure_column(c)
    return Column(lambda pdf, ctx: pd.to_datetime(cc._eval(pdf, ctx), errors="coerce").dt.day,
                  f"dayofmonth({cc._name})")


# --------------------------- pandas UDFs (L2) -------------------------------
def pandas_udf(returnType, functionType: Optional[str] = None):
    """`@pandas_udf("double")` — vectorized UDFs over column batches.

    Both reference shapes are supported (`SML/ML 12 - Inference with Pandas
    UDFs.py:71-112`):
    - scalar: fn(*series) -> series, applied per batch;
    - scalar-iterator: fn(Iterator[pd.Series | pd.DataFrame]) ->
      Iterator[pd.Series], detected from the signature — expensive state
      (model load) amortizes across batches.
    Batch size follows `sml.arrow.maxRecordsPerBatch` (`ML 12:90,121`); the
    Arrow JVM↔Python hop of the reference does not exist here, the batch
    boundary is host pandas ↔ the jitted compute inside the UDF body.
    """
    import inspect

    def deco(fn):
        sig = inspect.signature(fn)
        params = list(sig.parameters.values())
        hints = [str(p.annotation) for p in params]
        is_iter = len(params) == 1 and "Iterator" in hints[0]
        dtype = returnType if isinstance(returnType, str) else \
            getattr(returnType, "simpleString", lambda: str(returnType))()

        def udf_call(*cols):
            cols_c = [ensure_column(c) for c in cols]

            def ev(pdf: pd.DataFrame, ctx: EvalContext):
                from ..conf import GLOBAL_CONF
                series = [c._eval(pdf, ctx).reset_index(drop=True) for c in cols_c]
                n = len(pdf)
                if not is_iter:
                    out = fn(*series)
                else:
                    bs = GLOBAL_CONF.getInt("sml.arrow.maxRecordsPerBatch")

                    def batches():
                        # NB: builtins.max — this module defines an aggregate
                        # `max` that shadows it
                        for lo in range(0, n if n > 0 else 1, bs):
                            chunk = [s.iloc[lo:lo + bs].reset_index(drop=True)
                                     for s in series]
                            if len(chunk) == 1:
                                yield chunk[0]
                            else:
                                yield tuple(chunk)

                    outs = list(fn(batches()))
                    out = pd.concat(outs, ignore_index=True) if outs \
                        else pd.Series(dtype=float)
                if dtype in ("double", "float"):
                    out = pd.to_numeric(out, errors="coerce")
                return out.reset_index(drop=True)

            name = getattr(fn, "__name__", "udf") or "udf"
            return Column(ev, name)

        udf_call.__wrapped__ = fn
        return udf_call

    return deco


def udf(fn=None, returnType="string"):
    """Row-at-a-time UDF (the slow path the course contrasts pandas UDFs
    against, `ML 12:56-61`)."""
    def deco(f):
        def udf_call(*cols):
            cols_c = [ensure_column(c) for c in cols]

            def ev(pdf, ctx):
                series = [c._eval(pdf, ctx).reset_index(drop=True) for c in cols_c]
                return pd.Series([f(*vals) for vals in zip(*series)]) \
                    if series else pd.Series([f()] * len(pdf))

            return Column(ev, getattr(f, "__name__", "udf"))
        return udf_call
    return deco(fn) if callable(fn) else deco
