"""Schema types: the StructType/StructField surface the course uses.

The notebooks build explicit schemas for CSV reads and streaming sources
(`SML/ML 01 - Data Cleansing.py:34`, `SML/ML Electives/MLE 00 - MLlib
Deployment Options.py:52`) and inspect `df.schema`/`printSchema`. Backed by
pyarrow types for IO and pandas dtypes for compute.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Union

import numpy as np
import pandas as pd
import pyarrow as pa


class DataType:
    _name = "data"

    def simpleString(self) -> str:
        return self._name

    def __repr__(self) -> str:
        return f"{type(self).__name__}()"

    def __eq__(self, other) -> bool:
        return type(self) is type(other)

    def __hash__(self) -> int:
        return hash(type(self).__name__)

    def to_arrow(self) -> pa.DataType:
        raise NotImplementedError

    def to_pandas_dtype(self):
        raise NotImplementedError


class StringType(DataType):
    _name = "string"

    def to_arrow(self):
        return pa.string()

    def to_pandas_dtype(self):
        return object


class DoubleType(DataType):
    _name = "double"

    def to_arrow(self):
        return pa.float64()

    def to_pandas_dtype(self):
        return np.float64


class FloatType(DataType):
    _name = "float"

    def to_arrow(self):
        return pa.float32()

    def to_pandas_dtype(self):
        return np.float32


class IntegerType(DataType):
    _name = "int"

    def to_arrow(self):
        return pa.int32()

    def to_pandas_dtype(self):
        return np.int32


class LongType(DataType):
    _name = "bigint"

    def to_arrow(self):
        return pa.int64()

    def to_pandas_dtype(self):
        return np.int64


class BooleanType(DataType):
    _name = "boolean"

    def to_arrow(self):
        return pa.bool_()

    def to_pandas_dtype(self):
        return np.bool_


class TimestampType(DataType):
    _name = "timestamp"

    def to_arrow(self):
        return pa.timestamp("us")

    def to_pandas_dtype(self):
        return "datetime64[us]"


class DateType(DataType):
    _name = "date"

    def to_arrow(self):
        return pa.date32()

    def to_pandas_dtype(self):
        return "datetime64[s]"


class VectorType(DataType):
    """Dense feature vector column (MLlib Vector equivalent): the column
    holds fixed-width float32 arrays; stored in Arrow as FixedSizeList."""
    _name = "vector"

    def __init__(self, size: int = -1):
        self.size = size

    def __eq__(self, other):
        return isinstance(other, VectorType)

    def __hash__(self):
        return hash("VectorType")

    def to_arrow(self):
        return pa.list_(pa.float32()) if self.size < 0 else pa.list_(pa.float32(), self.size)

    def to_pandas_dtype(self):
        return object


@dataclass
class StructField:
    name: str
    dataType: DataType
    nullable: bool = True
    metadata: Dict[str, Any] = field(default_factory=dict)

    def simpleString(self) -> str:
        return f"{self.name}:{self.dataType.simpleString()}"


class StructType(DataType):
    _name = "struct"

    def __init__(self, fields: Optional[List[StructField]] = None):
        self.fields: List[StructField] = fields or []

    def add(self, name: Union[str, StructField], dataType: Optional[DataType] = None,
            nullable: bool = True) -> "StructType":
        if isinstance(name, StructField):
            self.fields.append(name)
        else:
            self.fields.append(StructField(name, dataType, nullable))
        return self

    @property
    def names(self) -> List[str]:
        return [f.name for f in self.fields]

    def __iter__(self):
        return iter(self.fields)

    def __len__(self):
        return len(self.fields)

    def __getitem__(self, key):
        if isinstance(key, int):
            return self.fields[key]
        for f in self.fields:
            if f.name == key:
                return f
        raise KeyError(key)

    def __eq__(self, other):
        return isinstance(other, StructType) and \
            [(f.name, f.dataType) for f in self.fields] == \
            [(f.name, f.dataType) for f in other.fields]

    def __repr__(self):
        inner = ", ".join(f.simpleString() for f in self.fields)
        return f"StructType({inner})"

    def simpleString(self) -> str:
        return "struct<" + ",".join(f.simpleString() for f in self.fields) + ">"

    def treeString(self) -> str:
        lines = ["root"]
        for f in self.fields:
            lines.append(f" |-- {f.name}: {f.dataType.simpleString()} "
                         f"(nullable = {str(f.nullable).lower()})")
        return "\n".join(lines) + "\n"

    def to_arrow(self) -> pa.Schema:
        return pa.schema([(f.name, f.dataType.to_arrow()) for f in self.fields])


_SIMPLE_NAMES = {
    "string": StringType, "str": StringType,
    "double": DoubleType, "float64": DoubleType,
    "float": FloatType, "float32": FloatType,
    "int": IntegerType, "integer": IntegerType, "int32": IntegerType,
    "long": LongType, "bigint": LongType, "int64": LongType,
    "boolean": BooleanType, "bool": BooleanType,
    "timestamp": TimestampType, "date": DateType,
    "vector": VectorType,
}


def parse_type(name: str) -> DataType:
    key = name.strip().lower()
    if key in _SIMPLE_NAMES:
        return _SIMPLE_NAMES[key]()
    raise ValueError(f"Unknown type name: {name}")


def parse_schema(s: Union[str, StructType]) -> StructType:
    """Parse a DDL-ish schema string: ``"a DOUBLE, b STRING"``."""
    if isinstance(s, StructType):
        return s
    st = StructType()
    for part in s.split(","):
        part = part.strip()
        if not part:
            continue
        toks = part.replace(":", " ").split()
        st.add(toks[0].strip("`"), parse_type(toks[1]))
    return st


def arrow_to_sml(t: pa.DataType) -> DataType:
    if pa.types.is_string(t) or pa.types.is_large_string(t):
        return StringType()
    if pa.types.is_float64(t):
        return DoubleType()
    if pa.types.is_float32(t):
        return FloatType()
    if pa.types.is_int64(t):
        return LongType()
    if pa.types.is_integer(t):
        return IntegerType()
    if pa.types.is_boolean(t):
        return BooleanType()
    if pa.types.is_timestamp(t):
        return TimestampType()
    if pa.types.is_date(t):
        return DateType()
    if pa.types.is_list(t) or pa.types.is_fixed_size_list(t):
        return VectorType()
    return StringType()


def infer_schema_from_pandas(pdf: pd.DataFrame) -> StructType:
    st = StructType()
    for name in pdf.columns:
        s = pdf[name]
        if getattr(s.dtype, "name", "") == "vector":  # columnar VectorArray
            st.add(str(name), VectorType())
            continue
        kind = s.dtype.kind
        if kind == "f":
            t: DataType = DoubleType() if s.dtype.itemsize > 4 else FloatType()
        elif kind in "iu":
            t = LongType() if s.dtype.itemsize > 4 else IntegerType()
        elif kind == "b":
            t = BooleanType()
        elif kind == "M":
            t = TimestampType()
        elif len(s) > 0 and s.map(lambda v: isinstance(v, (list, np.ndarray)), na_action="ignore").fillna(False).all() and s.notna().any():
            t = VectorType()
        else:
            t = StringType()
        st.add(str(name), t)
    return st


class Row:
    """Result row with attribute and index access (collect() output)."""

    def __init__(self, **kwargs):
        self.__dict__["_fields"] = list(kwargs.keys())
        self.__dict__["_values"] = dict(kwargs)

    def __getattr__(self, item):
        try:
            return self.__dict__["_values"][item]
        except KeyError:
            raise AttributeError(item)

    def __getitem__(self, item):
        if isinstance(item, int):
            return self._values[self._fields[item]]
        return self._values[item]

    def asDict(self) -> Dict[str, Any]:
        return dict(self._values)

    def __eq__(self, other):
        if isinstance(other, Row):
            return self._values == other._values
        return NotImplemented

    def __iter__(self):
        return iter(self._values.values())

    def __len__(self):
        return len(self._fields)

    def __repr__(self):
        inner = ", ".join(f"{k}={v!r}" for k, v in self._values.items())
        return f"Row({inner})"
