"""Minimal SQL shim: temp views + the SELECT subset the course drives.

`createOrReplaceTempView` + `spark.sql`/`%sql` usage (`ML 00b:59-64`,
`MLE 01:240-251`) runs against an in-memory sqlite database into which the
referenced views are materialized — an honest host-side fallback: SQL in the
reference is a convenience layer, never the hot path. DDL-ish statements the
course needs (CREATE/DROP DATABASE, USE, DESCRIBE HISTORY, SELECT from
``delta.`path``` ) are routed explicitly.
"""

from __future__ import annotations

import re
import sqlite3
from typing import TYPE_CHECKING

import numpy as np
import pandas as pd

from .column import Column, NamedColumn

if TYPE_CHECKING:
    from .session import TpuSession


class _ExprNamespace(dict):
    """Identifier → NamedColumn / function resolution for expression strings."""

    def __missing__(self, key):
        from . import functions as F
        fn = getattr(F, key, None)
        if fn is not None and not key.startswith("_"):
            return fn
        return NamedColumn(key)


def parse_simple_expr(expr: str) -> Column:
    """Translate a SQL-ish expression ('price > 0 AND bedrooms = 2',
    'log(price) as log_price') into a Column via restricted eval."""
    s = expr.strip()
    alias = None
    m = re.search(r"\s+[aA][sS]\s+([A-Za-z_][A-Za-z0-9_]*)\s*$", s)
    if m:
        alias = m.group(1)
        s = s[:m.start()]
    # SQL → Python operator translation
    s = re.sub(r"(?<![<>!=])=(?!=)", "==", s)
    s = re.sub(r"<>", "!=", s)
    s = re.sub(r"\bAND\b", "&", s, flags=re.I)
    s = re.sub(r"\bOR\b", "|", s, flags=re.I)
    s = re.sub(r"\bNOT\b", "~", s, flags=re.I)
    s = re.sub(r"\bIS\s+~\s*NULL\b", ".isNotNull()", s, flags=re.I)
    s = re.sub(r"\bIS\s+NULL\b", ".isNull()", s, flags=re.I)
    s = re.sub(r"`([^`]*)`", r"col('\1')", s)
    # Parenthesize comparison clauses joined by top-level &/| so Python's
    # operator precedence (& binds tighter than >=) doesn't bite.
    s = _parenthesize_clauses(s)
    col_ns = _ExprNamespace()
    out = eval(s, {"__builtins__": {}}, col_ns)  # noqa: S307 - restricted ns
    if not isinstance(out, Column):
        from .column import LitColumn
        out = LitColumn(out)
    if alias:
        out = out.alias(alias)
    return out


def _parenthesize_clauses(s: str) -> str:
    """Split on top-level & / | and wrap each clause in parens."""
    parts, ops = [], []
    depth, start = 0, 0
    for i, ch in enumerate(s):
        if ch in "([":
            depth += 1
        elif ch in ")]":
            depth -= 1
        elif ch in "&|" and depth == 0:
            parts.append(s[start:i])
            ops.append(ch)
            start = i + 1
    parts.append(s[start:])
    if not ops:
        return s
    out = f"({parts[0].strip()})"
    for op, p in zip(ops, parts[1:]):
        out += f" {op} ({p.strip()})"
    return out


_DELTA_REF = re.compile(r"delta\.`([^`]+)`", re.I)


def run_sql(session: "TpuSession", query: str):
    from .dataframe import DataFrame

    q = query.strip().rstrip(";")
    ql = q.lower()

    # --- DDL / catalog statements -----------------------------------------
    m = re.match(r"create\s+database\s+(if\s+not\s+exists\s+)?([\w`]+)(\s+location\s+'([^']*)')?",
                 ql)
    if m:
        name = re.match(r"create\s+database\s+(?:if\s+not\s+exists\s+)?([\w`]+)", q,
                        re.I).group(1).strip("`")
        session.catalog._create_database(name)
        return _empty(session)
    m = re.match(r"drop\s+database\s+(if\s+exists\s+)?([\w`]+)(\s+cascade)?", ql)
    if m:
        name = re.match(r"drop\s+database\s+(?:if\s+exists\s+)?([\w`]+)", q, re.I).group(1).strip("`")
        session.catalog._drop_database(name)
        return _empty(session)
    if ql.startswith("use "):
        session.catalog._use_database(q.split()[-1].strip("`"))
        return _empty(session)
    if ql.startswith("drop table"):
        name = q.split()[-1].strip("`")
        session.catalog._drop_table(name)
        return _empty(session)
    if ql.startswith("show tables"):
        rows = [{"database": d, "tableName": t, "isTemporary": tmp}
                for d, t, tmp in session.catalog._list_tables()]
        return DataFrame.from_pandas(pd.DataFrame(rows), session=session, num_partitions=1)
    m = re.match(r"describe\s+history\s+(.*)", q, re.I)
    if m:
        from ..delta.table import DeltaTable
        target = m.group(1).strip()
        dm = _DELTA_REF.match(target)
        path = dm.group(1) if dm else session.catalog._table_path(target.strip("`"))
        return DeltaTable.forPath(session, path).history()
    m = re.match(r"describe\s+(detail\s+)?(.*)", q, re.I)
    if m and not ql.startswith("describe select"):
        target = m.group(2).strip().strip("`")
        df = session.table(target)
        rows = [{"col_name": n, "data_type": t, "comment": None} for n, t in df.dtypes]
        return DataFrame.from_pandas(pd.DataFrame(rows), session=session, num_partitions=1)

    # --- SELECT via sqlite -------------------------------------------------
    con = sqlite3.connect(":memory:")
    try:
        # Time travel in SELECT (`ML 00c:184-209`): both the clause form
        # `delta.`p` VERSION AS OF n` / `TIMESTAMP AS OF 'ts'` (also on
        # registered table names) and the `delta.`p@vN` shorthand.
        def repl_travel(m_):
            target, kind, value = m_.group(1), m_.group(2), m_.group(3)
            dm = _DELTA_REF.match(target)
            path = dm.group(1) if dm else \
                session.catalog._table_path(target.strip("`"))
            key = "versionAsOf" if kind.lower().startswith("version") \
                else "timestampAsOf"
            from ..delta.table import read_delta
            df = read_delta(path, session, {key: value.strip("'\"")})
            tbl = "_tt_" + re.sub(r"\W", "_", f"{path}_{kind[0]}_{value}")
            _to_sqlite(df.toPandas(), tbl, con)
            return tbl

        q2 = re.sub(
            r"(delta\.`[^`]+`|[\w.`]+)\s+(version|timestamp)\s+as\s+of\s+"
            r"('[^']*'|\"[^\"]*\"|\d+)", repl_travel, q, flags=re.I)

        # Materialize delta.`path` references as temp tables.
        def repl(m_):
            path = m_.group(1)
            from ..delta.table import read_delta
            opts = {}
            at = re.search(r"@v(\d+)$", path)
            if at:  # delta.`path@vN` version shorthand
                path = path[:at.start()]
                opts["versionAsOf"] = int(at.group(1))
            tbl = "_delta_" + re.sub(r"\W", "_", m_.group(1))
            _to_sqlite(read_delta(path, session, opts).toPandas(), tbl, con)
            return tbl

        q2 = _DELTA_REF.sub(repl, q2)

        for name, df in session.catalog._views().items():
            if re.search(rf"\b{re.escape(name)}\b", q2, re.I):
                _to_sqlite(df.toPandas(), name, con)
        for fqname, (path, fmt) in session.catalog._tables().items():
            short = fqname.split(".")[-1]
            for candidate in (fqname, short):
                if re.search(rf"\b{re.escape(candidate)}\b", q2, re.I):
                    _to_sqlite(session.table(fqname).toPandas(), candidate.replace(".", "_"), con)
                    q2 = re.sub(rf"\b{re.escape(candidate)}\b", candidate.replace(".", "_"), q2)
                    break
        res = pd.read_sql_query(q2, con)
        return DataFrame.from_pandas(res, session=session)
    finally:
        con.close()


def _to_sqlite(pdf: pd.DataFrame, name: str, con) -> None:
    safe = pdf.copy()
    primitives = (type(None), str, bytes, bool, int, float,
                  np.integer, np.floating, np.bool_)
    for c in safe.columns:
        kind = getattr(safe[c].dtype, "kind", "O")
        if kind not in "ifubmM":  # objects, extension arrays (vectors), …
            safe[c] = pd.Series(
                [v if isinstance(v, primitives) else str(v)
                 for v in safe[c]], index=safe.index, dtype=object)
    safe.to_sql(name, con, index=False, if_exists="replace")


def _empty(session):
    from .dataframe import DataFrame
    return DataFrame.from_pandas(pd.DataFrame(), session=session, num_partitions=1)
