"""Minimal SQL shim: temp views + the SELECT subset the course drives.

`createOrReplaceTempView` + `spark.sql`/`%sql` usage (`ML 00b:59-64`,
`MLE 01:240-251`) runs against an in-memory sqlite database into which the
referenced views are materialized — an honest host-side fallback: SQL in the
reference is a convenience layer, never the hot path. DDL-ish statements the
course needs (CREATE/DROP DATABASE, USE, DESCRIBE HISTORY, SELECT from
``delta.`path``` ) are routed explicitly.
"""

from __future__ import annotations

import re
import sqlite3
from typing import TYPE_CHECKING

import numpy as np
import pandas as pd

from .column import Column, NamedColumn

if TYPE_CHECKING:
    from .session import TpuSession


class _ExprNamespace(dict):
    """Identifier → NamedColumn / function resolution for expression strings."""

    def __missing__(self, key):
        from . import functions as F
        fn = getattr(F, key, None)
        if fn is not None and not key.startswith("_"):
            return fn
        return NamedColumn(key)


def parse_simple_expr(expr: str) -> Column:
    """Translate a SQL-ish expression ('price > 0 AND bedrooms = 2',
    'log(price) as log_price') into a Column via restricted eval."""
    s = expr.strip()
    alias = None
    m = re.search(r"\s+[aA][sS]\s+([A-Za-z_][A-Za-z0-9_]*)\s*$", s)
    if m:
        alias = m.group(1)
        s = s[:m.start()]
    # SQL → Python operator translation
    s = re.sub(r"(?<![<>!=])=(?!=)", "==", s)
    s = re.sub(r"<>", "!=", s)
    s = re.sub(r"\bAND\b", "&", s, flags=re.I)
    s = re.sub(r"\bOR\b", "|", s, flags=re.I)
    s = re.sub(r"\bNOT\b", "~", s, flags=re.I)
    s = re.sub(r"\bIS\s+~\s*NULL\b", ".isNotNull()", s, flags=re.I)
    s = re.sub(r"\bIS\s+NULL\b", ".isNull()", s, flags=re.I)
    s = re.sub(r"`([^`]*)`", r"col('\1')", s)
    # Parenthesize comparison clauses joined by top-level &/| so Python's
    # operator precedence (& binds tighter than >=) doesn't bite.
    s = _parenthesize_clauses(s)
    col_ns = _ExprNamespace()
    out = eval(s, {"__builtins__": {}}, col_ns)  # noqa: S307 - restricted ns
    if not isinstance(out, Column):
        from .column import LitColumn
        out = LitColumn(out)
    if alias:
        out = out.alias(alias)
    return out


def _parenthesize_clauses(s: str) -> str:
    """Split on top-level & / | and wrap each clause in parens."""
    parts, ops = [], []
    depth, start = 0, 0
    for i, ch in enumerate(s):
        if ch in "([":
            depth += 1
        elif ch in ")]":
            depth -= 1
        elif ch in "&|" and depth == 0:
            parts.append(s[start:i])
            ops.append(ch)
            start = i + 1
    parts.append(s[start:])
    if not ops:
        return s
    out = f"({parts[0].strip()})"
    for op, p in zip(ops, parts[1:]):
        out += f" {op} ({p.strip()})"
    return out


_DELTA_REF = re.compile(r"delta\.`([^`]+)`", re.I)

# Session-scoped sqlite backing store: materialized views/tables persist
# across queries and re-load only when the referenced object changes
# (VERDICT r2 weak #7: per-query re-materialization of 1M-row views).
# The state lives ON the session object (its lifetime, no id()-keyed
# global), holds strong frame refs so identity tokens stay valid, and is
# locked — the connection is shared across that session's threads.


def _session_sql_state(session) -> dict:
    st = getattr(session, "_sql_state", None)
    if st is None:
        import threading
        st = {"con": sqlite3.connect(":memory:", check_same_thread=False),
              "tokens": {}, "lock": threading.RLock()}
        session._sql_state = st
    return st


def invalidate_cached_relation(session, name: str) -> None:
    """Drop a materialized relation from the session's SQL store — called
    by catalog drops so queries on a dropped view ERROR instead of reading
    the stale sqlite copy."""
    st = getattr(session, "_sql_state", None)
    if st is None:
        return
    with st["lock"]:
        st["tokens"].pop(name, None)
        try:
            st["con"].execute(f'DROP TABLE IF EXISTS "{name}"')
        except sqlite3.Error:
            pass


def invalidate_cached_path(session, path: str) -> None:
    """Drop every materialized relation derived from `path` — the `_tt_*`
    time-travel and `_delta_*` snapshots carry (path, ...) tuple tokens
    that stay constant across a drop+recreate at the same path, so a
    name-only invalidation would leave pre-drop snapshots live (ADVICE r3:
    `VERSION AS OF n` after recreate must not see the old table)."""
    st = getattr(session, "_sql_state", None)
    if st is None:
        return
    with st["lock"]:
        stale = [n for n, tok in st["tokens"].items()
                 if isinstance(tok, tuple) and tok and tok[0] == path]
        for n in stale:
            st["tokens"].pop(n, None)
            try:
                st["con"].execute(f'DROP TABLE IF EXISTS "{n}"')
            except sqlite3.Error:
                pass


def _materialize_cached(st, name: str, token, loader) -> None:
    """Load `name` into the session db unless the same `token` already did.
    Tokens compare by identity for frames (immutable once registered) and
    by equality for (path, version) tuples. Caller holds st["lock"]."""
    prev = st["tokens"].get(name)
    same = prev is token if not isinstance(token, tuple) else prev == token
    if same:
        return
    _to_sqlite(loader(), name, st["con"])
    st["tokens"][name] = token


def run_sql(session: "TpuSession", query: str):
    from .dataframe import DataFrame

    q = query.strip().rstrip(";")
    ql = q.lower()

    # --- DDL / catalog statements -----------------------------------------
    m = re.match(r"create\s+database\s+(if\s+not\s+exists\s+)?([\w`]+)(\s+location\s+'([^']*)')?",
                 ql)
    if m:
        name = re.match(r"create\s+database\s+(?:if\s+not\s+exists\s+)?([\w`]+)", q,
                        re.I).group(1).strip("`")
        session.catalog._create_database(name)
        return _empty(session)
    m = re.match(r"drop\s+database\s+(if\s+exists\s+)?([\w`]+)(\s+cascade)?", ql)
    if m:
        name = re.match(r"drop\s+database\s+(?:if\s+exists\s+)?([\w`]+)", q, re.I).group(1).strip("`")
        session.catalog._drop_database(name)
        return _empty(session)
    if ql.startswith("use "):
        session.catalog._use_database(q.split()[-1].strip("`"))
        return _empty(session)
    if ql.startswith("drop table"):
        name = q.split()[-1].strip("`")
        session.catalog._drop_table(name)
        return _empty(session)
    if ql.startswith("show tables"):
        rows = [{"database": d, "tableName": t, "isTemporary": tmp}
                for d, t, tmp in session.catalog._list_tables()]
        return DataFrame.from_pandas(pd.DataFrame(rows), session=session, num_partitions=1)
    m = re.match(r"describe\s+history\s+(.*)", q, re.I)
    if m:
        from ..delta.table import DeltaTable
        target = m.group(1).strip()
        dm = _DELTA_REF.match(target)
        path = dm.group(1) if dm else session.catalog._table_path(target.strip("`"))
        return DeltaTable.forPath(session, path).history()
    m = re.match(r"describe\s+(detail\s+)?(.*)", q, re.I)
    if m and not ql.startswith("describe select"):
        target = m.group(2).strip().strip("`")
        df = session.table(target)
        rows = [{"col_name": n, "data_type": t, "comment": None} for n, t in df.dtypes]
        return DataFrame.from_pandas(pd.DataFrame(rows), session=session, num_partitions=1)

    # --- SELECT via the session's cached sqlite store ---------------------
    st = _session_sql_state(session)
    st["lock"].acquire()
    try:
        return _run_select(session, st, q)
    finally:
        st["lock"].release()


def _run_select(session: "TpuSession", st: dict, q: str):
    from .dataframe import DataFrame
    con = st["con"]
    from ..delta.table import read_delta, _list_versions

    def _latest_version(path):
        vs = _list_versions(path)
        return vs[-1] if vs else -1

    # Time travel in SELECT (`ML 00c:184-209`): both the clause form
    # `delta.`p` VERSION AS OF n` / `TIMESTAMP AS OF 'ts'` (also on
    # registered table names) and the `delta.`p@vN` shorthand.
    def repl_travel(m_):
        target, kind, value = m_.group(1), m_.group(2), m_.group(3)
        dm = _DELTA_REF.match(target)
        path = dm.group(1) if dm else \
            session.catalog._table_path(target.strip("`"))
        key = "versionAsOf" if kind.lower().startswith("version") \
            else "timestampAsOf"
        tbl = "_tt_" + re.sub(r"\W", "_", f"{path}_{kind[0]}_{value}")
        _materialize_cached(
            st, tbl, (path, kind.lower(), str(value)),
            lambda: read_delta(path, session,
                               {key: value.strip("'\"")}).toPandas())
        return tbl

    q2 = re.sub(
        r"(delta\.`[^`]+`|[\w.`]+)\s+(version|timestamp)\s+as\s+of\s+"
        r"('[^']*'|\"[^\"]*\"|\d+)", repl_travel, q, flags=re.I)

    # Materialize delta.`path` references as temp tables.
    def repl(m_):
        path = m_.group(1)
        opts = {}
        at = re.search(r"@v(\d+)$", path)
        if at:  # delta.`path@vN` version shorthand
            path = path[:at.start()]
            opts["versionAsOf"] = int(at.group(1))
        tbl = "_delta_" + re.sub(r"\W", "_", m_.group(1))
        version = opts.get("versionAsOf", _latest_version(path))
        _materialize_cached(
            st, tbl, (path, version),
            lambda: read_delta(path, session, opts).toPandas())
        return tbl

    q2 = _DELTA_REF.sub(repl, q2)

    for name, df in session.catalog._views().items():
        if re.search(rf"\b{re.escape(name)}\b", q2, re.I):
            _materialize_cached(st, name, df, df.toPandas)
    for fqname, (path, fmt) in session.catalog._tables().items():
        short = fqname.split(".")[-1]
        for candidate in (fqname, short):
            if re.search(rf"\b{re.escape(candidate)}\b", q2, re.I):
                tbl = candidate.replace(".", "_")
                token = (path, _latest_version(path)) if fmt == "delta" \
                    else (path, _path_mtime(path))
                _materialize_cached(
                    st, tbl, token,
                    lambda fq=fqname: session.table(fq).toPandas())
                q2 = re.sub(rf"\b{re.escape(candidate)}\b", tbl, q2)
                break
    res = pd.read_sql_query(q2, con)
    return DataFrame.from_pandas(res, session=session)


def _path_mtime(path: str) -> float:
    """Recursive newest-file mtime (partitioned tables append in nested
    dirs); 0.0 for missing/empty paths."""
    import os
    try:
        if not os.path.isdir(path):
            return os.path.getmtime(path)
        newest = 0.0
        for root, _dirs, files in os.walk(path):
            for f in files:
                try:
                    newest = max(newest, os.path.getmtime(
                        os.path.join(root, f)))
                except OSError:
                    pass
        return newest
    except OSError:
        return 0.0


def _to_sqlite(pdf: pd.DataFrame, name: str, con) -> None:
    safe = pdf.copy()
    primitives = (type(None), str, bytes, bool, int, float,
                  np.integer, np.floating, np.bool_)
    for c in safe.columns:
        kind = getattr(safe[c].dtype, "kind", "O")
        if kind not in "ifubmM":  # objects, extension arrays (vectors), …
            safe[c] = pd.Series(
                [v if isinstance(v, primitives) else str(v)
                 for v in safe[c]], index=safe.index, dtype=object)
    safe.to_sql(name, con, index=False, if_exists="replace")


def _empty(session):
    from .dataframe import DataFrame
    return DataFrame.from_pandas(pd.DataFrame(), session=session, num_partitions=1)
