"""Spark's randomSplit sampler, draw for draw (SURVEY §4 north star;
VERDICT r4 missing #1).

The course makes split mechanics a first-class lesson: `randomSplit(seed=42)`
results change with the partition layout (`SML/Scalable-Machine-Learning-
with-Apache-Spark/ML 02 - Linear Regression I.py:38-52`). Spark's mechanism
is a published pure algorithm, reimplemented here without a JVM:

- `Dataset.randomSplit` first SORTS each partition locally by every
  sortable column ascending (to make per-partition row order
  deterministic), then samples each weight cell
  (sql/core/.../Dataset.scala `randomSplit`).
- Each cell is a `BernoulliCellSampler(lb, ub)`: one uniform draw per row,
  row kept iff `lb <= x < ub` — no gap sampling
  (core/.../util/random/RandomSampler.scala).
- The per-partition RNG is `XORShiftRandom` seeded with
  `seed + partitionIndex`, whose init scrambles the seed through
  MurmurHash3 of a 64-BYTE buffer — `ByteBuffer.allocate(java.lang.
  Long.SIZE)` where `Long.SIZE` is 64 *bits*, so Spark actually hashes
  the 8 big-endian seed bytes followed by 56 zeros, with length-64
  finalization (core/.../util/random/XORShiftRandom.scala `hashSeed`) —
  and whose `nextDouble` is java.util.Random's two-word construction
  over the XORShift `next(bits)`.

Known deviation (documented): our frames store SQL NULL as NaN, so the
pre-split sort places missing doubles FIRST (pandas na_position) where
Spark places true NaN LAST and NULL first — frames with missing numeric
values can order ties differently. String sort is bytewise-equal to
Spark's UTF8 binary order for ASCII data.
"""

from __future__ import annotations

import ctypes
import threading
from typing import Optional

import numpy as np
import pandas as pd

# ---------------------------------------------------------------- MurmurHash3
# scala.util.hashing.MurmurHash3.bytesHash over the buffer Spark builds in
# XORShiftRandom.hashSeed. Words are read little-endian (scala bytesHash);
# 64 bytes = 16 full words, no tail. The 56 zero words are NOT no-ops:
# each word still rotates and remixes h, and finalization xors the length.
_ARRAY_SEED = 0x3C074A61  # scala.util.hashing.MurmurHash3.arraySeed

_M = 0xFFFFFFFF


def _rotl(x: int, r: int) -> int:
    return ((x << r) | (x >> (32 - r))) & _M


def _mm3_bytes(data: bytes, seed: int) -> int:
    """murmur3_x86_32 over a word-aligned buffer (scala bytesHash
    semantics: little-endian words, length-xor finalization)."""
    h = seed & _M
    for i in range(0, len(data), 4):
        k = int.from_bytes(data[i:i + 4], "little")
        k = (k * 0xCC9E2D51) & _M
        k = _rotl(k, 15)
        k = (k * 0x1B873593) & _M
        h ^= k
        h = _rotl(h, 13)
        h = (h * 5 + 0xE6546B64) & _M
    h ^= len(data)  # finalize with length
    h ^= h >> 16
    h = (h * 0x85EBCA6B) & _M
    h ^= h >> 13
    h = (h * 0xC2B2AE35) & _M
    h ^= h >> 16
    return h


def hash_seed(seed: int) -> int:
    """XORShiftRandom.hashSeed: two chained MurmurHash3 passes over the
    64-byte buffer Spark actually hashes — `ByteBuffer.allocate(java.lang.
    Long.SIZE)` allocates Long.SIZE=64 BYTES (the constant is in bits), so
    the buffer is the seed's 8 big-endian bytes plus 56 zeros, finalized
    with length 64 -> 64-bit init state."""
    data = (seed & 0xFFFFFFFFFFFFFFFF).to_bytes(8, "big") + b"\x00" * 56
    low = _mm3_bytes(data, _ARRAY_SEED)
    high = _mm3_bytes(data, low)
    return ((high << 32) | low) & 0xFFFFFFFFFFFFFFFF


# ------------------------------------------------------------ XORShiftRandom
class XORShiftRandom:
    """Pure-python reference (the native kernel is the fast path)."""

    def __init__(self, seed: int):
        self._s = hash_seed(seed)

    def _next(self, bits: int) -> int:
        s = self._s
        x = (s ^ (s << 21)) & 0xFFFFFFFFFFFFFFFF
        x ^= x >> 35
        x = (x ^ (x << 4)) & 0xFFFFFFFFFFFFFFFF
        self._s = x
        return x & ((1 << bits) - 1)

    def next_double(self) -> float:
        return ((self._next(26) << 27) + self._next(27)) * (2.0 ** -53)


_lib_lock = threading.Lock()
_lib_state: dict = {}


def _xorshift_lib():
    with _lib_lock:
        if "lib" not in _lib_state:
            from ..native.build import load_library
            lib = load_library("xorshift")
            if lib is not None:
                lib.xorshift_fill_doubles.argtypes = [
                    ctypes.c_longlong, ctypes.c_longlong,
                    ctypes.POINTER(ctypes.c_double)]
                lib.xorshift_fill_doubles.restype = None
            _lib_state["lib"] = lib
        return _lib_state["lib"]


def partition_uniforms(seed: int, partition_index: int, n: int) -> np.ndarray:
    """The n sequential nextDouble draws Spark's sampler makes for one
    partition: XORShiftRandom(seed + partitionIndex). Every weight cell of
    one randomSplit re-draws this same sequence (Spark seeds each cell's
    sampler identically), which is what makes the splits disjoint and
    exhaustive."""
    out = np.empty(n, dtype=np.float64)
    if n == 0:
        return out
    hashed = hash_seed(seed + partition_index)
    lib = _xorshift_lib()
    if lib is not None:
        lib.xorshift_fill_doubles(
            ctypes.c_longlong(
                hashed - (1 << 64) if hashed >= (1 << 63) else hashed),
            ctypes.c_longlong(n),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
        return out
    rng = XORShiftRandom(seed + partition_index)
    rng._s = hashed  # skip re-hashing
    for i in range(n):
        out[i] = rng.next_double()
    return out


# ----------------------------------------------------- stateless per-row draws
# The out-of-core data plane (frame/_chunks.py) decides split/sample
# membership per GLOBAL ROW INDEX, not per partition stream: a stateless
# counter-based hash of (seed, row) is random-access, so any chunk can
# compute its own rows' draws without replaying a sequential stream —
# the host mirror of the PR-6 `tree_impl._sliced_draw` layout-invariance
# scheme (one replicated key, each shard slicing its block). Split
# membership is therefore bit-identical for ANY chunking of the same
# rows (tests/test_chunked_ingest.py pins it).

_U64 = np.uint64
_SPLITMIX_GAMMA = 0x9E3779B97F4A7C15  # splitmix64's golden-gamma increment


def row_uniforms(seed: int, start: int, n: int) -> np.ndarray:
    """Uniform [0, 1) draw per global row index in [start, start+n):
    splitmix64 finalizer over a (seed, index) counter — vectorized, no
    sequential state, identical per row regardless of the chunk layout
    that asked. (This is deliberately NOT the Spark-parity sampler: the
    XORShift stream is sequential per partition; the chunked plane needs
    random access.)"""
    if n == 0:
        return np.empty(0, dtype=np.float64)
    idx = np.arange(start, start + n, dtype=np.uint64)
    # mix the seed into the counter stream, then splitmix64-finalize
    z = (_U64((int(seed) * 0xD1B54A32D192ED03) & 0xFFFFFFFFFFFFFFFF)
         + (idx + _U64(1)) * _U64(_SPLITMIX_GAMMA))
    z = (z ^ (z >> _U64(30))) * _U64(0xBF58476D1CE4E5B9)
    z = (z ^ (z >> _U64(27))) * _U64(0x94D049BB133111EB)
    z = z ^ (z >> _U64(31))
    # top 53 bits -> double in [0, 1), the java/Random two-word convention
    return (z >> _U64(11)).astype(np.float64) * (2.0 ** -53)


# ------------------------------------------------------- pre-split local sort
# id(source pdf) -> (source, sorted, cost_bytes). BYTE-bounded like the
# repo's other memos (sml.split.sortMemoBytes): each entry strong-refs a
# full partition AND its sorted copy, so a count-only bound could pin
# multi-GB of pandas data for the process lifetime.
_sort_memo: dict = {}
_sort_memo_bytes: list = [0]
_sort_lock = threading.Lock()


def _pdf_cost(pdf: pd.DataFrame) -> int:
    """Approximate resident bytes (deep=True counts string payloads —
    cheap next to the sort this memo amortizes)."""
    try:
        return int(pdf.memory_usage(index=True, deep=True).sum())
    except Exception:
        return int(pdf.shape[0] * max(pdf.shape[1], 1) * 8)


def drop_sort_memo_for(parts) -> None:
    """Invalidate sort-memo entries sourced from these partition frames
    (DataFrame.unpersist calls this, so dropping a cached frame actually
    releases its pre-split sort copies too)."""
    if not parts:
        return
    ids = {id(p) for p in parts}
    with _sort_lock:
        for k in [k for k in _sort_memo if k in ids]:
            _sort_memo_bytes[0] -= _sort_memo.pop(k)[2]


def presplit_sort(pdf: pd.DataFrame) -> pd.DataFrame:
    """Dataset.randomSplit's per-partition local sort: every sortable
    column ascending, in schema order, nulls first — making row order
    deterministic regardless of upstream partition materialization.
    Unsortable columns (vector/extension payloads, mixed objects) are
    pruned from the sort order, as Spark prunes unsortable types."""
    with _sort_lock:
        hit = _sort_memo.get(id(pdf))
        if hit is not None and hit[0] is pdf:
            # LRU touch (dicts iterate in insertion order): a split's
            # later weight cells re-hit its partitions, so eviction under
            # byte pressure should fall on stale splits first
            _sort_memo.pop(id(pdf))
            _sort_memo[id(pdf)] = hit
    if hit is not None and hit[0] is pdf:
        return hit[1]
    cols = []
    for c in pdf.columns:
        dt = pdf[c].dtype
        if dt.kind in "ifubMm" or isinstance(dt, pd.StringDtype):
            cols.append(c)
        elif dt == object or "string" in str(dt) or "large_string" in str(dt):
            cols.append(c)
    out = pdf
    if cols:
        try:
            out = pdf.sort_values(cols, kind="stable", na_position="first",
                                  ignore_index=True)
        except Exception:
            # a column that passed the dtype screen but still won't sort
            # (mixed-type object payloads): drop offenders one at a time —
            # probing a head slice can miss a late mixed value
            sortable = list(cols)
            while sortable:
                try:
                    out = pdf.sort_values(sortable, kind="stable",
                                          na_position="first",
                                          ignore_index=True)
                    break
                except Exception:
                    sortable.pop()
            else:
                out = pdf
    # memoize per partition object: every weight cell of one randomSplit
    # sorts the SAME partition — k cells must not pay k sorts. Strong ref
    # to the source keeps its id valid. LRU within the byte budget; the
    # NEWEST entry always stays (the split's remaining cells are about to
    # hit it) even when it alone exceeds the budget.
    from ..conf import GLOBAL_CONF
    budget = GLOBAL_CONF.getInt("sml.split.sortMemoBytes")
    # an unsortable partition memoizes (pdf, pdf): charge the one object
    cost = _pdf_cost(pdf) + (0 if out is pdf else _pdf_cost(out))
    with _sort_lock:
        if id(pdf) not in _sort_memo:
            _sort_memo[id(pdf)] = (pdf, out, cost)
            _sort_memo_bytes[0] += cost
        while _sort_memo_bytes[0] > budget and len(_sort_memo) > 1:
            _sort_memo_bytes[0] -= _sort_memo.pop(next(iter(_sort_memo)))[2]
    return out
