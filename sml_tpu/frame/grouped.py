"""GroupedData: keyed aggregation + per-group pandas training fan-out.

Covers `groupBy().count()/agg(...)` (SURVEY L1) and
`groupBy(...).applyInPandas(fn, schema)` — the per-group sklearn-training
path of `SML/ML 13 - Training with Pandas Function API.py:119-161` (P8).
The shuffle is a Murmur3 hash repartition by key; per-group functions then
run host-side (the payload is arbitrary Python: sklearn/JAX/etc.), matching
the reference's executor-side Python workers.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Union

import numpy as np
import pandas as pd

from ..conf import GLOBAL_CONF
from .column import Column, EvalContext
from .dataframe import DataFrame, _concat, _hash_repartition, coerce_to_schema
from .types import StructType, parse_schema


class GroupedData:
    def __init__(self, df: DataFrame, keys: List[Column]):
        self._df = df
        self._keys = keys

    def _grouped(self):
        # toPandas, not a fresh concat: the frame memoizes its concat, so
        # repeated grouped actions on a cached frame share one materialization
        pdf = self._df.toPandas() if hasattr(self._df, "toPandas") \
            else _concat(self._df._materialize())
        key_names = [k._name for k in self._keys]
        for k in self._keys:
            if k._name not in pdf.columns:
                pdf[k._name] = k._eval(pdf, EvalContext()).values
        return pdf, key_names

    def agg(self, *exprs) -> DataFrame:
        if len(exprs) == 1 and isinstance(exprs[0], dict):
            from . import functions as F
            mapping = {"avg": F.avg, "mean": F.avg, "max": F.max, "min": F.min,
                       "sum": F.sum, "count": F.count, "stddev": F.stddev,
                       "first": F.first, "last": F.last}
            exprs = tuple(mapping[op](c) for c, op in exprs[0].items())

        parent = self

        def compute():
            pdf, key_names = parent._grouped()
            results: Dict[str, pd.Series] = {}
            if key_names:
                gb_index = pdf.groupby(key_names, sort=False, dropna=False)
            for e in exprs:
                if e._agg is None:
                    raise ValueError(f"non-aggregate expression in agg: {e._name}")
                evaluated = e._eval(pdf, EvalContext()) if len(pdf) else pd.Series(dtype=float)
                if key_names:
                    if isinstance(evaluated, pd.DataFrame):
                        grouped = evaluated.groupby([pdf[k].values for k in key_names],
                                                    sort=False, dropna=False).apply(e._agg)
                    else:
                        grouped = evaluated.groupby([pdf[k].values for k in key_names],
                                                    sort=False, dropna=False).agg(e._agg)
                    results[e._name] = grouped
                else:
                    results[e._name] = pd.Series([e._agg(evaluated)])
            if key_names:
                keys_df = gb_index.size().reset_index()[key_names]
                out = keys_df.copy()
                for name, series in results.items():
                    series = series.reset_index(drop=True)
                    # align by recomputing group order: pandas groupby(sort=False)
                    # preserves first-appearance order in both paths
                    out[name] = series.values
            else:
                out = pd.DataFrame({k: v for k, v in results.items()})
            nparts = GLOBAL_CONF.getInt("sml.shuffle.partitions")
            if key_names:
                return _hash_repartition(out, key_names, nparts)
            return [out]

        return DataFrame(compute, session=self._df._session)

    def count(self) -> DataFrame:
        from . import functions as F
        out = self.agg(F.count("*").alias("count"))
        return out

    def _simple(self, op: str, cols) -> DataFrame:
        from . import functions as F
        fns = {"avg": F.avg, "mean": F.avg, "sum": F.sum, "min": F.min, "max": F.max}
        if not cols:
            pdf = _concat(self._df._materialize())
            cols = [c for c in pdf.columns if pdf[c].dtype.kind in "ifu"
                    and c not in [k._name for k in self._keys]]
        return self.agg(*[fns[op](c) for c in cols])

    def avg(self, *cols) -> DataFrame:
        return self._simple("avg", cols)

    mean = avg

    def sum(self, *cols) -> DataFrame:  # noqa: A003
        return self._simple("sum", cols)

    def min(self, *cols) -> DataFrame:  # noqa: A003
        return self._simple("min", cols)

    def max(self, *cols) -> DataFrame:  # noqa: A003
        return self._simple("max", cols)

    def applyInPandas(self, fn: Callable[[pd.DataFrame], pd.DataFrame],
                      schema: Union[str, StructType]) -> DataFrame:
        """Hash-shuffle by key, run `fn` once per group, enforce schema
        (`ML 13:119-127`). Group key columns are included in the input block,
        as in the reference."""
        sch = parse_schema(schema)
        parent = self

        def compute():
            pdf, key_names = parent._grouped()
            if len(pdf) == 0:
                return [coerce_to_schema(pd.DataFrame(), sch)]
            gb = pdf.groupby(key_names, sort=False, dropna=False)
            par = GLOBAL_CONF.getInt("sml.applyInPandas.parallelism")
            if gb.ngroups > 1 and par > 1:
                # per-group fns run concurrently, as on Spark executors
                # (P8): sklearn/numpy payloads release the GIL in BLAS.
                # Groups are SUBMITTED as the groupby iterator yields them,
                # so worker fns overlap with the remaining group extraction
                # (the per-group take of a wide object-column frame is the
                # expensive half of the split).
                # NOTE these are threads of ONE interpreter — a fn that
                # mutates shared closure state needs
                # sml.applyInPandas.parallelism=1 (Spark's process-isolated
                # workers could never share state in the first place)
                from concurrent.futures import ThreadPoolExecutor
                with ThreadPoolExecutor(
                        max_workers=min(par, gb.ngroups)) as ex:
                    futs = [ex.submit(fn, g.reset_index(drop=True))
                            for _, g in gb]
                    outs = [coerce_to_schema(f.result(), sch) for f in futs]
            else:
                outs = [coerce_to_schema(fn(g.reset_index(drop=True)), sch)
                        for _, g in gb]
            full = pd.concat(outs, ignore_index=True)
            nparts = min(len(outs), GLOBAL_CONF.getInt("sml.shuffle.partitions"))
            avail = [k for k in key_names if k in full.columns]
            if avail:
                return _hash_repartition(full, avail, max(1, nparts))
            return [full]

        return DataFrame(compute, session=self._df._session, schema=sch)

    def applyInPandasWithState(self, *a, **k):
        raise NotImplementedError("stateful streaming aggregation is not supported")

    def pivot(self, pivot_col: str, values=None) -> "GroupedData":
        raise NotImplementedError("pivot is not in the covered course surface")
