"""GroupedData: keyed aggregation + per-group pandas training fan-out.

Covers `groupBy().count()/agg(...)` (SURVEY L1) and
`groupBy(...).applyInPandas(fn, schema)` — the per-group sklearn-training
path of `SML/ML 13 - Training with Pandas Function API.py:119-161` (P8).
The shuffle is a Murmur3 hash repartition by key; per-group functions then
run host-side (the payload is arbitrary Python: sklearn/JAX/etc.), matching
the reference's executor-side Python workers.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Union

import numpy as np
import pandas as pd

from ..conf import GLOBAL_CONF
from .column import Column, EvalContext
from .dataframe import DataFrame, _concat, _hash_repartition, coerce_to_schema
from .types import StructType, parse_schema


import threading as _threading

# SHUFFLE REUSE (SURVEY L1): Spark reuses shuffle files when the same
# stage re-executes over unchanged lineage; here the per-key group split
# of a cached frame is the shuffle output, memoized by the identity of
# the frame's memoized concat (id-stable for cached frames, held strongly
# so the id cannot be recycled). Entries: (token, groups, bytes);
# byte-bounded LRU (sml.shuffle.reuseBytes) — a split pins a full copy of
# its dataset, so a count-only bound would hold multi-GB frames for the
# process lifetime. `DataFrame.unpersist` drops matching entries.
# Handed-out groups are CoW shallow copies, so a fn that mutates its
# input cannot pollute the cache (pandas>=3 copy-on-write is always on;
# under an older pandas with CoW disabled the handout deep-copies, the
# same defense DataFrame.toPandas applies).
_split_cache: Dict[tuple, tuple] = {}
_split_lock = _threading.Lock()


def _split_cache_put(ckey, token, groups) -> None:
    # deep accounting: the split's cost IS its string payloads (shallow
    # counts object columns at pointer size), plus the pinned token frame
    nbytes = int(sum(int(g.memory_usage(deep=True).sum()) for g in groups))
    nbytes += int(token.memory_usage(deep=True).sum())
    max_bytes = GLOBAL_CONF.getInt("sml.shuffle.reuseBytes")
    if nbytes > max_bytes:
        return
    with _split_lock:
        _split_cache[ckey] = (token, groups, nbytes)
        total = sum(e[2] for e in _split_cache.values())
        while len(_split_cache) > 1 and total > max_bytes:
            total -= _split_cache.pop(next(iter(_split_cache)))[2]


def drop_split_cache_for(token) -> None:
    """Invalidate shuffle-reuse entries for a frame's memoized concat
    (DataFrame.unpersist calls this so dropping a cached frame actually
    releases the split's memory)."""
    if token is None:
        return
    with _split_lock:
        for k in [k for k, v in _split_cache.items() if v[0] is token]:
            _split_cache.pop(k)


def _group_handout(g: pd.DataFrame) -> pd.DataFrame:
    """The frame a user fn receives: shallow under CoW (writes can't reach
    the cached split), deep when someone disabled CoW on an older pandas."""
    if int(pd.__version__.split(".")[0]) < 3 \
            and pd.options.mode.copy_on_write is not True:
        return g.copy(deep=True)
    return g.copy(deep=False)


class GroupedData:
    def __init__(self, df: DataFrame, keys: List[Column]):
        self._df = df
        self._keys = keys

    def _grouped(self):
        # toPandas, not a fresh concat: the frame memoizes its concat, so
        # repeated grouped actions on a cached frame share one materialization
        if hasattr(self._df, "toPandas"):
            pdf = self._df.toPandas()
            token = self._df.__dict__.get("_pdf_cache")
        else:
            pdf = _concat(self._df._materialize())
            token = None
        key_names = [k._name for k in self._keys]
        for k in self._keys:
            if k._name not in pdf.columns:
                pdf[k._name] = k._eval(pdf, EvalContext()).values
                token = None  # computed key: beyond the memoized concat
        return pdf, key_names, token

    def agg(self, *exprs) -> DataFrame:
        if len(exprs) == 1 and isinstance(exprs[0], dict):
            from . import functions as F
            mapping = {"avg": F.avg, "mean": F.avg, "max": F.max, "min": F.min,
                       "sum": F.sum, "count": F.count, "stddev": F.stddev,
                       "first": F.first, "last": F.last}
            exprs = tuple(mapping[op](c) for c, op in exprs[0].items())

        parent = self

        def compute():
            pdf, key_names, _ = parent._grouped()
            results: Dict[str, pd.Series] = {}
            if key_names:
                gb_index = pdf.groupby(key_names, sort=False, dropna=False)
            for e in exprs:
                if e._agg is None:
                    raise ValueError(f"non-aggregate expression in agg: {e._name}")
                evaluated = e._eval(pdf, EvalContext()) if len(pdf) else pd.Series(dtype=float)
                if key_names:
                    if isinstance(evaluated, pd.DataFrame):
                        grouped = evaluated.groupby([pdf[k].values for k in key_names],
                                                    sort=False, dropna=False).apply(e._agg)
                    else:
                        grouped = evaluated.groupby([pdf[k].values for k in key_names],
                                                    sort=False, dropna=False).agg(e._agg)
                    results[e._name] = grouped
                else:
                    results[e._name] = pd.Series([e._agg(evaluated)])
            if key_names:
                keys_df = gb_index.size().reset_index()[key_names]
                out = keys_df.copy()
                for name, series in results.items():
                    series = series.reset_index(drop=True)
                    # align by recomputing group order: pandas groupby(sort=False)
                    # preserves first-appearance order in both paths
                    out[name] = series.values
            else:
                out = pd.DataFrame({k: v for k, v in results.items()})
            nparts = GLOBAL_CONF.getInt("sml.shuffle.partitions")
            if key_names:
                return _hash_repartition(out, key_names, nparts)
            return [out]

        return DataFrame(compute, session=self._df._session)

    def count(self) -> DataFrame:
        from . import functions as F
        out = self.agg(F.count("*").alias("count"))
        return out

    def _simple(self, op: str, cols) -> DataFrame:
        from . import functions as F
        fns = {"avg": F.avg, "mean": F.avg, "sum": F.sum, "min": F.min, "max": F.max}
        if not cols:
            pdf = _concat(self._df._materialize())
            cols = [c for c in pdf.columns if pdf[c].dtype.kind in "ifu"
                    and c not in [k._name for k in self._keys]]
        return self.agg(*[fns[op](c) for c in cols])

    def avg(self, *cols) -> DataFrame:
        return self._simple("avg", cols)

    mean = avg

    def sum(self, *cols) -> DataFrame:  # noqa: A003
        return self._simple("sum", cols)

    def min(self, *cols) -> DataFrame:  # noqa: A003
        return self._simple("min", cols)

    def max(self, *cols) -> DataFrame:  # noqa: A003
        return self._simple("max", cols)

    def applyInPandas(self, fn: Callable[[pd.DataFrame], pd.DataFrame],
                      schema: Union[str, StructType]) -> DataFrame:
        """Hash-shuffle by key, run `fn` once per group, enforce schema
        (`ML 13:119-127`). Group key columns are included in the input block,
        as in the reference."""
        sch = parse_schema(schema)
        parent = self

        def compute():
            pdf, key_names, token = parent._grouped()
            if len(pdf) == 0:
                return [coerce_to_schema(pd.DataFrame(), sch)]
            ckey = ((id(token), tuple(key_names))
                    if token is not None else None)
            groups = None
            if ckey is not None:
                with _split_lock:
                    hit = _split_cache.get(ckey)
                # `is` check: the strong ref in the entry keeps the id
                # valid, but a rebuilt concat for the same frame must miss
                if hit is not None and hit[0] is token:
                    groups = hit[1]
            par = GLOBAL_CONF.getInt("sml.applyInPandas.parallelism")
            from concurrent.futures import ThreadPoolExecutor
            if groups is not None:
                # shuffle reuse: the split is already materialized — the
                # leg is pure fn execution, fanned across workers
                if len(groups) > 1 and par > 1:
                    with ThreadPoolExecutor(
                            max_workers=min(par, len(groups))) as ex:
                        futs = [ex.submit(fn, _group_handout(g))
                                for g in groups]
                        outs = [coerce_to_schema(f.result(), sch)
                                for f in futs]
                else:
                    outs = [coerce_to_schema(fn(_group_handout(g)), sch)
                            for g in groups]
            else:
                gb = pdf.groupby(key_names, sort=False, dropna=False)
                collected = []

                def split():
                    for _, g in gb:
                        g = g.reset_index(drop=True)
                        if ckey is not None:  # else: never cached — don't
                            collected.append(g)  # pin a dataset copy
                        yield g

                if gb.ngroups > 1 and par > 1:
                    # per-group fns run concurrently, as on Spark executors
                    # (P8): sklearn/numpy payloads release the GIL in BLAS.
                    # Groups are SUBMITTED as the groupby iterator yields
                    # them, so worker fns overlap with the remaining group
                    # extraction (the per-group take of a wide
                    # object-column frame is the expensive half of the
                    # split).
                    # NOTE these are threads of ONE interpreter — a fn that
                    # mutates shared closure state needs
                    # sml.applyInPandas.parallelism=1 (Spark's
                    # process-isolated workers could never share state in
                    # the first place)
                    with ThreadPoolExecutor(
                            max_workers=min(par, gb.ngroups)) as ex:
                        futs = [ex.submit(fn, _group_handout(g))
                                for g in split()]
                        outs = [coerce_to_schema(f.result(), sch)
                                for f in futs]
                else:
                    outs = [coerce_to_schema(fn(_group_handout(g)), sch)
                            for g in split()]
                if ckey is not None:
                    _split_cache_put(ckey, token, collected)
            full = pd.concat(outs, ignore_index=True)
            nparts = min(len(outs), GLOBAL_CONF.getInt("sml.shuffle.partitions"))
            avail = [k for k in key_names if k in full.columns]
            if avail:
                return _hash_repartition(full, avail, max(1, nparts))
            return [full]

        return DataFrame(compute, session=self._df._session, schema=sch)

    def applyInPandasWithState(self, *a, **k):
        raise NotImplementedError("stateful streaming aggregation is not supported")

    def pivot(self, pivot_col: str, values=None) -> "GroupedData":
        raise NotImplementedError("pivot is not in the covered course surface")
