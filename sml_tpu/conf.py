"""Typed layered configuration tree (the `spark.conf` equivalent).

The reference uses Spark conf as an ad-hoc KV store: course keys
(`SML/Includes/Classroom-Setup.py:2`), engine knobs such as
`spark.sql.shuffle.partitions` (`Solutions/Labs/ML 00L`) and the Arrow batch
size `spark.sql.execution.arrow.maxRecordsPerBatch`
(`SML/ML 12 - Inference with Pandas UDFs.py:90,121`), plus Delta retention
checks (`SML/ML 00c - Delta Review.py:235`).

Here the same surface is one typed config tree: known keys carry a type and a
default; unknown keys are allowed as free-form strings (the course stores its
own `com.databricks.training.*` keys that way).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional


@dataclass(frozen=True)
class ConfEntry:
    key: str
    default: Any
    caster: Callable[[str], Any]
    doc: str = ""


def _to_bool(v: Any) -> bool:
    if isinstance(v, bool):
        return v
    return str(v).strip().lower() in ("true", "1", "yes", "on")


_KNOWN: Dict[str, ConfEntry] = {}


def _register(key: str, default: Any, caster: Callable[[str], Any], doc: str = "") -> None:
    _KNOWN[key] = ConfEntry(key, default, caster, doc)


# Engine knobs the courseware actually touches, plus our TPU-side knobs.
_register("sml.shuffle.partitions", 8, int, "Partition count after shuffles (spark.sql.shuffle.partitions)")
_register("spark.sql.shuffle.partitions", 8, int, "Alias kept for course compatibility")
_register("sml.arrow.maxRecordsPerBatch", 10000, int, "Arrow record-batch size for pandas-fn fan-out")
_register("spark.sql.execution.arrow.maxRecordsPerBatch", 10000, int, "Alias kept for course compatibility")
_register("sml.delta.retentionDurationCheck.enabled", True, _to_bool, "Refuse vacuum(0) unless disabled")
_register("spark.databricks.delta.retentionDurationCheck.enabled", True, _to_bool, "Alias for course compatibility")
_register("sml.default.parallelism", 8, int, "Default partition count for new data sources")
# (sml.tpu.mesh.axis was registered here until PR 3: the mesh axis name is
# the parallel.mesh.DATA_AXIS constant and the knob was never read — the
# graftlint conf-key-registry rule now keeps such dead keys out.)
_register("sml.tpu.donate", True, _to_bool, "Donate input buffers on training steps")
_register("sml.profiler.enabled", False, _to_bool, "Record op-level timings")
_register("sml.applyInPandas.parallelism", 8, int,
          "Concurrent per-group fn threads in applyInPandas; 1 = sequential "
          "(needed only by fns that mutate shared closure state)")
_register("sml.predict.binCacheBytes", 1 << 30, int,
          "LRU byte bound for memoized predict-time binned matrices (CV/"
          "tuning suites hold ~20 (matrix, model-edges) pairs at once)")
_register("sml.tree.histSubtraction", True, _to_bool,
          "Histogram-subtraction tree builds (right child = parent - "
          "left sibling): halves the hist matmul below the root. Exact "
          "counts with the built-in integer sampling weights; fractional "
          "fit_tree weights and grad/hess sums pick up depth-compounding "
          "cancellation noise")
_register("sml.tree.hierarchicalAllreduce", "auto", str,
          "Two-level histogram allreduce on host-grouped meshes: "
          "'auto' = intra-group reduce-scatter over the 'ici' hop + "
          "inter-group allreduce over the 'dcn' hop + allgather back "
          "whenever the active mesh declares a host axis "
          "(mesh.host_mesh); 'true' = same, but error-prone on flat "
          "meshes so it still requires the host axes; 'false' = always "
          "the flat single-hop psum. Per-hop launches/bytes land in "
          "collective.psum[_bytes].ici/.dcn (docs/PERF.md)")
_register("sml.mesh.hostGroups", 0, int,
          "Default host-group count for mesh.host_mesh() when called "
          "without an explicit `hosts`: 0 = auto (jax.process_count() "
          "on a real multi-host slice, else 1); N>0 = N virtual host "
          "groups partitioning the flat device set (the single-machine "
          "testing story for the multi-host code path)")
_register("sml.tree.kernel", "auto", str,
          "Histogram-build + split-scan implementation for tree fits: "
          "'xla' = the one-hot dot + cumsum HLO chain (the pre-kernel "
          "path, kept verbatim); 'pallas' = the fused "
          "sml_tpu/native/hist_kernel.py Pallas kernels (bin-accumulate "
          "straight from the compact bin cache, in-register gain scan; "
          "runs in interpret mode on non-TPU backends — the tier-1 "
          "bit-parity testing story); 'auto' = pallas on real TPU only, "
          "xla everywhere else. Unavailable pallas falls back to xla and "
          "counts kernel.fallback. See docs/KERNELS.md")
_register("sml.tree.kernelBlockRows", 4096, int,
          "Row-block size of the pallas bin-accumulate kernel's grid on "
          "hardware (bounds the VMEM one-hot tile to ~blockRows*F*bins "
          "elements; actual block is the largest divisor of the per-chip "
          "padded rows at or under this). Interpret mode always runs ONE "
          "block so kernel math is op-for-op the XLA path's "
          "(bit-parity)")
_register("sml.split.sampler", "spark", str,
          "randomSplit sampler: 'spark' = draw-for-draw Spark parity "
          "(per-partition determinism sort + XORShiftRandom Bernoulli "
          "cells); 'legacy' = the pre-r5 numpy draws")
_register("sml.shuffle.reuseBytes", 1 << 30, int,
          "Byte bound for the shuffle-reuse cache (memoized applyInPandas "
          "group splits of cached frames); 0 disables reuse")
_register("sml.linear.compactBytes", 1 << 28, int,
          "Expanded-block size (n*d*4) above which linear/logistic fits "
          "stage the compact numeric+code form and expand one-hot slots "
          "on-chip instead of materializing the (n, d) matrix")
_register("sml.fit.foldStackBytes", 1 << 30, int,
          "Byte bound for the fit-time fold-stack memo (stacked CV fold "
          "datasets reused across a tuning grid); independent of the "
          "predict bin cache's budget")
_register("sml.tree.binCacheBytes", 2 << 30, int,
          "Device-bytes budget for the quantized bin-index cache (compact "
          "uint8/uint16 bin matrices staged once per dataset and reused by "
          "every tree, boosting round, and CV fold); separate from the "
          "general staging budget so fold stacks cannot evict hot bins")
_register("sml.tree.roundsPerDispatch", 0, int,
          "Boosting rounds fused per device dispatch. 0 = the whole "
          "ensemble in one scan program (default). k > 0 chunks the scan "
          "into ceil(n_trees/k) dispatches whose margin carry stays in HBM "
          "with the input buffer DONATED between chunks — bounds compile "
          "time for very deep ensembles without per-round host transfers")
_register("sml.compile.cacheDir", "", str,
          "Persistent XLA compilation-cache directory. Empty = the "
          "repo-local .jax_cache default (or JAX_COMPILATION_CACHE_DIR / "
          "SML_TPU_COMPILE_CACHE when set); applied at import and "
          "re-applied whenever this key is set "
          "(parallel.dispatch.ensure_compile_cache)")
_register("sml.split.sortMemoBytes", 1 << 30, int,
          "Byte bound for randomSplit's pre-split sort memo (each entry "
          "pins the source partition AND its sorted copy); entries for a "
          "frame are also dropped by DataFrame.unpersist. Sized like the "
          "sibling caches so one bench-scale frame's partitions fit — a "
          "budget below one split's working set makes every later weight "
          "cell re-sort (FIFO evicts the in-flight split's own entries)")
_register("sml.obs.enabled", False, _to_bool,
          "Flight-recorder event bus (sml_tpu.obs): record typed engine "
          "events (spans, counters, dispatch decisions, cache traffic, "
          "collectives, compiles, HBM ledger gauges) into a bounded ring "
          "buffer for Chrome-trace export, the dispatch audit, and run "
          "autologging. Disabled, every instrumentation site costs one "
          "attribute load")
_register("sml.obs.ringEvents", 65536, int,
          "Capacity of the flight recorder's in-memory event ring; the "
          "oldest events are dropped (and counted) once full. Resizing "
          "preserves the newest events")
_register("sml.obs.sinkPath", "", str,
          "Optional JSONL sink: every recorded event is also appended to "
          "this file as one JSON object per line (empty = ring only). "
          "Applied immediately when set")
_register("sml.obs.sinkMaxBytes", 64 << 20, int,
          "Byte bound for the JSONL sink file: past it the live file "
          "rotates ONCE to <sinkPath>.1 (replacing the previous roll) and "
          "reopens fresh, so the sink holds at most ~2x this bound on "
          "disk. 0 = unlimited (the pre-PR-7 behavior)")
_register("sml.obs.metricsWindowSec", 300, int,
          "Rolling-window span of the streaming metrics registry "
          "(obs/_metrics.py): windowed quantiles and rates cover the "
          "trailing this-many seconds (8 ring slots); all-time "
          "histograms are kept regardless")
_register("sml.obs.autoLogRunMetrics", True, _to_bool,
          "With the recorder enabled, every outermost Estimator.fit under "
          "an active tracking run logs engine.* metrics (h2d/d2h bytes, "
          "cache hit rates, route mix, compile count, peak HBM ledger "
          "bytes) to the run — the MLflow system-metrics equivalent")
_register("sml.obs.driftBaselineRows", 32768, int,
          "Fit-time drift-baseline capture (obs/drift.py): with the "
          "recorder on (sml.obs.enabled — an obs-off fit pays one "
          "attribute load, not a sketch pass), tree fits sketch up to "
          "this many deterministically-strided training rows (features "
          "+ label + the model's own predictions) into the fitted "
          "model's DriftBaseline, persisted with the model and logged "
          "through tracking.log_model; persisted sketches compress to "
          "the sml.data.sketchBuckets centroid budget. 0 disables "
          "capture. Also bounds the retained values per stream of "
          "serving live-window sketches. The chunked-ingest path "
          "reuses its full-data pass-1 sketch instead (no extra cost)")
_register("sml.obs.driftBins", 10, int,
          "PSI cell count for drift distances: live-vs-baseline "
          "population stability is measured over this many "
          "equal-probability cells cut at the BASELINE's quantiles")
_register("sml.obs.driftMargin", 2.0, float,
          "Drift flag threshold as a multiple of the noise floor (the "
          "max self-distance of resampled-baseline iid windows): a "
          "feature flags when its distance exceeds margin x floor. "
          "Higher = less sensitive")
_register("sml.obs.driftMinRows", 256, int,
          "Minimum live rows in a drift window before it is judged — "
          "tiny windows carry too much sampling noise to name a "
          "drifting feature honestly")
_register("sml.obs.driftResamples", 3, int,
          "Bootstrap resamples of the baseline used to set each "
          "feature's noise floor (deterministic seeds; floors cached "
          "per rounded-down power-of-two live-row count)")
_register("sml.obs.driftWindowSec", 300, int,
          "Rolling-window span of serving drift monitors: live sketches "
          "rotate in two half-window slots, so a drift report covers "
          "between half and one full window of recent traffic")
_register("sml.training.module-name", "", str,
          "Course module name stamped by the Classroom-Setup shim "
          "(courseware.CourseConfig)")
_register("sml.training.username", "", str,
          "Course username stamped by the Classroom-Setup shim")
_register("sml.infer.kernel", "auto", str,
          "Ensemble-traversal implementation for device-routed scoring "
          "(DeviceScorer.score_block / forest predict+eval programs): "
          "'xla' = the one-hot where-sum HLO chain (the pre-kernel path, "
          "kept verbatim); 'pallas' = the fused "
          "sml_tpu/native/traverse_kernel.py batched-traversal kernel "
          "(level-order SoA node tables resident in VMEM, depth-unrolled "
          "predicated descent, leaf sums accumulated in-register; runs "
          "in interpret mode on non-TPU backends — the tier-1 bit-parity "
          "testing story); 'auto' = pallas on real TPU only, xla "
          "everywhere else. Unavailable pallas falls back to xla and "
          "counts infer.kernel.fallback. See docs/KERNELS.md")
_register("sml.infer.kernelBlockRows", 2048, int,
          "Row-block size of the pallas traversal kernel's grid on "
          "hardware (bounds the VMEM per-level one-hot tile to "
          "~blockRows*(n_nodes+F) elements; the actual block is the "
          "largest divisor of the per-chip padded rows at or under "
          "this). The hand-set default the --kernelbench autotuner "
          "exists to beat: a tuned spec from the prewarm manifest "
          "overrides this per (model shape, batch width) when "
          "sml.infer.autotune is on. Interpret mode always runs ONE "
          "block (the traversal has no cross-row reduction, so blocking "
          "never changes results — bit-parity either way)")
_register("sml.infer.autotune", True, _to_bool,
          "Consult the prewarm manifest's autotuned traversal-kernel "
          "specs (persisted by bench.py --kernelbench) when resolving "
          "the scoring kernel: a recorded winner for this (model shape, "
          "maxBins, batch width) on this mesh overrides sml.infer.kernel"
          "/kernelBlockRows, so replicas and replays pick the tuned "
          "spec without re-sweeping. Off = conf-resolved spec only")
_register("sml.infer.prefetchBatches", 4, int,
          "DeviceScorer.score_batches lookahead: batches dispatched ahead "
          "of the drain point so batch i+1's prep + H2D staging overlaps "
          "batch i's compute and D2H (was a hard-coded 4). 1 = fully "
          "synchronous")
_register("sml.cv.batchFolds", True, _to_bool,
          "Fuse tree-regressor CV/TVS trial fits into vmapped device "
          "programs. With sml.cv.maxFusedTrials > 1 the GRID axis fuses "
          "too (per-trial hyperparameters pad to the grid maxima as "
          "traced scalars), so a G-point grid over k folds costs "
          "ceil(G*k/maxFusedTrials) tree-fit dispatches — the r01 bench's "
          "ml07_cv/ml08 legs were dominated by dispatch COUNT, not "
          "kernel time. Metrics match the placed-trials path within "
          "float tolerance (below-max-depth trials derive terminal-level "
          "stats from the level histograms rather than the dedicated "
          "leaf pass); false forces placed trials")
_register("sml.cv.maxFusedTrials", 16, int,
          "Max (grid point x fold) trial fits fused into one device "
          "dispatch by the grid-fused CV path (bounds the stacked "
          "operand memory to ~maxFusedTrials fold copies); <= 1 falls "
          "back to fold-only fusion (one dispatch per parameter map)")
_register("sml.cv.trialAxisDevices", 0, int,
          "Devices spanned by the fused-trial ELEMENT axis: grid-fused "
          "(grid point x fold) trials shard over a second ('trial') mesh "
          "axis while each trial lane keeps sharding rows over the "
          "remainder — E trials progress on disjoint chips with an "
          "n_dev/t-wide (often allreduce-free) data axis apiece, instead "
          "of vmapping every trial onto one program spanning all chips. "
          "0 = auto (shard trials whenever one trial's padded rows fit a "
          "single chip comfortably — the small-rows regime where the "
          "per-level psum latency dominates the per-chip matmul); 1 = "
          "rows-only sharding (the pre-r6 layout); k > 1 clamps to the "
          "largest mesh divisor <= k. Results match the rows-only layout "
          "within float reduction-order tolerance (sampling draws are "
          "mesh-layout-invariant)")
_register("sml.data.chunkRows", 65536, int,
          "Row-block size of the out-of-core data plane (frame/_chunks.py): "
          "ChunkSources yield columnar chunks of at most this many rows, "
          "and the chunked ingest path quantizes + stages one chunk at a "
          "time so host residency is bounded by a few chunk buffers plus "
          "the COMPACT bin matrix, never the raw float data. See "
          "docs/DATAPLANE.md")
_register("sml.data.sketchBuckets", 2048, int,
          "Centroid budget per feature for the streamed-quantization "
          "quantile sketch: below the exact cap the sketch holds raw "
          "values (bin edges bit-identical to the monolithic "
          "make_bins), above it each feature compresses to this many "
          "weight-uniform centroids (edges within one bin width for "
          "buckets >> maxBins). Sketches merge like obs._metrics "
          "snapshots: per-chunk summaries sum into one")
_register("sml.data.prefetchChunks", 2, int,
          "Chunked-ingest lookahead: chunks dispatched (H2D + device "
          "bin-accumulate) ahead of the drain point, so chunk i+1's host "
          "quantization overlaps chunk i's transfer and device work — "
          "the double-buffered H2D prefetch. Also bounds the chunk_stage "
          "HBM pool to ~this many chunk blocks. 1 = fully synchronous")
_register("sml.tune.candidatesPerDispatch", 4, int,
          "TPE candidates proposed AND scored per generation for "
          "batch-capable fmin objectives (fn.score_batch): a "
          "tree-estimator objective backed by "
          "ml.tuning.fused_param_scores pays one fused device dispatch "
          "per generation instead of one per trial; <= 1 keeps the "
          "sequential propose-score loop")


class TpuConf:
    """Thread-safe KV config with typed known keys and free-form extras."""

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._values: Dict[str, Any] = {}
        self._on_set: Dict[str, Callable[[], None]] = {}

    def on_set(self, key: str, fn: Callable[[], None]) -> None:
        """Register a callback fired after `key` changes (one per key —
        used by knobs whose effect must be re-applied to process state,
        e.g. sml.compile.cacheDir re-pointing the XLA compile cache)."""
        with self._lock:
            self._on_set[key] = fn

    def set(self, key: str, value: Any) -> None:
        with self._lock:
            ent = _KNOWN.get(key)
            if ent is not None and not isinstance(value, type(ent.default)):
                value = ent.caster(value)
            self._values[key] = value
            # Keep spark.* aliases and sml.* keys in sync both ways.
            alias = _ALIASES.get(key)
            if alias is not None:
                self._values[alias] = value
            hook = self._on_set.get(key)
        if hook is not None:  # outside the lock: hooks may read conf
            hook()

    def get(self, key: str, default: Optional[Any] = None) -> Any:
        with self._lock:
            if key in self._values:
                return self._values[key]
            ent = _KNOWN.get(key)
            if ent is not None:
                return ent.default
            if default is not None:
                return default
            if key.startswith(("sml.", "spark.")):
                import difflib
                near = difflib.get_close_matches(key, _KNOWN, n=3,
                                                 cutoff=0.6)
                hint = ("; did you mean: " + ", ".join(near)
                        if near else "")
                raise KeyError(
                    f"No such config key: {key!r} — not registered in "
                    f"sml_tpu/conf.py and never set(){hint}")
            raise KeyError(f"No such config key: {key}")

    def getInt(self, key: str) -> int:
        return int(self.get(key))

    def getBool(self, key: str) -> bool:
        return _to_bool(self.get(key))

    def unset(self, key: str) -> None:
        with self._lock:
            self._values.pop(key, None)

    def asDict(self) -> Dict[str, Any]:
        with self._lock:
            d = {k: e.default for k, e in _KNOWN.items()}
            d.update(self._values)
            return d


def registered_keys() -> tuple:
    """Every registered key, sorted — the programmatic registry dump the
    graftlint conf-key-registry rule cross-checks call sites against
    (conf.py stays importable by path with zero heavy deps for exactly
    this reason)."""
    return tuple(sorted(_KNOWN))


def describe() -> Dict[str, Dict[str, Any]]:
    """key -> {default, type, doc} for the full registry (late registrars
    like parallel.dispatch appear once they have imported)."""
    return {k: {"default": e.default, "type": type(e.default).__name__,
                "doc": e.doc}
            for k, e in sorted(_KNOWN.items())}


_ALIASES = {
    "spark.sql.shuffle.partitions": "sml.shuffle.partitions",
    "sml.shuffle.partitions": "spark.sql.shuffle.partitions",
    "spark.sql.execution.arrow.maxRecordsPerBatch": "sml.arrow.maxRecordsPerBatch",
    "sml.arrow.maxRecordsPerBatch": "spark.sql.execution.arrow.maxRecordsPerBatch",
    "spark.databricks.delta.retentionDurationCheck.enabled": "sml.delta.retentionDurationCheck.enabled",
    "sml.delta.retentionDurationCheck.enabled": "spark.databricks.delta.retentionDurationCheck.enabled",
}

# Process-wide conf (one driver process; no JVM — see SURVEY §2.3 Py4J row).
GLOBAL_CONF = TpuConf()
