"""AutoML-lite: `regress()` / `classify()` (SURVEY §1 L6).

The reference calls `databricks.automl.regress(train_df, target_col=...,
primary_metric="rmse", timeout_minutes=5, max_trials=10)` and reads
`summary.best_trial.mlflow_run_id` (`SML/ML 09 - AutoML.py:48-81`); its
implementation is described there as sklearn/XGBoost trials under Hyperopt
(`ML 09:25`). This does the same natively: feature-type inference →
StringIndexer/OneHot/Imputer/Assembler pipeline → TPE search over model
family + hyperparameters (linear / random forest / boosted trees from
`sml_tpu.ml`), every trial logged as a tracking run, best refit on all data.
"""

from __future__ import annotations

import types
from typing import Any, Dict, List, Optional

import numpy as np

from . import tracking as mlflow
from .ml import Pipeline
from .ml.evaluation import (BinaryClassificationEvaluator, RegressionEvaluator)
from .ml.feature import Imputer, OneHotEncoder, StringIndexer, VectorAssembler
from .ml.regression import GBTRegressor, LinearRegression, RandomForestRegressor
from .ml.classification import (GBTClassifier, LogisticRegression,
                                RandomForestClassifier)
from .tune import STATUS_OK, Trials, fmin, hp, tpe
from .utils.profiler import wallclock


class TrialInfo:
    def __init__(self, run_id: str, metrics: Dict[str, float],
                 params: Dict[str, Any], model_description: str):
        self.mlflow_run_id = run_id
        self.metrics = metrics
        self.params = params
        self.model_description = model_description

    @property
    def notebook_path(self):  # surface parity; there are no notebooks here
        return None

    def __repr__(self):
        return f"TrialInfo({self.model_description}, metrics={self.metrics})"


class AutoMLSummary:
    def __init__(self, best_trial: TrialInfo, trials: List[TrialInfo],
                 experiment_id: str, output_df_schema=None):
        self.best_trial = best_trial
        self.trials = trials
        self.experiment = types.SimpleNamespace(experiment_id=experiment_id)

    def __repr__(self):
        return f"AutoMLSummary(best={self.best_trial!r}, n_trials={len(self.trials)})"


def _build_feature_pipeline(df, target_col: str):
    schema = {f.name: f.dataType.simpleString() for f in df.schema.fields}
    str_cols = [c for c, t in schema.items() if t == "string" and c != target_col]
    num_cols = [c for c, t in schema.items()
                if t in ("double", "float", "int", "bigint") and c != target_col]
    stages: List = []
    assembled: List[str] = []
    if num_cols:
        out_num = [f"{c}__imp" for c in num_cols]
        stages.append(Imputer(strategy="median", inputCols=num_cols,
                              outputCols=out_num))
        assembled += out_num
    if str_cols:
        idx = [f"{c}__idx" for c in str_cols]
        ohe = [f"{c}__ohe" for c in str_cols]
        stages.append(StringIndexer(inputCols=str_cols, outputCols=idx,
                                    handleInvalid="keep"))
        stages.append(OneHotEncoder(inputCols=idx, outputCols=ohe))
        assembled += ohe
    stages.append(VectorAssembler(inputCols=assembled, outputCol="features",
                                 handleInvalid="keep"))
    return stages


def _search(df, target_col: str, primary_metric: str, timeout_minutes: float,
            max_trials: int, task: str, experiment_name: Optional[str]) -> AutoMLSummary:
    exp = mlflow.set_experiment(experiment_name or
                                f"automl-{task}-{target_col}-{int(wallclock())}")
    feature_stages = _build_feature_pipeline(df, target_col)
    train, val = df.randomSplit([0.8, 0.2], seed=42)
    deadline = wallclock() + timeout_minutes * 60

    if task == "regress":
        evaluator = RegressionEvaluator(labelCol=target_col,
                                        metricName=primary_metric)
        families = {
            "linear": lambda p: LinearRegression(
                labelCol=target_col, regParam=p["reg"],
                elasticNetParam=p["enet"]),
            "rf": lambda p: RandomForestRegressor(
                labelCol=target_col, maxDepth=int(p["depth"]),
                numTrees=int(p["trees"]), seed=42),
            "gbt": lambda p: GBTRegressor(
                labelCol=target_col, maxDepth=int(p["depth"]),
                maxIter=int(p["trees"]), stepSize=p["lr"], seed=42),
        }
    else:
        evaluator = BinaryClassificationEvaluator(labelCol=target_col)
        families = {
            "linear": lambda p: LogisticRegression(
                labelCol=target_col, regParam=p["reg"]),
            "rf": lambda p: RandomForestClassifier(
                labelCol=target_col, maxDepth=int(p["depth"]),
                numTrees=int(p["trees"]), seed=42),
            "gbt": lambda p: GBTClassifier(
                labelCol=target_col, maxDepth=int(p["depth"]),
                maxIter=int(p["trees"]), stepSize=p["lr"], seed=42),
        }

    space = {
        "family": hp.choice("family", list(families)),
        "reg": hp.loguniform("reg", np.log(1e-4), np.log(1.0)),
        "enet": hp.uniform("enet", 0.0, 1.0),
        "depth": hp.quniform("depth", 3, 8, 1),
        "trees": hp.quniform("trees", 10, 60, 10),
        "lr": hp.loguniform("lr", np.log(0.02), np.log(0.5)),
    }
    larger_better = evaluator.isLargerBetter()
    infos: List[TrialInfo] = []

    def objective(params):
        if wallclock() > deadline:
            return {"status": "fail", "error": "timeout"}
        family = params["family"]
        est = families[family](params)
        pipeline = Pipeline(stages=feature_stages + [est])
        with mlflow.start_run(run_name=f"trial-{family}") as run:
            model = pipeline.fit(train)
            metric = evaluator.evaluate(model.transform(val))
            mlflow.log_params({k: v for k, v in params.items()})
            mlflow.log_metric(f"val_{primary_metric}", metric)
            mlflow.spark.log_model(model, "model")
        infos.append(TrialInfo(run.info.run_id,
                               {f"val_{primary_metric}": metric}, params,
                               model_description=family))
        return {"loss": -metric if larger_better else metric,
                "status": STATUS_OK}

    trials = Trials()
    fmin(objective, space, algo=tpe, max_evals=max_trials, trials=trials,
         rstate=np.random.RandomState(42))
    ok = [(t, i) for i, t in enumerate(trials.trials)
          if t["result"].get("status") == STATUS_OK]
    if not ok:
        raise RuntimeError("AutoML: no successful trials within budget")
    best_i = min(range(len(infos)),
                 key=lambda i: (-(infos[i].metrics[f"val_{primary_metric}"])
                                if larger_better
                                else infos[i].metrics[f"val_{primary_metric}"]))
    return AutoMLSummary(infos[best_i], infos, exp.experiment_id)


def regress(dataset, target_col: str, primary_metric: str = "rmse",
            timeout_minutes: float = 5.0, max_trials: int = 10,
            experiment_name: Optional[str] = None, **kw) -> AutoMLSummary:
    return _search(dataset, target_col, primary_metric, timeout_minutes,
                   max_trials, "regress", experiment_name)


def classify(dataset, target_col: str, primary_metric: str = "areaUnderROC",
             timeout_minutes: float = 5.0, max_trials: int = 10,
             experiment_name: Optional[str] = None, **kw) -> AutoMLSummary:
    return _search(dataset, target_col, primary_metric, timeout_minutes,
                   max_trials, "classify", experiment_name)
