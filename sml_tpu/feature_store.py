"""Feature store on Delta-lite tables (SURVEY §1 L6).

The reference's `FeatureStoreClient` workflow (`SML/ML 10 - Feature
Store.py:65-348`): compute features → `create_feature_table` / `create_table`
→ `write_table(mode="overwrite"|"merge")` → `FeatureLookup` +
`create_training_set` → `log_model(..., training_set=)` → `score_batch`
joins the stored features back automatically at inference.

Tables are Delta-lite directories (versioned commit log, so feature history
is time-travelable) under a feature-store root, plus a JSON metadata file
carrying primary keys/description — the lookup metadata the scorer needs.
"""

from __future__ import annotations

import functools
import json
import os
from typing import Any, Dict, List, Optional, Sequence

import pandas as pd

from .frame.session import get_session
from . import tracking as _mlflow


class FeatureLookup:
    def __init__(self, table_name: str, lookup_key,
                 feature_names: Optional[Sequence[str]] = None,
                 output_name: Optional[str] = None):
        self.table_name = table_name
        self.lookup_key = [lookup_key] if isinstance(lookup_key, str) else list(lookup_key)
        self.feature_names = list(feature_names) if feature_names else None
        self.output_name = output_name


class FeatureTable:
    def __init__(self, name: str, keys: List[str], path: str,
                 description: str = "", features: Optional[List[str]] = None):
        self.name = name
        self.keys = keys
        self.primary_keys = keys
        self.path = path
        self.description = description
        self.features = features or []

    def __repr__(self):
        return (f"FeatureTable(name={self.name!r}, keys={self.keys}, "
                f"features={self.features})")


class TrainingSet:
    """Join spec + materialization (`fs.create_training_set`)."""

    def __init__(self, df, lookups: List[FeatureLookup], label: Optional[str],
                 exclude_columns: Sequence[str], client: "FeatureStoreClient"):
        self._df = df
        self._lookups = lookups
        self._label = label
        self._exclude = list(exclude_columns)
        self._client = client

    def load_df(self):
        out = self._df
        for lk in self._lookups:
            feat = self._client.read_table(lk.table_name)
            if lk.feature_names:
                feat = feat.select(*(lk.lookup_key + lk.feature_names))
            out = out.join(feat, on=lk.lookup_key, how="left")
        drop = [c for c in self._exclude if c in out.columns]
        if drop:
            out = out.drop(*drop)
        return out


class FeatureStoreClient:
    def __init__(self, root: Optional[str] = None):
        self._root = root or os.environ.get(
            "SML_FEATURE_STORE_DIR", os.path.join(os.getcwd(), "feature_store"))
        os.makedirs(self._root, exist_ok=True)

    # -- metadata ---------------------------------------------------------
    def _meta_path(self, name: str) -> str:
        return os.path.join(self._root, name.replace(".", "__") + ".meta.json")

    def _table_path(self, name: str) -> str:
        return os.path.join(self._root, name.replace(".", "__"))

    def _write_meta(self, meta: Dict[str, Any]) -> None:
        with open(self._meta_path(meta["name"]), "w") as f:
            json.dump(meta, f, indent=1)

    def _read_meta(self, name: str) -> Dict[str, Any]:
        try:
            with open(self._meta_path(name)) as f:
                return json.load(f)
        except FileNotFoundError:
            raise ValueError(f"feature table {name!r} does not exist")

    # -- table lifecycle --------------------------------------------------
    def create_table(self, name: str, primary_keys, df=None, schema=None,
                     description: str = "") -> FeatureTable:
        keys = [primary_keys] if isinstance(primary_keys, str) else list(primary_keys)
        path = self._table_path(name)
        features: List[str] = []
        if df is not None:
            df.write.format("delta").mode("overwrite").save(path)
            features = [c for c in df.columns if c not in keys]
        meta = {"name": name, "keys": keys, "path": path,
                "description": description, "features": features}
        self._write_meta(meta)
        return FeatureTable(**meta)

    # the 2021-era surface used by the course
    def create_feature_table(self, name: str, keys, features_df=None,
                             schema=None, description: str = "") -> FeatureTable:
        return self.create_table(name, keys, df=features_df, schema=schema,
                                 description=description)

    def write_table(self, name: str, df, mode: str = "merge") -> None:
        meta = self._read_meta(name)
        path = meta["path"]
        if mode == "overwrite":
            df.write.format("delta").mode("overwrite") \
                .option("overwriteSchema", "true").save(path)
        elif mode == "merge":
            existing = self.read_table(name)
            keys = meta["keys"]
            new_pdf = df.toPandas()
            old_pdf = existing.toPandas()
            # upsert: new rows replace matching keys, union of columns
            merged = pd.concat([old_pdf, new_pdf], ignore_index=True)
            merged = merged.drop_duplicates(subset=keys, keep="last") \
                .reset_index(drop=True)
            mdf = get_session().createDataFrame(merged)
            mdf.write.format("delta").mode("overwrite") \
                .option("overwriteSchema", "true").save(path)
        else:
            raise ValueError(f"unknown write mode {mode!r}")
        meta["features"] = [c for c in df.columns if c not in meta["keys"]]
        self._write_meta(meta)

    def read_table(self, name: str):
        meta = self._read_meta(name)
        return get_session().read.format("delta").load(meta["path"])

    def get_table(self, name: str) -> FeatureTable:
        return FeatureTable(**self._read_meta(name))

    get_feature_table = get_table

    def drop_table(self, name: str) -> None:
        import shutil
        meta = self._read_meta(name)
        shutil.rmtree(meta["path"], ignore_errors=True)
        os.remove(self._meta_path(name))

    # -- training sets ----------------------------------------------------
    def create_training_set(self, df, feature_lookups: List[FeatureLookup],
                            label: Optional[str] = None,
                            exclude_columns: Sequence[str] = ()) -> TrainingSet:
        return TrainingSet(df, feature_lookups, label, exclude_columns, self)

    # -- models -----------------------------------------------------------
    def log_model(self, model, artifact_path: str, flavor=None,
                  training_set: Optional[TrainingSet] = None,
                  registered_model_name: Optional[str] = None, **kw):
        """Log model + the lookup metadata needed for score_batch."""
        flavor = flavor or _mlflow.spark
        info_dir = flavor.log_model(model, artifact_path,
                                    registered_model_name=registered_model_name)
        if training_set is not None:
            lookups = [{"table_name": lk.table_name,
                        "lookup_key": lk.lookup_key,
                        "feature_names": lk.feature_names}
                       for lk in training_set._lookups]
            spec = {"lookups": lookups,
                    "exclude_columns": training_set._exclude,
                    "label": training_set._label,
                    "feature_store_root": self._root}
            with open(os.path.join(info_dir, "feature_spec.json"), "w") as f:
                json.dump(spec, f, indent=1)
        return info_dir

    def score_batch(self, model_uri: str, df, result_type: str = "double"):
        """Join stored features onto `df` by key, then predict — the
        automatic-lookup scoring of `ML 10:285-348`."""
        from .tracking import _resolve_model_uri
        from .ml.base import Saveable
        path = _resolve_model_uri(model_uri)
        spec_path = os.path.join(path, "feature_spec.json")
        joined = df
        label = None
        if os.path.exists(spec_path):
            with open(spec_path) as f:
                spec = json.load(f)
            client = FeatureStoreClient(spec.get("feature_store_root", self._root))
            lookups = [FeatureLookup(**lk) for lk in spec["lookups"]]
            joined = TrainingSet(df, lookups, spec.get("label"),
                                 spec.get("exclude_columns", ()),
                                 client).load_df()
            label = spec.get("label")
        model = Saveable.load(os.path.join(path, "native"))
        out = model.transform(joined)
        return out


def feature_table(fn):
    """Decorator marking a feature-computation function (`ML 10`'s
    `@feature_table`); calling it just runs the computation, the marker is
    for documentation/lineage."""
    @functools.wraps(fn)
    def wrapper(*a, **kw):
        return fn(*a, **kw)
    wrapper._is_feature_table = True
    return wrapper
