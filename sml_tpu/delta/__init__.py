from .table import DeltaTable, read_delta, write_delta

__all__ = ["DeltaTable", "read_delta", "write_delta"]
