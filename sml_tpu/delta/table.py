"""Delta-lite: versioned ACID-ish table format (L0 — SURVEY §7.3).

Re-implements the behaviors `SML/ML 00c - Delta Review.py` and
`SML/Labs/ML 05L - MLflow Lab.py:54-93` exercise, on the documented Delta
format shape (`ML 00c:95-117`): a `_delta_log/` directory of JSON commit
files `00000000000000000000.json`… each recording add/remove file actions +
commit info; data as (optionally partitioned) parquet part-files.

Supported: create/overwrite/append, partitionBy, overwriteSchema,
mergeSchema, time travel via `versionAsOf` / `timestampAsOf`, DESCRIBE
HISTORY, vacuum(0) gated by the retention-check conf
(`ML 00c:233-237`).
"""

from __future__ import annotations

import glob
import json
import os
import shutil
import uuid
from typing import Any, Dict, List, Optional

import pandas as pd
import pyarrow.parquet as pq

from ..conf import GLOBAL_CONF
from ..frame.dataframe import DataFrame, _concat
from ..utils.profiler import wallclock

LOG_DIR = "_delta_log"


def _log_path(table_path: str, version: int) -> str:
    return os.path.join(table_path, LOG_DIR, f"{version:020d}.json")


def _list_versions(table_path: str) -> List[int]:
    files = glob.glob(os.path.join(table_path, LOG_DIR, "*.json"))
    return sorted(int(os.path.basename(f)[:-5]) for f in files)


def _read_commit(table_path: str, version: int) -> List[Dict[str, Any]]:
    with open(_log_path(table_path, version)) as fh:
        return [json.loads(line) for line in fh if line.strip()]


def _snapshot(table_path: str, version: int) -> Dict[str, Any]:
    """Replay the log up to `version`: active files + schema + partition cols."""
    active: Dict[str, Dict[str, Any]] = {}
    meta: Dict[str, Any] = {}
    for v in [x for x in _list_versions(table_path) if x <= version]:
        for action in _read_commit(table_path, v):
            if "metaData" in action:
                meta = action["metaData"]
            elif "add" in action:
                active[action["add"]["path"]] = action["add"]
            elif "remove" in action:
                active.pop(action["remove"]["path"], None)
    return {"files": list(active.values()), "meta": meta}


def _write_commit(table_path: str, version: int, actions: List[Dict[str, Any]]) -> None:
    os.makedirs(os.path.join(table_path, LOG_DIR), exist_ok=True)
    with open(_log_path(table_path, version), "w") as fh:
        for a in actions:
            fh.write(json.dumps(a) + "\n")


def write_delta(df: DataFrame, path: str, mode: str = "errorifexists",
                options: Optional[Dict[str, Any]] = None,
                partition_by: Optional[List[str]] = None) -> None:
    options = options or {}
    partition_by = partition_by or []
    versions = _list_versions(path)
    exists = bool(versions)
    if exists and mode in ("error", "errorifexists"):
        raise FileExistsError(f"Delta table already exists at {path}")
    if exists and mode == "ignore":
        return

    new_version = (versions[-1] + 1) if exists else 0
    overwrite_schema = str(options.get("overwriteSchema", "false")).lower() == "true"
    merge_schema = str(options.get("mergeSchema", "false")).lower() == "true"

    new_cols = df.columns
    actions: List[Dict[str, Any]] = [{
        "commitInfo": {
            "timestamp": int(wallclock() * 1000),
            "operation": "WRITE",
            "operationParameters": {"mode": mode.upper(),
                                    "partitionBy": json.dumps(partition_by)},
            "version": new_version,
        }
    }]

    if exists:
        prev = _snapshot(path, versions[-1])
        prev_cols = [f["name"] for f in json.loads(prev["meta"].get("schemaString", "[]"))] \
            if prev["meta"].get("schemaString") else []
        if prev_cols and set(new_cols) != set(prev_cols):
            additive = set(prev_cols) <= set(new_cols)
            if mode == "overwrite" and not overwrite_schema and \
                    not (merge_schema and additive):
                # Delta allows ADDITIVE evolution under mergeSchema for
                # both append and overwrite (`ML 05L` overwrites with a
                # new column under mergeSchema); destructive changes still
                # need overwriteSchema
                raise ValueError(
                    "A schema mismatch detected when writing to the Delta table. "
                    "To overwrite your schema, set option('overwriteSchema', 'true').")
            if mode == "append" and not merge_schema:
                raise ValueError(
                    "A schema mismatch detected when writing to the Delta table. "
                    "To merge the new schema, set option('mergeSchema', 'true').")
        if mode == "overwrite":
            for f in prev["files"]:
                actions.append({"remove": {"path": f["path"],
                                           "deletionTimestamp": int(wallclock() * 1000)}})

    schema_string = json.dumps([{"name": c, "type": t} for c, t in df.dtypes])
    actions.append({"metaData": {"id": str(uuid.uuid4()),
                                 "schemaString": schema_string,
                                 "partitionColumns": partition_by,
                                 "createdTime": int(wallclock() * 1000)}})

    os.makedirs(path, exist_ok=True)
    parts = df._materialize()
    from ..frame.io import _pandas_to_arrow
    if partition_by:
        pdf = _concat(parts)
        for keys, g in pdf.groupby(partition_by, sort=False, dropna=False):
            if not isinstance(keys, tuple):
                keys = (keys,)
            reldir = "/".join(f"{k}={v}" for k, v in zip(partition_by, keys))
            os.makedirs(os.path.join(path, reldir), exist_ok=True)
            rel = f"{reldir}/part-{uuid.uuid4().hex[:12]}.snappy.parquet"
            body = g.drop(columns=list(partition_by)).reset_index(drop=True)
            pq.write_table(_pandas_to_arrow(body), os.path.join(path, rel))
            actions.append({"add": {"path": rel, "size": os.path.getsize(os.path.join(path, rel)),
                                    "partitionValues": {k: str(v) for k, v in zip(partition_by, keys)},
                                    "modificationTime": int(wallclock() * 1000),
                                    "numRecords": len(body), "dataChange": True}})
    else:
        for i, p in enumerate(parts):
            rel = f"part-{i:05d}-{uuid.uuid4().hex[:12]}.snappy.parquet"
            pq.write_table(_pandas_to_arrow(p), os.path.join(path, rel))
            actions.append({"add": {"path": rel, "size": os.path.getsize(os.path.join(path, rel)),
                                    "partitionValues": {},
                                    "modificationTime": int(wallclock() * 1000),
                                    "numRecords": len(p), "dataChange": True}})

    _write_commit(path, new_version, actions)


def read_delta(path: str, session, options: Dict[str, Any]) -> DataFrame:
    versions = _list_versions(path)
    if not versions:
        raise FileNotFoundError(f"{path} is not a Delta table")
    version = versions[-1]
    if "versionAsOf" in options:
        version = int(options["versionAsOf"])
        if version not in versions:
            raise ValueError(f"Cannot time travel to version {version}; "
                             f"available: {versions}")
    elif "timestampAsOf" in options:
        ts = pd.Timestamp(options["timestampAsOf"]).timestamp() * 1000
        eligible = []
        for v in versions:
            info = next((a["commitInfo"] for a in _read_commit(path, v) if "commitInfo" in a), {})
            if info.get("timestamp", 0) <= ts:
                eligible.append(v)
        if not eligible:
            raise ValueError(f"No version of the table at or before {options['timestampAsOf']}")
        version = eligible[-1]

    snap = _snapshot(path, version)
    part_cols = snap["meta"].get("partitionColumns", [])
    parts = []
    for f in snap["files"]:
        full = os.path.join(path, f["path"])
        pdf = pq.read_table(full).to_pandas().reset_index(drop=True)
        for k, v in f.get("partitionValues", {}).items():
            try:
                pdf[k] = pd.to_numeric(pd.Series([v] * len(pdf)))
            except (ValueError, TypeError):
                pdf[k] = v
        parts.append(pdf)
    return DataFrame.from_partitions(parts or [pd.DataFrame()], session=session)


class DeltaTable:
    """`delta.tables.DeltaTable` surface: forPath, history, vacuum
    (`ML 00c:184,233-237`)."""

    def __init__(self, session, path: str):
        self._session = session
        self._path = path

    @classmethod
    def forPath(cls, session, path: str) -> "DeltaTable":
        if not _list_versions(path):
            raise FileNotFoundError(f"{path} is not a Delta table")
        return cls(session, path)

    @classmethod
    def isDeltaTable(cls, _session, path: str) -> bool:
        return bool(_list_versions(path))

    def toDF(self) -> DataFrame:
        return read_delta(self._path, self._session, {})

    def history(self, limit: Optional[int] = None) -> DataFrame:
        rows = []
        for v in reversed(_list_versions(self._path)):
            info = next((a["commitInfo"] for a in _read_commit(self._path, v)
                         if "commitInfo" in a), {})
            rows.append({
                "version": v,
                "timestamp": pd.Timestamp(info.get("timestamp", 0), unit="ms"),
                "operation": info.get("operation", "WRITE"),
                "operationParameters": json.dumps(info.get("operationParameters", {})),
            })
        if limit:
            rows = rows[:limit]
        return DataFrame.from_pandas(pd.DataFrame(rows), session=self._session,
                                     num_partitions=1)

    def vacuum(self, retentionHours: float = 168.0) -> None:
        """Delete files no longer referenced by the latest version. Retention
        below the safe default requires disabling the retention check, exactly
        as the course demonstrates (`ML 00c:233-237`)."""
        if retentionHours < 168.0 and GLOBAL_CONF.getBool("sml.delta.retentionDurationCheck.enabled"):
            raise ValueError(
                "requirement failed: Are you sure you would like to vacuum files with such a "
                "low retention period? ... Set sml.delta.retentionDurationCheck.enabled "
                "to false to disable this check.")
        versions = _list_versions(self._path)
        latest = _snapshot(self._path, versions[-1])
        live = {f["path"] for f in latest["files"]}
        cutoff = wallclock() - retentionHours * 3600
        for root, _dirs, files in os.walk(self._path):
            for f in files:
                full = os.path.join(root, f)
                rel = os.path.relpath(full, self._path)
                if rel.startswith(LOG_DIR) or rel in live:
                    continue
                if not f.endswith(".parquet"):
                    continue
                if os.path.getmtime(full) <= cutoff or retentionHours == 0:
                    os.remove(full)

    def delete(self, condition: Optional[str] = None) -> None:
        df = self.toDF()
        if condition is not None:
            from ..frame.sql import parse_simple_expr
            cond = parse_simple_expr(condition)
            df = df.filter(~cond)
        else:
            df = df.limit(0)
        write_delta(df, self._path, mode="overwrite")
