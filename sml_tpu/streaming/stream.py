"""Micro-batch structured streaming (the MLE 00 deployment path, P10).

`spark.readStream.schema(s).option("maxFilesPerTrigger", 1).parquet(dir)` →
`pipeline_model.transform(stream)` → `writeStream.format("memory"|"delta")
.option("checkpointLocation", …).outputMode("append").queryName(n).start()`
(`SML/ML Electives/MLE 00 - MLlib Deployment Options.py:52-85`).

Design: a StreamingDataFrame is a source spec + a chain of DataFrame→
DataFrame ops (recorded generically, so *any* batch transformation —
including a fitted PipelineModel — composes). A StreamingQuery runs a
host-side trigger loop: discover unseen files (the processed-set lives in
checkpointLocation for crash recovery), build a static DataFrame per batch,
apply the op chain (TPU inference inside), append to the sink.
"""

from __future__ import annotations

import glob
import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Union

import pandas as pd

from ..frame.dataframe import DataFrame
from ..frame.types import StructType, parse_schema
from ..utils.profiler import wallclock

_active_queries: List["StreamingQuery"] = []
_lock = threading.RLock()


class StreamManager:
    """`spark.streams` — lifecycle management used by Classroom-Setup
    (`SML/Includes/Classroom-Setup.py:96-110`)."""

    @property
    def active(self) -> List["StreamingQuery"]:
        with _lock:
            return [q for q in _active_queries if q.isActive]

    def get(self, query_id: str) -> Optional["StreamingQuery"]:
        for q in self.active:
            if q.id == query_id or q.name == query_id:
                return q
        return None

    def awaitAnyTermination(self, timeout: Optional[float] = None) -> bool:
        """Block until ANY query has terminated (Spark semantics: one
        termination ends the wait — the pre-fix loop waited for the
        whole active set to drain, so a supervisor over N long-running
        queries hung until every stream died). Judged over the queries
        started so far: one already terminated — including before this
        call — returns True immediately; `timeout=None` blocks until a
        termination happens. Returns False only on timeout."""
        with _lock:
            started = list(_active_queries)
        t0 = wallclock()
        while True:
            if not started or any(not q.isActive for q in started):
                return True
            if timeout is not None and wallclock() - t0 > timeout:
                return False
            time.sleep(0.05)


class DataStreamReader:
    def __init__(self, session):
        self._session = session
        self._schema: Optional[StructType] = None
        self._options: Dict[str, Any] = {}
        self._format = "parquet"

    def schema(self, s: Union[str, StructType]) -> "DataStreamReader":
        self._schema = parse_schema(s)
        return self

    def option(self, key: str, value) -> "DataStreamReader":
        self._options[key] = value
        return self

    def format(self, f: str) -> "DataStreamReader":  # noqa: A003
        self._format = f.lower()
        return self

    def parquet(self, path: str) -> "StreamingDataFrame":
        return StreamingDataFrame(self._session, path, "parquet", self._schema, self._options)

    def csv(self, path: str) -> "StreamingDataFrame":
        return StreamingDataFrame(self._session, path, "csv", self._schema, self._options)

    def load(self, path: str) -> "StreamingDataFrame":
        return StreamingDataFrame(self._session, path, self._format, self._schema, self._options)


class StreamingDataFrame:
    """Unbounded DataFrame: source + recorded batch ops. Any DataFrame method
    called on it is recorded and replayed per micro-batch."""

    isStreaming = True

    def __init__(self, session, path: str, fmt: str, schema: Optional[StructType],
                 options: Dict[str, Any],
                 ops: Optional[List[Callable[[DataFrame], DataFrame]]] = None):
        self._session = session
        self._path = path
        self._fmt = fmt
        self._schema = schema
        self._options = options
        self._ops = ops or []
        self._ml_attrs: Dict[str, Any] = {}

    def _append(self, op: Callable[[DataFrame], DataFrame]) -> "StreamingDataFrame":
        out = StreamingDataFrame(self._session, self._path, self._fmt, self._schema,
                                 self._options, self._ops + [op])
        out._ml_attrs = dict(getattr(self, "_ml_attrs", {}))
        return out

    # ML transformers drive frames through these two hooks; recording them
    # lets `model.transform(stream_df)` and feature stages apply per
    # micro-batch exactly like `MLE 00`'s streaming inference
    def _derive(self, fn, schema=None) -> "StreamingDataFrame":
        return self._append(lambda df: df._derive(fn, schema))

    def _derive_rowlocal(self, fn, schema=None) -> "StreamingDataFrame":
        return self._append(lambda df: df._derive_rowlocal(fn, schema))

    def __getattr__(self, item):
        if item.startswith("_") or item in ("writeStream",):
            raise AttributeError(item)

        def recorder(*args, **kwargs):
            def op(df: DataFrame) -> DataFrame:
                out = getattr(df, item)(*args, **kwargs)
                if not isinstance(out, DataFrame):
                    raise TypeError(f"streaming op {item} must return a DataFrame")
                return out
            return self._append(op)

        return recorder

    @property
    def writeStream(self) -> "DataStreamWriter":
        return DataStreamWriter(self)

    # -- source side --
    def _list_files(self) -> List[str]:
        exts = {"parquet": ".parquet", "csv": ".csv"}[self._fmt]
        if os.path.isdir(self._path):
            out = []
            for root, _d, files in os.walk(self._path):
                for f in sorted(files):
                    if f.endswith(exts) and not f.startswith(("_", ".")):
                        out.append(os.path.join(root, f))
            return sorted(out)
        return sorted(glob.glob(self._path))

    def _read_files(self, files: List[str]) -> DataFrame:
        reader = self._session.read
        if self._schema is not None:
            reader = reader.schema(self._schema)
        import pyarrow.parquet as pq
        parts = []
        for f in files:
            if self._fmt == "parquet":
                parts.append(pq.read_table(f).to_pandas().reset_index(drop=True))
            else:
                parts.append(pd.read_csv(f))
        df = DataFrame.from_partitions(parts or [pd.DataFrame()], session=self._session)
        if self._schema is not None and parts:
            from ..frame.dataframe import coerce_to_schema
            df = DataFrame.from_partitions([coerce_to_schema(p, self._schema) for p in parts],
                                           session=self._session, schema=self._schema)
        return df


class DataStreamWriter:
    def __init__(self, sdf):
        self._sdf = sdf
        self._format = "memory"
        self._output_mode = "append"
        self._options: Dict[str, Any] = {}
        self._query_name: Optional[str] = None
        self._trigger_once = False
        self._interval_s = 0.1

    def format(self, f: str) -> "DataStreamWriter":  # noqa: A003
        self._format = f.lower()
        return self

    def outputMode(self, m: str) -> "DataStreamWriter":
        self._output_mode = m
        return self

    def option(self, key: str, value) -> "DataStreamWriter":
        self._options[key] = value
        return self

    def queryName(self, name: str) -> "DataStreamWriter":
        self._query_name = name
        return self

    def trigger(self, once: bool = False, processingTime: Optional[str] = None,
                availableNow: bool = False) -> "DataStreamWriter":
        self._trigger_once = once or availableNow
        if processingTime:
            num = float(processingTime.split()[0])
            unit = processingTime.split()[1] if " " in processingTime else "seconds"
            self._interval_s = num * (60 if unit.startswith("min") else 1)
        return self

    def start(self, path: Optional[str] = None) -> "StreamingQuery":
        if path is not None:
            self._options.setdefault("path", path)
        q = StreamingQuery(self._sdf, self._format, self._output_mode, self._options,
                           self._query_name, self._trigger_once, self._interval_s)
        with _lock:
            _active_queries.append(q)
        q._start()
        return q

    def toTable(self, name: str) -> "StreamingQuery":
        self._options["table"] = name
        return self.start()


class StreamingQuery:
    _next_id = 0

    def __init__(self, sdf, fmt: str, output_mode: str, options: Dict[str, Any],
                 name: Optional[str], once: bool, interval_s: float):
        StreamingQuery._next_id += 1
        self.id = f"query-{StreamingQuery._next_id}"
        self.name = name or self.id
        self._sdf = sdf
        self._fmt = fmt
        self._options = options
        self._once = once
        self._interval_s = interval_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.recentProgress: List[Dict[str, Any]] = []
        self._mem_parts: List[pd.DataFrame] = []
        self._ckpt = options.get("checkpointLocation")
        self._processed = self._load_checkpoint()
        self._ckpt_dirty = False
        self._exception: Optional[BaseException] = None

    # -- checkpoint (recovery contract of MLE 00:75-85) --
    def _load_checkpoint(self) -> set:
        if self._ckpt and os.path.exists(os.path.join(self._ckpt, "processed.json")):
            with open(os.path.join(self._ckpt, "processed.json")) as fh:
                return set(json.load(fh))
        return set()

    def _save_checkpoint(self) -> None:
        if not self._ckpt:
            return
        os.makedirs(self._ckpt, exist_ok=True)
        tmp = os.path.join(self._ckpt, "processed.json.tmp")
        with open(tmp, "w") as fh:
            json.dump(sorted(self._processed), fh)
        os.replace(tmp, os.path.join(self._ckpt, "processed.json"))

    def _start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self) -> None:
        try:
            while not self._stop.is_set():
                did = self._process_one_trigger()
                if self._once and not did:
                    break
                if not did:
                    time.sleep(self._interval_s)
        except BaseException as e:  # surfaced via .exception()
            self._exception = e
        finally:
            # a trigger stopped/killed between its sink write landing
            # and its checkpoint save must still flush EXACTLY ONCE: the
            # dirty flag is raised right after the write and lowered by
            # the save, so resume on this checkpointLocation never
            # reprocesses a committed micro-batch (duplicate rows in an
            # append sink) and a clean trigger never double-saves
            try:
                if self._ckpt_dirty:
                    self._save_checkpoint()
                    self._ckpt_dirty = False
            except Exception:  # noqa: BLE001 — checkpoint dir gone /
                pass  # serialization failure: resume will reprocess
            finally:
                # unconditional: a flush failure must never leave the
                # query "active" forever (awaitTermination liveness)
                self._stop.set()

    def _process_one_trigger(self) -> bool:
        files = [f for f in self._sdf._list_files() if f not in self._processed]
        if not files:
            return False
        per_trigger = int(self._sdf._options.get("maxFilesPerTrigger", len(files)))
        batch_files = files[:max(1, per_trigger)]
        df = self._sdf._read_files(batch_files)
        for op in self._sdf._ops:
            df = op(df)
        self._write_batch(df)
        # the sink write LANDED: from here the checkpoint must record
        # this batch even if a stop or exception interrupts before the
        # save (the _run finally covers the gap via the dirty flag)
        self._processed.update(batch_files)
        self._ckpt_dirty = True
        self._save_checkpoint()
        self._ckpt_dirty = False
        self.recentProgress.append({
            "id": self.id, "name": self.name, "numInputRows": df.count(),
            "files": batch_files, "timestamp": wallclock(),
        })
        return True

    def _write_batch(self, df: DataFrame) -> None:
        if self._fmt == "memory":
            self._mem_parts.append(df.toPandas())
            session = self._sdf._session
            full = pd.concat(self._mem_parts, ignore_index=True)
            session.catalog._register_view(
                self.name, DataFrame.from_pandas(full, session=session))
        elif self._fmt in ("parquet", "csv", "json"):
            df.write.format(self._fmt).mode("append").save(self._options["path"])
        elif self._fmt == "delta":
            df.write.format("delta").mode("append").save(self._options["path"])
        elif self._fmt == "noop":
            df.count()
        else:
            raise ValueError(f"unknown sink format {self._fmt}")

    # -- public control surface --
    @property
    def isActive(self) -> bool:
        return not self._stop.is_set()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=10)

    def awaitTermination(self, timeout: Optional[float] = None) -> bool:
        self._stop.wait(timeout)
        return self._stop.is_set()

    def processAllAvailable(self) -> None:
        while any(f not in self._processed for f in self._sdf._list_files()):
            if not self.isActive:
                # snapshot: the trigger thread publishes `_exception`
                # before setting `_stop`; one load keeps check+raise
                # atomic against a late rebind
                exc = self._exception
                if exc is not None:
                    raise RuntimeError("streaming query terminated with error") from exc
                return
            time.sleep(0.05)

    def exception(self) -> Optional[BaseException]:
        return self._exception

    @property
    def lastProgress(self) -> Optional[Dict[str, Any]]:
        # snapshot: the trigger thread appends to `recentProgress`
        # between our emptiness check and the [-1] index otherwise
        progress = self.recentProgress
        return progress[-1] if progress else None
