from .stream import DataStreamReader, DataStreamWriter, StreamingQuery

__all__ = ["DataStreamReader", "DataStreamWriter", "StreamingQuery"]
