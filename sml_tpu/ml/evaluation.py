"""Evaluators: rmse/r2/mae, AUROC/AUPR, accuracy/f1.

Reference surface: `RegressionEvaluator` (`SML/ML 02 - Linear Regression
I.py:146-151`), `BinaryClassificationEvaluator` (`SML/Labs/ML 07L -
Hyperparameter Tuning Lab.py:104-110`), `MulticlassClassificationEvaluator`
(`SML/ML Electives/MLE 03 - Logistic Regression Lab.py:64-67`).

The metric reductions are single-pass sums over row shards — the jitted
psum pattern of `_staging.run_data_parallel`; ranking metrics (AUROC/AUPR)
sort on host (n log n on scalars) then reduce.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from ..parallel import collectives as coll
from ..parallel import dispatch
from ..parallel.dispatch import WorkHint
from .base import Evaluator
from ._staging import run_data_parallel


def _pred_label(df, predictionCol: str, labelCol: str):
    pdf = df.toPandas() if hasattr(df, "toPandas") else df
    pred = np.asarray(pdf[predictionCol], dtype=np.float64)
    lab = np.asarray(pdf[labelCol], dtype=np.float64)
    ok = np.isfinite(pred) & np.isfinite(lab)
    return pred[ok], lab[ok]


def _reg_stats(p, l, mask):
    # five sufficient statistics, one fused data-parallel pass
    n = coll.psum(jnp.sum(mask))
    se = coll.psum(jnp.sum(mask * (p - l) ** 2))
    ae = coll.psum(jnp.sum(mask * jnp.abs(p - l)))
    sl = coll.psum(jnp.sum(mask * l))
    sl2 = coll.psum(jnp.sum(mask * l * l))
    return n, se, ae, sl, sl2


def _acc_stats(p, l, mask):
    n = coll.psum(jnp.sum(mask))
    c = coll.psum(jnp.sum(mask * (p == l)))
    return c, n


def _stats_route(hint: WorkHint) -> str:
    """Route for a metric reduction. On the host route the evaluators use
    plain numpy instead of the host-mesh XLA program: the math is identical
    (psum over one device is identity) but numpy pays no per-shape compile —
    CV folds/tuning trials present a new length every call, and each first
    sight cost a ~150ms XLA:CPU compile inside the r4 bench's timed pass."""
    pre = dispatch.preroute(hint)
    if pre is not None:
        dispatch.audit_preroute(hint, pre)  # flight-recorder receipt
        return pre
    return dispatch.decide(hint)[0]


def host_reg_stats(pred: np.ndarray, lab: np.ndarray):
    """The five regression sufficient statistics in host numpy, f32
    accumulation to match the device programs' dtype class. `pred`/`lab`
    are f64 arrays NOT yet finite-filtered; the filter here matches
    `_pred_label`. Shared by the evaluator's host route and the pushdown
    hooks so the two paths cannot drift."""
    ok = np.isfinite(pred) & np.isfinite(lab)
    p32 = pred[ok].astype(np.float32)
    l32 = lab[ok].astype(np.float32)
    d = p32 - l32
    return (float(len(p32)), float(np.dot(d, d)),
            float(np.sum(np.abs(d))), float(np.sum(l32)),
            float(np.dot(l32, l32)))


def _reg_metric(metric: str, n: float, se: float, ae: float,
                sl: float, sl2: float) -> float:
    if n == 0:
        return float("nan")
    mse = se / n
    if metric == "rmse":
        return float(np.sqrt(mse))
    if metric == "mse":
        return mse
    if metric == "mae":
        return ae / n
    if metric in ("r2", "var"):
        var = sl2 / n - (sl / n) ** 2
        if metric == "var":
            return var
        return 1.0 - mse / var if var > 0 else 0.0
    raise ValueError(f"unknown metricName {metric!r}")


class RegressionEvaluator(Evaluator):
    def _init_params(self):
        self._declareParam("predictionCol", default="prediction", doc="prediction column")
        self._declareParam("labelCol", default="label", doc="label column")
        self._declareParam("metricName", default="rmse", doc="rmse|mse|mae|r2|var")

    def __init__(self, predictionCol=None, labelCol=None, metricName=None):
        super().__init__()
        self._set(predictionCol=predictionCol, labelCol=labelCol, metricName=metricName)

    def setMetricName(self, v):
        return self._set(metricName=v)

    def getMetricName(self):
        return self.getOrDefault("metricName")

    def isLargerBetter(self) -> bool:
        return self.getOrDefault("metricName") in ("r2", "var")

    def _evaluate(self, df) -> float:
        metric = self.getOrDefault("metricName")
        # evaluator pushdown: a lazy model-transform frame carries a hook
        # that computes the five sufficient statistics in ONE fused device
        # program (traverse + masked reductions, D2H of five scalars) —
        # the prediction column, and the transform frame itself, are never
        # materialized. Spark's analogue is Catalyst collapsing the
        # predict+agg plan; here the lazy frame is the plan.
        hook = getattr(df, "_fused_eval", None)
        if hook is not None and getattr(df, "_parts", False) is None:
            stats = hook.reg_stats(self.getOrDefault("predictionCol"),
                                   self.getOrDefault("labelCol"))
            if stats is not None:
                return _reg_metric(metric, *stats)
        pred, lab = _pred_label(df, self.getOrDefault("predictionCol"),
                                self.getOrDefault("labelCol"))
        hint = WorkHint(flops=10.0 * len(pred), kind="blas")
        if _stats_route(hint) == "host":
            return _reg_metric(metric, *host_reg_stats(pred, lab))
        n, se, ae, sl, sl2 = run_data_parallel(
            _reg_stats, pred.astype(np.float32), lab.astype(np.float32),
            work=hint)
        return _reg_metric(metric, float(n), float(se), float(ae),
                           float(sl), float(sl2))


class BinaryClassificationEvaluator(Evaluator):
    def _init_params(self):
        self._declareParam("rawPredictionCol", default="rawPrediction", doc="score column")
        self._declareParam("labelCol", default="label", doc="label column")
        self._declareParam("metricName", default="areaUnderROC",
                           doc="areaUnderROC|areaUnderPR")

    def __init__(self, rawPredictionCol=None, labelCol=None, metricName=None):
        super().__init__()
        self._set(rawPredictionCol=rawPredictionCol, labelCol=labelCol,
                  metricName=metricName)

    def setMetricName(self, v):
        return self._set(metricName=v)

    def _scores(self, df):
        pdf = df.toPandas() if hasattr(df, "toPandas") else df
        col = self.getOrDefault("rawPredictionCol")
        if col not in pdf.columns:
            for alt in ("probability", "prediction"):
                if alt in pdf.columns:
                    col = alt
                    break
        vals = pdf[col]
        from .linalg import VectorArray, to_matrix
        if isinstance(getattr(vals, "array", None), VectorArray):
            score = to_matrix(vals)[:, -1].astype(np.float64)
        elif len(vals) and hasattr(vals.iloc[0], "toArray"):
            score = np.asarray([v.toArray()[-1] for v in vals], dtype=np.float64)
        elif len(vals) and isinstance(vals.iloc[0], (list, tuple, np.ndarray)):
            score = np.asarray([v[-1] for v in vals], dtype=np.float64)
        else:
            score = np.asarray(vals, dtype=np.float64)
        lab = np.asarray(pdf[self.getOrDefault("labelCol")], dtype=np.float64)
        ok = np.isfinite(score) & np.isfinite(lab)
        return score[ok], lab[ok]

    def _evaluate(self, df) -> float:
        score, lab = self._scores(df)
        metric = self.getOrDefault("metricName")
        order = np.argsort(-score, kind="mergesort")
        lab = lab[order]
        score = score[order]
        tp = np.cumsum(lab)
        fp = np.cumsum(1 - lab)
        # collapse ties: keep last index of each distinct score
        distinct = np.nonzero(np.diff(score))[0]
        idx = np.concatenate([distinct, [len(score) - 1]])
        tp, fp = tp[idx], fp[idx]
        P, N = tp[-1], fp[-1]
        if P == 0 or (metric == "areaUnderROC" and N == 0):
            return float("nan")
        if metric == "areaUnderROC":
            tpr = np.concatenate([[0.0], tp / P])
            fpr = np.concatenate([[0.0], fp / N])
            return float(np.trapezoid(tpr, fpr))
        # areaUnderPR
        precision = tp / (tp + fp)
        recall = tp / P
        recall = np.concatenate([[0.0], recall])
        precision = np.concatenate([[precision[0]], precision])
        return float(np.trapezoid(precision, recall))


class MulticlassClassificationEvaluator(Evaluator):
    def _init_params(self):
        self._declareParam("predictionCol", default="prediction", doc="prediction column")
        self._declareParam("labelCol", default="label", doc="label column")
        self._declareParam("metricName", default="f1", doc="f1|accuracy|weightedPrecision|weightedRecall")

    def __init__(self, predictionCol=None, labelCol=None, metricName=None):
        super().__init__()
        self._set(predictionCol=predictionCol, labelCol=labelCol, metricName=metricName)

    def setMetricName(self, v):
        return self._set(metricName=v)

    def _evaluate(self, df) -> float:
        pred, lab = _pred_label(df, self.getOrDefault("predictionCol"),
                                self.getOrDefault("labelCol"))
        metric = self.getOrDefault("metricName")
        if metric == "accuracy":
            hint = WorkHint(flops=4.0 * len(pred), kind="blas")
            if _stats_route(hint) == "host":
                n = len(pred)
                return float(np.sum(pred.astype(np.float32)
                                    == lab.astype(np.float32))) / n \
                    if n else float("nan")
            c, n = run_data_parallel(
                _acc_stats, pred.astype(np.float32), lab.astype(np.float32),
                work=hint)
            return float(c) / float(n) if n else float("nan")
        classes = np.unique(np.concatenate([pred, lab]))
        stats = []
        for k in classes:
            tp = np.sum((pred == k) & (lab == k))
            fp = np.sum((pred == k) & (lab != k))
            fn = np.sum((pred != k) & (lab == k))
            support = np.sum(lab == k)
            prec = tp / (tp + fp) if tp + fp else 0.0
            rec = tp / (tp + fn) if tp + fn else 0.0
            f1 = 2 * prec * rec / (prec + rec) if prec + rec else 0.0
            stats.append((support, prec, rec, f1))
        support = np.array([s[0] for s in stats], dtype=np.float64)
        w = support / support.sum()
        if metric == "weightedPrecision":
            return float(np.sum(w * [s[1] for s in stats]))
        if metric == "weightedRecall":
            return float(np.sum(w * [s[2] for s in stats]))
        if metric == "f1":
            return float(np.sum(w * [s[3] for s in stats]))
        raise ValueError(f"unknown metricName {metric!r}")


class ClusteringEvaluator(Evaluator):
    """Silhouette (squared euclidean) — the MLlib default."""

    def _init_params(self):
        self._declareParam("predictionCol", default="prediction", doc="cluster column")
        self._declareParam("featuresCol", default="features", doc="features column")
        self._declareParam("metricName", default="silhouette", doc="silhouette")

    def __init__(self, predictionCol=None, featuresCol=None, metricName=None):
        super().__init__()
        self._set(predictionCol=predictionCol, featuresCol=featuresCol,
                  metricName=metricName)

    def _evaluate(self, df) -> float:
        from ._staging import extract_features
        pdf = df.toPandas()
        X = extract_features(pdf, self.getOrDefault("featuresCol"))
        labels = np.asarray(pdf[self.getOrDefault("predictionCol")], dtype=int)
        ks = np.unique(labels)
        if len(ks) < 2:
            return float("nan")
        # simplified silhouette via cluster means (squared distances), the
        # same O(n·k) formulation MLlib uses
        centers = np.stack([X[labels == k].mean(axis=0) for k in ks])
        counts = np.array([(labels == k).sum() for k in ks], dtype=np.float64)
        d2 = ((X[:, None, :] - centers[None, :, :]) ** 2).sum(-1)
        own = np.array([np.nonzero(ks == l)[0][0] for l in labels])
        a = d2[np.arange(len(X)), own]
        d2_other = d2.copy()
        d2_other[np.arange(len(X)), own] = np.inf
        b = d2_other.min(axis=1)
        s = (b - a) / np.maximum(a, b)
        s[counts[own] == 1] = 0.0
        return float(np.mean(s))
