"""ALS — block-parallel alternating least squares (SURVEY §2.2 P4).

The reference's `ALS(userCol, itemCol, ratingCol, rank, maxIter,
coldStartStrategy)` trains MovieLens 1M (`SML/ML Electives/MLE 01 -
Collaborative Filtering Lab.py:159-201`). Spark's implementation blocks
users/items across executors and shuffles factor blocks; here the WHOLE
alternating fit is ONE jitted shard_map program (`fori_loop` over
iterations), each half-step inside it:

    per chip:  segment-sum of (f_i ⊗ f_i, r·f_i) by user  → (U, r, r), (U, r)
    psum       over ICI (the factor-block exchange)
    batched    solve of all U normal systems on-device

with ALS-WR regularization (λ·n_u, Spark's scheme). Ratings AND factors stay
in HBM for the entire fit: one dispatch, one packed factor download."""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
import pandas as pd

from ..parallel import collectives as coll
from .base import Estimator, Model, load_arrays, save_arrays
from ._staging import data_parallel


from functools import lru_cache


@lru_cache(maxsize=64)
def _als_fit_program(n_users: int, n_items: int, rank: int, reg: float,
                     max_iter: int, nonneg: bool):
    """The WHOLE alternating fit as one XLA program: `fori_loop` over
    iterations, both half-steps inside, factors living on-device for the
    entire fit. One dispatch per fit instead of 2·maxIter — the per-launch
    tunnel latency disappears, and the CPU test mesh never has multiple
    collective executables racing one rendezvous (r3: 20 async half-step
    launches could deadlock XLA:CPU's cross-module all-reduce).

    SORTED-SEGMENT normal equations, no scatters: `segment_sum` lowers to
    a serialized HBM read-modify-write scatter on TPU and made the
    half-steps ~3x slower than this formulation (measured 1.9s → 0.6s for
    a 10-iteration MovieLens-1M-scale fit). The rating triples are sorted
    by entity ON HOST once per fit (ids are static across iterations, so
    the permutation is too); each shard holds a contiguous slice of the
    sorted order plus its clipped local [start, end) bounds per entity,
    accumulates per-segment sums as cumsum boundary differences (a
    log-depth associative scan that streams at full HBM bandwidth), and
    `psum` merges the per-shard partial normal equations — segments that
    span a shard boundary add up across shards. Padding rows sit past
    every real segment's end, so bounds clipping makes them inert.

    Program args (leading axis row-sharded unless noted):
      ius     item ids in user-sorted order     (rows,)
      usi     user ids in item-sorted order     (rows,)
      rat_u   ratings in user-sorted order      (rows,)
      rat_i   ratings in item-sorted order      (rows,)
      ub      per-shard user bounds             (1, 2, n_users) per shard
      ib      per-shard item bounds             (1, 2, n_items) per shard
      uf0/if0 replicated factor inits
    (No mask arg: padding rows sit past every real segment's end, so the
    clipped bounds already exclude them.)
    """

    def half(other_sorted, rat_sorted, bounds, n_out):
        f = other_sorted
        stats = jnp.concatenate(
            [(f[:, :, None] * f[:, None, :]).reshape(f.shape[0],
                                                     rank * rank),
             f * rat_sorted[:, None]], axis=1)
        hi, lo = _ds_cumsum(stats)
        zero = jnp.zeros((1, stats.shape[1]), stats.dtype)
        hi = jnp.concatenate([zero, hi], axis=0)
        lo = jnp.concatenate([zero, lo], axis=0)
        starts, ends = bounds[0], bounds[1]
        # difference in double-single: the hi parts cancel exactly (both
        # exactly representable); the residual lives in lo
        seg = coll.psum((hi[ends] - hi[starts]) + (lo[ends] - lo[starts]))
        cnt = coll.psum((ends - starts).astype(jnp.float32))
        A = seg[:, :rank * rank].reshape(n_out, rank, rank)
        b = seg[:, rank * rank:]
        lam = reg * jnp.maximum(cnt, 1.0)
        A = A + lam[:, None, None] * jnp.eye(rank, dtype=A.dtype)[None]
        sol = jnp.linalg.solve(A, b[:, :, None])[:, :, 0]
        sol = jnp.where(cnt[:, None] > 0, sol, 0.0)
        return jnp.maximum(sol, 0.0) if nonneg else sol

    def program(ius, usi, rat_u, rat_i, ub, ib, uf0, if0):
        ub2 = ub[0]  # (2, n_users): this shard's local bounds
        ib2 = ib[0]

        def body(_, carry):
            uf, itf = carry
            uf = half(itf[ius], rat_u, ub2, n_users)
            itf = half(uf[usi], rat_i, ib2, n_items)
            return uf, itf

        return jax.lax.fori_loop(0, max_iter, body, (uf0, if0))

    return program


def _ds_cumsum(x):
    """Double-single (compensated) inclusive cumsum along axis 0: a
    TwoSum-combine associative scan carrying (sum, error) float32 pairs,
    ~float64-precision prefixes from float32 storage. A plain f32 prefix
    loses the tiny per-segment sums to cancellation once the prefix
    magnitude dwarfs them (at MovieLens-25M scale the boundary difference
    carried ~4% median error — r4 review); the compensated scan's
    residual keeps the difference exact to ~2^-45 of the prefix."""

    def two_sum(a, b):
        s = a + b
        bb = s - a
        err = (a - (s - bb)) + (b - bb)
        return s, err

    def combine(c1, c2):
        hi1, lo1 = c1
        hi2, lo2 = c2
        s, e = two_sum(hi1, hi2)
        return s, e + lo1 + lo2

    return jax.lax.associative_scan(
        combine, (x, jnp.zeros_like(x)), axis=0)


def sort_als_triples(u32: np.ndarray, i32: np.ndarray, ratings: np.ndarray):
    """Per-side stable sort of the rating triples (host, once per fit —
    ids are static across iterations). Returns the four row arrays the
    program will actually consume; callers pass THESE to the router so
    residency probes and background promotion see the staged arrays, not
    the unsorted originals."""
    u_order = np.argsort(u32, kind="stable")
    i_order = np.argsort(i32, kind="stable")
    return {
        "u_sorted": u32[u_order], "i_sorted": i32[i_order],
        "ius": i32[u_order], "usi": u32[i_order],
        "rat_u": ratings[u_order], "rat_i": ratings[i_order],
    }


def stage_als_sorted(prep: dict, n_users: int, n_items: int):
    """Stage the sorted triples + per-shard clipped local segment bounds
    for the active mesh. Returns the sharded program args
    (ius, usi, rat_u, rat_i, ub, ib)."""
    from ..parallel import mesh as meshlib
    from ._staging import stage_rows_cached

    mesh = meshlib.get_mesh()
    n_dev = meshlib.data_width(mesh)
    n = len(prep["rat_u"])
    n_padded = meshlib.bucket_rows(n, n_dev)
    blk = n_padded // n_dev

    def bounds_for(ids_sorted, n_out):
        g_starts = np.searchsorted(ids_sorted, np.arange(n_out)) \
            .astype(np.int64)
        g_ends = np.searchsorted(ids_sorted, np.arange(n_out) + 1) \
            .astype(np.int64)
        lo = (np.arange(n_dev) * blk)[:, None]
        hi = lo + blk
        st = np.clip(g_starts[None, :], lo, hi) - lo
        en = np.clip(g_ends[None, :], lo, hi) - lo
        return np.stack([st, en], axis=1).astype(np.int32)  # (n_dev,2,n_out)

    ub = bounds_for(prep["u_sorted"], n_users)
    ib = bounds_for(prep["i_sorted"], n_items)
    return (stage_rows_cached(prep["ius"]),
            stage_rows_cached(prep["usi"]),
            stage_rows_cached(prep["rat_u"]),
            stage_rows_cached(prep["rat_i"]),
            stage_rows_cached(ub, pad_to_multiple=False),
            stage_rows_cached(ib, pad_to_multiple=False))


class ALS(Estimator):
    def _init_params(self):
        self._declareParam("userCol", default="user", doc="user id column")
        self._declareParam("itemCol", default="item", doc="item id column")
        self._declareParam("ratingCol", default="rating", doc="rating column")
        self._declareParam("predictionCol", default="prediction", doc="prediction column")
        self._declareParam("rank", default=10, doc="latent factor size")
        self._declareParam("maxIter", default=10, doc="alternations")
        self._declareParam("regParam", default=0.1, doc="ALS-WR lambda")
        self._declareParam("coldStartStrategy", default="nan", doc="nan|drop")
        self._declareParam("nonnegative", default=False, doc="clip factors at 0")
        self._declareParam("implicitPrefs", default=False, doc="implicit feedback")
        self._declareParam("seed", default=None, doc="init seed")

    def __init__(self, userCol=None, itemCol=None, ratingCol=None, rank=None,
                 maxIter=None, regParam=None, coldStartStrategy=None,
                 nonnegative=None, implicitPrefs=None, seed=None,
                 predictionCol=None):
        super().__init__()
        self._set(userCol=userCol, itemCol=itemCol, ratingCol=ratingCol,
                  rank=rank, maxIter=maxIter, regParam=regParam,
                  coldStartStrategy=coldStartStrategy, nonnegative=nonnegative,
                  implicitPrefs=implicitPrefs, seed=seed,
                  predictionCol=predictionCol)

    def setColdStartStrategy(self, v):
        return self._set(coldStartStrategy=v)

    def getUserCol(self):
        return self.getOrDefault("userCol")

    def getItemCol(self):
        return self.getOrDefault("itemCol")

    def _fit(self, df) -> "ALSModel":
        pdf = df.toPandas()
        uc, ic, rc = (self.getOrDefault("userCol"), self.getOrDefault("itemCol"),
                      self.getOrDefault("ratingCol"))
        rank = int(self.getOrDefault("rank"))
        max_iter = int(self.getOrDefault("maxIter"))
        reg = float(self.getOrDefault("regParam"))
        seed = self.getOrDefault("seed")
        rng = np.random.default_rng(int(seed) if seed is not None else 0)

        users_raw = np.asarray(pdf[uc])
        items_raw = np.asarray(pdf[ic])
        ratings = np.asarray(pdf[rc], dtype=np.float32)
        u_ids, u_index = np.unique(users_raw, return_inverse=True)
        i_ids, i_index = np.unique(items_raw, return_inverse=True)
        U, I = len(u_ids), len(i_ids)

        # stage rating triples sharded by row; normal-equation accumulation
        # is nnz·rank² per half-step plus (U+I)·rank³ Cholesky solves
        from ..parallel import dispatch
        from ._staging import routed_for
        u32 = u_index.astype(np.int32)
        i32 = i_index.astype(np.int32)
        _hint = dispatch.WorkHint(
            flops=2.0 * max_iter * (len(ratings) * rank * rank
                                    + (U + I) * rank ** 3),
            kind="segment")
        nonneg = bool(self.getOrDefault("nonnegative"))
        from ..utils.profiler import PROFILER
        from ._staging import cached_data_parallel
        prep = sort_als_triples(u32, i32, ratings)
        with routed_for(_hint, prep["ius"], prep["usi"], prep["rat_u"],
                        prep["rat_i"]) as _mesh:
            staged = stage_als_sorted(prep, U, I)

            # MLlib-style init: |N(0,1)| rows normalized to unit norm
            # (ALS.scala initialize). r4's small signed init (0.1·N) sat
            # near the zero saddle: on ~25% of course-scale subsets the
            # alternating solves oscillated for >10 iterations at low reg
            # (observed rmse 1.7 vs 0.25 at maxIter=10), and MLE 01's
            # budget is 10 iterations — init quality IS convergence rate
            uf0 = np.abs(rng.standard_normal((U, rank))).astype(np.float32)
            if0 = np.abs(rng.standard_normal((I, rank))).astype(np.float32)
            uf0 /= np.linalg.norm(uf0, axis=1, keepdims=True) + 1e-12
            if0 /= np.linalg.norm(if0, axis=1, keepdims=True) + 1e-12

            fit = cached_data_parallel(
                _als_fit_program(U, I, rank, reg, max_iter, nonneg),
                replicated_argnums=(6, 7))
            _route = "host" if dispatch.is_host_mesh(_mesh) else "device"
            with PROFILER.span("program.als_fit", rows=len(ratings),
                               route=_route):
                # ONE dispatch for the whole alternating fit; one batched
                # device→host transfer for both factor matrices
                uf_h, itf_h = jax.device_get(fit(*staged, uf0, if0))
        m = ALSModel(user_ids=u_ids, item_ids=i_ids,
                     user_factors=uf_h, item_factors=itf_h)
        m._inherit_params(self)
        return m


class ALSModel(Model):
    def _init_params(self):
        ALS._init_params(self)

    def __init__(self, user_ids=None, item_ids=None, user_factors=None,
                 item_factors=None):
        super().__init__()
        self._user_ids = user_ids
        self._item_ids = item_ids
        self._uf = user_factors
        self._if = item_factors

    def setColdStartStrategy(self, v):
        return self._set(coldStartStrategy=v)

    @property
    def rank(self) -> int:
        if self._uf is None:
            # RuntimeError, not AttributeError: an AttributeError from a
            # property body would be re-reported by Params.__getattr__ as
            # "no attribute rank", hiding the real problem
            raise RuntimeError("ALSModel has no factors (not fitted)")
        return int(self._uf.shape[1])

    @property
    def userFactors(self):
        from ..frame.session import get_session
        return get_session().createDataFrame(pd.DataFrame(
            {"id": self._user_ids, "features": list(map(list, self._uf))}))

    @property
    def itemFactors(self):
        from ..frame.session import get_session
        return get_session().createDataFrame(pd.DataFrame(
            {"id": self._item_ids, "features": list(map(list, self._if))}))

    def _lookup(self, raw, ids, factors):
        idx = np.searchsorted(ids, raw)
        idx = np.clip(idx, 0, len(ids) - 1)
        known = ids[idx] == raw
        return idx, known

    def _transform(self, df):
        uc, ic = self.getOrDefault("userCol"), self.getOrDefault("itemCol")
        oc = self.getOrDefault("predictionCol")
        cold = self.getOrDefault("coldStartStrategy")

        def fn(pdf: pd.DataFrame, ctx) -> pd.DataFrame:
            out = pdf.copy()
            if len(out) == 0:
                out[oc] = pd.Series(dtype=float)
                return out
            ui, u_ok = self._lookup(np.asarray(out[uc]), self._user_ids, self._uf)
            ii, i_ok = self._lookup(np.asarray(out[ic]), self._item_ids, self._if)
            pred = np.einsum("ij,ij->i", self._uf[ui], self._if[ii])
            pred = np.where(u_ok & i_ok, pred, np.nan)
            out[oc] = pred.astype(np.float64)
            if cold == "drop":
                out = out[np.isfinite(out[oc])].reset_index(drop=True)
            return out

        return df._derive(fn)

    def _recommend(self, ids, factors, other_ids, other_factors, n: int,
                   id_col: str, rec_col: str):
        scores = factors @ other_factors.T                      # MXU matmul
        top = np.argsort(-scores, axis=1)[:, :n]
        rows = []
        for i, ident in enumerate(ids):
            recs = [
                {"id": int(other_ids[j]) if np.issubdtype(type(other_ids[j]), np.integer)
                 else other_ids[j], "rating": float(scores[i, j])}
                for j in top[i]]
            rows.append({id_col: ident, "recommendations": recs})
        from ..frame.session import get_session
        return get_session().createDataFrame(pd.DataFrame(rows))

    def recommendForAllUsers(self, numItems: int):
        return self._recommend(self._user_ids, self._uf, self._item_ids,
                               self._if, numItems,
                               self.getOrDefault("userCol"), "rec")

    def recommendForAllItems(self, numUsers: int):
        return self._recommend(self._item_ids, self._if, self._user_ids,
                               self._uf, numUsers,
                               self.getOrDefault("itemCol"), "rec")

    def recommendForUserSubset(self, dataset, numItems: int):
        uc = self.getOrDefault("userCol")
        want = np.unique(np.asarray(dataset.toPandas()[uc]))
        sel = np.isin(self._user_ids, want)
        return self._recommend(self._user_ids[sel], self._uf[sel],
                               self._item_ids, self._if, numItems, uc, "rec")

    def _save_state(self, path):
        save_arrays(path, user_ids=self._user_ids, item_ids=self._item_ids,
                    user_factors=self._uf, item_factors=self._if)

    def _load_state(self, path, meta):
        d = load_arrays(path)
        self._user_ids = d["user_ids"]
        self._item_ids = d["item_ids"]
        self._uf = d["user_factors"]
        self._if = d["item_factors"]
