"""Histogram tree engine — one second-order PLANET learner for DT/RF/GBT.

The reference trains trees by the PLANET recipe: discretize features into
`maxBins` bins, have each worker build per-(node, feature, bin) statistics
over its rows, merge "via tree reduce", pick splits centrally
(`SML/ML 06 - Decision Trees.py:98-118`); distributed XGBoost does the same
with gradient/hessian stats merged by Rabit allreduce (`SML/ML 11 -
XGBoost.py:55-69`). This module is the TPU-native re-design of both:

- binning on host (quantile edges; categorical slots get one bin per
  category, ordered by label mean — the ordered-categorical trick PLANET and
  Spark use for regression/binary targets);
- ONE jitted shard_map program builds a whole tree: level-wise scatter-add
  histograms of (grad, hess, weight) per chip → `psum` over ICI (the Rabit
  allreduce), replicated split selection from cumulative bin sums, and
  on-device node reassignment — no host round-trip per level;
- everything is second-order (XGBoost objective): squared loss ⇒ grad=-y,
  hess=1 reduces leaves to masked means and gain to SSE reduction, so plain
  decision trees, random forests and boosted trees are the same compiled
  program with different (grad, hess) streams and random masks.

Static shapes throughout: node arrays are full binary trees of size
2^(maxDepth+1)-1, rows are padded+masked, so one XLA compile per
(depth, features, bins, shard) signature serves every tree of a forest and
every boosting round (SURVEY §7 hard part #6).
"""

from __future__ import annotations

import math
from functools import partial
from typing import Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..parallel import collectives as coll
from ..parallel import mesh as meshlib
from ._staging import data_parallel, stage_sharded, transient_hbm


class TreeSpec(NamedTuple):
    """Static (hashable) build configuration — part of the jit cache key."""
    max_depth: int
    n_bins: int
    n_features: int
    feature_k: int          # features considered per node (RF subspace); =n_features for DT/GBT
    min_instances: int
    min_info_gain: float
    reg_lambda: float       # L2 on leaf values (XGBoost lambda; 0 for plain trees)
    gamma: float            # min split loss (XGBoost gamma)


class FittedTree(NamedTuple):
    split_feature: np.ndarray   # (N,) int32, -1 for leaves
    split_bin: np.ndarray       # (N,) int32: go left iff bin <= split_bin
    leaf_value: np.ndarray      # (N,) float32
    gain: np.ndarray            # (N,) float32 split gains (importance source)
    cover: np.ndarray           # (N,) float32 hessian mass per node


class TrialDyn(NamedTuple):
    """Per-TRIAL hyperparameters as TRACED scalars (grid-fused batching):
    the program is compiled once at the grid MAXIMA (static shapes come
    from TreeSpec), and each vmapped trial gates itself down to its own
    hyperparameters at run time — a grid over maxDepth x numTrees x ...
    is ONE executable, not one per grid point."""
    depth: object           # splits allowed only at level < depth
    feature_k: object       # RF subspace width (== n_features disables)
    min_instances: object   # min hessian-count per child
    min_info_gain: object   # min split gain


class Binning(NamedTuple):
    edges: np.ndarray           # (F, B-1) float32 upper-inclusive thresholds (+inf padded)
    cat_remap: Dict[int, np.ndarray]  # slot -> category->rank map (label-mean order)


def bin_dtype(max_bins: int) -> np.dtype:
    """Narrowest unsigned dtype holding bin ids in [0, max_bins): the
    quantized engine ships and keeps bin matrices COMPACT (uint8 at the
    default maxBins ≤ 256 — 4x less H2D traffic and HBM residency than the
    int32 matrices the seed staged), widening only when maxBins demands."""
    if max_bins <= (1 << 8):
        return np.dtype(np.uint8)
    if max_bins <= (1 << 16):
        return np.dtype(np.uint16)
    return np.dtype(np.int32)


def finalize_binning(F: int, max_bins: int,
                     categorical: Optional[Dict[int, int]],
                     cont_quantiles: Dict[int, Optional[np.ndarray]],
                     cat_means: Dict[int, np.ndarray],
                     max_categories_error: bool = True):
    """Assemble a `Binning` from per-feature quantile values + per-slot
    category label means — the ONE edge-assembly shared by the monolithic
    `make_bins` and the streamed-sketch path (`frame/_chunks.py`'s
    DatasetSketch), so the two ingest paths cannot drift: same
    unique/float32 edge collapse, same label-mean category ordering, same
    maxBins cardinality error, same compact-dtype sizing.

    `cont_quantiles[f]` is the raw `np.quantile` output for continuous
    slot f (None/empty = no finite values — the slot bins to 0);
    `cat_means[f]` is the per-category mean-label array (inf for absent
    categories). Returns (Binning, edge_list, out_dtype)."""
    categorical = categorical or {}
    for slot, card in categorical.items():
        if card > max_bins and max_categories_error:
            raise ValueError(
                f"DecisionTree requires maxBins (= {max_bins}) to be at least "
                f"as large as the number of values in each categorical feature, "
                f"but categorical feature {slot} has {card} values. "
                f"Consider removing this and other categorical features with "
                f"a large number of values, or add more training examples.")
    edges = np.full((F, max_bins - 1), np.inf, dtype=np.float32)
    remaps: Dict[int, np.ndarray] = {}
    edge_list: list = [np.zeros(0, dtype=np.float32)] * F
    for f in range(F):
        if f in categorical:
            card = int(categorical[f])
            means = cat_means[f]
            order = np.argsort(means, kind="stable")
            rank = np.empty(card, dtype=np.int32)
            rank[order] = np.arange(card, dtype=np.int32)
            remaps[f] = rank
            edges[f, :] = np.inf  # traversal uses bins directly
        else:
            qs = cont_quantiles.get(f)
            if qs is None or len(qs) == 0:
                continue
            qs = np.unique(np.asarray(qs).astype(np.float32))
            edges[f, :len(qs)] = qs
            edge_list[f] = qs
    # dtype must hold the categorical ranks too: with
    # max_categories_error=False a cardinality may legally exceed
    # max_bins, and a uint8 matrix would silently wrap those ranks
    need = max([max_bins] + [len(r) for r in remaps.values()])
    return Binning(edges=edges, cat_remap=remaps), edge_list, bin_dtype(need)


def make_bins(X: np.ndarray, y: np.ndarray, max_bins: int,
              categorical: Optional[Dict[int, int]] = None,
              max_categories_error: bool = True) -> Tuple[np.ndarray, Binning]:
    """Host-side discretization. Continuous features: quantile edges.
    Categorical slots: identity bins ordered by mean label; cardinality must
    fit in max_bins, reproducing Spark's maxBins error (`ML 06:91-126`)."""
    n, F = X.shape
    categorical = categorical or {}
    cont_quantiles: Dict[int, Optional[np.ndarray]] = {}
    cat_means: Dict[int, np.ndarray] = {}
    for f in range(F):
        col = X[:, f]
        if f in categorical:
            card = int(categorical[f])
            means = np.full(card, np.inf)
            ids = col.astype(np.int64)
            ids = np.clip(ids, 0, card - 1)
            for c in range(card):
                sel = ids == c
                if sel.any():
                    means[c] = float(y[sel].mean()) if y is not None else c
            cat_means[f] = means
        else:
            finite = col[np.isfinite(col)]
            if len(finite) == 0:
                cont_quantiles[f] = None
                continue
            # edges from a deterministic subsample above 256k rows — the
            # same approximation Spark's approxQuantile binning and
            # sklearn's HistGradientBoosting use; full-data quantiles cost
            # ~1.2s/fit at 1M rows and change edges negligibly
            if len(finite) > 262_144:
                stride = -(-len(finite) // 262_144)
                finite = finite[::stride]
            cont_quantiles[f] = np.quantile(
                finite, np.linspace(0, 1, max_bins + 1)[1:-1])
    binning, edge_list, out_dtype = finalize_binning(
        F, max_bins, categorical, cont_quantiles, cat_means,
        max_categories_error=max_categories_error)
    binned = _bin_columns(X, edge_list, binning.cat_remap, out_dtype)
    return binned, binning


def _bin_columns(X: np.ndarray, edge_list, remaps: Dict[int, np.ndarray],
                 out_dtype=np.int32) -> np.ndarray:
    """Full-column discretization against known edges/remaps: the threaded
    C++ kernel (`native/binning.cc`) when available, NumPy otherwise —
    identical semantics (searchsorted 'left'; non-finite → bin 0).
    `out_dtype` is the quantized engine's compact storage dtype (see
    `bin_dtype`); callers size it over max_bins AND every categorical
    cardinality, so all bin ids fit by construction."""
    from ..native import binning as _native_binning
    n, F = X.shape
    binned = _native_binning.bin_continuous(X, edge_list, remaps)
    if binned is not None:
        binned = binned.astype(out_dtype, copy=False)
    else:
        binned = np.zeros((n, F), dtype=out_dtype)
        for f in range(F):
            if f in remaps:
                continue
            qs = edge_list[f]
            if len(qs) == 0:
                continue
            col = X[:, f]
            binned[:, f] = np.searchsorted(qs, col,
                                           side="left").astype(out_dtype)
            binned[~np.isfinite(col), f] = 0  # missing → lowest bin
    for f, rank in remaps.items():
        ids = np.clip(X[:, f].astype(np.int64), 0, len(rank) - 1)
        binned[:, f] = rank[ids]
    return binned


import threading as _threading

_predict_bin_cache: dict = {}
_predict_bin_lock = _threading.Lock()  # CV trials bin concurrently
# bytes-bounded LRU (sml.predict.binCacheBytes): the CV/tuning suite
# legitimately holds ~20 distinct (matrix, model-edges) pairs at once
# (each fold's models re-bin the val matrix with their OWN quantile
# edges); an 8-entry cap thrashed every pass and re-paid ~0.3s of
# digitize per eval (r4 profile: 6.2s/pass)


def binning_edges_and_dtype(binning: Binning):
    """(edge_list, out_dtype) for quantizing FRESH rows under a saved
    `Binning` — the one shared derivation behind predict-time `bin_with`
    and the pinned-binning warm-start ingest (`ml/_chunked
    .ingest_source(binning=)`), so the two can never drift: same
    finite-edge extraction, same compact-dtype sizing over max_bins AND
    every categorical cardinality (which may exceed max_bins when the
    guard was suppressed at fit time)."""
    edge_list = [binning.edges[f][np.isfinite(binning.edges[f])]
                 for f in range(binning.edges.shape[0])]
    need = max([binning.edges.shape[1] + 1]
               + [len(r) for r in binning.cat_remap.values()])
    return edge_list, bin_dtype(need)


def bin_with(X: np.ndarray, binning: Binning) -> np.ndarray:
    """Apply training-time bin edges / category ranks at predict time.

    Content-memoized: tuning loops (ML 08's TPE objective, CV fold
    evaluates) re-predict on the SAME feature matrix with models whose bin
    edges are value-identical (same data, same maxBins), so the digitize
    pass would otherwise re-run per eval (~0.4s at 800k x 10)."""
    from ._staging import _memo_key, _normalize
    Xn = _normalize(X)
    edge_key = hash(tuple(e.tobytes() for e in binning.edges)) \
        ^ hash(tuple(sorted((k, v.tobytes())
                            for k, v in binning.cat_remap.items())))
    key = (_memo_key(Xn), edge_key)
    with _predict_bin_lock:
        hit = _predict_bin_cache.get(key)
        if hit is not None:
            # move-to-end LRU touch: dicts iterate in insertion order
            _predict_bin_cache.pop(key)
            _predict_bin_cache[key] = hit
    if hit is not None:
        return hit
    edge_list, out_dtype = binning_edges_and_dtype(binning)
    out = _bin_columns(Xn, edge_list, binning.cat_remap, out_dtype)
    from ..conf import GLOBAL_CONF
    max_bytes = GLOBAL_CONF.getInt("sml.predict.binCacheBytes")
    with _predict_bin_lock:
        total = out.nbytes + sum(v.nbytes for v in _predict_bin_cache.values())
        while total > max_bytes and _predict_bin_cache:
            oldest = next(iter(_predict_bin_cache))
            total -= _predict_bin_cache.pop(oldest).nbytes
        _predict_bin_cache[key] = out
    return out


# ---------------------------------------------------------------------------
#: id(mesh) -> (mesh, platform). The entry HOLDS the mesh so a recycled
#: id() after garbage collection can never serve a stale platform (the
#: hit path re-checks identity); meshes are few and small per process.
_platform_memo: Dict[int, tuple] = {}


def _mesh_platform(mesh=None) -> str:
    """The active mesh's device platform, memoized per mesh identity:
    `_hist_dtype` and `_kernel_choice` both run inside every fit setup,
    and walking `mesh.devices.flat` allocates a fresh device list per
    call. Mesh identity keys the memo (a new/rebuilt mesh re-probes);
    conf is deliberately NOT part of the memo — knobs like
    `sml.tree.kernel` are read fresh by their own resolvers on top of
    the memoized platform, so a conf change takes effect immediately."""
    mesh = mesh or meshlib.get_mesh()
    key = id(mesh)
    hit = _platform_memo.get(key)
    if hit is not None and hit[0] is mesh:
        return hit[1]
    plat = str(list(mesh.devices.flat)[0].platform)
    _platform_memo[key] = (mesh, plat)
    return plat


def _hist_dtype():
    """bf16 histogram operands on TPU (exact one-hot, f32 accumulation on
    the MXU); f32 elsewhere — XLA:CPU has no bf16xbf16=f32 dot."""
    return jnp.bfloat16 if _mesh_platform() == "tpu" else jnp.float32


def _kernel_choice() -> str:
    """Resolve `sml.tree.kernel` to the concrete build path ("pallas" /
    "xla") for the ACTIVE mesh — the resolved value is part of every
    tree-program cache key and rides the prewarm manifest so replay
    rebuilds the same executable.

    Fallback ladder (docs/KERNELS.md): 'xla' short-circuits; 'pallas'
    requires the toolchain probe (`native.hist_kernel.available`) and
    otherwise falls back to xla counting `kernel.fallback`; 'auto' only
    ever selects pallas on a real TPU mesh (interpret-mode emulation is
    an explicit opt-in via 'pallas', never a default on CPU)."""
    from ..conf import GLOBAL_CONF
    from ..utils.profiler import PROFILER
    mode = str(GLOBAL_CONF.get("sml.tree.kernel")).strip().lower()
    if mode not in ("auto", "pallas", "xla"):
        # a typo must not silently land on either path (on TPU an
        # unknown value would otherwise behave like 'auto' = pallas)
        raise ValueError(
            f"sml.tree.kernel must be one of auto/pallas/xla, got {mode!r}")
    if mode == "xla":
        return "xla"
    if mode == "auto" and _mesh_platform() != "tpu":
        return "xla"  # auto: never emulate on non-TPU backends
    from ..native import hist_kernel as _hk
    if _hk.available():
        return "pallas"
    PROFILER.count("kernel.fallback")
    return "xla"


#: compiled split_scan holds the whole per-level (F, B, width, 3) f32
#: histogram as ONE un-gridded VMEM block; past this budget it cannot
#: lower on real hardware (~16 MB VMEM/core, shared with the operands)
_SCAN_VMEM_BUDGET = 8 << 20


def _kernel_for(spec: TreeSpec) -> str:
    """Per-fit kernel resolution: `_kernel_choice` plus a STATIC shape
    guard for the compiled path — the split-scan kernel takes the whole
    widest-level histogram (F · bins · 2^(depth-1) · 3 f32) as one VMEM
    block, so specs past `_SCAN_VMEM_BUDGET` demote to xla with a
    `kernel.fallback` count instead of failing to lower mid-trace on
    real TPU (`available()` only proves the toolchain imports; it cannot
    probe every shape). Interpret mode has no VMEM and never demotes."""
    kernel = _kernel_choice()
    if kernel == "pallas" and _mesh_platform() == "tpu":
        width = 2 ** max(spec.max_depth - 1, 0)
        hist_bytes = spec.n_features * spec.n_bins * width * 3 * 4
        if hist_bytes > _SCAN_VMEM_BUDGET:
            from ..utils.profiler import PROFILER
            PROFILER.count("kernel.fallback")
            return "xla"
    return kernel


def _kernel_block_rows(kernel: str) -> int:
    """Resolved `sml.tree.kernelBlockRows` for pallas programs (0 on the
    XLA path, which has no block scheme). Read ONCE per program build and
    carried in every tree program cache key AND the prewarm manifest —
    toggling the knob must compile a fresh executable, not silently
    replay one traced under the old block scheme (the same contract
    `sml.tpu.donate` and `sml.tree.histSubtraction` already honor)."""
    if kernel != "pallas":
        return 0
    from ..conf import GLOBAL_CONF
    return GLOBAL_CONF.getInt("sml.tree.kernelBlockRows")


def _hist_subtract() -> bool:
    from ..conf import GLOBAL_CONF
    return GLOBAL_CONF.getBool("sml.tree.histSubtraction")


def _hier_ici(mesh=None) -> int:
    """Static ICI-hop width of the two-level histogram allreduce: the
    mesh's "ici" axis size when the mesh declares the host topology
    (`mesh.host_mesh`) and `sml.tree.hierarchicalAllreduce` allows it,
    else 0 (= flat single-hop psum). Resolved at PROGRAM BUILD time and
    part of every tree program cache key — toggling the knob or changing
    the group shape must compile a fresh program, never replay one traced
    under the other reduction structure."""
    from ..conf import GLOBAL_CONF
    mesh = mesh or meshlib.get_mesh()
    if not meshlib.is_hierarchical(mesh):
        return 0
    mode = str(GLOBAL_CONF.get("sml.tree.hierarchicalAllreduce")
               or "auto").strip().lower()
    if mode in ("false", "0", "off", "no"):
        return 0
    return int(mesh.shape[meshlib.ICI_AXIS])


def _make_tree_builder(spec: TreeSpec, hist_dtype=jnp.float32,
                       subtract: bool = True, kernel: str = "xla",
                       block_rows: int = 0, axes=None, hier_ici: int = 0):
    """Pure per-chip tree-build fn (called inside shard_map): one level-wise
    pass, histograms as one-hot dots, psum merges. Returns stacked node
    arrays as a single (5, n_nodes) f32 pack (one transfer, one scan slot).

    `subtract` enables the classic HISTOGRAM-SUBTRACTION trick (LightGBM's
    parent-minus-sibling): below the root, only LEFT children histogram
    from rows; right children are parent − left, computed post-psum — the
    one-hot hist matmul (the build's dominant FLOPs and HBM traffic)
    halves at every level, and the psum payload halves with it. With the
    built-in estimators' INTEGER sampling weights (Poisson/Bernoulli
    draws, f32-exact ≤ 2^24) the count channel is exact, so the
    min_instances gates cannot drift; grad/hess sums — and, for callers
    passing arbitrary FRACTIONAL weights through fit_tree, the count
    channel too — pick up cancellation noise that compounds with depth
    (each parent was itself subtraction-derived), so a weight sum sitting
    exactly on the min_instances boundary can gate differently than the
    direct build. Nodes whose parent did NOT split are gated to zero,
    exactly matching the direct computation (no rows ever reach them).

    `build(..., dyn=TrialDyn(...))` swaps depth / feature_k /
    min_instances / min_info_gain for TRACED per-trial scalars (the
    grid-fused batching path): the loop still unrolls to spec.max_depth,
    but splits are gated off at level >= dyn.depth, so a shallower trial
    produces the tree its own static program would have (deeper nodes
    keep zero cover and inherit the parent value).

    `kernel="pallas"` swaps the histogram dot and the gain scan for the
    fused `native/hist_kernel.py` launches (bin-accumulate straight from
    the compact `binned_c` operand — callers pass B1t=None — then the
    in-register split scan on the post-psum histogram); the psum, the
    histogram-subtraction gating, the RF-subspace draw, and the row
    routing stay in the shared glue, so per-chip partials and randomness
    are identical to the XLA path. On non-TPU platforms the kernels run
    in interpret mode (single row block — bit-parity with this very
    function's XLA branch, asserted by tests/test_hist_kernel.py)."""
    D, B, F = spec.max_depth, spec.n_bins, spec.n_features
    n_nodes = 2 ** (D + 1) - 1
    axes = tuple(axes) if axes else (meshlib.DATA_AXIS,)

    def _psum_merge(part):
        # the post-histogram merge: hierarchical two-level reduce when the
        # program was built for a host mesh with the knob on (hier_ici is
        # the static ici width), else the flat allreduce over the row
        # axes — same result, different hop structure and byte counters
        if hier_ici > 1:
            return coll.psum_hierarchical(
                part, ici_axis=meshlib.ICI_AXIS,
                dcn_axis=meshlib.DCN_AXIS, ici_size=hier_ici)
        return coll.psum(part, axes if len(axes) > 1 else axes[0])

    use_pallas = kernel == "pallas"
    if use_pallas:
        from ..native import hist_kernel as _hk
        interp = _mesh_platform() != "tpu"
        if not interp and block_rows:
            # the accumulate kernel's per-block one-hot tile is
            # block_rows·F·B·itemsize of VMEM: clamp the conf target to
            # the same budget the split-scan guard enforces, so an
            # oversized tile shrinks the block instead of failing to
            # lower (the conf value stays the cache key — this clamp is
            # a pure function of (spec, conf), both already keyed)
            per_row = F * B * np.dtype(hist_dtype).itemsize
            block_rows = max(
                min(block_rows, _SCAN_VMEM_BUDGET // max(per_row, 1)), 8)

    def build(B1t, binned, grad, hess, weight, feat_rng, dyn=None,
              binned_c=None):
        min_inst = spec.min_instances if dyn is None else dyn.min_instances
        min_gain = spec.min_info_gain if dyn is None else dyn.min_info_gain
        n = binned.shape[0]
        node = jnp.zeros((n,), dtype=jnp.int32)
        # EVERY row routes down the tree (active = still on a splitting
        # path), so the returned terminal nodes are valid for rows the
        # sampling weights excluded from the HISTOGRAMS (wq masks those) —
        # boosting margins update out-of-sample rows too
        active = jnp.ones((n,), dtype=bool)
        split_feature = jnp.full((n_nodes,), -1, dtype=jnp.int32)
        split_bin = jnp.zeros((n_nodes,), dtype=jnp.int32)
        gains = jnp.zeros((n_nodes,), dtype=jnp.float32)
        node_G = jnp.zeros((n_nodes,), dtype=jnp.float32)
        node_H = jnp.zeros((n_nodes,), dtype=jnp.float32)
        node_W = jnp.zeros((n_nodes,), dtype=jnp.float32)

        hist_prev = None   # (F, B, width/2, 3) — previous level, post-psum
        split_prev = None  # (width/2,) — previous level's do_split
        for level in range(D):
            width = 2 ** level
            base = width - 1
            lid = node - base
            in_level = active & (lid >= 0) & (lid < width)
            lid_c = jnp.where(in_level, lid, 0)
            wq = jnp.where(in_level, weight, 0.0)
            if subtract and level > 0:
                # rows histogram only into their LEFT-child slot; right
                # children come from parent − left below
                half = width // 2
                is_left = (lid_c % 2) == 0
                wl = jnp.where(is_left, wq, 0.0)
                hw, lid_h, w_eff = half, lid_c // 2, wl
            else:
                hw, lid_h, w_eff = width, lid_c, wq
            if use_pallas:
                # fused bin-accumulate straight from the compact bin
                # cache operand: the one-hot tiles live only in VMEM
                # block_rows is the HOST-resolved value carried by this
                # program's cache key; the kernel never reads conf at
                # trace time (0 means one full block)
                part = _hk.hist_accumulate(
                    binned if binned_c is None else binned_c,
                    lid_h, grad, hess, w_eff, n_bins=B, n_slots=hw,
                    hist_dtype=hist_dtype, interpret=interp,
                    block_rows=block_rows)
            else:
                node1hot = jax.nn.one_hot(lid_h, hw, dtype=hist_dtype) \
                    * (w_eff > 0)[:, None].astype(hist_dtype)
                stats = jnp.stack([grad * w_eff, hess * w_eff, w_eff],
                                  axis=1)
                ns = (node1hot[:, :, None]
                      * stats[:, None, :].astype(hist_dtype)
                      ).reshape(n, hw * 3)
                # bf16 operands (the one-hot side is EXACT in bf16), f32
                # accumulation: the MXU's native mode. B1t is
                # pre-transposed OUTSIDE the tree scan — a .T here would
                # re-materialize a ~1GB transpose every level of every
                # tree
                part = jax.lax.dot_general(
                    B1t, ns, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)
            hist = _psum_merge(part)
            if subtract and level > 0:
                half = width // 2
                left = hist.reshape(F, B, half, 3)
                # a parent that did not split has no children: gate its
                # whole histogram to zero, as the direct path computes
                parent = hist_prev * \
                    split_prev.astype(jnp.float32)[None, None, :, None]
                right = parent - left
                hist = jnp.stack([left, right], axis=3) \
                    .reshape(F, B, width, 3)
            else:
                hist = hist.reshape(F, B, width, 3)
            if dyn is not None or spec.feature_k < F:
                # under dyn the draw ALWAYS happens (feature_k is traced);
                # with feature_k == F the mask is all-True, so a
                # no-subspace trial sees the identical candidate set its
                # own static program (which skips the draw) produces. The
                # draw stays OUTSIDE the pallas kernel so both paths
                # consume the same randomness
                u = jax.random.uniform(
                    jax.random.fold_in(jax.random.wrap_key_data(feat_rng), level),
                    (width, F))
                ranks = jnp.argsort(jnp.argsort(u, axis=1), axis=1)
                fk = spec.feature_k if dyn is None else dyn.feature_k
                fmask = ranks < fk                             # (width, F)
            else:
                fmask = None
            if use_pallas:
                # fused split scan: cumsum + gain + masks + argmax in one
                # kernel on the post-psum histogram; only the (6, width)
                # best-split pack leaves it
                pack6 = _hk.split_scan(
                    hist,
                    jnp.ones((width, F), jnp.float32) if fmask is None
                    else fmask.astype(jnp.float32),
                    jnp.asarray(min_inst, jnp.float32).reshape(1, 1),
                    reg_lambda=spec.reg_lambda, gamma=spec.gamma,
                    interpret=interp)
                best_f = pack6[0].astype(jnp.int32)
                best_b = pack6[1].astype(jnp.int32)
                best_gain = pack6[2]
                gG, gH, gW = pack6[3], pack6[4], pack6[5]
            else:
                hG = jnp.transpose(hist[..., 0], (2, 0, 1))          # (width,F,B)
                hH = jnp.transpose(hist[..., 1], (2, 0, 1))
                hW = jnp.transpose(hist[..., 2], (2, 0, 1))
                GL = jnp.cumsum(hG, axis=2)
                HL = jnp.cumsum(hH, axis=2)
                WL = jnp.cumsum(hW, axis=2)
                G = GL[:, :, -1:]
                H = HL[:, :, -1:]
                W = WL[:, :, -1:]
                lam = spec.reg_lambda
                score = (GL ** 2 / (HL + lam + 1e-12)
                         + (G - GL) ** 2 / (H - HL + lam + 1e-12)
                         - G ** 2 / (H + lam + 1e-12))
                ok = ((WL >= min_inst)
                      & ((W - WL) >= min_inst))
                ok = ok & (jnp.arange(B)[None, None, :] < B - 1)
                if fmask is not None:
                    ok = ok & fmask[:, :, None]
                score = jnp.where(ok, score, -jnp.inf)
                flat_best = jnp.argmax(score.reshape(width, F * B), axis=1)
                best_f = (flat_best // B).astype(jnp.int32)
                best_b = (flat_best % B).astype(jnp.int32)
                best_gain = 0.5 * jnp.take_along_axis(
                    score.reshape(width, F * B), flat_best[:, None],
                    axis=1)[:, 0] - spec.gamma
                gG, gH, gW = G[:, 0, 0], H[:, 0, 0], W[:, 0, 0]
            do_split = (best_gain > min_gain) & jnp.isfinite(best_gain)
            if dyn is not None:  # trial's own maxDepth: no splits beyond it
                do_split = do_split & (level < dyn.depth)
            idx = base + jnp.arange(width)
            node_G = node_G.at[idx].set(gG)
            node_H = node_H.at[idx].set(gH)
            node_W = node_W.at[idx].set(gW)
            split_feature = split_feature.at[idx].set(
                jnp.where(do_split, best_f, -1))
            split_bin = split_bin.at[idx].set(best_b)
            gains = gains.at[idx].set(jnp.where(do_split, best_gain, 0.0))
            # row-dependent gathers (table[my_idx], take_along_axis) lower
            # to XLA's generic scratch-memory gather on TPU — ~22ms per
            # call at 800k rows, THE dominant cost of the whole build. The
            # same lookups as masked sums are plain VPU work.
            lid_eq = lid_c[:, None] == jnp.arange(width,
                                                  dtype=jnp.int32)[None, :]
            my_f = jnp.sum(jnp.where(lid_eq, best_f[None, :], 0), axis=1)
            my_b = jnp.sum(jnp.where(lid_eq, best_b[None, :], 0), axis=1)
            my_split = jnp.any(lid_eq & do_split[None, :], axis=1)
            feat_eq = my_f[:, None] == jnp.arange(F, dtype=jnp.int32)[None, :]
            xbin = jnp.sum(jnp.where(feat_eq, binned, 0), axis=1)
            go_right = xbin > my_b
            child = 2 * node + 1 + go_right.astype(jnp.int32)
            node = jnp.where(in_level & my_split, child, node)
            active = in_level & my_split
            hist_prev = hist
            split_prev = do_split

        # leaf stats for the last level
        width = 2 ** D
        base = width - 1
        lid = node - base
        in_level = (lid >= 0) & (lid < width) & (weight > 0)
        lid_c = jnp.where(in_level, lid, 0)
        wq = jnp.where(in_level, weight, 0.0)
        node1hot = jax.nn.one_hot(lid_c, width, dtype=jnp.float32) \
            * (wq > 0)[:, None]
        lstats = _psum_merge(node1hot.T @ jnp.stack(
            [grad * wq, hess * wq, wq], axis=1))
        idx = base + jnp.arange(width)
        node_G = node_G.at[idx].set(lstats[:, 0])
        node_H = node_H.at[idx].set(lstats[:, 1])
        node_W = node_W.at[idx].set(lstats[:, 2])
        leaf_value = -node_G / (node_H + spec.reg_lambda + 1e-12)
        # empty nodes (zero cover) inherit the parent value so unseen routes
        # at predict time fall back gracefully; D passes propagate top-down
        parent = jnp.maximum((jnp.arange(n_nodes) - 1) // 2, 0)
        for _ in range(D):
            leaf_value = jnp.where(node_W > 0, leaf_value, leaf_value[parent])
            split_feature = jnp.where(node_W > 0, split_feature, -1)
        pack = jnp.stack([split_feature.astype(jnp.float32),
                          split_bin.astype(jnp.float32),
                          leaf_value, gains, node_H])
        # `node` is each row's terminal node — the build IS the traversal,
        # so boosting margin updates need one gather, not a depth-long
        # re-walk of the tree it just built
        return pack, node

    return build


def _traverse(binned, split_feature, split_bin, leaf_value, depth: int):
    """Vectorized on-device tree traversal (shared by fit-time margin
    updates and predict)."""
    node = jnp.zeros((binned.shape[0],), dtype=jnp.int32)
    for _ in range(depth):
        f = split_feature[node]
        b = split_bin[node]
        is_internal = f >= 0
        xbin = jnp.take_along_axis(binned, jnp.maximum(f, 0)[:, None],
                                   axis=1)[:, 0]
        child = 2 * node + 1 + (xbin > b).astype(jnp.int32)
        node = jnp.where(is_internal, child, node)
    return leaf_value[node]


class EnsembleSpec(NamedTuple):
    """Static configuration of a whole-ensemble on-device build."""
    tree: TreeSpec
    n_trees: int
    loss: str           # "squared" | "logistic"
    boosting: bool
    bootstrap: bool
    subsample: float
    step_size: float


_ensemble_cache: Dict[EnsembleSpec, object] = {}


def _base_margin_fn(loss: str, axes=None):
    """Per-chip base-margin statistic (mean / log-odds of the masked
    labels) with ONE fused allreduce for both sufficient statistics —
    shared by the monolithic ensemble program and the chunked boosting
    path's standalone base program, so both produce bit-identical bases.
    `axes` generalizes the reduction to a host mesh's row-axis tuple."""
    ax = tuple(axes) if axes else (meshlib.DATA_AXIS,)
    ax = ax if len(ax) > 1 else ax[0]

    def base_fn(y, mask):
        n_tot, y_tot = coll.psum_scalars(jnp.sum(mask), jnp.sum(y * mask),
                                         axis=ax)
        if loss == "logistic":
            p0 = jnp.clip(y_tot / n_tot, 1e-6, 1 - 1e-6)
            return jnp.log(p0 / (1 - p0))
        return y_tot / n_tot
    base_fn.__name__ = f"tree_base_{loss}"
    return base_fn


def _sliced_draw(n: int, data_width: int, draw, axes=None):
    """Mesh-layout-INVARIANT sampling weights: every chip draws the FULL
    padded row space (`n * data_width` values — counter-based threefry,
    a few cheap VPU passes next to the histogram matmuls) from the same
    replicated key and slices out its own row block, so the selected
    weights are bit-identical to the single-device draw no matter how
    rows shard. Before r6 each chip folded its shard index into the key,
    which made every bootstrap forest a function of the mesh LAYOUT —
    adding chips silently changed the fitted model, and an 8-chip fit
    could never golden-match a 1-chip fit."""
    if data_width <= 1:
        return draw((n,))
    full = draw((n * data_width,))
    ax = tuple(axes) if axes else (meshlib.DATA_AXIS,)
    idx = coll.axis_index(ax if len(ax) > 1 else ax[0])
    return jax.lax.dynamic_slice(full, (idx * n,), (n,))


def _ensemble_pieces(es: EnsembleSpec, data_width: int = 1,
                     kernel: str = "xla", block_rows: int = 0,
                     axes=None, hier_ici: int = 0):
    """The shared internals of every ensemble program shape: `prepare`
    widens the compact quantized bins on-device and hoists the one-hot
    transpose; `make_round` returns the per-round scan body. Factored so
    the monolithic program and the chunked boosting program are the SAME
    math — a parity test holds them together. `data_width` is the mesh's
    STATIC data-axis size (part of every program cache's mesh-id key):
    sampling draws span `local_rows * data_width` so every layout selects
    the same global weights (see `_sliced_draw`). Under
    `kernel="pallas"` the fit-long B1t one-hot resident is never built
    (B1t=None) — the pallas kernel one-hots VMEM bin tiles per row block
    from the COMPACT operand instead."""
    spec = es.tree
    hist_dtype = _hist_dtype()
    build = _make_tree_builder(spec, hist_dtype, subtract=_hist_subtract(),
                               kernel=kernel, block_rows=block_rows,
                               axes=axes, hier_ici=hier_ici)
    B, F = spec.n_bins, spec.n_features

    def prepare(binned, rng):
        n = binned.shape[0]
        # compact uint8/uint16 bins widen ON-DEVICE (a fused VPU cast over
        # the 4x-smaller staged matrix), never on the host/H2D path; the
        # compact operand survives alongside — the kernel path histograms
        # straight from it
        binned_c = binned
        binned = binned.astype(jnp.int32)
        if kernel == "pallas":
            B1t = None  # kernel one-hots bin tiles in VMEM per block
        else:
            B1t = jax.nn.one_hot(binned, B, dtype=hist_dtype) \
                .reshape(n, F * B).T  # transposed ONCE, reused every tree
        # ONE replicated sampling stream (fold_in(0) preserves the
        # historical single-device draws bit-for-bit); per-chip weights
        # come from slicing the global draw, not from per-chip keys
        key = jax.random.fold_in(jax.random.wrap_key_data(rng), 0)
        return binned, binned_c, B1t, key

    def make_round(binned, binned_c, B1t, y, mask, key, rng):
        n = binned.shape[0]

        def round_fn(margin, t):
            if es.boosting:
                if es.loss == "logistic":
                    p = jax.nn.sigmoid(margin)
                    grad = p - y
                    hess = jnp.maximum(p * (1 - p), 1e-6)
                else:
                    grad = margin - y
                    hess = jnp.ones_like(y)
            else:
                grad = -y
                hess = jnp.ones_like(y)
            kt = jax.random.fold_in(key, t)
            if es.bootstrap and es.n_trees > 1:
                w = _sliced_draw(n, data_width, lambda s: jax.random.poisson(
                    kt, es.subsample, s).astype(jnp.float32), axes)
            elif es.subsample < 1.0:
                w = _sliced_draw(n, data_width, lambda s: jax.random.bernoulli(
                    kt, es.subsample, s).astype(jnp.float32), axes)
            else:
                w = jnp.ones((n,), jnp.float32)
            w = w * mask
            feat_rng = jax.random.key_data(jax.random.fold_in(
                jax.random.wrap_key_data(rng), t))  # same across chips
            pack, node_fin = build(B1t, binned, grad, hess, w, feat_rng,
                                   binned_c=binned_c)
            if es.boosting:
                # the build routed every row to its terminal node already:
                # the margin update is one gather, not a depth-long re-walk
                margin = margin + es.step_size * pack[2][node_fin]
            return margin, pack

        return round_fn

    return prepare, make_round


def _data_width(mesh=None) -> int:
    """The mesh's static row-shard count — the sampling-slice factor every
    program maker threads into `_ensemble_pieces` (programs cache per
    mesh id, so the width is as static as the mesh). On a hierarchical
    host mesh this is DCN×ICI — rows shard over both hops."""
    mesh = mesh or meshlib.get_mesh()
    if meshlib.is_hierarchical(mesh):
        return meshlib.data_width(mesh)
    return int(mesh.shape.get(meshlib.DATA_AXIS, 1))


def _make_ensemble_program(es: EnsembleSpec, data_width: int = 1,
                           kernel: str = "xla", block_rows: int = 0,
                           axes=None, hier_ici: int = 0):
    """The WHOLE forest/boosting fit as one XLA program: `lax.scan` over
    trees, margins and sampling weights living in HBM for the entire fit.
    One dispatch + one packed device→host transfer per ensemble — the
    per-tree host round-trips (expensive over a TPU tunnel) disappear."""
    prepare, make_round = _ensemble_pieces(es, data_width, kernel,
                                           block_rows, axes, hier_ici)
    base_of = _base_margin_fn(es.loss, axes)

    def program(binned, y, mask, rng):
        binned, binned_c, B1t, key = prepare(binned, rng)
        base = base_of(y, mask)
        margin0 = jnp.full((binned.shape[0],), base, dtype=jnp.float32)
        round_fn = make_round(binned, binned_c, B1t, y, mask, key, rng)
        _, packs = jax.lax.scan(round_fn, margin0, jnp.arange(es.n_trees))
        return packs, base

    return program


def _make_chunk_program(es: EnsembleSpec, chunk: int, data_width: int = 1,
                        kernel: str = "xla", block_rows: int = 0,
                        axes=None, hier_ici: int = 0):
    """`chunk` boosting rounds as one dispatch: the margin carry enters and
    leaves as a row-sharded HBM buffer (donated between dispatches by the
    caller), `t0` offsets the round index so sampling streams and feature
    subspaces match the monolithic scan round-for-round."""
    prepare, make_round = _ensemble_pieces(es, data_width, kernel,
                                           block_rows, axes, hier_ici)

    def program(binned, y, mask, margin, rng, t0):
        binned, binned_c, B1t, key = prepare(binned, rng)
        round_fn = make_round(binned, binned_c, B1t, y, mask, key, rng)
        margin, packs = jax.lax.scan(
            round_fn, margin, t0 + jnp.arange(chunk, dtype=jnp.int32))
        return margin, packs

    return program


_chunk_cache: Dict[tuple, object] = {}
_base_prog_cache: Dict[tuple, object] = {}


def _compiled_chunk(es: EnsembleSpec, chunk: int,
                    kernel: Optional[str] = None,
                    block_rows: Optional[int] = None):
    from ..parallel import mesh as _meshlib
    from ..conf import GLOBAL_CONF
    mesh = _meshlib.get_mesh()
    kernel = kernel or _kernel_for(es.tree)
    brows = _kernel_block_rows(kernel) if block_rows is None \
        else int(block_rows)
    # donate the margin carry so chunk k+1 reuses chunk k's HBM (the
    # chain's only fresh buffer — bins/labels/mask stay cache-owned
    # and are never donated); XLA:CPU ignores donation, so skip it
    # there to avoid the unused-donation warning. The donate decision is
    # part of the cache key: toggling sml.tpu.donate must not replay a
    # program compiled under the other setting.
    plat = _mesh_platform(mesh)
    donate = (3,) if plat != "cpu" \
        and GLOBAL_CONF.getBool("sml.tpu.donate") else ()
    key = (es, chunk, id(mesh), _hist_subtract(), _hier_ici(mesh), donate,
           kernel, brows)
    if key not in _chunk_cache:
        from ..obs import note_compile
        note_compile(f"tree_chunk_{chunk}")
        program = _make_chunk_program(es, chunk, _data_width(mesh), kernel,
                                      brows, _meshlib.row_axes(mesh),
                                      _hier_ici(mesh))
        P = jax.sharding.PartitionSpec
        Dx = _meshlib.row_spec_entry(mesh)
        wrapped = _meshlib.shard_map_compat(
            program, mesh=mesh,
            in_specs=(P(Dx, None), P(Dx), P(Dx), P(Dx), P(), P()),
            out_specs=(P(Dx), P()))
        _chunk_cache[key] = jax.jit(wrapped, donate_argnums=donate)
    return _chunk_cache[key]


def _boost_rounds(binned_dev, y_dev, mask_dev, es: EnsembleSpec, seed: int,
                  chunk: int, kernel: str, margin, t0: int = 0,
                  on_rounds=None):
    """The staged boosting dispatch loop: rounds [t0, es.n_trees) in
    ceil((n_trees - t0)/chunk) dispatches over a margin carry (donated
    between chunks). Shared by the fresh chunked fit (t0=0, margin =
    full(base)) and the warm-start resume (t0 = saved rounds, margin
    replayed from the saved trees), so an appended round t runs the
    exact program a fresh fit's round t would — the round index keys
    the sampling/feature streams, not the dispatch position.

    `on_rounds(t_done, new_trees)` fires after each non-final dispatch
    with the rounds appended SO FAR (one extra packed D2H per dispatch
    when set; the callers wrap in the fit's base as a third arg) — the
    round-level checkpoint hook of the continuous-training plane
    (sml_tpu/ct): an interrupted or preempted boost resumes from the
    last dispatch boundary instead of restarting the fit."""
    from ..parallel import prewarm as _prewarm
    from ..utils.profiler import PROFILER
    rng = jax.random.key_data(jax.random.PRNGKey(seed))
    packs_parts = []   # no-hook path: device packs, ONE batched D2H at end
    host_packs = []    # hook path: each pack fetched ONCE at its boundary
    t = int(t0)
    with transient_hbm("hist_onehot",
                       _onehot_bytes(es.tree, binned_dev.shape[0], kernel)):
        while t < es.n_trees:
            c = min(chunk, es.n_trees - t)
            _prewarm.record("tree_chunk", {
                "es": _es_meta(es), "chunk": int(c), "kernel": kernel,
                "kernel_rows": _kernel_block_rows(kernel),
                "args": _prewarm.arg_specs(binned_dev, y_dev, mask_dev,
                                           margin)})
            PROFILER.count("tree.fit_dispatch")
            margin, packs = _compiled_chunk(es, c, kernel)(
                binned_dev, y_dev, mask_dev, margin, rng, jnp.int32(t))
            t += c
            if on_rounds is None:
                packs_parts.append(packs)
            else:
                host_packs.append(np.asarray(jax.device_get(packs)))
                if t < es.n_trees:
                    on_rounds(t, _unpack_trees(
                        np.concatenate(host_packs, axis=0)))
        packs = (np.concatenate(host_packs, axis=0) if host_packs
                 else np.concatenate(jax.device_get(packs_parts), axis=0))
    return _unpack_trees(packs)


def _fit_ensemble_chunked(binned_dev, y_dev, mask_dev, es: EnsembleSpec,
                          seed: int, chunk: int,
                          kernel: Optional[str] = None, on_rounds=None):
    """Boosting rounds in ceil(n_trees/chunk) dispatches. The margin never
    visits the host between chunks — it carries as a donated device buffer
    — and per-chunk tree packs are fetched once at the end (one batched
    D2H). Bit-identical to the monolithic program on equal backends."""
    from ..parallel import mesh as _meshlib
    mesh = _meshlib.get_mesh()
    kernel = kernel or _kernel_for(es.tree)
    bkey = (es.loss, id(mesh))
    if bkey not in _base_prog_cache:
        _base_prog_cache[bkey] = data_parallel(
            _base_margin_fn(es.loss, _meshlib.row_axes(mesh)))
    base = float(jax.device_get(_base_prog_cache[bkey](y_dev, mask_dev)))
    margin = jax.device_put(
        np.full((binned_dev.shape[0],), base, np.float32),
        _meshlib.data_sharding(mesh, 1))
    # the chain's one fresh HBM buffer: donated between chunks, so live
    # bytes stay ONE margin's worth for the whole chunked fit
    from ..obs import LEDGER
    margin_bytes = margin.nbytes
    LEDGER.alloc("boost_margin", margin_bytes)
    try:
        hook = None if on_rounds is None \
            else (lambda t, tr: on_rounds(t, tr, base))
        trees = _boost_rounds(binned_dev, y_dev, mask_dev, es, seed, chunk,
                              kernel, margin, t0=0, on_rounds=hook)
    finally:
        LEDGER.free("boost_margin", margin_bytes)
    return trees, base


_margin_replay_cache: Dict[tuple, object] = {}


def _margin_replay_compiled(depth: int, n_trees: int):
    """Sharded device replay of a saved ensemble's boosting margin:
    margin_0 = full(base); margin_{t+1} = margin_t + step * leaf_t(row)
    — the SAME mul-then-add sequence (and scan shape) the fit program's
    carry runs, so a warm start resumes from a margin bit-identical to
    the one an uninterrupted fit would be carrying. Padding rows replay
    too (their binned rows are the same zeros the fit traversed), so
    the carry matches over the whole padded buffer."""
    mesh = meshlib.get_mesh()
    key = (int(depth), int(n_trees), id(mesh))
    if key not in _margin_replay_cache:
        from ..obs import note_compile
        note_compile("tree_margin_replay")

        def program(binned, sf, sb, lv, base, step):
            binned32 = binned.astype(jnp.int32)
            margin0 = jnp.full((binned.shape[0],), base, dtype=jnp.float32)

            def round_fn(margin, t):
                leaf = _traverse(binned32, sf[t], sb[t], lv[t], depth)
                return margin + step * leaf, ()

            margin, _ = jax.lax.scan(
                round_fn, margin0, jnp.arange(n_trees, dtype=jnp.int32))
            return margin

        _margin_replay_cache[key] = data_parallel(
            program, out_replicated=False,
            replicated_argnums=(1, 2, 3, 4, 5))
    return _margin_replay_cache[key]


def resume_ensemble_on_device(binned_dev, y_dev, mask_dev, es: EnsembleSpec,
                              seed: int, init_trees, base: float,
                              rounds_per_dispatch: Optional[int] = None,
                              on_rounds=None):
    """Warm-start incremental boosting: append rounds len(init_trees)..
    es.n_trees-1 to a saved ensemble. The saved rounds' margin replays
    on device (`_margin_replay_compiled`), then the appended rounds run
    through the SAME staged `roundsPerDispatch` dispatch as a fresh
    chunked fit, with round indices offset so sampling streams and
    feature subspaces match the monolithic scan round-for-round: k
    rounds + warm-start (N-k) rounds == N rounds bit-identically on the
    same data/seed (tests/test_ct.py). Returns (new_trees, base) — the
    appended rounds only; the caller prepends the saved trees."""
    from ..conf import GLOBAL_CONF
    from ..parallel import dispatch as _dispatch
    from ..utils.profiler import PROFILER
    if not es.boosting:
        raise ValueError("warm-start resume requires a boosting ensemble "
                         "(forest/DT rounds are independent — refit whole)")
    t0 = len(init_trees)
    if es.n_trees <= t0:
        return [], float(base)
    kernel = _kernel_for(es.tree)
    rounds = (rounds_per_dispatch if rounds_per_dispatch is not None
              else GLOBAL_CONF.getInt("sml.tree.roundsPerDispatch"))
    chunk = rounds if 0 < rounds else (es.n_trees - t0)
    mesh = meshlib.get_mesh()
    sf = np.stack([t.split_feature for t in init_trees])
    sb = np.stack([t.split_bin for t in init_trees])
    lv = np.stack([t.leaf_value for t in init_trees])
    with PROFILER.span(
            "program.tree_resume", rows=int(binned_dev.shape[0]),
            route="host" if _dispatch.is_host_mesh(mesh) else "device",
            trees=es.n_trees - t0):
        margin = _margin_replay_compiled(es.tree.max_depth, t0)(
            binned_dev, sf, sb, lv, np.float32(base),
            np.float32(es.step_size))
        from ..obs import LEDGER
        margin_bytes = margin.nbytes
        LEDGER.alloc("boost_margin", margin_bytes)
        try:
            hook = None if on_rounds is None \
                else (lambda t, tr: on_rounds(t, tr, float(base)))
            trees = _boost_rounds(binned_dev, y_dev, mask_dev, es, seed,
                                  chunk, kernel, margin, t0=t0,
                                  on_rounds=hook)
        finally:
            LEDGER.free("boost_margin", margin_bytes)
    return trees, float(base)


def fit_ensemble_on_device(binned_dev, y_dev, mask_dev, es: EnsembleSpec,
                           seed: int = 0,
                           rounds_per_dispatch: Optional[int] = None,
                           on_rounds=None):
    """Run the whole-ensemble program; returns (trees, base).
    `rounds_per_dispatch` overrides sml.tree.roundsPerDispatch (the
    sparkdl.xgboost surface exposes it per-estimator). `on_rounds` is
    the round-level checkpoint hook (boosting only — it forces the
    chunked dispatch path so the hook has dispatch boundaries to fire
    at; see `_boost_rounds`)."""
    from ..parallel import dispatch as _dispatch
    from ..parallel import mesh as _meshlib
    from ..utils.profiler import PROFILER
    with PROFILER.span(
            "program.tree_ensemble", rows=int(binned_dev.shape[0]),
            route="host" if _dispatch.is_host_mesh(_meshlib.get_mesh())
            else "device", trees=es.n_trees):
        return _fit_ensemble_on_device(binned_dev, y_dev, mask_dev, es, seed,
                                       rounds_per_dispatch, on_rounds)


def _ensemble_compiled(es: EnsembleSpec, kernel: Optional[str] = None,
                       block_rows: Optional[int] = None):
    """The monolithic whole-ensemble program from its per-mesh cache —
    shared by the fit path and the prewarm rebuilder (warming must
    populate the SAME cache entry the fit will hit). `kernel` is the
    RESOLVED build path ("pallas"/"xla"): part of the cache key, and
    replay passes the manifest-recorded value so a prewarm rebuilds the
    executable the fit actually compiled."""
    kernel = kernel or _kernel_for(es.tree)
    brows = _kernel_block_rows(kernel) if block_rows is None \
        else int(block_rows)
    mesh = meshlib.get_mesh()
    key = (es, id(mesh), _hist_subtract(), _hier_ici(mesh), kernel, brows)
    if key not in _ensemble_cache:
        from ..obs import note_compile
        note_compile("tree_ensemble")
        _ensemble_cache[key] = data_parallel(
            _make_ensemble_program(es, _data_width(mesh), kernel, brows,
                                   meshlib.row_axes(mesh), _hier_ici(mesh)),
            replicated_argnums=(3,))
    return _ensemble_cache[key]


def _onehot_bytes(spec: TreeSpec, rows: int, kernel: str) -> int:
    """HBM bytes of the XLA path's fit-long one-hot resident (`B1t`: rows
    × F × bins in hist_dtype) — the dominant transient the ledger charges
    for the duration of a tree-fit dispatch (every tree program shape,
    fit_tree included). The pallas kernel path never materializes it (bin
    tiles one-hot in VMEM per row block), so its charge is zero: the
    `hbm.hist_onehot_bytes` gauge difference IS the kernel's residency
    win."""
    if kernel == "pallas":
        return 0
    return int(rows) * spec.n_features * spec.n_bins \
        * np.dtype(_hist_dtype()).itemsize


def _fit_ensemble_on_device(binned_dev, y_dev, mask_dev, es: EnsembleSpec,
                            seed: int = 0,
                            rounds_per_dispatch: Optional[int] = None,
                            on_rounds=None):
    from ..conf import GLOBAL_CONF
    kernel = _kernel_for(es.tree)
    rounds = (rounds_per_dispatch if rounds_per_dispatch is not None
              else GLOBAL_CONF.getInt("sml.tree.roundsPerDispatch"))
    if es.boosting and (0 < rounds < es.n_trees or on_rounds is not None):
        return _fit_ensemble_chunked(binned_dev, y_dev, mask_dev, es,
                                     seed, rounds if 0 < rounds
                                     else es.n_trees, kernel,
                                     on_rounds=on_rounds)
    compiled = _ensemble_compiled(es, kernel)
    rng = jax.random.key_data(jax.random.PRNGKey(seed))
    from ..parallel import prewarm as _prewarm
    from ..utils.profiler import PROFILER
    _prewarm.record("tree_ensemble", {
        "es": _es_meta(es), "kernel": kernel,
        "kernel_rows": _kernel_block_rows(kernel),
        "args": _prewarm.arg_specs(binned_dev, y_dev, mask_dev)})
    PROFILER.count("tree.fit_dispatch")
    with transient_hbm("hist_onehot",
                       _onehot_bytes(es.tree, binned_dev.shape[0], kernel)):
        packs, base = jax.device_get(compiled(binned_dev, y_dev, mask_dev,
                                              rng))
    # ^ one batched D2H transfer for (packs, base): the tunnel charges a
    # fixed latency per transfer, so never fetch leaves separately
    return _unpack_trees(packs), float(base)


_folds_cache: Dict[tuple, object] = {}
_stack_memo: Dict[tuple, tuple] = {}
_stack_memo_lock = _threading.Lock()  # tuning trials stack concurrently


def build_fold_stacks(binned_list, y_list):
    """(bst, yst, mst) fold stacks padded to a common bucket, memoized by
    source-array identity — `_cached_bins` returns id-stable arrays for
    repeated content, so a grid over maxDepth×numTrees builds the stack
    once, not once per parameter map (the memo holds the sources, keeping
    their ids valid)."""
    from ..parallel import mesh as _meshlib
    mesh = _meshlib.get_mesh()
    n_dev = _data_width(mesh)
    n_pad = max(_meshlib.bucket_rows(b.shape[0], n_dev)
                for b in binned_list)
    key = (tuple(id(b) for b in binned_list),
           tuple(id(y) for y in y_list), n_pad)
    # build under the lock: concurrent tuning trials share the key, and a
    # double-checked miss would have each thread allocate its own multi-GB
    # stack (transient 2x memory spike); the loser waits and hits instead
    with _stack_memo_lock:
        hit = _stack_memo.get(key)
        if hit is not None:
            return hit[2]
        fo = len(binned_list)
        F = binned_list[0].shape[1]
        bst = np.zeros((fo, n_pad, F), dtype=binned_list[0].dtype)
        yst = np.zeros((fo, n_pad), dtype=np.float32)
        mst = np.zeros((fo, n_pad), dtype=np.float32)
        for k, (b, y) in enumerate(zip(binned_list, y_list)):
            bst[k, :b.shape[0]] = b
            yst[k, :len(y)] = y
            mst[k, :len(y)] = 1.0
        # bytes-bounded like the predict bin cache (a count-only bound
        # pinned multi-GB fold stacks for the process lifetime on large CV
        # datasets). The NEWEST stack is always cached — the active grid
        # reuses it per parameter map, so the build-once promise must hold
        # even when one stack alone exceeds the budget; the bound trims
        # OLDER entries, capping steady-state memory at ~one active stack.
        new_bytes = bst.nbytes + yst.nbytes + mst.nbytes
        from ..conf import GLOBAL_CONF as _conf
        max_bytes = _conf.getInt("sml.fit.foldStackBytes")
        total = new_bytes + sum(e[3] for e in _stack_memo.values())
        while _stack_memo and (len(_stack_memo) >= 2 or total > max_bytes):
            total -= _stack_memo.pop(next(iter(_stack_memo)))[3]
        _stack_memo[key] = (list(binned_list), list(y_list),
                            (bst, yst, mst), new_bytes)
    return bst, yst, mst


def _unpack_trees(packs) -> list:
    """(T, 5, n_nodes) device pack → FittedTree list — the ONE place that
    knows the pack layout (shared by the single-fit and fold-batched
    unpack paths)."""
    return [FittedTree(split_feature=p[0].astype(np.int32),
                       split_bin=p[1].astype(np.int32),
                       leaf_value=p[2].astype(np.float32),
                       gain=p[3].astype(np.float32),
                       cover=p[4].astype(np.float32)) for p in packs]


def fit_ensembles_folds(bst, yst, mst, es: EnsembleSpec, seed: int = 0):
    """Fit the SAME EnsembleSpec on stacked fold datasets as ONE vmapped
    device program (SURVEY §2.2 P6; VERDICT r3 #4): CV's k fold-fits per
    parameter map share every shape, so they stack on a leading fold axis
    — one dispatch, one compile, and k× wider matmuls for the MXU —
    instead of k sequential program launches. Rows shard over the data
    axis exactly as in the single-fit program (the fold axis is
    replicated), and the per-fold rng equals the sequential path's (each
    sequential fold fit used the estimator's one seed), so sampling
    weights match the unbatched semantics. Returns [(trees, base)] per
    fold."""
    from ..parallel import dispatch as _dispatch
    from ..parallel import mesh as _meshlib
    from ..utils.profiler import PROFILER
    from ._staging import stage_stacked_cached

    mesh = _meshlib.get_mesh()
    fo, n_pad = bst.shape[0], bst.shape[1]
    b_dev = stage_stacked_cached(bst)
    y_dev = stage_stacked_cached(yst)
    m_dev = stage_stacked_cached(mst)

    kernel = _kernel_for(es.tree)
    compiled = _folds_compiled(es, fo, kernel)
    from ..parallel import prewarm as _prewarm
    _prewarm.record("tree_folds", {
        "es": _es_meta(es), "fo": int(fo), "kernel": kernel,
        "kernel_rows": _kernel_block_rows(kernel),
        "args": _prewarm.arg_specs(b_dev, y_dev, m_dev)})
    rng = jax.random.key_data(jax.random.PRNGKey(seed))
    with PROFILER.span(
            "program.tree_ensemble_folds", rows=int(fo * n_pad),
            route="host" if _dispatch.is_host_mesh(mesh) else "device",
            trees=es.n_trees * fo), \
            transient_hbm("hist_onehot",
                          _onehot_bytes(es.tree, fo * n_pad, kernel)):
        PROFILER.count("tree.fit_dispatch")
        packs, bases = jax.device_get(compiled(b_dev, y_dev, m_dev, rng))
    return [(_unpack_trees(packs[k]), float(bases[k])) for k in range(fo)]


def _folds_compiled(es: EnsembleSpec, fo: int, kernel: Optional[str] = None,
                    block_rows: Optional[int] = None):
    """The fold-batched program from its per-mesh cache (shared with the
    prewarm rebuilder)."""
    mesh = meshlib.get_mesh()
    kernel = kernel or _kernel_for(es.tree)
    brows = _kernel_block_rows(kernel) if block_rows is None \
        else int(block_rows)
    key = (es, fo, id(mesh), _hist_subtract(), _hier_ici(mesh), kernel,
           brows)
    if key not in _folds_cache:
        from ..obs import note_compile
        note_compile(f"tree_ensemble_folds_{fo}")
        program = _make_ensemble_program(es, _data_width(mesh), kernel,
                                         brows, meshlib.row_axes(mesh),
                                         _hier_ici(mesh))

        def batched(binned_f, y_f, mask_f, rng):
            return jax.vmap(program, in_axes=(0, 0, 0, None))(
                binned_f, y_f, mask_f, rng)

        P = jax.sharding.PartitionSpec
        D = meshlib.row_spec_entry(mesh)
        wrapped = meshlib.shard_map_compat(
            batched, mesh=mesh,
            in_specs=(P(None, D, None), P(None, D), P(None, D), P()),
            out_specs=(P(), P()))
        _folds_cache[key] = jax.jit(wrapped)
    return _folds_cache[key]


# ------------------------------------------------- grid-fused trial batching
_trials_cache: Dict[tuple, object] = {}


def _make_trials_program(es: EnsembleSpec, data_width: int = 1,
                         kernel: str = "xla", block_rows: int = 0,
                         axes=None, hier_ici: int = 0):
    """Per-ELEMENT ensemble program with TRACED hyperparameters, vmapped
    over the trial axis by `fit_ensembles_trials`: `es` carries the grid
    MAXIMA as static shapes (max_depth, n_bins, n_trees), and each
    element's `TrialDyn` + sampling flags gate the build down to its own
    hyperparameters. Sampling weights select among poisson / bernoulli /
    ones draws from the SAME keys the per-trial static programs use —
    and through the same layout-invariant global-draw-then-slice
    (`_sliced_draw`), so the selected values match the unfused path
    draw-for-draw on ANY mesh layout (including the cross-chip
    trial-sharded one, whose data axis is only n_dev/trial_dim wide)."""
    spec = es.tree
    hist_dtype = _hist_dtype()
    build = _make_tree_builder(spec, hist_dtype, subtract=_hist_subtract(),
                               kernel=kernel, block_rows=block_rows,
                               axes=axes, hier_ici=hier_ici)
    B, F = spec.n_bins, spec.n_features
    base_of = _base_margin_fn(es.loss, axes)

    def program(binned, y, mask, rng, depth, feature_k, min_inst, mig,
                bootstrap, subsample):
        n = binned.shape[0]
        binned_c = binned
        binned = binned.astype(jnp.int32)
        if kernel == "pallas":
            B1t = None  # kernel one-hots bin tiles in VMEM per block
        else:
            B1t = jax.nn.one_hot(binned, B, dtype=hist_dtype) \
                .reshape(n, F * B).T
        key = jax.random.fold_in(jax.random.wrap_key_data(rng), 0)
        base = base_of(y, mask)
        dyn = TrialDyn(depth=depth, feature_k=feature_k,
                       min_instances=min_inst, min_info_gain=mig)

        def round_fn(carry, t):
            grad = -y
            hess = jnp.ones_like(y)
            kt = jax.random.fold_in(key, t)
            pois = _sliced_draw(n, data_width, lambda s: jax.random.poisson(
                kt, subsample, s).astype(jnp.float32), axes)
            bern = _sliced_draw(n, data_width, lambda s: jax.random.bernoulli(
                kt, subsample, s).astype(jnp.float32), axes)
            ones = jnp.ones((n,), jnp.float32)
            w = jnp.where(bootstrap, pois,
                          jnp.where(subsample < 1.0, bern, ones)) * mask
            feat_rng = jax.random.key_data(jax.random.fold_in(
                jax.random.wrap_key_data(rng), t))
            pack, _ = build(B1t, binned, grad, hess, w, feat_rng, dyn=dyn,
                            binned_c=binned_c)
            return carry, pack

        _, packs = jax.lax.scan(round_fn, 0.0, jnp.arange(es.n_trees))
        return packs, base

    return program


def _trials_compiled(es: EnsembleSpec, n_elems: int, mesh=None,
                     kernel: Optional[str] = None,
                     block_rows: Optional[int] = None):
    """The trial-batched program from its per-mesh cache (shared with the
    prewarm rebuilder). Cache key carries only STATIC maxima — a grid
    whose per-trial values change but whose maxima land on the same
    (depth, bins, trees) signature replays one executable. `mesh` may be
    a 2-D trial mesh (`meshlib.trial_mesh`): the element axis then SHARDS
    over TRIAL_AXIS (cross-chip trial parallelism) instead of
    replicating, and each trial lane's histogram psums span only its own
    n_dev/trial_dim-wide data axis."""
    mesh = mesh or meshlib.get_mesh()
    kernel = kernel or _kernel_for(es.tree)
    brows = _kernel_block_rows(kernel) if block_rows is None \
        else int(block_rows)
    key = (es, n_elems, id(mesh), _hist_subtract(), _hier_ici(mesh),
           kernel, brows)
    if key not in _trials_cache:
        from ..obs import note_compile
        note_compile(f"tree_ensemble_trials_{n_elems}")
        program = _make_trials_program(es, _data_width(mesh), kernel,
                                       brows, meshlib.row_axes(mesh),
                                       _hier_ici(mesh))

        def batched(binned_e, y_e, mask_e, rngs, *dyns):
            return jax.vmap(program,
                            in_axes=(0,) * (4 + len(dyns)))(
                binned_e, y_e, mask_e, rngs, *dyns)

        P = jax.sharding.PartitionSpec
        D = meshlib.DATA_AXIS
        T = meshlib.TRIAL_AXIS
        if T in mesh.shape:
            in_specs = (P(T, D, None), P(T, D), P(T, D), P(T, None)) \
                + (P(T),) * 6
            out_specs = (P(T), P(T))
        else:
            # replicated-element layout: rows shard over the mesh's row
            # axes (the host mesh's ("dcn", "ici") tuple included — the
            # fused-trial path on a host-partitioned mesh)
            Dr = meshlib.row_spec_entry(mesh)
            in_specs = (P(None, Dr, None), P(None, Dr), P(None, Dr)) \
                + (P(),) * 7
            out_specs = (P(), P())
        wrapped = meshlib.shard_map_compat(
            batched, mesh=mesh, in_specs=in_specs, out_specs=out_specs)
        _trials_cache[key] = jax.jit(wrapped)
    return _trials_cache[key]


#: auto trial-sharding threshold: one trial's padded rows below this fit
#: a single chip's compute comfortably (the dispatch cost model's
#: small-rows regime, where the per-level psum's fixed ICI latency
#: rivals the per-chip histogram matmul it synchronizes)
_TRIAL_SHARD_MAX_ROWS = 1 << 18


def _trial_axis_width(E: int, n_pad: int) -> int:
    """Devices the fused-trial ELEMENT axis spans; the rest keep sharding
    rows. `sml.cv.trialAxisDevices`: 0 = auto, 1 = rows-only, k > 1 =
    the largest mesh divisor <= k (honored even when E % k != 0 — the
    element axis pads by repeating element 0, `_pad_elems`). Auto
    mirrors the `dispatch.decide` trade (WorkHint pricing of compute vs
    the fixed per-collective latency term): small per-trial row counts
    gain nothing from splitting rows across every chip but pay
    D-levels × n_trees of allreduce latency per trial, so trials spread
    across chips instead — each lane's data axis shrinks (to 1 at full
    width: allreduce-free trials). Auto never pads: among the divisors
    of E it picks the largest (wall-clock per dispatch scales with
    ceil(E/t)*t, so padded elements are pure waste absent an explicit
    user choice)."""
    from ..conf import GLOBAL_CONF
    mesh = meshlib.get_mesh()
    if tuple(mesh.axis_names) != (meshlib.DATA_AXIS,):
        return 1  # placed submeshes / 2-D dryrun meshes keep row layout
    n_dev = int(mesh.shape[meshlib.DATA_AXIS])
    if n_dev <= 1 or E <= 1:
        return 1
    conf = GLOBAL_CONF.getInt("sml.cv.trialAxisDevices")
    if conf == 1:
        return 1
    if conf <= 0 and n_pad > _TRIAL_SHARD_MAX_ROWS:
        return 1  # big rows: per-chip row blocks already feed the MXU
    cap = n_dev if conf <= 0 else min(conf, n_dev)
    divisors = [d for d in range(2, cap + 1) if n_dev % d == 0]
    if conf > 1:
        return max(divisors, default=1)
    best, best_pad = 1, E
    for d in divisors:
        if d > E:
            continue
        pad = -(-E // d) * d
        if pad < best_pad or (pad == best_pad and d > best):
            best, best_pad = d, pad
    return best


def _pad_elems(a: np.ndarray, e_pad: int) -> np.ndarray:
    """Pad the element axis by REPEATING element 0 (real rows, real
    hyperparameters — never an all-masked element whose base margin would
    divide by a zero row count); the caller slices the duplicates away."""
    if a.shape[0] == e_pad:
        return a
    reps = np.repeat(a[:1], e_pad - a.shape[0], axis=0)
    return np.concatenate([a, reps], axis=0)


def fit_ensembles_trials(bst, yst, mst, es: EnsembleSpec, rngs,
                         depth, feature_k, min_inst, min_gain,
                         bootstrap, subsample):
    """Fit E = bst.shape[0] (grid point × fold) TRIALS as ONE vmapped
    device program — the grid-fused extension of `fit_ensembles_folds`:
    per-trial hyperparameters ride as traced (E,)-vectors (padded to the
    grid maxima carried statically by `es`), so a G-point grid over k
    folds is ceil(G*k / sml.cv.maxFusedTrials) dispatches instead of G*k
    (or G).

    Placement (`sml.cv.trialAxisDevices`, see `_trial_axis_width`): on a
    multi-device 1-D data mesh the element axis can SHARD over a second
    ("trial") mesh axis — E trials run on disjoint chip groups, each
    lane's rows sharded over its own (often width-1 = allreduce-free)
    data axis — instead of vmapping every trial onto one program spanning
    all chips. Sampling draws are layout-invariant (`_sliced_draw`), so
    both placements produce the same models up to float reduction order.
    Width 1 keeps the classic layout: rows over the data axis, element
    axis replicated, exactly like the fold axis in the fold-only program.

    Returns the raw (E, n_trees, 5, n_nodes) pack stack + (E,) bases —
    the caller slices each element down to its own numTrees."""
    from ..parallel import dispatch as _dispatch
    from ..parallel import prewarm as _prewarm
    from ..utils.profiler import PROFILER
    from ._staging import stage_stacked_cached, stage_trial_stacked_cached

    mesh = meshlib.get_mesh()
    E, n_pad = bst.shape[0], bst.shape[1]
    kernel = _kernel_for(es.tree)
    tdim = _trial_axis_width(E, n_pad)
    dyns = [np.asarray(depth, np.int32), np.asarray(feature_k, np.int32),
            np.asarray(min_inst, np.float32),
            np.asarray(min_gain, np.float32),
            np.asarray(bootstrap, bool), np.asarray(subsample, np.float32)]
    rngs = np.asarray(rngs)
    if tdim > 1:
        e_pad = -(-E // tdim) * tdim
        tmesh = meshlib.trial_mesh(tdim, mesh)
        bst, yst, mst = (_pad_elems(a, e_pad) for a in (bst, yst, mst))
        rngs = _pad_elems(rngs, e_pad)
        dyns = [_pad_elems(v, e_pad) for v in dyns]
        b_dev = stage_trial_stacked_cached(bst, tmesh)
        y_dev = stage_trial_stacked_cached(yst, tmesh)
        m_dev = stage_trial_stacked_cached(mst, tmesh)
        compiled = _trials_compiled(es, e_pad, tmesh, kernel)
    else:
        e_pad = E
        b_dev = stage_stacked_cached(bst)
        y_dev = stage_stacked_cached(yst)
        m_dev = stage_stacked_cached(mst)
        compiled = _trials_compiled(es, E, kernel=kernel)
    _prewarm.record("tree_trials", {
        "es": _es_meta(es), "n_elems": int(e_pad), "trial_dim": int(tdim),
        "kernel": kernel, "kernel_rows": _kernel_block_rows(kernel),
        "args": _prewarm.arg_specs(b_dev, y_dev, m_dev)})
    with PROFILER.span(
            "program.tree_ensemble_trials", rows=int(e_pad * n_pad),
            route="host" if _dispatch.is_host_mesh(mesh) else "device",
            trees=es.n_trees * e_pad), \
            transient_hbm("hist_onehot",
                          _onehot_bytes(es.tree, e_pad * n_pad, kernel)):
        PROFILER.count("tree.fit_dispatch")
        packs, bases = jax.device_get(compiled(
            b_dev, y_dev, m_dev, rngs, *dyns))
    return packs[:E], bases[:E]


# ------------------------------------------------------- prewarm rebuilders
def _es_meta(es: EnsembleSpec) -> dict:
    """JSON-serializable EnsembleSpec for the prewarm manifest."""
    return {"tree": list(es.tree), "n_trees": int(es.n_trees),
            "loss": str(es.loss), "boosting": bool(es.boosting),
            "bootstrap": bool(es.bootstrap),
            "subsample": float(es.subsample),
            "step_size": float(es.step_size)}


def _es_from_meta(meta: dict) -> EnsembleSpec:
    meta = meta.get("es", meta)
    t = meta["tree"]
    return EnsembleSpec(
        tree=TreeSpec(int(t[0]), int(t[1]), int(t[2]), int(t[3]),
                      int(t[4]), float(t[5]), float(t[6]), float(t[7])),
        n_trees=int(meta["n_trees"]), loss=str(meta["loss"]),
        boosting=bool(meta["boosting"]), bootstrap=bool(meta["bootstrap"]),
        subsample=float(meta["subsample"]),
        step_size=float(meta["step_size"]))


def _replay_zeros(meta, n: int):
    """Zero-filled device operands in the recorded shapes/dtypes, placed
    exactly like the fit paths place them (data-sharded rows; stacked
    layouts keep the leading axis replicated) so the replayed dispatch
    hits the very executable the recorded call compiled."""
    mesh = meshlib.get_mesh()
    stacked = ("n_elems" in meta) or ("fo" in meta)
    out = []
    for shape, dtype in meta["args"][:n]:
        a = np.zeros(tuple(shape), dtype=np.dtype(dtype))
        if stacked and a.ndim >= 2:  # (elems/folds, rows, ...) layout
            spec = jax.sharding.PartitionSpec(
                None, meshlib.row_spec_entry(mesh), *([None] * (a.ndim - 2)))
            out.append(jax.device_put(
                a, jax.sharding.NamedSharding(mesh, spec)))
        else:
            out.append(jax.device_put(a, meshlib.data_sharding(mesh, a.ndim)))
    return out


def _replay_kernel(meta: dict) -> tuple:
    """(kernel, block_rows) as recorded in the manifest: replay must
    rebuild the SAME executable the recorded fit compiled — flag AND
    block scheme — regardless of the replaying process's live conf.
    Pre-kernel manifests carry neither — those resolve live (None)."""
    k = meta.get("kernel")
    k = str(k) if k in ("pallas", "xla") else None
    r = meta.get("kernel_rows")
    r = int(r) if k is not None and isinstance(r, (int, float)) else None
    return k, r


def _replay_tree_ensemble(meta: dict) -> None:
    es = _es_from_meta(meta)
    b, y, m = _replay_zeros(meta, 3)
    rng = jax.random.key_data(jax.random.PRNGKey(0))
    jax.device_get(_ensemble_compiled(es, *_replay_kernel(meta))(
        b, y, m, rng))


def _replay_tree_chunk(meta: dict) -> None:
    es = _es_from_meta(meta)
    b, y, m, margin = _replay_zeros(meta, 4)
    rng = jax.random.key_data(jax.random.PRNGKey(0))
    jax.device_get(_compiled_chunk(es, int(meta["chunk"]),
                                   *_replay_kernel(meta))(
        b, y, m, margin, rng, jnp.int32(0)))


def _replay_tree_folds(meta: dict) -> None:
    es = _es_from_meta(meta)
    b, y, m = _replay_zeros(meta, 3)
    rng = jax.random.key_data(jax.random.PRNGKey(0))
    jax.device_get(_folds_compiled(es, int(meta["fo"]),
                                   *_replay_kernel(meta))(b, y, m, rng))


def _replay_tree_trials(meta: dict) -> None:
    es = _es_from_meta(meta)
    E = int(meta["n_elems"])
    tdim = int(meta.get("trial_dim", 1))
    if tdim > 1:
        # trial-sharded variant: rebuild the 2-D mesh over the live data
        # mesh's devices and place operands exactly like the fit path
        tmesh = meshlib.trial_mesh(tdim)
        P = jax.sharding.PartitionSpec
        arrs = []
        for shape, dtype in meta["args"][:3]:
            a = np.zeros(tuple(shape), dtype=np.dtype(dtype))
            spec = P(meshlib.TRIAL_AXIS, meshlib.DATA_AXIS,
                     *([None] * (a.ndim - 2)))
            arrs.append(jax.device_put(
                a, jax.sharding.NamedSharding(tmesh, spec)))
        b, y, m = arrs
        compiled = _trials_compiled(es, E, tmesh, *_replay_kernel(meta))
    else:
        b, y, m = _replay_zeros(meta, 3)
        kk, kr = _replay_kernel(meta)
        compiled = _trials_compiled(es, E, kernel=kk, block_rows=kr)
    rngs = np.zeros((E, 2), np.uint32)
    jax.device_get(compiled(
        b, y, m, rngs,
        np.full(E, es.tree.max_depth, np.int32),
        np.full(E, es.tree.n_features, np.int32),
        np.ones(E, np.float32), np.zeros(E, np.float32),
        np.zeros(E, bool), np.ones(E, np.float32)))


def _register_prewarm_rebuilders() -> None:
    from ..parallel import prewarm as _prewarm
    _prewarm.register_rebuilder("tree_ensemble", _replay_tree_ensemble)
    _prewarm.register_rebuilder("tree_chunk", _replay_tree_chunk)
    _prewarm.register_rebuilder("tree_folds", _replay_tree_folds)
    _prewarm.register_rebuilder("tree_trials", _replay_tree_trials)


_register_prewarm_rebuilders()


def _build_tree_program(spec: TreeSpec, hist_dtype=jnp.float32,
                        kernel: str = "xla", block_rows: int = 0,
                        axes=None, hier_ici: int = 0):
    """Single-tree program (kept for the dryrun/compile-check path)."""
    B, F = spec.n_bins, spec.n_features
    build = _make_tree_builder(spec, hist_dtype, subtract=_hist_subtract(),
                               kernel=kernel, block_rows=block_rows,
                               axes=axes, hier_ici=hier_ici)

    def program(binned, grad, hess, weight, feat_rng):
        n = binned.shape[0]
        binned_c = binned
        binned = binned.astype(jnp.int32)  # compact bins widen on-device
        if kernel == "pallas":
            B1t = None
        else:
            B1t = jax.nn.one_hot(binned, B,
                                 dtype=hist_dtype).reshape(n, F * B).T
        pack, _ = build(B1t, binned, grad, hess, weight, feat_rng,
                        binned_c=binned_c)
        return (pack[0].astype(jnp.int32), pack[1].astype(jnp.int32),
                pack[2], pack[3], pack[4])

    return program


_tree_cache: Dict[TreeSpec, object] = {}


def fit_tree(binned_dev, grad_dev, hess_dev, weight_dev, spec: TreeSpec,
             rng: int = 0, feat_key: Optional[np.ndarray] = None) -> FittedTree:
    """Build one tree on the mesh from pre-staged device arrays."""
    from ..parallel import mesh as _meshlib
    kernel = _kernel_for(spec)
    brows = _kernel_block_rows(kernel)
    mesh = _meshlib.get_mesh()
    key = (spec, id(mesh), _hist_subtract(), _hier_ici(mesh), kernel, brows)
    if key not in _tree_cache:
        from ..obs import note_compile
        note_compile("tree_single")
        _tree_cache[key] = data_parallel(
            _build_tree_program(spec, _hist_dtype(), kernel, brows,
                                _meshlib.row_axes(mesh), _hier_ici(mesh)),
            replicated_argnums=(4,))
    compiled = _tree_cache[key]
    if feat_key is None:
        feat_key = jax.random.key_data(jax.random.PRNGKey(rng))
    from ..utils.profiler import PROFILER
    PROFILER.count("tree.fit_dispatch")
    with transient_hbm("hist_onehot",
                       _onehot_bytes(spec, binned_dev.shape[0], kernel)):
        out = compiled(binned_dev, grad_dev, hess_dev, weight_dev, feat_key)
        sf, sb, lv, g, cov = jax.device_get(out)  # one batched transfer
    sf, lv = sf.copy(), lv.copy()
    # nodes never reached in training (zero cover) inherit the parent value so
    # unseen routes at predict time fall back gracefully
    for i in range(1, len(lv)):
        if cov[i] == 0:
            lv[i] = lv[(i - 1) // 2]
            sf[i] = -1
    return FittedTree(sf, sb, lv, g, cov)


# ---------------------------------------------------------------------------
@partial(jax.jit, static_argnames=("depth",))
def _predict_binned(binned, split_feature, split_bin, leaf_value, depth: int):
    n = binned.shape[0]
    binned = binned.astype(jnp.int32)  # compact bins widen on-device
    node = jnp.zeros((n,), dtype=jnp.int32)
    for _ in range(depth):
        f = split_feature[node]
        b = split_bin[node]
        is_internal = f >= 0
        xbin = jnp.take_along_axis(binned, jnp.maximum(f, 0)[:, None], axis=1)[:, 0]
        child = 2 * node + 1 + (xbin > b).astype(jnp.int32)
        node = jnp.where(is_internal, child, node)
    return leaf_value[node]


def predict_tree(binned: np.ndarray, tree: FittedTree, depth: int) -> np.ndarray:
    out = _predict_binned(jnp.asarray(binned), jnp.asarray(tree.split_feature),
                          jnp.asarray(tree.split_bin),
                          jnp.asarray(tree.leaf_value), depth)
    return np.asarray(out)


def predict_forest(binned: np.ndarray, trees, depth: int,
                   weights: Optional[np.ndarray] = None) -> np.ndarray:
    """Sum/average of per-tree predictions, evaluated as stacked vmapped
    traversals (one fused XLA program rather than T python loops)."""
    sf = jnp.stack([jnp.asarray(t.split_feature) for t in trees])
    sb = jnp.stack([jnp.asarray(t.split_bin) for t in trees])
    lv = jnp.stack([jnp.asarray(t.leaf_value) for t in trees])
    b = jnp.asarray(binned)
    per_tree = jax.vmap(lambda f, s, v: _predict_binned(b, f, s, v, depth))(sf, sb, lv)
    if weights is None:
        return np.asarray(per_tree.mean(axis=0))
    w = jnp.asarray(weights, dtype=jnp.float32)
    return np.asarray(jnp.tensordot(w, per_tree, axes=1))


def feature_importances(trees, n_features: int) -> np.ndarray:
    """Gain-weighted importance, normalized to sum 1 (Spark semantics:
    per-tree normalization, then averaged over trees)."""
    total = np.zeros(n_features, dtype=np.float64)
    for t in trees:
        imp = np.zeros(n_features, dtype=np.float64)
        for node, f in enumerate(t.split_feature):
            if f >= 0:
                imp[int(f)] += max(float(t.gain[node]), 0.0)
        s = imp.sum()
        if s > 0:
            total += imp / s
    s = total.sum()
    return total / s if s > 0 else total


# ---------------------------------------------------------------------------
class StagedData(NamedTuple):
    binned: np.ndarray          # host copy (training-time re-prediction)
    binned_dev: jax.Array
    mask_dev: jax.Array
    y: np.ndarray
    n_true: int
    binning: Binning
    n_padded: int


def stage_tree_data(X: np.ndarray, y: np.ndarray, max_bins: int,
                    categorical: Optional[Dict[int, int]] = None,
                    prebinned=None) -> StagedData:
    """`prebinned=(binned, binning)` skips re-binning when the caller
    already discretized (it bins BEFORE routing so the dispatcher can probe
    the staging cache with the actual device operand). The compact
    quantized matrix stages through the shared bin cache (`stage_sharded`
    routes 2-D integer matrices there), so every tree, boosting round, CV
    fold, and eval pushdown on the same rows reuses ONE device copy."""
    if prebinned is not None:
        binned, binning = prebinned
    else:
        binned, binning = make_bins(X, y, max_bins, categorical)
    binned_dev, mask_dev, n_true = stage_sharded(binned)
    return StagedData(binned=binned, binned_dev=binned_dev, mask_dev=mask_dev,
                      y=y, n_true=n_true, binning=binning,
                      n_padded=binned_dev.shape[0])


def stage_aligned(arr: np.ndarray, n_padded: int):
    """Shard a per-row array aligned with previously staged binned data."""
    from ._staging import stage_rows_cached
    padded = np.zeros((n_padded,) + arr.shape[1:], dtype=np.float32)
    padded[:arr.shape[0]] = arr
    return stage_rows_cached(padded, pad_to_multiple=False)
