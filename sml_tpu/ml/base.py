"""Transformer / Estimator / Model / Pipeline and on-disk persistence.

Contract (stated in the reference at `SML/ML 01 - Data Cleansing.py:242-247`):
a Transformer's `.transform(df)` appends columns; an Estimator's `.fit(df)`
learns and returns a Model, which is itself a Transformer. `Pipeline` chains
stages (`SML/ML 03 - Linear Regression II.py:100-129`), and pipeline models
persist via `.write().overwrite().save(path)` / `PipelineModel.load(path)`.

Persistence format (ours, not Spark's): a directory with `metadata.json`
({class, uid, params, extra}) plus optional `data.npz` for array state;
pipelines hold `stages/NN_uid/` subdirectories. Classes self-describe their
array state through `_save_state()/_load_state()`.
"""

from __future__ import annotations

import importlib
import json
import os
import shutil
from typing import Any, Dict, List, Optional

import numpy as np

from .param import Params


class MLWriter:
    def __init__(self, instance: "Saveable"):
        self._instance = instance
        self._overwrite = False

    def overwrite(self) -> "MLWriter":
        self._overwrite = True
        return self

    def save(self, path: str) -> None:
        if os.path.exists(path):
            if not self._overwrite:
                raise IOError(f"Path {path} already exists; use .overwrite()")
            shutil.rmtree(path)
        self._instance._save_to(path)


class Saveable:
    """Mixin providing write()/save()/load() over the directory format."""

    def write(self) -> MLWriter:
        return MLWriter(self)

    def save(self, path: str) -> None:
        self.write().save(path)

    # -- subclass hooks ---------------------------------------------------
    def _extra_metadata(self) -> Dict[str, Any]:
        return {}

    def _save_state(self, path: str) -> None:
        """Save non-param array/object state; default: nothing."""

    def _load_state(self, path: str, meta: Dict[str, Any]) -> None:
        """Restore non-param state; default: nothing."""

    # -- machinery --------------------------------------------------------
    def _save_to(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)
        meta = {
            "class": f"{type(self).__module__}.{type(self).__name__}",
            "uid": getattr(self, "uid", None),
            "params": self._params_to_dict() if isinstance(self, Params) else {},
            "extra": self._extra_metadata(),
        }
        with open(os.path.join(path, "metadata.json"), "w") as f:
            json.dump(meta, f, indent=2, default=str)
        self._save_state(path)

    @classmethod
    def load(cls, path: str) -> Any:
        with open(os.path.join(path, "metadata.json")) as f:
            meta = json.load(f)
        module, _, name = meta["class"].rpartition(".")
        klass = getattr(importlib.import_module(module), name)
        obj = klass.__new__(klass)
        Params.__init__(obj)
        if meta.get("uid"):
            obj.uid = meta["uid"]
        obj._init_params()
        obj._params_from_dict(meta.get("params", {}))
        obj._load_state(path, meta.get("extra", {}))
        return obj

    def _init_params(self) -> None:
        """Subclasses declare their Params here (called by both __init__ and
        load); default: nothing."""

    @staticmethod
    def read():
        raise NotImplementedError("use .load(path)")


def save_arrays(path: str, **arrays) -> None:
    np.savez(os.path.join(path, "data.npz"), **arrays)


def load_arrays(path: str) -> Dict[str, np.ndarray]:
    fp = os.path.join(path, "data.npz")
    if not os.path.exists(fp):
        return {}
    with np.load(fp, allow_pickle=True) as z:
        return {k: z[k] for k in z.files}


class Transformer(Params, Saveable):
    def __init__(self):
        Params.__init__(self)
        self._init_params()

    def transform(self, df, params: Optional[dict] = None):
        if params:
            return self.copy(params).transform(df)
        return self._transform(df)

    def _transform(self, df):
        raise NotImplementedError


class Estimator(Params, Saveable):
    def __init__(self):
        Params.__init__(self)
        self._init_params()

    def fit(self, df, params: Optional[dict] = None):
        if params:
            return self.copy(params).fit(df)
        # flight-recorder run autologging: with the recorder on and a
        # tracking run active, the OUTERMOST fit logs engine.* metric
        # deltas to the run (obs.autolog_fit is a cheap no-op otherwise)
        from ..obs import autolog_fit
        with autolog_fit(self):
            return self._fit(df)

    def _fit(self, df):
        raise NotImplementedError


class Model(Transformer):
    """A fitted Transformer (MLlib: Model[M] extends Transformer)."""

    def _inherit_params(self, est: Params) -> "Model":
        """Copy the estimator's set params onto this model (shared names)."""
        for p, v in est._paramMap.items():
            if self.hasParam(p.name):
                self._paramMap[self.getParam(p.name)] = v
        return self


class Evaluator(Params, Saveable):
    def __init__(self):
        Params.__init__(self)
        self._init_params()

    def evaluate(self, df, params: Optional[dict] = None) -> float:
        if params:
            return self.copy(params).evaluate(df)
        return self._evaluate(df)

    def _evaluate(self, df) -> float:
        raise NotImplementedError

    def isLargerBetter(self) -> bool:
        return True


def _attach_fused_features(cur, fitted_transforms, est, raw_pdf):
    """Fused fit path: when the fitted prep chain compiles to a
    CompiledFeaturizer (Imputer/StringIndexer/OHE/VectorAssembler shapes)
    and the final estimator reads `featuresCol` + raw-frame `labelCol`,
    assemble the (n, d) block in ONE columnar pass over the raw pandas and
    attach it to the frame — the estimator's extract_xy then never
    materializes the lazy transform chain (~1s/fit of pandas work at 1M
    rows). Falls through unchanged whenever the pattern doesn't apply."""
    try:
        from .feature import VectorAssembler
        from .featurizer import CompiledFeaturizer
        if not fitted_transforms or not hasattr(cur, "toPandas"):
            return cur
        assembler = fitted_transforms[-1]
        if not isinstance(assembler, VectorAssembler):
            return cur
        if not (est.hasParam("featuresCol") and est.hasParam("labelCol")):
            return cur
        if est.getOrDefault("featuresCol") != assembler.getOrDefault("outputCol"):
            return cur
        feat = CompiledFeaturizer.from_stages(fitted_transforms[:-1], assembler)
        if feat is None or raw_pdf is None:
            return cur
        if est.getOrDefault("labelCol") not in raw_pdf.columns:
            return cur
        # Shared guard with featurizer.try_fast_fit: if any prep stage
        # overwrites labelCol/weightCol, raw_pdf holds PRE-transform labels
        # and the fused path would silently train a different model.
        from .featurizer import prep_overwrites_label
        if prep_overwrites_label(fitted_transforms[:-1], est):
            return cur
        X, keep = feat.transform_with_mask(raw_pdf)
        cur._featurized = {assembler.getOrDefault("outputCol"):
                           (X, keep, raw_pdf)}
        return cur
    except Exception:
        return cur  # any surprise: the generic per-stage path is correct


class Pipeline(Estimator):
    """`Pipeline(stages=[...])` — sequentially fit estimators / apply
    transformers (`ML 03:100-113`)."""

    def _init_params(self):
        self._declareParam("stages", default=[], doc="pipeline stages")

    def __init__(self, stages: Optional[List] = None):
        super().__init__()
        if stages is not None:
            self._set(stages=stages)

    def getStages(self) -> List:
        return self.getOrDefault("stages")

    def setStages(self, stages: List) -> "Pipeline":
        return self._set(stages=stages)

    def _fit(self, df) -> "PipelineModel":
        stages = self.getStages()
        fitted: List[Transformer] = []
        cur = df
        # Fit-time fast path: collapse to ONE partition so each stage's
        # per-partition fn runs once over the whole frame and inter-stage
        # concats are no-ops. Row-local transforms are partition-count
        # invariant and global fits (Imputer median, StringIndexer
        # frequencies) already aggregate across partitions, so results are
        # unchanged — only the constant factor is (r2 spent ~0.7s/fit in
        # repeated 8-way concats, VERDICT weak #1). The returned model is
        # partitioning-agnostic either way.
        raw_pdf = None
        if hasattr(cur, "toPandas") and hasattr(cur, "_ml_attrs"):
            from ..frame.dataframe import DataFrame as _DF
            # build the 1-partition frame from the frame's memoized concat:
            # repeated fits on a cached frame re-use one materialization
            raw_pdf = cur.toPandas()
            session = getattr(cur, "_session", None)

            def make_frame(pdf):
                f = _DF.from_partitions([pdf], session=session)
                f._ml_attrs = dict(df._ml_attrs)
                return f

            # whole-chain fused fit (featurizer.try_fast_fit): the standard
            # prep chain fits from the raw pandas and the estimator reads a
            # one-pass assembled block — nothing else materializes. Only
            # the CHAIN COMPILATION is guarded (any surprise falls back to
            # the always-correct generic path); the estimator fit runs
            # unguarded so its real errors propagate.
            from .featurizer import try_fast_fit
            try:
                fast = try_fast_fit(stages, raw_pdf, make_frame)
            except Exception:
                fast = None
            if fast is not None:
                fitted_prep, shim = fast
                return PipelineModel(fitted_prep + [stages[-1].fit(shim)])
            one = make_frame(raw_pdf)
            cur = one
        for i, stage in enumerate(stages):
            if isinstance(stage, Estimator):
                if i == len(stages) - 1:
                    cur = _attach_fused_features(cur, fitted, stage, raw_pdf)
                model = stage.fit(cur)
                fitted.append(model)
                if i < len(stages) - 1:
                    cur = model.transform(cur)
            elif isinstance(stage, Transformer):
                fitted.append(stage)
                if i < len(stages) - 1:
                    cur = stage.transform(cur)
            else:
                raise TypeError(f"stage {stage!r} is neither Estimator nor Transformer")
        return PipelineModel(fitted)

    def copy(self, extra=None) -> "Pipeline":
        that = super().copy(extra)
        # stages hold estimators with their own params: apply any extra params
        # addressed to them (tuning passes {est.param: v} through the pipeline)
        if extra:
            new_stages = []
            for s in that.getStages():
                applicable = {p: v for p, v in extra.items()
                              if getattr(p, "parent", None) == s.uid}
                new_stages.append(s.copy(applicable) if applicable else s)
            that._paramMap[that.getParam("stages")] = new_stages
        return that

    # -- persistence ------------------------------------------------------
    def _extra_metadata(self):
        return {"n_stages": len(self.getStages())}

    def _save_state(self, path: str) -> None:
        for i, s in enumerate(self.getStages()):
            s._save_to(os.path.join(path, "stages", f"{i:02d}_{s.uid}"))

    def _load_state(self, path: str, meta) -> None:
        stage_dir = os.path.join(path, "stages")
        stages = []
        for d in sorted(os.listdir(stage_dir)) if os.path.exists(stage_dir) else []:
            stages.append(Saveable.load(os.path.join(stage_dir, d)))
        self._paramMap[self.getParam("stages")] = stages


class RegStatsHook:
    """Base evaluator-pushdown hook for lazy model-transform frames.

    `RegressionEvaluator` consults `reg_stats` on an UNMATERIALIZED
    transform frame: a subclass computes the five regression sufficient
    statistics straight from the raw parent frame, without assembling the
    transform's output. This class owns the shared scaffolding — the
    (prediction_col, label_col) stats cache, the predictionCol/parent/
    label guards, the strict label conversion (a non-numeric label column
    must raise on the materialize path and DECLINE here, never silently
    coerce to NaN), and the decline-on-any-surprise contract — so the
    producers cannot drift apart. Subclasses implement `_compute(raw,
    lab, label_col)` and may override `_label_ok`. Returning None always
    means: the evaluator takes the ordinary materialize path, so results
    never depend on the hook firing."""

    # names only — resolved via getattr(np, name) host-side and
    # getattr(jnp, name) in the device program, so the two sides cannot
    # drift (numpy and jax.numpy mirror these fn names)
    LINKS = frozenset({"identity", "exp", "log"})

    def __init__(self, tail, parent):
        self._tail = tail
        self._parent = parent
        self._stats_cache: dict = {}
        self._link = "identity"

    def with_link(self, link: str, col_name: str):
        """A clone of this hook whose predictions pass through the
        elementwise `link` before the metric reductions — the ML 11 shape
        (fit on log(label), evaluate exp(prediction) on the raw scale).
        Returns None (caller keeps NO hook) unless `col_name` is this
        hook's own prediction column, the link is known, and no link is
        already applied."""
        if link not in self.LINKS or self._link != "identity":
            return None
        try:
            if self._tail.getOrDefault("predictionCol") != col_name:
                return None
        except Exception:
            return None
        import copy
        clone = copy.copy(self)
        clone._link = link
        clone._stats_cache = {}
        return clone

    def _label_ok(self, label_col: str) -> bool:
        return True

    def _compute(self, raw, lab, label_col: str):
        raise NotImplementedError

    def reg_stats(self, prediction_col: str, label_col: str):
        cached = self._stats_cache.get((prediction_col, label_col))
        if cached is not None:
            return cached  # rmse-then-mae-then-r2 costs one predict, not 3
        try:
            if self._tail.getOrDefault("predictionCol") != prediction_col:
                return None
            if not hasattr(self._parent, "toPandas"):
                return None
            raw = self._parent.toPandas()
            if label_col not in raw.columns or len(raw) == 0:
                return None
            if not self._label_ok(label_col):
                return None
            lab = np.asarray(raw[label_col], dtype=np.float64)
            stats = self._compute(raw, lab, label_col)
            if stats is not None:
                self._stats_cache[(prediction_col, label_col)] = stats
            return stats
        except Exception:
            return None  # any surprise: the materialize path is correct


class _ScorerEvalHook(RegStatsHook):
    """Pushdown for lazy fused pipeline transforms: one columnar
    featurize pass + the scorer's routed predict (or, for tree tails,
    the fused traverse+metric device program), with no output-frame
    assembly (vector columns, interim stage columns, prediction
    series)."""

    def __init__(self, feat, scorer, tail, parent, prep_stages):
        super().__init__(tail, parent)
        self._feat = feat
        self._scorer = scorer
        self._prep_stages = prep_stages

    def _label_ok(self, label_col: str) -> bool:
        # a prep stage that writes labelCol means raw labels are
        # pre-transform values: the materialize path is authoritative
        from .featurizer import produced_columns
        return label_col not in produced_columns(self._prep_stages)

    def _compute(self, raw, lab, label_col: str):
        X, keep = self._feat.transform_with_mask(raw)
        if keep is not None:
            lab = lab[keep]
        spec = getattr(self._tail, "_spec", None)
        if spec is not None and hasattr(spec, "trees"):
            # tree tail: the whole traverse+metric fuses into one device
            # program (five-scalar D2H) when the router agrees; the link
            # (if any) is applied to predictions INSIDE the program
            from ._tree_models import fused_reg_stats_from_matrix
            stats = fused_reg_stats_from_matrix(spec, X, lab,
                                                link=self._link)
            if stats is not None:
                return stats
        pred = np.asarray(self._scorer.score_block(X), dtype=np.float64)
        if pred.shape[0] != lab.shape[0]:
            return None
        if self._link != "identity":
            pred = getattr(np, self._link)(pred)
        from .evaluation import host_reg_stats
        return host_reg_stats(pred, lab)


class PipelineModel(Model):
    def _init_params(self):
        pass

    def __init__(self, stages: Optional[List[Transformer]] = None):
        super().__init__()
        self.stages: List[Transformer] = stages or []

    def _transform(self, df):
        fast = self._fast_transform(df)
        if fast is not None:
            return fast
        cur = df
        for s in self.stages:
            cur = s.transform(cur)
        return cur

    def _fast_plan(self):
        """Compile (featurizer, scorer, assembler, tail) for the fused
        transform, memoized per stage list. `scorer` is None for a pure
        feature pipeline (no final model); a plan of None means the stage
        shapes don't fit and the generic per-stage path must run."""
        token = tuple((id(s), type(s).__name__,
                       getattr(s, "_param_version", 0))
                      for s in self.stages)
        cached = getattr(self, "_fast_plan_cache", None)
        if cached is not None and cached[0] == token:
            return cached[1]
        plan = self._build_fast_plan()
        self._fast_plan_cache = (token, plan)
        return plan

    def _build_fast_plan(self):
        from .feature import VectorAssembler
        from .featurizer import CompiledFeaturizer
        from .regression import LinearRegressionModel
        from ._tree_models import _TreeRegressionModel
        stages = self.stages
        if not stages:
            return None
        tail = stages[-1]
        prep = stages
        scorer = None
        if isinstance(tail, (LinearRegressionModel, _TreeRegressionModel)):
            # regression tails append EXACTLY predictionCol — classifiers
            # (probability/rawPrediction columns) keep the generic path
            prep = stages[:-1]
        else:
            tail = None
        if not prep or not isinstance(prep[-1], VectorAssembler):
            return None
        assembler = prep[-1]
        feat = CompiledFeaturizer.from_stages(prep[:-1], assembler)
        if feat is None:
            return None
        if tail is not None:
            if tail.getOrDefault("featuresCol") != \
                    assembler.getOrDefault("outputCol"):
                return None
            from .inference import DeviceScorer
            try:
                scorer = DeviceScorer(tail)
            except TypeError:
                return None
        return feat, scorer, assembler, tail

    def _fast_transform(self, df):
        """Whole-pipeline fused TRANSFORM (the serving twin of the fused
        fit): for the standard course chain the entire stage sequence —
        feature prep, assembly, model predict — runs as ONE columnar pass
        over the parent's pandas plus one routed predict program, instead
        of materializing an intermediate frame per stage (r3 VERDICT #1:
        41s of the 40s benchmark suite was per-stage host materialization).
        Interim stage-output columns and their ml attrs are reproduced
        exactly; falls back to the generic path whenever the shape doesn't
        fit. Mirrors Spark's lazy whole-stage codegen philosophy
        (`SML/ML 00b - Spark Review.py:45`) on the host side."""
        import os as _os
        debug = _os.environ.get("SML_FUSED_DEBUG") == "1"
        try:
            if not hasattr(df, "toPandas") or getattr(df, "isStreaming", False):
                return None
            plan = self._fast_plan()
            if plan is None:
                return None
            feat, scorer, assembler, tail = plan
        except Exception:
            if debug:
                raise
            return None
        from ..frame.dataframe import DataFrame as _DF, _split_rows
        from .linalg import vector_series
        out_col = assembler.getOrDefault("outputCol")
        parent = df

        def compute():
            import pandas as pd
            raw = parent.toPandas()
            n_parts = len(parent._materialize())
            X, keep, cols = feat.transform_with_columns(raw)
            if cols is None:
                return None  # un-recoverable interim: caller falls back
            base = raw if keep is None else \
                raw[keep].reset_index(drop=True)
            out = base.copy(deep=False)
            for name, val in cols.items():
                if isinstance(val, tuple) and val[0] == "block":
                    out[name] = vector_series(val[1], index=out.index,
                                              sparse=True, na=val[2])
                else:
                    out[name] = pd.Series(val, index=out.index)
            out[out_col] = vector_series(X, index=out.index)
            if scorer is not None:
                out[tail.getOrDefault("predictionCol")] = pd.Series(
                    np.asarray(scorer.score_block(X), dtype=np.float64),
                    index=out.index)
            return _split_rows(out, n_parts)

        # LAZY: the pass runs at first materialization, like every other
        # frame op — so an evaluator pushdown (`_fused_eval` hook below) on
        # a transform that is only ever evaluated never assembles the
        # output frame at all. A mid-pass surprise (odd dtype, unseen
        # interim shape) falls back to the generic per-stage chain INSIDE
        # compute(), so laziness never changes what a consumer sees.
        from ..utils.profiler import PROFILER
        stages = self.stages

        def compute_or_fallback():
            try:
                with PROFILER.span("fused_transform",
                                   rows=None, stages=len(stages)):
                    parts = compute()
                if parts is not None:
                    return parts
            except Exception:
                if debug:
                    raise
            cur = parent
            for s in stages:
                cur = s.transform(cur)
            return cur._materialize()

        res = _DF(compute_or_fallback, session=getattr(df, "_session", None),
                  op="_fast_transform")
        res._ml_attrs = dict(df._ml_attrs)
        res._ml_attrs.update(feat.interim_attrs())
        res._ml_attrs[out_col] = feat.feature_attrs()
        if scorer is not None:
            res._fused_eval = _ScorerEvalHook(feat, scorer, tail, df,
                                              self.stages[:-1])
        return res

    def copy(self, extra=None) -> "PipelineModel":
        that = super().copy(extra)
        that.stages = [s.copy(extra) for s in self.stages]
        return that

    def _extra_metadata(self):
        return {"n_stages": len(self.stages)}

    def _save_state(self, path: str) -> None:
        for i, s in enumerate(self.stages):
            s._save_to(os.path.join(path, "stages", f"{i:02d}_{s.uid}"))

    def _load_state(self, path: str, meta) -> None:
        stage_dir = os.path.join(path, "stages")
        self.stages = []
        for d in sorted(os.listdir(stage_dir)) if os.path.exists(stage_dir) else []:
            self.stages.append(Saveable.load(os.path.join(stage_dir, d)))


def load_native(path: str):
    """Load any persisted sml_tpu ML object (generic entry point)."""
    return Saveable.load(path)
