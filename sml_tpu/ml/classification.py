"""Classification estimators.

`LogisticRegression` (`SML/Solutions/ML Electives/MLE 03` answer path) fits
by IRLS Newton steps whose X^T W X reduction is a mesh psum
(`linear_impl.fit_logistic`); transform appends `rawPrediction`,
`probability`, and `prediction` columns like MLlib. Tree classifiers ride
`tree_impl`.
"""

from __future__ import annotations

from typing import Optional

import numpy as np
import pandas as pd

from .base import Estimator, Model, load_arrays, save_arrays
from .feature import _as_object_series
from .linalg import DenseVector, vector_series
from ._staging import extract_compact, extract_features, extract_xy
from . import linear_impl
from ._tree_models import (DecisionTreeClassificationModel,
                           DecisionTreeClassifier, GBTClassificationModel,
                           GBTClassifier, RandomForestClassificationModel,
                           RandomForestClassifier)


class BinaryLogisticRegressionSummary:
    """Training summary. On the compact fast path the margin and accuracy
    are computed EAGERLY at fit time (two cheap O(n) sweeps, so the
    summary closure need not pin the training block); only the
    O(n log n) AUC sort stays lazy, materializing on first read."""

    def __init__(self, accuracy: float = None, areaUnderROC: float = None,
                 numInstances: int = 0, lazy_fn=None):
        self._accuracy = accuracy
        self._auc = areaUnderROC
        self.numInstances = numInstances
        self._lazy_fn = lazy_fn

    def _force(self):
        if self._lazy_fn is not None:
            self._accuracy, self._auc = self._lazy_fn()
            self._lazy_fn = None

    @property
    def accuracy(self) -> float:
        if self._accuracy is None:  # an eager value must not force the
            self._force()           # lazy AUC sort alongside it
        return self._accuracy

    @property
    def areaUnderROC(self) -> float:
        self._force()
        return self._auc


class LogisticRegression(Estimator):
    def _init_params(self):
        self._declareParam("featuresCol", default="features", doc="features column")
        self._declareParam("labelCol", default="label", doc="label column")
        self._declareParam("predictionCol", default="prediction", doc="prediction column")
        self._declareParam("rawPredictionCol", default="rawPrediction", doc="margin column")
        self._declareParam("probabilityCol", default="probability", doc="probability column")
        self._declareParam("regParam", default=0.0, doc="regularization strength")
        self._declareParam("elasticNetParam", default=0.0, doc="L1 mixing in [0,1]")
        self._declareParam("maxIter", default=100, doc="max iterations")
        self._declareParam("tol", default=1e-6, doc="convergence tolerance")
        self._declareParam("fitIntercept", default=True, doc="fit intercept")
        self._declareParam("threshold", default=0.5, doc="decision threshold")

    def __init__(self, featuresCol=None, labelCol=None, predictionCol=None,
                 regParam=None, elasticNetParam=None, maxIter=None, tol=None,
                 fitIntercept=None, threshold=None):
        super().__init__()
        self._set(featuresCol=featuresCol, labelCol=labelCol,
                  predictionCol=predictionCol, regParam=regParam,
                  elasticNetParam=elasticNetParam, maxIter=maxIter, tol=tol,
                  fitIntercept=fitIntercept, threshold=threshold)

    def setLabelCol(self, v):
        return self._set(labelCol=v)

    def setFeaturesCol(self, v):
        return self._set(featuresCol=v)

    def _fit(self, df) -> "LogisticRegressionModel":
        lam = float(self.getOrDefault("regParam"))
        maxIter = int(self.getOrDefault("maxIter"))
        tol = float(self.getOrDefault("tol"))
        fit_int = bool(self.getOrDefault("fitIntercept"))
        compact = extract_compact(df, self.getOrDefault("featuresCol"),
                                  self.getOrDefault("labelCol"))
        if compact is not None and lam == 0.0 and fit_int:
            # fused-IRLS device program: the whole Newton loop in one
            # dispatch, one-hot slots expanded on-chip (linear_impl)
            parts, y = compact
            res = linear_impl.fit_logistic_compact(parts, y,
                                                   maxIter=maxIter, tol=tol)
            model = LogisticRegressionModel(coefficients=res.coefficients,
                                            intercept=res.intercept)
            model._inherit_params(self)

            # margin + accuracy run EAGERLY (two cheap O(n) sweeps) so the
            # summary closure holds only two 1-D arrays — the previous
            # closure pinned the full CompactParts block (hundreds of MB
            # at the 8M-row scale this path is gated to) until the summary
            # was read, or forever if it never was. Only the O(n log n)
            # AUC sort stays lazy; all metrics are EXACT full-data values,
            # and _force drops the arrays once reduced to floats.
            margin = parts.predict_affine(res.coefficients, res.intercept)
            acc = float(np.mean(((margin > 0).astype(float)) == y))

            def lazy_metrics(margin=margin, y=y, acc=acc):
                return acc, _fast_auc(margin, y)

            model._summary = BinaryLogisticRegressionSummary(
                accuracy=acc, numInstances=len(y), lazy_fn=lazy_metrics)
            return model
        else:
            if compact is not None:
                # penalized config needs the materialized block (prox on
                # raw coefficients); expand host-side and take the loop
                parts, y = compact
                X = parts.expand_host()
            else:
                X, y, _ = extract_xy(df, self.getOrDefault("featuresCol"),
                                     self.getOrDefault("labelCol"))
                ok = np.isfinite(y)
                X, y = X[ok], y[ok]
            res = linear_impl.fit_logistic(
                X, y, regParam=lam,
                elasticNetParam=float(self.getOrDefault("elasticNetParam")),
                fitIntercept=fit_int, maxIter=maxIter, tol=tol)
            margin = X @ res.coefficients + res.intercept
        model = LogisticRegressionModel(coefficients=res.coefficients,
                                        intercept=res.intercept)
        model._inherit_params(self)
        pred = (margin > 0).astype(float)
        model._summary = BinaryLogisticRegressionSummary(
            accuracy=float(np.mean(pred == y)),
            areaUnderROC=_fast_auc(margin, y), numInstances=len(y))
        return model


def _fast_auc(score: np.ndarray, label: np.ndarray) -> float:
    order = np.argsort(score)
    ranks = np.empty(len(score))
    ranks[order] = np.arange(1, len(score) + 1)
    pos = label > 0.5
    n_pos, n_neg = pos.sum(), (~pos).sum()
    if n_pos == 0 or n_neg == 0:
        return float("nan")
    return float((ranks[pos].sum() - n_pos * (n_pos + 1) / 2) / (n_pos * n_neg))


class LogisticRegressionModel(Model):
    def _init_params(self):
        LogisticRegression._init_params(self)

    def __init__(self, coefficients=None, intercept: float = 0.0):
        super().__init__()
        self._coefficients = np.asarray(coefficients, dtype=np.float64) \
            if coefficients is not None else None
        self._intercept = float(intercept)
        self._summary: Optional[BinaryLogisticRegressionSummary] = None

    @property
    def coefficients(self) -> DenseVector:
        return DenseVector(self._coefficients)

    @property
    def intercept(self) -> float:
        return self._intercept

    @property
    def summary(self):
        return self._summary

    @property
    def numClasses(self) -> int:
        return 2

    def _transform(self, df):
        fc = self.getOrDefault("featuresCol")
        pc = self.getOrDefault("predictionCol")
        rc = self.getOrDefault("rawPredictionCol")
        prc = self.getOrDefault("probabilityCol")
        thr = float(self.getOrDefault("threshold"))
        w, b = self._coefficients, self._intercept

        def fn(pdf: pd.DataFrame, ctx) -> pd.DataFrame:
            out = pdf.copy(deep=False)  # CoW: column adds never touch the parent
            if len(out) == 0:
                for c in (rc, prc, pc):
                    out[c] = pd.Series(dtype=object if c != pc else float)
                return out
            X = extract_features(out, fc)
            margin = linear_impl.predict_linear(X, w, b)
            p1 = 1.0 / (1.0 + np.exp(-margin))
            out[rc] = vector_series(np.stack([-margin, margin], axis=1),
                                    index=out.index)
            out[prc] = vector_series(np.stack([1 - p1, p1], axis=1),
                                     index=out.index)
            out[pc] = (p1 > thr).astype(float)
            return out

        return df._derive_rowlocal(fn)

    def _save_state(self, path):
        save_arrays(path, coefficients=self._coefficients,
                    intercept=np.asarray([self._intercept]))

    def _load_state(self, path, meta):
        d = load_arrays(path)
        self._coefficients = d["coefficients"]
        self._intercept = float(d["intercept"][0])
        self._summary = None
