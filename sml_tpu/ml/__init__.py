"""MLlib-compatible pipeline API whose distributed math is jitted XLA over
the device mesh (SURVEY §1 L3; build plan §7 stages 4-6)."""

from .base import Estimator, Model, Pipeline, PipelineModel, Transformer, load_native
from .inference import DeviceScorer
from .param import Param, Params

__all__ = ["Estimator", "Model", "Pipeline", "PipelineModel", "Transformer",
           "Param", "Params", "load_native", "DeviceScorer"]
