"""Regression estimators.

`LinearRegression` (`SML/ML 02 - Linear Regression I.py:84-123`): fit via the
sharded Gram/psum solvers in `linear_impl`, expose `coefficients`,
`intercept`, and a training `summary` (rmse/r2) like the reference inspects.
Tree regressors (`SML/ML 06 - Decision Trees.py`, `ML 07`, `ML 11`) ride the
histogram engine in `tree_impl`.
"""

from __future__ import annotations

from typing import Optional

import numpy as np
import pandas as pd

from .base import Estimator, Model, load_arrays, save_arrays
from .feature import _as_object_series
from .linalg import DenseVector
from ._staging import extract_compact, extract_features, extract_xy
from . import linear_impl
from ._tree_models import (DecisionTreeRegressionModel, DecisionTreeRegressor,
                           GBTRegressionModel, GBTRegressor,
                           RandomForestRegressionModel, RandomForestRegressor)


class _PredictorParams:
    """Shared param declarations for supervised estimators/models."""

    def _declare_predictor_params(self):
        self._declareParam("featuresCol", default="features", doc="features column")
        self._declareParam("labelCol", default="label", doc="label column")
        self._declareParam("predictionCol", default="prediction", doc="prediction column")


class LinearRegressionSummary:
    def __init__(self, rmse: float, r2: float, mae: float, explainedVariance: float,
                 numInstances: int, objectiveHistory=None, mae_fn=None):
        self.rootMeanSquaredError = rmse
        self.r2 = r2
        self._mae = mae
        self._mae_fn = mae_fn  # lazy: MAE needs a residual pass, rmse/r2 don't
        self.meanSquaredError = rmse ** 2
        self.explainedVariance = explainedVariance
        self.numInstances = numInstances
        self.objectiveHistory = objectiveHistory or []

    @property
    def meanAbsoluteError(self) -> float:
        if self._mae is None and self._mae_fn is not None:
            self._mae = self._mae_fn()
            self._mae_fn = None
        return self._mae


class LinearRegression(Estimator, _PredictorParams):
    def _init_params(self):
        self._declare_predictor_params()
        self._declareParam("regParam", default=0.0, doc="regularization strength")
        self._declareParam("elasticNetParam", default=0.0, doc="L1 mixing in [0,1]")
        self._declareParam("maxIter", default=100, doc="max iterations")
        self._declareParam("tol", default=1e-6, doc="convergence tolerance")
        self._declareParam("fitIntercept", default=True, doc="fit intercept")
        self._declareParam("standardization", default=True, doc="standardize before penalty")
        self._declareParam("solver", default="auto", doc="auto|normal|l-bfgs")
        self._declareParam("weightCol", doc="instance weight column")

    def __init__(self, featuresCol=None, labelCol=None, predictionCol=None,
                 regParam=None, elasticNetParam=None, maxIter=None, tol=None,
                 fitIntercept=None, standardization=None, solver=None, weightCol=None):
        super().__init__()
        self._set(featuresCol=featuresCol, labelCol=labelCol,
                  predictionCol=predictionCol, regParam=regParam,
                  elasticNetParam=elasticNetParam, maxIter=maxIter, tol=tol,
                  fitIntercept=fitIntercept, standardization=standardization,
                  solver=solver, weightCol=weightCol)

    def setLabelCol(self, v):
        return self._set(labelCol=v)

    def setFeaturesCol(self, v):
        return self._set(featuresCol=v)

    def _fit(self, df) -> "LinearRegressionModel":
        # pass the FRAME, not a pandas copy: extract_xy short-circuits on a
        # fused-fit featurized block without materializing the chain
        kw = dict(
            regParam=float(self.getOrDefault("regParam")),
            elasticNetParam=float(self.getOrDefault("elasticNetParam")),
            fitIntercept=bool(self.getOrDefault("fitIntercept")),
            standardization=bool(self.getOrDefault("standardization")),
            maxIter=int(self.getOrDefault("maxIter")),
            tol=float(self.getOrDefault("tol")))
        compact = extract_compact(df, self.getOrDefault("featuresCol"),
                                  self.getOrDefault("labelCol"))
        if compact is not None:
            # beyond-one-machine block: one-hot slots expand ON CHIP; the
            # (n, d) matrix never exists host-side (featurizer.CompactParts)
            parts, y = compact
            res = linear_impl.fit_linear_compact(parts, y, **kw)
            X = parts
        else:
            X, y, _ = extract_xy(df, self.getOrDefault("featuresCol"),
                                 self.getOrDefault("labelCol"))
            ok = np.isfinite(y)
            X, y = X[ok], y[ok]
            res = linear_impl.fit_linear(X, y, **kw)
        model = LinearRegressionModel(coefficients=res.coefficients,
                                      intercept=res.intercept)
        model._inherit_params(self)
        # rmse/r2/explained-variance come FREE from the fit's own Gram pass
        # (linear_impl._fit_stats) — no second data pass, no extra device
        # round trip; MAE (not Gram-derivable) is computed only if read
        st = res.stats or {}
        n_f = st.get("n", len(y))
        mse = st.get("sse", 0.0) / n_f if n_f else 0.0
        var_y = st.get("var_y", 0.0)

        def lazy_mae(X=X, y=y, w=res.coefficients, b=res.intercept):
            if compact is not None:
                pred = X.predict_affine(w, b)
            else:
                pred = linear_impl.predict_linear(X, w, b)
            return float(np.mean(np.abs(y - pred)))

        model._summary = LinearRegressionSummary(
            rmse=float(np.sqrt(mse)), r2=1 - mse / var_y if var_y else 0.0,
            mae=None, mae_fn=lazy_mae,
            explainedVariance=st.get("var_pred", 0.0), numInstances=int(n_f))
        return model


class LinearRegressionModel(Model, _PredictorParams):
    def _init_params(self):
        LinearRegression._init_params(self)

    def __init__(self, coefficients=None, intercept: float = 0.0):
        super().__init__()
        self._coefficients = np.asarray(coefficients, dtype=np.float64) \
            if coefficients is not None else None
        self._intercept = float(intercept)
        self._summary: Optional[LinearRegressionSummary] = None

    @property
    def coefficients(self) -> DenseVector:
        return DenseVector(self._coefficients)

    @property
    def intercept(self) -> float:
        return self._intercept

    @property
    def summary(self) -> LinearRegressionSummary:
        return self._summary

    @property
    def numFeatures(self) -> int:
        return int(self._coefficients.shape[0])

    def evaluate(self, df) -> LinearRegressionSummary:
        X, y, _ = extract_xy(df.toPandas(), self.getOrDefault("featuresCol"),
                             self.getOrDefault("labelCol"))
        pred = linear_impl.predict_linear(X, self._coefficients, self._intercept)
        resid = y - pred
        var_y = float(np.var(y))
        mse = float(np.mean(resid ** 2))
        return LinearRegressionSummary(
            rmse=float(np.sqrt(mse)), r2=1 - mse / var_y if var_y else 0.0,
            mae=float(np.mean(np.abs(resid))),
            explainedVariance=float(np.var(pred)), numInstances=len(y))

    def _transform(self, df):
        fc = self.getOrDefault("featuresCol")
        oc = self.getOrDefault("predictionCol")
        w, b = self._coefficients, self._intercept

        def fn(pdf: pd.DataFrame, ctx) -> pd.DataFrame:
            out = pdf.copy(deep=False)  # CoW: column adds never touch the parent
            if len(out) == 0:
                out[oc] = pd.Series(dtype=float)
                return out
            X = extract_features(out, fc)
            out[oc] = linear_impl.predict_linear(X, w, b)
            return out

        return df._derive_rowlocal(fn)

    def _save_state(self, path):
        save_arrays(path, coefficients=self._coefficients,
                    intercept=np.asarray([self._intercept]))

    def _load_state(self, path, meta):
        d = load_arrays(path)
        self._coefficients = d["coefficients"]
        self._intercept = float(d["intercept"][0])
        self._summary = None
