"""Model selection: grid search CV and train/validation split.

Reference surface (`SML/ML 07 - Random Forests and Hyperparameter
Tuning.py:72-158`): `ParamGridBuilder().addGrid(...).build()`,
`CrossValidator(estimator, evaluator, estimatorParamMaps, numFolds=3,
parallelism=4, seed=42)` with `avgMetrics`/`bestModel`, and both stage
orders (CV-inside-pipeline vs pipeline-inside-CV, `ML 07:134-149`).

Parallelism: trials run `parallelism`-wide with REAL chip placement — the
active mesh is partitioned into disjoint per-worker submeshes
(`parallel.mesh.run_placed_trials`), so concurrent fits execute on
different chips instead of serializing device programs on one shared mesh.
This is the TPU form of the reference's driver thread pool + executor
tasks (`ML 07:120-130`) — the task-parallel model-selection strategy
SURVEY §2.2 P6.
"""

from __future__ import annotations

import itertools
from typing import Any, Dict, List, Optional

import numpy as np

from ..parallel.mesh import run_placed_trials
from .base import Estimator, Model, Saveable
from .param import Param


class ParamGridBuilder:
    def __init__(self):
        self._grid: Dict[Param, List[Any]] = {}

    def addGrid(self, param: Param, values) -> "ParamGridBuilder":
        self._grid[param] = list(values)
        return self

    def baseOn(self, *args) -> "ParamGridBuilder":
        for m in args:
            for p, v in (m.items() if isinstance(m, dict) else [m]):
                self._grid[p] = [v]
        return self

    def build(self) -> List[Dict[Param, Any]]:
        keys = list(self._grid.keys())
        out = []
        for combo in itertools.product(*[self._grid[k] for k in keys]):
            out.append(dict(zip(keys, combo)))
        return out or [{}]


class _ValidatorParams:
    def _declare_validator_params(self):
        self._declareParam("estimator", doc="estimator to tune")
        self._declareParam("estimatorParamMaps", doc="param grid")
        self._declareParam("evaluator", doc="metric evaluator")
        self._declareParam("seed", default=None, doc="fold assignment seed")
        self._declareParam("parallelism", default=1, doc="concurrent trials")
        self._declareParam("collectSubModels", default=False, doc="keep sub-models")
        # getEstimator/getEstimatorParamMaps/getEvaluator (the course reads
        # them off both the validator and its model, `ML 07:154-159`) come
        # from Params.__getattr__'s synthesized accessors


def _fit_and_eval(est: Estimator, pmap, train, val, evaluator) -> float:
    model = est.copy(pmap).fit(train)
    return evaluator.evaluate(model.transform(val))


def _batched_fold_metrics(est, grid, fold_pairs, evaluator):
    """Fused CV for tree regressors: the G×k (parameter map × fold)
    fit matrix runs as ceil(G*k / sml.cv.maxFusedTrials) trial-batched
    device programs (`_tree_models._fit_ensembles_grid`) — per-trial
    hyperparameters pad to the grid maxima and ride as traced scalars,
    so the dispatch count stops scaling with the grid. With
    sml.cv.maxFusedTrials <= 1 only the fold axis fuses (the VERDICT r3
    per-parameter-map `fit_ensembles_folds` shape: G dispatches). On a
    multi-device mesh the fused elements shard across a second "trial"
    mesh axis when that placement prices better
    (sml.cv.trialAxisDevices; see tree_impl._trial_axis_width) — E
    trials on disjoint chip groups instead of one all-chip vmap.
    Returns the (len(grid), k) metric matrix, or None whenever the shape
    doesn't apply (non-tree estimator, grid touching data-shaping
    params, sml.cv.batchFolds=false, or any surprise) — the caller then
    runs the ordinary placed-trials path, so results never depend on
    fusion firing."""
    from ..conf import GLOBAL_CONF
    from ._tree_models import (_feature_k, _fit_ensemble_folds,
                               _fit_ensembles_grid,
                               DecisionTreeRegressionModel,
                               DecisionTreeRegressor,
                               RandomForestRegressionModel,
                               RandomForestRegressor)
    if not GLOBAL_CONF.getBool("sml.cv.batchFolds"):
        return None
    kinds = {DecisionTreeRegressor: (DecisionTreeRegressionModel, False),
             RandomForestRegressor: (RandomForestRegressionModel, True)}
    info = kinds.get(type(est))
    if info is None:
        return None
    allowed = {"maxDepth", "maxBins", "numTrees", "featureSubsetStrategy",
               "subsamplingRate", "minInstancesPerNode", "minInfoGain",
               "seed"}
    if any(p.name not in allowed for pm in grid for p in pm):
        return None  # a param that reshapes the data: fall back
    try:
        model_cls, is_rf = info
        extracted = [(est._extract(train), val) for train, val in fold_pairs]
        Xs = [e[0][0] for e in extracted]
        ys = [e[0][1] for e in extracted]
        cat = extracted[0][0][2]
        F = Xs[0].shape[1]
        cfgs = []
        for pm in grid:
            ec = est.copy(pm)
            if is_rf:
                n_trees = int(ec.getOrDefault("numTrees"))
                feature_k = _feature_k(
                    ec.getOrDefault("featureSubsetStrategy"), F,
                    ec._is_classifier)
                bootstrap, subsample = True, \
                    float(ec.getOrDefault("subsamplingRate"))
            else:
                n_trees, feature_k, bootstrap, subsample = 1, None, False, 1.0
            cfgs.append(dict(
                est=ec,
                max_depth=int(ec.getOrDefault("maxDepth")),
                max_bins=int(ec.getOrDefault("maxBins")),
                min_instances=int(ec.getOrDefault("minInstancesPerNode")),
                min_info_gain=float(ec.getOrDefault("minInfoGain")),
                n_trees=n_trees, feature_k=feature_k, bootstrap=bootstrap,
                subsample=subsample, seed=ec._seed()))
        metrics = np.zeros((len(grid), len(fold_pairs)), dtype=np.float64)
        max_fused = GLOBAL_CONF.getInt("sml.cv.maxFusedTrials")
        # the padded-bins argmax argument needs min_instances >= 1 (a
        # candidate bin past a trial's own maxBins always leaves an empty
        # right child); 0 is below Spark's own floor, but guard anyway
        if max_fused > 1 and all(c["min_instances"] >= 1 for c in cfgs):
            fused = _fit_ensembles_grid(Xs, ys, cat, cfgs, max_fused)
            for (gi, fi), spec in fused.items():
                model = model_cls(spec)
                model._inherit_params(cfgs[gi]["est"])
                metrics[gi, fi] = evaluator.evaluate(
                    model.transform(extracted[fi][1]))
            return metrics
        for gi, c in enumerate(cfgs):
            specs = _fit_ensemble_folds(
                Xs, ys, cat,
                max_depth=c["max_depth"], max_bins=c["max_bins"],
                min_instances=c["min_instances"],
                min_info_gain=c["min_info_gain"],
                n_trees=c["n_trees"], feature_k=c["feature_k"],
                bootstrap=c["bootstrap"], subsample=c["subsample"],
                seed=c["seed"])
            for fi, (spec, (_, val)) in enumerate(zip(specs, extracted)):
                model = model_cls(spec)
                model._inherit_params(c["est"])
                metrics[gi, fi] = evaluator.evaluate(model.transform(val))
        return metrics
    except Exception:
        # the sequential path is always correct — but record that the
        # batched path bailed (a silent fallback would make a parity bug
        # in the experimental path invisible), and re-raise under the
        # debug env so it can be diagnosed
        import os

        from ..utils.profiler import PROFILER
        PROFILER.count("cv.batchFolds.fallback")
        if os.environ.get("SML_FUSED_DEBUG") == "1":
            raise
        return None


def fused_param_scores(est, pmaps, train, val, evaluator):
    """Score arbitrary param maps of a tree regressor on ONE (train, val)
    pair through the grid-fused trial batch — the evaluator behind
    TrainValidationSplit and the TPE loop's candidate batches
    (`tune.fmin` objectives expose it via `score_batch`). Returns the
    per-map metric list, or None whenever fusion doesn't apply — callers
    fall back to their per-trial path, so results never depend on fusion
    firing."""
    m = _batched_fold_metrics(est, pmaps, [(train, val)], evaluator)
    if m is None:
        return None
    return [float(x) for x in m[:, 0]]


class CrossValidator(Estimator, _ValidatorParams):
    def _init_params(self):
        self._declare_validator_params()
        self._declareParam("numFolds", default=3, doc="number of folds")

    def __init__(self, estimator=None, estimatorParamMaps=None, evaluator=None,
                 numFolds=None, seed=None, parallelism=None, collectSubModels=None):
        super().__init__()
        self._set(estimator=estimator, estimatorParamMaps=estimatorParamMaps,
                  evaluator=evaluator, numFolds=numFolds, seed=seed,
                  parallelism=parallelism, collectSubModels=collectSubModels)

    def _fit(self, df) -> "CrossValidatorModel":
        est = self.getOrDefault("estimator")
        grid = self.getOrDefault("estimatorParamMaps")
        evaluator = self.getOrDefault("evaluator")
        k = int(self.getOrDefault("numFolds"))
        seed = self.getOrDefault("seed")
        seed = int(seed) if seed is not None else 42
        par = max(1, int(self.getOrDefault("parallelism")))

        # seeded per-partition fold assignment — same contract class as
        # randomSplit (`ML 02:38-52`): deterministic given (seed, layout)
        folds = df.randomSplit([1.0 / k] * k, seed=seed)
        for f in folds:
            f.cache()

        fold_pairs = []
        for fi in range(k):
            val = folds[fi]
            rest = [folds[j] for j in range(k) if j != fi]
            train = rest[0]
            for r in rest[1:]:
                train = train.union(r)
            train.cache()
            fold_pairs.append((train, val))

        metrics = _batched_fold_metrics(est, grid, fold_pairs, evaluator)
        if metrics is None:
            metrics = np.zeros((len(grid), k), dtype=np.float64)
            jobs = [(gi, fi, train, val, pmap)
                    for fi, (train, val) in enumerate(fold_pairs)
                    for gi, pmap in enumerate(grid)]

            def run(job):
                gi, fi, train, val, pmap = job
                return gi, fi, _fit_and_eval(est, pmap, train, val,
                                             evaluator)

            results = run_placed_trials(jobs, run, par)
            for gi, fi, m in results:
                metrics[gi, fi] = m

        avg = metrics.mean(axis=1)
        best_idx = int(np.argmax(avg) if evaluator.isLargerBetter()
                       else np.argmin(avg))
        best_model = est.copy(grid[best_idx]).fit(df)
        cvm = CrossValidatorModel(bestModel=best_model, avgMetrics=list(avg))
        cvm._inherit_params(self)
        return cvm


class CrossValidatorModel(Model, _ValidatorParams):
    def _init_params(self):
        CrossValidator._init_params(self)

    def __init__(self, bestModel=None, avgMetrics=None, subModels=None):
        super().__init__()
        self.bestModel = bestModel
        self.avgMetrics = avgMetrics or []
        self.subModels = subModels

    def _transform(self, df):
        return self.bestModel.transform(df)

    def _extra_metadata(self):
        return {"avgMetrics": [float(m) for m in self.avgMetrics]}

    def _save_state(self, path):
        import os
        self.bestModel._save_to(os.path.join(path, "bestModel"))

    def _load_state(self, path, meta):
        import os
        self.avgMetrics = meta.get("avgMetrics", [])
        self.bestModel = Saveable.load(os.path.join(path, "bestModel"))


class TrainValidationSplit(Estimator, _ValidatorParams):
    def _init_params(self):
        self._declare_validator_params()
        self._declareParam("trainRatio", default=0.75, doc="train fraction")

    def __init__(self, estimator=None, estimatorParamMaps=None, evaluator=None,
                 trainRatio=None, seed=None, parallelism=None):
        super().__init__()
        self._set(estimator=estimator, estimatorParamMaps=estimatorParamMaps,
                  evaluator=evaluator, trainRatio=trainRatio, seed=seed,
                  parallelism=parallelism)

    def _fit(self, df) -> "TrainValidationSplitModel":
        est = self.getOrDefault("estimator")
        grid = self.getOrDefault("estimatorParamMaps")
        evaluator = self.getOrDefault("evaluator")
        ratio = float(self.getOrDefault("trainRatio"))
        seed = self.getOrDefault("seed")
        seed = int(seed) if seed is not None else 42
        par = max(1, int(self.getOrDefault("parallelism")))
        train, val = df.randomSplit([ratio, 1 - ratio], seed=seed)
        train.cache()
        val.cache()

        # same fused evaluator as CrossValidator (one (train, val) pair =
        # a 1-fold grid); placed trials whenever fusion doesn't apply
        fused = _batched_fold_metrics(est, grid, [(train, val)], evaluator)
        if fused is not None:
            arr = np.asarray(fused[:, 0])
        else:
            def run(pmap):
                return _fit_and_eval(est, pmap, train, val, evaluator)

            arr = np.asarray(run_placed_trials(grid, run, par))
        best_idx = int(np.argmax(arr) if evaluator.isLargerBetter()
                       else np.argmin(arr))
        best_model = est.copy(grid[best_idx]).fit(df)
        m = TrainValidationSplitModel(bestModel=best_model,
                                      validationMetrics=list(arr))
        m._inherit_params(self)
        return m


class TrainValidationSplitModel(Model, _ValidatorParams):
    def _init_params(self):
        TrainValidationSplit._init_params(self)

    def __init__(self, bestModel=None, validationMetrics=None):
        super().__init__()
        self.bestModel = bestModel
        self.validationMetrics = validationMetrics or []

    def _transform(self, df):
        return self.bestModel.transform(df)

    def _save_state(self, path):
        import os
        self.bestModel._save_to(os.path.join(path, "bestModel"))

    def _load_state(self, path, meta):
        import os
        self.bestModel = Saveable.load(os.path.join(path, "bestModel"))
