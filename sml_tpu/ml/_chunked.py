"""Out-of-core chunked ingest + fit/CV/predict (the device half).

`frame/_chunks.py` defines the host protocol (ChunkSource, the mergeable
quantile sketch, chunk-local split draws); this module runs it against
the engine:

- `ingest_source`: the TWO-PASS streamed quantization. Pass 1 streams
  chunks through per-chunk `DatasetSketch`es merged into one (counting
  rows as it goes); the unified sketch finalizes into the bin edges.
  Pass 2 re-streams the source through the shared
  `parallel.pipeline.prefetch_pipeline`: chunk i+1's host quantization
  (`_bin_columns` on worker threads) overlaps chunk i's H2D transfer +
  device bin-accumulate (`_staging._chunk_assemble_program`, a donated
  dynamic_update_slice into the padded device matrix), with
  `ingest.dispatch`/`ingest.drain` events proving the overlap and a
  stall-watchdog ticket per in-flight chunk. The assembled device matrix
  is adopted into the bin cache (`insert_bins_cached`), so HBM holds the
  COMPACT representation plus ~`sml.data.prefetchChunks` transient chunk
  blocks (ledger pool `chunk_stage`) — never the raw float data.
- `fit_ensemble_chunked`: `_tree_models._fit_ensemble` fed through
  `prebinned=` — everything downstream of quantization is the SAME code
  path as the monolithic fit (bit-parity by construction when the
  sketch is exact).
- `cross_validate_chunked` / `predict_chunked`: k-fold CV over
  `FoldChunkSource` views and streamed prediction, so fit + CV + predict
  all run end-to-end from a ChunkSource.

Per-chunk prep walls feed `obs.INGEST_SKEW` (the `SKEW.note`-style
BSP attribution with chunk indices as lanes), so a slow ingest chunk is
NAMED in `engine_health()["ingest"]` instead of averaged away.
"""

from __future__ import annotations

from typing import Dict, NamedTuple, Optional

import numpy as np

from ..conf import GLOBAL_CONF
from ..frame._chunks import ChunkSource, DatasetSketch, FoldChunkSource
from ..parallel import mesh as meshlib
from ..utils.profiler import PROFILER, now
from .tree_impl import Binning, _bin_columns, binning_edges_and_dtype


class IngestResult(NamedTuple):
    binned: np.ndarray          # (n, F) compact host mirror (view of the
                                # padded assembly buffer — the bin-cache key)
    y: Optional[np.ndarray]     # (n,) float32 labels (None = unlabeled)
    binning: Binning
    n_rows: int
    n_padded: int
    stats: dict                 # per-ingest attribution (see docs/DATAPLANE.md)
    sketch: Optional[DatasetSketch] = None  # the pass-1 full-data sketch
                                # (drift-baseline source: obs/drift.py)


#: fingerprint-keyed memo of completed ingests: a re-fit on the SAME
#: source (CV over fold views shares the parent's chunks, repeated
#: bench fits) skips both passes. Two entries: each pins one compact
#:  matrix (~n bytes) — the realistic reuse window is "the dataset I am
#: working on" plus one fold view.
_ingest_memo: dict = {}
_INGEST_MEMO_ENTRIES = 2


def _memo_key(source: ChunkSource, max_bins: int,
              categorical: Optional[Dict[int, int]]) -> Optional[tuple]:
    fp = source.fingerprint()
    if fp is None:
        return None
    return (fp, int(max_bins), tuple(sorted((categorical or {}).items())),
            int(source.chunk_rows))


def sketch_source(source: ChunkSource, max_bins: int,
                  categorical: Optional[Dict[int, int]] = None,
                  monitor=None) -> DatasetSketch:
    """Ingest pass 1: one `DatasetSketch` PER CHUNK, merged into the
    unified sketch (the mergeable contract — per-chunk summaries built
    independently then unified, exactly how a multi-process ingest would
    combine them). `monitor` (an obs/drift.py DriftMonitor) judges each
    chunk's sketch against a training baseline as it streams past — the
    ingest-time drift monitor, at zero extra sketching cost."""
    unified = DatasetSketch(source.n_features, categorical)
    for i, (X, y) in enumerate(source.chunks()):
        chunk_sk = DatasetSketch(source.n_features, categorical)
        chunk_sk.update(X, y)
        if monitor is not None:
            monitor.observe_sketch(chunk_sk, i)
        unified.merge(chunk_sk)
    return unified


def ingest_source(source: ChunkSource, max_bins: int,
                  categorical: Optional[Dict[int, int]] = None,
                  label: str = "source",
                  drift_baseline=None, binning: Binning = None,
                  sketch: Optional[DatasetSketch] = None) -> IngestResult:
    """Two-pass streamed quantization of a ChunkSource into the engine's
    compact bin representation (module docstring has the pipeline
    shape). Returns the host mirror + binning with the assembled device
    copy already adopted into the bin cache.

    `drift_baseline` (an obs/drift.py DriftBaseline — typically a
    registered model's training baseline) arms the INGEST-TIME DRIFT
    MONITOR: every chunk's pass-1 sketch is judged against it, flagged
    chunks count `drift.chunk_flagged`, and the monitor registers as
    "ingest" in `engine_health()["drift"]` — the refit-trigger signal
    for continuous training. The "ingest" slot is LAST-WINS (the block
    reflects the most recent monitored ingest; its `idle_s` field marks
    how stale the verdicts are).

    `binning` pins the quantization to a SAVED model's edges/remaps
    instead of finalizing fresh ones from the pass-1 sketch — the
    warm-start ingest shape (`warm_start_ensemble_chunked`): appended
    boosting rounds must split on the bin ids the saved trees
    reference, so fresh micro-batches quantize under the old edges.
    Pass 1 still streams (row count, the refreshed model's own
    baseline sketch, the optional drift monitor ride along free)."""
    # a monitored ingest is a MONITORING PASS: it must actually stream
    # the chunks against the caller's baseline, never be satisfied by a
    # cached result (and never poison the cache for unmonitored reuse);
    # binning-pinned and caller-sketched ingests skip the memo too —
    # their result depends on caller state the fingerprint cannot see
    key = None if (drift_baseline is not None or binning is not None
                   or sketch is not None) \
        else _memo_key(source, max_bins, categorical)
    hit = _ingest_memo.get(key) if key is not None else None
    if hit is not None:
        PROFILER.count("ingest.memo_hit")
        return hit

    monitor = None
    if drift_baseline is not None:
        from ..obs import drift as _driftmod
        monitor = _driftmod.DriftMonitor(drift_baseline, name="ingest")
        _driftmod.DRIFT.register("ingest", monitor)

    # ---- pass 1: streamed sketch (counts rows, learns edges). A
    # caller-provided `sketch` of the SAME frozen window (the trainer's
    # judgment pass already streamed one) substitutes for the pass —
    # but never when a monitor must stream (monitoring is per-chunk)
    t0 = now()
    if sketch is None or monitor is not None:
        sketch = sketch_source(source, max_bins, categorical,
                               monitor=monitor)
    if binning is not None:
        edge_list, out_dtype = binning_edges_and_dtype(binning)
    else:
        binning, edge_list, out_dtype = sketch.to_binning(max_bins)
    n = sketch.n_rows
    sketch_s = now() - t0
    PROFILER.count("ingest.sketch_compress", float(sum(
        sk.compressions for sk in sketch.features.values())))

    # ---- pass 2: quantize + double-buffered device assembly
    import jax
    from ..obs import INGEST_SKEW, LEDGER
    from ..parallel.pipeline import prefetch_pipeline
    from ._staging import (_chunk_assemble_program, insert_bins_cached,
                           transient_hbm)
    mesh = meshlib.get_mesh()
    n_dev = meshlib.data_width(mesh)
    n_padded = meshlib.bucket_rows(n, n_dev)
    F = source.n_features
    C = min(max(int(source.chunk_rows), 1), n_padded)
    host = np.zeros((n_padded, F), dtype=out_dtype)
    # labels allocated up front (padded, zeros) so concurrent preps never
    # race an allocation; whether ANY chunk carried labels is resolved at
    # dispatch (serial)
    y_host = np.zeros(n_padded, dtype=np.float32)
    labeled = [False]
    buf = None  # created lazily so an empty source never stages
    prog = _chunk_assemble_program()
    prep_walls: list = []   # appended at DISPATCH (serial) -> chunk order
    dispatch_walls: list = []
    raw_bytes = [0]
    depth = max(GLOBAL_CONF.getInt("sml.data.prefetchChunks"), 1)

    def offsets():
        start = 0
        for X, y in source.chunks():
            rows = int(np.shape(X)[0])
            yield start, X, y
            start += rows

    def prep(item):
        """Host quantization of one chunk (worker threads; the numpy/
        native-binning C paths release the GIL) + write into the compact
        host mirror. Chunks prep at most `workers` ahead, so host
        residency is the mirror plus a few RAW chunk buffers — never the
        raw dataset. Writes are disjoint row ranges; shared counters are
        returned, not mutated (dispatch is the serial side)."""
        t1 = now()
        start, X, y = item
        X = np.asarray(X)
        rows = X.shape[0]
        nbytes = X.nbytes + (0 if y is None else np.asarray(y).nbytes)
        block = _bin_columns(X, edge_list, binning.cat_remap, out_dtype)
        host[start:start + rows] = block
        if y is not None:
            y_host[start:start + rows] = np.asarray(y, dtype=np.float32)
        return start, rows, y is not None, nbytes, now() - t1

    def dispatch(_i, prepped):
        """Serial, in submission order: H2D the chunk's device block and
        fold it into the resident matrix. The block is sliced from the
        host mirror over a FIXED C-row window (clamped at the buffer
        end), so every chunk — including ragged filtered chunks — rides
        ONE executable; rows a window covers beyond its own chunk are
        rewritten correctly by later (strictly ordered) dispatches."""
        nonlocal buf
        start, rows, has_y, nbytes, prep_wall = prepped
        t1 = now()
        labeled[0] = labeled[0] or has_y
        raw_bytes[0] += nbytes
        prep_walls.append(prep_wall)
        if buf is None:
            buf = jax.device_put(np.zeros((n_padded, F), dtype=out_dtype),
                                 meshlib.data_sharding(mesh, 2))
        start_d = min(start, n_padded - C)
        block = np.ascontiguousarray(host[start_d:start_d + C])
        # replicated across the mesh (no divisibility constraint on C):
        # the transient chunk_stage pool charges the PER-DEVICE copies
        block_dev = jax.device_put(block, meshlib.replicated(mesh))
        hold = transient_hbm("chunk_stage", block.nbytes * n_dev)
        hold.__enter__()
        PROFILER.count("ingest.h2d_bytes", float(block.nbytes))
        buf = prog(buf, block_dev, np.int32(start_d))
        dispatch_walls.append(now() - t1)
        return hold

    def drain(_i, hold):
        hold.__exit__(None, None, None)
        return None

    t2 = now()
    for _ in prefetch_pipeline(offsets(), prep, dispatch, drain,
                               depth=depth, workers=min(depth + 1, 4),
                               family="ingest", index_key="chunk"):
        pass
    pipeline_s = now() - t2

    binned = host[:n]
    y_out = y_host[:n] if labeled[0] else None
    if buf is not None:
        insert_bins_cached(binned, buf)
    n_chunks = len(prep_walls)
    PROFILER.count("ingest.chunks", float(n_chunks))
    PROFILER.count("ingest.rows", float(n))
    PROFILER.count("ingest.raw_bytes", float(raw_bytes[0]))
    if n_chunks:
        INGEST_SKEW.note(f"ingest.{label}", prep_walls,
                         devices=list(range(n_chunks)), wall_s=pipeline_s)
    stats = {
        "n_chunks": n_chunks,
        "chunk_rows": C,
        "prefetch_depth": depth,
        "sketch_exact": sketch.exact,
        "sketch_s": round(sketch_s, 4),
        "pipeline_s": round(pipeline_s, 4),
        "prep_s": round(float(sum(prep_walls)), 4),
        "dispatch_s": round(float(sum(dispatch_walls)), 4),
        "raw_bytes": int(raw_bytes[0]),
        "compact_bytes": int(host.nbytes),
        "chunk_stage_peak_bytes": int(
            LEDGER.snapshot().get("chunk_stage", {}).get("peak", 0)),
    }
    out = IngestResult(binned=binned, y=y_out, binning=binning,
                       n_rows=n, n_padded=n_padded, stats=stats,
                       sketch=sketch)
    if key is not None:
        while len(_ingest_memo) >= _INGEST_MEMO_ENTRIES:
            _ingest_memo.pop(next(iter(_ingest_memo)))
        _ingest_memo[key] = out
    return out


def fit_ensemble_chunked(source: ChunkSource, *, categorical=None,
                         max_depth: int, max_bins: int,
                         min_instances: int = 1,
                         min_info_gain: float = 0.0, n_trees: int = 1,
                         feature_k: Optional[int] = None,
                         bootstrap: bool = False, subsample: float = 1.0,
                         seed: int = 17, loss: str = "squared",
                         step_size: float = 0.1, reg_lambda: float = 0.0,
                         gamma: float = 0.0, boosting: bool = False,
                         rounds_per_dispatch: Optional[int] = None,
                         drift_baseline=None, on_rounds=None,
                         sketch=None):
    """Tree-ensemble fit end-to-end from a ChunkSource: streamed
    quantization, then the ordinary `_fit_ensemble` over the prebinned
    compact matrix — the raw float data is never resident whole on host
    or device. The ingest pass-1 sketch doubles as the fitted model's
    drift baseline (full-data features, zero extra sketching);
    `drift_baseline` additionally arms the per-chunk ingest monitor
    against a PRIOR model's baseline (see `ingest_source`)."""
    from ._tree_models import _fit_ensemble
    ing = ingest_source(source, max_bins, categorical, label="fit",
                        drift_baseline=drift_baseline, sketch=sketch)
    if ing.y is None:
        raise ValueError("fit_ensemble_chunked needs a labeled ChunkSource "
                         "(chunks must yield (X, y) with y not None)")
    return _fit_ensemble(
        None, ing.y, categorical=categorical or {}, max_depth=max_depth,
        max_bins=max_bins, min_instances=min_instances,
        min_info_gain=min_info_gain, n_trees=n_trees, feature_k=feature_k,
        bootstrap=bootstrap, subsample=subsample, seed=seed, loss=loss,
        step_size=step_size, reg_lambda=reg_lambda, gamma=gamma,
        boosting=boosting, rounds_per_dispatch=rounds_per_dispatch,
        prebinned=(ing.binned, ing.binning), baseline_sketch=ing.sketch,
        on_rounds=on_rounds)


def warm_start_ensemble_chunked(spec, source: ChunkSource, *,
                                n_new_trees: int, seed: int = 17,
                                drift_baseline=None, sketch=None,
                                **resume_kwargs):
    """Warm-start incremental boosting from a ChunkSource: fresh chunks
    quantize under the SAVED spec's binning (appended rounds must split
    on the bin ids the saved trees reference — `ingest_source(binning=)`
    pins the edges), pass 1's sketch doubles as the refreshed model's
    drift baseline and feeds the optional per-chunk ingest monitor
    (`drift_baseline=` — the continuous-training refit loop's signal),
    pass 2 assembles through the same double-buffered prefetch, then the
    saved rounds' margin replays on device and `n_new_trees` rounds
    append via the staged `roundsPerDispatch` dispatch. k rounds +
    warm-start (N-k) rounds == an N-round fit bit-identically on the
    same data/seed; `resume_kwargs` mirror `warm_start_ensemble`'s
    (subsample, step_size, feature_k, rounds_per_dispatch, on_rounds —
    the round-level checkpoint hook). `sketch` is a caller-provided
    pass-1 sketch of the SAME frozen window (the continuous trainer's
    judgment pass already streamed one — reusing it saves a full read
    of the window)."""
    from ._tree_models import _resume_ensemble
    if spec.tree_weights is None:
        raise ValueError(
            "warm start needs a boosted spec (GBT/xgboost): forest/DT "
            "trees average independent rounds — refit those whole")
    categorical = {f: len(r) for f, r in spec.binning.cat_remap.items()}
    max_bins = spec.binning.edges.shape[1] + 1
    ing = ingest_source(source, max_bins, categorical, label="warm_fit",
                        drift_baseline=drift_baseline,
                        binning=spec.binning, sketch=sketch)
    if ing.y is None:
        raise ValueError("warm_start_ensemble_chunked needs a labeled "
                         "ChunkSource (chunks must yield (X, y) with y "
                         "not None)")
    return _resume_ensemble(spec, ing.binned, ing.y,
                            n_new_trees=n_new_trees, seed=seed,
                            baseline_sketch=ing.sketch, **resume_kwargs)


def iter_predictions(spec, source: ChunkSource):
    """Streamed prediction: one (chunk_predictions, chunk_labels) pair
    per chunk through `_EnsembleSpec.predict_margin` — each chunk bins
    and stages alone, so predict-side residency is chunk-bounded too.
    Per-row traversal is batch-size-invariant, so chunked predictions
    are bit-identical to the monolithic call."""
    for X, y in source.chunks():
        yield spec.predict_margin(np.asarray(X, dtype=np.float64)), y


def predict_chunked(spec_or_model, source: ChunkSource) -> np.ndarray:
    """Concatenated predictions for a whole ChunkSource (the (n,) output
    is float64 — 8 bytes/row, bounded even at 100M rows)."""
    spec = getattr(spec_or_model, "_spec", spec_or_model)
    outs = [p for p, _ in iter_predictions(spec, source)]
    return np.concatenate(outs) if outs else np.zeros(0)


def cross_validate_chunked(source: ChunkSource, k: int, split_seed: int, *,
                           categorical=None, **fit_params) -> dict:
    """k-fold CV from a ChunkSource: fold membership is the chunk-local
    stateless draw (`FoldChunkSource`), each fold's training view fits
    through the chunked path and evaluates streaming RMSE on the held
    fold — no fold dataset is ever materialized whole. `split_seed`
    seeds the fold draw; the estimator's own `seed` rides `fit_params`.

    Fold FITS are bit-identical to any other chunking of the same source
    (fold membership and quantization both are); the streamed RMSE
    accumulates per chunk, so the metric matches other chunkings within
    float reduction-order tolerance (~1 ulp), not bit-for-bit."""
    fold_rmse = []
    for j in range(int(k)):
        train = FoldChunkSource(source, split_seed, k, j, invert=True)
        val = FoldChunkSource(source, split_seed, k, j, invert=False)
        spec = fit_ensemble_chunked(train, categorical=categorical,
                                    **fit_params)
        sse = 0.0
        cnt = 0
        for pred, y in iter_predictions(spec, val):
            d = pred - np.asarray(y, dtype=np.float64)
            sse += float(d @ d)
            cnt += d.size
        fold_rmse.append(float(np.sqrt(sse / max(cnt, 1))))
    return {"avg_rmse": float(np.mean(fold_rmse)), "fold_rmse": fold_rmse,
            "k": int(k), "seed": int(split_seed)}
