"""Param system for the ML pipeline API.

The reference's MLlib estimators are parameterized through `Param`s with
defaults, `explainParams()` (`SML/ML 07 - Random Forests and Hyperparameter
Tuning.py:56`), and `copy(paramMap)` used by tuning loops
(`SML/ML 08 - Hyperopt.py:97`). This re-implements that contract standalone:
a Param is a (parent, name, doc) descriptor; a Params object holds a default
map and a user-set map; `copy({param: value})` clones with extra overrides.
"""

from __future__ import annotations

import copy as _copy
from typing import Any, Dict, Generic, List, Optional, TypeVar

T = TypeVar("T")


class Param(Generic[T]):
    def __init__(self, parent: "Params", name: str, doc: str = ""):
        self.parent = parent.uid if isinstance(parent, Params) else parent
        self.name = name
        self.doc = doc

    def __repr__(self):
        return f"{self.parent}__{self.name}"

    def __hash__(self):
        return hash(str(self))

    def __eq__(self, other):
        return isinstance(other, Param) and self.parent == other.parent \
            and self.name == other.name


_uid_counters: Dict[str, int] = {}


def _gen_uid(cls_name: str) -> str:
    n = _uid_counters.get(cls_name, 0)
    _uid_counters[cls_name] = n + 1
    return f"{cls_name}_{n:04x}"


class Params:
    """Base for everything that carries Params (Transformer/Estimator/Model)."""

    def __init__(self):
        self.uid = _gen_uid(type(self).__name__)
        self._defaultParamMap: Dict[Param, Any] = {}
        self._paramMap: Dict[Param, Any] = {}
        self._shadowed_params: Dict[str, Param] = {}

    # -- declaration ------------------------------------------------------
    def _declareParam(self, name: str, default: Any = None, doc: str = "") -> Param:
        p = Param(self, name, doc)
        try:
            setattr(self, name, p)
        except AttributeError:
            # name shadowed by a class property (e.g. ALSModel.rank);
            # the param stays reachable via getParam/_shadowed
            self._shadowed_params[name] = p
        self._defaultParamMap[p] = default
        return p

    # -- access -----------------------------------------------------------
    @property
    def params(self) -> List[Param]:
        found = [v for v in self.__dict__.values() if isinstance(v, Param)]
        found += list(self._shadowed_params.values())
        return sorted(found, key=lambda p: p.name)

    def getParam(self, name: str) -> Param:
        if name in self._shadowed_params:
            return self._shadowed_params[name]
        p = getattr(self, name, None)
        if not isinstance(p, Param):
            raise AttributeError(f"{type(self).__name__} has no param {name!r}")
        return p

    def isDefined(self, param) -> bool:
        param = self._resolve(param)
        return param in self._paramMap or self._defaultParamMap.get(param) is not None

    def isSet(self, param) -> bool:
        return self._resolve(param) in self._paramMap

    def hasParam(self, name: str) -> bool:
        return name in self._shadowed_params or \
            isinstance(getattr(self, name, None), Param)

    def getOrDefault(self, param) -> Any:
        param = self._resolve(param)
        if param in self._paramMap:
            return self._paramMap[param]
        return self._defaultParamMap.get(param)

    def __getattr__(self, name: str):
        """MLlib auto-generates `get<Param>()`/`set<Param>(v)` for every
        declared param; synthesize the same accessors for any param that
        has no explicit method (explicit defs win — this only runs when
        normal lookup fails)."""
        if name.startswith(("get", "set")) and len(name) > 3 and \
                not name.startswith("__"):
            pname = name[3].lower() + name[4:]
            # hasParam uses getattr(self, pname) which re-enters here for
            # unknown names and correctly raises below — no recursion
            if self.hasParam(pname):
                if name.startswith("get"):
                    return lambda: self.getOrDefault(pname)

                def setter(value, _pname=pname):
                    # same _set semantics as every explicit setter in the
                    # codebase (None means "leave unset") — one setter
                    # contract everywhere beats a PySpark corner case that
                    # no course code exercises
                    self._set(**{_pname: value})
                    return self

                return setter
        # NOTE: a property whose body raises AttributeError lands here and
        # gets re-reported as a missing attribute (Python swallows the
        # original before calling __getattr__) — properties on Params
        # subclasses should raise RuntimeError for internal errors
        raise AttributeError(
            f"{type(self).__name__!r} object has no attribute {name!r}")

    def get(self, param) -> Any:
        return self.getOrDefault(param)

    def _resolve(self, param) -> Param:
        return self.getParam(param) if isinstance(param, str) else param

    def set(self, param, value) -> "Params":  # noqa: A003
        self._paramMap[self._resolve(param)] = value
        return self

    def _set(self, **kwargs) -> "Params":
        for k, v in kwargs.items():
            if v is not None:
                self._paramMap[self.getParam(k)] = v
        # monotonically counts param mutations: compiled-plan caches (the
        # fused pipeline transform) fold this into their tokens so a
        # post-fit setter call invalidates them
        self._param_version = getattr(self, "_param_version", 0) + 1
        return self

    def _setDefault(self, **kwargs) -> "Params":
        for k, v in kwargs.items():
            self._defaultParamMap[self.getParam(k)] = v
        return self

    def extractParamMap(self, extra: Optional[Dict[Param, Any]] = None) -> Dict[Param, Any]:
        m = dict(self._defaultParamMap)
        m.update(self._paramMap)
        if extra:
            m.update(extra)
        return m

    def explainParam(self, param) -> str:
        param = self._resolve(param)
        default = self._defaultParamMap.get(param)
        cur = self._paramMap.get(param, "undefined")
        if param in self._paramMap:
            state = f"current: {cur}"
        else:
            state = "undefined"
        return f"{param.name}: {param.doc} (default: {default}, {state})"

    def explainParams(self) -> str:
        return "\n".join(self.explainParam(p) for p in self.params)

    def copy(self, extra: Optional[Dict[Param, Any]] = None) -> "Params":
        """Clone; tuning loops rely on `est.copy(paramMap)` (`ML 08:97`)."""
        that = _copy.copy(self)
        that._paramMap = dict(self._paramMap)
        that._defaultParamMap = dict(self._defaultParamMap)
        # rebind Param descriptors to this instance's uid (shared uid semantics
        # — MLlib keeps the same uid on copy, which tuning depends on)
        if extra:
            for p, v in extra.items():
                if isinstance(p, Param):
                    # params addressed to another object (e.g. a pipeline
                    # stage) are skipped here; composite estimators like
                    # Pipeline route them to their children in their copy()
                    if p.parent == that.uid and that.hasParam(p.name):
                        that._paramMap[that.getParam(p.name)] = v
                else:
                    that._paramMap[that.getParam(p)] = v
        return that

    # -- (de)serialization of param values -------------------------------
    def _params_to_dict(self) -> Dict[str, Any]:
        out = {}
        for p, v in self.extractParamMap().items():
            if _is_jsonable(v):
                out[p.name] = v
        return out

    def _params_from_dict(self, d: Dict[str, Any]) -> None:
        for name, v in d.items():
            if self.hasParam(name):
                self._paramMap[self.getParam(name)] = v


def _is_jsonable(v) -> bool:
    import json
    try:
        json.dumps(v)
        return True
    except (TypeError, ValueError):
        return False
