"""Tree estimators/models over the histogram engine (`tree_impl`).

Surface parity targets:
- `DecisionTreeRegressor` + `maxBins` failure semantics and
  `featureImportances` — `SML/ML 06 - Decision Trees.py:73-154`
- `RandomForestRegressor/Classifier` (numTrees, maxDepth,
  featureSubsetStrategy) — `SML/ML 07 - Random Forests and Hyperparameter
  Tuning.py:41-77`, `SML/Labs/ML 07L - Hyperparameter Tuning Lab.py`
- GBT (`SML/ML 11 - XGBoost.py:109` mentions GBTRegressor; the
  XGBoost-equivalent surface lives in `sml_tpu.xgboost`)

All learners share one second-order histogram program; the differences are
the (grad, hess) stream, bootstrap weights, and per-node feature subspaces.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional

import numpy as np
import pandas as pd

from ..parallel import dispatch
from .base import Estimator, Model, RegStatsHook, load_arrays, save_arrays
from .feature import _as_object_series
from .linalg import DenseVector, vector_series
from ._staging import extract_features, extract_xy
from . import tree_impl
from .tree_impl import (Binning, FittedTree, TreeSpec, bin_with,
                        feature_importances, fit_tree, predict_forest,
                        stage_aligned, stage_tree_data)


def _categorical_slots(df, featuresCol: str) -> Dict[int, int]:
    attrs = getattr(df, "_ml_attrs", {}).get(featuresCol) or {}
    return {int(k): int(v) for k, v in (attrs.get("slots") or {}).items()}


class _TreeParams:
    def _declare_tree_params(self):
        self._declareParam("featuresCol", default="features", doc="features column")
        self._declareParam("labelCol", default="label", doc="label column")
        self._declareParam("predictionCol", default="prediction", doc="prediction column")
        self._declareParam("maxDepth", default=5, doc="max tree depth")
        self._declareParam("maxBins", default=32, doc="max discretization bins")
        self._declareParam("minInstancesPerNode", default=1, doc="min rows per child")
        self._declareParam("minInfoGain", default=0.0, doc="min split gain")
        self._declareParam("seed", default=None, doc="random seed")


def _feature_k(strategy: str, F: int, is_classification: bool) -> int:
    s = str(strategy).lower()
    if s == "auto":
        s = "sqrt" if is_classification else "onethird"
    if s == "all":
        return F
    if s == "sqrt":
        return max(1, int(math.sqrt(F)))
    if s == "log2":
        return max(1, int(math.log2(F)))
    if s == "onethird":
        return max(1, int(F / 3))
    try:
        v = float(strategy)
        if v <= 1.0:
            return max(1, int(v * F))
        return min(F, int(v))
    except ValueError:
        raise ValueError(f"unknown featureSubsetStrategy {strategy!r}")


class _EnsembleSpec:
    """Host-side description of a fitted ensemble (persisted whole)."""

    #: training drift baseline (obs/drift.py DriftBaseline), stamped by
    #: `_fit_ensemble` and persisted as baseline.json next to data.npz —
    #: the distribution a serving/ingest drift monitor compares against
    baseline = None

    def __init__(self, trees: List[FittedTree], depth: int, binning: Binning,
                 tree_weights: Optional[np.ndarray], base: float,
                 n_features: int, mode: str):
        self.trees = trees
        self.depth = depth
        self.binning = binning
        self.tree_weights = tree_weights  # None → average
        self.base = base
        self.n_features = n_features
        self.mode = mode  # "regression" | "binary"

    def stacked(self):
        """Stacked (T, n_nodes) tree tensors + per-tree weights, cached —
        the replicated operands of the sharded traversal program."""
        if not hasattr(self, "_stacked"):
            sf = np.stack([t.split_feature for t in self.trees])
            sb = np.stack([t.split_bin for t in self.trees])
            lv = np.stack([t.leaf_value for t in self.trees])
            w = (np.full(len(self.trees), 1.0 / len(self.trees), np.float32)
                 if self.tree_weights is None
                 else np.asarray(self.tree_weights, dtype=np.float32))
            self._stacked = (sf, sb, lv, w)
        return self._stacked

    def predict_margin(self, X: np.ndarray) -> np.ndarray:
        from ..utils.profiler import PROFILER
        with PROFILER.span("binning.predict", rows=int(X.shape[0])):
            binned = bin_with(X, self.binning)
        n = binned.shape[0]
        from ._staging import route_for_arrays
        hint = dispatch.WorkHint(
            flops=4.0 * n * len(self.trees) * self.depth, kind="traverse",
            out_bytes=4.0 * n)
        mesh, route = route_for_arrays(hint, binned)
        with PROFILER.span("program.forest_predict", rows=n, route=route):
            if route == "device":
                # rows shard over the mesh; tree tensors replicate (P8 path)
                from .inference import predict_forest_sharded
                sf, sb, lv, w = self.stacked()
                return predict_forest_sharded(
                    binned, sf, sb, lv, w, self.depth, base=self.base,
                    n_bins=self.binning.edges.shape[1] + 1)
            import jax
            with dispatch.observe_host("traverse", hint.flops), \
                    jax.default_device(list(mesh.devices.flat)[0]):
                return self.base + predict_forest(binned, self.trees,
                                                  self.depth,
                                                  self.tree_weights)

    def save(self, path: str) -> None:
        remap_keys = sorted(self.binning.cat_remap)
        save_arrays(
            path,
            split_feature=np.stack([t.split_feature for t in self.trees]),
            split_bin=np.stack([t.split_bin for t in self.trees]),
            leaf_value=np.stack([t.leaf_value for t in self.trees]),
            gain=np.stack([t.gain for t in self.trees]),
            cover=np.stack([t.cover for t in self.trees]),
            edges=self.binning.edges,
            tree_weights=(self.tree_weights if self.tree_weights is not None
                          else np.zeros(0)),
            scalars=np.asarray([self.depth, self.base, self.n_features,
                                1.0 if self.mode == "binary" else 0.0,
                                len(remap_keys)], dtype=np.float64),
            remap_slots=np.asarray(remap_keys, dtype=np.int64),
            **{f"remap_{k}": self.binning.cat_remap[k] for k in remap_keys},
        )
        if self.baseline is not None:
            import json as _json
            import os as _os
            with open(_os.path.join(path, "baseline.json"), "w") as f:
                _json.dump(self.baseline.to_dict(), f)

    @classmethod
    def load(cls, path: str) -> "_EnsembleSpec":
        d = load_arrays(path)
        depth, base, n_features, is_bin, _ = d["scalars"]
        remap = {int(k): d[f"remap_{int(k)}"] for k in d["remap_slots"]}
        trees = [FittedTree(sf, sb, lv, g, c) for sf, sb, lv, g, c in
                 zip(d["split_feature"], d["split_bin"], d["leaf_value"],
                     d["gain"], d["cover"])]
        tw = d["tree_weights"] if len(d["tree_weights"]) else None
        spec = cls(trees, int(depth),
                   Binning(edges=d["edges"], cat_remap=remap),
                   tw, float(base), int(n_features),
                   "binary" if is_bin else "regression")
        import os as _os
        bp = _os.path.join(path, "baseline.json")
        if _os.path.exists(bp):
            import json as _json

            from ..obs.drift import DriftBaseline
            with open(bp) as f:
                spec.baseline = DriftBaseline.from_dict(_json.load(f))
        return spec


import threading as _threading

_bins_cache: dict = {}
_bins_cache_order: list = []
_bins_cache_bytes: list = [0]
_bins_inflight: dict = {}  # key -> Event set when that key's bins land
_bins_lock = _threading.Lock()  # parallel tuning trials bin concurrently
_BINS_CACHE_MAX_BYTES = 1 << 30


def _cached_bins(X, y32, max_bins, categorical):
    """make_bins memoized by (content fingerprint, bins, categorical):
    CV folds and tuning trials re-fit trees on IDENTICAL matrices once per
    parameter set — re-quantizing 1M rows per fit was ~0.3s apiece.
    Byte-budgeted and locked like the staging cache (same concurrent
    TpuTrials path, same multi-100MB operands)."""
    from ._staging import _content_key, _normalize
    from .tree_impl import make_bins
    Xc = _normalize(X)
    key = (_content_key(Xc), _content_key(_normalize(y32)), int(max_bins),
           tuple(sorted((categorical or {}).items())))
    while True:
        with _bins_lock:
            hit = _bins_cache.get(key)
            if hit is None and key not in _bins_inflight:
                _bins_inflight[key] = _threading.Event()
                break  # this thread computes
            waiter = _bins_inflight.get(key) if hit is None else None
        if hit is not None:
            return hit
        # another tuning trial is quantizing the SAME matrix: wait for it
        # instead of paying the ~0.3s re-binning the cache exists to avoid
        waiter.wait()
    try:
        hit = make_bins(Xc, y32, max_bins, categorical)
        cost = hit[0].nbytes
        with _bins_lock:
            _bins_cache[key] = hit
            _bins_cache_order.append((key, cost))
            _bins_cache_bytes[0] += cost
            while _bins_cache_bytes[0] > _BINS_CACHE_MAX_BYTES \
                    and len(_bins_cache_order) > 1:
                old, old_cost = _bins_cache_order.pop(0)
                _bins_cache.pop(old, None)
                _bins_cache_bytes[0] -= old_cost
    finally:
        with _bins_lock:
            ev = _bins_inflight.pop(key, None)
        if ev is not None:
            ev.set()
    return hit


def _fit_ensemble(X: np.ndarray, y: np.ndarray, *, categorical: Dict[int, int],
                  max_depth: int, max_bins: int, min_instances: int,
                  min_info_gain: float, n_trees: int, feature_k: Optional[int],
                  bootstrap: bool, subsample: float, seed: int, loss: str,
                  step_size: float = 0.1, reg_lambda: float = 0.0,
                  gamma: float = 0.0, boosting: bool = False,
                  missing: Optional[float] = None,
                  rounds_per_dispatch: Optional[int] = None,
                  prebinned=None, baseline_sketch=None,
                  on_rounds=None) -> _EnsembleSpec:
    """The one training path behind every tree learner: bin on host, then
    the WHOLE forest/boosting fit runs as a single on-device program
    (`tree_impl.fit_ensemble_on_device`).

    `prebinned=(binned, binning)` is the out-of-core entry
    (`ml/_chunked.py`): the compact matrix was quantized CHUNK BY CHUNK
    and (on the device route) its assembled device copy already sits in
    the bin cache, so X may be None — the raw float data never existed
    whole. Everything downstream is the SAME code path as the monolithic
    fit, which makes chunked-vs-monolithic bit-parity a structural
    property rather than a numerical accident."""
    from ._staging import routed_for
    y32 = np.asarray(y, np.float32)
    if prebinned is not None:
        binned, binning = prebinned
        F = binned.shape[1]
    else:
        if missing is not None and not np.isnan(missing):
            X = X.copy()
            X[X == missing] = np.nan
        F = X.shape[1]
        # bin on host FIRST so the dispatcher can probe the staging cache
        # with the actual device operand; histogram builds dominate the
        # program: trees x levels x (n x F x bins) one-hot accumulations
        binned, binning = _cached_bins(X, y32, max_bins, categorical)
    # measured host-mesh rate for this program is ~1.2e9 ops/s (one-hot
    # expansion defeats CPU BLAS) — scatter-class, not blas
    hint = dispatch.WorkHint(
        flops=2.0 * n_trees * max_depth * binned.shape[0] * F * max_bins,
        kind="scatter")
    with routed_for(hint, binned):
        staged = stage_tree_data(X, y32, max_bins, categorical,
                                 prebinned=(binned, binning))
        spec = TreeSpec(max_depth=max_depth, n_bins=max_bins, n_features=F,
                        feature_k=feature_k or F, min_instances=min_instances,
                        min_info_gain=min_info_gain, reg_lambda=reg_lambda,
                        gamma=gamma)
        es = tree_impl.EnsembleSpec(
            tree=spec, n_trees=n_trees, loss=loss, boosting=boosting,
            bootstrap=bootstrap and n_trees > 1, subsample=float(subsample),
            step_size=float(step_size))
        y_dev = stage_aligned(y32, staged.n_padded)
        trees, base = tree_impl.fit_ensemble_on_device(
            staged.binned_dev, y_dev, staged.mask_dev, es, seed=seed,
            rounds_per_dispatch=rounds_per_dispatch, on_rounds=on_rounds)
    mode = "binary" if loss == "logistic" else "regression"
    if boosting:
        weights = np.full(len(trees), step_size, dtype=np.float32)
        spec = _EnsembleSpec(trees, max_depth, staged.binning, weights,
                             base, F, mode)
    else:
        spec = _EnsembleSpec(trees, max_depth, staged.binning, None, 0.0,
                             F, mode)
    # training drift baseline (obs/drift.py): features + label + the
    # model's own training predictions, sketched from a strided
    # subsample bounded by sml.obs.driftBaselineRows (the chunked path
    # passes its full-data ingest sketch instead). Host-side numpy only
    # — capture must not perturb the fit's program/dispatch counters
    from ..obs import drift as _drift
    spec.baseline = _drift.capture_fit_baseline(
        X, y32, categorical, spec, binned=binned, sketch=baseline_sketch)
    return spec


def _resume_ensemble(spec: _EnsembleSpec, binned: np.ndarray,
                     y32: np.ndarray, *, n_new_trees: int, seed: int,
                     feature_k: Optional[int] = None, min_instances: int = 1,
                     min_info_gain: float = 0.0, reg_lambda: float = 0.0,
                     gamma: float = 0.0, subsample: float = 1.0,
                     bootstrap: bool = False,
                     step_size: Optional[float] = None,
                     loss: Optional[str] = None,
                     rounds_per_dispatch: Optional[int] = None,
                     X: Optional[np.ndarray] = None, baseline_sketch=None,
                     on_rounds=None) -> _EnsembleSpec:
    """Warm-start core shared by the monolithic (`warm_start_ensemble`)
    and chunked (`ml/_chunked.warm_start_ensemble_chunked`) paths: stage
    the matrix ALREADY QUANTIZED under the saved spec's binning (the
    appended rounds must split on the bin ids the saved trees
    reference), replay the saved rounds' margin on device, and append
    `n_new_trees` boosting rounds through the same staged dispatch a
    fresh fit uses. Round t of the combined ensemble draws the same
    sampling/feature stream whether it was fitted monolithically or
    appended later (the fold_in(t) streams are round-indexed), so k
    rounds + warm-start (N-k) rounds == N rounds bit-identically on the
    same data/seed (tests/test_ct.py pins it)."""
    if spec.tree_weights is None:
        raise ValueError(
            "warm start needs a boosted spec (GBT/xgboost): forest/DT "
            "trees average independent rounds — refit those whole")
    saved_step = float(spec.tree_weights[0])
    step = float(step_size) if step_size is not None else saved_step
    if np.float32(step) != np.float32(saved_step):
        # the margin replay and the combined weight vector both apply
        # ONE step to every round: a different step would silently
        # rescale the SAVED rounds' contribution, changing the
        # incumbent's predictions retroactively
        raise ValueError(
            f"warm start cannot change step_size: the saved rounds were "
            f"fitted at {saved_step} (got {step}); refit full to move it")
    loss = loss or ("logistic" if spec.mode == "binary" else "squared")
    F = spec.n_features
    max_bins = spec.binning.edges.shape[1] + 1
    n_total = len(spec.trees) + int(n_new_trees)
    from ._staging import routed_for
    hint = dispatch.WorkHint(
        flops=2.0 * n_new_trees * spec.depth * binned.shape[0] * F
        * max_bins, kind="scatter")
    with routed_for(hint, binned):
        staged = stage_tree_data(X, y32, max_bins, None,
                                 prebinned=(binned, spec.binning))
        tspec = TreeSpec(max_depth=spec.depth, n_bins=max_bins,
                         n_features=F, feature_k=feature_k or F,
                         min_instances=min_instances,
                         min_info_gain=min_info_gain,
                         reg_lambda=reg_lambda, gamma=gamma)
        es = tree_impl.EnsembleSpec(
            tree=tspec, n_trees=n_total, loss=loss, boosting=True,
            bootstrap=bool(bootstrap) and n_total > 1,
            subsample=float(subsample), step_size=step)
        y_dev = stage_aligned(y32, staged.n_padded)
        new_trees, base = tree_impl.resume_ensemble_on_device(
            staged.binned_dev, y_dev, staged.mask_dev, es, seed=seed,
            init_trees=spec.trees, base=float(spec.base),
            rounds_per_dispatch=rounds_per_dispatch, on_rounds=on_rounds)
    trees = list(spec.trees) + list(new_trees)
    weights = np.full(len(trees), step, dtype=np.float32)
    out = _EnsembleSpec(trees, spec.depth, spec.binning, weights,
                        float(spec.base), F, spec.mode)
    categorical = {f: len(r) for f, r in spec.binning.cat_remap.items()}
    from ..obs import drift as _drift
    out.baseline = _drift.capture_fit_baseline(
        X, y32, categorical, out, binned=binned, sketch=baseline_sketch)
    return out


def warm_start_ensemble(spec: _EnsembleSpec, X: np.ndarray, y: np.ndarray,
                        *, n_new_trees: int, seed: int,
                        **resume_kwargs) -> _EnsembleSpec:
    """Resume a saved boosted `_EnsembleSpec` on in-memory (X, y):
    quantize with the SAVED binning (`bin_with` — warm-started rounds
    never move the bin edges) and append `n_new_trees` rounds. Keyword
    knobs mirror `_fit_ensemble`'s (subsample, step_size, feature_k,
    rounds_per_dispatch, ...); step_size/loss default to the saved
    spec's. The out-of-core twin is
    `ml/_chunked.warm_start_ensemble_chunked`."""
    X = np.asarray(X)
    y32 = np.asarray(y, np.float32)
    binned = bin_with(X, spec.binning)
    return _resume_ensemble(spec, binned, y32, n_new_trees=n_new_trees,
                            seed=seed, X=X, **resume_kwargs)


def _fit_ensemble_folds(Xs, ys, cats, *, max_depth: int, max_bins: int,
                        min_instances: int, min_info_gain: float,
                        n_trees: int, feature_k: Optional[int],
                        bootstrap: bool, subsample: float, seed: int,
                        loss: str = "squared") -> List[_EnsembleSpec]:
    """`_fit_ensemble` for k SAME-SPEC fold datasets in one vmapped device
    program (`tree_impl.fit_ensembles_folds`): CV's fold fits share every
    static shape, so one dispatch replaces k. Binning stays per fold (each
    fold's quantile edges come from ITS rows, matching the sequential
    path's models exactly in structure)."""
    from ._staging import routed_for
    binned_list, binnings, y32s = [], [], []
    for X, y in zip(Xs, ys):
        y32 = np.asarray(y, np.float32)
        binned, binning = _cached_bins(X, y32, max_bins, cats)
        binned_list.append(binned)
        binnings.append(binning)
        y32s.append(y32)
    F = Xs[0].shape[1]
    n_total = sum(b.shape[0] for b in binned_list)
    # stack BEFORE routing so the router prices/promotes the exact
    # axis-1-sharded arrays the program stages (probing the per-fold 2-D
    # arrays would discount/promote dead copies)
    bst, yst, mst = tree_impl.build_fold_stacks(binned_list, y32s)
    hint = dispatch.WorkHint(
        flops=2.0 * n_trees * max_depth * n_total * F * max_bins,
        kind="scatter")
    with routed_for(hint, bst, yst, mst, stacked=True):
        spec = TreeSpec(max_depth=max_depth, n_bins=max_bins, n_features=F,
                        feature_k=feature_k or F, min_instances=min_instances,
                        min_info_gain=min_info_gain, reg_lambda=0.0,
                        gamma=0.0)
        es = tree_impl.EnsembleSpec(
            tree=spec, n_trees=n_trees, loss=loss, boosting=False,
            bootstrap=bootstrap and n_trees > 1, subsample=float(subsample),
            step_size=0.1)
        results = tree_impl.fit_ensembles_folds(bst, yst, mst, es, seed)
    mode = "binary" if loss == "logistic" else "regression"
    return [_EnsembleSpec(trees, max_depth, binnings[k], None, 0.0, F, mode)
            for k, (trees, base) in enumerate(results)]


def _fit_ensembles_grid(Xs, ys, cats, trials, max_fused: int,
                        loss: str = "squared"):
    """GRID-FUSED CV fits: `trials` carries one hyperparameter config per
    grid point (max_depth, max_bins, min_instances, min_info_gain,
    n_trees, feature_k (None = all features), bootstrap, subsample,
    seed); every (grid point, fold) pair becomes one ELEMENT of the
    trial-batched device program (`tree_impl.fit_ensembles_trials`),
    dispatched in chunks of `max_fused` elements — a G-point grid over k
    folds costs ceil(G*k / max_fused) tree-fit dispatches instead of G.

    Static shapes are the grid MAXIMA (depth/bins/trees), so the whole
    grid shares ONE compiled program per chunk width; each element gates
    itself down to its own hyperparameters with traced scalars, and its
    extra trees/nodes are sliced away host-side. Binning stays per
    (fold, maxBins): a grid over maxBins legitimately re-quantizes,
    everything else reuses the fold's cached matrices.

    On a multi-device mesh the fused elements may SHARD across a second
    "trial" mesh axis instead of all-replicating (cross-chip trial
    parallelism — `sml.cv.trialAxisDevices` /
    `tree_impl._trial_axis_width` decide placement inside
    `fit_ensembles_trials`); dispatch counts and results are unchanged
    up to float reduction order.

    Returns {(grid_index, fold_index): _EnsembleSpec}."""
    import jax

    from ..parallel import mesh as _meshlib
    from ._staging import routed_for

    F = Xs[0].shape[1]
    k = len(Xs)
    y32s = [np.asarray(y, np.float32) for y in ys]
    binned: Dict[tuple, np.ndarray] = {}
    binnings: Dict[tuple, object] = {}
    for mb in sorted({t["max_bins"] for t in trials}):
        for fi, (X, y32) in enumerate(zip(Xs, y32s)):
            b, bn = _cached_bins(X, y32, mb, cats)
            binned[(fi, mb)] = b
            binnings[(fi, mb)] = bn
    D = max(t["max_depth"] for t in trials)
    B = max(t["max_bins"] for t in trials)
    T = max(t["n_trees"] for t in trials)
    mesh = _meshlib.get_mesh()
    n_dev = _meshlib.data_width(mesh)
    n_pad = max(_meshlib.bucket_rows(b.shape[0], n_dev)
                for b in binned.values())
    stack_dtype = np.result_type(*[b.dtype for b in binned.values()])
    spec = TreeSpec(max_depth=D, n_bins=B, n_features=F, feature_k=F,
                    min_instances=1, min_info_gain=0.0, reg_lambda=0.0,
                    gamma=0.0)
    es = tree_impl.EnsembleSpec(tree=spec, n_trees=T, loss=loss,
                                boosting=False, bootstrap=False,
                                subsample=1.0, step_size=0.1)
    elems = [(gi, fi) for gi in range(len(trials)) for fi in range(k)]
    mode = "binary" if loss == "logistic" else "regression"
    out: Dict[tuple, _EnsembleSpec] = {}
    max_fused = max(1, int(max_fused))
    for lo in range(0, len(elems), max_fused):
        chunk = elems[lo:lo + max_fused]
        E = len(chunk)
        bst = np.zeros((E, n_pad, F), dtype=stack_dtype)
        yst = np.zeros((E, n_pad), dtype=np.float32)
        mst = np.zeros((E, n_pad), dtype=np.float32)
        depth = np.zeros(E, np.int32)
        feat_k = np.zeros(E, np.int32)
        min_inst = np.zeros(E, np.float32)
        min_gain = np.zeros(E, np.float32)
        boot = np.zeros(E, bool)
        sub = np.ones(E, np.float32)
        rngs = np.zeros((E, 2), np.uint32)
        n_rows = 0
        for e, (gi, fi) in enumerate(chunk):
            t = trials[gi]
            b = binned[(fi, t["max_bins"])]
            bst[e, :b.shape[0]] = b
            yst[e, :len(y32s[fi])] = y32s[fi]
            mst[e, :len(y32s[fi])] = 1.0
            n_rows += b.shape[0]
            depth[e] = t["max_depth"]
            feat_k[e] = t["feature_k"] or F
            min_inst[e] = t["min_instances"]
            min_gain[e] = t["min_info_gain"]
            boot[e] = bool(t["bootstrap"]) and t["n_trees"] > 1
            sub[e] = t["subsample"]
            rngs[e] = np.asarray(
                jax.random.key_data(jax.random.PRNGKey(int(t["seed"]))),
                np.uint32)
        hint = dispatch.WorkHint(
            flops=2.0 * T * D * n_rows * F * B, kind="scatter")
        with routed_for(hint, bst, yst, mst, stacked=True):
            packs, _bases = tree_impl.fit_ensembles_trials(
                bst, yst, mst, es, rngs, depth, feat_k, min_inst,
                min_gain, boot, sub)
        for e, (gi, fi) in enumerate(chunk):
            t = trials[gi]
            trees = tree_impl._unpack_trees(packs[e][:t["n_trees"]])
            out[(gi, fi)] = _EnsembleSpec(
                trees, int(t["max_depth"]),
                binnings[(fi, t["max_bins"])], None, 0.0, F, mode)
    return out


# ---------------------------------------------------------------------------
class _TreeModelBase(Model, _TreeParams):
    """Shared transform/persistence for tree ensemble models."""

    def __init__(self, spec: Optional[_EnsembleSpec] = None):
        super().__init__()
        self._spec = spec

    @property
    def featureImportances(self) -> DenseVector:
        return DenseVector(feature_importances(self._spec.trees,
                                               self._spec.n_features))

    @property
    def numFeatures(self) -> int:
        return self._spec.n_features

    def getNumTrees(self) -> int:
        return len(self._spec.trees)

    @property
    def treeWeights(self) -> List[float]:
        if self._spec.tree_weights is None:
            return [1.0] * len(self._spec.trees)
        return [float(w) for w in self._spec.tree_weights]

    @property
    def toDebugString(self) -> str:
        lines = [f"{type(self).__name__} with {len(self._spec.trees)} trees, "
                 f"depth {self._spec.depth}"]
        t0 = self._spec.trees[0]
        for node in range(min(len(t0.split_feature), 15)):
            f = int(t0.split_feature[node])
            if f >= 0:
                lines.append(f"  node {node}: split feature {f} "
                             f"@bin {int(t0.split_bin[node])} "
                             f"gain {float(t0.gain[node]):.4f}")
            else:
                lines.append(f"  node {node}: leaf "
                             f"value {float(t0.leaf_value[node]):.4f}")
        return "\n".join(lines)

    def _margin(self, pdf: pd.DataFrame) -> np.ndarray:
        X = extract_features(pdf, self.getOrDefault("featuresCol"))
        return self._spec.predict_margin(X)

    def _save_state(self, path):
        self._spec.save(path)

    def _load_state(self, path, meta):
        self._spec = _EnsembleSpec.load(path)


def fused_reg_stats_from_matrix(spec, X: np.ndarray, lab: np.ndarray,
                                link: str = "identity"):
    """The fused traverse+metric device pass over a raw feature matrix:
    bins (content-memoized), routes, and — on the device route — returns
    the five regression sufficient statistics from ONE program dispatch
    (D2H is five scalars). Returns None on the host route or any surprise;
    callers then take the ordinary predict+stats path. Shared by the bare
    tree-model hook and the fused-pipeline hook."""
    if spec.mode != "regression":
        return None
    if link != "identity":
        import jax.numpy as _jnp
        if getattr(_jnp, link, None) is None:
            return None  # unresolvable device link: materialize path wins
    from ..utils.profiler import PROFILER
    with PROFILER.span("binning.predict", rows=int(X.shape[0])):
        binned = bin_with(np.asarray(X, dtype=np.float64), spec.binning)
    n = binned.shape[0]
    if n != len(lab):
        return None
    finite = np.isfinite(lab)
    l32 = np.where(finite, lab, 0.0).astype(np.float32)
    f32 = finite.astype(np.float32)
    # compact quantized dtype preserved: the eval program shares the fit's
    # bin-cache device copy instead of staging an int32 duplicate
    binned_q = np.ascontiguousarray(binned)
    hint = dispatch.WorkHint(
        flops=(4.0 * len(spec.trees) * spec.depth + 10.0) * n,
        kind="traverse", out_bytes=64.0)
    from ._staging import routed_for, run_data_parallel
    with routed_for(hint, binned_q, l32, f32) as mesh:
        if dispatch.is_host_mesh(mesh):
            return None  # host route: ordinary path is cheaper
        from .inference import forest_eval_fn, resolve_infer_kernel
        sf, sb, lv, w = spec.stacked()
        kernel, block_rows, _ = resolve_infer_kernel(
            n_trees=sf.shape[0], depth=spec.depth, n_nodes=sf.shape[1],
            n_feat=binned_q.shape[1],
            n_bins=spec.binning.edges.shape[1] + 1, n_rows=n)
        stats = run_data_parallel(
            forest_eval_fn(spec.depth, link, kernel, block_rows),
            binned_q, l32, f32,
            replicated=(np.asarray(sf), np.asarray(sb),
                        np.asarray(lv, dtype=np.float32),
                        np.asarray(w, dtype=np.float32),
                        np.float32(spec.base)))
    return tuple(float(s) for s in stats)


class _TreeEvalHook(RegStatsHook):
    """Evaluator pushdown for lazy BARE tree-regression transforms (the
    CV/tuning shape: model.transform(featurized_frame)): the whole
    predict+metric computes as ONE device program
    (`inference.forest_eval_fn`) returning five scalars, instead of
    materializing a prediction column (host traversal or a 3.2MB/800k-row
    D2H) and re-uploading pred/label for the stats pass."""

    def _compute(self, raw, lab, label_col: str):
        model = self._tail
        X = extract_features(raw, model.getOrDefault("featuresCol"))
        return fused_reg_stats_from_matrix(model._spec, X, lab,
                                           link=self._link)


class _TreeRegressionModel(_TreeModelBase):
    def _transform(self, df):
        oc = self.getOrDefault("predictionCol")

        def fn(pdf, ctx):
            out = pdf.copy(deep=False)  # CoW: column adds never touch the parent
            if len(out) == 0:
                out[oc] = pd.Series(dtype=float)
                return out
            out[oc] = self._margin(out)
            return out

        out = df._derive_rowlocal(fn)
        out._fused_eval = _TreeEvalHook(self, df)
        return out


class _TreeClassificationModel(_TreeModelBase):
    def _transform(self, df):
        oc = self.getOrDefault("predictionCol")
        rc = self.getOrDefault("rawPredictionCol")
        prc = self.getOrDefault("probabilityCol")

        def fn(pdf, ctx):
            out = pdf.copy(deep=False)  # CoW: column adds never touch the parent
            if len(out) == 0:
                for c in (rc, prc):
                    out[c] = pd.Series(dtype=object)
                out[oc] = pd.Series(dtype=float)
                return out
            m = self._margin(out)
            if self._spec.tree_weights is None:  # forest of probability leaves
                p1 = np.clip(m, 0.0, 1.0)
            else:  # boosted margins
                p1 = 1.0 / (1.0 + np.exp(-m))
            probs = np.stack([1 - p1, p1], axis=1)
            out[rc] = vector_series(probs, index=out.index)
            out[prc] = vector_series(probs.copy(), index=out.index)
            out[oc] = (p1 > 0.5).astype(float)
            return out

        return df._derive_rowlocal(fn)


# ------------------------------------------------------------ estimators
class _TreeEstimatorBase(Estimator, _TreeParams):
    _is_classifier = False
    _loss = "squared"

    def _extract(self, df):
        X, y, _ = extract_xy(df, self.getOrDefault("featuresCol"),
                             self.getOrDefault("labelCol"))
        ok = np.isfinite(y)
        return X[ok], y[ok], _categorical_slots(df, self.getOrDefault("featuresCol"))

    def _seed(self) -> int:
        s = self.getOrDefault("seed")
        return int(s) if s is not None else 17

    def fit_chunked(self, source):
        """Out-of-core fit: the same estimator params applied to a
        `frame._chunks.ChunkSource` through the streamed-quantization
        ingest (`ml/_chunked.py`) — the raw dataset is never resident
        whole. Returns the same model class `.fit` would (DT/RF/GBT,
        regressor/classifier); an exact-mode sketch makes the model
        bit-identical to fitting the materialized frame."""
        from ._chunked import fit_ensemble_chunked
        kwargs = dict(
            categorical={},
            max_depth=int(self.getOrDefault("maxDepth")),
            max_bins=int(self.getOrDefault("maxBins")),
            min_instances=int(self.getOrDefault("minInstancesPerNode")),
            min_info_gain=float(self.getOrDefault("minInfoGain")),
            seed=self._seed(),
            loss="logistic" if self._is_classifier else "squared")
        if self.hasParam("maxIter"):        # boosted (GBT) shape
            kwargs.update(
                n_trees=int(self.getOrDefault("maxIter")), feature_k=None,
                bootstrap=False,
                subsample=float(self.getOrDefault("subsamplingRate")),
                step_size=float(self.getOrDefault("stepSize")),
                boosting=True)
        elif self.hasParam("numTrees"):     # bootstrap-forest shape
            kwargs.update(
                n_trees=int(self.getOrDefault("numTrees")),
                feature_k=_feature_k(
                    self.getOrDefault("featureSubsetStrategy"),
                    source.n_features, self._is_classifier),
                bootstrap=True,
                subsample=float(self.getOrDefault("subsamplingRate")))
        else:                               # single decision tree
            kwargs.update(n_trees=1, feature_k=None, bootstrap=False,
                          subsample=1.0)
        spec = fit_ensemble_chunked(source, **kwargs)
        cls = getattr(self, "_model_cls", None) \
            or _CHUNKED_MODEL_FOR[type(self).__name__]
        m = cls(spec)
        m._inherit_params(self)
        return m


class DecisionTreeRegressor(_TreeEstimatorBase):
    def _init_params(self):
        self._declare_tree_params()

    def __init__(self, featuresCol=None, labelCol=None, predictionCol=None,
                 maxDepth=None, maxBins=None, minInstancesPerNode=None,
                 minInfoGain=None, seed=None):
        super().__init__()
        self._set(featuresCol=featuresCol, labelCol=labelCol,
                  predictionCol=predictionCol, maxDepth=maxDepth,
                  maxBins=maxBins, minInstancesPerNode=minInstancesPerNode,
                  minInfoGain=minInfoGain, seed=seed)

    def setMaxBins(self, v):
        return self._set(maxBins=v)

    def setMaxDepth(self, v):
        return self._set(maxDepth=v)

    def _fit(self, df):
        X, y, cat = self._extract(df)
        spec = _fit_ensemble(
            X, y, categorical=cat,
            max_depth=int(self.getOrDefault("maxDepth")),
            max_bins=int(self.getOrDefault("maxBins")),
            min_instances=int(self.getOrDefault("minInstancesPerNode")),
            min_info_gain=float(self.getOrDefault("minInfoGain")),
            n_trees=1, feature_k=None, bootstrap=False, subsample=1.0,
            seed=self._seed(), loss="squared")
        m = DecisionTreeRegressionModel(spec)
        m._inherit_params(self)
        return m


class DecisionTreeRegressionModel(_TreeRegressionModel):
    def _init_params(self):
        DecisionTreeRegressor._init_params(self)

    @property
    def depth(self) -> int:
        return self._spec.depth


class DecisionTreeClassifier(_TreeEstimatorBase):
    _is_classifier = True

    def _init_params(self):
        self._declare_tree_params()
        self._declareParam("rawPredictionCol", default="rawPrediction", doc="raw scores")
        self._declareParam("probabilityCol", default="probability", doc="probabilities")

    def __init__(self, featuresCol=None, labelCol=None, predictionCol=None,
                 maxDepth=None, maxBins=None, minInstancesPerNode=None,
                 minInfoGain=None, seed=None):
        super().__init__()
        self._set(featuresCol=featuresCol, labelCol=labelCol,
                  predictionCol=predictionCol, maxDepth=maxDepth,
                  maxBins=maxBins, minInstancesPerNode=minInstancesPerNode,
                  minInfoGain=minInfoGain, seed=seed)

    def setMaxBins(self, v):
        return self._set(maxBins=v)

    def _fit(self, df):
        X, y, cat = self._extract(df)
        spec = _fit_ensemble(
            X, y, categorical=cat,
            max_depth=int(self.getOrDefault("maxDepth")),
            max_bins=int(self.getOrDefault("maxBins")),
            min_instances=int(self.getOrDefault("minInstancesPerNode")),
            min_info_gain=float(self.getOrDefault("minInfoGain")),
            n_trees=1, feature_k=None, bootstrap=False, subsample=1.0,
            seed=self._seed(), loss="logistic")
        m = DecisionTreeClassificationModel(spec)
        m._inherit_params(self)
        return m


class DecisionTreeClassificationModel(_TreeClassificationModel):
    def _init_params(self):
        DecisionTreeClassifier._init_params(self)


class RandomForestRegressor(_TreeEstimatorBase):
    def _init_params(self):
        self._declare_tree_params()
        self._declareParam("numTrees", default=20, doc="number of trees")
        self._declareParam("featureSubsetStrategy", default="auto",
                           doc="auto|all|sqrt|log2|onethird|fraction")
        self._declareParam("subsamplingRate", default=1.0, doc="bootstrap rate")

    def __init__(self, featuresCol=None, labelCol=None, predictionCol=None,
                 maxDepth=None, maxBins=None, numTrees=None,
                 featureSubsetStrategy=None, subsamplingRate=None,
                 minInstancesPerNode=None, minInfoGain=None, seed=None):
        super().__init__()
        self._set(featuresCol=featuresCol, labelCol=labelCol,
                  predictionCol=predictionCol, maxDepth=maxDepth,
                  maxBins=maxBins, numTrees=numTrees,
                  featureSubsetStrategy=featureSubsetStrategy,
                  subsamplingRate=subsamplingRate,
                  minInstancesPerNode=minInstancesPerNode,
                  minInfoGain=minInfoGain, seed=seed)

    def setMaxBins(self, v):
        return self._set(maxBins=v)

    def _fit(self, df):
        X, y, cat = self._extract(df)
        F = X.shape[1]
        spec = _fit_ensemble(
            X, y, categorical=cat,
            max_depth=int(self.getOrDefault("maxDepth")),
            max_bins=int(self.getOrDefault("maxBins")),
            min_instances=int(self.getOrDefault("minInstancesPerNode")),
            min_info_gain=float(self.getOrDefault("minInfoGain")),
            n_trees=int(self.getOrDefault("numTrees")),
            feature_k=_feature_k(self.getOrDefault("featureSubsetStrategy"),
                                 F, self._is_classifier),
            bootstrap=True,
            subsample=float(self.getOrDefault("subsamplingRate")),
            seed=self._seed(), loss="squared")
        m = RandomForestRegressionModel(spec)
        m._inherit_params(self)
        return m


class RandomForestRegressionModel(_TreeRegressionModel):
    def _init_params(self):
        RandomForestRegressor._init_params(self)


class RandomForestClassifier(RandomForestRegressor):
    _is_classifier = True

    def _init_params(self):
        RandomForestRegressor._init_params(self)
        self._declareParam("rawPredictionCol", default="rawPrediction", doc="raw scores")
        self._declareParam("probabilityCol", default="probability", doc="probabilities")

    def _fit(self, df):
        X, y, cat = self._extract(df)
        F = X.shape[1]
        spec = _fit_ensemble(
            X, y, categorical=cat,
            max_depth=int(self.getOrDefault("maxDepth")),
            max_bins=int(self.getOrDefault("maxBins")),
            min_instances=int(self.getOrDefault("minInstancesPerNode")),
            min_info_gain=float(self.getOrDefault("minInfoGain")),
            n_trees=int(self.getOrDefault("numTrees")),
            feature_k=_feature_k(self.getOrDefault("featureSubsetStrategy"),
                                 F, True),
            bootstrap=True,
            subsample=float(self.getOrDefault("subsamplingRate")),
            seed=self._seed(), loss="logistic")
        m = RandomForestClassificationModel(spec)
        m._inherit_params(self)
        return m


class RandomForestClassificationModel(_TreeClassificationModel):
    def _init_params(self):
        RandomForestClassifier._init_params(self)


class GBTRegressor(_TreeEstimatorBase):
    def _init_params(self):
        self._declare_tree_params()
        self._declareParam("maxIter", default=20, doc="boosting rounds")
        self._declareParam("stepSize", default=0.1, doc="learning rate")
        self._declareParam("subsamplingRate", default=1.0, doc="row subsample per round")

    def __init__(self, featuresCol=None, labelCol=None, predictionCol=None,
                 maxDepth=None, maxBins=None, maxIter=None, stepSize=None,
                 subsamplingRate=None, minInstancesPerNode=None,
                 minInfoGain=None, seed=None):
        super().__init__()
        self._set(featuresCol=featuresCol, labelCol=labelCol,
                  predictionCol=predictionCol, maxDepth=maxDepth,
                  maxBins=maxBins, maxIter=maxIter, stepSize=stepSize,
                  subsamplingRate=subsamplingRate,
                  minInstancesPerNode=minInstancesPerNode,
                  minInfoGain=minInfoGain, seed=seed)

    _loss = "squared"
    _model_cls = None  # set below

    def _fit(self, df):
        X, y, cat = self._extract(df)
        spec = _fit_ensemble(
            X, y, categorical=cat,
            max_depth=int(self.getOrDefault("maxDepth")),
            max_bins=int(self.getOrDefault("maxBins")),
            min_instances=int(self.getOrDefault("minInstancesPerNode")),
            min_info_gain=float(self.getOrDefault("minInfoGain")),
            n_trees=int(self.getOrDefault("maxIter")), feature_k=None,
            bootstrap=False,
            subsample=float(self.getOrDefault("subsamplingRate")),
            seed=self._seed(), loss=self._loss,
            step_size=float(self.getOrDefault("stepSize")), boosting=True)
        m = self._model_cls(spec)
        m._inherit_params(self)
        return m


class GBTRegressionModel(_TreeRegressionModel):
    def _init_params(self):
        GBTRegressor._init_params(self)


GBTRegressor._model_cls = GBTRegressionModel


class GBTClassifier(GBTRegressor):
    _is_classifier = True
    _loss = "logistic"

    def _init_params(self):
        GBTRegressor._init_params(self)
        self._declareParam("rawPredictionCol", default="rawPrediction", doc="raw scores")
        self._declareParam("probabilityCol", default="probability", doc="probabilities")


class GBTClassificationModel(_TreeClassificationModel):
    def _init_params(self):
        GBTClassifier._init_params(self)


GBTClassifier._model_cls = GBTClassificationModel

#: estimator -> model class for `_TreeEstimatorBase.fit_chunked` (the
#: DT/RF classes construct their models inline in `_fit`; GBT's
#: `_model_cls` attribute wins when present)
_CHUNKED_MODEL_FOR = {
    "DecisionTreeRegressor": DecisionTreeRegressionModel,
    "DecisionTreeClassifier": DecisionTreeClassificationModel,
    "RandomForestRegressor": RandomForestRegressionModel,
    "RandomForestClassifier": RandomForestClassificationModel,
}
