"""Host → HBM staging for estimator math.

Every distributed fit in this package has the same shape (SURVEY §3.1's TPU
mapping): pull the assembled feature column + label out of the host frame,
densify to (n, d) float arrays, zero-pad rows to a per-chip-equal block,
`jax.device_put` sharded over the mesh's data axis, and run a jitted
`shard_map` program whose cross-chip reductions are `psum` over ICI — the
replacement for Spark's executor→driver `treeAggregate`
(`SML/Labs/ML 02L - Linear Regression I Lab.py:70-77`).
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Optional, Tuple

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P
try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map

from ..parallel import mesh as meshlib
from .linalg import Vector, VectorArray, to_matrix


def extract_features(df, featuresCol: str) -> np.ndarray:
    """(n, d) float32 matrix from a vector/array column of a host frame.

    Columnar `VectorArray` columns (VectorAssembler/OHE output) hand over
    their backing (n, d) block directly — no per-row objects on the staging
    path (VERDICT r1 weak #3)."""
    pdf = df.toPandas() if hasattr(df, "toPandas") else df
    col = pdf[featuresCol]
    if isinstance(getattr(col, "array", None), VectorArray):
        return np.ascontiguousarray(to_matrix(col), dtype=np.float32)
    vals = col.tolist()
    if vals and isinstance(vals[0], (Vector, list, tuple, np.ndarray)):
        X = to_matrix(vals)
    else:  # single numeric column used as a 1-feature matrix
        X = np.asarray(col, dtype=np.float64)[:, None]
    return np.ascontiguousarray(X, dtype=np.float32)


def extract_xy(df, featuresCol: str, labelCol: str,
               weightCol: Optional[str] = None) -> Tuple[np.ndarray, np.ndarray, Optional[np.ndarray]]:
    pdf = df.toPandas() if hasattr(df, "toPandas") else df
    X = extract_features(pdf, featuresCol)
    y = np.asarray(pdf[labelCol], dtype=np.float32)
    w = np.asarray(pdf[weightCol], dtype=np.float32) if weightCol else None
    return X, y, w


_stage_cache: "dict" = {}
_stage_cache_order: list = []
_STAGE_CACHE_MAX = 48


def _content_key(a: np.ndarray) -> tuple:
    """Cheap content fingerprint for the staging cache: shape, dtype, and a
    hash of the bytes. Hashing ~4MB costs ~1ms; re-staging through the
    device tunnel costs two orders of magnitude more."""
    a = np.ascontiguousarray(a)
    return (a.shape, str(a.dtype), hash(a.tobytes()))


def _cache_put(key, value):
    if key in _stage_cache:
        return
    _stage_cache[key] = value
    _stage_cache_order.append(key)
    while len(_stage_cache_order) > _STAGE_CACHE_MAX:
        old = _stage_cache_order.pop(0)
        _stage_cache.pop(old, None)


def stage_rows_cached(a: np.ndarray, pad_to_multiple: bool = True) -> jax.Array:
    """device_put a row-sharded array through the content cache."""
    mesh = meshlib.get_mesh()
    n_dev = mesh.shape[meshlib.DATA_AXIS]
    a = np.asarray(a)
    key = (_content_key(a), id(mesh), "arr", n_dev)
    hit = _stage_cache.get(key)
    if hit is None:
        padded = meshlib.pad_rows(a, n_dev)[0] if pad_to_multiple else a
        hit = jax.device_put(padded, meshlib.data_sharding(mesh, padded.ndim))
        _cache_put(key, hit)
    return hit


def stage_mask_cached(n_padded: int, n_true: int) -> jax.Array:
    mesh = meshlib.get_mesh()
    mkey = (n_padded, n_true, id(mesh), "mask", mesh.shape[meshlib.DATA_AXIS])
    mask_dev = _stage_cache.get(mkey)
    if mask_dev is None:
        mask = meshlib.row_mask(n_padded, n_true)
        mask_dev = jax.device_put(mask, meshlib.data_sharding(mesh, 1))
        _cache_put(mkey, mask_dev)
    return mask_dev


def stage_sharded(*arrays: np.ndarray):
    """Pad + shard host arrays by rows over the data axis.

    Returns (device_arrays..., mask_device, n_true). The mask is 1.0 for real
    rows, 0.0 for padding; all statistics must be mask-weighted so padding is
    inert under psum.

    Results are memoized by content: CV folds, hyperopt trials, and repeated
    fits re-stage identical arrays constantly, and each fresh H2D through
    the device tunnel pays a fixed sync penalty at first use.
    """
    n_true = arrays[0].shape[0]
    outs = [stage_rows_cached(a) for a in arrays]
    n_padded = outs[0].shape[0]
    mask_dev = stage_mask_cached(n_padded, n_true)
    return (*outs, mask_dev, n_true)


def data_parallel(fn: Callable, *, out_replicated: bool = True,
                  replicated_argnums: Tuple[int, ...] = ()) -> Callable:
    """jit(shard_map(fn)) over the active mesh's data axis.

    `fn` sees per-chip row blocks and may call `parallel.collectives.psum`
    etc. on the "data" axis; outputs are replicated (each chip returns the
    same reduced value) unless out_replicated=False (then row-sharded).
    Args listed in `replicated_argnums` (rng keys, small parameter vectors)
    are broadcast to every chip instead of row-sharded.
    """
    mesh = meshlib.get_mesh()
    out_spec = P() if out_replicated else P(meshlib.DATA_AXIS)

    def spec_for(i, x):
        if i in replicated_argnums:
            return P()
        return P(*([meshlib.DATA_AXIS] + [None] * (np.ndim(x) - 1)))

    def wrapped(*args):
        specs = tuple(spec_for(i, a) for i, a in enumerate(args))
        mapped = shard_map(fn, mesh=mesh, in_specs=specs,
                           out_specs=out_spec, check_vma=False)
        return mapped(*args)

    return jax.jit(wrapped)


_compiled_cache: dict = {}


def cached_data_parallel(fn: Callable, *, out_replicated: bool = True,
                         replicated_argnums: Tuple[int, ...] = ()) -> Callable:
    """data_parallel with a program cache keyed by (fn, mesh, flags).

    jax.jit caches per function object; wrapping a fresh closure per fit
    would recompile every call. Callers must pass module-level fns (stable
    identity) for the cache to hit.
    """
    mesh = meshlib.get_mesh()
    key = (fn, id(mesh), out_replicated, replicated_argnums)
    if key not in _compiled_cache:
        _compiled_cache[key] = data_parallel(
            fn, out_replicated=out_replicated,
            replicated_argnums=replicated_argnums)
    return _compiled_cache[key]


def run_data_parallel(fn: Callable, *arrays, out_replicated: bool = True,
                      replicated: Tuple = ()):
    """One-shot: stage arrays sharded, run fn(blocks..., mask, *replicated)
    under jit+shard_map, return host numpy results. `replicated` values are
    broadcast to all chips (small parameter vectors)."""
    staged = stage_sharded(*arrays)
    dev_args, mask, _ = staged[:-2], staged[-2], staged[-1]
    n_lead = len(dev_args) + 1
    rep_nums = tuple(range(n_lead, n_lead + len(replicated)))
    compiled = cached_data_parallel(fn, out_replicated=out_replicated,
                                    replicated_argnums=rep_nums)
    out = compiled(*dev_args, mask, *replicated)
    # ONE batched device→host transfer for the whole output tree: per-leaf
    # np.asarray pays the tunnel's fixed D2H latency once PER ARRAY, which
    # dominated r1's per-fit wall-clock on the real chip
    return jax.device_get(out)
