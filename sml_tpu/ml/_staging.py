"""Host → HBM staging for estimator math.

Every distributed fit in this package has the same shape (SURVEY §3.1's TPU
mapping): pull the assembled feature column + label out of the host frame,
densify to (n, d) float arrays, zero-pad rows to a per-chip-equal block,
`jax.device_put` sharded over the mesh's data axis, and run a jitted
`shard_map` program whose cross-chip reductions are `psum` over ICI — the
replacement for Spark's executor→driver `treeAggregate`
(`SML/Labs/ML 02L - Linear Regression I Lab.py:70-77`).
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Optional, Tuple

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P
try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map

from ..parallel import mesh as meshlib
from .linalg import Vector, VectorArray, to_matrix


def extract_features(df, featuresCol: str) -> np.ndarray:
    """(n, d) float32 matrix from a vector/array column of a host frame.

    Columnar `VectorArray` columns (VectorAssembler/OHE output) hand over
    their backing (n, d) block directly — no per-row objects on the staging
    path (VERDICT r1 weak #3)."""
    pdf = df.toPandas() if hasattr(df, "toPandas") else df
    col = pdf[featuresCol]
    if isinstance(getattr(col, "array", None), VectorArray):
        return np.ascontiguousarray(to_matrix(col), dtype=np.float32)
    vals = col.tolist()
    if vals and isinstance(vals[0], (Vector, list, tuple, np.ndarray)):
        X = to_matrix(vals)
    else:  # single numeric column used as a 1-feature matrix
        X = np.asarray(col, dtype=np.float64)[:, None]
    return np.ascontiguousarray(X, dtype=np.float32)


def extract_xy(df, featuresCol: str, labelCol: str,
               weightCol: Optional[str] = None) -> Tuple[np.ndarray, np.ndarray, Optional[np.ndarray]]:
    pdf = df.toPandas() if hasattr(df, "toPandas") else df
    X = extract_features(pdf, featuresCol)
    y = np.asarray(pdf[labelCol], dtype=np.float32)
    w = np.asarray(pdf[weightCol], dtype=np.float32) if weightCol else None
    return X, y, w


def stage_sharded(*arrays: np.ndarray):
    """Pad + shard host arrays by rows over the data axis.

    Returns (device_arrays..., mask_device, n_true). The mask is 1.0 for real
    rows, 0.0 for padding; all statistics must be mask-weighted so padding is
    inert under psum.
    """
    mesh = meshlib.get_mesh()
    n_dev = mesh.shape[meshlib.DATA_AXIS]
    n_true = arrays[0].shape[0]
    outs = []
    for a in arrays:
        padded, _ = meshlib.pad_rows(np.asarray(a), n_dev)
        outs.append(jax.device_put(padded, meshlib.data_sharding(mesh, padded.ndim)))
    n_padded = outs[0].shape[0]
    mask = meshlib.row_mask(n_padded, n_true)
    mask_dev = jax.device_put(mask, meshlib.data_sharding(mesh, 1))
    return (*outs, mask_dev, n_true)


def data_parallel(fn: Callable, *, out_replicated: bool = True,
                  replicated_argnums: Tuple[int, ...] = ()) -> Callable:
    """jit(shard_map(fn)) over the active mesh's data axis.

    `fn` sees per-chip row blocks and may call `parallel.collectives.psum`
    etc. on the "data" axis; outputs are replicated (each chip returns the
    same reduced value) unless out_replicated=False (then row-sharded).
    Args listed in `replicated_argnums` (rng keys, small parameter vectors)
    are broadcast to every chip instead of row-sharded.
    """
    mesh = meshlib.get_mesh()
    out_spec = P() if out_replicated else P(meshlib.DATA_AXIS)

    def spec_for(i, x):
        if i in replicated_argnums:
            return P()
        return P(*([meshlib.DATA_AXIS] + [None] * (np.ndim(x) - 1)))

    def wrapped(*args):
        specs = tuple(spec_for(i, a) for i, a in enumerate(args))
        mapped = shard_map(fn, mesh=mesh, in_specs=specs,
                           out_specs=out_spec, check_vma=False)
        return mapped(*args)

    return jax.jit(wrapped)


_compiled_cache: dict = {}


def cached_data_parallel(fn: Callable, *, out_replicated: bool = True,
                         replicated_argnums: Tuple[int, ...] = ()) -> Callable:
    """data_parallel with a program cache keyed by (fn, mesh, flags).

    jax.jit caches per function object; wrapping a fresh closure per fit
    would recompile every call. Callers must pass module-level fns (stable
    identity) for the cache to hit.
    """
    mesh = meshlib.get_mesh()
    key = (fn, id(mesh), out_replicated, replicated_argnums)
    if key not in _compiled_cache:
        _compiled_cache[key] = data_parallel(
            fn, out_replicated=out_replicated,
            replicated_argnums=replicated_argnums)
    return _compiled_cache[key]


def run_data_parallel(fn: Callable, *arrays, out_replicated: bool = True,
                      replicated: Tuple = ()):
    """One-shot: stage arrays sharded, run fn(blocks..., mask, *replicated)
    under jit+shard_map, return host numpy results. `replicated` values are
    broadcast to all chips (small parameter vectors)."""
    staged = stage_sharded(*arrays)
    dev_args, mask, _ = staged[:-2], staged[-2], staged[-1]
    n_lead = len(dev_args) + 1
    rep_nums = tuple(range(n_lead, n_lead + len(replicated)))
    compiled = cached_data_parallel(fn, out_replicated=out_replicated,
                                    replicated_argnums=rep_nums)
    out = compiled(*dev_args, mask, *replicated)
    return jax.tree_util.tree_map(np.asarray, out)
