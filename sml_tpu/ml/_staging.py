"""Host → HBM staging for estimator math.

Every distributed fit in this package has the same shape (SURVEY §3.1's TPU
mapping): pull the assembled feature column + label out of the host frame,
densify to (n, d) float arrays, zero-pad rows to a per-chip-equal block,
`jax.device_put` sharded over the mesh's data axis, and run a jitted
`shard_map` program whose cross-chip reductions are `psum` over ICI — the
replacement for Spark's executor→driver `treeAggregate`
(`SML/Labs/ML 02L - Linear Regression I Lab.py:70-77`).
"""

from __future__ import annotations

import contextlib
from functools import partial
from typing import Callable, Optional, Tuple

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..parallel import dispatch
from ..parallel import mesh as meshlib
from .linalg import Vector, VectorArray, to_matrix


def extract_features(df, featuresCol: str) -> np.ndarray:
    """(n, d) float32 matrix from a vector/array column of a host frame.

    Columnar `VectorArray` columns (VectorAssembler/OHE output) hand over
    their backing (n, d) block directly — no per-row objects on the staging
    path (VERDICT r1 weak #3). A frame carrying a `_featurized` fast-path
    block (attached by Pipeline's fused fit, see base.Pipeline._fit) hands
    that over WITHOUT materializing its lazy transform chain at all."""
    feat = getattr(df, "_featurized", None)
    if feat is not None and featuresCol in feat:
        return feat[featuresCol][0]
    pdf = df.toPandas() if hasattr(df, "toPandas") else df
    col = pdf[featuresCol]
    if isinstance(getattr(col, "array", None), VectorArray):
        return np.ascontiguousarray(to_matrix(col), dtype=np.float32)
    vals = col.tolist()
    if vals and isinstance(vals[0], (Vector, list, tuple, np.ndarray)):
        X = to_matrix(vals)
    else:  # single numeric column used as a 1-feature matrix
        X = np.asarray(col, dtype=np.float64)[:, None]
    return np.ascontiguousarray(X, dtype=np.float32)


def extract_xy(df, featuresCol: str, labelCol: str,
               weightCol: Optional[str] = None) -> Tuple[np.ndarray, np.ndarray, Optional[np.ndarray]]:
    feat = getattr(df, "_featurized", None)
    if feat is not None and featuresCol in feat:
        # fused-fit fast path: X was assembled in one columnar pass over
        # the RAW frame; labels come from the same raw pandas (with the
        # featurizer's row-drop mask applied) — the lazy transform chain
        # never materializes
        X, keep, raw_pdf = feat[featuresCol]
        y = np.asarray(raw_pdf[labelCol], dtype=np.float32)
        w = np.asarray(raw_pdf[weightCol], dtype=np.float32) if weightCol \
            else None
        if keep is not None:
            y = y[keep]
            w = w[keep] if w is not None else None
        return X, y, w
    pdf = df.toPandas() if hasattr(df, "toPandas") else df
    X = extract_features(pdf, featuresCol)
    y = np.asarray(pdf[labelCol], dtype=np.float32)
    w = np.asarray(pdf[weightCol], dtype=np.float32) if weightCol else None
    return X, y, w


def extract_compact(df, featuresCol: str, labelCol: str):
    """(CompactParts, y) when the frame carries a compact featurized block
    (attached by base.Pipeline's fused fit for huge linear fits — see
    featurizer.CompactParts), else None. Labels come from the raw pandas
    with the featurizer's row-drop mask and the finite-label filter
    applied to BOTH sides, matching extract_xy's semantics."""
    feat = getattr(df, "_featurized_compact", None)
    if feat is None or featuresCol not in feat:
        return None
    parts, raw_pdf = feat[featuresCol]
    y = np.asarray(raw_pdf[labelCol], dtype=np.float32)
    if parts.keep is not None:
        y = y[parts.keep]
    ok = np.isfinite(y)
    if not ok.all():
        # compose the raw-row mask so parts.keep keeps describing the
        # surviving rows of the RAW frame (its documented contract)
        if parts.keep is not None:
            keep = parts.keep.copy()
            keep[keep] = ok
        else:
            keep = ok
        parts = parts._replace(num=parts.num[ok], codes=parts.codes[ok],
                               keep=keep)
        y = y[ok]
    return parts, y


import threading as _threading

_stage_cache: "dict" = {}
_stage_cache_order: list = []
_stage_cache_bytes: list = [0]
_stage_lock = _threading.Lock()  # parallel tuning trials stage concurrently
_STAGE_CACHE_MAX_BYTES = 6 << 30  # device-bytes budget across all meshes
_FULL_HASH_MAX_BYTES = 1 << 24    # 16 MB
_SAMPLE_WINDOW = 1 << 16
_SAMPLE_COUNT = 16
_tls_keys = _threading.local()    # probe→stage key handoff


def _normalize(a) -> np.ndarray:
    """The staging boundary: a C-contiguous ndarray (no copy when the
    caller already complies, which every internal extract path does)."""
    return np.ascontiguousarray(np.asarray(a))


_CKSUM_CHUNK = 1 << 20  # words per block (8MB) — bounds the arange temp


def _word_checksum(u8: np.ndarray) -> int:
    """Position-weighted wraparound uint64 checksum over EVERY byte:
    sum(w_i) and sum(w_i * (i+1)) mod 2^64, computed blockwise. A few
    vectorized memory-bandwidth passes (~60ms for 240MB) — far cheaper
    than a cryptographic hash, but both point edits (a delta UPDATE, an
    imputed cell) AND row permutations (orderBy/shuffle/compaction
    rewrites) perturb it: a plain commutative sum is permutation-blind,
    and serving a stale device X in pre-shuffle row order against freshly
    extracted labels would silently train on mispaired (X, y) (r4
    review). A collision now needs edits with both zero sum and zero
    position-weighted sum mod 2^64."""
    n8 = u8.size & ~7
    w = u8[:n8].view(np.uint64)
    idx = np.arange(1, min(_CKSUM_CHUNK, max(w.size, 1)) + 1,
                    dtype=np.uint64)
    s1 = 0
    s2 = 0
    for start in range(0, w.size, _CKSUM_CHUNK):
        blk = w[start:start + _CKSUM_CHUNK]
        b1 = int(blk.sum(dtype=np.uint64))
        # sum(blk * (start+1 .. start+len)) = sum(blk*local_idx) + start*b1
        b2 = int((blk * idx[:blk.size]).sum(dtype=np.uint64)) + start * b1
        s1 += b1
        s2 += b2
    if n8 != u8.size:  # tail bytes fold in with their own positions
        tail = u8[n8:].astype(np.uint64)
        s1 += int(tail.sum(dtype=np.uint64))
        s2 += int((tail * np.arange(w.size + 1, w.size + 1 + tail.size,
                                    dtype=np.uint64)).sum(dtype=np.uint64))
    return ((s1 & 0xFFFFFFFFFFFFFFFF) << 64) | (s2 & 0xFFFFFFFFFFFFFFFF)


def _content_key(a: np.ndarray) -> tuple:
    """Staging-cache fingerprint of a NORMALIZED array. Small arrays hash
    their full bytes (~1ms/4MB). Large arrays combine 16 evenly-spaced
    64KB window hashes (order-sensitive) with a whole-array wraparound
    word-sum (point-edit-sensitive) plus length/shape/dtype: a full
    SHA-class pass over a 240MB block costs ~0.4s PER FIT (r2 paid it on
    every large-N call), while windows + word-sum cost ~20ms total and
    catch both global byte shifts (CV folds, randomSplit variants) and
    point edits outside the sampled windows (ADVICE r3 medium)."""
    assert a.flags.c_contiguous
    if a.nbytes <= _FULL_HASH_MAX_BYTES:
        return ("h", a.shape, str(a.dtype), hash(a.tobytes()))
    u8 = a.reshape(-1).view(np.uint8)
    n = u8.size
    starts = np.linspace(0, n - _SAMPLE_WINDOW, _SAMPLE_COUNT).astype(np.int64)
    parts = tuple(hash(u8[s:s + _SAMPLE_WINDOW].tobytes()) for s in starts)
    return ("s", a.shape, str(a.dtype), hash((n, _word_checksum(u8)) + parts))


def _memo_key(a: np.ndarray) -> tuple:
    """_content_key with a per-thread (id → key) memo so a probe in
    _route_mesh and the stage in the same routed block hash a buffer once,
    not twice (fit_logistic re-probes every Newton iteration)."""
    memo = getattr(_tls_keys, "memo", None)
    if memo is not None:
        hit = memo.get(id(a))
        if hit is not None and hit[0] is a:
            return hit[1]
    key = _content_key(a)
    if memo is not None:
        memo[id(a)] = (a, key)
    return key


def _cache_put(key, value):
    from ..obs import LEDGER
    from ..utils.profiler import PROFILER
    evicted = 0
    with _stage_lock:
        if key in _stage_cache:
            return
        cost = value.nbytes
        _stage_cache[key] = value
        _stage_cache_order.append((key, cost))
        _stage_cache_bytes[0] += cost
        while _stage_cache_bytes[0] > _STAGE_CACHE_MAX_BYTES \
                and len(_stage_cache_order) > 1:
            old, old_cost = _stage_cache_order.pop(0)
            _stage_cache.pop(old, None)
            _stage_cache_bytes[0] -= old_cost
            evicted += old_cost
    LEDGER.alloc("stage_cache", cost)
    if evicted:
        from ..obs import RECORDER
        LEDGER.free("stage_cache", evicted)
        PROFILER.count("staging.evict_bytes", float(evicted))
        if RECORDER.enabled:
            RECORDER.emit("cache", "cache.evict",
                          args={"pool": "stage_cache", "bytes": evicted})


# QUANTIZED BIN-INDEX CACHE (the shared-histogram engine's hot operand):
# compact uint8/uint16 bin matrices staged ONCE per dataset content and
# reused by every tree, every boosting round, and every CV fold that
# re-fits on the same rows. Kept SEPARATE from the general staging cache
# (its own byte budget, sml.tree.binCacheBytes) so a burst of fold stacks
# or predict batches cannot evict the bins mid-grid; entries are LRU by
# touch order.
_bin_stage_cache: "dict" = {}
_bin_stage_bytes: list = [0]


def _bin_cache_budget() -> int:
    from ..conf import GLOBAL_CONF
    return GLOBAL_CONF.getInt("sml.tree.binCacheBytes")


def _bin_cache_key(a: np.ndarray, mesh) -> tuple:
    return (_memo_key(a), id(mesh), "bins",
            meshlib.data_width(mesh))


def _bin_cache_touch(key):
    """LRU probe: returns the cached device array (touched to the end of
    eviction order) or None."""
    with _stage_lock:
        hit = _bin_stage_cache.get(key)
        if hit is not None:
            # move-to-end LRU touch (dicts iterate in insertion order)
            _bin_stage_cache.pop(key)
            _bin_stage_cache[key] = hit
    return hit


def _bin_cache_store(key, hit) -> None:
    """Insert + LRU/ledger accounting shared by `stage_bins_cached` and
    the chunked-ingest assembly (`insert_bins_cached`)."""
    from ..obs import LEDGER, RECORDER
    from ..utils.profiler import PROFILER
    stored = evicted = 0
    with _stage_lock:
        if key not in _bin_stage_cache:
            _bin_stage_cache[key] = hit
            _bin_stage_bytes[0] += hit.nbytes
            stored = hit.nbytes
            budget = _bin_cache_budget()
            while _bin_stage_bytes[0] > budget and len(_bin_stage_cache) > 1:
                old = next(iter(_bin_stage_cache))
                old_bytes = _bin_stage_cache.pop(old).nbytes
                _bin_stage_bytes[0] -= old_bytes
                evicted += old_bytes
    if stored:
        LEDGER.alloc("bin_cache", stored)
    if evicted:
        LEDGER.free("bin_cache", evicted)
        PROFILER.count("staging.bin_evict_bytes", float(evicted))
        if RECORDER.enabled:
            RECORDER.emit("cache", "cache.evict",
                          args={"pool": "bin_cache", "bytes": evicted})


def stage_bins_cached(binned: np.ndarray) -> jax.Array:
    """device_put a quantized bin-index matrix through the bin cache.

    Rows are bucket-padded exactly like `stage_rows_cached`, so aligned
    per-row arrays (labels, masks) staged through the general cache land
    on the same padded shape."""
    from ..utils.profiler import PROFILER
    mesh = meshlib.get_mesh()
    n_dev = meshlib.data_width(mesh)
    a = _normalize(binned)
    key = _bin_cache_key(a, mesh)
    hit = _bin_cache_touch(key)
    if hit is not None:
        PROFILER.count("staging.bin_cache_hit")
        PROFILER.count("staging.h2d_bytes_saved", a.nbytes)
        return hit
    padded = meshlib.pad_rows(a, meshlib.bucket_rows(a.shape[0], n_dev))[0]
    hit = jax.device_put(padded, meshlib.data_sharding(mesh, padded.ndim))
    _bin_cache_store(key, hit)
    PROFILER.count("staging.bin_cache_miss")
    PROFILER.count("staging.h2d_bytes", padded.nbytes)
    return hit


def bin_cache_probe(binned: np.ndarray) -> Optional[jax.Array]:
    """Cache probe WITHOUT staging on miss (the chunked ingest asks
    before paying a second pass over the source)."""
    mesh = meshlib.get_mesh()
    a = _normalize(binned)
    return _bin_cache_touch(_bin_cache_key(a, mesh))


def insert_bins_cached(binned_host: np.ndarray, dev: jax.Array) -> jax.Array:
    """Adopt an EXTERNALLY ASSEMBLED device bin matrix (the chunked
    ingest's per-chunk device-side assembly) into the bin cache under
    the standard content key of its host mirror, so every later fit,
    predict, and eval on the same rows hits the assembled copy exactly
    as if `stage_bins_cached` had staged it in one shot. The array is
    resharded to the canonical data sharding if assembly left it
    elsewhere (device-to-device, never back through the host)."""
    mesh = meshlib.get_mesh()
    a = _normalize(binned_host)
    expect = meshlib.data_sharding(mesh, dev.ndim)
    if getattr(dev, "sharding", None) != expect:
        dev = jax.device_put(dev, expect)
    key = _bin_cache_key(a, mesh)
    _bin_cache_store(key, dev)
    return _bin_cache_touch(key)


def bin_cache_stats() -> dict:
    """(entries, bytes) snapshot — test/debug surface for the bin cache."""
    with _stage_lock:
        return {"entries": len(_bin_stage_cache),
                "bytes": _bin_stage_bytes[0]}


# ----------------------------------------------------- chunked bin assembly
# The out-of-core ingest path (ml/_chunked.py) builds the device-resident
# compact matrix CHUNK BY CHUNK: each quantized block H2Ds into a small
# transient buffer (ledger pool `chunk_stage`) and a donated
# dynamic_update_slice program folds it into the padded bin matrix — the
# "bin accumulate" device work the prefetch pipeline overlaps with the
# next chunk's host quantization. HBM therefore holds the COMPACT matrix
# plus ~prefetchChunks chunk blocks, never the raw float data.
_chunk_assemble_prog: list = []


def _chunk_assemble_step(buf, block, start):
    """Rows [start, start+block_rows) of `buf` become `block`. `buf` is
    DONATED (arg 0): on real devices the update is in place, so assembly
    never holds two copies of the matrix in HBM (XLA:CPU ignores
    donation and copies — correct, just unamortized, like every other
    donation site on the test mesh)."""
    return jax.lax.dynamic_update_slice(buf, block, (start, 0))


def _chunk_assemble_program():
    """The one compiled assembly program. jit specializes per
    (buf, block) shape/dtype/sharding internally; the chunk OFFSET rides
    as a traced scalar, so every chunk of an ingest shares one
    executable (note_compile records the program once — per-shape
    re-specializations are jit-internal, like the other program
    caches)."""
    if not _chunk_assemble_prog:
        from ..obs import note_compile
        note_compile("chunk_assemble")
        _chunk_assemble_prog.append(
            jax.jit(_chunk_assemble_step, donate_argnums=(0,)))
    return _chunk_assemble_prog[0]


@contextlib.contextmanager
def transient_hbm(pool: str, nbytes: int):
    """Account a dispatch's dominant TRANSIENT device working set in the
    HBM ledger for the duration of the call (alloc on entry, free on
    exit) — live/peak visibility for program-internal buffers the staging
    caches never own. The tree fit paths charge the XLA path's fit-long
    one-hot resident (`hist_onehot`) through this; the pallas kernel path
    charges zero, so the ledger shows the bytes the kernel keeps out of
    HBM. No-ops on nbytes <= 0."""
    if nbytes <= 0:
        yield
        return
    from ..obs import LEDGER
    LEDGER.alloc(pool, int(nbytes))
    try:
        yield
    finally:
        LEDGER.free(pool, int(nbytes))


def stage_rows_cached(a: np.ndarray, pad_to_multiple: bool = True) -> jax.Array:
    """device_put a row-sharded array through the content cache."""
    from ..utils.profiler import PROFILER
    mesh = meshlib.get_mesh()
    n_dev = meshlib.data_width(mesh)
    a = _normalize(a)
    key = (_memo_key(a), id(mesh), "arr", n_dev)
    hit = _stage_cache.get(key)
    if hit is None:
        padded = (meshlib.pad_rows(
            a, meshlib.bucket_rows(a.shape[0], n_dev))[0]
            if pad_to_multiple else a)
        hit = jax.device_put(padded, meshlib.data_sharding(mesh, padded.ndim))
        _cache_put(key, hit)
        PROFILER.count("staging.cache_miss")
        PROFILER.count("staging.h2d_bytes", padded.nbytes)
    else:
        PROFILER.count("staging.cache_hit")
        PROFILER.count("staging.h2d_bytes_saved", a.nbytes)
    return hit


def stage_stacked_cached(a: np.ndarray) -> jax.Array:
    """device_put a FOLD-STACKED array (folds, rows, ...) through the
    content cache, rows (axis 1) sharded over the data axis, fold axis
    replicated across shards. The caller pre-pads axis 1 to a multiple of
    the mesh's data dimension. Used by the batched fold×param tree fits."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    mesh = meshlib.get_mesh()
    n_dev = meshlib.data_width(mesh)
    a = _normalize(a)
    key = (_memo_key(a), id(mesh), "stack", n_dev)
    hit = _stage_cache.get(key)
    from ..utils.profiler import PROFILER
    if hit is None:
        spec = P(None, meshlib.row_spec_entry(mesh),
                 *([None] * (a.ndim - 2)))
        hit = jax.device_put(a, NamedSharding(mesh, spec))
        _cache_put(key, hit)
        PROFILER.count("staging.cache_miss")
        PROFILER.count("staging.h2d_bytes", a.nbytes)
    else:
        PROFILER.count("staging.cache_hit")
        PROFILER.count("staging.h2d_bytes_saved", a.nbytes)
    return hit


def stage_trial_stacked_cached(a: np.ndarray, mesh) -> jax.Array:
    """device_put an ELEMENT-STACKED array (elems, rows, ...) through the
    content cache onto a 2-D trial mesh (`meshlib.trial_mesh`): trial
    elements shard over TRIAL_AXIS, rows over DATA_AXIS — the resident
    layout of cross-chip trial parallelism. The caller pre-pads axis 0 to
    a multiple of the trial dim and axis 1 to a multiple of the FULL
    device count (so any data-axis width divides it)."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    a = _normalize(a)
    key = (_memo_key(a), id(mesh), "tstack",
           mesh.shape[meshlib.TRIAL_AXIS], mesh.shape[meshlib.DATA_AXIS])
    hit = _stage_cache.get(key)
    from ..utils.profiler import PROFILER
    if hit is None:
        spec = P(meshlib.TRIAL_AXIS, meshlib.DATA_AXIS,
                 *([None] * (a.ndim - 2)))
        hit = jax.device_put(a, NamedSharding(mesh, spec))
        _cache_put(key, hit)
        PROFILER.count("staging.cache_miss")
        PROFILER.count("staging.h2d_bytes", a.nbytes)
    else:
        PROFILER.count("staging.cache_hit")
        PROFILER.count("staging.h2d_bytes_saved", a.nbytes)
    return hit


def stage_mask_cached(n_padded: int, n_true: int) -> jax.Array:
    mesh = meshlib.get_mesh()
    mkey = (n_padded, n_true, id(mesh), "mask",
            meshlib.data_width(mesh))
    hit = _stage_cache.get(mkey)
    if hit is None:
        hit = meshlib.row_mask(n_padded, n_true)
        hit = jax.device_put(hit, meshlib.data_sharding(mesh, 1))
        _cache_put(mkey, hit)
    return hit


def _route_mesh(hint, arrays, may_promote: bool = True,
                stacked: bool = False) -> Tuple[object, str]:
    """Stage-aware dispatch: charge the H2D term only for bytes NOT already
    resident on the device mesh, and when the device loses solely because
    of that one-time staging cost, promote the arrays in the background
    (device_put is async) so the NEXT fit on this dataset rides the chip —
    repeated fits (CV folds, tuning trials, warm benchmarks) converge to
    device-resident execution without explicit placement.

    Returns (mesh, route): callers that want a plain-numpy fast path must
    branch on route == "host", NOT on the mesh's device platform — on a
    CPU-backend process the *device* route legitimately runs on a CPU mesh
    (the virtual test mesh).

    `may_promote` distinguishes fit paths (datasets that WILL be re-used:
    CV folds, tuning trials) from one-shot predict batches — promoting a
    streaming batch would waste tunnel bandwidth on data never seen
    again."""
    import dataclasses

    from ..conf import GLOBAL_CONF
    pre = dispatch.preroute(hint)
    if pre is not None:  # no tunnel / forced mode: skip the probe entirely
        dispatch.audit_preroute(hint, pre)  # flight-recorder receipt
        return (meshlib.get_mesh() if pre == "device"
                else dispatch.host_mesh()), pre
    resident = dispatch.WorkHint(hint.flops, hint.kind, hint.out_bytes, None)
    if dispatch.decide(resident, _record=False)[0] == "host":
        # the device loses even with everything resident: no point hashing
        # the arrays to price their H2D (hot on per-batch predict paths).
        # The probe was unrecorded (_record=False); THIS is the dispatch
        # decision, so it gets exactly one audit row
        dispatch.audit_decision(resident, "host")
        return dispatch.host_mesh(), "host"
    dev_mesh = meshlib.get_mesh()
    n_dev = meshlib.data_width(dev_mesh)
    eff = hint
    keyed = []
    kind = "stack" if stacked else "arr"
    if arrays:
        unstaged = 0.0
        for a in arrays:
            a = _normalize(a)
            ck = _memo_key(a)
            key = (ck, id(dev_mesh), kind, n_dev)
            bkey = (ck, id(dev_mesh), "bins", n_dev)
            # quantized bin matrices live in their OWN cache (see
            # stage_bins_cached) — charge H2D only when absent from both
            if key not in _stage_cache and bkey not in _bin_stage_cache:
                unstaged += a.nbytes
            keyed.append(a)
        eff = dataclasses.replace(hint,
                                  in_bytes=unstaged if unstaged else None)
    route, promote = dispatch.decide(eff)
    if route == "device":
        return dev_mesh, "device"
    if promote and may_promote and keyed \
            and GLOBAL_CONF.getBool("sml.dispatch.autoPromote"):
        for a in keyed:
            # async put under the device mesh, in the layout AND cache the
            # program will actually read (probing "arr" keys while the
            # program stages "stack" layouts would promote dead copies;
            # likewise a compact bin matrix must land in the bin cache the
            # tree/predict programs probe, not the general rows cache)
            if stacked:
                stage_stacked_cached(a)
            elif _is_bin_matrix(a):
                stage_bins_cached(a)
            else:
                stage_rows_cached(a)
    return dispatch.host_mesh(), "host"


def _is_bin_matrix(a: np.ndarray) -> bool:
    """The quantized engine's staging discriminator: compact (uint8/uint16)
    2-D matrices are quantized bin indices — only `tree_impl.bin_dtype`
    produces them. Wider integer matrices (CompactParts.codes is int32,
    ALS id columns are 1-D) stay in the general rows cache, so a burst of
    compact linear fits cannot evict hot bins from the tree budget."""
    return a.ndim == 2 and a.dtype.kind == "u" and a.dtype.itemsize <= 2


@contextlib.contextmanager
def routed_for(hint, *arrays, stacked: bool = False):
    """Context manager binding the stage-aware dispatch decision as the
    thread's active mesh (see _route_mesh). Also installs the per-thread
    key memo so the probe's fingerprints are reused by the stage.
    `stacked=True` prices/promotes fold-stacked (folds, rows, ...) arrays
    in their axis-1-sharded layout."""
    had_memo = getattr(_tls_keys, "memo", None)
    if had_memo is None:
        _tls_keys.memo = {}
    try:
        mesh, _ = _route_mesh(hint, arrays, stacked=stacked)
        with meshlib.use_mesh_local(mesh):
            yield mesh
    finally:
        if had_memo is None:
            _tls_keys.memo = None


def route_for_arrays(hint, *arrays) -> Tuple[object, str]:
    """One-shot stage-aware decision for predict paths that want a plain
    host-numpy fast path: returns (mesh, route). Never promotes — predict
    batches are one-shot; only fit paths (routed_for) bet on re-use."""
    return _route_mesh(hint, arrays, may_promote=False)


def stage_sharded(*arrays: np.ndarray):
    """Pad + shard host arrays by rows over the data axis.

    Returns (device_arrays..., mask_device, n_true). The mask is 1.0 for real
    rows, 0.0 for padding; all statistics must be mask-weighted so padding is
    inert under psum.

    Results are memoized by content: CV folds, hyperopt trials, and repeated
    fits re-stage identical arrays constantly, and each fresh H2D through
    the device tunnel pays a fixed sync penalty at first use.

    Quantized bin-index matrices (compact uint8/uint16 2-D — see
    `_is_bin_matrix`) stage through the dedicated bin cache so fit,
    predict, and eval-pushdown programs share ONE device copy per dataset
    under its own byte budget.
    """
    n_true = arrays[0].shape[0]
    outs = [stage_bins_cached(a) if _is_bin_matrix(np.asarray(a))
            else stage_rows_cached(a) for a in arrays]
    n_padded = outs[0].shape[0]
    mask_dev = stage_mask_cached(n_padded, n_true)
    return (*outs, mask_dev, n_true)


def data_parallel(fn: Callable, *, out_replicated: bool = True,
                  replicated_argnums: Tuple[int, ...] = ()) -> Callable:
    """jit(shard_map(fn)) over the active mesh's data axis.

    `fn` sees per-chip row blocks and may call `parallel.collectives.psum`
    etc. on the "data" axis; outputs are replicated (each chip returns the
    same reduced value) unless out_replicated=False (then row-sharded).
    Args listed in `replicated_argnums` (rng keys, small parameter vectors)
    are broadcast to every chip instead of row-sharded.

    Donation is deliberately NOT offered here: any input of a
    data_parallel program may be a staging-cache-owned buffer, and
    donating one would poison every later cache hit. The one donation
    site (the chunked boosting scan's margin carry) builds its own
    shard_map+jit in `tree_impl._compiled_chunk`.
    """
    mesh = meshlib.get_mesh()
    out_spec = P() if out_replicated else P(meshlib.row_spec_entry(mesh))

    def spec_for(i, x):
        if i in replicated_argnums:
            return P()
        return P(*([meshlib.row_spec_entry(mesh)]
                   + [None] * (np.ndim(x) - 1)))

    def wrapped(*args):
        specs = tuple(spec_for(i, a) for i, a in enumerate(args))
        mapped = meshlib.shard_map_compat(fn, mesh=mesh, in_specs=specs,
                                          out_specs=out_spec)
        return mapped(*args)

    return jax.jit(wrapped)


_compiled_cache: dict = {}


class _RecordingProgram:
    """Thin callable over a compiled data_parallel program that records a
    prewarm signature per DISTINCT arg-shape set (each set is its own XLA
    executable; the manifest must name them all). Per-call cost once a
    shape is seen: one tuple build + one set lookup."""

    __slots__ = ("_compiled", "_src", "_flags", "_seen")

    def __init__(self, compiled, src, flags):
        self._compiled = compiled
        self._src = src
        self._flags = flags
        self._seen: set = set()

    def __call__(self, *args):
        sig = tuple((np.shape(a), str(getattr(a, "dtype", type(a).__name__)))
                    for a in args)
        if sig not in self._seen:
            self._seen.add(sig)
            from ..parallel import prewarm as _prewarm
            out_rep, rep_nums = self._flags
            _prewarm.record("data_parallel", {
                "src": self._src, "out_replicated": bool(out_rep),
                "replicated_argnums": list(rep_nums),
                "args": [[list(s), d] for s, d in sig]})
        return self._compiled(*args)


def cached_data_parallel(fn: Callable, *, out_replicated: bool = True,
                         replicated_argnums: Tuple[int, ...] = ()) -> Callable:
    """data_parallel with a program cache keyed by (fn, mesh, flags).

    jax.jit caches per function object; wrapping a fresh closure per fit
    would recompile every call. Callers must pass module-level fns (stable
    identity) for the cache to hit. Programs whose fn carries a
    replayable source (module-level name or a `_prewarm` factory tag) are
    wrapped to record their shapes into the prewarm manifest.
    """
    mesh = meshlib.get_mesh()
    key = (fn, id(mesh), out_replicated, replicated_argnums)
    if key not in _compiled_cache:
        from ..obs import note_compile
        from ..parallel import prewarm as _prewarm
        note_compile(getattr(fn, "__name__", "fn"))
        compiled = data_parallel(
            fn, out_replicated=out_replicated,
            replicated_argnums=replicated_argnums)
        src = _prewarm.fn_src(fn)
        if src is not None:
            compiled = _RecordingProgram(
                compiled, src, (out_replicated, replicated_argnums))
        _compiled_cache[key] = compiled
    return _compiled_cache[key]


def _replay_data_parallel(meta: dict) -> None:
    """Prewarm rebuilder for `cached_data_parallel` programs: resolve the
    fn, build through the SAME cache, and first-dispatch on zero-filled
    operands placed like the live call sites place them (rows
    data-sharded, replicated argnums left to jit placement)."""
    from ..parallel import prewarm as _prewarm
    fn = _prewarm.resolve_fn(meta["src"])
    rep = tuple(int(i) for i in meta["replicated_argnums"])
    compiled = cached_data_parallel(fn,
                                    out_replicated=bool(meta["out_replicated"]),
                                    replicated_argnums=rep)
    mesh = meshlib.get_mesh()
    args = []
    for i, (shape, dtype) in enumerate(meta["args"]):
        a = np.zeros(tuple(shape), dtype=np.dtype(dtype))
        if i in rep or a.ndim == 0:
            args.append(a)
        else:
            args.append(jax.device_put(a, meshlib.data_sharding(mesh, a.ndim)))
    jax.device_get(compiled(*args))


from ..parallel import prewarm as _prewarm_mod

_prewarm_mod.register_rebuilder("data_parallel", _replay_data_parallel)


def run_data_parallel(fn: Callable, *arrays, out_replicated: bool = True,
                      replicated: Tuple = (),
                      work: "Optional[dispatch.WorkHint]" = None):
    """One-shot: stage arrays sharded, run fn(blocks..., mask, *replicated)
    under jit+shard_map, return host numpy results. `replicated` values are
    broadcast to all chips (small parameter vectors).

    `work` is the caller's cost estimate; when given, the program is routed
    host/device by the measured-latency dispatcher (tiny reductions lose to
    a tunneled chip's fixed round-trip by orders of magnitude)."""
    from ..utils.profiler import PROFILER
    with routed_for(work, *arrays) as mesh:
        route = "host" if dispatch.is_host_mesh(mesh) else "device"
        with PROFILER.span(f"program.{getattr(fn, '__name__', 'fn')}",
                           rows=int(np.shape(arrays[0])[0]) if arrays else 0,
                           route=route):
            staged = stage_sharded(*arrays)
            dev_args, mask, _ = staged[:-2], staged[-2], staged[-1]
            n_lead = len(dev_args) + 1
            rep_nums = tuple(range(n_lead, n_lead + len(replicated)))
            compiled = cached_data_parallel(fn, out_replicated=out_replicated,
                                            replicated_argnums=rep_nums)
            out = compiled(*dev_args, mask, *replicated)
            # ONE batched device→host transfer for the whole output tree:
            # per-leaf np.asarray pays the tunnel's fixed D2H latency once
            # PER ARRAY, which dominated r1's per-fit wall-clock
            host = jax.device_get(out)
            PROFILER.count("staging.d2h_bytes", sum(
                np.asarray(x).nbytes for x in jax.tree_util.tree_leaves(host)))
            return host
