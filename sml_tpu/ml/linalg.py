"""Vector types for feature columns.

MLlib's `VectorAssembler` output column holds `DenseVector`/`SparseVector`
values (`SML/ML 02 - Linear Regression I.py:103-107`; sparse OHE output at
`SML/ML 03 - Linear Regression II.py:54-61`). Here vectors are thin
numpy-backed values living in object columns of the host DataFrame; the ML
layer densifies whole columns straight into sharded HBM arrays, so these
types exist for API parity and host-side inspection, never for device math.
"""

from __future__ import annotations

from typing import Iterable, Sequence, Union

import numpy as np


class Vector:
    def toArray(self) -> np.ndarray:
        raise NotImplementedError

    @property
    def size(self) -> int:
        raise NotImplementedError

    def __len__(self):
        return self.size

    def __eq__(self, other):
        if not isinstance(other, Vector):
            return NotImplemented
        return np.array_equal(self.toArray(), other.toArray())

    def __hash__(self):
        return hash(self.toArray().tobytes())


class DenseVector(Vector):
    __slots__ = ("values",)

    def __init__(self, values: Iterable[float]):
        self.values = np.asarray(values, dtype=np.float64)

    def toArray(self) -> np.ndarray:
        return self.values

    @property
    def size(self) -> int:
        return int(self.values.shape[0])

    def __getitem__(self, i):
        return self.values[i]

    def __iter__(self):
        return iter(self.values)

    def dot(self, other) -> float:
        other = other.toArray() if isinstance(other, Vector) else np.asarray(other)
        return float(self.values @ other)

    def norm(self, p: float = 2.0) -> float:
        return float(np.linalg.norm(self.values, p))

    def __repr__(self):
        return f"DenseVector({np.array2string(self.values, separator=', ')})"


class SparseVector(Vector):
    __slots__ = ("_size", "indices", "values")

    def __init__(self, size: int, indices, values=None):
        self._size = int(size)
        if values is None:  # dict or list-of-pairs form
            if isinstance(indices, dict):
                pairs = sorted(indices.items())
            else:
                pairs = sorted(indices)
            self.indices = np.asarray([p[0] for p in pairs], dtype=np.int32)
            self.values = np.asarray([p[1] for p in pairs], dtype=np.float64)
        else:
            self.indices = np.asarray(indices, dtype=np.int32)
            self.values = np.asarray(values, dtype=np.float64)

    def toArray(self) -> np.ndarray:
        arr = np.zeros(self._size, dtype=np.float64)
        arr[self.indices] = self.values
        return arr

    @property
    def size(self) -> int:
        return self._size

    def __getitem__(self, i):
        if i < 0:
            i += self._size
        pos = np.searchsorted(self.indices, i)
        if pos < len(self.indices) and self.indices[pos] == i:
            return float(self.values[pos])
        return 0.0

    def dot(self, other) -> float:
        other_arr = other.toArray() if isinstance(other, Vector) else np.asarray(other)
        return float(self.values @ other_arr[self.indices])

    def __repr__(self):
        idx = ", ".join(str(int(i)) for i in self.indices)
        vals = ", ".join(repr(float(v)) for v in self.values)
        return f"SparseVector({self._size}, {{{idx and ''}}})" if False else \
            f"SparseVector({self._size}, [{idx}], [{vals}])"


class Vectors:
    @staticmethod
    def dense(*values) -> DenseVector:
        if len(values) == 1 and isinstance(values[0], (list, tuple, np.ndarray)):
            values = values[0]
        return DenseVector(values)

    @staticmethod
    def sparse(size: int, indices, values=None) -> SparseVector:
        return SparseVector(size, indices, values)

    @staticmethod
    def zeros(size: int) -> DenseVector:
        return DenseVector(np.zeros(size))


def to_matrix(col: Sequence[Union[Vector, Sequence[float]]]) -> np.ndarray:
    """Densify a host column of vectors into an (n, d) float64 matrix — the
    staging boundary before `parallel.mesh.shard_rows` ships it to HBM."""
    n = len(col)
    if n == 0:
        return np.zeros((0, 0))
    first = col[0]
    d = first.size if isinstance(first, Vector) else len(first)
    out = np.zeros((n, d), dtype=np.float64)
    for i, v in enumerate(col):
        out[i] = v.toArray() if isinstance(v, Vector) else np.asarray(v, dtype=np.float64)
    return out
