"""Vector types for feature columns.

MLlib's `VectorAssembler` output column holds `DenseVector`/`SparseVector`
values (`SML/ML 02 - Linear Regression I.py:103-107`; sparse OHE output at
`SML/ML 03 - Linear Regression II.py:54-61`). Here vectors are thin
numpy-backed values living in object columns of the host DataFrame; the ML
layer densifies whole columns straight into sharded HBM arrays, so these
types exist for API parity and host-side inspection, never for device math.
"""

from __future__ import annotations

from typing import Iterable, Optional, Sequence, Union

import numpy as np


class Vector:
    def toArray(self) -> np.ndarray:
        raise NotImplementedError

    @property
    def size(self) -> int:
        raise NotImplementedError

    def __len__(self):
        return self.size

    def __eq__(self, other):
        if not isinstance(other, Vector):
            return NotImplemented
        return np.array_equal(self.toArray(), other.toArray())

    def __hash__(self):
        return hash(self.toArray().tobytes())


class DenseVector(Vector):
    __slots__ = ("values",)

    def __init__(self, values: Iterable[float]):
        self.values = np.asarray(values, dtype=np.float64)

    def toArray(self) -> np.ndarray:
        return self.values

    @property
    def size(self) -> int:
        return int(self.values.shape[0])

    def __getitem__(self, i):
        return self.values[i]

    def __iter__(self):
        return iter(self.values)

    def dot(self, other) -> float:
        other = other.toArray() if isinstance(other, Vector) else np.asarray(other)
        return float(self.values @ other)

    def norm(self, p: float = 2.0) -> float:
        return float(np.linalg.norm(self.values, p))

    def __repr__(self):
        return f"DenseVector({np.array2string(self.values, separator=', ')})"


class SparseVector(Vector):
    __slots__ = ("_size", "indices", "values")

    def __init__(self, size: int, indices, values=None):
        self._size = int(size)
        if values is None:  # dict or list-of-pairs form
            if isinstance(indices, dict):
                pairs = sorted(indices.items())
            else:
                pairs = sorted(indices)
            self.indices = np.asarray([p[0] for p in pairs], dtype=np.int32)
            self.values = np.asarray([p[1] for p in pairs], dtype=np.float64)
        else:
            self.indices = np.asarray(indices, dtype=np.int32)
            self.values = np.asarray(values, dtype=np.float64)

    def toArray(self) -> np.ndarray:
        arr = np.zeros(self._size, dtype=np.float64)
        arr[self.indices] = self.values
        return arr

    @property
    def size(self) -> int:
        return self._size

    def __getitem__(self, i):
        if i < 0:
            i += self._size
        pos = np.searchsorted(self.indices, i)
        if pos < len(self.indices) and self.indices[pos] == i:
            return float(self.values[pos])
        return 0.0

    def dot(self, other) -> float:
        other_arr = other.toArray() if isinstance(other, Vector) else np.asarray(other)
        return float(self.values @ other_arr[self.indices])

    def __repr__(self):
        idx = ", ".join(str(int(i)) for i in self.indices)
        vals = ", ".join(repr(float(v)) for v in self.values)
        return f"SparseVector({self._size}, {{{idx and ''}}})" if False else \
            f"SparseVector({self._size}, [{idx}], [{vals}])"


class Vectors:
    @staticmethod
    def dense(*values) -> DenseVector:
        if len(values) == 1 and isinstance(values[0], (list, tuple, np.ndarray)):
            values = values[0]
        return DenseVector(values)

    @staticmethod
    def sparse(size: int, indices, values=None) -> SparseVector:
        return SparseVector(size, indices, values)

    @staticmethod
    def zeros(size: int) -> DenseVector:
        return DenseVector(np.zeros(size))


def to_matrix(col: Sequence[Union[Vector, Sequence[float]]]) -> np.ndarray:
    """Densify a host column of vectors into an (n, d) float64 matrix — the
    staging boundary before `parallel.mesh.shard_rows` ships it to HBM.

    Columnar `VectorArray`-backed Series return their backing block with NO
    per-row work (the hot path: VERDICT r1 flagged the per-row
    `v.toArray()` loop as the framework's bottleneck)."""
    import pandas as pd

    if isinstance(col, VectorArray):
        return col.block
    if isinstance(col, pd.Series) and isinstance(col.array, VectorArray):
        return col.array.block
    n = len(col)
    if n == 0:
        return np.zeros((0, 0))
    first = col[0]
    d = first.size if isinstance(first, Vector) else len(first)
    out = np.zeros((n, d), dtype=np.float64)
    for i, v in enumerate(col):
        out[i] = v.toArray() if isinstance(v, Vector) else np.asarray(v, dtype=np.float64)
    return out


# ===================================================================== columnar
# A pandas ExtensionArray holding the whole vector column as ONE dense
# (n, d) float64 block. This is the Arrow-FixedSizeList role from
# `SML/ML 12 - Inference with Pandas UDFs.py:64` (zero-copy columnar
# interchange): the ML layer stages `col.array.block` straight to HBM, and
# per-row Vector objects exist only when an element is actually inspected.

from pandas.api.extensions import (ExtensionArray, ExtensionDtype,  # noqa: E402
                                   register_extension_dtype)
import pandas as pd  # noqa: E402


@register_extension_dtype
class VectorDtype(ExtensionDtype):
    name = "vector"
    type = Vector
    kind = "O"
    na_value = None

    @classmethod
    def construct_array_type(cls):
        return VectorArray

    @classmethod
    def construct_from_string(cls, string):
        if string == cls.name:
            return cls()
        raise TypeError(f"cannot construct VectorDtype from {string!r}")


class VectorArray(ExtensionArray):
    """Column of vectors backed by a single dense (n, d) block.

    `sparse=True` marks columns whose elements should materialize as
    SparseVector (OneHotEncoder output parity with MLlib); the backing
    storage is dense either way — one-hot widths in the course are tiny and
    a dense block is what the MXU wants. NA elements are a True in `_na`
    and a NaN row in the block (so finite-ness checks see them naturally).
    """

    def __init__(self, block: np.ndarray, na: Optional[np.ndarray] = None,
                 sparse: bool = False, copy: bool = False):
        block = np.asarray(block, dtype=np.float64)
        if block.ndim != 2:
            raise ValueError(f"VectorArray needs (n, d) block, got {block.shape}")
        if copy:
            block = block.copy()
        self._block = block
        self._na = (np.zeros(len(block), dtype=bool) if na is None
                    else np.asarray(na, dtype=bool))
        self._sparse = bool(sparse)

    # -- block access (the point of this class) ---------------------------
    @property
    def block(self) -> np.ndarray:
        return self._block

    @property
    def width(self) -> int:
        return int(self._block.shape[1])

    # -- pandas EA interface ----------------------------------------------
    @property
    def dtype(self):
        return VectorDtype()

    def __len__(self):
        return len(self._block)

    @property
    def nbytes(self):
        return self._block.nbytes + self._na.nbytes

    def _make_scalar(self, row: np.ndarray):
        if self._sparse:
            nz = np.nonzero(row)[0]
            return SparseVector(len(row), nz.astype(np.int32), row[nz])
        return DenseVector(row)

    def __getitem__(self, key):
        if isinstance(key, (int, np.integer)):
            if self._na[key]:
                return None
            return self._make_scalar(self._block[int(key)])
        if isinstance(key, slice):
            return VectorArray(self._block[key], self._na[key], self._sparse)
        key = np.asarray(key)
        if key.dtype == bool:
            return VectorArray(self._block[key], self._na[key], self._sparse)
        return self.take(key)

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def isna(self):
        return self._na.copy()

    def take(self, indices, allow_fill: bool = False, fill_value=None):
        indices = np.asarray(indices, dtype=np.intp)
        if allow_fill:
            na = indices == -1
            safe = np.where(na, 0, indices)
            if len(self._block) == 0 and na.all():
                block = np.full((len(indices), self.width), np.nan)
                return VectorArray(block, np.ones(len(indices), bool), self._sparse)
            block = self._block[safe].copy()
            block[na] = np.nan
            return VectorArray(block, self._na[safe] | na, self._sparse)
        return VectorArray(self._block[indices], self._na[indices], self._sparse)

    def copy(self):
        # Shallow by design: this EA is immutable (no __setitem__), so the
        # backing block can be shared. pandas calls EA.copy() on every
        # column insert/reindex, and deep-copying a (1M, 40) f64 block per
        # pipeline stage was the single largest host cost at scale.
        return VectorArray(self._block, self._na.copy(), self._sparse)

    @classmethod
    def _from_sequence(cls, scalars, *, dtype=None, copy=False):
        if isinstance(scalars, VectorArray):
            return scalars.copy() if copy else scalars
        vals = list(scalars)
        na = np.array([v is None or (isinstance(v, float) and np.isnan(v))
                       for v in vals], dtype=bool)
        d = 0
        sparse = False
        for v in vals:
            if isinstance(v, Vector):
                d = v.size
                sparse = isinstance(v, SparseVector)
                break
            if isinstance(v, (list, tuple, np.ndarray)):
                d = len(v)
                break
        block = np.full((len(vals), d), np.nan)
        for i, v in enumerate(vals):
            if na[i]:
                continue
            block[i] = v.toArray() if isinstance(v, Vector) else \
                np.asarray(v, dtype=np.float64)
        return cls(block, na, sparse)

    @classmethod
    def _concat_same_type(cls, to_concat):
        arrs = list(to_concat)
        widths = {a.width for a in arrs if len(a)}
        if len(widths) > 1:
            raise ValueError(f"cannot concat vector columns of widths {widths}")
        d = widths.pop() if widths else (arrs[0].width if arrs else 0)
        blocks = [a.block if len(a) else np.zeros((0, d)) for a in arrs]
        nas = [a._na for a in arrs]
        sparse = any(a._sparse for a in arrs)
        return cls(np.concatenate(blocks, axis=0) if blocks else np.zeros((0, d)),
                   np.concatenate(nas) if nas else None, sparse)

    def _values_for_factorize(self):
        return np.asarray(self.astype(object)), None

    def astype(self, dtype, copy: bool = True):
        if isinstance(dtype, VectorDtype):
            return self.copy() if copy else self
        dtype = np.dtype(dtype) if not isinstance(dtype, ExtensionDtype) else dtype
        if dtype == np.dtype(object):
            out = np.empty(len(self), dtype=object)
            for i in range(len(self)):
                out[i] = self[i]
            return out
        return super().astype(dtype, copy=copy)

    def __eq__(self, other):  # elementwise, pandas semantics
        if isinstance(other, VectorArray):
            return np.all(self._block == other._block, axis=1) & \
                ~self._na & ~other._na
        return NotImplemented

    def __ne__(self, other):
        eq = self.__eq__(other)
        return NotImplemented if eq is NotImplemented else ~eq


def vector_series(block: np.ndarray, index=None, sparse: bool = False,
                  na: Optional[np.ndarray] = None) -> "pd.Series":
    """Wrap an (n, d) block as a columnar vector Series."""
    return pd.Series(VectorArray(block, na=na, sparse=sparse), index=index)
