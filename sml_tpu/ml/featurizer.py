"""Compiled columnar featurizer — the serving-path fusion pass.

The reference's ML 12 lesson streams Arrow batches into a pyfunc whose
sklearn pipeline re-runs preprocessing per batch
(`SML/ML 12 - Inference with Pandas UDFs.py:101-143`). The generic path
here does the same: each feature stage's pandas fn runs in sequence,
allocating intermediate columns. For inference throughput that is pure
overhead: the chain Imputer → StringIndexer → OneHotEncoder →
VectorAssembler is a STATIC column program, so `CompiledFeaturizer`
resolves it once at scorer build time into per-slot writers that scatter
straight into ONE preallocated (n, d) float32 block — the exact layout
`_staging` ships to the chip, with no intermediate frames, vector columns,
or per-stage copies.

Falls back to None (callers keep the generic path) for any stage or
option outside the supported chain, so behavior never silently diverges.
Supported: ImputerModel / StringIndexerModel (all handleInvalid modes,
with "skip" dropping rows exactly like the stage) / OneHotEncoderModel /
VectorAssembler(handleInvalid in ("error", "keep")).
"""

from __future__ import annotations

from typing import List, NamedTuple, Optional

import numpy as np
import pandas as pd


class CompactParts(NamedTuple):
    """Compact pre-expansion form of a numeric+one-hot feature block.

    The expanded (n, d) one-hot matrix never materializes: `num` holds the
    plain numeric slots, `codes` the integer category codes, and `layout`
    records the assembler's slot order as ("num", num_col) / ("oh",
    code_col, width) entries. The device programs expand one-hots ON CHIP
    (`linear_impl._expand_masked`) — staging ships n*(p+k) words instead
    of n*d, a ~6x H2D cut at the course's schema and the difference
    between feasible and impossible at 8M+ rows over a ~1.3 GB/s tunnel.
    """
    num: np.ndarray                 # (n, p) float32 numeric slots
    codes: np.ndarray               # (n, k) int32 category codes
    layout: tuple                   # slot-order expansion recipe
    width: int                      # expanded feature count d
    keep: Optional[np.ndarray]      # row-keep mask (indexer "skip" drops)

    def expand_host(self) -> np.ndarray:
        """(n, d) float32 — the exact block the generic featurizer would
        build; the memory-heavy fallback for paths that need X itself."""
        n = self.num.shape[0]
        out = np.zeros((n, self.width), dtype=np.float32)
        lo = 0
        for item in self.layout:
            if item[0] == "num":
                out[:, lo] = self.num[:, item[1]]
                lo += 1
            else:
                _, j, width = item
                idx = self.codes[:, j]
                ok = (idx >= 0) & (idx < width)
                rows = np.nonzero(ok)[0]
                out[rows, lo + idx[rows].astype(np.intp)] = 1.0
                lo += width
        return out

    def predict_affine(self, coef: np.ndarray, intercept: float) -> np.ndarray:
        """X @ coef + intercept without expanding: numeric dot + one
        embedding-table lookup per encoded column (w·onehot(i) == w[i])."""
        coef = np.asarray(coef, dtype=np.float64)
        acc = np.full(self.num.shape[0], float(intercept), dtype=np.float64)
        lo = 0
        num_cols, num_w = [], []
        for item in self.layout:
            if item[0] == "num":
                num_cols.append(item[1])
                num_w.append(coef[lo])
                lo += 1
            else:
                _, j, width = item
                idx = self.codes[:, j]
                table = coef[lo:lo + width]
                ok = (idx >= 0) & (idx < width)
                contrib = np.zeros(len(idx), dtype=np.float64)
                contrib[ok] = table[idx[ok].astype(np.intp)]
                acc += contrib
                lo += width
        if num_cols:
            acc += self.num[:, num_cols].astype(np.float64) \
                @ np.asarray(num_w)
        return acc


def _numeric(col) -> np.ndarray:
    return pd.to_numeric(col, errors="coerce").to_numpy(dtype=np.float64,
                                                        na_value=np.nan)


def extract_numeric_block(pdf: pd.DataFrame, cols: List[str],
                          fills: np.ndarray) -> np.ndarray:
    """(n, k) float64 block of `cols` with per-column NaN fills — ONE
    pandas extraction with a coercion fallback for non-numeric storage.
    Shared by the fused featurizer pass and the factorized scorer so their
    coercion semantics can never diverge."""
    try:
        block = pdf[cols].to_numpy(np.float64, na_value=np.nan)
    except (TypeError, ValueError):  # non-numeric storage: coerce
        block = pdf[cols].apply(
            lambda c: pd.to_numeric(c, errors="coerce")).to_numpy(
            np.float64, na_value=np.nan)
    return np.where(np.isfinite(block), block, fills[None, :])


class _Source:
    """One resolved input column: writes its slot(s) of the output block."""

    width = 1

    def write(self, pdf: pd.DataFrame, out: np.ndarray, lo: int) -> None:
        raise NotImplementedError


class _NumericSource(_Source):
    def __init__(self, col: str, fill: Optional[float] = None):
        self.col = col
        self.fill = fill  # imputer median/mean, applied on the fly

    def write(self, pdf, out, lo):
        v = _numeric(pdf[self.col])
        if self.fill is not None:
            v = np.where(np.isfinite(v), v, self.fill)
        out[:, lo] = v


class _IndexSource(_Source):
    """StringIndexerModel output: label → ordinal, with the stage's exact
    handleInvalid semantics (error raises; keep maps to len(labels); skip
    marks the row for dropping via the featurizer-level mask)."""

    def __init__(self, col: str, labels: np.ndarray, invalid: str):
        self.col = col
        self.labels = pd.Index(labels)
        self.invalid = invalid
        self._idx_by_dtype = {}  # dtype str -> Index in the COLUMN's dtype
        self._value_sets = {}    # arrow type str -> pa.Array of labels

    def _index_for(self, col: pd.Series) -> pd.Index:
        """get_indexer against an Index in the column's own dtype skips the
        per-batch arrow→object conversion (~2x on arrow-string batches)."""
        key = str(col.dtype)
        idx = self._idx_by_dtype.get(key)
        if idx is None:
            try:
                idx = pd.Index(pd.array([str(v) for v in self.labels],
                                        dtype=col.dtype))
            except Exception:
                idx = self.labels
            self._idx_by_dtype[key] = idx
        return idx

    def _arrow_codes(self, col: pd.Series):
        """pyarrow `index_in` over the column's native chunks: ~7x faster
        than Index.get_indexer on arrow-backed STRING columns AND releases
        the GIL (batch-scoring threads actually overlap). String columns
        only: labels are strings, and a string→string cast is injective,
        so unseen and null both yield -1 exactly like get_indexer against
        a unique label index. (A numeric cast could collapse distinct
        labels — "1" and "1.0" — onto one value; those columns keep the
        fallback's string-comparison semantics.) Returns None when the
        path doesn't apply."""
        pa_arr = getattr(getattr(col, "array", None), "_pa_array", None)
        if pa_arr is None:
            return None
        try:
            import pyarrow as pa
            import pyarrow.compute as pc
            if not (pa.types.is_string(pa_arr.type)
                    or pa.types.is_large_string(pa_arr.type)
                    or pa.types.is_string_view(pa_arr.type)):
                return None
            key = str(pa_arr.type)
            vs = self._value_sets.get(key)
            if vs is None:
                vs = pa.array([str(v) for v in self.labels]).cast(
                    pa_arr.type)
                self._value_sets[key] = vs
            r = pc.index_in(pa_arr, value_set=vs)
            return np.asarray(r.fill_null(-1).to_numpy(
                zero_copy_only=False), dtype=np.int64)
        except Exception:
            return None

    def codes(self, pdf) -> np.ndarray:
        """float codes with NaN for missing/unseen (pre-handleInvalid)."""
        col = pdf[self.col]
        c = self._arrow_codes(col)
        if c is None:
            notna = col.notna().to_numpy()
            try:
                c = self._index_for(col).get_indexer(col)
            except Exception:
                c = self.labels.get_indexer(
                    col.astype(str).to_numpy(dtype=object))
            c = c.astype(np.float64)
            c[(c < 0) | ~notna] = np.nan
            return c
        # arrow path: nulls are already -1 via fill_null — no notna pass
        c = c.astype(np.float64)
        c[c < 0] = np.nan
        return c

    def resolve(self, pdf, drop_mask, sink=None) -> np.ndarray:
        c = self.codes(pdf)
        missing = ~np.isfinite(c)
        if missing.any():
            if self.invalid == "error":
                bad = pdf[self.col][missing].iloc[0]
                raise ValueError(f"Unseen label {bad!r} in column "
                                 f"{self.col!r} (handleInvalid='error')")
            if self.invalid == "skip":
                drop_mask |= missing
            else:  # keep
                c[missing] = float(len(self.labels))
        if sink is not None:  # fused-transform interim capture (one pass)
            sink[id(self)] = c
        return c

    def write(self, pdf, out, lo, drop_mask=None, sink=None):
        out[:, lo] = self.resolve(
            pdf, drop_mask if drop_mask is not None
            else np.zeros(len(pdf), dtype=bool), sink)


class _OneHotSource(_Source):
    """OneHotEncoderModel over an indexed (or raw numeric-code) column."""

    def __init__(self, inner, width: int):
        self.inner = inner  # _IndexSource or _NumericSource
        self.width = int(width)

    def write(self, pdf, out, lo, drop_mask=None, sink=None):
        if isinstance(self.inner, _IndexSource):
            idx = self.inner.resolve(
                pdf, drop_mask if drop_mask is not None
                else np.zeros(len(pdf), dtype=bool), sink)
        else:
            idx = _numeric(pdf[self.inner.col])
            if self.inner.fill is not None:  # Imputer feeding the encoder
                idx = np.where(np.isfinite(idx), idx, self.inner.fill)
        na = ~np.isfinite(idx)
        ok = ~na & (idx >= 0) & (idx < self.width)
        rows = np.nonzero(ok)[0]
        out[:, lo:lo + self.width] = 0.0
        out[rows, lo + idx[ok].astype(np.intp)] = 1.0
        if na.any():  # matches OneHotEncoderModel: NaN input → NaN row
            out[na, lo:lo + self.width] = np.nan


class CompiledFeaturizer:
    """Fused replacement for a feature-stage chain; see module docstring."""

    def __init__(self, sources: List[_Source], handle_invalid: str):
        self.sources = sources
        self.handle_invalid = handle_invalid
        self.width = sum(s.width for s in sources)
        # (name, source) for every prep-stage output column in stage order —
        # the fused transform path rebuilds these interim columns from the
        # one-pass results instead of running per-stage pandas chains
        self.named_producers: List[tuple] = []

    @classmethod
    def from_stages(cls, stages, assembler) -> Optional["CompiledFeaturizer"]:
        from .feature import (ImputerModel, OneHotEncoder,
                              OneHotEncoderModel, StringIndexer,
                              StringIndexerModel, VectorAssembler)
        if not isinstance(assembler, VectorAssembler):
            return None
        invalid = assembler.getOrDefault("handleInvalid")
        if invalid not in ("error", "keep"):
            return None  # assembler "skip" drops by finiteness, not label

        producers = {}  # intermediate column name -> _Source
        for st in stages:
            if st is assembler:
                continue
            if isinstance(st, ImputerModel):
                ins = list(st.getOrDefault("inputCols") or [])
                outs = list(st.getOrDefault("outputCols") or ins)
                if any(c in producers for c in ins):
                    return None  # imputing a produced column: generic path
                for c, oc in zip(ins, outs):
                    producers[oc] = _NumericSource(c, float(st.surrogates[c]))
            elif isinstance(st, StringIndexerModel):
                ins, outs = StringIndexer._in_out(st)
                mode = st.getOrDefault("handleInvalid")
                if any(c in producers for c in ins):
                    return None  # indexing a produced column: generic path
                for c, oc, labels in zip(ins, outs, st.labelsArray):
                    producers[oc] = _IndexSource(
                        c, np.asarray(labels, dtype=object), mode)
            elif isinstance(st, OneHotEncoderModel):
                ins, outs = OneHotEncoder._in_out(st)
                drop_last = bool(st.getOrDefault("dropLast"))
                for c, oc, size in zip(ins, outs, st.categorySizes):
                    width = size - 1 if drop_last else size
                    inner = producers.get(c) or _NumericSource(c)
                    producers[oc] = _OneHotSource(inner, width)
            else:
                return None  # unknown stage: keep the generic path

        sources: List[_Source] = []
        for c in assembler.getOrDefault("inputCols"):
            sources.append(producers.get(c) or _NumericSource(c))
        out = cls(sources, invalid)
        out.named_producers = list(producers.items())
        return out

    def transform_with_mask(self, pdf: pd.DataFrame, sink=None):
        """(X, keep): the assembled block and the row-keep mask (None when
        no StringIndexer 'skip' drops happened) — callers that pair X with
        labels from the RAW frame must apply the same mask. `sink` captures
        resolved indexer codes by id(source) for the fused transform."""
        out = np.empty((len(pdf), self.width), dtype=np.float32)
        drop = np.zeros(len(pdf), dtype=bool)
        # contiguous runs of plain numeric sources extract as ONE pandas
        # block instead of a per-column to_numeric each (hot per batch)
        runs = []
        lo = 0
        for s in self.sources:
            simple = type(s) is _NumericSource
            if simple and runs and runs[-1][-1][0] + runs[-1][-1][1].width \
                    == lo and type(runs[-1][-1][1]) is _NumericSource:
                runs[-1].append((lo, s))
            elif simple:
                runs.append([(lo, s)])
            lo += s.width
        done = set()
        for run in runs:
            if len(run) < 2:
                continue
            cols = [s.col for _, s in run]
            fills = np.asarray([np.nan if s.fill is None else s.fill
                                for _, s in run])
            out[:, run[0][0]:run[0][0] + len(run)] = \
                extract_numeric_block(pdf, cols, fills)
            done.update(id(s) for _, s in run)
        lo = 0
        for s in self.sources:
            if id(s) in done:
                pass
            elif isinstance(s, (_IndexSource, _OneHotSource)):
                s.write(pdf, out, lo, drop, sink)
            else:
                s.write(pdf, out, lo)
            lo += s.width
        keep = None
        if drop.any():  # StringIndexer handleInvalid="skip" row drops
            keep = ~drop
            out = out[keep]
        if self.handle_invalid == "error" and not np.isfinite(out).all():
            raise ValueError(
                "VectorAssembler found NaN/null in assembled features; set "
                "handleInvalid='skip' or impute first")
        return out, keep

    def __call__(self, pdf: pd.DataFrame) -> np.ndarray:
        return self.transform_with_mask(pdf)[0]

    def compact_parts(self, pdf: pd.DataFrame) -> Optional[CompactParts]:
        """Extract the block in compact form (see CompactParts) when every
        source is numeric or one-hot-of-index — the standard course chain.
        Returns None (caller keeps the materialized path) for any other
        source shape, or when a value the expanded block would carry as
        NaN appears (the generic path's NaN semantics — error raises,
        NaN-poisoned fits — are not worth duplicating on the fast path)."""
        n = len(pdf)
        drop = np.zeros(n, dtype=bool)
        layout: List[tuple] = []
        num_srcs: List[_NumericSource] = []
        code_cols: List[np.ndarray] = []
        for s in self.sources:
            if type(s) is _NumericSource:
                layout.append(("num", len(num_srcs)))
                num_srcs.append(s)
            elif isinstance(s, _OneHotSource):
                if isinstance(s.inner, _IndexSource):
                    c = s.inner.resolve(pdf, drop)
                else:
                    c = _numeric(pdf[s.inner.col])
                    if s.inner.fill is not None:
                        c = np.where(np.isfinite(c), c, s.inner.fill)
                # rows the indexer marked for dropping may carry NaN codes
                # (they never reach the expanded block); any OTHER NaN
                # means a NaN one-hot row — generic-path semantics, bail
                if not np.isfinite(np.where(drop, 0.0, c)).all():
                    return None
                layout.append(("oh", len(code_cols), s.width))
                code_cols.append(np.where(drop, 0.0, c).astype(np.int32))
            else:
                return None
        if num_srcs:
            fills = np.asarray([np.nan if s.fill is None else s.fill
                                for s in num_srcs])
            num = extract_numeric_block(
                pdf, [s.col for s in num_srcs], fills).astype(np.float32)
            if not np.isfinite(num[~drop]).all():
                return None  # NaN feature: generic path raises/poisons
        else:
            num = np.zeros((n, 0), dtype=np.float32)
        codes = (np.stack(code_cols, axis=1) if code_cols
                 else np.zeros((n, 0), dtype=np.int32))
        keep = None
        if drop.any():
            keep = ~drop
            num, codes = num[keep], codes[keep]
        return CompactParts(np.ascontiguousarray(num),
                            np.ascontiguousarray(codes),
                            tuple(layout), self.width, keep)

    def _slot_map(self) -> dict:
        """assembler input position by source id: id(source) → (lo, width)."""
        m, lo = {}, 0
        for s in self.sources:
            m[id(s)] = (lo, s.width)
            lo += s.width
        return m

    def feature_attrs(self) -> dict:
        """The `_ml_attrs` entry the generic VectorAssembler transform would
        publish for its output column: categorical slot cardinalities (tree
        learners' maxBins semantics) + total width."""
        slots, lo = {}, 0
        for s in self.sources:
            if isinstance(s, _IndexSource):
                extra = 1 if s.invalid == "keep" else 0
                slots[lo] = len(s.labels) + extra
            lo += s.width
        return {"slots": slots, "numFeatures": self.width}

    def interim_attrs(self) -> dict:
        """Per-interim-column `_ml_attrs` matching the generic stage
        transforms (indexer 'categorical', OHE 'numFeatures')."""
        attrs = {}
        for name, src in self.named_producers:
            if isinstance(src, _IndexSource):
                extra = 1 if src.invalid == "keep" else 0
                attrs[name] = {"categorical": len(src.labels) + extra}
            elif isinstance(src, _OneHotSource):
                attrs[name] = {"numFeatures": src.width}
        return attrs

    def transform_with_columns(self, pdf: pd.DataFrame):
        """One-pass fused TRANSFORM: (X, keep, cols) where `cols` maps every
        prep-stage output column name to its value — a 1-D float array for
        scalar outputs or a `("block", arr2d, na_mask)` tuple for one-hot
        vector outputs. Everything is recovered from the single columnar
        pass: assembler-input producers read back their X slice, indexer
        codes consumed only by an encoder come from the resolve sink."""
        sink: dict = {}
        X, keep = self.transform_with_mask(pdf, sink)
        slot = self._slot_map()
        cols = {}
        for name, src in self.named_producers:
            sid = id(src)
            if sid in slot:
                lo, w = slot[sid]
                val = X[:, lo] if w == 1 else X[:, lo:lo + w]
            elif sid in sink:
                v = sink[sid]
                val = v[keep] if keep is not None else v
            elif isinstance(src, _NumericSource):
                v = _numeric(pdf[src.col])
                if src.fill is not None:
                    v = np.where(np.isfinite(v), v, src.fill)
                val = v[keep] if keep is not None else v
            else:  # an un-assembled encoder output: not worth a second pass
                return X, keep, None
            if isinstance(src, _OneHotSource) and np.ndim(val) == 2:
                na = ~np.isfinite(val).all(axis=1)
                cols[name] = ("block", val, na)
            else:
                cols[name] = np.asarray(val, dtype=np.float64).reshape(-1) \
                    if np.ndim(val) == 1 else val
        return X, keep, cols


def try_fast_fit(stages, raw_pdf, make_frame):
    """Whole-pipeline fused FIT: for the standard course chain
    [Imputer?, StringIndexer?, OneHotEncoder?, VectorAssembler, estimator],
    fit every prep stage from the RAW pandas (their inputs are raw columns),
    derive OneHotEncoder sizes from the indexer's labels (`max(idx)+1 ==
    len(labels)` when labels come from the same data), reconstruct the
    assembler's slot metadata analytically, and hand the estimator a frame
    carrying the one-pass assembled block — NO transform chain ever
    materializes. Returns (fitted_prep_stages, estimator_input_frame) or
    None (caller falls back to the generic sequential fit, which is always
    correct); the caller runs the estimator fit itself so estimator errors
    propagate unmasked.
    """
    if len(stages) < 2 or raw_pdf is None:
        return None
    return _try_fast_fit(stages, raw_pdf, make_frame)


def produced_columns(prep_stages) -> set:
    """Column names a prep chain WRITES. Stages with output params unset
    write in place (Imputer's outputCols default to inputCols), so the
    input columns count as produced in that case (r4 review)."""
    produced = set()
    for st in prep_stages:
        outs = set()
        for attr in ("outputCols", "outputCol"):
            try:
                v = st.getOrDefault(attr)
            except Exception:
                v = None
            if isinstance(v, str):
                outs.add(v)
            elif v:
                outs.update(v)
        if not outs:  # no explicit outputs: the stage overwrites its inputs
            for attr in ("inputCols", "inputCol"):
                try:
                    v = st.getOrDefault(attr)
                except Exception:
                    v = None
                if isinstance(v, str):
                    outs.add(v)
                elif v:
                    outs.update(v)
        produced |= outs
    return produced


def prep_overwrites_label(prep_stages, est) -> bool:
    """True when any prep stage's OUTPUT columns collide with the
    estimator's labelCol/weightCol — the fused fast paths read labels from
    the RAW pandas, so a stage that rewrites the label there would make
    them train on pre-transform values."""
    label_like = {est.getOrDefault("labelCol")}
    if est.hasParam("weightCol"):
        w = est.getOrDefault("weightCol")
        if w:
            label_like.add(w)
    return bool(produced_columns(prep_stages) & label_like)


def _try_fast_fit(stages, raw_pdf, make_frame):
    from .base import Estimator
    from .feature import (Imputer, OneHotEncoder, OneHotEncoderModel,
                          StringIndexer, VectorAssembler)
    *prep, est = stages
    if not isinstance(est, Estimator):
        return None
    if not (est.hasParam("featuresCol") and est.hasParam("labelCol")):
        return None
    if not prep or not isinstance(prep[-1], VectorAssembler):
        return None
    assembler = prep[-1]
    if est.getOrDefault("featuresCol") != assembler.getOrDefault("outputCol"):
        return None
    if est.getOrDefault("labelCol") not in raw_pdf.columns:
        return None
    if prep_overwrites_label(prep[:-1], est):
        return None  # a prep stage rewrites the label: raw labels are wrong

    raw_frame = make_frame(raw_pdf)
    fitted = []
    attrs = {}          # column -> ml attrs (categorical cardinalities)
    idx_labels = {}     # indexer output col -> label list
    ohe_widths = {}     # ohe output col -> vector width
    for st in prep[:-1]:
        if isinstance(st, Imputer):
            ins = list(st.getOrDefault("inputCols") or [])
            if any(c not in raw_pdf.columns for c in ins):
                return None
            fitted.append(st.fit(raw_frame))
        elif isinstance(st, StringIndexer):
            ins, outs = st._in_out()
            if any(c not in raw_pdf.columns for c in ins):
                return None
            m = st.fit(raw_frame)
            extra = 1 if st.getOrDefault("handleInvalid") == "keep" else 0
            for oc, ls in zip(outs, m.labelsArray):
                idx_labels[oc] = ls
                attrs[oc] = {"categorical": len(ls) + extra}
            fitted.append(m)
        elif isinstance(st, OneHotEncoder):
            ins, outs = st._in_out()
            if any(c not in idx_labels for c in ins):
                return None  # OHE over a non-indexer column: generic path
            sizes = [len(idx_labels[c]) for c in ins]
            m = OneHotEncoderModel(categorySizes=sizes)
            m._inherit_params(st)
            drop_last = bool(m.getOrDefault("dropLast"))
            for oc, size in zip(outs, sizes):
                ohe_widths[oc] = size - 1 if drop_last else size
            fitted.append(m)
        else:
            return None
    fitted.append(assembler)

    feat = CompiledFeaturizer.from_stages(fitted[:-1], assembler)
    if feat is None:
        return None
    # the assembler's slot metadata (VectorAssembler._transform computes
    # this from column attrs + row peeks; here widths are known statically)
    slots, pos = {}, 0
    for c in assembler.getOrDefault("inputCols"):
        if c in attrs and "categorical" in attrs[c]:
            slots[pos] = int(attrs[c]["categorical"])
            pos += 1
        elif c in ohe_widths:
            pos += ohe_widths[c]
        else:
            pos += 1
    out_col = assembler.getOrDefault("outputCol")

    # huge linear fits skip X entirely: the compact block stages n*(p+k)
    # words and expands one-hots on-chip (CompactParts; the 8M-row scale
    # path). Gated by size so course-scale fits keep the materialized
    # block and its golden-pinned numerics bit-for-bit.
    if type(est).__name__ in ("LinearRegression", "LogisticRegression"):
        from ..conf import GLOBAL_CONF
        if len(raw_pdf) * feat.width * 4 \
                >= GLOBAL_CONF.getInt("sml.linear.compactBytes"):
            parts = feat.compact_parts(raw_pdf)
            if parts is not None:
                shim = make_frame(raw_pdf)
                shim._ml_attrs = dict(attrs)
                shim._ml_attrs[out_col] = {"slots": slots,
                                           "numFeatures": pos}
                shim._featurized_compact = {out_col: (parts, raw_pdf)}
                return fitted, shim

    X, keep = feat.transform_with_mask(raw_pdf)
    shim = make_frame(raw_pdf)
    shim._ml_attrs = dict(attrs)
    shim._ml_attrs[out_col] = {"slots": slots, "numFeatures": pos}
    shim._featurized = {out_col: (X, keep, raw_pdf)}
    # the ESTIMATOR fit happens in the caller, OUTSIDE any fallback guard:
    # its errors (bad hyperparameters, device OOM) must propagate, not
    # trigger a silent re-fit through the generic path
    return fitted, shim
